"""Import shim — the dense sharer-reduction kernel moved into the step
subsystem as `primesim_tpu.kernels.reductions` (DESIGN.md §11), where it
is the third resident kernel next to probe_classify and commit_step.
Kept so external callers of the historical path keep working."""

from ..kernels.reductions import sharer_reductions  # noqa: F401
