"""Pallas TPU kernel for the dense sharer-expansion reductions
(SURVEY.md §2 #4/#6's "part of the Pallas uncore kernel" column).

The step's invalidation / back-invalidation reductions expand each
winner's packed sharer words into per-target-core booleans and reduce
latencies/counts/hops over the target axis — a dense [C_block, C] tiled
computation with NO data-dependent indexing, which is the shape TPU
Pallas handles well: the word->bit expansion is a static masked select
(Mosaic rejects the reshape `jnp.repeat` would emit), and pair
latencies come from index arithmetic. `pallas_reduce=true` in
MachineConfig routes the engine's full-map dense path through this
kernel; results are BIT-IDENTICAL to the jnp path (tests/test_pallas.py
runs the golden parity suite through it).

Scope note (an honest engineering finding, not a TODO): the rest of the
step is gather/scatter over multi-hundred-MB directory arrays with
data-dependent indices — the access pattern TPU Pallas's block model is
worst at — so the kernel boundary is drawn around the dense reduction,
and the gathers stay with XLA, which lowers them natively.

On non-TPU backends the kernel runs in Pallas interpreter mode, so the
parity suite exercises the identical kernel logic on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..config.machine import MachineConfig


def _expand_bits(words, t, NW: int):
    """[BC, NW] packed words -> [BC, NW*32] per-target booleans, column
    c = bit (c % 32) of word (c // 32). Static masked select per word:
    Mosaic-friendly (no minor-dim reshape, no gather)."""
    wsel = t >> 5
    rep = jnp.zeros(t.shape, jnp.int32)
    for w in range(NW):
        rep = rep + jnp.where(wsel == w, words[:, w][:, None], 0)
    return ((rep >> (t & 31)) & 1) != 0


def _reduce_kernel(
    shw_ref, vic_ref, btile_ref, vic_owner_ref, inv_row_ref, vic_valid_ref,
    self_ref,
    inv_lat_ref, inv_cnt_ref, inv_hops_ref, back_cnt_ref, back_hops_ref,
    *, C: int, NW: int, n_tiles: int, mesh_x: int, link_lat: int,
    router_lat: int,
):
    BC = shw_ref.shape[0]
    t = jax.lax.broadcasted_iota(jnp.int32, (BC, NW * 32), 1)  # target ids
    bits = _expand_bits(shw_ref[...], t, NW)  # recorded targets
    vbits = _expand_bits(vic_ref[...], t, NW)
    tvalid = t < C
    # pair geometry: home tile of this row vs target tile, from indices
    bt = btile_ref[...]  # [BC, 1]
    tt = t % n_tiles
    bx, by = bt % mesh_x, bt // mesh_x
    tx, ty = tt % mesh_x, tt // mesh_x
    hops = jnp.abs(bx - tx) + jnp.abs(by - ty)
    lat2 = 2 * (hops * link_lat + (hops + 1) * router_lat)
    hops2 = 2 * hops
    selfid = self_ref[...]
    inv_row = inv_row_ref[...] != 0
    sh_b = bits & (t != selfid) & inv_row & tvalid
    inv_lat_ref[...] = jnp.max(
        jnp.where(sh_b, lat2, 0), axis=1, keepdims=True
    )
    inv_cnt_ref[...] = jnp.sum(
        sh_b.astype(jnp.int32), axis=1, keepdims=True
    )
    inv_hops_ref[...] = jnp.sum(
        jnp.where(sh_b, hops2, 0), axis=1, keepdims=True
    )
    vic_owner = vic_owner_ref[...]
    vic_valid = vic_valid_ref[...] != 0
    ob = (t == vic_owner) & (vic_owner >= 0)
    bk_b = (vbits | ob) & vic_valid & tvalid
    back_cnt_ref[...] = jnp.sum(
        bk_b.astype(jnp.int32), axis=1, keepdims=True
    )
    back_hops_ref[...] = jnp.sum(
        jnp.where(bk_b, hops2, 0), axis=1, keepdims=True
    )


@functools.partial(jax.jit, static_argnums=(0,))
def sharer_reductions(
    cfg: MachineConfig, shw, vic_shw, btile, vic_owner, inv_row, vic_valid,
    arange_c,
):
    """Dense invalidation/back-invalidation reductions as one Pallas
    kernel: returns (inv_lat, inv_count, inv_hops, back_count,
    back_hops), each [C] int32 — bit-identical to the engine's jnp dense
    path. Full-map vectors only (cfg validation enforces it)."""
    C = cfg.n_cores
    NW = cfg.n_sharer_words
    BC = 128 if C % 128 == 0 else C
    kern = functools.partial(
        _reduce_kernel,
        C=C,
        NW=NW,
        n_tiles=cfg.n_tiles,
        mesh_x=cfg.noc.mesh_x,
        link_lat=cfg.noc.link_lat,
        router_lat=cfg.noc.router_lat,
    )
    col = lambda i: (i, 0)
    out = pl.pallas_call(
        kern,
        grid=(C // BC,),
        in_specs=[
            pl.BlockSpec((BC, NW), col),
            pl.BlockSpec((BC, NW), col),
        ]
        + [pl.BlockSpec((BC, 1), col)] * 5,
        out_specs=[pl.BlockSpec((BC, 1), col)] * 5,
        out_shape=[jax.ShapeDtypeStruct((C, 1), jnp.int32)] * 5,
        interpret=jax.default_backend() != "tpu",
    )(
        shw.astype(jnp.int32),
        vic_shw.astype(jnp.int32),
        btile.astype(jnp.int32)[:, None],
        vic_owner.astype(jnp.int32)[:, None],
        inv_row.astype(jnp.int32)[:, None],
        vic_valid.astype(jnp.int32)[:, None],
        arange_c.astype(jnp.int32)[:, None],
    )
    return tuple(o[:, 0] for o in out)
