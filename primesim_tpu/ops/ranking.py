"""Sort-based segmented FIFO ranking — the shared rank primitive of the
router and DRAM-queue contention models (DESIGN.md §13).

Both models need, per same-step transaction i and per FIFO segment s it
enters (a directed NoC link, or a DRAM bank controller),

    rank[i, s] = #{ j : key[j] < key[i],  lane j enters segment s }

— the number of packets ahead of lane i in s's same-step FIFO, ordered
by the phase-2 arbitration key.  The engine historically produced this
as an int8 one-hot matmul: a [C, C] `kless` comparison matrix contracted
against a [C, n_seg] membership one-hot — O(C² · n_seg) int-MACs
(~4×10⁹ per step at C=1024, n_seg≈4096).  `segmented_rank` computes the
identical int32 counts in O(E log E) over the E = C·S flattened
(segment, key) entries: one sort, one binary-search gather, one
segment-start histogram.

EXACT-EQUIVALENCE ARGUMENT (why the counts are integer-equal to the
matmul's, including duplicate keys):

1. `lane_order` maps each lane's key to its dense first-occurrence rank
   ``ord[i] = #{j : key[j] < key[i]}``.  ord is monotone in key and
   collapses ties, so ``key[j] < key[i]  ⟺  ord[j] < ord[i]``.
2. Each entry packs to ``seg·C + ord`` (strictly ordered by (seg, ord));
   after one flat sort, ``searchsorted(side="left")`` returns the count
   of entries with a strictly smaller packed value — all entries of
   earlier segments plus same-segment entries with strictly smaller ord.
   Equal keys share one packed value, so tied lanes never count each
   other, exactly like the matmul's strict `<`.
3. Subtracting the segment's start offset (an exclusive cumsum of the
   per-segment histogram = the count of entries in earlier segments)
   leaves the same-segment strictly-smaller count: the matmul rank.

CONTRACT: one entry per (lane, segment) — a lane may not enter the same
segment's FIFO twice in one step, or the sort counts it twice while the
matmul's one-hot `.set(1)` collapses it.  The engine guarantees this by
construction: request and reply legs traverse *reversed directed* links
(distinct ids), and the barrier-arrival leg is masked to barrier lanes,
disjoint from home-transaction lanes.  Masked entries use ``seg ==
n_seg`` (one past the last real segment); their ranks are garbage the
caller must mask, same as the matmul path's out-of-range gathers.

Everything here is plain int32 sort/scan/scatter — vmap-safe, so the
fleet engine batches it unchanged, and the jit key stays geometry-only
(keys/segments are traced data).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

INT32_MAX = jnp.iinfo(jnp.int32).max


def lane_order(key):
    """Dense first-occurrence rank of each lane's arbitration key:
    ``ord[i] = #{j : key[j] < key[i]}`` — [C] int32 in [0, C).

    Monotone in key with ties collapsed, so strict key comparisons and
    strict ord comparisons agree; computed with one C-element sort plus
    a group-start cummax (duplicates inherit their group's start)."""
    C = key.shape[0]
    pos = jnp.arange(C, dtype=jnp.int32)
    sk, sl = jax.lax.sort((key.astype(jnp.int32), pos), num_keys=1)
    grp_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sk[1:] != sk[:-1]]
    )
    gstart = jax.lax.cummax(jnp.where(grp_start, pos, 0))
    return jnp.zeros((C,), jnp.int32).at[sl].set(gstart)


def _segment_starts(seg_flat, n_seg: int):
    """Exclusive per-segment start offsets: start[s] = # entries with
    segment id < s, via histogram + exclusive cumsum ([n_seg + 1])."""
    h = jnp.zeros((n_seg + 1,), jnp.int32).at[seg_flat].add(1, mode="drop")
    return jnp.cumsum(h) - h


def segmented_rank(seg, key=None, n_seg=None, *, order=None, method="auto"):
    """Same-step FIFO ranks, integer-equal to the one-hot-matmul path.

    seg    [C, S] int32 — segment id per (lane, slot), in [0, n_seg];
           ``n_seg`` is the masked sentinel (ranks at masked slots are
           unspecified, mask them downstream).
    key    [C] int32 — per-lane arbitration key (any dtype ordering);
           ignored when a precomputed ``order=lane_order(key)`` is given
           (share one lane_order across the router and DRAM blocks).
    n_seg  static int — number of real segments.

    Returns [C, S] int32: rank[i, s] = # of (lane j ≠ i, slot) entries
    with seg == seg[i, s] and key[j] strictly < key[i], counting each
    such lane once (contract: entries unique per (lane, segment)).

    method="packed" sorts ``seg·C + ord`` as ONE int32 key (requires
    (n_seg + 1)·C ≤ int32 max — true for every shipped geometry);
    "lex" is the general two-key lexicographic sort; "auto" picks.
    """
    if n_seg is None:
        raise TypeError("segmented_rank: n_seg is required")
    C, S = seg.shape
    if order is None:
        order = lane_order(key)
    seg = seg.astype(jnp.int32)
    seg_flat = seg.reshape(C * S)
    if method == "auto":
        method = "packed" if (n_seg + 1) * C <= int(INT32_MAX) else "lex"
    if method == "packed":
        packed = (seg * jnp.int32(C) + order[:, None]).reshape(C * S)
        sp = jax.lax.sort(packed)
        first = jnp.searchsorted(sp, packed, side="left").astype(jnp.int32)
        start = _segment_starts(seg_flat, n_seg)
        return (
            first - start[jnp.clip(seg_flat, 0, n_seg)]
        ).reshape(C, S)
    if method == "lex":
        E = C * S
        pos = jnp.arange(E, dtype=jnp.int32)
        ord_flat = jnp.broadcast_to(order[:, None], (C, S)).reshape(E)
        sseg, sord, sidx = jax.lax.sort(
            (seg_flat, ord_flat, pos), num_keys=2
        )
        one = jnp.ones((1,), jnp.bool_)
        seg_start = jnp.concatenate([one, sseg[1:] != sseg[:-1]])
        grp_start = seg_start | jnp.concatenate([one, sord[1:] != sord[:-1]])
        seg0 = jax.lax.cummax(jnp.where(seg_start, pos, 0))
        grp0 = jax.lax.cummax(jnp.where(grp_start, pos, 0))
        return jnp.zeros((E,), jnp.int32).at[sidx].set(grp0 - seg0).reshape(
            C, S
        )
    raise ValueError(f"segmented_rank: unknown method {method!r}")
