"""The two VMEM-resident fused step kernels (DESIGN.md §11).

`probe_classify` fuses the step's phase-1 front half: the L1 set probe
(five-plane gather + local-run patch), the pointer validation of every
way against its directory entry, hit classification, the LLC home-row
parse (tags/owner/LRU/epoch/sharers), the sharer-set predicates
(popcount, self bit), and victim selection — previously ~a dozen serial
XLA gather kernels, now one kernel over core blocks with the needed
directory rows STAGED into VMEM by two XLA row gathers (the one access
shape Pallas cannot beat XLA at; see the fusion-boundary contract in
DESIGN.md §11).

`commit_step` fuses the back half ("scatters+tail", the ~1.0 ms cut in
scripts/prof/prof_phase.py): all 7 + 2*rl L1 plane writes, the winner's
full directory-row delta + join contributions, and the stacked counter
fold — emitting the new L1 block, the per-core [DW] row delta (the
engine applies the one remaining data-dependent row scatter-add), and
the folded counters.

Both kernels are written in the Mosaic-safe idioms of layouts.py (static
masked selects instead of gathers, first-occurrence emulations of
argmax/argmin, iota column arithmetic instead of reshapes) and are
BIT-EXACT vs the XLA step: same integer arithmetic, same tie-breaking,
same duplicate-write resolution (tests/test_step_pallas.py proves
golden/xla/pallas three-way parity on every workload generator,
including coarse-directory and fleet-vmapped paths). Core ids arrive as
a [BC, 1] input — never pl.program_id — so jax.vmap batching (the fleet
engine) stays correct, and traced step scalars ride as (1, 1) blocks so
timing sweeps never recompile.

FAULT-LANE CONTRACT (DESIGN.md §12). Fault injection is deliberately
IMPLEMENTATION-AGNOSTIC: every architectural fault effect lands outside
the kernel fusion boundary, so `step_impl=pallas` and `step_impl=xla`
see byte-identical operands and need no fault-specific code paths.
Concretely: the fail-stop directory scrub rewrites `dirm` BEFORE the
phase-1 row gathers stage it; dead cores are removed from the lane
predicates (`countable`/`active`/local-run `pref`) that gate what these
kernels classify and commit; NoC detour latencies and reroute/ECC
counter deltas are added to the composed per-lane latencies and counter
fold AFTER `commit_step` returns (the fold derives its width from
`counters.shape[0]`, so the four fault counters flow through the stacked
fold untouched). A faults-off config reaches these kernels with bit-
identical inputs to a build without the fault subsystem at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..config.machine import MachineConfig
from ..sim.state import I, M, S, dirm_width, llc_meta_width
from .layouts import (
    across,
    core_block,
    interpret_mode,
    popcount,
    select_col,
)

# probe_classify packed-lane indices (column k of the [C, PROBE_LANES]
# output): the scalar classification results phase 2/3 consume
(
    PL_HIT_ANY,
    PL_HIT_WAY,
    PL_HIT_STATE,
    PL_LLC_HAS,
    PL_LLC_HWAY,
    PL_OWNER,
    PL_SELF_BIT,
    PL_OTHER_SH,
    PL_VIC_TAG,
    PL_VIC_OWNER,
    PL_LLC_VWAY,
) = range(11)
PROBE_LANES = 11

# commit_step packed-lane indices (column k of the [C, COMMIT_LANES]
# input): every phase-2/3 scalar the fused tail needs
(
    CL_LINE,
    CL_HIT_WAY,
    CL_L1_VWAY,
    CL_HIT,
    CL_WRITE_HIT,
    CL_UPG_IN_PLACE,
    CL_WINNER,
    CL_JOIN,
    CL_LLC_HIT,
    CL_ST_VAL,
    CL_SLOT,
    CL_LLC_HWAY,
    CL_LLC_VWAY,
    CL_JREP,
    CL_TAKES_OWN,
    CL_GETS_PROBE,
    CL_GETS_SHARED,
    CL_OCLAMP,
) = range(18)
COMMIT_LANES = 18


def _sel_list(vals, idx):
    """vals[idx] over a python list of [BC, 1] columns (static unroll)."""
    acc = jnp.zeros_like(idx)
    for k, v in enumerate(vals):
        acc = acc + jnp.where(idx == k, v, 0)
    return acc


def _first_idx(masks, default: int):
    """Index of the first True across a python list of [BC, 1] bools
    (jnp.argmax tie-breaking), `default` when none."""
    idx = jnp.full_like(masks[0].astype(jnp.int32), default)
    for w in reversed(range(len(masks))):
        idx = jnp.where(masks[w], w, idx)
    return idx


def _probe_kernel(
    *refs, C: int, S1: int, W1: int, W2: int, NW: int, MW: int, DW: int,
    G: int, rl: int,
):
    FS = W1 * S1
    n_in = 6 + (3 if rl else 0)
    l1_ref, vrows_ref, mrows_ref, line_ref, cid_ref, step_ref = refs[:6]
    if rl:
        hm_ref, wm_ref, cm_ref = refs[6:9]
    tag_out, lru_out, weff_out, shw_out, vshw_out, lane_out = refs[n_in:]

    l1 = l1_ref[...]
    vrows = vrows_ref[...]
    mrows = mrows_ref[...]
    line = line_ref[...]  # [BC, 1]
    cid = cid_ref[...]
    step_no = step_ref[...]  # [1, 1], broadcasts

    # ---- L1 set probe: five planes x W1 ways via one-hot set select ----
    l1s = line & (S1 - 1)
    set_oh = jax.lax.broadcasted_iota(jnp.int32, (1, S1), 1) == l1s

    def pick(p, w):  # plane p, way w of the accessed set -> [BC, 1]
        c0 = p * FS + w * S1
        return jnp.sum(
            jnp.where(set_oh, l1[:, c0 : c0 + S1], 0), axis=1, keepdims=True
        )

    tag_w = [pick(0, w) for w in range(W1)]
    st_w = [pick(1, w) for w in range(W1)]
    lru_w = [pick(2, w) for w in range(W1)]
    ptr_w = [pick(3, w) for w in range(W1)]
    eph_w = [pick(4, w) for w in range(W1)] if G > 1 else None
    if rl:
        # the local run's deferred L1 writes patched in-register (silent
        # E->M at wm columns, LRU stamps at hm columns) — same values
        # regardless of which run slot matched, so sequential wheres
        # reproduce _l1_probe's any()-collapsed patch exactly
        hmm, wmm, cmm = hm_ref[...], wm_ref[...], cm_ref[...]
        for w in range(W1):
            wcol = w * S1 + l1s
            for k in range(rl):
                mk = cmm[:, k : k + 1] == wcol
                st_w[w] = jnp.where(
                    (wmm[:, k : k + 1] != 0) & mk, M, st_w[w]
                )
                lru_w[w] = jnp.where(
                    (hmm[:, k : k + 1] != 0) & mk, step_no, lru_w[w]
                )

    # ---- pointer validation (sim/engine._validate_ways semantics) ------
    logG = G.bit_length() - 1
    g_c = cid >> logG
    u_w = g_c >> 5  # self -> sharer word / bit (group id under Dir-G)
    u_b = g_c & 31
    weff_w = []
    for w in range(W1):
        pway = ptr_w[w] % W2  # ptr = slot*W2 + way, nonneg
        base = w * DW
        vtag = select_col(vrows, pway, W2, lambda v: base + 2 * v)
        vown = select_col(vrows, pway, W2, lambda v: base + 2 * v + 1)
        # sharer word: way select over NW-wide segments, then word select
        row_w = jnp.zeros((line.shape[0], NW), jnp.int32)
        for v in range(W2):
            c0 = base + MW + v * NW
            row_w = row_w + jnp.where(pway == v, vrows[:, c0 : c0 + NW], 0)
        vsh = select_col(row_w, u_w, NW)
        vbit = ((vsh >> u_b) & 1) != 0
        if G > 1:
            veph = select_col(vrows, pway, W2, lambda v: base + 3 * W2 + v)
            vbit = vbit & (veph == eph_w[w])
        weff_w.append(
            jnp.where(
                (st_w[w] == I) | (vtag != tag_w[w]),
                I,
                jnp.where(vown == cid, st_w[w], jnp.where(vbit, S, I)),
            )
        )

    # ---- hit classification -------------------------------------------
    match_w = [(tag_w[w] == line) & (weff_w[w] != I) for w in range(W1)]
    hit_any = functools.reduce(jnp.logical_or, match_w)
    hit_way = jnp.where(hit_any, _first_idx(match_w, W1), 0)
    hit_state = _sel_list(weff_w, hit_way)

    # ---- LLC home-row parse -------------------------------------------
    ltag_w = [mrows[:, 2 * v : 2 * v + 1] for v in range(W2)]
    lown_w = [mrows[:, 2 * v + 1 : 2 * v + 2] for v in range(W2)]
    lmatch = [ltag_w[v] == line for v in range(W2)]
    llc_has = functools.reduce(jnp.logical_or, lmatch)
    llc_hway = jnp.where(llc_has, _first_idx(lmatch, W2), 0)
    owner = _sel_list(lown_w, llc_hway)
    shw = jnp.zeros((line.shape[0], NW), jnp.int32)
    for v in range(W2):
        c0 = MW + v * NW
        shw = shw + jnp.where(llc_hway == v, mrows[:, c0 : c0 + NW], 0)

    # sharer-set predicates from the packed words
    self_bit = (select_col(shw, u_w, NW) >> u_b) & 1
    total = jnp.sum(popcount(shw), axis=1, keepdims=True)
    if G > 1:
        # coarse: the requester's own group bit may cover OTHER cores
        other_sh = total > 0
    else:
        other_sh = (total - self_bit) > 0

    # ---- victim selection (first-minimum LRU over valid ways) ----------
    vkey_w = [
        jnp.where(ltag_w[v] != -1, mrows[:, 2 * W2 + v : 2 * W2 + v + 1], -1)
        for v in range(W2)
    ]
    vmin = functools.reduce(jnp.minimum, vkey_w)
    llc_vway = _first_idx([vkey_w[v] == vmin for v in range(W2)], 0)
    vic_tag = _sel_list(ltag_w, llc_vway)
    vic_owner = _sel_list(lown_w, llc_vway)
    vic_shw = jnp.zeros((line.shape[0], NW), jnp.int32)
    for v in range(W2):
        c0 = MW + v * NW
        vic_shw = vic_shw + jnp.where(llc_vway == v, mrows[:, c0 : c0 + NW], 0)

    tag_out[...] = across(tag_w, W1)
    lru_out[...] = across(lru_w, W1)
    weff_out[...] = across(weff_w, W1)
    shw_out[...] = shw
    vshw_out[...] = vic_shw
    lane_out[...] = across(
        [
            hit_any, hit_way, hit_state, llc_has, llc_hway, owner,
            self_bit, other_sh, vic_tag, vic_owner, llc_vway,
        ],
        PROBE_LANES,
    )


@functools.partial(jax.jit, static_argnums=(0,))
def probe_classify(
    cfg: MachineConfig, l1, vrows, mrows, line, arange_c, step_no,
    hm=None, wm=None, cm=None,
):
    """Fused phase 1: returns (tag_rows, lru_rows, weff) [C, W1],
    (shw, vic_shw) [C, NW], and the packed classification lanes
    [C, PROBE_LANES] (see PL_* indices). `vrows` is dirm[ptr//W2]
    flattened to [C, W1*DW] (XLA-staged), `mrows` is dirm[slot] [C, DW];
    `hm/wm/cm` carry the local run's deferred L1 patch when
    cfg.local_run_len > 0."""
    C = cfg.n_cores
    S1, W1 = cfg.l1.sets, cfg.l1.ways
    W2 = cfg.llc.ways
    NW = cfg.n_sharer_words
    MW = llc_meta_width(cfg)
    DW = dirm_width(cfg)
    FS = W1 * S1
    BC = core_block(C)
    rl = 0 if hm is None else hm.shape[1]
    kern = functools.partial(
        _probe_kernel, C=C, S1=S1, W1=W1, W2=W2, NW=NW, MW=MW, DW=DW,
        G=cfg.sharer_group, rl=rl,
    )
    col = lambda i: (i, 0)
    scal = lambda i: (0, 0)
    in_specs = [
        pl.BlockSpec((BC, 5 * FS), col),
        pl.BlockSpec((BC, W1 * DW), col),
        pl.BlockSpec((BC, DW), col),
        pl.BlockSpec((BC, 1), col),
        pl.BlockSpec((BC, 1), col),
        pl.BlockSpec((1, 1), scal),
    ]
    ins = [
        l1,
        vrows,
        mrows,
        line.astype(jnp.int32)[:, None],
        arange_c.astype(jnp.int32)[:, None],
        jnp.asarray(step_no, jnp.int32).reshape(1, 1),
    ]
    if rl:
        in_specs += [pl.BlockSpec((BC, rl), col)] * 3
        ins += [hm.astype(jnp.int32), wm.astype(jnp.int32), cm]
    return pl.pallas_call(
        kern,
        grid=(C // BC,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((BC, W1), col)] * 3
        + [pl.BlockSpec((BC, NW), col)] * 2
        + [pl.BlockSpec((BC, PROBE_LANES), col)],
        out_shape=[jax.ShapeDtypeStruct((C, W1), jnp.int32)] * 3
        + [jax.ShapeDtypeStruct((C, NW), jnp.int32)] * 2
        + [jax.ShapeDtypeStruct((C, PROBE_LANES), jnp.int32)],
        interpret=interpret_mode(),
    )(*ins)


def _commit_kernel(
    *refs, NC: int, S1: int, W1: int, W2: int, NW: int, MW: int, DW: int,
    G: int, rl: int, moesi: bool,
):
    FS = W1 * S1
    n_in = 9 + (3 if rl else 0)
    (
        l1_ref, mrows_ref, tag_ref, shw_ref, lanes_ref, cid_ref, step_ref,
        cnt_ref, delta_ref,
    ) = refs[:9]
    if rl:
        hm_ref, wm_ref, cm_ref = refs[9:12]
    l1_out, drow_out, cnt_out = refs[n_in:]

    lanes = lanes_ref[...]

    def lane(k):
        return lanes[:, k : k + 1]

    def laneb(k):
        return lanes[:, k : k + 1] != 0

    mrows = mrows_ref[...]
    shw = shw_ref[...]
    cid = cid_ref[...]
    step_no = step_ref[...]  # [1, 1]
    line = lane(CL_LINE)
    hit_way = lane(CL_HIT_WAY)
    l1_vway = lane(CL_L1_VWAY)
    st_val = lane(CL_ST_VAL)
    slot = lane(CL_SLOT)
    llc_hway = lane(CL_LLC_HWAY)
    llc_vway = lane(CL_LLC_VWAY)
    oclamp = lane(CL_OCLAMP)
    hitb = laneb(CL_HIT)
    write_hit = laneb(CL_WRITE_HIT)
    upg_w = laneb(CL_UPG_IN_PLACE)
    winner = laneb(CL_WINNER)
    join = laneb(CL_JOIN)
    llc_hit = laneb(CL_LLC_HIT)
    jrep = laneb(CL_JREP)
    takes_own = laneb(CL_TAKES_OWN)
    gets_probe = laneb(CL_GETS_PROBE)
    gets_shared = laneb(CL_GETS_SHARED)

    # ---- L1 plane writes (phase 4.A's single fused scatter) ------------
    l1s = line & (S1 - 1)
    upd_way = jnp.where(upg_w, hit_way, l1_vway)
    hit_col = hit_way * S1 + l1s
    upd_col = upd_way * S1 + l1s
    fill = (winner & ~upg_w) | join
    tag_rows = tag_ref[...]
    tagm = [tag_rows[:, w : w + 1] == line for w in range(W1)]
    t_way = _first_idx(tagm, 0)
    any_tagm = functools.reduce(jnp.logical_or, tagm)
    dup = fill & any_tagm & (t_way != upd_way)
    dup_col = t_way * S1 + l1s
    wj = winner | join
    lru_m = hitb | wj
    lru_col = jnp.where(hitb, hit_col, upd_col)
    st_m = write_hit | wj
    st_col = jnp.where(write_hit, hit_col, upd_col)
    llc_uway = jnp.where(llc_hit, llc_hway, llc_vway)
    eph_way = jnp.where(join, llc_hway, llc_uway)
    eph_old = select_col(mrows, eph_way, W2, lambda v: 3 * W2 + v)
    new_eph = eph_old + takes_own.astype(jnp.int32)
    fill_ptr = slot * W2 + jnp.where(join | llc_hit, llc_hway, llc_vway)

    cols5 = jax.lax.broadcasted_iota(jnp.int32, (1, 5 * FS), 1)
    blk = l1_ref[...]

    def wr(b, m, col, val):
        return jnp.where(m & (cols5 == col), val, b)

    # write set identical to the XLA scatter (targets pairwise distinct
    # up to benign identical-value duplicates — see engine phase 4.A);
    # the run writes go last with the same E->M suppression, matching
    # the serialized order the XLA comment argues from
    blk = wr(blk, dup, dup_col, -1)  # stale duplicate tag clear
    blk = wr(blk, dup, dup_col + FS, I)  # stale duplicate state clear
    blk = wr(blk, lru_m, lru_col + 2 * FS, step_no)  # LRU stamp
    blk = wr(blk, st_m, st_col + FS, st_val)  # silent E->M + grant state
    blk = wr(blk, wj, upd_col, line)  # fill tag
    blk = wr(blk, wj, upd_col + 3 * FS, fill_ptr)  # fill way pointer
    blk = wr(blk, wj, upd_col + 4 * FS, new_eph)  # fill-time epoch
    if rl:
        hmm, wmm, cmm = hm_ref[...], wm_ref[...], cm_ref[...]
        for k in range(rl):
            cmk = cmm[:, k : k + 1]
            blk = wr(blk, hmm[:, k : k + 1] != 0, cmk + 2 * FS, step_no)
            sup = (wmm[:, k : k + 1] != 0) & ~(st_m & (st_col == cmk))
            blk = wr(blk, sup, cmk + FS, M)
    l1_out[...] = blk

    # ---- directory row delta (engine "Directory update:" semantics) ----
    logG = G.bit_length() - 1
    g = cid >> logG
    iota_nw = jax.lax.broadcasted_iota(jnp.int32, (1, NW), 1)
    self_word = jnp.where(iota_nw == (g >> 5), jnp.int32(1) << (g & 31), 0)
    og = oclamp >> logG
    owner_word = jnp.where(
        iota_nw == (og >> 5), jnp.int32(1) << (og & 31), 0
    )
    new_owner = jnp.where(takes_own, cid, -1)
    probe_word = self_word | owner_word
    if moesi:
        # dirty sharing (DESIGN.md §25): a GETS probe leaves the probed
        # owner recorded (derived Owned) and accumulates sharers; shw is
        # always 0 here under mesi, so mesi output is unchanged
        new_owner = jnp.where(gets_probe, oclamp, new_owner)
        probe_word = shw | probe_word
    new_shw = jnp.where(
        gets_probe,
        probe_word,
        jnp.where(gets_shared, shw | self_word, 0),
    )
    join_word = self_word & ~shw

    jD = jax.lax.broadcasted_iota(jnp.int32, (1, DW), 1)
    old = mrows
    pairv = jnp.where((jD & 1) == 0, line, new_owner)
    jsh = jnp.maximum(jD - MW, 0)
    w_sh = jsh // NW
    n_sh = jsh - w_sh * NW
    shv = jnp.zeros(old.shape, jnp.int32)
    jwv = jnp.zeros(old.shape, jnp.int32)
    for n in range(NW):
        n_oh = n_sh == n
        shv = shv + jnp.where(n_oh, new_shw[:, n : n + 1], 0)
        jwv = jwv + jnp.where(n_oh, join_word[:, n : n + 1], 0)
    new_full = jnp.where(
        jD < 2 * W2,
        jnp.where((jD >> 1) == llc_uway, pairv, old),
        jnp.where(
            jD < 3 * W2,
            jnp.where(jD - 2 * W2 == llc_uway, step_no, old),
            jnp.where(
                jD < 4 * W2,
                jnp.where(jD - 3 * W2 == llc_uway, new_eph, old),
                jnp.where(
                    jD < MW, old, jnp.where(w_sh == llc_uway, shv, old)
                ),
            ),
        ),
    )
    old_lru_h = select_col(mrows, llc_hway, W2, lambda v: 2 * W2 + v)
    jdelta = jnp.where(jrep, step_no - old_lru_h, 0)
    join_row = jnp.where(jD == 2 * W2 + llc_hway, jdelta, 0) + jnp.where(
        (jD >= MW) & (w_sh == llc_hway), jwv, 0
    )
    drow_out[...] = jnp.where(
        winner, new_full - old, jnp.where(join, join_row, 0)
    )

    # ---- counter fold --------------------------------------------------
    cnt_out[...] = cnt_ref[...] + delta_ref[...]


@functools.partial(jax.jit, static_argnums=(0,))
def commit_step(
    cfg: MachineConfig, l1, mrows, tag_rows, shw, lanes, arange_c, step_no,
    counters, delta, hm=None, wm=None, cm=None,
):
    """Fused phase 4.A + counter fold: returns (l1_new [C, 5*W1*S1],
    delta_row [C, DW], counters_new [NC, C]). `lanes` packs the CL_*
    columns; `mrows`/`tag_rows`/`shw` come straight from probe_classify's
    staging/outputs; `delta` is the step's stacked counter delta
    [NC, C]. The caller applies the one remaining data-dependent row
    scatter: dirm.at[upd_slot].add(delta_row)."""
    C = cfg.n_cores
    S1, W1 = cfg.l1.sets, cfg.l1.ways
    W2 = cfg.llc.ways
    NW = cfg.n_sharer_words
    MW = llc_meta_width(cfg)
    DW = dirm_width(cfg)
    FS = W1 * S1
    BC = core_block(C)
    NC = counters.shape[0]
    rl = 0 if hm is None else hm.shape[1]
    kern = functools.partial(
        _commit_kernel, NC=NC, S1=S1, W1=W1, W2=W2, NW=NW, MW=MW, DW=DW,
        G=cfg.sharer_group, rl=rl, moesi=cfg.coherence == "moesi",
    )
    col = lambda i: (i, 0)
    scal = lambda i: (0, 0)
    row = lambda i: (0, i)  # counters block the LANE axis
    in_specs = [
        pl.BlockSpec((BC, 5 * FS), col),
        pl.BlockSpec((BC, DW), col),
        pl.BlockSpec((BC, W1), col),
        pl.BlockSpec((BC, NW), col),
        pl.BlockSpec((BC, COMMIT_LANES), col),
        pl.BlockSpec((BC, 1), col),
        pl.BlockSpec((1, 1), scal),
        pl.BlockSpec((NC, BC), row),
        pl.BlockSpec((NC, BC), row),
    ]
    ins = [
        l1,
        mrows,
        tag_rows,
        shw,
        lanes,
        arange_c.astype(jnp.int32)[:, None],
        jnp.asarray(step_no, jnp.int32).reshape(1, 1),
        counters,
        delta,
    ]
    if rl:
        in_specs += [pl.BlockSpec((BC, rl), col)] * 3
        ins += [hm.astype(jnp.int32), wm.astype(jnp.int32), cm]
    return pl.pallas_call(
        kern,
        grid=(C // BC,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((BC, 5 * FS), col),
            pl.BlockSpec((BC, DW), col),
            pl.BlockSpec((NC, BC), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, 5 * FS), jnp.int32),
            jax.ShapeDtypeStruct((C, DW), jnp.int32),
            jax.ShapeDtypeStruct((NC, C), jnp.int32),
        ],
        interpret=interpret_mode(),
    )(*ins)
