"""Pallas TPU kernel for the hop-by-hop router's wait-floor + cascade
block (ISSUE 6 second prong; DESIGN.md §13) — the fourth resident kernel
of the step subsystem, behind the same config-gated `step_impl="pallas"`
selector as probe/classify and commit.

The router walk (sim/engine.py, NocConfig contention_model="router")
composes, per leg of every home transaction, the same-step FIFO wait
floors F_k = max(link_free, base) + rank·link_lat at each hop k, runs
the closed-form contention cascade

    t_k = max(t0 + router_lat, cummax_{k'<=k}(F_k' - k'·c)) + k·c,
    c = link_lat + router_lat,

and emits per-hop link departures plus each leg's end time.  That is a
dense [BC, H] VMEM shape with NO data-dependent indexing — exactly what
the block model handles — so this kernel fuses the wait-floor selects,
three per-leg cummax cascades (request, reply, barrier-arrival), and
the departure composition into one pallas_call.  The surrounding
data-dependent pieces stay XLA on purpose: the per-hop link_free/base
row GATHERS feeding the kernel and the departure scatter-max back into
link_free are the one access shape the block model cannot express
(same boundary the commit kernel draws at the dirm row scatter).

VMEM LAYOUT (layouts.py geometry): every per-leg operand is a [BC, H]
core-axis block (H = mesh diameter, the -1-padded XY path width); lane
vectors ride as [BC, 1] columns; link/router latencies arrive as TRACED
(1, 1) scalar blocks — the jit key stays geometry-only, so fleet knob
sweeps compile once.  The lane-dim cummax is `layouts.cummax_rows`, a
static unroll of masked reduces (Mosaic has no lane scan); masked hops
carry the engine's SENT sentinel and never surface: their departures
scatter to the dropped NL index upstream.

Legs chain exactly like the XLA path: the reply leg starts at
t_req_end + service, the barrier-arrival leg (compiled only when the
trace has sync events — `has_sync` is jit-static) at t0.  All int32;
bit-exact vs XLA and the golden scalar walk (tests/test_router_pallas.py
three-way parity).  On non-TPU backends the kernel runs in Pallas
interpreter mode, tier-1-gated on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .layouts import core_block, cummax_rows, interpret_mode

#: masked-hop wait floor; must equal the engine's router-block SENT
#: (more negative than any real floor, offset-safe under - hidx*c_hop)
SENT = -(1 << 30) - (1 << 21)


def _cascade_kernel(
    lf_req, bs_req, r_req, ok_req,
    lf_rep, bs_rep, r_rep, ok_rep,
    *refs,
    H: int, has_sync: bool,
):
    if has_sync:
        (lf_arr, bs_arr, r_arr, ok_arr, t0, service, req_hops, rep_hops,
         arr_hops, link, router,
         d_req_o, d_rep_o, d_arr_o, t_rep_o, t_arr_o) = refs
    else:
        (t0, service, req_hops, rep_hops, link, router,
         d_req_o, d_rep_o, t_rep_o) = refs
    L = link[...]  # [1, 1] traced knobs
    R = router[...]
    c_hop = L + R
    hidx = jax.lax.broadcasted_iota(jnp.int32, (1, H), 1)

    def leg(lf, bs, r, ok, t_start, nh):
        F = jnp.where(
            ok[...] != 0,
            jnp.maximum(lf[...], bs[...]) + r[...] * L,
            SENT,
        )
        G = F - hidx * c_hop
        cum = cummax_rows(G)
        t1 = t_start + R  # [BC, 1]
        t_end = jnp.maximum(
            t1, jnp.max(G, axis=1, keepdims=True)
        ) + nh[...] * c_hop
        departs = jnp.maximum(t1, cum) + hidx * c_hop + L
        return t_end, departs

    t0v = t0[...]
    t_req_end, d_req = leg(lf_req, bs_req, r_req, ok_req, t0v, req_hops)
    t_rep_end, d_rep = leg(
        lf_rep, bs_rep, r_rep, ok_rep, t_req_end + service[...], rep_hops
    )
    d_req_o[...] = d_req
    d_rep_o[...] = d_rep
    t_rep_o[...] = t_rep_end
    if has_sync:
        t_arr_end, d_arr = leg(lf_arr, bs_arr, r_arr, ok_arr, t0v, arr_hops)
        d_arr_o[...] = d_arr
        t_arr_o[...] = t_arr_end


def router_cascade(
    lf_all, bs_all, r_all, ok_all, t0, service,
    req_hops, rep_hops, arr_hops, link_lat, router_lat, *, has_sync: bool,
):
    """Fused wait-floor + cascade + departures: takes the XLA-staged
    [C, legs·H] per-hop gathers (link_free, base), ranks, and hop masks,
    returns (t_rep_end [C], t_arr_end [C] | None, departs [C, legs·H])
    — bit-identical to the engine's XLA `_cascade` path.  `link_lat` /
    `router_lat` are the TRACED knob scalars."""
    C = lf_all.shape[0]
    legs = 3 if has_sync else 2
    H = lf_all.shape[1] // legs
    BC = core_block(C)
    kern = functools.partial(_cascade_kernel, H=H, has_sync=has_sync)
    col = lambda i: (i, 0)
    scal = lambda i: (0, 0)

    def leg_ins(k):
        s = slice(k * H, (k + 1) * H)
        return [
            lf_all[:, s], bs_all[:, s], r_all[:, s],
            ok_all[:, s].astype(jnp.int32),
        ]

    ins = leg_ins(0) + leg_ins(1)
    lane = [t0, service, req_hops, rep_hops]
    if has_sync:
        ins += leg_ins(2)
        lane.append(arr_hops)
    n_hout = legs  # one departure block per leg
    out = pl.pallas_call(
        kern,
        grid=(C // BC,),
        in_specs=[pl.BlockSpec((BC, H), col)] * (4 * legs)
        + [pl.BlockSpec((BC, 1), col)] * len(lane)
        + [pl.BlockSpec((1, 1), scal)] * 2,
        out_specs=[pl.BlockSpec((BC, H), col)] * n_hout
        + [pl.BlockSpec((BC, 1), col)] * (2 if has_sync else 1),
        out_shape=[jax.ShapeDtypeStruct((C, H), jnp.int32)] * n_hout
        + [jax.ShapeDtypeStruct((C, 1), jnp.int32)] * (2 if has_sync else 1),
        interpret=interpret_mode(),
    )(
        *ins,
        *[v.astype(jnp.int32)[:, None] for v in lane],
        jnp.asarray(link_lat, jnp.int32).reshape(1, 1),
        jnp.asarray(router_lat, jnp.int32).reshape(1, 1),
    )
    d_all = jnp.concatenate(out[:n_hout], axis=1)
    t_rep_end = out[n_hout][:, 0]
    t_arr_end = out[n_hout + 1][:, 0] if has_sync else None
    return t_rep_end, t_arr_end, d_all
