"""Pallas TPU kernel for the dense sharer-expansion reductions
(SURVEY.md §2 #4/#6's "part of the Pallas uncore kernel" column) — the
third resident kernel of the step subsystem (absorbed from
ops/reductions.py, which remains as an import shim).

The step's invalidation / back-invalidation reductions expand each
winner's packed sharer words into per-target-core booleans and reduce
latencies/counts/hops over the target axis — a dense [C_block, C] tiled
computation with NO data-dependent indexing, which is the shape TPU
Pallas handles well: the word->bit expansion is a static masked select
(Mosaic rejects the reshape `jnp.repeat` would emit), and pair
latencies come from index arithmetic. `pallas_reduce=true` in
MachineConfig routes the engine's full-map dense path through this
kernel (and `step_impl="pallas"` routes it unconditionally); results are
BIT-IDENTICAL to the jnp path (tests/test_pallas.py runs the golden
parity suite through it).

Link/router latencies arrive as TRACED (1, 1) scalar inputs, not static
kwargs: the fleet engine's jit key is the timing-normalized geometry and
real timing lives in the traced knob pytree, so baking `cfg.noc` values
into the kernel would silently mistime every swept element.

On non-TPU backends the kernel runs in Pallas interpreter mode, so the
parity suite exercises the identical kernel logic on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..config.machine import MachineConfig
from .layouts import core_block, interpret_mode


def _expand_bits(words, t, NW: int):
    """[BC, NW] packed words -> [BC, NW*32] per-target booleans, column
    c = bit (c % 32) of word (c // 32). Static masked select per word:
    Mosaic-friendly (no minor-dim reshape, no gather)."""
    wsel = t >> 5
    rep = jnp.zeros(t.shape, jnp.int32)
    for w in range(NW):
        rep = rep + jnp.where(wsel == w, words[:, w][:, None], 0)
    return ((rep >> (t & 31)) & 1) != 0


def _reduce_kernel(
    shw_ref, vic_ref, btile_ref, vic_owner_ref, inv_row_ref, vic_valid_ref,
    self_ref, link_ref, router_ref,
    inv_lat_ref, inv_cnt_ref, inv_hops_ref, back_cnt_ref, back_hops_ref,
    *, C: int, NW: int, n_tiles: int, mesh_x: int, mesh_y: int,
    topology: str,
):
    BC = shw_ref.shape[0]
    t = jax.lax.broadcasted_iota(jnp.int32, (BC, NW * 32), 1)  # target ids
    bits = _expand_bits(shw_ref[...], t, NW)  # recorded targets
    vbits = _expand_bits(vic_ref[...], t, NW)
    tvalid = t < C
    # pair geometry: home tile of this row vs target tile, from indices;
    # latencies are the traced knobs ((1, 1) blocks broadcast per row)
    bt = btile_ref[...]  # [BC, 1]
    link_lat = link_ref[...]  # [1, 1]
    router_lat = router_ref[...]
    tt = t % n_tiles
    bx, by = bt % mesh_x, bt // mesh_x
    tx, ty = tt % mesh_x, tt // mesh_x
    # topology is a STATIC kwarg (part of the jit/exec-cache key via
    # timing_normalized); coord_hops is all elementwise min/abs/where
    # arithmetic, so every topology stays Mosaic-safe
    from ..noc.topology import coord_hops

    hops = coord_hops(topology, bx, by, tx, ty, mesh_x, mesh_y, xp=jnp)
    lat2 = 2 * (hops * link_lat + (hops + 1) * router_lat)
    hops2 = 2 * hops
    selfid = self_ref[...]
    inv_row = inv_row_ref[...] != 0
    sh_b = bits & (t != selfid) & inv_row & tvalid
    inv_lat_ref[...] = jnp.max(
        jnp.where(sh_b, lat2, 0), axis=1, keepdims=True
    )
    inv_cnt_ref[...] = jnp.sum(
        sh_b.astype(jnp.int32), axis=1, keepdims=True
    )
    inv_hops_ref[...] = jnp.sum(
        jnp.where(sh_b, hops2, 0), axis=1, keepdims=True
    )
    vic_owner = vic_owner_ref[...]
    vic_valid = vic_valid_ref[...] != 0
    ob = (t == vic_owner) & (vic_owner >= 0)
    bk_b = (vbits | ob) & vic_valid & tvalid
    back_cnt_ref[...] = jnp.sum(
        bk_b.astype(jnp.int32), axis=1, keepdims=True
    )
    back_hops_ref[...] = jnp.sum(
        jnp.where(bk_b, hops2, 0), axis=1, keepdims=True
    )


@functools.partial(jax.jit, static_argnums=(0,))
def sharer_reductions(
    cfg: MachineConfig, shw, vic_shw, btile, vic_owner, inv_row, vic_valid,
    arange_c, link_lat=None, router_lat=None,
):
    """Dense invalidation/back-invalidation reductions as one Pallas
    kernel: returns (inv_lat, inv_count, inv_hops, back_count,
    back_hops), each [C] int32 — bit-identical to the engine's jnp dense
    path. Full-map vectors only (cfg validation enforces it).
    `link_lat`/`router_lat` are the TRACED knob scalars (the engine
    passes `kn.link_lat`/`kn.router_lat`); they default to the config
    values only for direct standalone calls."""
    C = cfg.n_cores
    NW = cfg.n_sharer_words
    BC = core_block(C)
    if link_lat is None:
        link_lat = cfg.noc.link_lat
    if router_lat is None:
        router_lat = cfg.noc.router_lat
    kern = functools.partial(
        _reduce_kernel,
        C=C,
        NW=NW,
        n_tiles=cfg.n_tiles,
        mesh_x=cfg.noc.mesh_x,
        mesh_y=cfg.noc.mesh_y,
        topology=cfg.noc.topology,
    )
    col = lambda i: (i, 0)
    scal = lambda i: (0, 0)
    out = pl.pallas_call(
        kern,
        grid=(C // BC,),
        in_specs=[
            pl.BlockSpec((BC, NW), col),
            pl.BlockSpec((BC, NW), col),
        ]
        + [pl.BlockSpec((BC, 1), col)] * 5
        + [pl.BlockSpec((1, 1), scal)] * 2,
        out_specs=[pl.BlockSpec((BC, 1), col)] * 5,
        out_shape=[jax.ShapeDtypeStruct((C, 1), jnp.int32)] * 5,
        interpret=interpret_mode(),
    )(
        shw.astype(jnp.int32),
        vic_shw.astype(jnp.int32),
        btile.astype(jnp.int32)[:, None],
        vic_owner.astype(jnp.int32)[:, None],
        inv_row.astype(jnp.int32)[:, None],
        vic_valid.astype(jnp.int32)[:, None],
        arange_c.astype(jnp.int32)[:, None],
        jnp.asarray(link_lat, jnp.int32).reshape(1, 1),
        jnp.asarray(router_lat, jnp.int32).reshape(1, 1),
    )
    return tuple(o[:, 0] for o in out)
