"""Pallas TPU step-kernel subsystem (DESIGN.md §11).

The engine's step body is a serial chain of dozens of small XLA
gather/scatter kernels whose PER-KERNEL overhead — not bytes — sets the
~2.8 ms/step floor at 1024 cores (DESIGN.md §9 postscript). This package
fuses the dominant serial segments into a few VMEM-resident Pallas
kernels, selected by `MachineConfig.step_impl == "pallas"`:

- `step_kernels.probe_classify` — phase 1 + the LLC home-row parse: L1
  set probe, pointer validation, hit classification, sharer predicates
  and victim selection, one kernel over core blocks.
- `step_kernels.commit_step` — phase 4.A + the counter fold: the fused
  L1 writes, the directory row delta, and the stacked counter add.
- `reductions.sharer_reductions` — the dense invalidation /
  back-invalidation reductions (absorbed from ops/reductions.py).

`layouts.py` pins the shared block geometry (core-block size, plane and
directory-row column maps) and the Mosaic-safe select/reduce idioms all
three kernels are written in. Every kernel is bit-exact vs the XLA step
(tests/test_step_pallas.py) and runs in interpreter mode off-TPU.
"""

from .layouts import core_block  # noqa: F401
from .reductions import sharer_reductions  # noqa: F401
from .step_kernels import commit_step, probe_classify  # noqa: F401
