"""Shared block geometry + Mosaic-safe idioms for the step kernels.

VMEM BLOCK LAYOUT (DESIGN.md §11). All step kernels block the CORE axis:
grid = (C // core_block(C),), every per-core operand arrives as a
[BC, width] VMEM block with index map `lambda i: (i, 0)` (the counter
array, [n_counters, C], blocks its LANE axis instead: `lambda i: (0, i)`).
Widths are the engine's own fused-array layouts, staged verbatim:

- L1 block: [BC, 5 * W1 * S1] — five planes (tag/state/lru/ptr/epoch) at
  an FS = W1*S1 column stride, way w of set s of plane p at column
  p*FS + w*S1 + s (sim/state.py).
- Directory rows: [BC, DW] — tag/owner pairs at columns 2w / 2w+1, LRU at
  2*W2 + w, epoch at 3*W2 + w, zero padding to MW = llc_meta_width, then
  sharer word n of way w at MW + w*NW + n (sim/state.py).
- Lane vectors ([C] classification flags and ids) ride as [BC, 1]
  columns; traced step scalars as (1, 1) blocks broadcast to every grid
  step.

MOSAIC IDIOMS. TPU Pallas rejects minor-dim reshapes and data-dependent
gathers, so every "index with a computed id" becomes a static unroll of
masked selects (`select_col`, `across`) and every argmax/argmin becomes
the first-occurrence emulation (`first_true` / `first_min`) — all
bit-exact against the XLA step's jnp.argmax/argmin/take_along_axis
semantics, which the parity suite proves.

Kernels must NOT derive core ids from `pl.program_id`: the fleet engine
vmaps the whole step, and the Pallas batching rule prepends a grid axis,
which would silently renumber the blocks. Global core ids arrive as a
[BC, 1] input instead (`sharer_reductions` set the pattern).

These layouts are also the reason fault injection (DESIGN.md §12) never
touches kernel code: fault effects are expressed entirely on the staged
operands (a pre-gather `dirm` scrub, lane-predicate masking, post-fold
latency/counter addends), and the counter fold is width-generic over
`counters.shape[0]` — adding the fault counters changed no block spec.
See the FAULT-LANE CONTRACT note in step_kernels.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def core_block(C: int) -> int:
    """Core-axis block size: full 128-lane blocks when the core count
    allows, else one block of all C cores (small test geometries)."""
    return 128 if C % 128 == 0 else C


def interpret_mode() -> bool:
    """Run kernels in Pallas interpreter mode off-TPU so the identical
    kernel logic is exercised (and tier-1-gated) on CPU."""
    return jax.default_backend() != "tpu"


def block_spec(width: int):
    """BlockSpec tuple args for a [BC, width] core-axis block."""
    return width, (lambda i: (i, 0))


def select_col(mat, idx, ncols: int, colf=None):
    """mat[:, colf(v)] at v = idx per row — a data-dependent column pick
    as a static unroll of masked adds. `mat` [BC, W], `idx` [BC, 1],
    colf maps v -> static column (default identity). Returns [BC, 1]."""
    colf = colf or (lambda v: v)
    acc = jnp.zeros_like(idx)
    for v in range(ncols):
        c = colf(v)
        acc = acc + jnp.where(idx == v, mat[:, c : c + 1], 0)
    return acc


def across(vals, width: int):
    """Pack a list of `width` [BC, 1] columns into one [BC, width] value
    via one-hot masked adds (no concatenate on the lane dim)."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, width), 1)
    acc = jnp.zeros((vals[0].shape[0], width), jnp.int32)
    for k, v in enumerate(vals):
        acc = acc + jnp.where(iota == k, v.astype(jnp.int32), 0)
    return acc


def first_true(mask):
    """jnp.argmax semantics over axis 1 of a [BC, W] bool: index of the
    FIRST True, 0 when none. Returns ([BC, 1] any, [BC, 1] index)."""
    W = mask.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
    any_ = jnp.max(mask.astype(jnp.int32), axis=1, keepdims=True) != 0
    idx = jnp.min(jnp.where(mask, iota, W), axis=1, keepdims=True)
    return any_, jnp.where(any_, idx, 0)


def first_min(vals):
    """jnp.argmin semantics over axis 1 of a [BC, W] int32: index of the
    FIRST minimum. Returns [BC, 1]."""
    W = vals.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
    m = jnp.min(vals, axis=1, keepdims=True)
    return jnp.min(jnp.where(vals == m, iota, W), axis=1, keepdims=True)


def cummax_rows(vals):
    """jax.lax.cummax(axis=1) semantics over a [BC, W] int32: inclusive
    running max along the lane dim as a static unroll of masked reduces
    (one masked max + one-hot select per output column — Mosaic has no
    lane-dim scan or shift). Bit-exact vs lax.cummax: integer max is
    associative, so the per-column reduce IS the prefix."""
    W = vals.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
    NEG = jnp.iinfo(jnp.int32).min
    out = jnp.zeros_like(vals)
    for k in range(W):
        m = jnp.max(jnp.where(iota <= k, vals, NEG), axis=1, keepdims=True)
        out = jnp.where(iota == k, m, out)
    return out


def popcount(x):
    """Per-element bit count of nonneg int32 words, shift/mask form (no
    multiply that could wrap; matches lax.population_count exactly)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    x = x + (x >> 8)
    x = x + (x >> 16)
    return x & 0x3F
