/* ocean_like — a small SPLASH-2-ocean-shaped pthread workload for the
 * capture frontend: phases of private grid relaxation (memcpy traffic)
 * separated by a global barrier, plus a mutex-protected global reduction
 * each phase. Deterministic event STRUCTURE per thread (counts of
 * lock/unlock/barrier and memcpy lines), so tests can assert the captured
 * trace shape exactly.
 *
 * Build: gcc -O2 -o ocean_like ocean_like.c -lpthread
 * Usage: ocean_like [n_threads] [n_phases] [rows_per_thread]
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define COLS 256 /* 1KB rows: 16 cache lines per row */

static int n_threads = 4, n_phases = 3, rows = 8;
static pthread_barrier_t phase_barrier;
static pthread_mutex_t sum_mu = PTHREAD_MUTEX_INITIALIZER;
static double global_sum = 0.0;

static void* worker(void* argp) {
  long id = (long)argp;
  double* grid = malloc(sizeof(double) * rows * COLS);
  double* next = malloc(sizeof(double) * rows * COLS);
  for (int i = 0; i < rows * COLS; i++) grid[i] = id + i * 1e-6;

  for (int p = 0; p < n_phases; p++) {
    double local = 0.0;
    for (int r = 0; r < rows; r++) {
      for (int c = 1; c < COLS - 1; c++) {
        double v = 0.5 * grid[r * COLS + c] +
                   0.25 * (grid[r * COLS + c - 1] + grid[r * COLS + c + 1]);
        next[r * COLS + c] = v;
        local += v;
      }
      /* row copy-back: real memcpy traffic the shim captures as LD/ST */
      memcpy(&grid[r * COLS], &next[r * COLS], sizeof(double) * COLS);
    }
    pthread_mutex_lock(&sum_mu);
    global_sum += local;
    pthread_mutex_unlock(&sum_mu);
    pthread_barrier_wait(&phase_barrier);
  }
  free(grid);
  free(next);
  return NULL;
}

int main(int argc, char** argv) {
  if (argc > 1) n_threads = atoi(argv[1]);
  if (argc > 2) n_phases = atoi(argv[2]);
  if (argc > 3) rows = atoi(argv[3]);
  pthread_barrier_init(&phase_barrier, NULL, n_threads);
  pthread_t t[256];
  /* main thread is captured as core 0 but does no phase work */
  for (long i = 0; i < n_threads; i++)
    pthread_create(&t[i], NULL, worker, (void*)i);
  for (int i = 0; i < n_threads; i++) pthread_join(t[i], NULL);
  printf("ocean_like done: threads=%d phases=%d sum=%.3f\n", n_threads,
         n_phases, global_sum);
  return 0;
}
