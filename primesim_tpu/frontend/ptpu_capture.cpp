// ptpu_capture — LD_PRELOAD execution-capture frontend (SURVEY.md §2 #1).
//
// The reference's Pin tool instruments every instruction and intercepts
// pthread routines so target synchronization is modeled rather than
// host-timed (SURVEY.md §3.2/3.5). This shim is the same idea at
// LD_PRELOAD granularity: it interposes pthread_create/mutex/barrier,
// counts REAL retired instructions between events with perf_event_open
// (PERF_COUNT_HW_INSTRUCTIONS per thread; falls back to a TSC-based
// estimate, then to zero, when perf is unavailable in the container), and
// optionally captures memcpy/memset as line-granular LD/ST traffic. On
// process exit it writes a PTPU v4 binary trace (primesim_tpu/trace/
// format.py layout, line_addressed flag) ready for `primetpu run --trace`.
//
// Environment:
//   PTPU_TRACE_OUT      output path (default ptpu_capture.ptpu)
//   PTPU_MAX_CORES      thread slots (default 256)
//   PTPU_MAX_EVENTS     per-thread event cap (default 1<<20)
//   PTPU_CAPTURE_MEMOPS 1 = interpose memcpy/memset as LD/ST (default 1)
//   PTPU_LINE           cache-line bytes for memop expansion (default 64)
//   PTPU_MEMOP_MAX_LINES max lines emitted per memcpy/memset (default 64)
//   PTPU_RING_OUT       ONLINE MODE: mmap'd shared-memory ring file the
//                       host simulator drains WHILE this process runs
//                       (SURVEY.md §2 #9's shared-memory queue fast path;
//                       replaces the end-of-run trace file — events go
//                       straight to per-thread SPSC rings)
//   PTPU_RING_RECORDS   per-thread ring capacity in 16-byte records
//                       (default 1<<16)
//   PTPU_RING_TIMEOUT_MS max wait on a full ring before dropping events
//                       (default 30000; a vanished host must not hang
//                       the target forever)
//
// Addresses are emitted LINE-granular (PTPU v4 line_addressed flag): the
// 31-bit addr field holds `byte_address / PTPU_LINE`, widening coverage
// 64x over byte addressing (2^31 lines = 128 GiB at 64-byte lines; line
// indices beyond that still alias under the 31-bit mask — a 2x32-bit
// record is the future fully-un-aliased path). The capture line size is
// recorded in flags bits 8-15 so engines reject mismatched configs.
// Mutex addresses identify the lock by line; barrier ids are dense
// registration indices with the participant count from
// pthread_barrier_init.
//
// Build: g++ -O2 -shared -fPIC -o libptpu_capture.so ptpu_capture.cpp -ldl -lpthread

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <dlfcn.h>
#include <fcntl.h>
#include <linux/perf_event.h>
#include <pthread.h>
#include <sched.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

namespace {

// ---- event model (trace/format.py) ----------------------------------------
constexpr int32_t EV_INS = 0, EV_LD = 1, EV_ST = 2, EV_END = 3;
constexpr int32_t EV_LOCK = 4, EV_UNLOCK = 5, EV_BARRIER = 6;
constexpr uint32_t PTPU_MAGIC = 0x50545055u;
constexpr uint32_t PTPU_VERSION = 4;
constexpr uint32_t FLAG_LINE_ADDRESSED = 1;  // v4: addr = line index
constexpr int32_t ADDR_MASK = 0x7fffffff;
// Per-event instruction-batch cap: keeps the engine's per-chunk counter
// accumulators far from their 2^30 carry bound at default chunk sizes.
constexpr int64_t MAX_BATCH = 1 << 20;

struct Event {
  int32_t type, arg, addr, pre;
};

// ---- online shared-memory ring (PTPU_RING_OUT) ----------------------------
// One SPSC ring per thread slot inside one mmap'd file the host simulator
// maps concurrently. The thread is the only writer of `widx` and the data
// it guards (release-published); the host is the only writer of `ridx`.
// File layout (all little-endian):
//   [0..64)                      RingHeader
//   [64 .. 64 + n*64)            RingCtl per thread slot (cacheline each)
//   [data0 ...]                  n rings of `records` 16-byte events
constexpr uint32_t RING_MAGIC = 0x50525247u;  // 'PRRG'
constexpr uint32_t RING_VERSION = 1;
constexpr uint32_t RSTATE_UNUSED = 0, RSTATE_ACTIVE = 1, RSTATE_DONE = 2;

struct RingHeader {
  uint32_t magic, version;
  uint32_t max_cores, records;
  uint32_t line, flags;
  // producer_done: set once by the exit hook after every row is flushed —
  // the host treats (producer_done && state != ACTIVE && drained) as EOF
  std::atomic<uint32_t> producer_done;
  uint32_t _pad[9];
};
static_assert(sizeof(RingHeader) == 64, "ring header layout");

struct RingCtl {
  std::atomic<uint64_t> widx;  // thread-owned
  std::atomic<uint64_t> ridx;  // host-owned
  std::atomic<uint32_t> state;
  uint32_t _pad0;
  std::atomic<uint64_t> dropped;
  uint32_t _pad[8];
};
static_assert(sizeof(RingCtl) == 64, "ring ctl layout");

uint8_t* g_ring_base = nullptr;  // mmap'd file; null = offline capture
RingHeader* g_ring_hdr = nullptr;
RingCtl* g_ring_ctl = nullptr;
Event* g_ring_data = nullptr;
uint32_t g_ring_records = 1 << 16;
int64_t g_ring_timeout_ms = 30000;

struct ThreadRec {
  Event* ev = nullptr;
  int64_t n = 0;
  int64_t cap = 0;
  int64_t dropped = 0;
  int64_t n_mem = 0;   // captured LD/ST line events (coverage stat)
  int64_t n_sync = 0;  // captured lock/unlock/barrier events
  int64_t n_ins = 0;   // instructions attributed via perf/TSC
  int perf_fd = -1;
  uint64_t last_count = 0;  // instructions (or TSC) at last event
  bool tsc_fallback = false;
  bool active = false;
  // guards ev/n/cap between the owning thread's emits and write_trace()
  // flushing at process exit while unjoined threads still run (a real
  // program may exit() without joining workers)
  std::atomic_flag mu = ATOMIC_FLAG_INIT;
  void lock() {
    while (mu.test_and_set(std::memory_order_acquire)) {
    }
  }
  void unlock() { mu.clear(std::memory_order_release); }
};

int g_max_cores = 256;
int64_t g_max_events = 1 << 20;
bool g_capture_memops = true;
int g_line = 64;
int g_memop_max_lines = 64;
ThreadRec* g_threads = nullptr;
std::atomic<int> g_next_core{0};
std::atomic<int> g_next_barrier_id{0};
// set at trace-write time; emits from unjoined threads then drop, so the
// recorded row lengths stay consistent with the rows written
std::atomic<bool> g_shutdown{false};
pthread_mutex_t g_reg_mu = PTHREAD_MUTEX_INITIALIZER;

// barrier registry: pthread_barrier_t* -> (dense id, participant count)
struct BarrierRec {
  void* key;
  int32_t id;
  int32_t count;
};
BarrierRec* g_barriers = nullptr;
int g_n_barriers = 0, g_barriers_cap = 0;

thread_local int t_core = -1;
thread_local bool t_in_shim = false;  // recursion guard (memcpy in shim)

// real libc/libpthread entry points
int (*real_pthread_create)(pthread_t*, const pthread_attr_t*,
                           void* (*)(void*), void*) = nullptr;
int (*real_mutex_lock)(pthread_mutex_t*) = nullptr;
int (*real_mutex_trylock)(pthread_mutex_t*) = nullptr;
int (*real_mutex_unlock)(pthread_mutex_t*) = nullptr;
int (*real_barrier_init)(pthread_barrier_t*, const pthread_barrierattr_t*,
                         unsigned) = nullptr;
int (*real_barrier_wait)(pthread_barrier_t*) = nullptr;
void* (*real_memcpy)(void*, const void*, size_t) = nullptr;
void* (*real_memset)(void*, int, size_t) = nullptr;

template <typename T>
void resolve(T& fn, const char* name) {
  fn = reinterpret_cast<T>(dlsym(RTLD_NEXT, name));
}

// ---- retired-instruction counting -----------------------------------------

uint64_t rdtsc_now() {
#if defined(__x86_64__)
  uint32_t lo, hi;
  __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
  return (uint64_t(hi) << 32) | lo;
#else
  return 0;  // no estimate on non-x86; INS batches become 0
#endif
}

int perf_open_self() {
  struct perf_event_attr pe;
  memset(&pe, 0, sizeof(pe));
  pe.type = PERF_TYPE_HARDWARE;
  pe.size = sizeof(pe);
  pe.config = PERF_COUNT_HW_INSTRUCTIONS;
  pe.disabled = 0;
  pe.exclude_kernel = 1;
  pe.exclude_hv = 1;
  return (int)syscall(__NR_perf_event_open, &pe, 0, -1, -1, 0);
}

uint64_t counter_read(ThreadRec& tr) {
  if (!tr.tsc_fallback) {
    uint64_t v = 0;
    if (tr.perf_fd >= 0 && read(tr.perf_fd, &v, sizeof(v)) == sizeof(v))
      return v;
    // permanent source switch — mixing perf values with TSC values would
    // fabricate a delta of ~TSC-since-boot; re-anchor on the new source
    tr.tsc_fallback = true;
    tr.last_count = rdtsc_now();
  }
  return rdtsc_now();  // (0 on non-x86: INS batches become 0)
}

// ---- per-thread event emission --------------------------------------------

void thread_register() {
  if (t_core >= 0) return;
  int c = g_next_core.fetch_add(1);
  if (c >= g_max_cores) {
    t_core = -2;  // overflow: capture nothing for this thread
    return;
  }
  t_core = c;
  ThreadRec& tr = g_threads[c];
  // Registration writes happen under tr.mu: write_trace()'s locked flush
  // pass can run concurrently when the process exits while a worker is
  // mid-registration, and without the lock `active = true` could become
  // visible before ev/cap under relaxed ordering (unsynchronized race).
  // t_in_shim guards the whole section: malloc/read below may call the
  // interposed memcpy/memset, whose emit would spin on the held tr.mu.
  bool saved_in_shim = t_in_shim;
  t_in_shim = true;
  tr.lock();
  if (!g_shutdown.load(std::memory_order_relaxed)) {
    if (!g_ring_base) {
      tr.ev = (Event*)malloc(sizeof(Event) * 4096);
      tr.cap = 4096;
    }
    tr.n = 0;
    tr.perf_fd = perf_open_self();
    tr.tsc_fallback = tr.perf_fd < 0;
    tr.last_count = counter_read(tr);
    tr.active = true;
    if (g_ring_base)
      g_ring_ctl[c].state.store(RSTATE_ACTIVE, std::memory_order_release);
  } else {
    t_core = -2;  // trace already written: capture nothing for this thread
  }
  tr.unlock();
  t_in_shim = saved_in_shim;
}

// instructions retired since the last event; TSC fallback scales cycles
// by an assumed IPC of 1 (documented estimate). Deltas are clamped at
// 16*MAX_BATCH (16M instructions between two events): anything larger is
// a counter glitch or host idle time, not workload, and the clamp bounds
// the INS-split fan-out per event.
int64_t ins_delta(ThreadRec& tr) {
  uint64_t now = counter_read(tr);
  int64_t d = (int64_t)(now - tr.last_count);
  tr.last_count = now;
  if (d < 0) return 0;
  return d > 16 * MAX_BATCH ? 16 * MAX_BATCH : d;
}

void ring_push(int core, const Event& e) {
  RingCtl& rc = g_ring_ctl[core];
  uint64_t w = rc.widx.load(std::memory_order_relaxed);
  if (w - rc.ridx.load(std::memory_order_acquire) >= g_ring_records) {
    // ring full: the host is behind (or gone). Briefly yield-spin, then
    // drop — a vanished consumer must not wedge the target program.
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (;;) {
      sched_yield();
      if (w - rc.ridx.load(std::memory_order_acquire) < g_ring_records)
        break;
      clock_gettime(CLOCK_MONOTONIC, &t1);
      int64_t ms = (t1.tv_sec - t0.tv_sec) * 1000 +
                   (t1.tv_nsec - t0.tv_nsec) / 1000000;
      if (ms > g_ring_timeout_ms) {
        rc.dropped.fetch_add(1, std::memory_order_relaxed);
        return;
      }
    }
  }
  g_ring_data[(uint64_t)core * g_ring_records + (w % g_ring_records)] = e;
  rc.widx.store(w + 1, std::memory_order_release);  // publish after data
}

void push_raw(ThreadRec& tr, int32_t type, int32_t arg, int32_t addr,
              int32_t pre) {
  if (g_ring_base) {
    // online mode: events go straight to this thread's SPSC ring; the
    // host simulator consumes them while the program runs
    ring_push((int)(&tr - g_threads), Event{type, arg, addr, pre});
    tr.n++;  // row length still tracked for the exit summary
    return;
  }
  if (tr.n >= g_max_events) {
    tr.dropped++;
    return;
  }
  if (tr.n == tr.cap) {
    int64_t nc = tr.cap * 2;
    Event* ne = (Event*)realloc(tr.ev, sizeof(Event) * nc);
    if (!ne) {
      tr.dropped++;
      return;
    }
    tr.ev = ne;
    tr.cap = nc;
  }
  tr.ev[tr.n++] = Event{type, arg, addr, pre};
}

// split an oversized pending batch into explicit INS events, returning
// the <= MAX_BATCH remainder to fold into the next event's `pre`
int64_t split_batch(ThreadRec& tr, int64_t pre) {
  while (pre > MAX_BATCH) {
    push_raw(tr, EV_INS, (int32_t)MAX_BATCH, 0, 0);
    pre -= MAX_BATCH;
  }
  return pre;
}

// flush the whole pending batch as explicit INS events (thread retiring
// or final trace write — no follow-on event to fold into)
void flush_pending(ThreadRec& tr) {
  int64_t pre = split_batch(tr, ins_delta(tr));
  if (pre > 0) push_raw(tr, EV_INS, (int32_t)pre, 0, 0);
}

// emit an event, folding the pending instruction batch into `pre`
// (PriME's per-BBL batching folded to event boundaries, SURVEY.md §3.2)
void emit(int32_t type, int32_t arg, int32_t addr) {
  if (t_core < 0 || g_shutdown.load(std::memory_order_relaxed)) return;
  ThreadRec& tr = g_threads[t_core];
  tr.lock();
  // re-check under the lock: write_trace sets g_shutdown BEFORE taking
  // rec locks, so any emit that wins the lock after the flush pass sees
  // the flag and drops, keeping row lengths frozen
  if (!g_shutdown.load(std::memory_order_relaxed)) {
    int64_t pre = split_batch(tr, ins_delta(tr));
    push_raw(tr, type, arg, addr, (int32_t)pre);
    tr.n_ins += pre;
    if (type == EV_LD || type == EV_ST)
      tr.n_mem++;
    else if (type != EV_INS)
      tr.n_sync++;
    // exclude our own bookkeeping from the next batch
    tr.last_count = counter_read(tr);
  }
  tr.unlock();
}

void emit_memops(int32_t type, const void* p, size_t len) {
  if (t_core < 0 || len == 0) return;
  uintptr_t a0 = (uintptr_t)p & ~(uintptr_t)(g_line - 1);
  uintptr_t a1 = ((uintptr_t)p + len - 1) & ~(uintptr_t)(g_line - 1);
  int64_t lines = (int64_t)((a1 - a0) / g_line) + 1;
  if (lines > g_memop_max_lines) lines = g_memop_max_lines;
  for (int64_t i = 0; i < lines; i++) {
    int32_t addr = (int32_t)((((a0 + i * g_line)) / (uintptr_t)g_line) & ADDR_MASK);
    emit(type, g_line, addr);
  }
}

// ---- barrier registry ------------------------------------------------------

void barrier_register(void* key, unsigned count) {
  if (!real_mutex_lock) resolve(real_mutex_lock, "pthread_mutex_lock");
  if (!real_mutex_unlock) resolve(real_mutex_unlock, "pthread_mutex_unlock");
  real_mutex_lock(&g_reg_mu);
  if (g_n_barriers == g_barriers_cap) {
    g_barriers_cap = g_barriers_cap ? g_barriers_cap * 2 : 64;
    g_barriers =
        (BarrierRec*)realloc(g_barriers, sizeof(BarrierRec) * g_barriers_cap);
  }
  g_barriers[g_n_barriers++] =
      BarrierRec{key, g_next_barrier_id.fetch_add(1), (int32_t)count};
  real_mutex_unlock(&g_reg_mu);
}

BarrierRec barrier_lookup(void* key) {
  if (!real_mutex_lock) resolve(real_mutex_lock, "pthread_mutex_lock");
  if (!real_mutex_unlock) resolve(real_mutex_unlock, "pthread_mutex_unlock");
  real_mutex_lock(&g_reg_mu);
  BarrierRec out{key, -1, 0};
  for (int i = g_n_barriers - 1; i >= 0; i--) {  // latest init wins
    if (g_barriers[i].key == key) {
      out = g_barriers[i];
      break;
    }
  }
  real_mutex_unlock(&g_reg_mu);
  return out;
}

// ---- trace writer ----------------------------------------------------------

void write_trace() {
  const char* path = getenv("PTPU_TRACE_OUT");
  if (!path || !*path) path = "ptpu_capture.ptpu";
  int n_cores = g_next_core.load();
  if (n_cores > g_max_cores) n_cores = g_max_cores;
  if (n_cores == 0) return;

  if (g_ring_base) {
    // online mode: flush trailing batches into the rings, mark every row
    // finished, and publish producer_done — the host drains the rest
    g_shutdown.store(true, std::memory_order_seq_cst);
    int64_t total_dropped = 0;
    for (int c = 0; c < n_cores; c++) {
      ThreadRec& tr = g_threads[c];
      tr.lock();
      if (tr.active) flush_pending(tr);
      g_ring_ctl[c].state.store(RSTATE_DONE, std::memory_order_release);
      total_dropped +=
          (int64_t)g_ring_ctl[c].dropped.load(std::memory_order_relaxed);
      tr.unlock();
    }
    g_ring_hdr->producer_done.store(1, std::memory_order_release);
    fprintf(stderr, "ptpu_capture: ring done (%d threads%s)\n", n_cores,
            total_dropped ? ", EVENTS DROPPED on full ring" : "");
    return;
  }

  g_shutdown.store(true, std::memory_order_seq_cst);
  int64_t max_len = 1;
  int64_t total_dropped = 0;
  for (int c = 0; c < n_cores; c++) {
    // flush the trailing instruction batch of still-registered threads
    // (unjoined threads' emits drop once g_shutdown is visible, so after
    // this locked pass every row length is frozen)
    ThreadRec& tr = g_threads[c];
    tr.lock();
    if (tr.active) flush_pending(tr);
    total_dropped += tr.dropped;
    if (tr.n + 1 > max_len) max_len = tr.n + 1;  // +1 for END
    tr.unlock();
  }

  FILE* f = fopen(path, "wb");
  if (!f) {
    fprintf(stderr, "ptpu_capture: cannot open %s\n", path);
    return;
  }
  uint32_t line_bits = 0;
  for (int l = g_line; l > 1; l >>= 1) line_bits++;
  uint32_t hdr[5] = {PTPU_MAGIC, PTPU_VERSION, (uint32_t)n_cores,
                     (uint32_t)max_len,
                     FLAG_LINE_ADDRESSED | (line_bits << 8)};
  fwrite(hdr, sizeof(uint32_t), 5, f);
  for (int c = 0; c < n_cores; c++) {
    uint32_t len = (uint32_t)(g_threads[c].n + 1);
    fwrite(&len, sizeof(uint32_t), 1, f);
  }
  Event end{EV_END, 0, 0, 0};
  for (int c = 0; c < n_cores; c++) {
    ThreadRec& tr = g_threads[c];
    tr.lock();
    int64_t n = tr.n;  // freeze this row: no emits can interleave
    if (n) fwrite(tr.ev, sizeof(Event), (size_t)n, f);
    tr.unlock();
    for (int64_t i = n; i < max_len; i++) fwrite(&end, sizeof(Event), 1, f);
  }
  fclose(f);
  int64_t t_mem = 0, t_sync = 0, t_ins = 0;
  for (int c = 0; c < n_cores; c++) {
    t_mem += g_threads[c].n_mem;
    t_sync += g_threads[c].n_sync;
    t_ins += g_threads[c].n_ins;
  }
  fprintf(stderr,
          "ptpu_capture: wrote %s (%d threads, max %lld events%s%s)\n", path,
          n_cores, (long long)(max_len - 1),
          g_threads[0].tsc_fallback ? ", TSC-estimate INS" : ", perf INS",
          total_dropped ? ", EVENTS DROPPED at cap" : "");
  // capture-coverage honesty (SURVEY.md §2 #1): unlike Pin, this shim
  // sees memory traffic only at interposed library calls (mem*/str*) and
  // ptpu_annotate.h hooks — ordinary loads/stores appear solely inside
  // the instruction batches
  fprintf(stderr,
          "ptpu_capture: coverage: %lld mem-line events, %lld sync events, "
          "%lld instructions in batches; ordinary loads/stores OUTSIDE "
          "interposed calls/annotations are NOT captured as traffic\n",
          (long long)t_mem, (long long)t_sync, (long long)t_ins);
}

struct Init {
  Init() {
    resolve(real_pthread_create, "pthread_create");
    resolve(real_mutex_lock, "pthread_mutex_lock");
    resolve(real_mutex_trylock, "pthread_mutex_trylock");
    resolve(real_mutex_unlock, "pthread_mutex_unlock");
    resolve(real_barrier_init, "pthread_barrier_init");
    resolve(real_barrier_wait, "pthread_barrier_wait");
    resolve(real_memcpy, "memcpy");
    resolve(real_memset, "memset");
    if (const char* v = getenv("PTPU_MAX_CORES")) g_max_cores = atoi(v);
    if (const char* v = getenv("PTPU_MAX_EVENTS")) g_max_events = atoll(v);
    if (const char* v = getenv("PTPU_CAPTURE_MEMOPS"))
      g_capture_memops = atoi(v) != 0;
    if (const char* v = getenv("PTPU_LINE")) {
      int l = atoi(v);
      if (l > 0 && (l & (l - 1)) == 0)
        g_line = l;
      else
        fprintf(stderr, "ptpu_capture: PTPU_LINE=%s invalid (want a power "
                        "of two), using %d\n", v, g_line);
    }
    if (const char* v = getenv("PTPU_MEMOP_MAX_LINES"))
      g_memop_max_lines = atoi(v) > 0 ? atoi(v) : g_memop_max_lines;
    if (const char* ring = getenv("PTPU_RING_OUT"); ring && *ring) {
      if (const char* v = getenv("PTPU_RING_RECORDS")) {
        long r = atol(v);
        if (r >= 64) g_ring_records = (uint32_t)r;
      }
      if (const char* v = getenv("PTPU_RING_TIMEOUT_MS"))
        g_ring_timeout_ms = atoll(v);
      size_t bytes = sizeof(RingHeader) +
                     (size_t)g_max_cores * sizeof(RingCtl) +
                     (size_t)g_max_cores * g_ring_records * sizeof(Event);
      int fd = open(ring, O_RDWR | O_CREAT | O_TRUNC, 0644);
      if (fd >= 0 && ftruncate(fd, (off_t)bytes) == 0) {
        void* m = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                       fd, 0);
        if (m != MAP_FAILED) {
          memset(m, 0, sizeof(RingHeader) +
                           (size_t)g_max_cores * sizeof(RingCtl));
          g_ring_base = (uint8_t*)m;
          g_ring_ctl = (RingCtl*)(g_ring_base + sizeof(RingHeader));
          g_ring_data = (Event*)((uint8_t*)g_ring_ctl +
                                 (size_t)g_max_cores * sizeof(RingCtl));
          g_ring_hdr = (RingHeader*)g_ring_base;
          uint32_t line_bits = 0;
          for (int l = g_line; l > 1; l >>= 1) line_bits++;
          g_ring_hdr->max_cores = (uint32_t)g_max_cores;
          g_ring_hdr->records = g_ring_records;
          g_ring_hdr->line = (uint32_t)g_line;
          g_ring_hdr->flags = FLAG_LINE_ADDRESSED | (line_bits << 8);
          g_ring_hdr->version = RING_VERSION;
          // magic last, release: a host that sees the magic sees a fully
          // initialized header
          std::atomic_thread_fence(std::memory_order_release);
          g_ring_hdr->magic = RING_MAGIC;
          msync(m, sizeof(RingHeader), MS_SYNC);
        } else {
          fprintf(stderr, "ptpu_capture: mmap(%s) failed, offline mode\n",
                  ring);
        }
      } else {
        fprintf(stderr, "ptpu_capture: cannot create ring %s, offline mode\n",
                ring);
      }
      if (fd >= 0) close(fd);
    }
    g_threads = new ThreadRec[g_max_cores]();
    thread_register();  // main thread = core 0
  }
  ~Init() { write_trace(); }
};
Init g_init __attribute__((init_priority(150)));

struct TrampolineArg {
  void* (*fn)(void*);
  void* arg;
};

void* thread_trampoline(void* p) {
  TrampolineArg a = *(TrampolineArg*)p;
  free(p);
  thread_register();
  void* r = a.fn(a.arg);
  if (t_core >= 0) {
    // flush the thread's trailing instruction batch while it still runs
    // (t_in_shim: flush may realloc, whose memcpy would re-enter emit and
    // spin on the held tr.mu)
    ThreadRec& tr = g_threads[t_core];
    bool saved_in_shim = t_in_shim;
    t_in_shim = true;
    tr.lock();
    if (!g_shutdown.load(std::memory_order_relaxed)) flush_pending(tr);
    tr.active = false;
    if (g_ring_base)
      g_ring_ctl[t_core].state.store(RSTATE_DONE, std::memory_order_release);
    tr.unlock();
    t_in_shim = saved_in_shim;
  }
  return r;
}

}  // namespace

extern "C" {

int pthread_create(pthread_t* t, const pthread_attr_t* at, void* (*fn)(void*),
                   void* arg) {
  if (!real_pthread_create) resolve(real_pthread_create, "pthread_create");
  TrampolineArg* p = (TrampolineArg*)malloc(sizeof(TrampolineArg));
  p->fn = fn;
  p->arg = arg;
  return real_pthread_create(t, at, thread_trampoline, p);
}

int pthread_mutex_lock(pthread_mutex_t* m) {
  if (!real_mutex_lock) resolve(real_mutex_lock, "pthread_mutex_lock");
  if (t_core >= 0 && !t_in_shim) {
    t_in_shim = true;
    emit(EV_LOCK, 0, (int32_t)(((uintptr_t)m / (uintptr_t)g_line) & ADDR_MASK));
    t_in_shim = false;
  }
  return real_mutex_lock(m);
}

int pthread_mutex_trylock(pthread_mutex_t* m) {
  if (!real_mutex_trylock)
    resolve(real_mutex_trylock, "pthread_mutex_trylock");
  int r = real_mutex_trylock(m);
  if (r == 0 && t_core >= 0 && !t_in_shim) {
    t_in_shim = true;
    emit(EV_LOCK, 0, (int32_t)(((uintptr_t)m / (uintptr_t)g_line) & ADDR_MASK));
    t_in_shim = false;
  }
  return r;
}

int pthread_mutex_unlock(pthread_mutex_t* m) {
  if (!real_mutex_unlock) resolve(real_mutex_unlock, "pthread_mutex_unlock");
  if (t_core >= 0 && !t_in_shim) {
    t_in_shim = true;
    emit(EV_UNLOCK, 0, (int32_t)(((uintptr_t)m / (uintptr_t)g_line) & ADDR_MASK));
    t_in_shim = false;
  }
  return real_mutex_unlock(m);
}

int pthread_barrier_init(pthread_barrier_t* b, const pthread_barrierattr_t* at,
                         unsigned count) {
  if (!real_barrier_init) resolve(real_barrier_init, "pthread_barrier_init");
  barrier_register((void*)b, count);
  return real_barrier_init(b, at, count);
}

int pthread_barrier_wait(pthread_barrier_t* b) {
  if (!real_barrier_wait) resolve(real_barrier_wait, "pthread_barrier_wait");
  if (t_core >= 0 && !t_in_shim) {
    BarrierRec r = barrier_lookup((void*)b);
    if (r.id >= 0) {
      t_in_shim = true;
      emit(EV_BARRIER, r.count, r.id);
      t_in_shim = false;
    }
  }
  return real_barrier_wait(b);
}

void* memcpy(void* dst, const void* src, size_t n) {
  if (!real_memcpy) resolve(real_memcpy, "memcpy");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, src, n);
    emit_memops(EV_ST, dst, n);
    t_in_shim = false;
  }
  return real_memcpy(dst, src, n);
}

void* memset(void* dst, int v, size_t n) {
  if (!real_memset) resolve(real_memset, "memset");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_ST, dst, n);
    t_in_shim = false;
  }
  return real_memset(dst, v, n);
}

// ---- wider interposition surface (VERDICT r4 #9): memmove/memcmp/str*
// calls are line-granular memory traffic exactly like memcpy. Each
// resolves its real entry lazily and guards recursion with t_in_shim.

void* memmove(void* dst, const void* src, size_t n) {
  static void* (*real)(void*, const void*, size_t) = nullptr;
  if (!real) resolve(real, "memmove");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, src, n);
    emit_memops(EV_ST, dst, n);
    t_in_shim = false;
  }
  return real(dst, src, n);
}

int memcmp(const void* a, const void* b, size_t n) {
  static int (*real)(const void*, const void*, size_t) = nullptr;
  if (!real) resolve(real, "memcmp");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, a, n);
    emit_memops(EV_LD, b, n);
    t_in_shim = false;
  }
  return real(a, b, n);
}

size_t strlen(const char* s) {
  static size_t (*real)(const char*) = nullptr;
  if (!real) resolve(real, "strlen");
  size_t n = real(s);
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, s, n + 1);
    t_in_shim = false;
  }
  return n;
}

char* strcpy(char* dst, const char* src) {  // NOLINT
  static char* (*real)(char*, const char*) = nullptr;
  static size_t (*real_len)(const char*) = nullptr;
  if (!real) resolve(real, "strcpy");
  if (!real_len) resolve(real_len, "strlen");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    size_t n = real_len(src) + 1;
    emit_memops(EV_LD, src, n);
    emit_memops(EV_ST, dst, n);
    t_in_shim = false;
  }
  return real(dst, src);
}

char* strncpy(char* dst, const char* src, size_t n) {
  static char* (*real)(char*, const char*, size_t) = nullptr;
  if (!real) resolve(real, "strncpy");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, src, n);
    emit_memops(EV_ST, dst, n);
    t_in_shim = false;
  }
  return real(dst, src, n);
}

int strcmp(const char* a, const char* b) {
  static int (*real)(const char*, const char*) = nullptr;
  static size_t (*real_len)(const char*) = nullptr;
  if (!real) resolve(real, "strcmp");
  if (!real_len) resolve(real_len, "strlen");
  if (g_capture_memops && t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    size_t n = real_len(a) + 1;
    emit_memops(EV_LD, a, n);
    emit_memops(EV_LD, b, n);
    t_in_shim = false;
  }
  return real(a, b);
}

// ---- user annotation hooks (frontend/ptpu_annotate.h) ---------------------
// An application (or an instrumented build) can report ORDINARY loads and
// stores the library-call surface cannot see. No-ops unless running under
// the shim.

void ptpu_capture_load(const void* p, size_t n) {
  if (t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_LD, p, n);
    t_in_shim = false;
  }
}

void ptpu_capture_store(const void* p, size_t n) {
  if (t_core >= 0 && !t_in_shim && g_threads) {
    t_in_shim = true;
    emit_memops(EV_ST, p, n);
    t_in_shim = false;
  }
}

}  // extern "C"
