/* ptpu_annotate.h — user annotation hooks for the capture frontend.
 *
 * The LD_PRELOAD shim (ptpu_capture.cpp) observes memory traffic only at
 * interposed library calls (memcpy/memset/memmove/memcmp/str*). A target
 * program can report its ORDINARY loads and stores explicitly:
 *
 *     #include "ptpu_annotate.h"
 *     for (i = 0; i < n; i++) sum += a[i];
 *     PTPU_LOAD(a, n * sizeof(a[0]));   // tell the simulator about it
 *
 * The hooks resolve dynamically and are no-ops when the program runs
 * without the shim, so annotated binaries need no build-time dependency.
 */
#ifndef PTPU_ANNOTATE_H_
#define PTPU_ANNOTATE_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* weak: defined by libptpu_capture.so when preloaded, absent otherwise */
void ptpu_capture_load(const void* p, size_t n) __attribute__((weak));
void ptpu_capture_store(const void* p, size_t n) __attribute__((weak));

#define PTPU_LOAD(p, n) \
  do { if (ptpu_capture_load) ptpu_capture_load((p), (n)); } while (0)
#define PTPU_STORE(p, n) \
  do { if (ptpu_capture_store) ptpu_capture_store((p), (n)); } while (0)

#ifdef __cplusplus
}
#endif

#endif /* PTPU_ANNOTATE_H_ */
