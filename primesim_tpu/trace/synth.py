"""Synthetic workload trace generators.

Stand-ins for the reference's benchmark inputs (SPLASH-2 / PARSEC binaries run
under Pin, SURVEY.md §4). Each generator emits the access *pattern class* of a
benchmark family so cache/coherence/NoC behavior is representative and the
expected statistics are analyzable:

- ``uniform_random``  — uncorrelated loads/stores over a working set
- ``stream``          — sequential streaming (stride = line), low reuse
- ``pointer_chase``   — dependent chain, one hot line at a time per core
- ``false_sharing``   — all cores hammer distinct words of the SAME lines
                        (coherence ping-pong; the MESI stress test)
- ``fft_like``        — phases of private strided work + butterfly exchange
                        with partner cores (SPLASH-2 FFT communication shape)
- ``readers_writer``  — one producer writes a block, all others read it
                        (invalidation broadcast shape)
- ``lock_contention`` — cores hammer a small set of mutexes around short
                        critical sections (pthread_mutex shape; LOCK/UNLOCK)
- ``barrier_phases``  — bulk-synchronous phases of private work separated
                        by global (or subset) barriers (SPLASH-2 phase shape)

All generators are deterministic given ``seed``.
"""

from __future__ import annotations

import numpy as np

from .format import (
    EV_BARRIER,
    EV_INS,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
    Trace,
    from_event_lists,
)


def _rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(seed)


def _interleave(rng, mem_events, ins_per_mem: int):
    """Weave INS batches between memory events (~ins_per_mem each, >=1)."""
    out = []
    for ev in mem_events:
        k = int(rng.integers(1, 2 * ins_per_mem + 1)) if ins_per_mem > 0 else 0
        if k:
            out.append((EV_INS, k, 0))
        out.append(ev)
    return out


def uniform_random(
    n_cores: int,
    n_mem_ops: int = 256,
    working_set: int = 1 << 20,
    write_frac: float = 0.3,
    ins_per_mem: int = 3,
    shared_frac: float = 0.2,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Random accesses; a `shared_frac` of them hit a common shared region."""
    rng = _rng(seed)
    shared_base = 0
    shared_size = max(line * 16, working_set // 8)
    per_core = []
    for c in range(n_cores):
        priv_base = (1 + c) * working_set
        n = n_mem_ops
        is_shared = rng.random(n) < shared_frac
        is_write = rng.random(n) < write_frac
        offs = rng.integers(0, working_set, n)
        sh_offs = rng.integers(0, shared_size, n)
        addrs = np.where(is_shared, shared_base + sh_offs, priv_base + offs)
        addrs = (addrs // 4) * 4
        evs = [
            (EV_ST if w else EV_LD, 4, int(a))
            for w, a in zip(is_write, addrs)
        ]
        per_core.append(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core)


def stream(
    n_cores: int,
    n_mem_ops: int = 256,
    ins_per_mem: int = 2,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Each core streams sequentially through its own region (cold misses)."""
    rng = _rng(seed)
    per_core = []
    for c in range(n_cores):
        base = (1 + c) * (n_mem_ops * line + (1 << 12))
        evs = [(EV_LD, 4, base + i * line) for i in range(n_mem_ops)]
        per_core.append(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core)


def pointer_chase(
    n_cores: int,
    n_mem_ops: int = 256,
    n_nodes: int = 64,
    ins_per_mem: int = 1,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Dependent-chain loads over a private ring of nodes (latency-bound)."""
    rng = _rng(seed)
    per_core = []
    for c in range(n_cores):
        base = (1 + c) * (n_nodes * line * 4)
        perm = rng.permutation(n_nodes)
        node = 0
        evs = []
        for _ in range(n_mem_ops):
            evs.append((EV_LD, 8, base + int(perm[node]) * line))
            node = (node + 1) % n_nodes
        per_core.append(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core)


def false_sharing(
    n_cores: int,
    n_mem_ops: int = 256,
    n_hot_lines: int = 4,
    ins_per_mem: int = 1,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """All cores read-modify-write distinct words of the same few lines."""
    rng = _rng(seed)
    per_core = []
    for c in range(n_cores):
        evs = []
        word = (c * 4) % line
        for i in range(n_mem_ops // 2):
            ln = int(rng.integers(0, n_hot_lines))
            addr = ln * line + word
            evs.append((EV_LD, 4, addr))
            evs.append((EV_ST, 4, addr))
        per_core.append(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core)


def fft_like(
    n_cores: int,
    n_phases: int = 4,
    points_per_core: int = 64,
    ins_per_mem: int = 4,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """SPLASH-2 FFT shape: local strided compute, then butterfly exchange.

    Phase p: each core loads/stores its own `points_per_core` elements
    (stride grows with phase), then reads the block of its butterfly partner
    (c XOR 2^p) — cross-tile communication whose distance doubles each phase.
    """
    rng = _rng(seed)
    block = points_per_core * 8  # 8-byte points
    per_core_evs: list[list] = [[] for _ in range(n_cores)]
    for p in range(n_phases):
        stride = 8 << p
        for c in range(n_cores):
            base = (1 + c) * (block * 8)
            evs = []
            for i in range(points_per_core):
                a = base + (i * stride) % block
                evs.append((EV_LD, 8, a))
                evs.append((EV_ST, 8, a))
            partner = c ^ (1 << (p % max(1, (n_cores - 1).bit_length())))
            partner %= n_cores
            pbase = (1 + partner) * (block * 8)
            for i in range(0, points_per_core, max(1, line // 8)):
                evs.append((EV_LD, 8, pbase + i * 8))
            per_core_evs[c].extend(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core_evs)


def readers_writer(
    n_cores: int,
    n_rounds: int = 8,
    block_lines: int = 8,
    ins_per_mem: int = 2,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Core 0 writes a shared block; all others read it (each round)."""
    rng = _rng(seed)
    per_core_evs: list[list] = [[] for _ in range(n_cores)]
    for r in range(n_rounds):
        base = r * block_lines * line
        w = [(EV_ST, 4, base + i * line) for i in range(block_lines)]
        per_core_evs[0].extend(_interleave(rng, w, ins_per_mem))
        for c in range(1, n_cores):
            rd = [(EV_LD, 4, base + i * line) for i in range(block_lines)]
            per_core_evs[c].extend(_interleave(rng, rd, ins_per_mem))
    return from_event_lists(per_core_evs)


def lock_contention(
    n_cores: int,
    n_critical: int = 16,
    n_locks: int = 2,
    ins_per_mem: int = 2,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Cores repeatedly acquire a few shared mutexes, touch the protected
    data (load + store), and release — the pthread_mutex critical-section
    shape the reference captures by interception (SURVEY.md §2 #1)."""
    rng = _rng(seed)
    per_core = []
    for c in range(n_cores):
        evs = []
        for _ in range(n_critical):
            lk = int(rng.integers(0, n_locks))
            mtx = 0x10000 + lk * 4 * line  # mutex addresses, distinct lines
            data = 0x80000 + lk * line  # protected data, one line per lock
            evs.append((EV_LOCK, 0, mtx))
            evs.append((EV_LD, 4, data))
            evs.append((EV_ST, 4, data))
            evs.append((EV_UNLOCK, 0, mtx))
        per_core.append(_interleave(rng, evs, ins_per_mem))
    return from_event_lists(per_core)


def barrier_phases(
    n_cores: int,
    n_phases: int = 4,
    work_per_phase: int = 12,
    ins_per_mem: int = 2,
    subset: bool = False,
    seed: int = 0,
    line: int = 64,
) -> Trace:
    """Bulk-synchronous phases: private strided work, then a barrier.

    Barrier ids alternate over two slots to exercise slot reuse (count
    reset + re-arm). With ``subset=True`` only the first half of the cores
    participate (participant count = n_cores // 2), the rest free-run —
    exercising per-waiter participant counts.
    """
    rng = _rng(seed)
    half = max(1, n_cores // 2)
    per_core: list[list] = [[] for _ in range(n_cores)]
    for p in range(n_phases):
        for c in range(n_cores):
            base = (1 + c) * (1 << 14) + p * work_per_phase * line
            evs = [(EV_LD, 4, base + i * line) for i in range(work_per_phase)]
            evs.append((EV_ST, 4, base))
            w = _interleave(rng, evs, ins_per_mem)
            if subset:
                if c < half:
                    w.append((EV_BARRIER, half, p % 2))
            else:
                w.append((EV_BARRIER, n_cores, p % 2))
            per_core[c].extend(w)
    return from_event_lists(per_core)


GENERATORS = {
    "uniform_random": uniform_random,
    "stream": stream,
    "pointer_chase": pointer_chase,
    "false_sharing": false_sharing,
    "fft_like": fft_like,
    "readers_writer": readers_writer,
    "lock_contention": lock_contention,
    "barrier_phases": barrier_phases,
}
