"""Trace event format and binary trace files.

TPU-native replacement for the reference's Pin-frontend event stream
(SURVEY.md §2 #1, §3.2/3.3: per-BBL instruction-count batching + per-access
`execMem(addr, size, R/W)` analysis calls). Events are fixed 4x int32
records so host->device ingest is a single contiguous copy and the C++
frontend (`primesim_tpu/frontend/`) can write the same format with one
fwrite.

The fourth field, `pre`, carries the count of non-memory instructions
retired immediately before a memory event — the PriME-style per-basic-block
batching (SURVEY.md §3.2) folded to memory-access boundaries. A trace using
explicit INS events (pre = 0 everywhere) and its `fold_ins()` image are the
same workload; folding retires each INS batch together with the following
access in ONE simulation step, which matters because steps, not events, are
the engine's unit of wall-clock cost.

Binary file layout (little-endian):
    magic   uint32  0x50545055  ("PTPU")
    version uint32  4   (v1: 3-field records, pre=0; v2: no sync events;
                         v3: no flags word)
    n_cores uint32
    max_len uint32  (padded per-core event count)
    flags   uint32  (v4+ only; bit 0 = line-addressed)
    lengths uint32[n_cores]  (true event count per core, <= max_len)
    events  int32[n_cores, max_len, 4]   (type, arg, addr, pre)

Cores with fewer than max_len events are padded with END events.

v3 adds the inter-thread synchronization events the reference's Pin
frontend captures by intercepting pthread_mutex/barrier calls (SURVEY.md
§2 #1, §3.5): LOCK/UNLOCK carry the mutex's byte address (hashed to a
lock-table slot by the engines), BARRIER carries a dense barrier id in
`addr` and the participant count in `arg`. All three use `pre` like
memory events. Timing/blocking semantics are DESIGN.md §3-sync.

v4 adds the `flags` header word. Flag bit 0 (`line_addressed`): the
`addr` field of LD/ST/LOCK/UNLOCK events holds a cache-LINE index, not a
byte address — widening the addressable range 64x, from 2^31 bytes (2
GiB) to 2^31 lines (128 GiB at 64-byte lines). Larger captured address
spaces still alias (the frontend masks line indices to 31 bits); a
2x32-bit record extension remains the path to fully un-aliased 48-bit
spaces. Flags bits 8-15 record log2(line size) at capture time; engines
reject line-addressed traces whose line size differs from the machine
config. Both engines normalize ingest to line granularity, so byte- and
line-addressed encodings of one workload simulate identically.
"""

from __future__ import annotations

import numpy as np

MAGIC = 0x50545055
VERSION = 4
FLAG_LINE_ADDRESSED = 1

# Event types (DESIGN.md §2)
EV_INS = 0  # batch of non-memory instructions; arg = count
EV_LD = 1  # load;  addr = byte address (31-bit in v1), arg = size
EV_ST = 2  # store; addr = byte address (31-bit in v1), arg = size
EV_END = 3  # core finished
EV_LOCK = 4  # acquire mutex; addr = mutex byte address
EV_UNLOCK = 5  # release mutex; addr = mutex byte address
EV_BARRIER = 6  # barrier wait; addr = barrier id, arg = participant count

N_FIELDS = 4  # (type, arg, addr, pre)
SYNC_TYPES = (EV_LOCK, EV_UNLOCK, EV_BARRIER)


class TraceError(ValueError):
    """Typed trace load/validation error carrying WHERE the trace is bad:
    the source `path` (file loads), the `core` index, and the event
    `offset` within that core's row. Fleet fault isolation
    (sim/supervisor.py) surfaces these fields in the quarantined
    element's JSON line so a malformed element in a thousand-element
    sweep is diagnosable without rerunning it solo. Subclasses ValueError
    so existing `except ValueError` callers are unaffected."""

    def __init__(
        self,
        message: str,
        *,
        path: str | None = None,
        core: int | None = None,
        offset: int | None = None,
    ):
        self.reason = message
        self.path = path
        self.core = core
        self.offset = offset
        where = []
        if path is not None:
            where.append(str(path))
        if core is not None:
            where.append(f"core {core}")
        if offset is not None:
            where.append(f"event {offset}")
        super().__init__(": ".join(where + [message]) if where else message)

    def location(self) -> dict:
        """JSON-ready location fields (None entries omitted)."""
        loc = {"path": self.path, "core": self.core, "offset": self.offset}
        return {k: v for k, v in loc.items() if v is not None}


def _first_bad(mask: np.ndarray) -> tuple[int, int]:
    """(core, event offset) of the first True in a [n_cores, max_len] mask."""
    c, o = np.argwhere(mask)[0]
    return int(c), int(o)


class Trace:
    """Per-core event arrays: events[n_cores, max_len, 4] int32 records
    (type, arg, addr, pre). With `line_addressed`, LD/ST/LOCK/UNLOCK addr
    fields hold cache-line indices instead of byte addresses (v4 flag)."""

    def __init__(
        self,
        events: np.ndarray,
        lengths: np.ndarray,
        line_addressed: bool = False,
        line_bits: int | None = None,
        validate: bool = True,
    ):
        """`validate=False` skips the eager whole-array scans (used by the
        mmap load path, where touching every page defeats lazy loading;
        the engines' ingest checks still apply per window)."""
        if validate:
            events = np.asarray(events, dtype=np.int32)
        lengths = np.asarray(lengths, dtype=np.int32)
        assert events.ndim == 3 and events.shape[2] == N_FIELDS
        assert lengths.shape == (events.shape[0],)
        self.line_addressed = bool(line_addressed)
        # line size (log2) the line indices were derived with; None =
        # unknown/not applicable (byte-addressed traces)
        self.line_bits = line_bits if line_addressed else None
        t = events[:, :, 0] if validate else np.zeros(0)
        if t.size:
            bad = ~((t >= EV_INS) & (t <= EV_BARRIER))
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError(
                    "trace contains invalid event types", core=c, offset=o
                )
            mem = (t == EV_LD) | (t == EV_ST) | (t == EV_LOCK) | (t == EV_UNLOCK)
            bad = mem & (events[:, :, 2] < 0)
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError(
                    "addresses must be in [0, 2^31) (31-bit)", core=c, offset=o
                )
            bad = (t == EV_INS) & (events[:, :, 1] < 0)
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError(
                    "INS batch counts must be >= 0", core=c, offset=o
                )
            bar = t == EV_BARRIER
            bad = bar & (events[:, :, 2] < 0)
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError("barrier ids must be >= 0", core=c, offset=o)
            bad = bar & (events[:, :, 1] < 1)
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError(
                    "barrier participant counts must be >= 1", core=c, offset=o
                )
            bad = (mem | bar) & (events[:, :, 3] < 0)
            if bad.any():
                c, o = _first_bad(bad)
                raise TraceError(
                    "pre-batched instruction counts must be >= 0",
                    core=c, offset=o,
                )
            badlen = (lengths > events.shape[1]) | (lengths < 1)
            if badlen.any():
                raise TraceError(
                    "per-core lengths out of range",
                    core=int(np.argwhere(badlen)[0][0]),
                )
            # every core's row must terminate: the event at lengths-1 is END
            # and padding beyond it is END (engines clamp ptr to max_len-1)
            last = events[np.arange(events.shape[0]), lengths - 1, 0]
            bad_last = last != EV_END
            bad_pad = events[:, -1, 0] != EV_END
            if bad_last.any() or bad_pad.any():
                if bad_last.any():
                    c = int(np.argwhere(bad_last)[0][0])
                    o = int(lengths[c]) - 1
                else:
                    c = int(np.argwhere(bad_pad)[0][0])
                    o = events.shape[1] - 1
                raise TraceError(
                    "every core's event row must terminate with END",
                    core=c, offset=o,
                )
        self.events = events
        self.lengths = lengths

    @property
    def n_cores(self) -> int:
        return self.events.shape[0]

    @property
    def max_len(self) -> int:
        return self.events.shape[1]

    def total_instructions(self) -> int:
        """Total simulated instructions (INS + pre-batched + 1 per mem/sync op)."""
        t = self.events[:, :, 0]
        ins = np.where(t == EV_INS, self.events[:, :, 1], 0).astype(np.int64).sum()
        op_mask = (t != EV_INS) & (t != EV_END)  # mem + sync events
        pre = np.where(op_mask, self.events[:, :, 3], 0).astype(np.int64).sum()
        return int(ins) + int(pre) + int(op_mask.sum())

    def line_events(self, line_bits: int) -> np.ndarray:
        """Events normalized to LINE-granular addresses (the engines'
        internal form): LD/ST/LOCK/UNLOCK addr fields become line indices;
        barrier ids and all other fields pass through. Line-addressed
        traces return the SHARED events array (engines never mutate it);
        their recorded line size must match the machine's."""
        if self.line_addressed:
            if self.line_bits is not None and self.line_bits != line_bits:
                raise ValueError(
                    f"trace was captured with {1 << self.line_bits}-byte "
                    f"lines but the machine uses {1 << line_bits}-byte lines"
                )
            return self.events
        ev = self.events.copy()
        t = ev[:, :, 0]
        addr_ev = (t == EV_LD) | (t == EV_ST) | (t == EV_LOCK) | (t == EV_UNLOCK)
        ev[:, :, 2] = np.where(addr_ev, ev[:, :, 2] >> line_bits, ev[:, :, 2])
        return ev

    # ---------------------------------------------------------------- I/O

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            hdr = np.array([MAGIC, VERSION, self.n_cores, self.max_len], dtype="<u4")
            hdr.tofile(f)
            fl = FLAG_LINE_ADDRESSED if self.line_addressed else 0
            if self.line_addressed and self.line_bits is not None:
                fl |= (self.line_bits & 0xFF) << 8
            np.array([fl], dtype="<u4").tofile(f)
            self.lengths.astype("<u4").tofile(f)
            self.events.astype("<i4").tofile(f)

    @staticmethod
    def load(path: str, mmap: bool = False) -> "Trace":
        """Load a PTPU trace; `mmap=True` memory-maps the event array so
        host memory stays O(1) — pair with ingest.stream.StreamEngine for
        traces larger than host/device memory. mmap skips the eager
        whole-array validation pass (windows still hit engine checks) and
        requires a 4-field (v2+) file.
        """
        with open(path, "rb") as f:
            hdr = np.fromfile(f, dtype="<u4", count=4)
            if hdr.shape[0] != 4 or hdr[0] != MAGIC:
                raise TraceError("not a primesim_tpu trace file", path=path)
            if hdr[1] not in (1, 2, 3, 4):
                raise TraceError(
                    f"unsupported trace version {hdr[1]}", path=path
                )
            nf = 3 if hdr[1] == 1 else N_FIELDS
            flags = 0
            if hdr[1] >= 4:
                fw = np.fromfile(f, dtype="<u4", count=1)
                if fw.shape[0] != 1:
                    raise TraceError("truncated trace file", path=path)
                flags = int(fw[0])
            n_cores, max_len = int(hdr[2]), int(hdr[3])
            lengths = np.fromfile(f, dtype="<u4", count=n_cores).astype(np.int32)
            lb = (flags >> 8) & 0xFF
            line_addressed = bool(flags & FLAG_LINE_ADDRESSED)
            if mmap:
                if nf != N_FIELDS:
                    raise TraceError(
                        "mmap loading requires a 4-field (v2+) trace; "
                        "this is v1",
                        path=path,
                    )
                events = np.memmap(
                    path, dtype="<i4", mode="r", offset=f.tell(),
                    shape=(n_cores, max_len, nf),
                )
                return Trace(
                    events,
                    lengths,
                    line_addressed=line_addressed,
                    line_bits=lb if lb else None,
                    validate=False,
                )
            events = np.fromfile(f, dtype="<i4", count=n_cores * max_len * nf)
            if events.size != n_cores * max_len * nf:
                raise TraceError("truncated trace file", path=path)
            events = events.reshape(n_cores, max_len, nf).astype(np.int32)
            if nf == 3:  # v1: no pre field
                events = np.concatenate(
                    [events, np.zeros((n_cores, max_len, 1), np.int32)], axis=2
                )
        try:
            return Trace(
                events,
                lengths,
                line_addressed=line_addressed,
                line_bits=lb if lb else None,
            )
        except TraceError as e:
            # re-raise with the file path attached to the core/offset info
            raise TraceError(
                e.reason, path=path, core=e.core, offset=e.offset
            ) from None


def validate_sync(trace: Trace, barrier_slots: int) -> None:
    """Reject traces whose barrier ids exceed a machine's slot table.

    Shared by both engines (golden + JAX) so they accept exactly the same
    traces; barrier ids are dense ints < barrier_slots by contract.
    """
    _, _, bad_bid = scan_trace_meta(trace, barrier_slots)
    if bad_bid:
        raise TraceError(
            f"trace uses barrier ids >= barrier_slots={barrier_slots}",
            core=bad_bid[0],
            offset=bad_bid[1],
        )


def scan_trace_meta(
    trace: Trace,
    barrier_slots: int,
    max_chunk_records: int = 1 << 24,
) -> tuple[bool, int, tuple[int, int] | None]:
    """One bounded-memory pass over a (possibly memory-mapped) trace:
    returns (has_sync, max per-event instruction batch, location of the
    first barrier id >= barrier_slots as (core, offset) — or None when
    all ids fit). Tiled along BOTH axes with the tile sizes co-tuned so
    one chunk holds at most `max_chunk_records` records (~256 MB at the
    default), never O(file) — row-only chunking still materialized
    rows * max_len records, which for a few-cores/very-long trace (the
    streaming engine's target shape) could itself exceed RAM."""
    has_sync = False
    per_ev = 1
    bad_bid: tuple[int, int] | None = None
    events_per_chunk = min(trace.max_len, max_chunk_records)
    rows_per_chunk = max(1, max_chunk_records // events_per_chunk)
    for lo in range(0, trace.n_cores, rows_per_chunk):
        for elo in range(0, trace.max_len, events_per_chunk):
            ev = np.asarray(
                trace.events[
                    lo : lo + rows_per_chunk, elo : elo + events_per_chunk
                ]
            )
            t = ev[:, :, 0]
            if not has_sync:
                has_sync = bool(
                    ((t == EV_LOCK) | (t == EV_UNLOCK) | (t == EV_BARRIER)).any()
                )
            per_ev = max(
                per_ev,
                int(ev[:, :, 1].max(initial=0)),
                int(ev[:, :, 3].max(initial=0)) + 1,
            )
            if bad_bid is None:
                over = (t == EV_BARRIER) & (ev[:, :, 2] >= barrier_slots)
                if over.any():
                    c, o = np.argwhere(over)[0]
                    bad_bid = (int(c) + lo, int(o) + elo)
    return has_sync, per_ev, bad_bid


def from_event_lists(
    per_core: list[list[tuple]], line_addressed: bool = False
) -> Trace:
    """Build a padded Trace from python per-core event lists.

    Each event is (type, arg, addr) or (type, arg, addr, pre); pre defaults
    to 0. An END event is appended to every core.
    """
    n_cores = len(per_core)
    lengths = np.array([len(evs) + 1 for evs in per_core], dtype=np.int32)
    max_len = int(lengths.max()) if n_cores else 1
    events = np.zeros((n_cores, max_len, N_FIELDS), dtype=np.int32)
    events[:, :, 0] = EV_END
    for c, evs in enumerate(per_core):
        if evs:
            arr = np.asarray(
                [tuple(e) + (0,) * (N_FIELDS - len(e)) for e in evs],
                dtype=np.int64,
            )
            e = np.empty((len(evs), N_FIELDS), dtype=np.int32)
            e[:, 0] = arr[:, 0].astype(np.int32)
            e[:, 1] = arr[:, 1].astype(np.int32)
            oob = (arr[:, 2] < 0) | (arr[:, 2] >= 2**31)
            if oob.any():
                raise TraceError(
                    "addresses must be in [0, 2^31) (31-bit)",
                    core=c,
                    offset=int(np.argwhere(oob)[0][0]),
                )
            e[:, 2] = arr[:, 2].astype(np.int32)
            e[:, 3] = arr[:, 3].astype(np.int32)
            events[c, : len(evs)] = e
    return Trace(events, lengths, line_addressed=line_addressed)


def fold_ins(trace: Trace) -> Trace:
    """Fold INS batches into the following memory/sync event's `pre` field.

    The folded trace is the same workload expressed in PriME's per-BBL
    batched form (SURVEY.md §3.2): each batch of non-memory instructions
    retires in the same simulation step as the memory/sync operation that
    follows it. INS batches not followed by one (trailing work before END)
    are kept as explicit INS events.
    """
    out: list[list[tuple]] = []
    for c in range(trace.n_cores):
        evs: list[tuple] = []
        acc = 0
        for i in range(int(trace.lengths[c])):
            t, arg, addr, pre = (int(x) for x in trace.events[c, i])
            if t == EV_INS:
                acc += arg
            elif t != EV_END:
                evs.append((t, arg, addr, pre + acc))
                acc = 0
            else:  # END
                if acc:
                    evs.append((EV_INS, acc, 0))
                    acc = 0
        if acc:
            evs.append((EV_INS, acc, 0))
        out.append(evs)
    return from_event_lists(out, line_addressed=trace.line_addressed)


def multiplex(
    traces: list[Trace],
    prog_bits: int | None = None,
    line_bits: int = 6,
) -> Trace:
    """Combine several programs' traces into ONE machine's trace — the
    reference's MULTIPROGRAMMED mode (SURVEY.md §2 parallelism table:
    "several trace streams multiplexed into the core axis"; PriME runs
    multiple Pin processes against one shared uncore). Program k's cores
    become cores [sum(C_0..k-1), sum(C_0..k)); its address space is kept
    disjoint by setting the top `prog_bits` of every memory/lock address
    (default: just enough bits for the program count), and its barrier
    ids are offset past the earlier programs' — so programs share the
    LLC/NoC/DRAM (and contend there) but never false-share lines or sync
    objects (lock identities fold the program id into their low LINE
    bits because the engines' lock-slot hash uses
    `line & (lock_slots-1)`; for byte-addressed traces `line_bits` names
    the machine's line-offset width so the fold lands in line-index
    bits — pass the target config's `cfg.line_bits`. Requires
    prog_bits <= log2(lock_slots), true for any realistic program
    count).

    All traces must use the same addressing (byte, or line with equal
    line_bits). Raises if any program's addresses overflow its window.
    The combined trace is materialized in host RAM (mmapped inputs are
    densified) — multiprogram streaming is not supported.
    """
    if not traces:
        raise ValueError("multiplex: need at least one trace")
    la = traces[0].line_addressed
    lb = traces[0].line_bits
    if any(t.line_addressed != la or t.line_bits != lb for t in traces):
        raise ValueError("multiplex: traces mix addressing modes")
    n = len(traces)
    if prog_bits is None:
        prog_bits = max(1, (n - 1).bit_length())
    if n > (1 << prog_bits):
        raise ValueError(f"multiplex: {n} programs need more than "
                         f"prog_bits={prog_bits}")
    shift = 31 - prog_bits
    max_len = max(t.max_len for t in traces)
    rows, lengths = [], []
    bid_base = 0
    for k, t in enumerate(traces):
        ev = np.zeros((t.n_cores, max_len, N_FIELDS), np.int32)
        ev[:, :, 0] = EV_END  # tail padding; real rows overwritten next
        ev[:, : t.max_len] = t.events
        ty = ev[:, :, 0]
        mem = (ty == EV_LD) | (ty == EV_ST) | (ty == EV_LOCK) | (
            ty == EV_UNLOCK
        )
        if (ev[:, :, 2][mem] >> shift).any():
            raise ValueError(
                f"multiplex: program {k}'s addresses exceed its "
                f"2^{shift}-entry window (lower prog_bits or shrink the "
                "working set)"
            )
        ev[:, :, 2] = np.where(mem, ev[:, :, 2] | (k << shift), ev[:, :, 2])
        # lock identities additionally fold the program id into the LOW
        # address bits: both engines hash the lock-table slot from
        # `line & (lock_slots - 1)`, so a high-bit tag alone would let
        # two programs' same-addressed mutexes serialize on one slot.
        # Clearing the low prog_bits costs only legal conservative
        # aliasing WITHIN a program (lock_slots is a hash table already).
        lk = (ty == EV_LOCK) | (ty == EV_UNLOCK)
        lo = 0 if la else line_bits  # fold into LINE-index bits
        lk_mask = ((1 << prog_bits) - 1) << lo
        ev[:, :, 2] = np.where(
            lk, (ev[:, :, 2] & ~lk_mask) | (k << lo), ev[:, :, 2]
        )
        bar = ty == EV_BARRIER
        n_bids = int(ev[:, :, 2][bar].max()) + 1 if bar.any() else 0
        ev[:, :, 2] = np.where(bar, ev[:, :, 2] + bid_base, ev[:, :, 2])
        bid_base += n_bids
        rows.append(ev)
        lengths.append(np.asarray(t.lengths))
    return Trace(
        np.concatenate(rows, axis=0),
        np.concatenate(lengths),
        line_addressed=la,
        line_bits=lb,
    )
