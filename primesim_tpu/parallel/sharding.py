"""Multi-chip sharding of the simulated machine over a jax device mesh.

TPU-native replacement for the reference's MPI process topology (SURVEY.md
§2 "Parallelism-strategy inventory"): where PriME splits the uncore across
MPI ranks each owning LLC banks/directory slices, we lay the simulated
machine out over a 1-D `jax.sharding.Mesh` axis ``"tiles"``:

- core-axis arrays (clocks, trace pointers, private L1s, per-core counters,
  the event stream) are sharded by core — each device simulates a sub-grid
  of tiles' cores;
- bank-axis arrays (LLC tags/owners/LRU, directory sharer words) are
  sharded by bank over the same axis — each device owns a slice of the
  LLC/directory, exactly like a PriME uncore rank.

Cross-device traffic (a core's request to a remote home bank, probes and
invalidations back to remote cores) is NOT hand-written message passing:
the step function stays pure and global, and XLA's SPMD partitioner inserts
the all-gathers/reduce-scatters that realize it over ICI (multi-host: DCN).
The per-step `lax.scan` boundary doubles as the quantum barrier collective
(SURVEY.md §2 #10 [DRIVER]).

Works identically on real TPU meshes and on virtual CPU meshes
(``--xla_force_host_platform_device_count``), which is how tests and the
driver's `dryrun_multichip` validate multi-chip behavior without hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..faults.schedule import FaultState
from ..sim.state import MachineState, TimingKnobs

AXIS = "tiles"

# Revoked-device registry (DESIGN.md §26). Real accelerators vanish from
# the runtime on ICI/PCIe failure; virtual CPU meshes cannot, so device
# loss is modeled the same way everywhere: a process-local set of device
# ids that `healthy_devices()` filters out. Chaos `capacity_loss` trials
# and the kill+shrink acceptance test populate it; on real hardware the
# runtime's own device list shrinking has the identical effect because
# `healthy_devices()` starts from `jax.devices()`.
_REVOKED: set = set()


def revoke_devices(ids) -> None:
    """Mark device ids as lost (chaos injection / test hook)."""
    _REVOKED.update(int(i) for i in ids)


def restore_devices(ids=None) -> None:
    """Heal revoked devices (all of them when `ids` is None)."""
    if ids is None:
        _REVOKED.clear()
    else:
        _REVOKED.difference_update(int(i) for i in ids)


def healthy_devices() -> list:
    """Currently-visible devices minus the revoked set."""
    return [d for d in jax.devices() if d.id not in _REVOKED]


class DeviceMeshError(ValueError):
    """Typed `--devices N` validation failure (CLI exit 2, structured
    ``{"error": …}`` on stderr) raised BEFORE any compile, instead of the
    mid-compile shape error XLA would produce for a non-dividing mesh."""

    def __init__(self, detail: str, *, devices: int, visible: int | None = None):
        super().__init__(detail)
        self.devices = devices
        self.visible = visible

    def location(self):
        loc = {"devices": self.devices}
        if self.visible is not None:
            loc["visible"] = self.visible
        return loc


def validate_devices(cfg, n_devices: int) -> None:
    """Validate a `--devices N` request against the machine geometry and
    the visible device set. Raises DeviceMeshError (exit 2 at the CLI)
    on any mismatch; returns None when a tile_mesh(n_devices) run of this
    config is shape-sound."""
    if n_devices < 1:
        raise DeviceMeshError(
            f"--devices must be >= 1, got {n_devices}", devices=n_devices
        )
    visible = len(jax.devices())
    if n_devices > visible:
        raise DeviceMeshError(
            f"--devices {n_devices} exceeds the {visible} visible "
            f"device(s); set XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={n_devices} for a virtual CPU mesh",
            devices=n_devices,
            visible=visible,
        )
    for name, extent in (("n_cores", cfg.n_cores), ("n_banks", cfg.n_banks)):
        if extent % n_devices != 0:
            raise DeviceMeshError(
                f"--devices {n_devices} does not divide {name}={extent}; "
                f"the {AXIS!r} mesh axis shards cores and banks evenly",
                devices=n_devices,
                visible=visible,
            )


def largest_valid_submesh(cfg, n_available: int) -> int:
    """Largest mesh size <= `n_available` that shards this geometry
    evenly (divides both n_cores and n_banks). n=1 always qualifies, so
    any run with at least one healthy device has a valid landing mesh;
    zero healthy devices is a hard DeviceMeshError."""
    if n_available < 1:
        raise DeviceMeshError(
            "no healthy devices remain to host the mesh",
            devices=0,
            visible=n_available,
        )
    for n in range(int(n_available), 0, -1):
        if cfg.n_cores % n == 0 and cfg.n_banks % n == 0:
            return n
    return 1


def tile_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D device mesh over the tile axis (the only axis the sim needs:
    cores and banks shard over the same tile sub-grids)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"tile_mesh: {n_devices} devices requested but only "
                    f"{len(devices)} visible"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def state_pspecs() -> MachineState:
    """PartitionSpec per MachineState field (leading core/bank axis)."""
    return MachineState(
        cycles=P(AXIS),
        ptr=P(AXIS),
        l1=P(AXIS),
        dirm=P(AXIS),
        # link/lock/barrier tables are small and written from arbitrary
        # cores' lanes — replicate them (XLA reduces the scatters across
        # devices)
        link_free=P(),
        dram_free=P(AXIS),  # bank-axis, like the LLC it sits beside
        lock_holder=P(),
        barrier_count=P(),
        barrier_time=P(),
        sync_flag=P(AXIS),
        quantum_end=P(),
        step=P(),
        # per-core stride-prefetcher tracking state shards with its cores
        pf_line=P(AXIS),
        pf_stride=P(AXIS),
        pf_streak=P(AXIS),
        counters=P(None, AXIS),
        # traced timing knobs: the per-core cpi vector shards with the
        # cores it feeds; the scalars replicate
        knobs=TimingKnobs(
            quantum=P(),
            cpi=P(AXIS),
            l1_lat=P(),
            llc_lat=P(),
            link_lat=P(),
            router_lat=P(),
            dram_lat=P(),
            dram_service=P(),
            contention_lat=P(),
            prefetch_degree=P(),
            prefetch_lat=P(),
        ),
        # fault state: the per-core dead mask shards with the cores it
        # gates; link masks and the (tiny) schedule arrays replicate like
        # the link/lock tables above
        faults=FaultState(
            seed=P(),
            core_dead=P(AXIS),
            link_dead=P(),
            link_extra=P(),
            ev_step=P(),
            ev_kind=P(),
            ev_a=P(),
            ev_b=P(),
            flip_l1=P(),
            flip_llc=P(),
            due_rate=P(),
        ),
    )


def events_pspec() -> P:
    return P(AXIS)  # events[C, T, 3] sharded by core


def shard_state(mesh: Mesh, st: MachineState) -> MachineState:
    specs = state_pspecs()
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), st, specs
    )


def shard_events(mesh: Mesh, events) -> jax.Array:
    return jax.device_put(events, NamedSharding(mesh, events_pspec()))


def fleet_state_pspecs() -> MachineState:
    """state_pspecs() lifted under the fleet's leading batch axis: every
    leaf gains an UNSHARDED leading dim (elements replicate across the
    mesh; cores/banks shard within each element, shard x vmap)."""
    solo = state_pspecs()
    return jax.tree.map(
        lambda spec: P(None, *spec),
        solo,
        is_leaf=lambda x: isinstance(x, P),
    )


def fleet_events_pspec() -> P:
    return P(None, AXIS)  # events[Batch, C, T, 4]: batch whole, core-sharded


def shard_fleet_state(mesh: Mesh, st: MachineState) -> MachineState:
    specs = fleet_state_pspecs()
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), st, specs
    )


def shard_fleet_events(mesh: Mesh, events) -> jax.Array:
    return jax.device_put(events, NamedSharding(mesh, fleet_events_pspec()))
