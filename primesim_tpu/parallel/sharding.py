"""Multi-chip sharding of the simulated machine over a jax device mesh.

TPU-native replacement for the reference's MPI process topology (SURVEY.md
§2 "Parallelism-strategy inventory"): where PriME splits the uncore across
MPI ranks each owning LLC banks/directory slices, we lay the simulated
machine out over a 1-D `jax.sharding.Mesh` axis ``"tiles"``:

- core-axis arrays (clocks, trace pointers, private L1s, per-core counters,
  the event stream) are sharded by core — each device simulates a sub-grid
  of tiles' cores;
- bank-axis arrays (LLC tags/owners/LRU, directory sharer words) are
  sharded by bank over the same axis — each device owns a slice of the
  LLC/directory, exactly like a PriME uncore rank.

Cross-device traffic (a core's request to a remote home bank, probes and
invalidations back to remote cores) is NOT hand-written message passing:
the step function stays pure and global, and XLA's SPMD partitioner inserts
the all-gathers/reduce-scatters that realize it over ICI (multi-host: DCN).
The per-step `lax.scan` boundary doubles as the quantum barrier collective
(SURVEY.md §2 #10 [DRIVER]).

Works identically on real TPU meshes and on virtual CPU meshes
(``--xla_force_host_platform_device_count``), which is how tests and the
driver's `dryrun_multichip` validate multi-chip behavior without hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..faults.schedule import FaultState
from ..sim.state import MachineState, TimingKnobs

AXIS = "tiles"


def tile_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D device mesh over the tile axis (the only axis the sim needs:
    cores and banks shard over the same tile sub-grids)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"tile_mesh: {n_devices} devices requested but only "
                    f"{len(devices)} visible"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def state_pspecs() -> MachineState:
    """PartitionSpec per MachineState field (leading core/bank axis)."""
    return MachineState(
        cycles=P(AXIS),
        ptr=P(AXIS),
        l1=P(AXIS),
        dirm=P(AXIS),
        # link/lock/barrier tables are small and written from arbitrary
        # cores' lanes — replicate them (XLA reduces the scatters across
        # devices)
        link_free=P(),
        dram_free=P(AXIS),  # bank-axis, like the LLC it sits beside
        lock_holder=P(),
        barrier_count=P(),
        barrier_time=P(),
        sync_flag=P(AXIS),
        quantum_end=P(),
        step=P(),
        counters=P(None, AXIS),
        # traced timing knobs: the per-core cpi vector shards with the
        # cores it feeds; the scalars replicate
        knobs=TimingKnobs(
            quantum=P(),
            cpi=P(AXIS),
            l1_lat=P(),
            llc_lat=P(),
            link_lat=P(),
            router_lat=P(),
            dram_lat=P(),
            dram_service=P(),
            contention_lat=P(),
        ),
        # fault state: the per-core dead mask shards with the cores it
        # gates; link masks and the (tiny) schedule arrays replicate like
        # the link/lock tables above
        faults=FaultState(
            seed=P(),
            core_dead=P(AXIS),
            link_dead=P(),
            link_extra=P(),
            ev_step=P(),
            ev_kind=P(),
            ev_a=P(),
            ev_b=P(),
            flip_l1=P(),
            flip_llc=P(),
            due_rate=P(),
        ),
    )


def events_pspec() -> P:
    return P(AXIS)  # events[C, T, 3] sharded by core


def shard_state(mesh: Mesh, st: MachineState) -> MachineState:
    specs = state_pspecs()
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), st, specs
    )


def shard_events(mesh: Mesh, events) -> jax.Array:
    return jax.device_put(events, NamedSharding(mesh, events_pspec()))
