"""Multi-host (DCN) scale-out — SURVEY.md §5.8.

The reference spans hosts with MPI: point-to-point memory messages between
ranks plus barrier collectives. The TPU-native equivalent needs NO new
message-passing code: `jax.distributed` connects the processes, the tile
mesh simply spans every process's devices, and the SAME global step
function runs SPMD — XLA routes intra-slice traffic over ICI and
cross-slice traffic over DCN, with the per-step scan boundary acting as
the global quantum barrier (SURVEY.md §2 #10).

Launch one process per host:

    # host 0                                # host 1
    python -c "                              python -c "
    from primesim_tpu.parallel.distributed \\
        import init_multi_host, global_tile_mesh
    init_multi_host('host0:1234', 2, 0)      init_multi_host('host0:1234', 2, 1)
    mesh = global_tile_mesh()
    eng = Engine(cfg, trace, mesh=mesh)      ...same program...
    eng.run()"

Every process must run the identical program (SPMD). This module is API
plumbing over `jax.distributed.initialize`; single-host environments
(including this repo's CI, which has one process) exercise the same mesh
path on local devices — multi-host behavior is XLA's contract, not new
code here.
"""

from __future__ import annotations

import jax

from .sharding import tile_mesh


def init_multi_host(
    coordinator_address: str, num_processes: int, process_id: int, **kw
) -> None:
    """Connect this process to the multi-host job (call before any other
    JAX operation; one call per process, every host the same program)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )


def global_tile_mesh():
    """1-D tile mesh over EVERY process's devices (jax.devices() is global
    after init_multi_host): cores and LLC banks shard across all hosts,
    exactly like the reference's uncore ranks spanning machines."""
    return tile_mesh(devices=jax.devices())


def process_info() -> dict:
    """Small diagnostic bundle for launch scripts / logs."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
