"""Typed errors for the attestation subsystem (DESIGN.md §24).

`AttestationError` rides the existing CLI error contract: `primetpu`
catches it in `main()` and prints `{"error": {type, location, detail}}`
on stderr with exit code 2, exactly like TraceError / CheckpointCorrupt
/ FsckCorrupt. `location()` anchors the failure to the site that
detected it (lease grant, ack compare, offline audit) plus the unit and
chunk index when known.
"""

from __future__ import annotations


class AttestationError(ValueError):
    """Result integrity could not be established: a fingerprint chain
    diverged between two executions of the same unit, a worker's
    toolchain disagrees with the coordinator's, or an offline audit
    re-derived a different chain head than the journaled one."""

    def __init__(self, msg: str, *, site: str = "", unit: str = "",
                 chunk: int | None = None):
        super().__init__(msg)
        self.site = site
        self.unit = unit
        self.chunk = chunk

    def location(self) -> dict:
        loc: dict = {}
        if self.site:
            loc["site"] = self.site
        if self.unit:
            loc["unit"] = self.unit
        if self.chunk is not None:
            loc["chunk"] = int(self.chunk)
        return loc
