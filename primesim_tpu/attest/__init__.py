"""Result attestation: fingerprint chains, ACK cross-checks, audits.

See DESIGN.md §24. Public surface:

- `SoloAttest` / `FleetAttest` — per-chunk chain holders the engines
  call at every committed chunk boundary (dead-branch off by default:
  engines hold `self.attest = None` and never touch state).
- `AttestChain`, `chunk_digest`, `comparable`, `heads_equal` — the
  chain primitives.
- `toolchain_fingerprint` / `toolchain_matches` — lease-time worker
  toolchain verification (reuses the exec-cache key fields).
- `AttestationError` — typed error on the CLI's exit-2 contract.
- `audit` module — offline re-execution audit (`primetpu audit`).
"""

from .chain import (AttestChain, FleetAttest, SoloAttest, chunk_digest,
                    comparable, heads_equal, link, toolchain_fingerprint,
                    toolchain_matches)
from .errors import AttestationError

__all__ = [
    "AttestChain", "FleetAttest", "SoloAttest", "chunk_digest",
    "comparable", "heads_equal", "link", "toolchain_fingerprint",
    "toolchain_matches", "AttestationError",
]
