"""Per-chunk fingerprint chains over committed simulator state.

The chunked loop already materializes everything a verifier needs at
every commit point: the drained `host_counters`, the rebased
`cycle_base`, `steps_run`, and the `MachineState` pytree itself. A
fingerprint is a single SHA-256 over those values in a fixed layout;
chaining folds each chunk's fingerprint into a running head
(`head_{k} = H(head_{k-1} || digest_k)`), so two executions agree on
the final head iff they agreed on *every* committed chunk. Because the
simulator is bit-exact across solo/fleet/sharded execution (DESIGN
§10/§16/§22), the chain is a checkable cross-worker invariant: a
silently-wrong worker (bad DIMM, miscompiled kernel, mismatched
jaxlib) produces a different head, not a plausible-looking result.

Everything here is pure host-side numpy on data the loop already
holds; engines keep `self.attest = None` by default and never touch
state when it is off, so `--attest off` is bit-exact trivially.

Chain payloads are small dicts `{head, chunks, start, chunk_steps}`.
Two payloads are *comparable* only when `start` and `chunk_steps`
agree — a warm-forked run (chain starts at the prefix boundary) or an
OOM-halved chunk cadence produces a different but equally valid chain,
which must never be treated as divergence.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np

from ..stats.counters import COUNTER_NAMES

# Domain tag: bump if the digest layout ever changes, so heads from
# different layouts can never collide as "equal".
_DOMAIN = b"ptattest1"

GENESIS = ""


def chunk_digest(steps_run: int, cycle_base: int, host_counters: dict,
                 leaves: list, cursor: int | None = None) -> str:
    """Fingerprint one committed chunk: counters + state leaves in a
    fixed order. `leaves` is the tree-flattened `MachineState` (host
    numpy arrays); `cursor` joins only for stream engines, whose chain
    is window-based and scoped to the stream run."""
    h = hashlib.sha256(_DOMAIN)
    h.update(np.int64(steps_run).tobytes())
    h.update(np.int64(cycle_base).tobytes())
    if cursor is not None:
        # stream engines: per-core window cursors join the cut
        h.update(np.ascontiguousarray(
            np.asarray(cursor, dtype=np.int64)).tobytes())
    for name in COUNTER_NAMES:
        arr = np.ascontiguousarray(np.asarray(host_counters[name],
                                              dtype=np.int64))
        h.update(arr.tobytes())
    for leaf in leaves:
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def link(prev_head: str, digest: str) -> str:
    return hashlib.sha256(
        _DOMAIN + prev_head.encode() + digest.encode()).hexdigest()


def comparable(a: dict | None, b: dict | None) -> bool:
    """Two chain payloads can be meaningfully compared only when they
    cover the same steps from the same starting boundary at the same
    chunk cadence."""
    if not a or not b or not a.get("head") or not b.get("head"):
        return False
    return (int(a.get("start", 0)) == int(b.get("start", 0))
            and int(a.get("chunk_steps", 0)) == int(b.get("chunk_steps", 0)))


def heads_equal(a: dict, b: dict) -> bool:
    return (a.get("head") == b.get("head")
            and int(a.get("chunks", -1)) == int(b.get("chunks", -2)))


class AttestChain:
    """One engine's (or fleet element's) running fingerprint chain."""

    __slots__ = ("head", "chunks", "start", "chunk_steps")

    def __init__(self, chunk_steps: int, *, start: int = 0,
                 head: str = GENESIS, chunks: int = 0):
        self.chunk_steps = int(chunk_steps)
        self.start = int(start)
        self.head = str(head)
        self.chunks = int(chunks)

    def update(self, digest: str) -> str:
        self.head = link(self.head, digest)
        self.chunks += 1
        return self.head

    def payload(self) -> dict:
        return {"head": self.head, "chunks": self.chunks,
                "start": self.start, "chunk_steps": self.chunk_steps}

    def snapshot(self) -> tuple:
        return (self.head, self.chunks)

    def restore(self, snap: tuple) -> None:
        self.head, self.chunks = str(snap[0]), int(snap[1])

    @classmethod
    def from_payload(cls, p: dict) -> "AttestChain":
        return cls(p.get("chunk_steps", 0), start=p.get("start", 0),
                   head=p.get("head", GENESIS), chunks=p.get("chunks", 0))

    def note_cadence(self, chunk_steps: int) -> None:
        """The supervisor OOM-halved the chunk cadence mid-run: the
        chain stays internally valid but is no longer comparable to a
        full-cadence execution — recording the new cadence here makes
        `comparable()` say so instead of reporting a false mismatch."""
        self.chunk_steps = int(chunk_steps)


def _host_leaves(state) -> list:
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(state)]


class SoloAttest:
    """Chain holder for a solo (or stream) engine. The engine calls
    `observe(self)` once per committed chunk from `run_steps` /
    `_advance_window`; everything read is already on the host."""

    def __init__(self, chunk_steps: int, *, start: int = 0,
                 head: str = GENESIS, chunks: int = 0):
        self.chain = AttestChain(chunk_steps, start=start, head=head,
                                 chunks=chunks)

    def observe(self, eng) -> None:
        d = chunk_digest(int(eng.steps_run), int(eng.cycle_base),
                         eng.host_counters, _host_leaves(eng.state),
                         cursor=getattr(eng, "cursor", None))
        self.chain.update(d)

    def payload(self) -> dict:
        return self.chain.payload()

    def snapshot(self) -> tuple:
        return self.chain.snapshot()

    def restore(self, snap: tuple) -> None:
        self.chain.restore(snap)

    def seed(self, payload: dict | None, fallback_start: int = 0) -> None:
        """Continue a checkpointed chain, or — for a pre-attestation
        checkpoint with no chain members — start a fresh chain whose
        coverage begins at the checkpoint's step count."""
        if payload and payload.get("head"):
            self.chain = AttestChain.from_payload(payload)
        else:
            self.chain = AttestChain(self.chain.chunk_steps,
                                     start=int(fallback_start))

    def note_cadence(self, chunk_steps: int) -> None:
        self.chain.note_cadence(chunk_steps)


class FleetAttest:
    """Per-element chains for a FleetEngine. Only tracked slots hash;
    only elements *live at chunk start* advance their chain — finished
    elements keep stepping in the batched program (their `state.step`
    moves) but their chain stops exactly where the solo engine's loop
    would have stopped, which is what makes fleet heads comparable to
    solo heads."""

    def __init__(self):
        self.chains: dict[int, AttestChain] = {}

    def track(self, i: int, chunk_steps: int, *, start: int = 0,
              head: str = GENESIS, chunks: int = 0) -> AttestChain:
        ch = AttestChain(chunk_steps, start=start, head=head,
                         chunks=chunks)
        self.chains[int(i)] = ch
        return ch

    def drop(self, i: int) -> None:
        self.chains.pop(int(i), None)

    def chain(self, i: int) -> AttestChain | None:
        return self.chains.get(int(i))

    def payload(self, i: int) -> dict | None:
        ch = self.chains.get(int(i))
        return None if ch is None else ch.payload()

    def observe(self, fleet, live) -> None:
        if not self.chains:
            return
        live = np.asarray(live)
        leaves = _host_leaves(fleet.state)
        for i, ch in self.chains.items():
            if not bool(live[i]):
                continue
            counters = {k: fleet.host_counters[k][i]
                        for k in COUNTER_NAMES}
            d = chunk_digest(int(fleet.steps_run[i]),
                             int(fleet.cycle_base[i]), counters,
                             [leaf[i] for leaf in leaves])
            ch.update(d)

    def snapshot(self) -> dict:
        return {i: ch.snapshot() for i, ch in self.chains.items()}

    def restore(self, snap: dict) -> None:
        for i, s in snap.items():
            ch = self.chains.get(i)
            if ch is not None:
                ch.restore(s)

    def note_cadence(self, chunk_steps: int) -> None:
        for ch in self.chains.values():
            ch.note_cadence(chunk_steps)


def toolchain_fingerprint() -> dict:
    """The toolchain fields a lease grant verifies before letting a
    worker compute anything — the same jax/jaxlib/backend triple the
    exec-cache key embeds (`exec_cache.exec_key_payload`), so "same
    toolchain" here means "would deserialize the same executable"."""
    return {
        "jax": str(jax.__version__),
        "jaxlib": str(jax.lib.__version__),
        "backend": str(jax.default_backend()),
    }


def toolchain_matches(ours: dict, theirs: dict) -> str:
    """Return '' when compatible, else the first mismatched field."""
    for k in ("jax", "jaxlib", "backend"):
        if str(theirs.get(k, "")) != str(ours.get(k, "")):
            return k
    return ""
