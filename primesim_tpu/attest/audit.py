"""Offline replay audit — `primetpu audit DIR` (DESIGN.md §24).

A pool directory is self-describing: the ledger journals every unit's
full SPEC (config JSON, workload, overrides, chunk cadence) next to the
acked result and its fingerprint-chain head, and retains the losing
half of every hedged pair as `ack_dup` evidence. This module
re-executes DONE units from those specs — in this process, long after
the campaign and its workers are gone — and compares the recomputed
chain head against everything the ledger recorded:

  - the authoritative ack's chain head (a mismatch means the campaign
    shipped a result no honest execution reproduces — the finding
    `primetpu audit` exists for);
  - every retained `ack_dup` / held payload, so a unit parked in the
    terminal SUSPECT state gets adjudicated offline: the replay is the
    third execution the live tiebreak never got;
  - the unit's surviving element checkpoint, whose chain members must
    be a PREFIX of the replayed chain (the ack-vs-checkpoint agreement
    fsck checks statically, proven dynamically here).

The ledger is read with fsck's read-only segment reader — never via
JobJournal, whose constructor repairs crash debris — so auditing a
kill -9'd campaign leaves its evidence byte-identical.

Only chains with `start == 0` and an unhalved cadence are replayable
from scratch; a warm-forked or OOM-halved execution's chain is
reported as `incomparable`, never as a mismatch (chain.comparable's
rule, applied offline).
"""

from __future__ import annotations

import os

from .chain import comparable, heads_equal
from .errors import AttestationError


def _ledger_records(root: str) -> list:
    from ..analysis.fsck import _check_journal_dir

    records, findings = _check_journal_dir(root, root)
    corrupt = [f for f in findings if f.corrupt]
    if corrupt:
        raise AttestationError(
            f"{root}: pool ledger fails verification before any replay "
            f"({corrupt[0].path}: {corrupt[0].detail}); run `primetpu "
            "fsck` first",
            site="audit.ledger",
        )
    if not records:
        raise AttestationError(
            f"{root}: no pool ledger found (need a `sweep --workers` / "
            "dispatch pool directory)",
            site="audit.ledger",
        )
    return records


def audit_targets(root: str) -> list:
    """Fold the ledger into audit targets: one entry per unit carrying
    its spec, the authoritative attest payload, and every piece of
    retained divergence evidence."""
    from ..pool.units import fold_unit_records

    records = _ledger_records(root)
    specs: dict = {}
    for rec in records:
        if rec.get("t") == "unit":
            spec = rec.get("unit") or {}
            uid = str(spec.get("unit_id", ""))
            if uid:
                specs.setdefault(uid, spec)
    units, _ = fold_unit_records(records)
    out = []
    for uid in sorted(set(specs) | set(units)):
        u = units.get(uid, {})
        out.append({
            "unit_id": uid,
            "spec": specs.get(uid),
            "attest": u.get("attest"),
            "result": u.get("result"),
            "poison": bool(u.get("poison")),
            "suspect": u.get("suspect"),
            "held": list(u.get("held") or []),
            "dup_acks": list(u.get("dup_acks") or []),
            "ack_worker": u.get("ack_worker"),
        })
    return out


def replay_unit(spec: dict) -> dict:
    """Re-execute one unit from its journaled spec with a fresh chain.
    Returns {attest, heads, result} where `heads` is the chain head
    after every committed chunk (the checkpoint cross-check index) and
    `result` carries the replayed counters summary."""
    from ..config.machine import MachineConfig
    from ..serve.scheduler import PAGE_EVENTS, parse_synth_spec
    from ..sim.fleet import FleetEngine
    from ..sim.supervisor import RunSupervisor
    from ..trace.format import Trace, fold_ins
    from .chain import FleetAttest

    cfg = MachineConfig.from_json(spec["config"])
    if spec.get("synth") is not None:
        trace = parse_synth_spec(spec["synth"], cfg.n_cores,
                                 bool(spec.get("fold")))
    else:
        trace = Trace.load(spec["trace_path"])
        if spec.get("fold"):
            trace = fold_ins(trace)
    mesh = None
    if int(spec.get("devices") or 0):
        from ..parallel.sharding import tile_mesh, validate_devices

        validate_devices(cfg, int(spec["devices"]))
        mesh = tile_mesh(int(spec["devices"]))
    cs = int(spec["chunk_steps"])
    if spec.get("capacity_pages") is not None:
        fleet = FleetEngine.make_slots(
            cfg, 1, int(spec["capacity_pages"]) * PAGE_EVENTS,
            chunk_steps=cs, mesh=mesh,
        )
        fleet.replace_element(0, trace,
                              override=dict(spec.get("overrides") or {}))
    else:
        fleet = FleetEngine(
            cfg, [trace], [dict(spec.get("overrides") or {})],
            chunk_steps=cs, mesh=mesh,
        )
    fa = FleetAttest()
    fa.track(0, cs, start=0)
    fleet.attest = fa
    heads: list = []

    def on_chunk(sup):
        ch = fa.chain(0)
        if ch is not None and ch.chunks > len(heads):
            heads.append(ch.head)

    sup = RunSupervisor(fleet, handle_signals=False, on_chunk=on_chunk)
    sup.run(max_steps=int(spec["max_steps"]))
    ec = fleet.element_counters(0)
    return {
        "attest": fa.payload(0),
        "heads": heads,
        "result": {
            "instructions": int(ec["instructions"].sum()),
            "max_core_cycles": int(fleet.cycles[0].max()),
            "steps": int(fleet.steps_run[0]),
        },
    }


def _checkpoint_attest(root: str, unit_id: str):
    """The unit's surviving element checkpoint chain members, or None.
    Unreadable / digest-refuted checkpoints surface as a verdict, not a
    crash — the audit's whole point is distrusting artifacts."""
    from ..sim.checkpoint import _attest_from, load_verified_npz

    path = os.path.join(root, "units", f"{unit_id}.npz")
    if not os.path.exists(path):
        return None, None
    try:
        z = load_verified_npz(path)
        return _attest_from(z), None
    except Exception as e:  # noqa: BLE001 — any rot is a finding here
        return None, f"{type(e).__name__}: {e}"


def audit_unit(root: str, target: dict) -> dict:
    """Replay one target and judge every recorded chain against the
    replay. Returns a verdict record (one JSON line on the CLI)."""
    uid = target["unit_id"]
    spec = target.get("spec")
    verdict = {"unit_id": uid, "status": "ok", "detail": {}}

    def skip(why: str) -> dict:
        verdict["status"] = "skipped"
        verdict["detail"]["reason"] = why
        return verdict

    if spec is None:
        return skip("no spec record in the ledger (pre-§24 campaign?)")
    if spec.get("kind") == "ingest":
        return skip("ingest units carry no chain (segment files have "
                    "their own framing)")
    if target["poison"]:
        return skip("poisoned unit — there is no result to audit")
    at = target.get("attest")
    if target.get("suspect") != "terminal" and not (at and at.get("head")):
        return skip("no chain on record (attest was off, or the unit "
                    "never finished)")
    if at and int(at.get("start", 0)) != 0:
        return skip("chain starts mid-run (warm fork / resumed cadence "
                    "change); only start-0 chains replay from scratch")

    replay = replay_unit(spec)
    rp = replay["attest"]
    verdict["detail"]["replay"] = {"head": rp["head"],
                                   "chunks": rp["chunks"],
                                   **replay["result"]}

    # 1) the authoritative ack (absent for terminal-SUSPECT units)
    if at and at.get("head"):
        if not comparable(at, rp):
            verdict["status"] = "incomparable"
            verdict["detail"]["reason"] = (
                "journaled chain cadence/coverage differs from the "
                "replay (OOM-halved chunk cadence?)"
            )
        elif heads_equal(at, rp):
            verdict["detail"]["ack"] = "confirmed"
        else:
            verdict["status"] = "mismatch"
            verdict["detail"]["ack"] = {
                "worker": target.get("ack_worker"),
                "journaled_head": at["head"],
            }

    # 2) retained divergence evidence: held payloads + hedged-twin
    #    losers — the replay adjudicates what the live tiebreak couldn't
    evidence = []
    for h in target["held"]:
        evidence.append(("held", h))
    for d in target["dup_acks"]:
        evidence.append(("audit_dup" if d.get("audit") else "hedge_dup",
                         d))
    judged = []
    for kind, e in evidence:
        ea = e.get("attest")
        if not (ea and ea.get("head")):
            continue
        judged.append({
            "kind": kind,
            "worker": str(e.get("worker", "?")),
            # None = incomparable cadence, never counted either way
            "agrees": (heads_equal(ea, rp)
                       if comparable(ea, rp) else None),
        })
    if judged:
        verdict["detail"]["evidence"] = judged
    if target.get("suspect") == "terminal":
        agreeing = sorted({j["worker"] for j in judged if j["agrees"]})
        verdict["status"] = "adjudicated" if agreeing else "mismatch"
        verdict["detail"]["suspect"] = {
            "agrees_with_replay": agreeing,
            "disagrees": sorted(
                {j["worker"] for j in judged if j["agrees"] is False}
            ),
        }

    # 3) checkpoint prefix agreement (the dynamic half of fsck's static
    #    ack-vs-checkpoint check)
    ca, rot = _checkpoint_attest(root, uid)
    if rot is not None:
        verdict["status"] = "mismatch"
        verdict["detail"]["checkpoint"] = f"unreadable: {rot}"
    elif ca and ca.get("head") and int(ca.get("start", 0)) == 0 \
            and int(ca.get("chunk_steps", 0)) == int(rp["chunk_steps"]):
        k = int(ca.get("chunks", 0))
        if 1 <= k <= len(replay["heads"]):
            if replay["heads"][k - 1] == ca["head"]:
                verdict["detail"]["checkpoint"] = f"prefix ok at chunk {k}"
            else:
                verdict["status"] = "mismatch"
                verdict["detail"]["checkpoint"] = (
                    f"chain head at chunk {k} diverges from the replay "
                    "— the checkpoint holds state no honest execution "
                    "committed"
                )
    return verdict


def run_audit(root: str, unit_ids=None) -> dict:
    """Audit every replayable unit under `root` (or just `unit_ids`).
    Returns {units: [verdict...], summary: {...}}; the CLI raises
    AttestationError when any verdict is a mismatch."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        raise AttestationError(f"not a directory: {root}",
                               site="audit.ledger")
    targets = audit_targets(root)
    if unit_ids:
        want = {str(u) for u in unit_ids}
        unknown = want - {t["unit_id"] for t in targets}
        if unknown:
            raise AttestationError(
                f"unknown unit id(s): {', '.join(sorted(unknown))}",
                site="audit.ledger", unit=sorted(unknown)[0],
            )
        targets = [t for t in targets if t["unit_id"] in want]
    verdicts = [audit_unit(root, t) for t in targets]
    summary = {"audited": 0, "ok": 0, "mismatch": 0, "adjudicated": 0,
               "incomparable": 0, "skipped": 0}
    for v in verdicts:
        s = v["status"]
        if s != "skipped":
            summary["audited"] += 1
        summary[s] = summary.get(s, 0) + 1
    return {"root": root, "units": verdicts, "summary": summary}
