"""Prefix forking: pay for a sweep's shared prefix once (DESIGN.md §16).

Every `sweep --vary` / chaos-seed campaign re-simulates an identical
trace prefix B times: elements share the trace, the geometry, and (for
seed sweeps) the entire timing-knob vector, and differ only in inputs
that cannot influence the machine before a known step. This module
computes that step (divergence analysis), groups elements into
prefix-sharing classes, runs each class's prefix ONCE as a solo Engine,
and broadcasts the snapshot into the fleet slots via
`FleetEngine.fork_element` — turning O(B·T) campaigns into
O(T_prefix + B·T_tail).

Divergence rules (first step at which two elements CAN differ — a
conservative lower bound is always sound, since forking at any step at
or below the true divergence point is bit-exact):

- different trace, or different timing knobs        -> step 0 (no sharing)
- different ECC flip/DUE rates                      -> step 0
- different seeds AND any flip rate nonzero         -> step 0 (the seed
  feeds per-step site hashes from the first step)
- different seeds, all rates zero                   -> the first scheduled
  fault-event step (the schedule start; with rates zero the seed is
  architecturally unreachable, so this is conservative — see the warm-key
  derivation in sim.checkpoint)
- schedules differ                                  -> the earliest event
  NOT common to every member
- fully identical effective configs                 -> never (dedup's
  domain, not forking's)

An event scheduled at step S fires while executing step index S
(`faults.inject.fire_events` matches `ev_step == step_no`), so a P-step
prefix fires exactly the events with step < P: any P at or below the
divergence point is safe, and the planner additionally floors P to a
chunk boundary so the solo prefix engine stops exactly where the fleet's
select-masked chunks would.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from .checkpoint import (
    CheckpointCorrupt,
    load_warm_state,
    save_warm_state,
    trace_fingerprint,
    warm_cache_root,
    warm_key,
)

#: "never diverges" sentinel — far above any reachable step budget
NEVER = 1 << 62


@dataclasses.dataclass
class PrefixGroup:
    """One prefix-sharing class of fleet elements."""

    indices: list[int]  # batch positions sharing the prefix (len >= 2)
    divergence: int  # first step any two members can differ (or NEVER)
    prefix_steps: int  # chunk-floored steps the prefix actually runs
    cache_key: str | None = None  # warm-cache address (set at execution)
    cache_hit: bool = False  # prefix loaded from disk, not simulated


def _knob_sig(cfg) -> tuple:
    """The traced timing-knob values as a hashable signature."""
    from .state import knobs_from_config

    kn = knobs_from_config(cfg)
    return tuple(
        (k, tuple(np.asarray(v).reshape(-1).tolist()))
        for k, v in kn._asdict().items()
    )


def _rates(cfg) -> tuple:
    return (
        float(cfg.fault_flip_l1),
        float(cfg.fault_flip_llc),
        float(cfg.fault_due_rate),
    )


def _events(cfg) -> frozenset:
    return frozenset(
        tuple(int(x) for x in e) for e in (cfg.fault_events or ())
    )


def group_divergence(cfgs: list) -> int:
    """First step at which any two of these same-trace, same-knob,
    same-rate configs can produce different machine state."""
    seeds = {int(c.fault_seed) for c in cfgs}
    evsets = [_events(c) for c in cfgs]
    common = frozenset.intersection(*evsets)
    union = frozenset.union(*evsets)
    non_common = union - common
    div = NEVER
    if non_common:
        div = min(div, min(int(e[0]) for e in non_common))
    if len(seeds) > 1:
        # rates are zero here (nonzero rates split the class key), so the
        # seed is unreachable — but per the conservative rule the fork
        # point is the fault-schedule start
        if union:
            div = min(div, min(int(e[0]) for e in union))
    return div


def dedup_plan(elem_cfgs: list, traces: list) -> tuple[list[int], dict[int, int]]:
    """Identical-element detection: positions whose (trace, effective
    config) pair equals an earlier element's simulate nothing new.
    Returns (kept_indices, dup_of) where dup_of maps each duplicate
    position to the earlier position whose results it shares."""
    seen: dict = {}
    keep: list[int] = []
    dup_of: dict[int, int] = {}
    for i, (cfg, tr) in enumerate(zip(elem_cfgs, traces)):
        sig = (trace_fingerprint(tr), cfg.to_json())
        if sig in seen:
            dup_of[i] = seen[sig]
        else:
            seen[sig] = i
            keep.append(i)
    return keep, dup_of


def plan_prefix(
    elem_cfgs: list,
    traces: list,
    mode: str = "auto",
    chunk_steps: int = 256,
    cap: int | None = None,
) -> list[PrefixGroup]:
    """Group a fleet's elements into prefix-sharing classes.

    `mode` is the CLI's --fork-prefix value: "off" plans nothing, "auto"
    forks at the (chunk-floored) divergence point, and an integer CAPS
    the prefix at that many steps (useful to bound snapshot reuse when a
    divergence point is very deep). `cap` additionally bounds the prefix
    by the run's step budget. Groups whose floored prefix is zero, or
    with a single member, are dropped — forking them buys nothing."""
    if mode == "off":
        return []
    user_cap = None
    if mode not in ("auto", "off"):
        user_cap = int(mode)
        if user_cap <= 0:
            return []
    classes: dict = {}
    for i, (cfg, tr) in enumerate(zip(elem_cfgs, traces)):
        rates = _rates(cfg)
        key = (
            trace_fingerprint(tr),
            _knob_sig(cfg),
            rates,
            # nonzero flip rates make the seed architecturally live from
            # step 0, so it must split the class; with all rates zero,
            # seed-varying elements share the prefix
            int(cfg.fault_seed) if any(r > 0.0 for r in rates) else None,
        )
        classes.setdefault(key, []).append(i)
    groups = []
    for members in classes.values():
        if len(members) < 2:
            continue
        div = group_divergence([elem_cfgs[i] for i in members])
        if div == NEVER and cap is None and user_cap is None:
            # identical elements with no step budget to bound the prefix:
            # nothing sound to fork to (dedup should have caught these)
            continue
        p = div
        if cap is not None:
            p = min(p, int(cap))
        if user_cap is not None:
            p = min(p, user_cap)
        p = (p // chunk_steps) * chunk_steps
        if p <= 0:
            continue
        groups.append(
            PrefixGroup(
                indices=list(members), divergence=div, prefix_steps=p
            )
        )
    groups.sort(key=lambda g: g.indices[0])
    return groups


def execute_prefix_plan(
    fleet,
    groups: list[PrefixGroup],
    warm_cache: bool = False,
    cache_root: str | None = None,
    obs=None,
) -> dict:
    """Run (or load) each group's shared prefix and fork it into the
    fleet's slots. Returns the stats dict the CLI reports as the
    `prefix_fork` metric line.

    The prefix runs as a solo Engine on the group representative's
    effective config with the FLEET's chunk_steps — `run_steps` stops on
    the same chunk boundaries the vmapped fleet would, so the snapshot is
    exactly the state an unforked fleet element would hold after
    `prefix_steps` steps. A warm-cache hit skips the simulation entirely;
    a corrupt or mismatched entry falls back to recompute (and
    overwrites the bad entry)."""
    from .engine import Engine

    stats = {
        "groups": len(groups),
        "forked_elements": 0,
        "prefix_steps": 0,
        "cache_hits": 0,
        "cache_misses": 0,
        "prefix_wall_s": 0.0,
    }
    root = None
    if warm_cache:
        root = cache_root or warm_cache_root()
    for g in groups:
        rep = g.indices[0]
        rcfg = fleet.elem_cfgs[rep]
        rtrace = fleet.traces[rep]
        fp = trace_fingerprint(rtrace)
        g.cache_key = warm_key(rcfg, fp, g.prefix_steps)
        snap = None
        if root is not None:
            try:
                snap = load_warm_state(root, g.cache_key, rcfg, fp, g.prefix_steps)
                g.cache_hit = True
                stats["cache_hits"] += 1
                if obs is not None:
                    obs.prefix_event("warm-hit", key=g.cache_key, steps=g.prefix_steps)
            except FileNotFoundError:
                stats["cache_misses"] += 1
                if obs is not None:
                    obs.prefix_event("warm-miss", key=g.cache_key, steps=g.prefix_steps)
            except (CheckpointCorrupt, ValueError) as e:
                # torn/tampered/mismatched entry: recompute (and replace)
                stats["cache_misses"] += 1
                if obs is not None:
                    obs.prefix_event("warm-corrupt", key=g.cache_key, error=str(e))
        if snap is None:
            t0 = time.perf_counter()
            eng = Engine(rcfg, rtrace, chunk_steps=fleet.chunk_steps)
            if obs is not None:
                obs.attach(eng, label="prefix")
            eng.run_steps(g.prefix_steps)
            eng._drain()
            snap = {
                "state": eng.state,
                "cycle_base": np.int64(eng.cycle_base),
                "steps_run": np.int64(eng.steps_run),
                "host_counters": {
                    k: v.copy() for k, v in eng.host_counters.items()
                },
            }
            stats["prefix_wall_s"] += time.perf_counter() - t0
            if root is not None:
                from ..util.diskpressure import DiskPressureError

                try:
                    save_warm_state(root, rcfg, fp, g.prefix_steps, snap)
                except DiskPressureError:
                    # the warm entry is an optimization; under disk
                    # pressure the fork still happens from live state
                    pass
                else:
                    if obs is not None:
                        obs.prefix_event("warm-store", key=g.cache_key, steps=g.prefix_steps)
        for i in g.indices:
            fleet.fork_element(i, snap, cache_key=g.cache_key)
        stats["forked_elements"] += len(g.indices)
        stats["prefix_steps"] = max(stats["prefix_steps"], g.prefix_steps)
    return stats
