"""Content-addressed AOT executable cache (DESIGN.md §23).

The warm-state cache (§16) persists *machine state* across processes;
this module is its sibling for the *compiled program*. Every jitted
entry point (solo `run_chunk`/`run_loop`, fleet `fleet_run_chunk`/
`fleet_run_loop`, stream `stream_loop`) is lowered + compiled
ahead-of-time, serialized with `jax.experimental.serialize_executable`,
and written to `$PRIMETPU_CACHE_DIR/exec/<key>.bin` so the *next*
process with the same geometry skips trace, lowering and XLA
compilation entirely.

Key derivation — the sha256 of a canonical-JSON payload over:

  - jax + jaxlib versions (jaxlib pins the XLA commit, so a toolchain
    upgrade silently invalidates every entry: a plain miss, never an
    error)
  - backend platform and device count
  - the checkpoint `_FORMAT` (state pytree layout) and this module's
    own `_FORMAT`
  - the entry-point name
  - `cfg.timing_normalized()` geometry hash — timing knobs are TRACED
    (they live in `state.knobs`), so one executable serves every
    timing variant of a geometry; `step_impl` and the model selectors
    ride inside the normalized config JSON
  - the remaining static args (chunk_steps) and static kwargs
    (has_sync)
  - per-leaf avals of the dynamic args: shape, dtype, weak_type, and
    the sharding description for non-trivially-sharded leaves (mesh
    shape and batch size are therefore part of the address), plus the
    pytree structure string

Entries are lowered with the NORMALIZED config substituted for the
static `cfg` so the on-disk artifact is a pure function of geometry —
this is the same contract `FleetEngine` already relies on (it passes
`geom_cfg = cfg.timing_normalized()` as the jit static and is bit-exact
against full-config solo runs).

Durability: `.bin` is MAGIC + CRC32 + pickle of
{payload, in_tree, out_tree}, written writer-unique-temp + fsync +
atomic rename (PT-DURABLE), with a JSON sidecar carrying the full key
payload so `primetpu fsck` can re-derive the address and verify
key<->content agreement offline. Corrupt, truncated, version-mismatched
or otherwise unusable entries degrade to MISS-and-recompile with a
structured warning — the cache can make a run faster, never wrong, and
never dead. LRU budget is shared with the warm-state cache: see
`checkpoint.prune_warm_cache`, which walks both the warm `.npz` pool
and this directory's `.bin` pool under one `PRIMETPU_CACHE_MAX_BYTES`.

Activation is process-global (`configure(enabled=True)`) so deep call
sites (supervisor resume, pool workers, serve buckets) route through
the cache without threading a handle through every constructor. With
the cache off, `call()` is a single `is None` check and a tail call of
the jitted function — bit-identical to the pre-cache stack.
"""

from __future__ import annotations

import json
import hashlib
import logging
import os
import pickle
import struct
import tempfile
import time
import zlib

import jax
import numpy as np

from ..chaos import sites as chaos

log = logging.getLogger("primetpu.exec_cache")

_MAGIC = b"PTEXEC01"
_FORMAT = 1  # exec-entry layout; combined with checkpoint._FORMAT in the key


class ExecCacheCorrupt(Exception):
    """A `.bin` entry that cannot be trusted: bad magic, CRC mismatch,
    truncation, or an unpicklable body. Treated as a miss."""


def exec_cache_root() -> str:
    """`$PRIMETPU_CACHE_DIR/exec` (or the per-user default's `exec/`
    subdirectory) — a sibling pool of the warm-state entries so both
    share one tree and one LRU budget. Created on first use."""
    from .checkpoint import warm_cache_root

    root = os.path.join(warm_cache_root(), "exec")
    os.makedirs(root, exist_ok=True)
    return root


def _leaf_desc(x) -> list:
    """Aval descriptor of one dynamic-arg leaf: shape, dtype, weak_type,
    and the sharding string when it is not the trivial single-device
    placement (np arrays and uncommitted single-device jax arrays hash
    identically — both feed the same executable)."""
    if isinstance(x, jax.Array):
        d = [list(x.shape), str(x.dtype), bool(x.aval.weak_type)]
        if not isinstance(x.sharding, jax.sharding.SingleDeviceSharding):
            d.append(str(x.sharding))
        return d
    arr = np.asarray(x)
    return [list(arr.shape), str(arr.dtype), False]


def exec_key_payload(entry: str, statics: tuple, dynamics: tuple,
                     static_kwargs: dict) -> tuple[dict, tuple]:
    """The canonical key payload and the NORMALIZED statics to lower
    with. `statics[0]` must be the MachineConfig; the rest must be
    plain ints (chunk_steps and friends)."""
    from . import checkpoint as ckpt

    cfg = statics[0]
    norm_cfg = cfg.timing_normalized()
    rest = [int(s) for s in statics[1:]]
    leaves, treedef = jax.tree_util.tree_flatten(dynamics)
    payload = {
        "exec_format": _FORMAT,
        "ckpt_format": int(ckpt._FORMAT),
        "jax": jax.__version__,
        "jaxlib": jax.lib.__version__,
        "backend": jax.default_backend(),
        "devices": int(jax.device_count()),
        "entry": entry,
        "geom": hashlib.sha256(norm_cfg.to_json().encode()).hexdigest(),
        "statics": rest,
        "kwargs": {k: bool(v) for k, v in sorted(static_kwargs.items())},
        "tree": str(treedef),
        "avals": [_leaf_desc(x) for x in leaves],
    }
    return payload, (norm_cfg, *rest)


def exec_key(payload: dict) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()


class ExecCache:
    """One process's view of the on-disk executable pool: an in-process
    memo of loaded executables plus hit/miss/compile-wall accounting."""

    def __init__(self, root: str | None = None):
        self.root = root or exec_cache_root()
        self._memo: dict[str, object] = {}
        self._failed: set[str] = set()  # keys where the AOT path broke
        self.warnings: list[dict] = []  # structured fallback records
        self.stats = {
            "hits": 0,           # disk loads (deserialize, no compile)
            "misses": 0,         # AOT compiles (entry then persisted)
            "memo_hits": 0,      # in-process reuse, no disk touch
            "errors": 0,         # fallbacks to the jitted path
            "compile_wall_s": 0.0,
            "load_wall_s": 0.0,
        }

    # -- public entry points ------------------------------------------------

    def call(self, fn, entry: str, statics: tuple, dynamics: tuple,
             static_kwargs: dict):
        """Run `fn(*statics, *dynamics, **static_kwargs)` through the
        cache; any failure anywhere in the cache machinery falls back to
        the plain jitted call with a structured warning."""
        exe, key = self._lookup(fn, entry, statics, dynamics, static_kwargs)
        if exe is None:
            return fn(*statics, *dynamics, **static_kwargs)
        try:
            return exe(*dynamics)
        except Exception as e:  # wrong placement, stale artifact, ...
            self._fallback("execute", entry, key, e)
            return fn(*statics, *dynamics, **static_kwargs)

    def ensure(self, fn, entry: str, statics: tuple, dynamics: tuple,
               static_kwargs: dict) -> bool:
        """Load-or-compile the executable WITHOUT running it — the
        lease-grant warm path: pay deserialization before the first
        chunk so compile never eats lease TTL. Returns True when an
        executable is resident afterwards."""
        exe, _ = self._lookup(fn, entry, statics, dynamics, static_kwargs)
        return exe is not None

    # -- lookup / compile ---------------------------------------------------

    def _lookup(self, fn, entry, statics, dynamics, static_kwargs):
        try:
            payload, norm_statics = exec_key_payload(
                entry, statics, dynamics, static_kwargs
            )
            key = exec_key(payload)
        except Exception as e:
            self._fallback("key", entry, None, e)
            return None, None
        if key in self._failed:
            return None, key
        exe = self._memo.get(key)
        if exe is not None:
            self.stats["memo_hits"] += 1
            return exe, key
        exe = self._load(key, entry)
        if exe is None:
            exe = self._compile(
                key, payload, fn, entry, norm_statics, dynamics, static_kwargs
            )
        if exe is None:
            self._failed.add(key)
            return None, key
        self._memo[key] = exe
        return exe, key

    def _load(self, key: str, entry: str):
        from jax.experimental.serialize_executable import deserialize_and_load

        t0 = time.perf_counter()
        try:
            blob = self._read_blob(key)
        except FileNotFoundError:
            return None  # plain miss
        except Exception as e:
            self._fallback("load", entry, key, e)
            return None  # corrupt/stale -> miss-and-recompile
        try:
            exe = deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except Exception as e:
            self._fallback("deserialize", entry, key, e)
            return None
        self.stats["hits"] += 1
        self.stats["load_wall_s"] += time.perf_counter() - t0
        self._touch(key)
        return exe

    def _compile(self, key, payload, fn, entry, norm_statics, dynamics,
                 static_kwargs):
        from jax.experimental.serialize_executable import serialize

        t0 = time.perf_counter()
        try:
            exe = fn.lower(
                *norm_statics, *dynamics, **static_kwargs
            ).compile()
        except Exception as e:
            self._fallback("compile", entry, key, e)
            return None
        self.stats["misses"] += 1
        self.stats["compile_wall_s"] += time.perf_counter() - t0
        try:
            ser, in_tree, out_tree = serialize(exe)
            self._write_entry(
                key, payload,
                {"payload": ser, "in_tree": in_tree, "out_tree": out_tree},
            )
        except Exception as e:
            # the executable still works in-process; only persistence broke
            self._fallback("save", entry, key, e)
        return exe

    # -- on-disk format -----------------------------------------------------

    def _paths(self, key: str) -> tuple[str, str]:
        return (os.path.join(self.root, f"{key}.bin"),
                os.path.join(self.root, f"{key}.json"))

    def _read_blob(self, key: str) -> dict:
        bin_path, _ = self._paths(key)
        with open(bin_path, "rb") as f:
            record = f.read()
        head = len(_MAGIC) + 4
        if len(record) < head or record[: len(_MAGIC)] != _MAGIC:
            raise ExecCacheCorrupt(f"{bin_path}: bad magic / truncated")
        (crc,) = struct.unpack("<I", record[len(_MAGIC):head])
        body = record[head:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ExecCacheCorrupt(f"{bin_path}: CRC mismatch")
        try:
            blob = pickle.loads(body)
        except Exception as e:
            raise ExecCacheCorrupt(f"{bin_path}: undecodable body: {e}")
        if not isinstance(blob, dict) or "payload" not in blob:
            raise ExecCacheCorrupt(f"{bin_path}: not an exec entry")
        return blob

    def _write_entry(self, key: str, payload: dict, blob: dict) -> None:
        from .checkpoint import prune_warm_cache

        body = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
        record = _MAGIC + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF) + body
        os.makedirs(self.root, exist_ok=True)
        bin_path, meta_path = self._paths(key)
        self._atomic_write(bin_path, record)
        meta = {"key": key, "payload": payload,
                "size": len(record)}
        self._atomic_write(meta_path, json.dumps(meta).encode())
        # shared LRU budget: warm .npz pool + this exec .bin pool
        prune_warm_cache(os.path.dirname(self.root))

    def _atomic_write(self, dst: str, data: bytes) -> None:
        # disk-pressure gate: a DiskPressureError here unwinds into the
        # _write_entry caller's fallback — a cache entry that cannot be
        # persisted costs a recompile, never the run
        from ..util import diskpressure

        diskpressure.preflight(dst, len(data), kind="exec-cache")
        # writer-unique temp name: concurrent processes warming the same
        # entry must not rename each other's file away mid-write
        fd, tmp = tempfile.mkstemp(
            dir=self.root, prefix=os.path.basename(dst) + ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            chaos.durable("exec_cache.write", path=tmp)
            os.replace(tmp, dst)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def _touch(self, key: str) -> None:
        try:
            os.utime(self._paths(key)[0], None)  # LRU: mtime is use order
        except OSError:
            pass

    # -- structured fallback ------------------------------------------------

    def _fallback(self, stage: str, entry: str, key, err) -> None:
        rec = {
            "stage": stage,
            "entry": entry,
            "key": key,
            "error": f"{type(err).__name__}: {err}",
        }
        self.warnings.append(rec)
        self.stats["errors"] += 1
        log.warning("exec-cache fallback (recompiling via jit): %s",
                    json.dumps(rec, sort_keys=True))


# -- process-global activation ---------------------------------------------

_ACTIVE: ExecCache | None = None


def configure(enabled: bool, root: str | None = None) -> ExecCache | None:
    """Turn the process-global cache on/off. Deep call sites (engines,
    supervisor resume, pool workers, serve buckets) consult `active()`
    so one CLI flag covers the whole stack."""
    global _ACTIVE
    _ACTIVE = ExecCache(root) if enabled else None
    return _ACTIVE


def active() -> ExecCache | None:
    return _ACTIVE


def call(fn, entry: str, statics: tuple, dynamics: tuple,
         static_kwargs: dict | None = None):
    """Route one jitted-entry-point call through the active cache, or —
    when no cache is configured — straight through `fn` (bit-identical
    to the pre-cache stack: one None check, then a tail call)."""
    kw = static_kwargs or {}
    cache = _ACTIVE
    if cache is None:
        return fn(*statics, *dynamics, **kw)
    return cache.call(fn, entry, statics, dynamics, kw)
