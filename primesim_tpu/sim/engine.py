"""Vectorized JAX simulation engine — the TPU re-host of PriME's backend.

One `step()` advances every target core by up to `local_run_len` local
events (INS batches, L1 hits) plus at most one arbitrated uncore event,
implementing DESIGN.md's canonical per-step semantics branchlessly:

- CoreManager's per-core cycle tick (SURVEY.md §2 #2) is a masked lane
  update over the core axis (the `jax.vmap`-shaped dimension, fused by XLA).
- The private-cache lookup (#3), directory-MESI transition (#4), mesh-NoC
  latency (#6), and DRAM charge (#7) are `where`-chains + gathers/scatters
  over `[C]`-shaped lanes — no data-dependent Python control flow.
- The uncore request serializer (#5: `System::sim()` worker loop) becomes a
  scatter-min arbitration: one winner per LLC (bank,set) per step.
- The relaxed quantum barrier (#10) is the active-mask + quantum_end bump;
  the outer `lax.scan` step IS the quantum-bounded global clock [DRIVER].
- Local runs (#1/#3.2: PriME's non-memory path never crosses a process
  boundary) retire private-hit runs without paying a full step.

The engine must match `primesim_tpu.golden.sim.GoldenSim` BIT-EXACTLY —
tests/test_parity.py enforces this on every workload generator.

The host driver (`Engine`) dispatches ONE fused device program per run —
`lax.while_loop` over scan chunks with on-device counter draining, clock
rebasing, and termination tests — because each host->device dispatch costs
tens of ms through remote-TPU tunnels; SURVEY.md §7 "host->TPU ingest
bandwidth ... is the wall-clock make-or-break".
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from ..stats.counters import COUNTER_NAMES, zero_counters
from ..trace.format import (
    EV_BARRIER,
    EV_END,
    EV_INS,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
    Trace,
)
from . import exec_cache
from .state import (
    E,
    I,
    M,
    MachineState,
    O,
    S,
    dirm_width,
    init_state,
    llc_meta_width,
)

INT32_MAX = np.int32(2**31 - 1)
_ACC_BITS = 30  # device counter accumulators carry into hi above 2^30

@functools.lru_cache(maxsize=None)
def _group_tables(cfg: MachineConfig):
    """Static per-(home tile, sharer group) reduction tables for the
    coarse vector (sharer_group > 1): member count, max one-way HOPS over
    members, and summed round-trip hops — the group-level stand-ins for
    the full-map model's per-core [C, C] expansion, sized
    [n_tiles, n_groups] instead. GEOMETRY ONLY (latency knobs are traced
    per simulation; round-trip latency is monotone in hops, so
    2*(hmax*link + (hmax+1)*router) is computed from max2hops at the use
    site). NumPy at trace time; constants in the compiled graph."""
    G = cfg.sharer_group
    C = cfg.n_cores
    n_grp = cfg.n_sharer_groups
    nt = cfg.n_tiles
    mx = cfg.noc.mesh_x
    ids = np.arange(n_grp)[:, None] * G + np.arange(G)[None, :]  # [n_grp, G]
    valid = ids < C
    mt = (ids % nt).astype(np.int64)
    gx, gy = mt % mx, mt // mx
    members = valid.sum(1).astype(np.int32)  # [n_grp]
    max2hops = np.zeros((nt, n_grp), np.int32)
    sum2hops = np.zeros((nt, n_grp), np.int32)
    step = max(1, (1 << 24) // (n_grp * G))  # bound temporaries to ~16M
    for lo in range(0, nt, step):
        t = np.arange(lo, min(lo + step, nt))
        tx, ty = (t % mx)[:, None, None], (t // mx)[:, None, None]
        h = _topo.coord_hops(  # [T, n_grp, G]
            cfg.noc.topology, tx, ty, gx[None], gy[None],
            mx, cfg.noc.mesh_y, xp=np,
        )
        max2hops[t] = np.where(valid[None], h, 0).max(2).astype(np.int32)
        sum2hops[t] = (
            np.where(valid[None], 2 * h, 0).sum(2).astype(np.int32)
        )
    # NumPy out (converted at each use site): caching jnp arrays created
    # inside a trace would leak that trace's tracers into later jits
    return members, max2hops, sum2hops


def _one_way(tile_a, tile_b, cfg: MachineConfig, kn):
    """Vectorized one-way latency + hop count under cfg's topology
    (noc/topology.py semantics). Latencies come from the traced knobs;
    cfg supplies geometry — the topology selector is STATIC, so each
    topology compiles its own hop formula."""
    h = _topo.hops(cfg, tile_a, tile_b, xp=jnp)
    return h * kn.link_lat + (h + 1) * kn.router_lat, h


# vectorized route builder (link id = tile*4 + dir, dir 0=E 1=W 2=N 3=S,
# identical numbering for every topology), shared with the fault-injection
# detour model — dispatched on the static `noc_topology` selector by
# noc.topology next to each plugin's scalar reference walk
from ..noc import topology as _topo  # noqa: E402
from ..noc.mesh import concat_legs as _concat_legs  # noqa: E402
from ..noc.topology import path_links as _path_links  # noqa: E402

# sort-based segmented FIFO ranking (DESIGN.md §13) — the shared rank
# primitive of the router and DRAM-queue contention models; replaces the
# historical O(C²·n_seg) one-hot matmuls with one O(E log E) sort,
# integer-equal by construction
from ..ops.ranking import lane_order, segmented_rank  # noqa: E402


def _l1_probe(cfg: MachineConfig, arange_c, l1, dirm, line,
              run_patch=None, step_no=None):
    """Gather the accessed L1 set and derive each way's EFFECTIVE MESI state.

    PULL-BASED COHERENCE (the TPU-native shape of MESI): remote
    invalidations and downgrades are never pushed into target L1 arrays —
    that costs O(C * S1 * W1) table gathers per step. Instead each L1 way
    stores only locally-written state, and its effective state is derived
    on access by validating against the directory (which phase 4 maintains
    exactly):
        no local entry, or line absent from LLC          -> I
        directory owner == this core                     -> local state
        this core recorded in the sharer bit-vector      -> S  (covers
                                             probe-downgraded old owners)
        otherwise                                        -> I  (stale)
    Observably equivalent to eager invalidation (DESIGN.md §7); the eager
    golden model + parity tests prove it on every workload.

    The directory entry is located through the way pointer (`l1_ptr`,
    recorded at fill time) — one paired tag/owner gather plus one sharer
    -word gather — instead of a W2-wide tag search of the home set; a
    stale pointer self-detects by tag mismatch and yields exactly the
    search result (DESIGN.md §7).

    The pointer is decomposed into (bank, in-row offset) coordinates and
    the gathers index the LLC/sharer arrays in their NATIVE layouts: a
    `reshape(-1)` flat view of a TPU-tiled array is a physical relayout —
    XLA materializes a full copy of the (537 MB at 1024 cores) sharers
    array every step, the round-2 perf regression.

    Returns (w1cols, tag_rows, lru_rows, weff): the set's column indices,
    tags, LRU stamps, and effective per-way MESI states, all [C, W1].
    """
    S1, W1 = cfg.l1.sets, cfg.l1.ways
    FS = W1 * S1
    l1s = line & (S1 - 1)
    # the fused L1 array holds four planes (tag/state/lru/ptr) at a
    # FS-column stride; ONE take_along over the concatenated plane
    # columns fetches the accessed set's whole bookkeeping
    w1cols = jnp.arange(W1, dtype=jnp.int32)[None, :] * S1 + l1s[:, None]
    planes = [w1cols, w1cols + FS, w1cols + 2 * FS, w1cols + 3 * FS]
    if cfg.sharer_group > 1:
        planes.append(w1cols + 4 * FS)  # fill-time epoch plane
    rows = jnp.take_along_axis(
        l1, jnp.concatenate(planes, axis=1), axis=1
    )  # [C, 4*W1] or [C, 5*W1]
    tag_rows = rows[:, :W1]
    state_rows = rows[:, W1 : 2 * W1]
    lru_rows = rows[:, 2 * W1 : 3 * W1]
    ptr_rows = rows[:, 3 * W1 : 4 * W1]
    eph_rows = rows[:, 4 * W1 :] if cfg.sharer_group > 1 else None
    if run_patch is not None:
        # the local run's deferred L1 writes (applied only in phase 4.A's
        # fused scatter) patched in-register: silent E->M at wm columns,
        # LRU stamps at hm columns (tag/ptr/epoch planes never change
        # during a run)
        hm, wm, cm = run_patch
        colmatch = cm[:, :, None] == w1cols[:, None, :]  # [C, rl, W1]
        state_rows = jnp.where(
            jnp.any(wm[:, :, None] & colmatch, axis=1), M, state_rows
        )
        lru_rows = jnp.where(
            jnp.any(hm[:, :, None] & colmatch, axis=1), step_no, lru_rows
        )
    weff = _validate_ways(
        cfg, arange_c, tag_rows, state_rows, ptr_rows, eph_rows, dirm,
    )
    return w1cols, tag_rows, lru_rows, weff


def _validate_ways(cfg, arange_c, tag_rows, state_rows, ptr_rows, eph_rows,
                   dirm):
    """Pull-validate each way's locally-written state against the
    directory entry its fill-time way pointer names (see `_l1_probe`):
    two tag/owner element gathers + one sharer-word gather, all [C, W1].

    Under the coarse sharer vector (sharer_group > 1) the core checks
    its GROUP's bit, which may stay set on a NEIGHBOR's behalf after
    this core was invalidated — so the group-bit path additionally
    requires the entry's INVALIDATION EPOCH (bumped by every sharer-
    clearing transition) to still equal the one this core recorded at
    fill time. Epoch-match + group-bit is exactly eager-golden validity:
    every S grant after the last clearing records the current epoch, and
    anything older was invalidated by that clearing. The owner path
    needs no epoch (owner identity is exact)."""
    S2, W2 = cfg.llc.sets, cfg.llc.ways
    NW = cfg.n_sharer_words
    logG = cfg.sharer_group.bit_length() - 1
    g_c = arange_c >> logG
    pway = ptr_rows % W2  # ptr = (bank*S2 + set)*W2 + way
    pslot = ptr_rows // W2
    MW = llc_meta_width(cfg)
    vtag = dirm[pslot, 2 * pway]  # [C, W1]
    vown = dirm[pslot, 2 * pway + 1]
    vsh = dirm[pslot, MW + pway * NW + (g_c[:, None] >> 5)]
    vbit = ((vsh >> (g_c[:, None] & 31)) & 1) != 0
    if cfg.sharer_group > 1:
        veph = dirm[pslot, 3 * W2 + pway]
        vbit = vbit & (veph == eph_rows)
    return jnp.where(
        (state_rows == I) | (vtag != tag_rows),
        I,
        jnp.where(
            vown == arange_c[:, None],
            state_rows,
            jnp.where(vbit, S, I),
        ),
    )  # [C, W1] effective MESI per way


def step(
    cfg: MachineConfig,
    events: jnp.ndarray,
    st: MachineState,
    has_sync: bool = True,
) -> MachineState:
    C = cfg.n_cores
    B = cfg.n_banks
    S1, W1 = cfg.l1.sets, cfg.l1.ways
    S2, W2 = cfg.llc.sets, cfg.llc.ways
    NW = cfg.n_sharer_words
    MW = llc_meta_width(cfg)  # sharer words start here in a dirm row
    T = events.shape[1]
    n_tiles = cfg.n_tiles
    arange_c = jnp.arange(C, dtype=jnp.int32)
    # TIMING comes from the TRACED knob pytree carried in state, never
    # from cfg (which is a jit-static arg and may be timing-normalized):
    # one compiled program per GEOMETRY serves every timing variant, and
    # the fleet engine vmaps per-simulation knob values over the batch
    # axis. cfg keeps geometry and model selectors only.
    kn = st.knobs
    Q = kn.quantum
    cpi_vec = kn.cpi
    l1_lat = kn.l1_lat
    llc_lat = kn.llc_lat
    # Counter deltas accumulate in a host-side dict of [C] lanes and fold
    # into the [n_counters, C] array in ONE stacked add at the end of the
    # step: each `.at[row].add` is its own dynamic-update-slice kernel,
    # and ~25 of them per step cost real per-kernel overhead (the phase
    # profile billed ~0.26 ms to a block of ten) while the dict adds fuse
    # into the surrounding elementwise work for free.
    _cacc: dict[str, object] = {}

    def cadd(cnt, name, amount):
        a = amount.astype(jnp.int32)
        _cacc[name] = a if name not in _cacc else _cacc[name] + a
        return cnt

    def cstack():
        rows = [
            _cacc[k] if k in _cacc else jnp.zeros(C, jnp.int32)
            for k in COUNTER_NAMES
        ]
        return jnp.stack(rows)

    def cflush(cnt):
        return cnt + cstack()

    cnt = st.counters

    # ---- phase -1: fault injection (DESIGN.md §12) -----------------------
    # STATIC gate: faults-off programs contain none of this — the faults
    # pytree passes through untouched and the step graph is the pre-fault
    # one (the bit-exact / zero-overhead contract). Faults-on, everything
    # is TRACED (schedule arrays, counter-based PRNG on (seed, step,
    # site)) so one compiled program serves every seed and schedule of a
    # geometry, and the fleet vmaps straight through it.
    if cfg.faults_enabled:
        from ..faults.inject import ecc_step, fire_events, scrub_dead_cond

        fsf = st.faults
        # only cores that haven't retired END absorb faults: a finished
        # core is powered down, and — critically for the solo-vs-fleet
        # determinism contract — a fleet element keeps stepping after it
        # completes (until the whole batch drains), so any fault counted
        # on an ended core would diverge from the same element run solo
        p_end = jnp.minimum(st.ptr, T - 1)
        alive0 = (events[arange_c, p_end, 0] != EV_END) & (
            fsf.core_dead == 0
        )
        kill_sched, link_dead_n, link_extra_n = fire_events(
            cfg, fsf, st.step
        )
        ecc_corr, ecc_due, l1_due = ecc_step(cfg, fsf, st.step, arange_c)
        kill_new = kill_sched
        if cfg.fault_due_failstop:
            # an uncorrectable error in a core's private cache is fatal
            # to that core (machine-check fail-stop)
            kill_new = kill_new | l1_due.astype(jnp.int32)
        kill_now = kill_new * alive0.astype(jnp.int32)
        cnt = cadd(cnt, "core_failstops", kill_now)
        cnt = cadd(cnt, "ecc_corrected", jnp.where(alive0, ecc_corr, 0))
        cnt = cadd(cnt, "ecc_due", jnp.where(alive0, ecc_due, 0))
        dirm_f, lockh_f, wb_dead = scrub_dead_cond(
            cfg, st.dirm, st.lock_holder, kill_now
        )
        if cfg.fault_dead_policy == "writeback":
            cnt = cadd(cnt, "l1_writebacks", wb_dead)
        fsf = fsf._replace(
            core_dead=fsf.core_dead | kill_now,
            link_dead=link_dead_n,
            link_extra=link_extra_n,
        )
        st = st._replace(dirm=dirm_f, lock_holder=lockh_f, faults=fsf)
        deadb = fsf.core_dead != 0  # [C] — dead cores leave every mask

    # ---- phase 0: quantum barrier (on step-entry state) ------------------
    # Barrier-frozen cores (arrived, waiting for release) neither bump nor
    # bound the quantum (DESIGN.md §3): they rejoin at release. With local
    # runs enabled the event at ptr is slot 0 of the phase-0.5 prefetch —
    # reuse it instead of a separate gather kernel.
    if cfg.local_run_len:
        _rl0 = cfg.local_run_len
        _ioff0 = jnp.arange(_rl0 + 1, dtype=jnp.int32)
        _pidx0 = jnp.minimum(st.ptr[:, None] + _ioff0[None, :], T - 1)
        _pev0 = events[arange_c[:, None], _pidx0]  # [C, rl+1, 4]
        et0 = _pev0[:, 0, 0]
    else:
        p0 = jnp.minimum(st.ptr, T - 1)
        et0 = events[arange_c, p0, 0]
    countable0 = (et0 != EV_END) & ~((et0 == EV_BARRIER) & (st.sync_flag != 0))
    if cfg.faults_enabled:
        # a fail-stopped core neither bumps nor bounds the quantum — it
        # leaves the barrier instead of deadlocking it
        countable0 = countable0 & ~deadb
    any_countable = jnp.any(countable0)
    any_active = jnp.any(countable0 & (st.cycles < st.quantum_end))
    min_nd = jnp.min(jnp.where(countable0, st.cycles, INT32_MAX))
    bumped = (min_nd // Q + 1) * Q
    quantum_end = jnp.where(any_countable & ~any_active, bumped, st.quantum_end)

    step_no = st.step

    # ---- phase 0.5: local runs (DESIGN.md §3) ----------------------------
    # Up to `local_run_len` local events retire per core before the one
    # arbitrated event below: INS batches, L1 read hits, and L1 write hits
    # in E/M, judged against the step-start directory (unchanged during
    # runs) and the core's own live L1 state. Stops at the first non-local
    # event, the quantum boundary, or the run limit. These are one-hot
    # lane updates on the core's own row only — no cross-core effects.
    #
    # PREFETCHED: during a run the pointer advances by exactly one per
    # retired event, so candidate i sits at ptr0 + i and everything every
    # iteration's hit probe reads is known up front: the directory
    # (llc_meta/sharers) is read-only for the whole phase, l1_tag never
    # changes during a run, and l1_state changes only by deferred silent
    # E->M writes the probe cannot distinguish (match needs != I, write
    # hit needs >= E). So the rl+1 candidate events, their L1 set rows,
    # their home-set metadata, and their self-sharer words come in via
    # FIVE batched gathers, and the unrolled loop below is pure lane
    # arithmetic — the per-iteration element-gathers on the multi-hundred
    # -MB directory arrays (the round-4 local-run wall) are gone.
    #
    # The probe validates against the accessed line's HOME entry (W2-wide
    # tag search of the gathered metadata row) rather than through the L1
    # way pointer; DESIGN.md §7 proves search- and pointer-validation
    # observably identical (a stale pointer self-detects to exactly the
    # search result), and the parity suite re-proves it on every workload.
    cycles_c, ptr_c = st.cycles, st.ptr
    l1_c = st.l1
    FS = W1 * S1  # plane stride in the fused L1 array
    rl = cfg.local_run_len
    logB = B.bit_length() - 1
    if rl:
        pev = _pev0  # [C, rl+1, 4] — gathered once in phase 0
        pline = pev[:, :, 2]  # line-granular (Trace.line_events)
        ps = pline & (S1 - 1)
        pcols = (
            jnp.arange(W1, dtype=jnp.int32)[None, None, :] * S1
            + ps[:, :, None]
        )  # [C, rl+1, W1]
        pcf = pcols.reshape(C, (rl + 1) * W1)
        # tag + state planes of every candidate's set in ONE take_along
        # (lru/ptr aren't needed for run hit probes; feeding them to the
        # arbitration probe too was tried and measured SLOWER — the extra
        # select/patch kernels outweighed the saved gathers). The coarse
        # vector additionally needs the fill-time epoch plane.
        KW = (rl + 1) * W1
        pl_cols = [pcf, pcf + FS]
        if cfg.sharer_group > 1:
            pl_cols.append(pcf + 4 * FS)
        pts = jnp.take_along_axis(
            st.l1, jnp.concatenate(pl_cols, axis=1), axis=1
        )
        ptagr = pts[:, :KW].reshape(C, rl + 1, W1)
        pstater = pts[:, KW : 2 * KW].reshape(C, rl + 1, W1)
        pbank = pline & (B - 1)
        pbset = (pline >> logB) & (S2 - 1)
        pslot = pbank * S2 + pbset
        pmrows = st.dirm[pslot]  # [C, rl+1, DW] — metadata AND sharers
        pmeta = pmrows[:, :, : 2 * W2].reshape(C, rl + 1, W2, 2)
        pmmatch = pmeta[..., 0] == pline[:, :, None]
        pmhas = jnp.any(pmmatch, axis=2)
        pmway = jnp.argmax(pmmatch, axis=2).astype(jnp.int32)
        pown = jnp.take_along_axis(pmeta[..., 1], pmway[:, :, None], axis=2)[
            :, :, 0
        ]
        g_c0 = arange_c >> (cfg.sharer_group.bit_length() - 1)
        # the self sharer word rides the row gather: in-register select
        pshw = jnp.take_along_axis(
            pmrows[:, :, MW:],
            (pmway * NW + (g_c0[:, None] >> 5))[:, :, None],
            axis=2,
        )[:, :, 0]
        pbit = ((pshw >> (g_c0[:, None] & 31)) & 1) != 0
        pmatch_l = (ptagr == pline[:, :, None]) & (pstater != I)
        plhit = jnp.any(pmatch_l, axis=2)
        plway = jnp.argmax(pmatch_l, axis=2).astype(jnp.int32)
        plstate = jnp.take_along_axis(pstater, plway[:, :, None], axis=2)[
            :, :, 0
        ]
        if cfg.sharer_group > 1:
            # epoch guard (see _validate_ways): the group bit only keeps
            # this core's S line alive if no sharer-clearing transition
            # happened since its fill
            pleph = jnp.take_along_axis(
                pts[:, 2 * KW :].reshape(C, rl + 1, W1),
                plway[:, :, None],
                axis=2,
            )[:, :, 0]
            pveph = jnp.take_along_axis(
                pmrows[:, :, 3 * W2 : 4 * W2], pmway[:, :, None], axis=2
            )[:, :, 0]
            pbit = pbit & (pveph == pleph)
        peff = jnp.where(
            ~(plhit & pmhas),
            I,
            jnp.where(
                pown == arange_c[:, None],
                plstate,
                jnp.where(pbit, S, I),
            ),
        )  # [C, rl+1] effective MESI of the tag-matching way
        if cfg.coherence == "moesi":
            # derived Owned (DESIGN.md §25): this core owns the line at
            # the home while other sharers are recorded — a run's ST on
            # it must arbitrate (the sharers need invalidating), so the
            # probe's effective E/M demotes to O. sharer_group == 1 under
            # moesi (config validation), so pbit IS the self bit and the
            # word popcount is an exact sharer count.
            psh_all = pmrows[:, :, MW:].reshape(C, rl + 1, W2, NW)
            pwords = jnp.take_along_axis(
                psh_all, pmway[:, :, None, None], axis=2
            )[:, :, 0]  # [C, rl+1, NW]
            ptot = jnp.sum(jax.lax.population_count(pwords), axis=2)
            pothers = (ptot - pbit.astype(jnp.int32)) > 0
            peff = jnp.where(
                pothers & pmhas & (pown == arange_c[:, None]) & (peff >= E),
                O,
                peff,
            )
        phitcol = plway * S1 + ps
    if rl:
        # CLOSED FORM for the run itself (no unrolled loop): a candidate
        # retires iff every earlier candidate was local (prefix-AND via
        # cumprod) and the clock BEFORE it — an exclusive prefix sum of
        # retired costs — is still inside the quantum. The serial
        # recurrence and this form agree exactly: costs are
        # non-negative, so the clock-before sequence is non-decreasing
        # and the first quantum crossing cuts both the same way; a
        # pref-but-quantum-stopped candidate forces every later
        # clock-before past the boundary, so over-counting its cost in
        # the prefix sum can never resurrect a later candidate. L1
        # scatters and counter bumps are single fused ops over the
        # [C, rl] retire masks (nothing in the run reads l1_lru, and the
        # probe treats E and M identically, so the deferred silent E->M
        # is invisible — DESIGN.md §3).
        etr = pev[:, :rl, 0]
        eargr = pev[:, :rl, 1]
        eprer = pev[:, :rl, 3]
        is_ins_k = etr == EV_INS
        r_hit_k = (etr == EV_LD) & (peff[:, :rl] != I)
        # E/M exactly — a derived O (moesi) reads locally but must
        # arbitrate its stores (same pair under mesi, where peff <= M)
        w_hit_k = (etr == EV_ST) & (
            (peff[:, :rl] == E) | (peff[:, :rl] == M)
        )
        hit_k = r_hit_k | w_hit_k
        local_k = is_ins_k | hit_k  # END/sync/miss candidates stop the run
        pref = jnp.cumprod(local_k.astype(jnp.int32), axis=1) != 0
        if cfg.faults_enabled:
            pref = pref & ~deadb[:, None]  # dead cores retire nothing
        cost_k = jnp.where(
            is_ins_k,
            eargr * cpi_vec[:, None],
            eprer * cpi_vec[:, None] + l1_lat,
        )
        cost_p = jnp.where(pref, cost_k, 0)
        clock_before = (
            cycles_c[:, None] + jnp.cumsum(cost_p, axis=1) - cost_p
        )
        retire_k = pref & (clock_before < quantum_end)
        cycles_c = cycles_c + jnp.sum(
            jnp.where(retire_k, cost_k, 0), axis=1
        )
        ptr_c = ptr_c + jnp.sum(retire_k, axis=1).astype(jnp.int32)
        cnt = cadd(cnt, "l1_read_hits", jnp.sum(r_hit_k & retire_k, axis=1))
        cnt = cadd(cnt, "l1_write_hits", jnp.sum(w_hit_k & retire_k, axis=1))
        cnt = cadd(
            cnt,
            "instructions",
            jnp.sum(
                jnp.where(
                    retire_k,
                    jnp.where(is_ins_k, eargr, eprer + 1),
                    0,
                ),
                axis=1,
            ),
        )
        hm = hit_k & retire_k  # [C, rl]
        wm = w_hit_k & retire_k
        cm = phitcol[:, :rl]
        # The run's L1 writes (LRU refreshes, silent E->M) are DEFERRED
        # all the way into phase 4.A's single fused scatter: a second
        # scatter chained on the same array cannot alias its operand and
        # re-materializes it (the 5 ms/step join-lru lesson). Phase 1
        # patches the prefetched planes in-register instead.

    # ---- phase 0.9 + phase 1: the arbitration event and its L1 probe -----
    # addresses arrive LINE-granular (Trace.line_events normalizes byte
    # traces at ingest; v4 line-addressed traces pass through) — 2^31
    # lines = 128 GiB at 64B lines, 64x the byte-addressed range
    if rl:
        # a lane that retired k local events arbitrates candidate k
        # (clamped pidx repeats the final END row, so over-running lanes
        # read END here exactly as a direct gather would). Reusing MORE
        # of the prefetch here (classification, L1 planes, home metadata
        # row) was tried and measured slower: the select/patch kernels
        # cost more than the gathers they replaced.
        consumed = (ptr_c - st.ptr)[:, None, None]
        ev = jnp.take_along_axis(pev, consumed, axis=1)[:, 0]  # [C, 4]
    else:
        p = jnp.minimum(ptr_c, T - 1)
        ev = events[arange_c, p]  # [C, 4]
    et, earg, eaddr, epre = ev[:, 0], ev[:, 1], ev[:, 2], ev[:, 3]
    line = eaddr
    l1s = line & (S1 - 1)
    pallas_step = cfg.step_impl == "pallas"
    if pallas_step:
        # [PALLAS] fused probe_classify (DESIGN.md §11): phase 1 AND the
        # LLC home-row parse below run as ONE VMEM-blocked kernel. XLA
        # keeps only the two row gathers that STAGE the directory rows
        # into the kernel (data-dependent row gathers are the one access
        # shape the block model cannot express); everything downstream of
        # them — plane selects, pointer validation, classification,
        # sharer predicates, victim selection — fuses.
        from ..kernels.step_kernels import probe_classify

        DWK = dirm_width(cfg)
        bank = line & (B - 1)
        bset = (line >> logB) & (S2 - 1)
        slot = bank * S2 + bset
        meta_rows = st.dirm[slot]  # [C, DW], reused by commit_step
        w1cols = jnp.arange(W1, dtype=jnp.int32)[None, :] * S1 + l1s[:, None]
        ptr_pre = jnp.take_along_axis(l1_c, w1cols + 3 * FS, axis=1)
        vrows = st.dirm[ptr_pre // W2].reshape(C, W1 * DWK)
        tag_rows, lru_rows, weff, shw, vic_shw, pc_lanes = probe_classify(
            cfg, l1_c, vrows, meta_rows, line, arange_c, step_no,
            *((hm, wm, cm) if rl else ()),
        )
        from ..kernels.step_kernels import (
            PL_HIT_ANY,
            PL_HIT_STATE,
            PL_HIT_WAY,
        )

        hit_any = pc_lanes[:, PL_HIT_ANY] != 0
        hit_way = pc_lanes[:, PL_HIT_WAY]
        hit_state = pc_lanes[:, PL_HIT_STATE]
    else:
        w1cols, tag_rows, lru_rows, weff = _l1_probe(
            cfg, arange_c, l1_c, st.dirm, line,
            run_patch=(hm, wm, cm) if rl else None,
            step_no=step_no,
        )
        l1_match = (tag_rows == line[:, None]) & (weff != I)
        hit_any = jnp.any(l1_match, axis=1)
        hit_way = jnp.argmax(l1_match, axis=1).astype(jnp.int32)
        hit_state = weff[arange_c, hit_way]

    not_done = et != EV_END
    frozen = (et == EV_BARRIER) & (st.sync_flag != 0)
    active = not_done & ~frozen & (cycles_c < quantum_end)
    if cfg.faults_enabled:
        active = active & ~deadb

    is_ins = active & (et == EV_INS)
    is_st_ev = et == EV_ST
    is_mem = active & ((et == EV_LD) | is_st_ev)
    is_lock = active & (et == EV_LOCK)
    is_unlock = active & (et == EV_UNLOCK)
    is_barrier = active & (et == EV_BARRIER)  # arrivals (frozen excluded)

    # (hit classification moved below the LLC parse: the moesi derived-O
    # demotion needs the home row's owner + sharer predicates first)

    # LLC lookup for the accessed line (step-start, all lanes — needed both
    # for join eligibility below and the winner transitions in phase 3).
    # ONE full-row gather returns the home set's tags, owners AND LRU
    # stamps; the owner, victim-owner and victim-LRU reads below become
    # in-register row indexing instead of separate element gathers.
    if pallas_step:
        # [PALLAS] parse already fused into probe_classify; unpack lanes
        from ..kernels.step_kernels import PL_LLC_HAS, PL_LLC_HWAY, PL_OWNER

        llc_has = pc_lanes[:, PL_LLC_HAS] != 0
        llc_hway = pc_lanes[:, PL_LLC_HWAY]
        owner = pc_lanes[:, PL_OWNER]
    else:
        bank = line & (B - 1)
        bset = (line >> logB) & (S2 - 1)
        slot = bank * S2 + bset  # [C], exact (bank,set) id
        meta_rows = st.dirm[slot]  # [C, DW]: the set's metadata AND sharers
        mr2 = meta_rows[:, : 2 * W2].reshape(C, W2, 2)
        llc_tag_rows = mr2[..., 0]  # [C, W2]
        owner_rows = mr2[..., 1]
        llc_match = llc_tag_rows == line[:, None]
        llc_has = jnp.any(llc_match, axis=1)
        llc_hway = jnp.argmax(llc_match, axis=1).astype(jnp.int32)
        owner = owner_rows[arange_c, llc_hway]  # [C]
        # the sharer words came along in the same row gather
        sh_rows = meta_rows[:, MW:].reshape(C, W2, NW)  # [C, W2, NW]
        shw = jnp.take_along_axis(
            sh_rows, llc_hway[:, None, None], axis=1
        )[:, 0]

    # sharer-set predicates from the PACKED words — popcount minus the
    # self bit needs no [C, C] expansion (the expansion, when needed for
    # invalidation targets, happens in phase 3: dense, chunked, or — for
    # the coarse vector — group-table reductions). Bit index = the core's
    # GROUP under cfg.sharer_group (identity at G=1).
    logG = cfg.sharer_group.bit_length() - 1
    g_c = arange_c >> logG
    word_idx = g_c // 32  # [C] self -> sharer word
    bit_idx = g_c % 32

    def unpack_bits(words):  # [C, NW] words -> [C, C] bool per TARGET core
        b = (words[:, :, None] >> jnp.arange(32, dtype=jnp.int32)[None, None, :]) & 1
        groups = b.reshape(C, NW * 32) != 0
        # target core t is recorded iff its GROUP's bit is set (identity
        # expansion at G=1)
        return jnp.take(groups, g_c, axis=1)

    if pallas_step:
        from ..kernels.step_kernels import PL_OTHER_SH, PL_SELF_BIT

        self_bit = pc_lanes[:, PL_SELF_BIT]
        other_sharers = pc_lanes[:, PL_OTHER_SH] != 0
    else:
        self_bit = (
            (shw[arange_c, word_idx] >> bit_idx) & 1
        ).astype(jnp.int32)
        total_sharers = jnp.sum(
            jax.lax.population_count(shw), axis=1
        ).astype(jnp.int32)
        if cfg.sharer_group > 1:
            # coarse: the requester's own group bit may cover OTHER
            # cores, so exclusivity (E grants) requires an empty vector
            # (golden `shared_any`)
            other_sharers = total_sharers > 0
        else:
            other_sharers = (total_sharers - self_bit) > 0

    if cfg.coherence == "moesi":
        # derived Owned (DESIGN.md §25): a stored E/M hit while the home
        # directory still names this core owner WITH other sharers
        # recorded (a GETS left the dirty copy here) is an O hit — reads
        # stay local, but a store must arbitrate as an upgrade to
        # invalidate the sharers. Pure demotion of the classification
        # input; the stored plane is untouched (O is never written).
        hit_state = jnp.where(
            hit_any & llc_has & (owner == arange_c) & other_sharers
            & (hit_state >= E),
            O,
            hit_state,
        )

    read_hit = is_mem & ~is_st_ev & hit_any
    # E/M exactly, never a derived O (the `(== E) | (== M)` pair is
    # `>= E` under mesi, where hit_state <= M)
    write_hit = is_mem & is_st_ev & hit_any & (
        (hit_state == E) | (hit_state == M)
    )
    upg = is_mem & is_st_ev & hit_any & (
        (hit_state == S) | (hit_state == O)
    )
    gets = is_mem & ~is_st_ev & ~hit_any
    getm = is_mem & is_st_ev & ~hit_any

    # ---- phase 2: read-join coalescing + per-(bank,set) arbitration ------
    # GETS to an LLC-resident, ownerless, already-shared line may coalesce:
    # the serialized 'plain join' transition (S grant, sharers |= {c}) has
    # latency independent of the sharer set and commutative state updates,
    # so any number retire in one step, bit-exact to any serialization
    # order (DESIGN.md §3). A join only proceeds if no arbitrating request
    # targets its home (bank,set) this step; else it demotes to normal
    # GETS. Disabled under the coarse vector: same-group joiners' bit
    # updates would collide in the fused scatter-add.
    join_elig = gets & llc_has & (owner == -1) & other_sharers
    if cfg.sharer_group > 1:
        join_elig = jnp.zeros_like(join_elig)
    req = (gets & ~join_elig) | getm | upg
    # Packed single-scatter key ordering by (cycles, core_id). Valid because
    # every arbitrating lane's clock lies in [quantum_end - Q, quantum_end):
    # clocks never decrease, quantum bumps stop at min_countable + Q, and a
    # barrier release resumes waiters at the slot's max ARRIVAL clock — set
    # in the same step as the count-completing arrival, whose core was
    # active then — so released clocks re-enter the window too (DESIGN.md
    # §3-sync invariant; the golden model asserts it every step).
    rel = cycles_c - (quantum_end - Q)  # in [0, Q) for active requesters
    key = rel * C + arange_c  # orders by (cycles, core_id); < Q*C < 2^31
    table = jnp.full(B * S2, INT32_MAX, jnp.int32)
    table = table.at[jnp.where(req, slot, B * S2)].min(key, mode="drop")
    slot_busy = table[slot] != INT32_MAX
    join = join_elig & ~slot_busy
    demoted = join_elig & slot_busy
    table = table.at[jnp.where(demoted, slot, B * S2)].min(key, mode="drop")
    req = req | demoted
    winner = req & (table[slot] == key)
    retry = req & ~winner
    cnt = cadd(cnt, "retries", retry)

    # ---- phase 3: directory transition on step-start state ---------------
    ctile = arange_c % n_tiles
    btile = bank % n_tiles
    req_lat, req_hops = _one_way(ctile, btile, cfg, kn)
    rep_lat, rep_hops = _one_way(btile, ctile, cfg, kn)
    if cfg.faults_enabled:
        # link-fault penalties of the request/reply legs (detour around
        # dead links + degrade extras — faults/inject.py). The NOMINAL
        # legs are left untouched through the service/contention math:
        # the router model's `extra_home = raw_rt - (req_lat + service +
        # rep_lat)` decomposition and the link/tile contention counts are
        # all defined on the nominal XY path (a detour adds latency, it
        # does not re-route the contention walk), so the fault extras
        # join the composed latencies AFTER that block, and the hop
        # counters bump just before the counter fold.
        from ..faults.inject import leg_fault_penalty

        fx_req, fh_req, rr_req = leg_fault_penalty(
            cfg, st.faults, kn, ctile, btile
        )
        fx_rep, fh_rep, rr_rep = leg_fault_penalty(
            cfg, st.faults, kn, btile, ctile
        )
        flt_rt = fx_req + fx_rep  # round-trip fault extra, home txns

    # barrier home tile (bid lives in the addr field; ids validated
    # < barrier_slots at ingest) — shared by the contention count and the
    # phase-2.7 arrival/release paths
    bid = jnp.where(et == EV_BARRIER, eaddr, 0)
    htile = bid % n_tiles

    # ---- NoC contention (NocConfig.contention) ---------------------------
    # This step's uncore transactions: memory winners + joins (home bank),
    # lock/unlock RMWs (the lock's home == the same btile), barrier
    # arrivals (bid % n_tiles). Tile model: occupancy count per home tile,
    # charge contention_lat * (count - 1). Link model: each transaction's
    # XY request+reply path (barrier arrivals: one way) claims its links;
    # charge contention_lat * bottleneck (count - 1) over the path —
    # mirroring golden's _bump/_contention_extra exactly. The "router"
    # model replaces the analytic request/reply legs wholesale and is
    # computed after the service components are known (below).
    router = cfg.noc.contention and cfg.noc.contention_model == "router"
    home_txn = winner | join
    if has_sync:
        home_txn = home_txn | is_lock | is_unlock
    if cfg.noc.contention and not router:
        ccl = kn.contention_lat
        if cfg.noc.contention_model == "link":
            from ..noc.mesh import n_links

            NL = n_links(cfg)
            req_p = _path_links(cfg, ctile, btile)  # [C, H]
            rep_p = _path_links(cfg, btile, ctile)
            arr_p = _path_links(cfg, ctile, htile)
            # every leg's occupancy in ONE concatenated [C, legs*H]
            # scatter-add (the router block's idiom; integer adds are
            # order-independent, so folding the per-path loop is exact)
            lpth, lmask = _concat_legs(
                [(req_p, home_txn), (rep_p, home_txn)]
                + ([(arr_p, is_barrier)] if has_sync else [])
            )
            lcnt = jnp.zeros(NL, jnp.int32).at[
                jnp.where(lmask & (lpth >= 0), lpth, NL)
            ].add(1, mode="drop")

            def _path_worst(pth):
                cts = lcnt[jnp.where(pth >= 0, pth, 0)]
                return jnp.max(jnp.where(pth >= 0, cts - 1, 0), axis=1)

            extra_home = ccl * jnp.maximum(_path_worst(req_p), _path_worst(rep_p))
            extra_bar = ccl * _path_worst(arr_p)
        else:
            tcnt = jnp.zeros(n_tiles, jnp.int32)
            tcnt = tcnt.at[jnp.where(home_txn, btile, n_tiles)].add(
                1, mode="drop"
            )
            if has_sync:
                tcnt = tcnt.at[jnp.where(is_barrier, htile, n_tiles)].add(
                    1, mode="drop"
                )
            extra_home = ccl * (tcnt[btile] - 1)  # valid where home_txn
            extra_bar = ccl * (tcnt[htile] - 1)  # valid where is_barrier
        cnt = cadd(
            cnt,
            "noc_contention_cycles",
            jnp.where(home_txn, extra_home, 0)
            + (jnp.where(is_barrier, extra_bar, 0) if has_sync else 0),
        )
    else:
        extra_home = extra_bar = jnp.zeros(C, jnp.int32)

    llc_hit = llc_has & winner
    llc_miss = winner & ~llc_has

    has_owner = llc_hit & (owner >= 0) & (owner != arange_c)
    oclamp = jnp.maximum(owner, 0)
    otile = oclamp % n_tiles
    po_lat, po_hops = _one_way(btile, otile, cfg, kn)  # bank -> owner (symmetric back)
    if cfg.faults_enabled:
        # probe legs keep the analytic model's symmetric round-trip shape
        # (2 * po_lat): the forward-leg fault penalty is charged both
        # ways. Safe to bump in place — nothing downstream decomposes the
        # probe leg the way the router block decomposes req/rep.
        fx_po, fh_po, rr_po = leg_fault_penalty(
            cfg, st.faults, kn, btile, otile
        )
        po_lat = po_lat + fx_po
        po_hops = po_hops + fh_po

    is_write_req = getm | upg
    gets_w = gets & winner
    write_w = is_write_req & winner

    # --- GETS grant decision (other_sharers from the phase-1 popcount)
    gets_probe = gets_w & llc_hit & has_owner
    gets_shared = gets_w & llc_hit & ~has_owner & other_sharers
    gets_excl_hit = gets_w & llc_hit & ~has_owner & ~other_sharers

    write_probe = write_w & llc_hit & has_owner

    # --- LLC miss: victim + back-invalidation
    if pallas_step:
        # [PALLAS] victim chosen inside probe_classify (first-minimum
        # LRU over valid ways, identical tie-breaking); vic_shw is a
        # kernel output
        from ..kernels.step_kernels import (
            PL_LLC_VWAY,
            PL_VIC_OWNER,
            PL_VIC_TAG,
        )

        vic_tag = pc_lanes[:, PL_VIC_TAG]
        vic_owner = pc_lanes[:, PL_VIC_OWNER]
        llc_vway = pc_lanes[:, PL_LLC_VWAY]
    else:
        llc_state_valid = llc_tag_rows != -1
        llc_lru_rows = meta_rows[:, 2 * W2 : 3 * W2]  # [C, W2], row gather
        vkey = jnp.where(llc_state_valid, llc_lru_rows, -1)
        llc_vway = jnp.argmin(vkey, axis=1).astype(jnp.int32)
        vic_tag = llc_tag_rows[arange_c, llc_vway]
        vic_owner = owner_rows[arange_c, llc_vway]
        vic_shw = jnp.take_along_axis(
            sh_rows, llc_vway[:, None, None], axis=1
        )[:, 0]
    vic_valid = llc_miss & (vic_tag != -1)

    # --- invalidation + back-invalidation target reductions. Targets come
    # from the packed sharer words (write invalidations to the accessed
    # line's sharers excluding self; back-invalidations to the victim's
    # sharers PLUS its owner — golden adds the owner to vtargets when not
    # already recorded). The reduction is the dense [C, C] expansion
    # (fastest at <= 1024 cores), a lax.scan over K-word blocks bounding
    # temporaries to [C, 32K] (cfg.sharer_chunk_words; BASELINE rung 4),
    # or — under the coarse vector — per-GROUP table reductions sized
    # [C, n_groups] with NO per-core expansion at all (BASELINE rung 5:
    # 16384 cores x 256 groups). Each is bit-exact vs the golden model
    # under the same config.
    inv_row = write_w & llc_hit
    if cfg.sharer_group > 1:
        n_grp = cfg.n_sharer_groups
        memb_n, max2hops_n, sum2hops_n = _group_tables(cfg)
        memb = jnp.asarray(memb_n)
        max2hops = jnp.asarray(max2hops_n)
        sum2hops = jnp.asarray(sum2hops_n)
        bit5 = jnp.arange(32, dtype=jnp.int32)

        def _group_bools(words):  # [C, NW] -> [C, n_grp]
            b = (words[:, :, None] >> bit5[None, None, :]) & 1
            return b.reshape(C, NW * 32)[:, :n_grp] != 0

        grp = _group_bools(shw)
        vic_grp = _group_bools(vic_shw)
        # round-trip latency 2*(h*link + (h+1)*router) is monotone
        # nondecreasing in hop count, so the per-group max over members
        # is the latency AT the max hop count — the geometry-only hops
        # table composes with the TRACED link/router knobs here
        mh_rows = max2hops[btile]  # [C, n_grp]
        ml_rows = 2 * (mh_rows * kn.link_lat + (mh_rows + 1) * kn.router_lat)
        sumh_rows = sum2hops[btile]
        selfg = jnp.arange(n_grp, dtype=jnp.int32)[None, :] == g_c[:, None]
        self_rec = jnp.any(grp & selfg, axis=1)  # requester's group flagged
        # serialization latency spans every recorded core of flagged
        # groups INCLUDING the requester's slot (golden: the home node
        # serializes the whole group broadcast); messages/counters skip
        # the requester
        inv_lat = jnp.where(
            inv_row,
            jnp.max(jnp.where(grp, ml_rows, 0), axis=1),
            0,
        )
        inv_count = jnp.where(
            inv_row,
            jnp.sum(jnp.where(grp, memb[None, :], 0), axis=1)
            - self_rec.astype(jnp.int32),
            0,
        )
        _, self_hops = _one_way(btile, ctile, cfg, kn)
        inv_hops = jnp.where(
            inv_row,
            jnp.sum(jnp.where(grp, sumh_rows, 0), axis=1)
            - jnp.where(self_rec, 2 * self_hops, 0),
            0,
        )
        # back-invalidation: every recorded core of the victim's flagged
        # groups, plus its owner when not already recorded
        og = jnp.maximum(vic_owner, 0) >> logG
        own_rec = (
            jnp.take_along_axis(vic_grp, og[:, None], axis=1)[:, 0]
            & (vic_owner >= 0)
        )
        own_extra = (vic_owner >= 0) & ~own_rec
        _, own_hops = _one_way(
            btile, jnp.maximum(vic_owner, 0) % n_tiles, cfg, kn
        )
        back_count = jnp.where(
            vic_valid,
            jnp.sum(jnp.where(vic_grp, memb[None, :], 0), axis=1)
            + own_extra.astype(jnp.int32),
            0,
        )
        back_hops = jnp.where(
            vic_valid,
            jnp.sum(jnp.where(vic_grp, sumh_rows, 0), axis=1)
            + jnp.where(own_extra, 2 * own_hops, 0),
            0,
        )
    elif cfg.sharer_chunk_words:
        K = cfg.sharer_chunk_words
        nblk = NW // K
        bit5 = jnp.arange(32, dtype=jnp.int32)

        def _blk(carry, b):
            il, ic, ih, bc, bh = carry
            off = b * K
            sw = jax.lax.dynamic_slice_in_dim(shw, off, K, axis=1)
            vw = jax.lax.dynamic_slice_in_dim(vic_shw, off, K, axis=1)
            tt = off * 32 + jnp.arange(K * 32, dtype=jnp.int32)  # target ids
            tvalid = tt[None, :] < C  # padding bits beyond core C-1
            bits = (
                ((sw[:, :, None] >> bit5[None, None, :]) & 1).reshape(C, K * 32)
                != 0
            )
            vbits = (
                ((vw[:, :, None] >> bit5[None, None, :]) & 1).reshape(C, K * 32)
                != 0
            )
            plat, phops = _one_way(
                btile[:, None], (tt % n_tiles)[None, :], cfg, kn
            )
            sh_b = (
                bits
                & (tt[None, :] != arange_c[:, None])
                & inv_row[:, None]
                & tvalid
            )
            il = jnp.maximum(il, jnp.max(jnp.where(sh_b, 2 * plat, 0), axis=1))
            ic = ic + jnp.sum(sh_b, axis=1).astype(jnp.int32)
            ih = ih + jnp.sum(jnp.where(sh_b, 2 * phops, 0), axis=1).astype(
                jnp.int32
            )
            ob = (tt[None, :] == vic_owner[:, None]) & (vic_owner >= 0)[:, None]
            bk_b = (vbits | ob) & vic_valid[:, None] & tvalid
            bc = bc + jnp.sum(bk_b, axis=1).astype(jnp.int32)
            bh = bh + jnp.sum(jnp.where(bk_b, 2 * phops, 0), axis=1).astype(
                jnp.int32
            )
            return (il, ic, ih, bc, bh), None

        z5 = jnp.zeros(C, jnp.int32)
        (inv_lat, inv_count, inv_hops, back_count, back_hops), _ = jax.lax.scan(
            _blk, (z5, z5, z5, z5, z5), jnp.arange(nblk, dtype=jnp.int32)
        )
    elif cfg.pallas_reduce or pallas_step:
        # same dense reduction as the branch below, as ONE Pallas kernel
        # (SURVEY §2 #4's Pallas uncore piece; the step subsystem's third
        # resident kernel — step_impl="pallas" routes it unconditionally);
        # bit-identical. Latencies are the TRACED knobs, so fleet sweeps
        # through this kernel compile once per geometry.
        from ..kernels.reductions import sharer_reductions

        (inv_lat, inv_count, inv_hops, back_count, back_hops) = (
            sharer_reductions(
                cfg, shw, vic_shw, btile, vic_owner, inv_row, vic_valid,
                arange_c, kn.link_lat, kn.router_lat,
            )
        )
    else:
        ttile = arange_c % n_tiles  # target tiles
        pair_lat, pair_hops = _one_way(btile[:, None], ttile[None, :], cfg, kn)
        sh_bits = unpack_bits(shw)
        sh_bits = sh_bits & (arange_c[None, :] != arange_c[:, None])
        inv_pairs = sh_bits & inv_row[:, None]  # [C, C]
        inv_lat = jnp.max(jnp.where(inv_pairs, 2 * pair_lat, 0), axis=1)
        inv_count = jnp.sum(inv_pairs, axis=1).astype(jnp.int32)
        inv_hops = jnp.sum(jnp.where(inv_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)
        vic_sh_bits = unpack_bits(vic_shw)
        vic_owner_bit = (arange_c[None, :] == vic_owner[:, None]) & (vic_owner >= 0)[:, None]
        back_pairs = (vic_sh_bits | vic_owner_bit) & vic_valid[:, None]
        back_count = jnp.sum(back_pairs, axis=1).astype(jnp.int32)
        back_hops = jnp.sum(jnp.where(back_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)

    # --- stride prefetcher (DESIGN.md §25; cfg.prefetcher static) ---------
    # Per-core stride detector over the UNCORE access stream (winners +
    # joins — the retired home transactions; retries re-observe the same
    # line next step and must not retrain). An LLC miss whose line sits
    # within prefetch_degree strides ahead of the last trained access on
    # a confirmed stride (streak >= 2) is served from the prefetch buffer:
    # it pays the TRACED prefetch_lat instead of dram_lat and skips the
    # memory-controller queue. dram_accesses still counts every LLC miss
    # (the prefetcher moved the fetch earlier, it did not remove it);
    # prefetch_hits counts the covered ones. State is step-entry: at most
    # one retiring uncore event per core per step, and joins train only
    # their own core, so read-then-train is race-free.
    if cfg.prefetcher == "stride":
        pfl, pfs, pfk = st.pf_line, st.pf_stride, st.pf_streak
        safe_s = jnp.where(pfs == 0, 1, pfs)
        delta = line - pfl
        qd = delta // safe_s
        rem = delta - qd * safe_s
        pf_hit = (
            llc_miss & (pfs != 0) & (pfk >= 2) & (rem == 0)
            & (qd >= 1) & (qd <= kn.prefetch_degree)
        )
        miss_dram = llc_miss & ~pf_hit  # misses that still go to DRAM
        cnt = cadd(cnt, "prefetch_hits", pf_hit)
        pf_train = winner | join
        new_stride = line - pfl
        pf_streak_n = jnp.where(
            pf_train,
            jnp.where((new_stride == pfs) & (pfs != 0), pfk + 1, 1),
            pfk,
        )
        pf_stride_n = jnp.where(pf_train, new_stride, pfs)
        pf_line_n = jnp.where(pf_train, line, pfl)
    else:
        pf_hit = jnp.zeros(C, bool)
        miss_dram = llc_miss
        pf_line_n = st.pf_line
        pf_stride_n = st.pf_stride
        pf_streak_n = st.pf_streak

    # --- memory-controller queue (cfg.dram_queue, SURVEY §2 #7) -----------
    # Miss winners queue at their home bank's controller: wait floor =
    # max(dram_free[bank], bank's earliest nominal arrival this step) +
    # rank*service — the router model's FIFO shape on a per-bank clock.
    # Ranks via the shared sort-based segmented-rank primitive (one dense
    # key order feeds this block AND the router walk); bit-exact vs
    # golden (tests/test_dram.py).
    if cfg.dram_queue or router:
        ord_c = lane_order(key)
    if cfg.dram_queue:
        svc_d = jnp.where(kn.dram_service > 0, kn.dram_service, kn.dram_lat)
        a_nom = (
            cycles_c + epre * cpi_vec + l1_lat + req_lat
            + llc_lat
        )
        dtgt = jnp.where(miss_dram, bank, B)
        dbase = jnp.full(B, INT32_MAX, jnp.int32).at[dtgt].min(
            a_nom, mode="drop"
        )
        # non-miss lanes carry the sentinel segment: their rd is garbage
        # the where/drop masks below never let escape (same tolerance the
        # matmul path's full-table gather relied on)
        rd = segmented_rank(dtgt[:, None], n_seg=B, order=ord_c)[:, 0]
        dstart = jnp.maximum(
            a_nom,
            jnp.maximum(st.dram_free[bank], dbase[bank]) + rd * svc_d,
        )
        extra_dram = jnp.where(miss_dram, dstart - a_nom, 0)
        dram_free_n = st.dram_free.at[dtgt].max(dstart + svc_d, mode="drop")
        cnt = cadd(cnt, "dram_queue_cycles", extra_dram)
    else:
        extra_dram = jnp.zeros(C, jnp.int32)
        dram_free_n = st.dram_free

    # --- latency composition (golden order)
    probe_any = gets_probe | write_probe
    # service interval between the request's arrival at the home bank and
    # the reply's injection: LLC lookup + probe legs + invalidation waits
    # + controller queueing + DRAM (memory lanes), plain LLC lookup
    # (joins, lock/unlock RMWs)
    dram_term = jnp.where(miss_dram, kn.dram_lat, 0)
    if cfg.prefetcher != "none":
        # prefetch-covered misses pay the (traced) buffer latency instead
        dram_term = dram_term + jnp.where(pf_hit, kn.prefetch_lat, 0)
    service = jnp.where(
        winner,
        llc_lat
        + jnp.where(probe_any, 2 * po_lat, 0)
        + jnp.where(write_w & llc_hit, inv_lat, 0)
        + dram_term
        + extra_dram,
        llc_lat,
    )
    link_free_n = st.link_free
    if router:
        # ---- hop-by-hop router (golden _route/_route_rt, vectorized) ----
        # Model: every directed link keeps a next-free clock carried
        # across steps; a packet waits at link l for
        #   max(link_free[l], base[l]) + rank_l * link_lat
        # (base = the link's earliest NOMINAL same-step arrival, rank =
        # packets on l with smaller (clock, core) key — FIFO
        # serialization at link_lat per packet), then occupies the link
        # for link_lat and pays router_lat at the next router; waits
        # cascade into later hops. The cascade has a closed form: with
        # F_k the wait floor at hop k and c = link_lat + router_lat,
        #   t_k = max(t0 + router_lat, cummax_{k'<=k}(F_k' - k'c)) + kc
        # so one cummax per path replaces the sequential walk, and the
        # per-link departures feed one scatter-max into link_free. Ranks
        # come from the shared sort-based segmented-rank primitive
        # (ops/ranking.py, DESIGN.md §13): O(E log E) over the flattened
        # (link, key) entries instead of the historical O(C²·NL) one-hot
        # matmul, integer-equal by construction. Bit-exact vs the golden
        # scalar walk (tests/test_router.py).
        from ..noc.mesh import n_links

        NL = n_links(cfg)
        L_lat = kn.link_lat
        R_lat = kn.router_lat
        c_hop = kn.link_lat + kn.router_lat
        SENT = jnp.int32(-(1 << 30) - (1 << 21))  # < any real wait floor
        req_p = _path_links(cfg, ctile, btile)  # [C, H]
        rep_p = _path_links(cfg, btile, ctile)
        arr_p = _path_links(cfg, ctile, htile)
        H = req_p.shape[1]
        hidx = jnp.arange(H, dtype=jnp.int32)[None, :]
        first_lock = is_lock & (st.sync_flag == 0)
        mem_lane = winner | join
        pre_chg = mem_lane | is_unlock | first_lock | is_barrier
        t0 = (
            cycles_c
            + jnp.where(pre_chg, epre * cpi_vec, 0)
            + jnp.where(mem_lane, l1_lat, 0)
        )
        # nominal (uncontended) arrival at each hop; reply legs anchor
        # at llc.latency service by definition (golden _bump)
        a_req = t0[:, None] + R_lat + hidx * c_hop
        a_rep = (
            t0[:, None]
            + R_lat
            + req_hops[:, None] * c_hop
            + llc_lat
            + R_lat
            + hidx * c_hop
        )
        # EVERY per-link operation runs once over the concatenated paths
        # ([C, 2H] legs, or [C, 3H] with the barrier-arrival leg): one
        # segmented rank, one base scatter-min, one link_free/base gather
        # pair — per-kernel overhead is the budget, so per-path loops are
        # per-path kernels. The per-(lane, segment) uniqueness contract
        # of segmented_rank holds by construction: request and reply
        # legs traverse reversed DIRECTED links (distinct ids), and the
        # barrier-arrival leg is masked to barrier lanes, disjoint from
        # home-transaction lanes.
        pth_all, mask_all = _concat_legs(
            [(req_p, home_txn), (rep_p, home_txn)]
            + ([(arr_p, is_barrier)] if has_sync else [])
        )
        a_all = jnp.concatenate(
            [a_req, a_rep] + ([a_req] if has_sync else []), axis=1
        )
        ok_all = mask_all & (pth_all >= 0)
        tgt_all = jnp.where(ok_all, pth_all, NL)
        base = jnp.full(NL, INT32_MAX, jnp.int32).at[tgt_all].min(
            a_all, mode="drop"
        )
        # packets ahead of lane i in each hop's same-step FIFO, ordered
        # by the phase-2 arbitration key (masked slots carry garbage the
        # SENT select below discards, as the matmul table gather did)
        r_all = segmented_rank(tgt_all, n_seg=NL, order=ord_c)
        pc_all = jnp.where(pth_all >= 0, pth_all, 0)
        lf_g = st.link_free[pc_all]  # [C, legs*H] per-hop gather pair —
        bs_g = base[pc_all]  # data-dependent rows, staged in XLA (§13)
        arr_lat_a, arr_hops = _one_way(ctile, htile, cfg, kn)
        if pallas_step:
            # [PALLAS] wait floors + per-leg cummax cascades + departure
            # composition fused in one VMEM kernel (router_kernels.py);
            # the link_free/base row gathers above and the departure
            # scatter-max below stay XLA — the one access shape the
            # block model cannot express (same boundary as the commit
            # kernel's dirm row scatter)
            from ..kernels.router_kernels import router_cascade

            t_rep_end, t_arr_end, d_all = router_cascade(
                lf_g, bs_g, r_all, ok_all, t0, service, req_hops,
                rep_hops, arr_hops, L_lat, R_lat, has_sync=has_sync,
            )
        else:
            F_all = jnp.where(
                ok_all, jnp.maximum(lf_g, bs_g) + r_all * L_lat, SENT
            )  # [C, legs*H] wait floors

            def _cascade(t_start, F, nh):
                G = F - hidx * c_hop
                cum = jax.lax.cummax(G, axis=1)
                t1 = t_start + R_lat
                t_end = jnp.maximum(t1, cum[:, -1]) + nh * c_hop
                departs = (
                    jnp.maximum(t1[:, None], cum) + hidx * c_hop + L_lat
                )
                return t_end, departs

            t_req_end, d_req = _cascade(t0, F_all[:, :H], req_hops)
            t_rep_end, d_rep = _cascade(
                t_req_end + service, F_all[:, H : 2 * H], rep_hops
            )
            deps = [d_req, d_rep]
            if has_sync:
                t_arr_end, d_arr = _cascade(t0, F_all[:, 2 * H :], arr_hops)
                deps.append(d_arr)
            d_all = jnp.concatenate(deps, axis=1)
        raw_rt = t_rep_end - t0  # valid on home_txn lanes
        extra_home = raw_rt - (req_lat + service + rep_lat)
        if has_sync:
            raw_arr = t_arr_end - t0  # valid on barrier lanes
            extra_bar = raw_arr - arr_lat_a
        link_free_n = st.link_free.at[tgt_all].max(d_all, mode="drop")
        cnt = cadd(
            cnt,
            "noc_contention_cycles",
            jnp.where(home_txn, extra_home, 0)
            + (jnp.where(is_barrier, extra_bar, 0) if has_sync else 0),
        )
        lat = l1_lat + raw_rt  # memory lanes (service included)
        lat_join = lat
    else:
        lat = l1_lat + req_lat + service + rep_lat + extra_home
        # join path: same shape — service is llc.latency on join lanes
        lat_join = (
            l1_lat + req_lat + llc_lat + rep_lat + extra_home
        )
    if cfg.faults_enabled:
        # detour/degrade extras of the request+reply legs join the
        # composed round trip here (see the leg computation above); the
        # hop counts bump with their detours for the counter fold and the
        # phase-2.7 lock legs, now that the router walk is done with the
        # nominal values
        lat = lat + flt_rt
        lat_join = lat_join + flt_rt
        req_hops = req_hops + fh_req
        rep_hops = rep_hops + fh_rep
    ov = cfg.core.o3_overlap_256
    if ov:
        lat = lat - ((lat * ov) >> 8)
        lat_join = lat_join - ((lat_join * ov) >> 8)

    # --- granted L1 state (joins always take S)
    grant = jnp.where(
        join,
        S,
        jnp.where(
            write_w,
            M,
            jnp.where(gets_probe | gets_shared, S, E),  # GETS: E on excl/miss
        ),
    )

    # ---- counters for winners + joins -----------------------------------
    cnt = cadd(cnt, "l1_read_misses", gets_w | join)
    cnt = cadd(cnt, "l1_write_misses", getm & winner)
    cnt = cadd(cnt, "upgrades", upg & winner)
    cnt = cadd(cnt, "llc_hits", llc_hit | join)
    cnt = cadd(cnt, "llc_misses", llc_miss)
    cnt = cadd(cnt, "dram_accesses", llc_miss)
    cnt = cadd(cnt, "llc_writebacks", llc_miss & vic_valid & (vic_owner >= 0))
    cnt = cadd(cnt, "probes", probe_any)
    cnt = cadd(cnt, "invalidations", jnp.where(write_w & llc_hit, inv_count, 0) + back_count)
    noc_msgs = (
        jnp.where(winner | join, 2, 0)  # request + reply
        + jnp.where(probe_any, 2, 0)
        + jnp.where(write_w & llc_hit, 2 * inv_count, 0)
        + jnp.where(llc_miss, 2, 0)  # DRAM (co-located controller)
        + 2 * back_count
    )
    noc_hops = (
        jnp.where(winner | join, req_hops + rep_hops, 0)
        + jnp.where(probe_any, 2 * po_hops, 0)
        + jnp.where(write_w & llc_hit, inv_hops, 0)
        + back_hops
    )
    cnt = cadd(cnt, "noc_msgs", noc_msgs)
    cnt = cadd(cnt, "noc_hops", noc_hops)
    if cfg.faults_enabled:
        # rerouted messages: one-way legs whose XY path crossed a dead
        # link (invalidation fan-outs keep their analytic group/pair
        # latencies — model scope, like the router walk's)
        cnt = cadd(
            cnt,
            "noc_reroutes",
            jnp.where(winner | join, rr_req + rr_rep, 0)
            + jnp.where(probe_any, 2 * rr_po, 0),
        )

    # ---- phase 4.A: local updates ----------------------------------------
    # retire + clock advance (memory events also charge their pre-batched
    # non-memory instructions: epre * cpi, PriME per-BBL batching)
    hit = read_hit | write_hit
    cnt = cadd(cnt, "l1_read_hits", read_hit)
    cnt = cadd(cnt, "l1_write_hits", write_hit)
    retired = is_ins | hit | winner | join
    mem_ret = hit | winner | join
    mem_lat = jnp.where(
        hit, l1_lat, jnp.where(join, lat_join, lat)
    )
    cycles = cycles_c + jnp.where(
        is_ins,
        earg * cpi_vec,
        jnp.where(mem_ret, epre * cpi_vec + mem_lat, 0),
    )
    ptr = ptr_c + retired.astype(jnp.int32)
    cnt = cadd(
        cnt,
        "instructions",
        jnp.where(is_ins, earg, 0) + jnp.where(mem_ret, epre + 1, 0),
    )

    if pallas_step:
        # [PALLAS] fused commit (DESIGN.md §11): victim choice and the
        # writeback counter stay in-register here (they feed cadd), and
        # the join-LRU representative scatter-min keeps its tiny XLA
        # table, but EVERY array write of phase 4.A — the 7 + 2*rl L1
        # plane writes, the directory row delta, and the stacked counter
        # fold — is deferred into ONE commit_step kernel call at the end
        # of the step (after phase 2.7 contributes its counter deltas).
        upg_in_place = upg & winner  # upg requires an L1 hit: in-place
        fill = (winner & ~upg_in_place) | join
        l1_vkey = jnp.where(weff == I, -1, lru_rows)
        l1_vway = jnp.argmin(l1_vkey, axis=1).astype(jnp.int32)
        cnt = cadd(
            cnt, "l1_writebacks", fill & (weff[arange_c, l1_vway] == M)
        )
        takes_own = write_w | gets_excl_hit | llc_miss
        st_val_m = jnp.where(write_hit, M, grant)
        jsw = jnp.where(join, slot * W2 + llc_hway, B * S2 * W2)
        jtab = jnp.full(B * S2 * W2, INT32_MAX, jnp.int32).at[jsw].min(
            key, mode="drop"
        )
        jrep = join & (
            jtab[jnp.minimum(slot * W2 + llc_hway, B * S2 * W2 - 1)] == key
        )
        upd_slot = jnp.where(winner | join, slot, B * S2)
        commit_lanes = jnp.stack(
            [
                line,
                hit_way,
                l1_vway,
                hit.astype(jnp.int32),
                write_hit.astype(jnp.int32),
                upg_in_place.astype(jnp.int32),
                winner.astype(jnp.int32),
                join.astype(jnp.int32),
                llc_hit.astype(jnp.int32),
                st_val_m,
                slot,
                llc_hway,
                llc_vway,
                jrep.astype(jnp.int32),
                takes_own.astype(jnp.int32),
                gets_probe.astype(jnp.int32),
                gets_shared.astype(jnp.int32),
                oclamp,
            ],
            axis=1,
        )  # column order = kernels.step_kernels CL_* indices
    else:
        # L1-side updates touch at most TWO (row, column) slots per core — the
        # retired way, and (for fills) a stale duplicate of the filled tag —
        # so each is a [C]-element scatter into the [C, W1*S1] arrays, not a
        # full-array one-hot select (which rewrites 4x8MB per step at 1024
        # cores). Rows are the core's own, columns flat way*S1 + set; masked
        # lanes scatter to dropped row C.

        # winner L1 update: UPG-in-place vs fill. Victim preference counts
        # directory-invalidated (stale) ways as free, matching eager-MESI's
        # invalid-first rule; the victim writeback fires only on EFFECTIVE M.
        upg_in_place = upg & winner  # upg requires an L1 hit: always in-place
        fill = (winner & ~upg_in_place) | join
        l1_vkey = jnp.where(weff == I, -1, lru_rows)  # lru_rows from the probe
        l1_vway = jnp.argmin(l1_vkey, axis=1).astype(jnp.int32)
        cnt = cadd(cnt, "l1_writebacks", fill & (weff[arange_c, l1_vway] == M))
        upd_way = jnp.where(upg_in_place, hit_way, l1_vway)
        hit_col = hit_way * S1 + l1s
        upd_col = upd_way * S1 + l1s

        # a fill may duplicate a stale way's tag: clear the stale copy so tags
        # stay unique per set (else the refill could "resurrect" it, since the
        # directory once again records this core for the line); uniqueness also
        # means at most one duplicate way exists
        tagm = tag_rows == line[:, None]  # [C, W1], any state
        t_way = jnp.argmax(tagm, axis=1).astype(jnp.int32)
        dup = fill & jnp.any(tagm, axis=1) & (t_way != upd_way)
        dup_row = jnp.where(dup, arange_c, C)
        dup_col = t_way * S1 + l1s

        wj = winner | join
        lru_row = jnp.where(hit | wj, arange_c, C)
        lru_col = jnp.where(hit, hit_col, upd_col)
        st_row = jnp.where(write_hit | wj, arange_c, C)  # silent E->M + grants
        st_col = jnp.where(write_hit, hit_col, upd_col)
        st_val = jnp.where(write_hit, M, grant)
        wj_row = jnp.where(wj, arange_c, C)
        # the filled line's directory entry position (way pointer); joins and
        # LLC hits fill at the line's hit way, misses at the victim
        fill_ptr = slot * W2 + jnp.where(join | llc_hit, llc_hway, llc_vway)
        # invalidation epoch: every sharer-CLEARING transition (M grants,
        # exclusive grants, fills — exactly the owner-taking ones) bumps the
        # entry's epoch so coarse-vector validation can reject pre-clearing
        # fill records (GETS probe/shared grants preserve sharers: no bump);
        # fills record the POST-bump value
        llc_uway = jnp.where(llc_hit, llc_hway, llc_vway)
        takes_own = write_w | gets_excl_hit | llc_miss
        eph_rows2 = meta_rows[:, 3 * W2 : 4 * W2]  # [C, W2]
        eph_way = jnp.where(join, llc_hway, llc_uway)
        new_eph = eph_rows2[arange_c, eph_way] + takes_own.astype(jnp.int32)
        # ALL of this step's L1 writes — the seven phase-4 columns AND the
        # local run's deferred LRU/E->M writes — in ONE scatter on the fused
        # plane array (per-kernel overhead dominates, and a second scatter
        # chained on the same array cannot alias its operand). Targets are
        # pairwise distinct up to benign identical-value duplicates:
        # dup_col != upd_col (a duplicate is a different way than the fill
        # target), hit refresh and grant rows are disjoint lane classes, each
        # write addresses its own plane, run-LRU duplicates of phase-4 LRU
        # writes carry the identical step stamp, and a run E->M colliding
        # with a phase-4 state write at the same way is SUPPRESSED (phase 4
        # wrote after the run in the serialized order, so its value wins).
        l1_rows = [dup_row, dup_row, lru_row, st_row, wj_row, wj_row, wj_row]
        l1_cols = [
            dup_col,  # stale duplicate tag clear
            dup_col + FS,  # stale duplicate state clear
            lru_col + 2 * FS,  # hit refresh / fill LRU stamp
            st_col + FS,  # silent E->M + grant state
            upd_col,  # fill tag
            upd_col + 3 * FS,  # fill way pointer
            upd_col + 4 * FS,  # fill-time entry epoch (post-bump)
        ]
        l1_vals = [
            jnp.full(C, -1, jnp.int32),
            jnp.full(C, I, jnp.int32),
            jnp.broadcast_to(step_no, (C,)),
            st_val,
            line,
            fill_ptr,
            new_eph,
        ]
        rows_mat = jnp.stack(l1_rows, axis=1)
        cols_mat = jnp.stack(l1_cols, axis=1)
        vals_mat = jnp.stack(l1_vals, axis=1)
        if rl:
            own_state_write = (st_row == arange_c)
            run_m_sup = wm & ~(own_state_write[:, None] & (st_col[:, None] == cm))
            rows_mat = jnp.concatenate(
                [
                    rows_mat,
                    jnp.where(hm, arange_c[:, None], C),
                    jnp.where(run_m_sup, arange_c[:, None], C),
                ],
                axis=1,
            )
            cols_mat = jnp.concatenate(
                [cols_mat, cm + 2 * FS, cm + FS], axis=1
            )
            vals_mat = jnp.concatenate(
                [
                    vals_mat,
                    jnp.broadcast_to(step_no, (C, rl)),
                    jnp.full((C, rl), M, jnp.int32),
                ],
                axis=1,
            )
        l1_n = l1_c.at[rows_mat, cols_mat].set(vals_mat, mode="drop")

        # Directory update: ONE full-row scatter-ADD covers the winner's
        # whole row — tags, owner, LRU, epoch, AND sharer words — plus every
        # join's sharer bit (winner and join slots are disjoint: join slots
        # never have a winner). Winner rows carry the exact full-row delta
        # (new - old; exactly one winner per slot, so old + delta == new,
        # wrap-safe in int32); join rows contribute only the joiner's own
        # bit, masked against the step-start word (self_word & ~shw) so a
        # silently-evicted re-joiner's stale bit cannot carry into the
        # adjacent bit — golden's _set_sharer is idempotent, the masked add
        # matches it; multiple joiners per slot add distinct bits. Join LRU
        # refreshes land in a second element scatter (same-slot joiners write
        # the identical step stamp).
        new_owner = jnp.where(takes_own, arange_c, -1)
        if cfg.coherence == "moesi":
            # dirty sharing: a GETS probe LEAVES the probed owner recorded
            # (its line derives to Owned — DESIGN.md §25) instead of
            # clearing it; every other non-owning transition still clears.
            new_owner = jnp.where(gets_probe, oclamp, new_owner)
        wayeq = jnp.arange(W2, dtype=jnp.int32)[None, :] == llc_uway[:, None]
        new_meta = jnp.concatenate(
            [
                jnp.stack(
                    [
                        jnp.where(wayeq, line[:, None], llc_tag_rows),
                        jnp.where(wayeq, new_owner[:, None], owner_rows),
                    ],
                    axis=-1,
                ).reshape(C, 2 * W2),
                jnp.where(wayeq, step_no, llc_lru_rows),
                jnp.where(wayeq, new_eph[:, None], eph_rows2),
                jnp.zeros((C, MW - 4 * W2), jnp.int32),
            ],
            axis=1,
        )

        # new sharer words [C, NW]
        self_word = (
            (jnp.arange(NW)[None, :] == word_idx[:, None]).astype(jnp.int32)
            << bit_idx[:, None]
        )  # bit(c) as packed words
        # the probed owner is re-recorded as a sharer unconditionally: the home
        # node cannot observe silent L1 evictions (golden does the same), and
        # this keeps the transition free of cross-core L1 reads — which under
        # core-axis sharding would all-gather the L1 arrays every step
        og_bit = oclamp >> logG  # owner's sharer-GROUP bit (identity at G=1)
        owner_word = jnp.where(
            jnp.arange(NW)[None, :] == (og_bit // 32)[:, None],
            jnp.int32(1) << (og_bit % 32)[:, None],
            0,
        )
        probe_word = self_word | owner_word
        if cfg.coherence == "moesi":
            # dirty sharing accumulates: existing sharers stay recorded
            # alongside requester + owner (shw == 0 here under mesi — any
            # owner-setting transition cleared it)
            probe_word = shw | probe_word
        new_shw = jnp.where(
            gets_probe[:, None],
            probe_word,
            jnp.where(
                gets_shared[:, None],
                shw | self_word,
                jnp.zeros_like(shw),  # M grants, E grants, misses: cleared
            ),
        )
        way_seg = (
            jnp.arange(W2 * NW, dtype=jnp.int32)[None, :] // NW == llc_uway[:, None]
        )
        old_flat = sh_rows.reshape(C, W2 * NW)
        new_sh_row = jnp.where(
            way_seg,
            jnp.broadcast_to(new_shw[:, None, :], (C, W2, NW)).reshape(C, W2 * NW),
            old_flat,
        )
        join_seg = (
            jnp.arange(W2 * NW, dtype=jnp.int32)[None, :] // NW == llc_hway[:, None]
        )
        join_word = self_word & ~shw  # carry-free when the bit is already set
        join_sh_row = jnp.where(
            join_seg,
            jnp.broadcast_to(join_word[:, None, :], (C, W2, NW)).reshape(C, W2 * NW),
            0,
        )
        # Join LRU refreshes ride the SAME scatter-add: adds only commute for
        # identical targets if exactly one lane carries the delta, so a
        # per-(slot, way) scatter-min on the (small, 16 MB) representative
        # table picks one joiner per joined way to add (step_no - old_lru);
        # same-way co-joiners add zero. A second element scatter chained
        # after the row-add was measured at ~5 ms/step (prof_bisect r5: any
        # read-modify-write scatter that cannot alias re-materializes the
        # 800 MB operand), so everything must go through the ONE add.
        jsw = jnp.where(join, slot * W2 + llc_hway, B * S2 * W2)
        jtab = jnp.full(B * S2 * W2, INT32_MAX, jnp.int32).at[jsw].min(
            key, mode="drop"
        )
        jrep = join & (
            jtab[jnp.minimum(slot * W2 + llc_hway, B * S2 * W2 - 1)] == key
        )
        old_lru_h = meta_rows[arange_c, 2 * W2 + llc_hway]
        lru_oh = (
            jnp.arange(MW, dtype=jnp.int32)[None, :]
            == (2 * W2 + llc_hway)[:, None]
        )
        join_meta = jnp.where(
            lru_oh, jnp.where(jrep, step_no - old_lru_h, 0)[:, None], 0
        )
        new_full = jnp.concatenate([new_meta, new_sh_row], axis=1)  # [C, DW]
        delta_row = jnp.where(
            winner[:, None],
            new_full - meta_rows,
            jnp.concatenate([join_meta, join_sh_row], axis=1),
        )
        upd_slot = jnp.where(winner | join, slot, B * S2)
        dirm_n = st.dirm.at[upd_slot].add(delta_row, mode="drop")

    # No phase 4.B: under pull-based coherence, the directory updates above
    # ARE the invalidations/downgrades — remote L1s re-derive their state on
    # their next access (phase 1 validation).

    # ---- phase 2.7: synchronization events (golden/sim.py phase 2.7) -----
    # Sync lanes (LOCK/UNLOCK/BARRIER) are disjoint from every memory lane
    # above (classification is by event type), so ordering after phase 4.A
    # is immaterial; WITHIN sync the canonical order is unlocks -> lock
    # grants -> barrier arrivals -> releases. `has_sync` is static: traces
    # without sync events (checked at ingest) skip this block entirely.
    lock_holder = st.lock_holder
    barrier_count = st.barrier_count
    barrier_time = st.barrier_time
    sync_flag = st.sync_flag
    if has_sync:
        L = cfg.lock_slots
        BS = cfg.barrier_slots
        # mutex address -> lock slot; its home is the line's home bank, so
        # the phase-3 core<->home-bank latencies/hops apply verbatim
        lslot = line & (L - 1)
        lreq_lat, lreq_hops = req_lat, req_hops
        lrep_lat, lrep_hops = rep_lat, rep_hops
        if router:
            # raw_rt already reflects this lane's per-class injection
            # time (pre charged on unlocks and first lock attempts only)
            lat_rt = raw_rt
        else:
            lat_rt = lreq_lat + llc_lat + lrep_lat + extra_home
        if cfg.faults_enabled:
            # lock/unlock RMWs ride the same core<->home-bank legs as the
            # memory path: same round-trip fault extra
            lat_rt = lat_rt + flt_rt

        # unlocks: every unlock is a charged RMW round trip to the lock's
        # home; the slot is released only if this core actually holds it
        cycles = cycles + jnp.where(is_unlock, epre * cpi_vec + lat_rt, 0)
        ptr = ptr + is_unlock.astype(jnp.int32)
        cnt = cadd(cnt, "instructions", jnp.where(is_unlock, epre + 1, 0))
        cnt = cadd(cnt, "noc_msgs", jnp.where(is_unlock, 2, 0))
        cnt = cadd(cnt, "noc_hops", jnp.where(is_unlock, lreq_hops + lrep_hops, 0))
        held = lock_holder[lslot] == arange_c
        lock_holder = lock_holder.at[
            jnp.where(is_unlock & held, lslot, L)
        ].set(-1, mode="drop")

        # lock grants: per-slot scatter-min arbitration on (cycles, core_id)
        # — the golden sort order, same key packing as the (bank,set) table
        # above (the same clock-window invariant covers it). Grant iff the
        # slot is free AFTER unlocks and this core holds the minimum key,
        # OR the core already holds the lock (re-acquire). At most one
        # grant per slot: free excludes re-acquire.
        rel_l = cycles_c - (quantum_end - Q)
        lkey = rel_l * C + arange_c
        ltable = jnp.full(L, INT32_MAX, jnp.int32)
        ltable = ltable.at[jnp.where(is_lock, lslot, L)].min(lkey, mode="drop")
        lwin = is_lock & (ltable[lslot] == lkey)
        holder1 = lock_holder[lslot]
        grant = is_lock & ((holder1 == arange_c) | ((holder1 == -1) & lwin))
        spin = is_lock & ~grant
        # every attempt (grant or spin) is a charged round trip; the pre
        # batch is charged only on the FIRST attempt (sync_flag still 0)
        first = is_lock & (st.sync_flag == 0)
        cycles = (
            cycles
            + jnp.where(first, epre * cpi_vec, 0)
            + jnp.where(is_lock, lat_rt, 0)
        )
        cnt = cadd(
            cnt,
            "instructions",
            jnp.where(first, epre, 0) + grant.astype(jnp.int32),
        )
        cnt = cadd(cnt, "lock_acquires", grant)
        cnt = cadd(cnt, "lock_spins", spin)
        cnt = cadd(cnt, "noc_msgs", jnp.where(is_lock, 2, 0))
        cnt = cadd(cnt, "noc_hops", jnp.where(is_lock, lreq_hops + lrep_hops, 0))
        if cfg.faults_enabled:
            cnt = cadd(
                cnt,
                "noc_reroutes",
                jnp.where(is_unlock | is_lock, rr_req + rr_rep, 0),
            )
        lock_holder = lock_holder.at[jnp.where(grant, lslot, L)].set(
            arange_c, mode="drop"
        )
        sync_flag = jnp.where(grant, 0, jnp.where(spin, 1, sync_flag))
        ptr = ptr + grant.astype(jnp.int32)

        # barrier arrivals: charge pre + the arrival message, freeze the
        # core, bump the slot's count and max-arrival clock (bid/htile
        # hoisted above the contention block)
        barr_lat, barr_hops = _one_way(ctile, htile, cfg, kn)
        wake_lat, wake_hops = _one_way(htile, ctile, cfg, kn)
        barr_charge = raw_arr if router else barr_lat + extra_bar
        if cfg.faults_enabled:
            # barrier arrival and wake-up legs detour like any message
            fx_arr, fh_arr, rr_arr = leg_fault_penalty(
                cfg, st.faults, kn, ctile, htile
            )
            fx_wk, fh_wk, rr_wk = leg_fault_penalty(
                cfg, st.faults, kn, htile, ctile
            )
            barr_charge = barr_charge + fx_arr
            barr_hops = barr_hops + fh_arr
            wake_lat = wake_lat + fx_wk
            wake_hops = wake_hops + fh_wk
        cycles = cycles + jnp.where(
            is_barrier, epre * cpi_vec + barr_charge, 0
        )
        cnt = cadd(cnt, "instructions", jnp.where(is_barrier, epre, 0))
        cnt = cadd(cnt, "barrier_waits", is_barrier)
        cnt = cadd(cnt, "noc_msgs", is_barrier)
        cnt = cadd(cnt, "noc_hops", jnp.where(is_barrier, barr_hops, 0))
        if cfg.faults_enabled:
            cnt = cadd(
                cnt, "noc_reroutes", jnp.where(is_barrier, rr_arr, 0)
            )
        sync_flag = jnp.where(is_barrier, 1, sync_flag)
        barrier_count = barrier_count.at[
            jnp.where(is_barrier, bid, BS)
        ].add(1, mode="drop")
        barrier_time = barrier_time.at[
            jnp.where(is_barrier, bid, BS)
        ].max(cycles, mode="drop")

        # releases: every waiter (frozen earlier or arrived this step) whose
        # slot count reached ITS participant count resumes at the slot's
        # max arrival clock + wake-up message. Waiters' ptr/event are
        # unchanged this step (frozen lanes retire nothing), so the phase-0.9
        # gather is still current for them.
        wait_m = (et == EV_BARRIER) & (sync_flag == 1)
        if cfg.faults_enabled:
            # fail-stop barrier relief (DESIGN.md §12): a dead core will
            # never arrive, so waiters must not require its arrival — the
            # barrier twin of the dead-holder lock release above. A dead
            # core ALREADY counted in a slot (it arrived, froze, then
            # died) still satisfies its own arrival, so it grants no
            # relief there. Like the lock idealization this is a recovery
            # semantics choice: exact for global barriers; a subset
            # barrier is relieved even by a dead non-participant (the
            # trace encodes participant COUNTS, not sets) — chaos mode
            # favors forward progress over subset fidelity.
            dead_counted = (
                jnp.zeros(BS, jnp.int32)
                .at[jnp.where(wait_m & deadb, bid, BS)]
                .add(1, mode="drop")
            )
            missing = jnp.sum(deadb.astype(jnp.int32)) - dead_counted[bid]
            released = wait_m & (barrier_count[bid] + missing >= earg)
        else:
            released = wait_m & (barrier_count[bid] >= earg)
        cycles = jnp.where(released, barrier_time[bid] + wake_lat, cycles)
        cnt = cadd(cnt, "instructions", released)
        cnt = cadd(cnt, "noc_msgs", released)
        cnt = cadd(cnt, "noc_hops", jnp.where(released, wake_hops, 0))
        if cfg.faults_enabled:
            cnt = cadd(
                cnt, "noc_reroutes", jnp.where(released, rr_wk, 0)
            )
        sync_flag = jnp.where(released, 0, sync_flag)
        ptr = ptr + released.astype(jnp.int32)
        nrel = (
            jnp.zeros(BS, jnp.int32)
            .at[jnp.where(released, bid, BS)]
            .add(1, mode="drop")
        )
        barrier_count = barrier_count - nrel
        drained = barrier_count <= 0
        barrier_count = jnp.where(drained, 0, barrier_count)
        barrier_time = jnp.where(drained, 0, barrier_time)

    if pallas_step:
        # [PALLAS] end-of-step fused commit: by now phase 2.7's sync
        # counters have joined the delta accumulator, so ONE kernel call
        # performs every deferred array write of the step — the
        # 7 + 2*rl-column L1 plane scatter, the per-core directory row
        # delta, and the full counter fold. The single data-dependent
        # row scatter the block model cannot express stays in XLA.
        from ..kernels.step_kernels import commit_step

        l1_n, delta_row, counters_final = commit_step(
            cfg, l1_c, meta_rows, tag_rows, shw, commit_lanes, arange_c,
            step_no, cnt, cstack(),
            *((hm, wm, cm) if rl else ()),
        )
        dirm_n = st.dirm.at[upd_slot].add(delta_row, mode="drop")
    else:
        counters_final = cflush(cnt)

    return MachineState(
        cycles=cycles,
        ptr=ptr,
        l1=l1_n,
        dirm=dirm_n,
        link_free=link_free_n,
        dram_free=dram_free_n,
        lock_holder=lock_holder,
        barrier_count=barrier_count,
        barrier_time=barrier_time,
        sync_flag=sync_flag,
        quantum_end=quantum_end,
        step=step_no + 1,
        pf_line=pf_line_n,
        pf_stride=pf_stride_n,
        pf_streak=pf_streak_n,
        counters=counters_final,
        knobs=kn,
        # post-injection fault state (phase -1 rebound `st`); faults-off
        # this is the untouched input pytree
        faults=st.faults,
    )


@functools.partial(
    jax.jit, static_argnums=(0, 1), static_argnames=("has_sync",)
)
def run_chunk(
    cfg: MachineConfig, n_steps: int, events, st: MachineState,
    has_sync: bool = True,
):
    """lax.scan over `n_steps` steps — the jitted hot loop."""

    def body(carry, _):
        return step(cfg, events, carry, has_sync=has_sync), None

    st, _ = jax.lax.scan(body, st, None, length=n_steps)
    return st


def _np(x) -> np.ndarray:
    """Fetch a device array to host NumPy, working under MULTI-HOST
    sharding too: a cross-process-sharded array is not fully addressable,
    so it is allgathered first (every process computes the same global
    result — SPMD — and every process's Engine then reports it)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def _device_done(events, st, arange_c, faults_enabled=False):
    T = events.shape[1]
    p = jnp.minimum(st.ptr, T - 1)
    done = events[arange_c, p, 0] == EV_END
    if faults_enabled:
        # a fail-stopped core never reaches its END marker; it is done by
        # decree, so a run with injected fail-stops still terminates
        done = done | (st.faults.core_dead != 0)
    return jnp.all(done)


def _drain_and_rebase(cfg, st, acc_lo, acc_hi, base_lo, base_hi, nd):
    """On-device housekeeping shared by run_loop and stream_loop: drain
    int32 step counters into (lo, hi) carry pairs (hi above 2^30), and
    rebase the epoch-relative clocks by a whole number of quanta — the
    minimum over `nd` (not-done) lanes — including occupied barrier
    slots' arrival clocks."""
    Q = st.knobs.quantum  # traced — the fleet rebases per element
    acc_lo = acc_lo + st.counters
    acc_hi = acc_hi + (acc_lo >> _ACC_BITS)
    acc_lo = acc_lo & ((1 << _ACC_BITS) - 1)
    st = st._replace(counters=jnp.zeros_like(st.counters))
    m = jnp.min(jnp.where(nd, st.cycles, INT32_MAX))
    delta = jnp.where(jnp.any(nd), (m // Q) * Q, 0)
    st = st._replace(
        cycles=st.cycles - delta,
        quantum_end=st.quantum_end - delta,
        barrier_time=jnp.where(
            st.barrier_count > 0, st.barrier_time - delta, st.barrier_time
        ),
        # router link clocks are epoch-relative too; the clamp floor is
        # unreachable by any wait comparison (rank*link_lat < 2^21 and
        # live clocks are >= 0 post-rebase), so clamping is observably
        # exact while preventing int32 underflow on long-idle links.
        # Only shifted when the router model is live — otherwise the
        # field stays identically zero on every rebase schedule.
        link_free=(
            jnp.maximum(st.link_free - delta, -(1 << 30))
            if cfg.noc.contention and cfg.noc.contention_model == "router"
            else st.link_free
        ),
        dram_free=(
            jnp.maximum(st.dram_free - delta, -(1 << 30))
            if cfg.dram_queue
            else st.dram_free
        ),
    )
    base_lo = base_lo + delta
    base_hi = base_hi + (base_lo >> _ACC_BITS)
    base_lo = base_lo & ((1 << _ACC_BITS) - 1)
    return st, acc_lo, acc_hi, base_lo, base_hi


@functools.partial(
    jax.jit, static_argnums=(0, 1), static_argnames=("has_sync",)
)
def run_loop(cfg: MachineConfig, chunk_steps: int, events, st: MachineState,
             max_chunks, has_sync: bool = True):
    """ONE dispatched device program for a whole simulation run.

    `lax.while_loop` over scan chunks; after each chunk, ON DEVICE: drain
    int32 step counters into (lo, hi) int32 accumulator pairs (hi carries
    above 2^30, so per-chunk per-core increments must stay < 2^30), rebase
    the epoch-relative clocks by a multiple of the quantum (preserving
    barrier arithmetic) so int32 never overflows, and test termination.
    This replaces the reference's per-quantum MPI barrier + host polling
    (SURVEY.md §3.4) with zero host round-trips until the run completes.
    """
    C = cfg.n_cores
    T = events.shape[1]
    arange_c = jnp.arange(C, dtype=jnp.int32)

    def cond(carry):
        st, acc_lo, acc_hi, base_lo, base_hi, k = carry
        return (k < max_chunks) & ~_device_done(
            events, st, arange_c, cfg.faults_enabled
        )

    def body(carry):
        st, acc_lo, acc_hi, base_lo, base_hi, k = carry

        def sbody(c, _):
            return step(cfg, events, c, has_sync=has_sync), None

        st, _ = jax.lax.scan(sbody, st, None, length=chunk_steps)
        p = jnp.minimum(st.ptr, T - 1)
        nd = events[arange_c, p, 0] != EV_END
        if cfg.faults_enabled:
            # dead cores must not bound the rebase minimum: their frozen
            # clocks would pin delta at 0 forever (int32 overflow risk on
            # long post-fault runs)
            nd = nd & (st.faults.core_dead == 0)
        st, acc_lo, acc_hi, base_lo, base_hi = _drain_and_rebase(
            cfg, st, acc_lo, acc_hi, base_lo, base_hi, nd
        )
        return st, acc_lo, acc_hi, base_lo, base_hi, k + 1

    acc_lo = jnp.zeros_like(st.counters)
    acc_hi = jnp.zeros_like(st.counters)
    base_lo = jnp.asarray(0, jnp.int32)
    base_hi = jnp.asarray(0, jnp.int32)
    k = jnp.asarray(0, jnp.int32)
    return jax.lax.while_loop(
        cond, body, (st, acc_lo, acc_hi, base_lo, base_hi, k)
    )


@functools.partial(
    jax.jit, static_argnums=(0,), static_argnames=("has_sync",)
)
def stream_loop(cfg: MachineConfig, events, st: MachineState, exhausted,
                filled, max_steps, has_sync: bool = True):
    """Device loop for WINDOWED (streaming) ingest — SURVEY.md §2 #8's
    bounded-buffer hand-off: the events array holds only a window of each
    core's stream, END-padded; `exhausted[c]` marks cores with no events
    beyond their window and `filled[c]` counts the real events buffered.

    The while_loop cond runs EVERY step and exits while every live core
    still has at least local_run_len + 1 buffered events — the most one
    step can consume — so no step ever observes a window's fake END
    mid-run (which would truncate a local run or drop the core from an
    arbitration it would have joined with the full trace). Windowed
    simulation is therefore BIT-EXACT with the preloaded run, including
    LRU stamps (step_no advances only on executed steps). Counters drain
    and clocks rebase on-device every 64 steps, same arithmetic as
    run_loop.
    """
    C = cfg.n_cores
    T = events.shape[1]
    need = cfg.local_run_len + 1
    arange_c = jnp.arange(C, dtype=jnp.int32)

    def at_end(s):
        p = jnp.minimum(s.ptr, T - 1)
        done = events[arange_c, p, 0] == EV_END
        if cfg.faults_enabled:
            # defensive only — the CLI rejects streaming + faults (the
            # window prefetcher cannot know a core died mid-window), but
            # the device loop must still terminate if reached directly
            done = done | (s.faults.core_dead != 0)
        return done

    def cond(carry):
        st, acc_lo, acc_hi, base_lo, base_hi, k = carry
        # a live lane running low on buffered events hands back to the
        # host BEFORE a step could touch the window boundary
        low = jnp.any(~exhausted & (filled - st.ptr < need))
        return (k < max_steps) & ~low & ~jnp.all(at_end(st))

    def body(carry):
        st, acc_lo, acc_hi, base_lo, base_hi, k = carry
        st = step(cfg, events, st, has_sync=has_sync)
        # not-done for the rebase: a core at its window's fake END padding
        # (ptr past `filled` but the stream continues, ~exhausted) is LIVE —
        # it must still bound the rebase minimum, else the uniform shift
        # could push its epoch-relative clock negative (violating the clock
        # invariant even though results stay bit-exact under uniform shifts)
        st, acc_lo, acc_hi, base_lo, base_hi = jax.lax.cond(
            (k & 63) == 63,
            lambda args: _drain_and_rebase(
                cfg, *args, ~(at_end(args[0]) & exhausted)
            ),
            lambda args: args,
            (st, acc_lo, acc_hi, base_lo, base_hi),
        )
        return st, acc_lo, acc_hi, base_lo, base_hi, k + 1

    acc_lo = jnp.zeros_like(st.counters)
    acc_hi = jnp.zeros_like(st.counters)
    base_lo = jnp.asarray(0, jnp.int32)
    base_hi = jnp.asarray(0, jnp.int32)
    k = jnp.asarray(0, jnp.int32)
    return jax.lax.while_loop(
        cond, body, (st, acc_lo, acc_hi, base_lo, base_hi, k)
    )


class Engine:
    """Host runner (SURVEY.md §2 #8 UncoreManager equivalent).

    `run()` dispatches the whole simulation as ONE device program
    (`run_loop`) and makes a single synchronizing host transfer at the end —
    per-dispatch latency through remote-TPU tunnels is tens of ms, so chunked
    host loops (`run_chunked`, kept for debugging/inspection) are wall-clock
    poison. Between-chunk bookkeeping (counter drain to 64-bit, quantum
    rebase of the int32 clocks, termination) happens on device either way.
    """

    def __init__(
        self,
        cfg: MachineConfig,
        trace: Trace,
        chunk_steps: int = 256,
        mesh=None,
    ):
        assert trace.n_cores == cfg.n_cores
        self.cfg = cfg
        self.trace = trace
        # static specialization: traces without sync events skip phase 2.7
        from ..trace.format import validate_sync

        validate_sync(trace, cfg.barrier_slots)
        t = trace.events[:, :, 0]
        self.has_sync = bool(
            ((t == EV_LOCK) | (t == EV_UNLOCK) | (t == EV_BARRIER)).any()
        )
        self.events = jnp.asarray(trace.line_events(cfg.line_bits))
        self.state = init_state(cfg)
        self.mesh = mesh
        if mesh is not None:
            # multi-chip: lay cores/banks out over the tile axis (parallel/)
            from ..parallel.sharding import shard_events, shard_state

            self.events = shard_events(mesh, self.events)
            self.state = shard_state(mesh, self.state)
        self.chunk_steps = chunk_steps
        # Counter-accumulator guard (run_loop drains int32 step counters
        # into (lo, hi) pairs whose hi carries above 2^30): any per-core
        # counter's per-CHUNK increment must stay < 2^30. The largest
        # per-step increment is the instructions counter, bounded by
        # (local_run_len + 1) events each retiring at most max(arg, pre+1)
        # instructions.
        ev = trace.events
        per_ev = max(
            1,
            int(ev[:, :, 1].max(initial=0)),
            int(ev[:, :, 3].max(initial=0)) + 1,
        )
        per_step = (cfg.local_run_len + 1) * per_ev
        if chunk_steps * per_step >= 1 << _ACC_BITS:
            raise ValueError(
                f"chunk_steps={chunk_steps} x max per-step instruction "
                f"increment {per_step} overflows the 2^{_ACC_BITS} "
                "per-chunk counter accumulator; lower chunk_steps or split "
                "large INS batches"
            )
        self.cycle_base = np.int64(0)
        self.host_counters = zero_counters(cfg.n_cores)
        self.steps_run = 0
        # telemetry sink (obs.Recorder) — None means every telemetry
        # branch in the chunked loops is skipped; the fused run() never
        # consults it at all (DESIGN.md §15 overhead contract)
        self.obs = None
        self.obs_label = "engine"
        # attestation chain (attest.SoloAttest) — None means the chunked
        # loop never fingerprints; like obs, the fused run() never
        # consults it (DESIGN.md §24: --attest off is bit-exact by
        # construction)
        self.attest = None
        # prefix-fork provenance (checkpoint format v6): nonzero when this
        # engine's state was seeded from a shared-prefix / warm-cache
        # snapshot rather than run from step 0
        self.prefix_steps = 0
        self.prefix_cache_key = None
        # overlapped chunk dispatch (§23): when True, run_steps enqueues
        # chunk k+1 from the just-committed state before returning, so the
        # caller's host-side durability work (journal fsync, checkpoint
        # write, obs commit) runs concurrently with device compute.
        # _pending holds (source_state, dispatched_result, chunk_steps);
        # validity is the OBJECT IDENTITY of source_state — any rollback,
        # checkpoint load or restore reassigns self.state and thereby
        # invalidates the speculation automatically.
        self.overlap = False
        self._pending = None

    def _drain(self) -> None:
        cnt = _np(self.state.counters)
        for i, k in enumerate(COUNTER_NAMES):
            self.host_counters[k] += cnt[i].astype(np.int64)
        self.state = self.state._replace(
            counters=jnp.zeros_like(self.state.counters)
        )

    def _event_types_at_ptr(self) -> np.ndarray:
        p = np.minimum(_np(self.state.ptr), self.trace.max_len - 1)
        return self.trace.events[np.arange(self.cfg.n_cores), p, 0]

    def _dead_mask(self) -> np.ndarray:
        """[C] bool — fail-stopped cores (all-False with faults off)."""
        if self.cfg.faults_enabled:
            return _np(self.state.faults.core_dead) != 0
        return np.zeros(self.cfg.n_cores, bool)

    def _rebase(self) -> None:
        cyc = _np(self.state.cycles)
        nd = (self._event_types_at_ptr() != EV_END) & ~self._dead_mask()
        if not nd.any():
            return
        delta = (int(cyc[nd].min()) // self.cfg.quantum) * self.cfg.quantum
        if delta <= 0:
            return
        self.cycle_base += delta
        self.state = self.state._replace(
            cycles=self.state.cycles - np.int32(delta),
            quantum_end=self.state.quantum_end - np.int32(delta),
            # occupied barrier slots hold epoch-relative arrival clocks
            barrier_time=jnp.where(
                self.state.barrier_count > 0,
                self.state.barrier_time - np.int32(delta),
                self.state.barrier_time,
            ),
            link_free=(
                jnp.maximum(self.state.link_free - np.int32(delta), -(1 << 30))
                if self.cfg.noc.contention
                and self.cfg.noc.contention_model == "router"
                else self.state.link_free
            ),
            dram_free=(
                jnp.maximum(self.state.dram_free - np.int32(delta), -(1 << 30))
                if self.cfg.dram_queue
                else self.state.dram_free
            ),
        )

    def done(self) -> bool:
        return bool(self.done_mask().all())

    def done_mask(self) -> np.ndarray:
        """[C] bool — cores whose trace pointer sits on END, plus fail-
        stopped cores (dead by injected fault — they will never reach
        END, so completion means 'everyone else finished')."""
        return (self._event_types_at_ptr() == EV_END) | self._dead_mask()

    def live_mask(self) -> np.ndarray:
        """[C] bool — cores that bound the quantum window: not at END,
        not frozen at a barrier (a frozen core's clock legally lags
        `quantum_end` until release, mirroring the `countable` mask in
        step() phase 0), and not fail-stopped by an injected fault (a
        dead core's clock freezes at its death step). Input to the
        supervisor's clock-window guard (validate.check_chunk_invariants)
        — this exclusion is what keeps `--guard=fail` from false-
        positiving on intentionally injected faults."""
        et = self._event_types_at_ptr()
        frozen = (et == EV_BARRIER) & (_np(self.state.sync_flag) != 0)
        return (et != EV_END) & ~frozen & ~self._dead_mask()

    def run(self, max_steps: int = 10_000_000) -> None:
        """Run to completion in ONE device dispatch (preferred path).

        `max_steps` is a deadlock guard, rounded UP to a whole number of
        `chunk_steps` chunks (the device loop cannot stop mid-chunk): up
        to chunk_steps-1 extra steps may execute before the guard trips.
        """
        max_chunks = -(-max_steps // self.chunk_steps)
        st, acc_lo, acc_hi, base_lo, base_hi, k = exec_cache.call(
            run_loop, "engine.run_loop",
            (self.cfg, self.chunk_steps),
            (self.events, self.state, jnp.asarray(max_chunks, jnp.int32)),
            {"has_sync": self.has_sync},
        )
        # one synchronizing transfer for everything the host needs
        acc_lo = _np(acc_lo).astype(np.int64)
        acc_hi = _np(acc_hi).astype(np.int64)
        total = (acc_hi << _ACC_BITS) + acc_lo
        for i, name in enumerate(COUNTER_NAMES):
            self.host_counters[name] += total[i]
        self.cycle_base += (np.int64(np.asarray(base_hi)) << _ACC_BITS) + np.int64(
            np.asarray(base_lo)
        )
        self.state = st
        self.steps_run += int(np.asarray(k)) * self.chunk_steps
        if not self.done():
            raise RuntimeError("engine: max_steps exceeded (deadlock?)")

    def run_chunked(
        self, max_steps: int = 10_000_000, debug_invariants: bool = False
    ) -> None:
        """Host-loop variant: one dispatch per chunk + host drain/rebase.

        Semantically identical to `run()`; kept for debugging (state is
        inspectable between chunks) and as the reference for the fused
        loop's on-device bookkeeping. `debug_invariants` checks the
        DESIGN.md §5 machine invariants after every chunk.
        """
        self.run_steps(max_steps - self.steps_run, debug_invariants)
        if not self.done():
            raise RuntimeError("engine: max_steps exceeded (deadlock?)")

    def run_steps(self, n_steps: int, debug_invariants: bool = False) -> None:
        """Advance exactly `n_steps` (rounded up to whole chunks) WITHOUT
        the completion check — the building block for checkpointed runs:
        run_steps(A) -> save_checkpoint -> (later) load_checkpoint ->
        run() is bit-exact with an uninterrupted run()."""
        target = self.steps_run + n_steps
        while self.steps_run < target and not self.done():
            if self.obs is None:
                self._dispatch_chunk()
                self.steps_run += self.chunk_steps
                self._drain()
                self._rebase()
                if self.overlap and not self.done():
                    self._prefetch_chunk()
            else:
                # phase cuts: dispatch is the async enqueue; drain's
                # host transfer synchronizes, so "drain" includes the
                # device executing the chunk; rebase is pure host work
                t0 = time.perf_counter()
                self._dispatch_chunk()
                t1 = time.perf_counter()
                self.steps_run += self.chunk_steps
                self._drain()
                t2 = time.perf_counter()
                self._rebase()
                t3 = time.perf_counter()
                phases = {"dispatch": t1 - t0, "drain": t2 - t1,
                          "rebase": t3 - t2}
                if self.overlap and not self.done():
                    self._prefetch_chunk()
                    phases["prefetch"] = time.perf_counter() - t3
                self.obs.chunk_committed(
                    self.obs_label, self.chunk_steps, t3 - t0,
                    self.host_counters, phases=phases,
                )
            if self.attest is not None:
                self.attest.observe(self)
            if debug_invariants:
                self.verify_invariants()

    def _dispatch_chunk(self) -> None:
        """Advance self.state by one chunk: consume the prefetched result
        when it was speculated from EXACTLY this state object at this
        chunk size, else dispatch now (through the exec cache when one is
        active)."""
        pend, self._pending = self._pending, None
        if (
            pend is not None
            and pend[0] is self.state
            and pend[2] == self.chunk_steps
        ):
            self.state = pend[1]
            return
        self.state = exec_cache.call(
            run_chunk, "engine.run_chunk",
            (self.cfg, self.chunk_steps), (self.events, self.state),
            {"has_sync": self.has_sync},
        )

    def _prefetch_chunk(self) -> None:
        """Overlap prong (§23): enqueue chunk k+1 from the committed
        state. JAX's async dispatch returns immediately; the device works
        while the host does durability. The result is NOT committed here
        — _dispatch_chunk adopts it only if the committed state is still
        the same object it was speculated from."""
        src = self.state
        nxt = exec_cache.call(
            run_chunk, "engine.run_chunk",
            (self.cfg, self.chunk_steps), (self.events, src),
            {"has_sync": self.has_sync},
        )
        self._pending = (src, nxt, self.chunk_steps)

    def discard_prefetch(self) -> None:
        """Drop any speculated chunk (state surgery makes it moot; the
        identity check would reject it anyway — this just frees it)."""
        self._pending = None

    def block_until_ready(self) -> None:
        """Synchronize the engine's async device uploads (events + the
        whole state pytree). Call before starting a wall-clock measurement:
        through a remote-TPU tunnel a lazy multi-MB transfer otherwise
        completes inside the first timed dispatch and is billed to
        simulation."""
        jax.block_until_ready(self.events)
        jax.block_until_ready(self.state)

    def verify_invariants(self) -> None:
        """Check the DESIGN.md §5 machine invariants on the current state
        (host-side; raises AssertionError naming the violation)."""
        from .validate import check_invariants

        check_invariants(self.cfg, self.state, done_mask=self.done_mask())

    # ---- checkpoint / resume (SURVEY.md §5.4) ----------------------------

    def save_checkpoint(self, path: str) -> None:
        from .checkpoint import save_checkpoint

        save_checkpoint(path, self)

    def load_checkpoint(self, path: str) -> None:
        from .checkpoint import load_checkpoint

        load_checkpoint(path, self)

    # ---- results ---------------------------------------------------------

    @property
    def cycles(self) -> np.ndarray:
        return _np(self.state.cycles).astype(np.int64) + self.cycle_base

    @property
    def counters(self) -> dict[str, np.ndarray]:
        self._drain()
        return self.host_counters
