"""Resilient execution layer — RunSupervisor (DESIGN.md §10).

PriME's value is long campaigns: thousand-core configs and parameter
sweeps that run for hours. At that scale the limiting factor is not peak
MIPS but surviving the failures the fleet WILL throw at a long run —
preemption (TPU pods are preemptible by default), device OOM on an
over-ambitious chunk size, transient runtime errors, corrupt input
traces in a thousand-element sweep, and torn checkpoint files from the
previous crash. `RunSupervisor` wraps any of the three engines (solo
`Engine`, windowed `StreamEngine`, batched `FleetEngine`) and drives it
chunk by committed chunk with:

- **rotating atomic snapshots** — `ckpt-<seq>.npz` files written through
  `checkpoint.atomic_save_npz` (tmp + fsync + `os.replace`, per-array
  CRC32 manifest); `resume()` walks them newest-first and falls back
  past any that raise `CheckpointCorrupt`, so one torn file never
  strands a run. Cadence: every K committed chunks and/or W
  wall-seconds.
- **preemption handling** — SIGTERM/SIGINT set a flag; at the next
  committed chunk boundary the supervisor checkpoints and raises
  `Preempted`. The engine's chunk boundary is already a consistent cut,
  so the resumed run is bit-exact with an uninterrupted one
  (tests/test_supervisor.py).
- **retry with exponential backoff + graceful degradation** — failures
  whose text carries a transient gRPC-style status (UNAVAILABLE,
  DEADLINE_EXCEEDED, ...) are retried with doubling backoff; OOM
  (RESOURCE_EXHAUSTED) first halves `chunk_steps` (chunking only
  changes the drain/rebase cadence, never results); after
  `max_retries` the supervisor tries moving the run to the CPU backend
  once before giving up. Every decision lands in the run log
  (`log_lines()`, rendered into the report).
- **post-chunk invariant guard** — `--guard=off|warn|fail` runs
  `validate.check_chunk_invariants` (MESI/directory consistency, clock
  window, monotone counters) on every committed chunk.
- **fleet fault isolation** — `build_fleet_isolated` validates every
  element (trace loadable, core count, overrides, barrier ids) BEFORE
  batching and quarantines bad ones with their typed error, so one
  malformed element costs one JSON line, not the whole sweep.
- **chaos mode** — when the wrapped config arms ARCHITECTURAL fault
  injection (primesim_tpu.faults, DESIGN.md §12) the supervisor logs
  the armed schedule and every fault-counter movement at chunk
  boundaries; snapshots carry the fault state (checkpoint format v5),
  so a chaos run preempted mid-fault resumes bit-exactly.
"""

from __future__ import annotations

import json
import os
import re
import signal
import sys
import time

import numpy as np

from ..chaos import sites as chaos_sites
from ..stats.counters import COUNTER_NAMES
from .checkpoint import CheckpointCorrupt
from .validate import check_chunk_invariants


class Preempted(RuntimeError):
    """A SIGTERM/SIGINT arrived mid-run; the supervisor committed the
    current chunk, wrote a snapshot (`.checkpoint`, None when no
    snapshot dir was configured), and stopped cleanly. Rerun with
    `--resume` to continue bit-exactly."""

    def __init__(self, message: str, checkpoint: str | None = None,
                 signum: int | None = None):
        super().__init__(message)
        self.checkpoint = checkpoint
        self.signum = signum


class GuardViolation(RuntimeError):
    """`--guard=fail`: a post-chunk invariant check failed. The run
    stopped BEFORE checkpointing the bad state — the newest snapshot
    predates the violation."""


# Failure classification is textual by design: the JAX runtime surfaces
# device errors as XlaRuntimeError (jaxlib version-dependent import
# path) whose message embeds the gRPC-style status name.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "INTERNAL",
    "CANCELLED",
    "failed to connect",
    "Socket closed",
    # typed admission backpressure from util/diskpressure — the window
    # heals; back off and retry rather than kill the run
    "DiskPressureError",
)
# a device dropping out of the mesh: the runtime's own phrasing on real
# hardware, the typed mesh validator, and the chaos-injected synthetic
_DEVICE_LOSS_MARKERS = (
    "DEVICE_LOST",
    "device lost",
    "Device lost",
    "device unhealthy",
    "DeviceMeshError",
    "chip unreachable",
    "heartbeat timeout on device",
)


def classify_failure(exc: BaseException) -> str | None:
    """'device_loss' | 'oom' | 'transient' | None (permanent) for an
    engine dispatch failure. Deliberate errors (ValueError config/trace
    mismatches, AssertionError invariants, KeyboardInterrupt) are never
    retried — but device loss is checked FIRST, because the typed
    DeviceMeshError a vanished mesh raises is a ValueError, and it is
    precisely the recoverable case the reshard ladder exists for."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return None
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _DEVICE_LOSS_MARKERS):
        return "device_loss"
    if isinstance(exc, (AssertionError, ValueError)):
        return None
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    if any(m in text for m in _TRANSIENT_MARKERS):
        return "transient"
    return None


class JobContext:
    """Per-JOB supervision context for the serving daemon (serve/):
    the retry-with-backoff policy RunSupervisor applies per chunk,
    re-scoped to one job's whole lifetime. The scheduler consults it
    whenever the job's element fails (batch dispatch error attributed to
    the job, admission failure, guard violation): `next_retry(exc)`
    returns the backoff delay in seconds for another attempt, or None
    when the job must move to a terminal state instead (permanent error,
    or the retry budget is spent). Attempts and every decision are
    recorded so the job's journal/terminal record carries the audit
    trail, mirroring RunSupervisor.log_lines()."""

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.5):
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.attempts = 0
        self.log: list[str] = []

    def next_retry(self, exc: BaseException) -> float | None:
        kind = classify_failure(exc)
        if kind is None:
            self.log.append(f"permanent: {type(exc).__name__}: {exc}")
            return None
        if self.attempts >= self.max_retries:
            self.log.append(
                f"give-up: {kind} failure persisted after "
                f"{self.max_retries} retries: {exc}"
            )
            return None
        self.attempts += 1
        delay = min(self.backoff_s * (2 ** (self.attempts - 1)), 30.0)
        self.log.append(
            f"retry {self.attempts}/{self.max_retries} after {kind} "
            f"failure ({exc}); backoff {delay:.2f}s"
        )
        return delay


_SNAP_RE = re.compile(r"ckpt-(\d{8})\.npz")


class SnapshotStore:
    """Rotating checkpoint directory: `ckpt-<seq:08d>.npz`, newest wins,
    oldest pruned past `keep`. Sequence numbers only grow (they restart
    from the newest surviving file on resume), so "latest" is a pure
    filename sort — no mtime trust."""

    def __init__(self, directory: str, keep: int = 3):
        self.dir = str(directory)
        self.keep = max(1, int(keep))
        os.makedirs(self.dir, exist_ok=True)
        # disk-pressure rung 1 (after caches, before backpressure):
        # rotated snapshots are droppable down to the newest one — the
        # resume anchor itself is never evicted
        from ..util import diskpressure

        diskpressure.register_evictor(
            f"snapshots:{self.dir}", self._evict_rotated, priority=1
        )

    def _evict_rotated(self, need_bytes: int) -> int:
        removed = 0
        for p in self.snapshots()[1:]:
            try:
                os.unlink(p)
                removed += 1
            except OSError:
                pass
        return removed

    def snapshots(self) -> list[str]:
        """Snapshot paths, newest (highest sequence) first."""
        found = []
        for name in os.listdir(self.dir):
            m = _SNAP_RE.fullmatch(name)
            if m:
                found.append((int(m.group(1)), os.path.join(self.dir, name)))
        return [p for _, p in sorted(found, reverse=True)]

    def save(self, save_fn) -> str:
        """Write the next snapshot via `save_fn(path)` (the engines'
        `save_checkpoint`, already atomic), then prune."""
        snaps = self.snapshots()
        seq = (
            int(_SNAP_RE.fullmatch(os.path.basename(snaps[0])).group(1)) + 1
            if snaps
            else 1
        )
        path = os.path.join(self.dir, f"ckpt-{seq:08d}.npz")
        save_fn(path)
        for p in self.snapshots()[self.keep:]:
            try:
                os.unlink(p)
            except OSError:
                pass
        return path


class RunSupervisor:
    """Drive an engine to completion chunk by chunk, surviving what the
    fused `run()` paths cannot (module docstring). The wrapped engine is
    advanced through its own public stepping surface (`run_steps` /
    `_advance_window`), so supervised results are bit-exact with
    unsupervised ones — supervision changes WHEN work is committed,
    never what is computed.

    `on_chunk(supervisor)` fires after every committed chunk, before the
    guard/preemption checks — the deterministic injection point the
    crash-recovery tests use (`os.kill` from the callback lands the
    signal at an exact chunk boundary)."""

    def __init__(
        self,
        engine,
        snapshot_dir: str | None = None,
        keep_snapshots: int = 3,
        checkpoint_every_chunks: int = 0,
        checkpoint_every_s: float = 0.0,
        guard: str = "off",
        max_retries: int = 4,
        backoff_s: float = 0.5,
        handle_signals: bool = True,
        on_chunk=None,
        obs=None,
    ):
        if guard not in ("off", "warn", "fail"):
            raise ValueError(f"guard must be off|warn|fail, got {guard!r}")
        self.engine = engine
        self.kind = (
            "stream"
            if hasattr(engine, "_advance_window")
            else "fleet" if hasattr(engine, "elem_cfgs") else "solo"
        )
        self.store = (
            SnapshotStore(snapshot_dir, keep_snapshots)
            if snapshot_dir
            else None
        )
        self.checkpoint_every_chunks = int(checkpoint_every_chunks)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self.guard = guard
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.handle_signals = handle_signals
        self.on_chunk = on_chunk
        # telemetry sink (obs.Recorder) — every supervision event that
        # lands in the RESILIENCE audit trail is mirrored onto the
        # flight recorder's "supervisor" timeline row
        self.obs = obs
        self.committed = 0  # chunks committed under this supervisor
        self.retries = 0
        self.guard_warnings = 0
        self.checkpoints_written = 0
        self.resumed_from: str | None = None
        self.stalled_elements: list[int] = []  # fleet: budget-exhausted
        self._events_log: list[tuple[float, str, str]] = []
        self._t0 = time.monotonic()
        self._preempt: int | None = None
        self._prev_handlers: dict = {}
        self._prev_totals: dict[str, int] | None = None
        self._cpu_fallback_done = False
        self._stream_finished = False
        # which device-loss ladder rungs fired, in order ("reshard:8->4",
        # "cpu-fallback") — surfaced in summary() and the RESILIENCE log
        self.degrade_rungs: list[str] = []
        # chaos mode (DESIGN.md §12): when the wrapped engine's config
        # arms fault injection, the supervisor narrates every fault the
        # machine absorbs into the RESILIENCE audit trail
        cfg = getattr(engine, "cfg", None)
        self._chaos = bool(getattr(cfg, "faults_enabled", False))
        self._fault_seen: dict[str, int] = {}

    # ---- logging --------------------------------------------------------

    def _log(self, kind: str, msg: str) -> None:
        self._events_log.append((time.monotonic() - self._t0, kind, msg))
        if self.obs is not None:
            self.obs.supervisor_event(kind, msg)

    def log_lines(self) -> list[str]:
        """Human-readable supervision log (rendered into the report)."""
        return [
            f"[+{t:7.1f}s] {kind}: {msg}" for t, kind, msg in self._events_log
        ]

    def summary(self) -> dict:
        return {
            "supervised": True,
            "committed_chunks": self.committed,
            "checkpoints_written": self.checkpoints_written,
            "resumed_from": self.resumed_from,
            "retries": self.retries,
            "guard": self.guard,
            "guard_warnings": self.guard_warnings,
            "stalled_elements": self.stalled_elements,
            "degrade_rungs": list(self.degrade_rungs),
        }

    # ---- snapshots ------------------------------------------------------

    def checkpoint(self) -> str | None:
        """Write the next rotating snapshot (None without a store).

        Disk pressure that survives the whole evict+compact ladder skips
        THIS rotation instead of killing the run — a wider resume window
        is strictly better than no run at all."""
        if self.store is None:
            return None
        from ..util.diskpressure import DiskPressureError

        try:
            path = self.store.save(self.engine.save_checkpoint)
        except DiskPressureError as e:
            self._log("disk-pressure", f"snapshot skipped: {e}")
            return None
        self.checkpoints_written += 1
        self._log("checkpoint", os.path.basename(path))
        return path

    def resume(self) -> str | None:
        """Restore the newest VALID snapshot into the engine.

        Corrupt snapshots (torn write, failed CRC) are skipped with a
        log entry and the next-newest is tried; config/trace mismatches
        are real errors and propagate (resuming the wrong run silently
        is worse than dying). Returns the restored path, or None when
        the directory holds no snapshots (fresh start)."""
        if self.store is None:
            raise ValueError("resume() requires a snapshot_dir")
        snaps = self.store.snapshots()
        if not snaps:
            self._log("resume", "no snapshots found; starting fresh")
            return None
        for path in snaps:
            try:
                self.engine.load_checkpoint(path)
            except CheckpointCorrupt as e:
                self._log(
                    "resume-skip",
                    f"{os.path.basename(path)} invalid, trying older ({e})",
                )
                continue
            self.resumed_from = path
            self._log("resume", f"resumed from {os.path.basename(path)}")
            # a forked run's snapshot is self-describing (format v6):
            # surface the provenance in the audit trail so "this element
            # never simulated steps 0..P itself" is on the record
            pre = getattr(self.engine, "prefix_steps", None)
            forked = (
                int(np.asarray(pre).max()) if pre is not None else 0
            )
            if forked > 0:
                self._log(
                    "resume-prefix",
                    f"restored state carries prefix-fork provenance "
                    f"(max prefix_steps={forked})",
                )
            return path
        raise CheckpointCorrupt(
            f"{self.store.dir}: all {len(snaps)} snapshots are corrupt"
        )

    # ---- signals --------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        if self._preempt is not None:
            # second signal: the operator is insisting — die now
            raise KeyboardInterrupt
        self._preempt = signum

    def _install_signals(self) -> None:
        if not self.handle_signals:
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread
                pass

    def _restore_signals(self) -> None:
        for sig, h in self._prev_handlers.items():
            signal.signal(sig, h)
        self._prev_handlers = {}

    # ---- engine surface (kind dispatch) ---------------------------------

    def _done(self) -> bool:
        if self.kind == "stream":
            return self._stream_finished or self.engine.done()
        return self.engine.done()

    def _steps_used(self) -> int:
        if self.kind == "fleet":
            return int(self.engine.steps_run.max())
        return int(self.engine.steps_run)

    def _counter_totals(self) -> dict[str, int]:
        return {
            k: int(np.asarray(v).sum())
            for k, v in self.engine.host_counters.items()
        }

    def _host_snapshot(self) -> dict:
        """References/copies of everything `_advance_chunk` mutates, so a
        failed dispatch can be rolled back before a retry (the device
        computation is functional; only these host fields move)."""
        eng = self.engine
        snap = {
            "state": eng.state,
            "steps_run": (
                eng.steps_run.copy()
                if isinstance(eng.steps_run, np.ndarray)
                else eng.steps_run
            ),
            "cycle_base": (
                eng.cycle_base.copy()
                if isinstance(eng.cycle_base, np.ndarray)
                else eng.cycle_base
            ),
            "host_counters": {k: v.copy() for k, v in eng.host_counters.items()},
        }
        if self.kind == "stream":
            snap["cursor"] = eng.cursor.copy()
        if getattr(eng, "attest", None) is not None:
            # the chain must roll back with the state it covers, or a
            # retried chunk would be linked twice
            snap["attest"] = eng.attest.snapshot()
        return snap

    def _host_restore(self, snap: dict) -> None:
        eng = self.engine
        eng.state = snap["state"]
        eng.steps_run = snap["steps_run"]
        eng.cycle_base = snap["cycle_base"]
        eng.host_counters = snap["host_counters"]
        if self.kind == "stream":
            eng.cursor = snap["cursor"]
        if "attest" in snap and getattr(eng, "attest", None) is not None:
            eng.attest.restore(snap["attest"])
        # any overlapped speculation was made from a state we just rolled
        # away from; the identity check would reject it, this frees it
        getattr(eng, "discard_prefetch", lambda: None)()

    def _chaos_revoke_check(self) -> None:
        """Chaos `capacity_loss` site: at a chunk boundary, revoke
        device(s) from the live pool and raise the synthetic DEVICE_LOST
        the reshard ladder classifies. Enacted here (not inside the
        hook) because only the supervisor knows which devices its
        engine's mesh holds."""
        ev = chaos_sites.device_revoke("devices.revoke")
        if ev is None:
            return
        from ..parallel import sharding

        mesh = getattr(self.engine, "mesh", None)
        healthy_ids = {d.id for d in sharding.healthy_devices()}
        pool = [
            d
            for d in (
                list(mesh.devices.flat)
                if mesh is not None
                else sharding.healthy_devices()
            )
            if d.id in healthy_ids
        ]
        n = min(int(ev.arg("n", 1)), len(pool) - 1)
        if n < 1:
            return  # a single-device run has nothing left to lose
        victims = [d.id for d in pool[-n:]]
        sharding.revoke_devices(victims)
        raise RuntimeError(
            f"DEVICE_LOST: injected revocation of device id(s) {victims}"
        )

    def _advance_chunk(self, budget_left: int) -> int:
        """Advance the engine by one committed chunk; returns steps run
        (stream reports the device loop's count; solo/fleet report their
        chunk size)."""
        self._chaos_revoke_check()
        if self.kind == "stream":
            k, finished = self.engine._advance_window(budget_left)
            self._stream_finished = finished
            return k
        before = self._steps_used()
        self.engine.run_steps(self.engine.chunk_steps)
        return self._steps_used() - before

    # ---- retry / degradation --------------------------------------------

    def _fallback_to_cpu(self, cause: BaseException,
                         unshard: bool = False) -> bool:
        """Last-resort degradation: move the run to a single (CPU)
        device. Returns False when impossible (already fell back, no
        landing device) — the caller then re-raises the original.

        Mesh-sharded engines are refused UNLESS `unshard=True`: on the
        device-loss ladder this is the final rung, entered only after
        resharding onto a smaller mesh has already failed, and it
        collapses the run onto one healthy device (`engine.mesh = None`;
        parity is mesh-invariant, so results are unchanged)."""
        import jax

        from ..parallel import sharding

        if self._cpu_fallback_done:
            return False
        mesh = getattr(self.engine, "mesh", None)
        if mesh is not None and not unshard:
            self._log(
                "degrade", "cannot fall back to CPU: engine is mesh-sharded"
            )
            return False
        if mesh is None and jax.default_backend() == "cpu":
            return False
        healthy_ids = {d.id for d in sharding.healthy_devices()}
        try:
            cpus = [d for d in jax.devices("cpu") if d.id in healthy_ids]
        except RuntimeError:
            cpus = []
        if cpus:
            target = cpus[0]
        elif unshard and mesh is not None and healthy_ids:
            target = sharding.healthy_devices()[0]
        else:
            return False
        if mesh is not None:
            self.engine.mesh = None
            self._log(
                "degrade",
                f"device-loss final rung: unsharding onto single device "
                f"{target.id} after: {cause}",
            )
        else:
            self._log("degrade", f"moving run to CPU backend after: {cause}")
        jax.config.update("jax_default_device", target)
        for attr in ("events", "state"):
            if hasattr(self.engine, attr):
                setattr(
                    self.engine,
                    attr,
                    jax.device_put(getattr(self.engine, attr), target),
                )
        getattr(self.engine, "discard_prefetch", lambda: None)()
        self._cpu_fallback_done = True
        return True

    def _reshard_after_device_loss(self, cause: BaseException) -> bool:
        """First rung of the device-loss ladder: shrink the mesh onto
        the remaining healthy devices and re-place the run there.

        Prefers re-placing the newest verified snapshot through the
        existing cross-mesh loader path (checkpoint loaders re-shard
        restored state onto `engine.mesh` — re-running from a committed
        boundary is deterministic, so the continuation stays bit-exact);
        with no usable snapshot the live host-visible arrays are
        re-sharded in place. Returns False when there is no mesh to
        shrink, no healthy landing mesh exists, or the healthy set did
        not actually change (so retries cannot loop through here)."""
        from ..parallel import sharding

        mesh = getattr(self.engine, "mesh", None)
        if mesh is None or self.kind == "stream":
            # stream engines re-fill device windows from host cursors;
            # their recovery story is resume-from-snapshot, not live
            # surgery — let the next rung (or the caller) handle it
            return False
        healthy = sharding.healthy_devices()
        healthy_ids = {d.id for d in healthy}
        cur = list(mesh.devices.flat)
        lost = [d.id for d in cur if d.id not in healthy_ids]
        if not lost and len(healthy) >= len(cur):
            return False  # every mesh device still answers
        try:
            n = sharding.largest_valid_submesh(self.engine.cfg, len(healthy))
        except sharding.DeviceMeshError as e:
            self._log("degrade", f"device loss: no landing mesh ({e})")
            return False
        if n >= len(cur) and not lost:
            return False
        new_mesh = sharding.tile_mesh(devices=healthy[:n])
        self.engine.mesh = new_mesh
        restored = None
        if self.store is not None:
            for path in self.store.snapshots():
                try:
                    self.engine.load_checkpoint(path)
                except (CheckpointCorrupt, ValueError, OSError) as e:
                    self._log(
                        "resume-skip",
                        f"{os.path.basename(path)} unusable during "
                        f"reshard, trying older ({e})",
                    )
                    continue
                restored = path
                break
        # re-place whatever the loader didn't cover: events always ride
        # outside snapshots; state too when nothing was restorable (the
        # old buffers stay readable — virtual meshes never physically
        # lose devices, and on hardware the snapshot path above is the
        # one that fires)
        if self.kind == "fleet":
            self.engine._reshard()
        else:
            self.engine.events = sharding.shard_events(
                new_mesh, self.engine.events
            )
            if restored is None:
                self.engine.state = sharding.shard_state(
                    new_mesh, self.engine.state
                )
        getattr(self.engine, "discard_prefetch", lambda: None)()
        rung = f"reshard:{len(cur)}->{n}"
        self.degrade_rungs.append(rung)
        self._log(
            "degrade",
            f"device loss ({cause}): mesh {len(cur)} -> {n} device(s)"
            + (
                f", re-placed {os.path.basename(restored)}"
                if restored
                else ", re-placed live state"
            ),
        )
        print(
            json.dumps(
                {
                    "event": "degraded",
                    "reason": "device_loss",
                    "lost_devices": lost,
                    "from_devices": len(cur),
                    "to_devices": n,
                    "restored": (
                        os.path.basename(restored) if restored else None
                    ),
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        return True

    def _advance_with_retry(self, budget_left: int) -> int:
        from ..util.backoff import DecorrelatedJitter

        attempt = 0
        # decorrelated jitter (util.backoff): a fault front that knocks
        # over N supervised workers at once must not produce N
        # phase-locked retry storms
        backoff = DecorrelatedJitter(base=self.backoff_s, cap=30.0)
        while True:
            snap = self._host_snapshot()
            try:
                return self._advance_chunk(budget_left)
            except Exception as e:
                self._host_restore(snap)
                kind = classify_failure(e)
                if kind is None:
                    raise
                if kind == "device_loss":
                    # the device-loss ladder, in order: shrink the mesh
                    # onto healthy devices; only when no landing mesh
                    # exists, collapse onto a single (CPU) device; only
                    # then give up. Each rung logs itself.
                    if self._reshard_after_device_loss(e):
                        continue
                    if self._fallback_to_cpu(e, unshard=True):
                        self.degrade_rungs.append("cpu-fallback")
                        continue
                    # nothing to demote (already unsharded on the only
                    # healthy device): indistinguishable from a transient
                    # blip — take the bounded backoff-retry path below
                    kind = "transient"
                if attempt >= self.max_retries:
                    if self._fallback_to_cpu(e):
                        continue  # one full attempt on the CPU backend
                    self._log(
                        "give-up",
                        f"{kind} failure persisted after "
                        f"{self.max_retries} retries: {e}",
                    )
                    raise
                attempt += 1
                self.retries += 1
                chunk = getattr(self.engine, "chunk_steps", 1)
                if kind == "oom" and chunk > 1:
                    # halving only changes the drain/rebase cadence, so
                    # results stay bit-exact; recompile is the cost
                    self.engine.chunk_steps = max(1, chunk // 2)
                    at = getattr(self.engine, "attest", None)
                    if at is not None:
                        # the fingerprint chain is cadence-scoped (§24):
                        # record the halving so this run's chain reads as
                        # incomparable, never as a false divergence
                        at.note_cadence(self.engine.chunk_steps)
                    self._log(
                        "degrade",
                        f"device OOM: chunk_steps {chunk} -> "
                        f"{self.engine.chunk_steps}, retrying "
                        f"(attempt {attempt}/{self.max_retries})",
                    )
                else:
                    delay = backoff.next_delay()
                    self._log(
                        "retry",
                        f"transient failure ({e}); backing off "
                        f"{delay:.2f}s (attempt {attempt}/"
                        f"{self.max_retries})",
                    )
                    time.sleep(delay)

    # ---- chaos mode -----------------------------------------------------

    _CHAOS_KEYS = ("core_failstops", "noc_reroutes", "ecc_corrected",
                   "ecc_due")

    def _chaos_check(self) -> None:
        """Log fault-counter movement since the last committed chunk, so
        the RESILIENCE section records WHEN each injected fault landed."""
        if not self._chaos:
            return
        hc = self.engine.host_counters
        cur = {
            k: int(np.asarray(hc[k]).sum())
            for k in self._CHAOS_KEYS
            if k in hc
        }
        moved = [
            f"{k} +{v - self._fault_seen.get(k, 0)} (total {v})"
            for k, v in cur.items()
            if v > self._fault_seen.get(k, 0)
        ]
        if moved:
            self._log("chaos", "; ".join(moved))
        self._fault_seen = cur

    # ---- guard ----------------------------------------------------------

    def _guard_check(self) -> None:
        if self.guard == "off":
            return
        totals = self._counter_totals()
        try:
            if self.kind == "fleet":
                core_done = self.engine.core_done_mask()
                live = self.engine.live_mask()
                for i, cfg in enumerate(self.engine.elem_cfgs):
                    check_chunk_invariants(
                        cfg,
                        self.engine.element_state(i),
                        done_mask=core_done[i],
                        live_mask=live[i],
                    )
                check_chunk_invariants(
                    self.engine.cfg,
                    None,
                    prev_totals=self._prev_totals,
                    totals=totals,
                )
            else:
                check_chunk_invariants(
                    self.engine.cfg,
                    self.engine.state,
                    done_mask=self.engine.done_mask(),
                    live_mask=self.engine.live_mask(),
                    prev_totals=self._prev_totals,
                    totals=totals,
                )
        except AssertionError as e:
            if self.guard == "warn":
                self.guard_warnings += 1
                self._log("guard-warn", str(e))
            else:
                self._log("guard-fail", str(e))
                raise GuardViolation(str(e)) from e
        self._prev_totals = totals

    # ---- the supervised loop --------------------------------------------

    def run(self, max_steps: int | None = None) -> None:
        """Run the engine to completion under supervision.

        Raises Preempted (after checkpointing) on SIGTERM/SIGINT,
        GuardViolation under `--guard=fail`, RuntimeError when the step
        budget runs out with cores still live (fleet: budget-stalled
        elements are recorded in `stalled_elements` and reported instead
        — one deadlocked element must not void the batch)."""
        if max_steps is None:
            max_steps = (
                self.engine._default_budget()
                if self.kind == "stream"
                else 10_000_000
            )
        budget_left = int(max_steps)
        start_steps = self._steps_used()
        self._install_signals()
        self._prev_totals = self._counter_totals()
        if self._chaos:
            cfg = self.engine.cfg
            self._log(
                "chaos",
                f"fault injection armed: seed {cfg.fault_seed}, "
                f"{len(cfg.fault_events)} scheduled event(s), "
                f"dead policy {cfg.fault_dead_policy}",
            )
            self._fault_seen = {
                k: int(np.asarray(self.engine.host_counters[k]).sum())
                for k in self._CHAOS_KEYS
                if k in self.engine.host_counters
            }
        last_ckpt_t = time.monotonic()
        chunks_since_ckpt = 0
        try:
            while not self._done():
                if self.kind == "stream":
                    stepped = self._advance_with_retry(budget_left)
                    budget_left -= stepped
                else:
                    stepped = self._advance_with_retry(0)
                self.committed += 1
                chunks_since_ckpt += 1
                if self.on_chunk is not None:
                    self.on_chunk(self)
                self._chaos_check()
                self._guard_check()
                if self._preempt is not None:
                    signum = self._preempt
                    path = self.checkpoint()
                    name = signal.Signals(signum).name
                    where = (
                        f"snapshot {os.path.basename(path)}"
                        if path
                        else "no snapshot dir configured"
                    )
                    self._log("preempt", f"{name} at chunk boundary; {where}")
                    raise Preempted(
                        f"preempted by {name} after {self.committed} "
                        f"committed chunks ({where})",
                        checkpoint=path,
                        signum=signum,
                    )
                now = time.monotonic()
                if self.store is not None and (
                    (
                        self.checkpoint_every_chunks > 0
                        and chunks_since_ckpt >= self.checkpoint_every_chunks
                    )
                    or (
                        self.checkpoint_every_s > 0
                        and now - last_ckpt_t >= self.checkpoint_every_s
                    )
                ):
                    self.checkpoint()
                    chunks_since_ckpt = 0
                    last_ckpt_t = now
                if self.kind != "stream":
                    if stepped == 0 or (
                        self._steps_used() - start_steps >= max_steps
                        and not self._done()
                    ):
                        if self.kind == "fleet":
                            self.stalled_elements = [
                                self.engine.element_ids[j]
                                for j in np.flatnonzero(
                                    ~self.engine.done_mask()
                                )
                            ]
                            self._log(
                                "stall",
                                f"step budget exhausted; elements "
                                f"{self.stalled_elements} still live — "
                                "isolating, rest of the batch is complete",
                            )
                            break
                        raise RuntimeError(
                            f"supervised run: step budget ({max_steps}) "
                            "exhausted with cores still live (deadlock?)"
                        )
                elif budget_left <= 0 and not self._done():
                    raise RuntimeError(
                        f"supervised run: step budget ({max_steps}) "
                        "exhausted with the stream unfinished"
                    )
            if self.store is not None:
                self.checkpoint()  # final snapshot: resume == no-op rerun
        finally:
            self._restore_signals()


# ---- fleet fault isolation (pre-run) ------------------------------------


def validate_fleet_element(cfg, trace, override: dict | None = None) -> None:
    """Everything FleetEngine.__init__ would reject about ONE element,
    checked in isolation: override keys/values, core count, addressing
    line size, barrier ids vs the slot table. Raises ValueError (often
    the located TraceError subclass)."""
    from ..trace.format import validate_sync
    from .fleet import apply_overrides

    apply_overrides(cfg, override or {})
    if trace.n_cores != cfg.n_cores:
        raise ValueError(
            f"trace has {trace.n_cores} cores, config {cfg.n_cores}"
        )
    if trace.line_addressed:
        trace.line_events(cfg.line_bits)  # line-size validation only
    validate_sync(trace, cfg.barrier_slots)


def build_fleet_isolated(
    cfg,
    sources: list,
    overrides: list[dict] | None = None,
    chunk_steps: int = 256,
    mesh=None,
):
    """Build a FleetEngine from per-element sources with fault isolation.

    `sources[i]` is a Trace or a zero-arg callable returning one (pass
    callables for file loads so an unreadable/corrupt FILE quarantines
    its element instead of killing the batch). Elements whose load or
    validation fails are dropped; the survivors' batch positions map
    back to caller indices through `fleet.element_ids`.

    Returns `(fleet, quarantined)` where `quarantined` is a list of
    `(original_index, exception)` and `fleet` is None when nothing
    survived."""
    from .fleet import FleetEngine

    sources = list(sources)
    if overrides is None:
        overrides = [{}] * len(sources)
    overrides = list(overrides)
    if len(overrides) != len(sources):
        raise ValueError(
            f"got {len(sources)} trace sources but {len(overrides)} "
            "override dicts (must match 1:1)"
        )
    kept, kept_ovs, ids = [], [], []
    quarantined: list[tuple[int, Exception]] = []
    for i, (src, ov) in enumerate(zip(sources, overrides)):
        try:
            trace = src() if callable(src) else src
            validate_fleet_element(cfg, trace, ov)
        except (ValueError, OSError) as e:
            quarantined.append((i, e))
            continue
        kept.append(trace)
        kept_ovs.append(ov)
        ids.append(i)
    if not kept:
        return None, quarantined
    fleet = FleetEngine(cfg, kept, kept_ovs, chunk_steps=chunk_steps,
                        mesh=mesh)
    fleet.element_ids = ids
    return fleet, quarantined
