"""Machine state pytree — the lax.scan carry.

The entire simulated machine (SURVEY.md §5.4: "the scan carry IS the
checkpoint") lives in this one NamedTuple of device arrays: core clocks and
trace pointers (CoreManager state, SURVEY.md §2 #2), L1 arrays (#3), LLC +
directory arrays (#3/#4), the quantum clock (#10), and stat counters (#12).
Everything is int32/uint32 so state stays compact and TPU-friendly; the host
runner rebases clocks and drains counters into int64 between chunks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from ..faults.schedule import FaultState, fault_state_from_config
from ..stats.counters import COUNTER_NAMES

# MESI encoding (shared with primesim_tpu.golden.sim)
I, S, E, M = 0, 1, 2, 3
# MOESI's Owned state (cfg.coherence == "moesi", DESIGN.md §25). DERIVED,
# never stored: the L1 plane still holds only I/S/E/M, and an access sees
# O when the directory says this core owns the line while other sharers
# are recorded (a GETS left the dirty copy in place). Keeping O out of
# the stored encoding keeps every plane layout and Pallas kernel
# unchanged; O > M so `>= E`-style "exclusive" tests must be written as
# the explicit (== E) | (== M) pair wherever a derived state can appear.
O = 4


def llc_meta_width(cfg: MachineConfig) -> int:
    """Width of the metadata prefix of a `dirm` row: 4*W2 data columns
    (tag/owner pairs, lru, invalidation epoch) rounded up to a 128-lane
    multiple so both the prefix and the sharer words that follow stay
    lane-aligned (see field note)."""
    return ((4 * cfg.llc.ways + 127) // 128) * 128


def dirm_width(cfg: MachineConfig) -> int:
    """Full `dirm` row width: metadata prefix + W2*NW packed sharer
    words. These row/plane layouts are a PUBLIC contract: the Pallas
    step kernels (kernels/layouts.py, DESIGN.md §11) stage `dirm` rows
    and the five-plane L1 blocks into VMEM verbatim and hard-code the
    same column maps — change a layout here and the kernels' index maps
    must move with it (the three-way parity suite catches drift)."""
    return llc_meta_width(cfg) + cfg.llc.ways * cfg.n_sharer_words


class TimingKnobs(NamedTuple):
    """Per-simulation TIMING knobs, lifted out of the static
    `MachineConfig` into TRACED device scalars/vectors so one compiled
    program serves a whole parameter sweep (the fleet engine vmaps them
    over a leading batch axis; solo engines carry the config's values).
    GEOMETRY (core count, sets/ways, mesh shape, slot tables) and model
    SELECTORS (contention_model, dram_queue, sharer_group, local_run_len,
    o3_overlap_256) stay static — they change array shapes or the traced
    graph itself. All int32, like every clock they feed."""

    quantum: jnp.ndarray  # [] — relaxed-sync quantum, cycles
    cpi: jnp.ndarray  # [C] — per-core non-memory CPI
    l1_lat: jnp.ndarray  # [] — L1 hit/lookup latency
    llc_lat: jnp.ndarray  # [] — LLC bank lookup latency
    link_lat: jnp.ndarray  # [] — per-hop mesh link traversal
    router_lat: jnp.ndarray  # [] — per-router latency
    dram_lat: jnp.ndarray  # [] — DRAM access latency
    dram_service: jnp.ndarray  # [] — controller occupancy (0 -> dram_lat)
    contention_lat: jnp.ndarray  # [] — queueing cycles per transaction
    prefetch_degree: jnp.ndarray  # [] — stride-prefetch lookahead, lines
    prefetch_lat: jnp.ndarray  # [] — LLC-miss cost on a prefetch hit


def knobs_from_config(cfg: MachineConfig) -> TimingKnobs:
    """The config's timing values as a traced-knob pytree (the solo
    engine's knobs; fleet elements override per batch entry)."""

    def i32(v):
        return jnp.asarray(v, jnp.int32)

    return TimingKnobs(
        quantum=i32(cfg.quantum),
        cpi=jnp.asarray(cfg.core.cpi_vector(cfg.n_cores), jnp.int32),
        l1_lat=i32(cfg.l1.latency),
        llc_lat=i32(cfg.llc.latency),
        link_lat=i32(cfg.noc.link_lat),
        router_lat=i32(cfg.noc.router_lat),
        dram_lat=i32(cfg.dram_lat),
        dram_service=i32(cfg.dram_service),
        contention_lat=i32(cfg.noc.contention_lat),
        prefetch_degree=i32(cfg.prefetch_degree),
        prefetch_lat=i32(cfg.prefetch_lat),
    )


class MachineState(NamedTuple):
    # core (CoreManager)
    cycles: jnp.ndarray  # [C] int32 — per-core clock (epoch-relative)
    ptr: jnp.ndarray  # [C] int32 — next trace event index
    # L1 (private caches), all five fields FUSED into one array of
    # planes: plane f at columns [f*W1*S1, (f+1)*W1*S1), in-plane column
    # w*S1 + s (way-major). Planes: 0 = tag (-1 invalid), 1 = MESI state
    # (locally-written; see pull-based coherence), 2 = LRU step-stamp,
    # 3 = LLC way pointer recorded at fill time (slot*W2 + way of the
    # line's directory entry — phase-1 pull-validation follows it with
    # element gathers instead of W2-wide tag searches; a stale pointer is
    # self-detecting, DESIGN.md §7), 4 = the directory entry's
    # invalidation epoch at fill time (compared by coarse-vector
    # validation only). Fused because per-step cost on this TPU path is
    # dominated by per-KERNEL overhead: one take_along over concatenated
    # plane columns replaces three gathers, and one multi-column scatter
    # replaces the six L1 update scatters. 2D with a large minor dim
    # (>= 2560) so tiling stays natural; a 3D shape would make XLA pad
    # the tiny way dim to 128.
    l1: jnp.ndarray  # [C, 5*W1*S1] int32
    # The WHOLE directory, fused: ROW PER (bank, set) — row slot =
    # bank*S2 + set. Columns:
    #   [2w]            = way w's tag (-1 invalid)
    #   [2w+1]          = way w's owner (-1 none)
    #   [2*W2 + w]      = way w's LRU step-stamp
    #   [3*W2 + w]      = way w's invalidation epoch (bumped on every
    #                     sharer-CLEARING transition; the coarse sharer
    #                     vector's pull-validation compares it against
    #                     the L1's fill-time record so a neighbor's later
    #                     re-share cannot resurrect an invalidated entry)
    #   [4*W2 .. MW)    = zero pad up to llc_meta_width (128 multiple)
    #   [MW + w*NW + i] = way w's packed sharer bit-vector word i
    # ONE full-row gather returns EVERYTHING the step needs about the
    # accessed set — tags, owners, LRU, epochs, sharer words — and the
    # winner/join transition writes back through ONE row scatter-add
    # (winner rows carry exact full-row deltas; join rows just their own
    # sharer bit). Per-step cost on this TPU path is per-KERNEL overhead,
    # so collapsing the former sharers+meta arrays' separate gathers/
    # scatters is the win. Full-row forms are the ones XLA lowers well
    # (windowed dynamic-column forms cost 2-4 ms); the explicit 128-lane
    # alignment of the prefix stops XLA's layout assignment from flipping
    # the array to a dim0-minor (transposed) physical layout, which turns
    # every logical row into a strided walk across tiles. int32
    # throughout: sharer bit arithmetic (shift+mask extraction, popcount,
    # wrapping add-deltas) is representation-identical to uint32.
    dirm: jnp.ndarray  # [B*S2, dirm_width(cfg)] int32
    # hop-by-hop router (contention_model="router"): per-directed-link
    # next-free clock, epoch-relative, carried across steps; rebased with
    # the core clocks (clamped at -(1<<30) — a clock that far in the past
    # can never influence a wait, so the clamp is observably exact)
    link_free: jnp.ndarray  # [n_tiles*4] int32
    # memory-controller queueing (cfg.dram_queue): per-bank next-free
    # clock, same epoch/rebase/clamp treatment as link_free
    dram_free: jnp.ndarray  # [B] int32
    # synchronization state (DESIGN.md §3 phase 2.7)
    lock_holder: jnp.ndarray  # [lock_slots] int32 core id or -1
    barrier_count: jnp.ndarray  # [barrier_slots] int32 arrivals this round
    barrier_time: jnp.ndarray  # [barrier_slots] int32 max arrival clock (epoch-relative)
    sync_flag: jnp.ndarray  # [C] int32 1 = pre charged / arrived at event at ptr
    # global clocks
    quantum_end: jnp.ndarray  # [] int32
    step: jnp.ndarray  # [] int32
    # stride-prefetcher training state (cfg.prefetcher == "stride",
    # DESIGN.md §25): last trained line address, last stride (lines) and
    # the consecutive same-stride streak, per core. Always present so the
    # pytree structure is config-stable (like `faults`); with the
    # selector off (static) step() never reads them and carries the
    # zeros through untouched
    pf_line: jnp.ndarray  # [C] int32
    pf_stride: jnp.ndarray  # [C] int32
    pf_streak: jnp.ndarray  # [C] int32
    # stat counters, one row per COUNTER_NAMES entry
    counters: jnp.ndarray  # [n_counters, C] int32
    # traced per-simulation timing knobs (see TimingKnobs): constant
    # through a run (step passes them through), but TRACED so one
    # compiled program serves every timing variant of one geometry
    knobs: TimingKnobs
    # traced fault-injection state (faults.schedule.FaultState): seed,
    # schedule arrays, ECC thresholds, and the evolving dead-core/link
    # masks. Always present so the pytree structure is config-stable;
    # with cfg.faults_enabled == False (static) step() never reads it —
    # the faults-off step graph carries the leaves through untouched,
    # keeping it bit-exact vs the goldens at ~zero overhead
    faults: FaultState


def init_state(cfg: MachineConfig) -> MachineState:
    C, B = cfg.n_cores, cfg.n_banks
    s1, w1 = cfg.l1.sets, cfg.l1.ways
    s2, w2 = cfg.llc.sets, cfg.llc.ways
    nw = cfg.n_sharer_words
    if cfg.quantum * cfg.n_cores >= 2**31:
        raise ValueError(
            "quantum * n_cores must be < 2^31 (conflict-key packing); "
            f"got {cfg.quantum} * {cfg.n_cores}"
        )
    return MachineState(
        cycles=jnp.zeros(C, jnp.int32),
        ptr=jnp.zeros(C, jnp.int32),
        l1=jnp.concatenate(
            [
                jnp.full((C, w1 * s1), -1, jnp.int32),  # tag plane
                jnp.full((C, w1 * s1), I, jnp.int32),  # state plane
                jnp.zeros((C, 3 * w1 * s1), jnp.int32),  # lru/ptr/epoch
            ],
            axis=1,
        ),
        dirm=jnp.concatenate(
            [
                jnp.full((B * s2, 2 * w2), -1, jnp.int32),  # tag/owner
                jnp.zeros(
                    (B * s2, dirm_width(cfg) - 2 * w2), jnp.int32
                ),  # lru + epochs + pad + sharer words
            ],
            axis=1,
        ),
        link_free=jnp.zeros(cfg.n_tiles * 4, jnp.int32),
        dram_free=jnp.zeros(B, jnp.int32),
        lock_holder=jnp.full(cfg.lock_slots, -1, jnp.int32),
        barrier_count=jnp.zeros(cfg.barrier_slots, jnp.int32),
        barrier_time=jnp.zeros(cfg.barrier_slots, jnp.int32),
        sync_flag=jnp.zeros(C, jnp.int32),
        pf_line=jnp.zeros(C, jnp.int32),
        pf_stride=jnp.zeros(C, jnp.int32),
        pf_streak=jnp.zeros(C, jnp.int32),
        quantum_end=jnp.asarray(cfg.quantum, jnp.int32),
        step=jnp.asarray(0, jnp.int32),
        counters=jnp.zeros((len(COUNTER_NAMES), C), jnp.int32),
        knobs=knobs_from_config(cfg),
        faults=fault_state_from_config(cfg),
    )


def counters_to_dict(counters: np.ndarray) -> dict[str, np.ndarray]:
    return {k: np.asarray(counters[i], dtype=np.int64) for i, k in enumerate(COUNTER_NAMES)}
