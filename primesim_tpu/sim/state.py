"""Machine state pytree — the lax.scan carry.

The entire simulated machine (SURVEY.md §5.4: "the scan carry IS the
checkpoint") lives in this one NamedTuple of device arrays: core clocks and
trace pointers (CoreManager state, SURVEY.md §2 #2), L1 arrays (#3), LLC +
directory arrays (#3/#4), the quantum clock (#10), and stat counters (#12).
Everything is int32/uint32 so state stays compact and TPU-friendly; the host
runner rebases clocks and drains counters into int64 between chunks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from ..stats.counters import COUNTER_NAMES

# MESI encoding (shared with primesim_tpu.golden.sim)
I, S, E, M = 0, 1, 2, 3


class MachineState(NamedTuple):
    # core (CoreManager)
    cycles: jnp.ndarray  # [C] int32 — per-core clock (epoch-relative)
    ptr: jnp.ndarray  # [C] int32 — next trace event index
    # L1 (private caches). Stored 2D [C, W1*S1] (way-major columns,
    # column w*S1 + s): with a 3D shape XLA's layout assignment insists on
    # making the small way dimension minor, and TPU tiling pads the minor
    # dim to 128 — a 32x memory/bandwidth waste at W1=4. A 2D row of
    # W1*S1 (>= 512) columns tiles cleanly and leaves XLA nothing to
    # re-layout.
    l1_tag: jnp.ndarray  # [C, W1*S1] int32, -1 = invalid
    l1_state: jnp.ndarray  # [C, W1*S1] int32 MESI (locally-written)
    l1_lru: jnp.ndarray  # [C, W1*S1] int32 step-stamp
    # LLC way pointer recorded at fill time: slot*W2 + way of the line's
    # directory entry. Lets the phase-1 pull-validation use three 1-element
    # gathers instead of W2-wide tag searches (engine.py `_l1_probe`); a
    # stale pointer is self-detecting (the pointed tag no longer matches)
    # and exactly reproduces search validation — see DESIGN.md §7.
    l1_ptr: jnp.ndarray  # [C, W1*S1] int32
    # LLC banks + directory
    llc_tag: jnp.ndarray  # [B, S2, W2] int32, -1 = invalid
    llc_owner: jnp.ndarray  # [B, S2, W2] int32 core id or -1
    llc_lru: jnp.ndarray  # [B, S2, W2] int32 step-stamp
    # Directory sharer bit-vectors, stored row-per-(bank,set) with the way
    # axis folded into columns: row slot b*S2+s, columns [w*NW, (w+1)*NW).
    # Kept 2D so XLA settles on ONE layout for it — the natural
    # [B,S2,W2,NW] shape made layout assignment bounce this (huge, at large
    # core counts) array between gather- and loop-carry-preferred layouts,
    # costing two full copies per step. (At the 1024-core flagship config
    # the minor dim is also a 128 multiple, which tiles without padding.)
    sharers: jnp.ndarray  # [B*S2, W2*NW] uint32 packed sharer bits
    # synchronization state (DESIGN.md §3 phase 2.7)
    lock_holder: jnp.ndarray  # [lock_slots] int32 core id or -1
    barrier_count: jnp.ndarray  # [barrier_slots] int32 arrivals this round
    barrier_time: jnp.ndarray  # [barrier_slots] int32 max arrival clock (epoch-relative)
    sync_flag: jnp.ndarray  # [C] int32 1 = pre charged / arrived at event at ptr
    # global clocks
    quantum_end: jnp.ndarray  # [] int32
    step: jnp.ndarray  # [] int32
    # stat counters, one row per COUNTER_NAMES entry
    counters: jnp.ndarray  # [n_counters, C] int32


def init_state(cfg: MachineConfig) -> MachineState:
    C, B = cfg.n_cores, cfg.n_banks
    s1, w1 = cfg.l1.sets, cfg.l1.ways
    s2, w2 = cfg.llc.sets, cfg.llc.ways
    nw = cfg.n_sharer_words
    if cfg.quantum * cfg.n_cores >= 2**31:
        raise ValueError(
            "quantum * n_cores must be < 2^31 (conflict-key packing); "
            f"got {cfg.quantum} * {cfg.n_cores}"
        )
    return MachineState(
        cycles=jnp.zeros(C, jnp.int32),
        ptr=jnp.zeros(C, jnp.int32),
        l1_tag=jnp.full((C, w1 * s1), -1, jnp.int32),
        l1_state=jnp.full((C, w1 * s1), I, jnp.int32),
        l1_lru=jnp.zeros((C, w1 * s1), jnp.int32),
        l1_ptr=jnp.zeros((C, w1 * s1), jnp.int32),
        llc_tag=jnp.full((B, s2, w2), -1, jnp.int32),
        llc_owner=jnp.full((B, s2, w2), -1, jnp.int32),
        llc_lru=jnp.zeros((B, s2, w2), jnp.int32),
        sharers=jnp.zeros((B * s2, w2 * nw), jnp.uint32),
        lock_holder=jnp.full(cfg.lock_slots, -1, jnp.int32),
        barrier_count=jnp.zeros(cfg.barrier_slots, jnp.int32),
        barrier_time=jnp.zeros(cfg.barrier_slots, jnp.int32),
        sync_flag=jnp.zeros(C, jnp.int32),
        quantum_end=jnp.asarray(cfg.quantum, jnp.int32),
        step=jnp.asarray(0, jnp.int32),
        counters=jnp.zeros((len(COUNTER_NAMES), C), jnp.int32),
    )


def counters_to_dict(counters: np.ndarray) -> dict[str, np.ndarray]:
    return {k: np.asarray(counters[i], dtype=np.int64) for i, k in enumerate(COUNTER_NAMES)}
