"""Machine state pytree — the lax.scan carry.

The entire simulated machine (SURVEY.md §5.4: "the scan carry IS the
checkpoint") lives in this one NamedTuple of device arrays: core clocks and
trace pointers (CoreManager state, SURVEY.md §2 #2), L1 arrays (#3), LLC +
directory arrays (#3/#4), the quantum clock (#10), and stat counters (#12).
Everything is int32/uint32 so state stays compact and TPU-friendly; the host
runner rebases clocks and drains counters into int64 between chunks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from ..stats.counters import COUNTER_NAMES

# MESI encoding (shared with primesim_tpu.golden.sim)
I, S, E, M = 0, 1, 2, 3


def llc_meta_width(cfg: MachineConfig) -> int:
    """Padded llc_meta row width: 4*W2 data columns (tag/owner pairs,
    lru, invalidation epoch) rounded up to a 128-lane multiple so the
    array tiles row-major (see field note)."""
    return ((4 * cfg.llc.ways + 127) // 128) * 128


class MachineState(NamedTuple):
    # core (CoreManager)
    cycles: jnp.ndarray  # [C] int32 — per-core clock (epoch-relative)
    ptr: jnp.ndarray  # [C] int32 — next trace event index
    # L1 (private caches), all five fields FUSED into one array of
    # planes: plane f at columns [f*W1*S1, (f+1)*W1*S1), in-plane column
    # w*S1 + s (way-major). Planes: 0 = tag (-1 invalid), 1 = MESI state
    # (locally-written; see pull-based coherence), 2 = LRU step-stamp,
    # 3 = LLC way pointer recorded at fill time (slot*W2 + way of the
    # line's directory entry — phase-1 pull-validation follows it with
    # element gathers instead of W2-wide tag searches; a stale pointer is
    # self-detecting, DESIGN.md §7), 4 = the directory entry's
    # invalidation epoch at fill time (compared by coarse-vector
    # validation only). Fused because per-step cost on this TPU path is
    # dominated by per-KERNEL overhead: one take_along over concatenated
    # plane columns replaces three gathers, and one multi-column scatter
    # replaces the six L1 update scatters. 2D with a large minor dim
    # (>= 2560) so tiling stays natural; a 3D shape would make XLA pad
    # the tiny way dim to 128.
    l1: jnp.ndarray  # [C, 5*W1*S1] int32
    # LLC banks + directory metadata, fused: ROW PER (bank, set) — row
    # slot = bank*S2 + set, columns [2w]=tag, [2w+1]=owner, [2*W2+w]=lru,
    # [3*W2+w]=invalidation epoch (bumped on every sharer-CLEARING
    # transition; the coarse sharer vector's pull-validation compares it
    # against the L1's fill-time record so a neighbor's later re-share
    # cannot resurrect an invalidated entry), rest zero padding up to
    # `llc_meta_width` (a 128 multiple). One
    # FULL-ROW gather (`llc_meta[slot]`, same addressing as the sharers
    # array) returns the accessed set's tags+owners+LRU stamps in a
    # single op, and the winner transition writes them back in a single
    # full-row scatter. Full-row forms are the ones XLA lowers well on
    # TPU: the round-5 profile showed whole-row gather/scatter at ~0.02-
    # 0.1 ms while windowed (dynamic column offset) forms cost 2-4 ms and
    # three narrow [B,S2,W2] scatters cost 0.28 ms. The EXPLICIT pad to a
    # 128-lane minor dim matters as much as the form: at 3*W2 (=24)
    # columns XLA's layout assignment flips the array to a
    # dim0-minor physical layout (transposing beats 5x pad in its cost
    # model), which turns every logical row into a strided walk across
    # tiles — the compiled HLO showed {0,1:T(8,128)} and the phase
    # profile billed ~2 ms/step to meta traffic until the pad forced the
    # natural row-major tiling back.
    llc_meta: jnp.ndarray  # [B*S2, llc_meta_width(cfg)] int32
    # Directory sharer bit-vectors, stored row-per-(bank,set) with the way
    # axis folded into columns: row slot b*S2+s, columns [w*NW, (w+1)*NW).
    # Kept 2D so XLA settles on ONE layout for it — the natural
    # [B,S2,W2,NW] shape made layout assignment bounce this (huge, at large
    # core counts) array between gather- and loop-carry-preferred layouts,
    # costing two full copies per step. (At the 1024-core flagship config
    # the minor dim is also a 128 multiple, which tiles without padding.)
    sharers: jnp.ndarray  # [B*S2, W2*NW] uint32 packed sharer bits
    # hop-by-hop router (contention_model="router"): per-directed-link
    # next-free clock, epoch-relative, carried across steps; rebased with
    # the core clocks (clamped at -(1<<30) — a clock that far in the past
    # can never influence a wait, so the clamp is observably exact)
    link_free: jnp.ndarray  # [n_tiles*4] int32
    # memory-controller queueing (cfg.dram_queue): per-bank next-free
    # clock, same epoch/rebase/clamp treatment as link_free
    dram_free: jnp.ndarray  # [B] int32
    # synchronization state (DESIGN.md §3 phase 2.7)
    lock_holder: jnp.ndarray  # [lock_slots] int32 core id or -1
    barrier_count: jnp.ndarray  # [barrier_slots] int32 arrivals this round
    barrier_time: jnp.ndarray  # [barrier_slots] int32 max arrival clock (epoch-relative)
    sync_flag: jnp.ndarray  # [C] int32 1 = pre charged / arrived at event at ptr
    # global clocks
    quantum_end: jnp.ndarray  # [] int32
    step: jnp.ndarray  # [] int32
    # stat counters, one row per COUNTER_NAMES entry
    counters: jnp.ndarray  # [n_counters, C] int32


def init_state(cfg: MachineConfig) -> MachineState:
    C, B = cfg.n_cores, cfg.n_banks
    s1, w1 = cfg.l1.sets, cfg.l1.ways
    s2, w2 = cfg.llc.sets, cfg.llc.ways
    nw = cfg.n_sharer_words
    if cfg.quantum * cfg.n_cores >= 2**31:
        raise ValueError(
            "quantum * n_cores must be < 2^31 (conflict-key packing); "
            f"got {cfg.quantum} * {cfg.n_cores}"
        )
    return MachineState(
        cycles=jnp.zeros(C, jnp.int32),
        ptr=jnp.zeros(C, jnp.int32),
        l1=jnp.concatenate(
            [
                jnp.full((C, w1 * s1), -1, jnp.int32),  # tag plane
                jnp.full((C, w1 * s1), I, jnp.int32),  # state plane
                jnp.zeros((C, 3 * w1 * s1), jnp.int32),  # lru/ptr/epoch
            ],
            axis=1,
        ),
        llc_meta=jnp.concatenate(
            [
                jnp.full((B * s2, 2 * w2), -1, jnp.int32),  # tag/owner
                jnp.zeros(
                    (B * s2, llc_meta_width(cfg) - 2 * w2), jnp.int32
                ),  # lru stamps + tiling pad
            ],
            axis=1,
        ),
        sharers=jnp.zeros((B * s2, w2 * nw), jnp.uint32),
        link_free=jnp.zeros(cfg.n_tiles * 4, jnp.int32),
        dram_free=jnp.zeros(B, jnp.int32),
        lock_holder=jnp.full(cfg.lock_slots, -1, jnp.int32),
        barrier_count=jnp.zeros(cfg.barrier_slots, jnp.int32),
        barrier_time=jnp.zeros(cfg.barrier_slots, jnp.int32),
        sync_flag=jnp.zeros(C, jnp.int32),
        quantum_end=jnp.asarray(cfg.quantum, jnp.int32),
        step=jnp.asarray(0, jnp.int32),
        counters=jnp.zeros((len(COUNTER_NAMES), C), jnp.int32),
    )


def counters_to_dict(counters: np.ndarray) -> dict[str, np.ndarray]:
    return {k: np.asarray(counters[i], dtype=np.int64) for i, k in enumerate(COUNTER_NAMES)}
