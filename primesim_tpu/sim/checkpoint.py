"""Checkpoint / resume (SURVEY.md §5.4).

The reference has no checkpointing — runs are one-shot. Here the entire
simulated machine is one pytree (the scan carry, `MachineState`) plus a
handful of host-side accumulators, so a checkpoint is a single `.npz`:
every state field, the 64-bit counter/clock bases, and fingerprints of the
config and trace (resuming against a different machine or workload is an
error, not silent corruption). Quantum boundaries need no special casing —
any step boundary is a consistent cut.

Bit-exactness contract: run(A+B steps) == run(A) -> save -> load -> run(B),
for cycles, counters, and all cache/directory/sync state
(tests/test_checkpoint.py).

Durability contract (DESIGN.md §10): every save goes through
`atomic_save_npz` — write to `<path>.tmp`, fsync, `os.replace` — so a
crash mid-write can never replace a good snapshot with a torn one, and a
per-array CRC32 manifest inside the npz turns silent media corruption
into a typed `CheckpointCorrupt` at load time (which the supervisor's
snapshot rotation treats as "fall back to the next-newest valid one").
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import zlib

import jax.numpy as jnp
import numpy as np

from ..chaos import sites as chaos
from ..config.machine import MachineConfig
from ..faults.schedule import FaultState
from ..stats.counters import COUNTER_NAMES
from ..util import diskpressure
from .state import MachineState, TimingKnobs

_FORMAT = 7  # v3: fused dirm row (metadata + sharers) replaces
# llc_meta/sharers; 5-plane l1; link_free/dram_free queue clocks.
# v4: nested TimingKnobs state field (flattened to state_knobs__<name>
# keys — npz holds flat arrays only).
# v5: nested FaultState field (state_faults__<name>) + four fault
# counters — resuming a chaos run replays the surviving schedule and
# dead-core/link masks bit-exactly.
# v6: prefix-fork provenance (prefix_steps + warm-cache key) on solo,
# fleet, and element snapshots — --resume of a forked run is
# self-describing, and the warm-state cache (below) shares the format.
# v7: machine-zoo state — per-core stride-prefetcher tracking arrays
# (pf_line/pf_stride/pf_streak) + two TimingKnobs fields
# (prefetch_degree/prefetch_lat); older snapshots lack the arrays, so
# the format bump keeps them from resuming with silently-zeroed
# prefetcher state.

# nested-NamedTuple state fields and their types (flattened by
# _state_arrays to `state_<field>__<sub>` keys; extend here when a new
# nested pytree joins MachineState)
_NESTED = {"knobs": TimingKnobs, "faults": FaultState}

_CRC_KEY = "crc_json"  # reserved npz member: {array name: crc32} manifest


class CheckpointCorrupt(ValueError):
    """The checkpoint file is torn, truncated, or fails CRC verification.

    Distinct from the plain ValueErrors the loaders raise for MISMATCHED
    checkpoints (wrong config/trace/kind): a mismatch means the caller
    pointed a healthy snapshot at the wrong engine and retrying another
    snapshot would silently resume the wrong run, while corruption means
    THIS file is unusable and an older snapshot is the right fallback.
    The supervisor's rotation logic relies on that distinction."""


def atomic_save_npz(path: str, **arrays) -> None:
    """Write an npz atomically with per-array CRC32s.

    The bytes go to a writer-unique temp file beside `path` first, are
    flushed and fsynced, and
    only then `os.replace`d over `path` — so `path` always holds either
    the previous complete snapshot or the new complete snapshot, never a
    torn hybrid (the POSIX rename-is-atomic contract). A `crc_json`
    member maps every array name to the CRC32 of its contiguous bytes;
    `load_verified_npz` recomputes and compares before any array is
    trusted."""
    named = {k: np.asarray(v) for k, v in arrays.items()}
    if _CRC_KEY in named:
        raise ValueError(f"array name {_CRC_KEY!r} is reserved")
    crcs = {
        k: zlib.crc32(np.ascontiguousarray(v).tobytes())
        for k, v in named.items()
    }
    named[_CRC_KEY] = np.frombuffer(
        json.dumps(crcs, sort_keys=True).encode(), dtype=np.uint8
    )
    # disk-pressure gate BEFORE any byte lands: uncompressed total is a
    # conservative ceiling on the compressed npz. On pressure this runs
    # the evict->compact ladder and raises DiskPressureError rather than
    # letting savez die mid-write with an ENOSPC-torn temp file
    diskpressure.preflight(
        path,
        sum(v.nbytes for v in named.values()),
        kind="checkpoint",
    )
    # the temp name must be unique PER WRITER, not per destination: a
    # hedged pool pair checkpoints the same unit path from two processes
    # concurrently, and a shared `<path>.tmp` lets one writer rename the
    # other's file away mid-flight (observed as FileNotFoundError on the
    # loser's os.replace)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **named)
            f.flush()
            os.fsync(f.fileno())
        # chaos durable-write site: a torn/fsync fault here dies BEFORE
        # the rename, proving `path` keeps its previous complete snapshot
        chaos.durable("checkpoint.write", path=tmp)
        os.replace(tmp, path)
        # fsync the directory so the rename itself survives power loss
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load_verified_npz(path: str) -> dict[str, np.ndarray]:
    """Load an npz fully into host memory, verifying the CRC manifest.

    Any read/decode failure (missing file is the exception — that stays
    FileNotFoundError so "no snapshot yet" and "bad snapshot" remain
    distinguishable) and any CRC mismatch raises CheckpointCorrupt.
    Files written before the manifest existed (no `crc_json`) load
    unverified — zipfile's own member CRCs still catch torn writes."""
    try:
        with np.load(path) as z:
            data = {k: np.asarray(z[k]) for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e})"
        ) from e
    if _CRC_KEY in data:
        try:
            crcs = json.loads(bytes(data.pop(_CRC_KEY)).decode())
        except Exception as e:
            raise CheckpointCorrupt(
                f"{path}: unreadable CRC manifest ({e})"
            ) from e
        for k, want in crcs.items():
            if k not in data:
                raise CheckpointCorrupt(
                    f"{path}: array {k!r} in CRC manifest is missing"
                )
            got = zlib.crc32(np.ascontiguousarray(data[k]).tobytes())
            if got != int(want):
                raise CheckpointCorrupt(
                    f"{path}: array {k!r} fails CRC32 "
                    f"(stored {int(want)}, recomputed {got})"
                )
    return data


def _require_format(z, path: str) -> None:
    """Loud typed rejection of any snapshot not written by this build's
    format. Older formats predate prefix-fork provenance (v6) and would
    resume with silently-missing fields; newer ones may reinterpret
    arrays. Either way the answer is the same: regenerate, don't guess."""
    got = int(z["format"]) if "format" in z else None
    if got != _FORMAT:
        raise ValueError(
            f"{path}: unsupported checkpoint format {got} (this build "
            f"reads format {_FORMAT} only — re-run to regenerate the "
            "snapshot)"
        )


def _str_field(z, key: str) -> str:
    """Decode an optional uint8-string npz member ('' when absent)."""
    return bytes(z[key]).decode() if key in z else ""


def _state_arrays(st: MachineState) -> dict[str, np.ndarray]:
    """Flatten the state pytree to npz-storable arrays: plain fields as
    `state_<name>`, nested NamedTuples (_NESTED) as
    `state_<name>__<sub>`."""
    arrays = {}
    for k, v in st._asdict().items():
        if isinstance(v, tuple(_NESTED.values())):
            for kk, vv in v._asdict().items():
                arrays[f"state_{k}__{kk}"] = np.asarray(vv)
        else:
            arrays[f"state_{k}"] = np.asarray(v)
    return arrays


def _state_from(z) -> MachineState:
    """Rebuild a MachineState from a v5 npz (inverse of _state_arrays)."""
    fields = {}
    for k in MachineState._fields:
        # nested-pytree fields are flattened, so the flat key is absent
        if k in _NESTED:
            typ = _NESTED[k]
            fields[k] = typ(
                **{
                    kk: jnp.asarray(z[f"state_{k}__{kk}"])
                    for kk in typ._fields
                }
            )
        else:
            fields[k] = jnp.asarray(z[f"state_{k}"])
    return MachineState(**fields)


def trace_fingerprint(trace) -> str:
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(trace.events).tobytes())
    h.update(np.ascontiguousarray(trace.lengths).tobytes())
    # addressing interpretation is part of the workload identity: the same
    # raw arrays read as byte- vs line-addressed are different workloads
    h.update(
        f"line_addressed={trace.line_addressed},{trace.line_bits}".encode()
    )
    return h.hexdigest()


def _payload_digest(arrays: dict, cycle_base, steps_run) -> str:
    """Self-digest over an element checkpoint's payload arrays, computed
    from the in-memory values BEFORE the bytes head to disk. The CRC
    manifest proves the file holds what was written; this proves what
    was written is what the engine held — the two together bracket the
    silent_corruption `checkpoint.payload` site (DESIGN.md §24)."""
    h = hashlib.sha256(b"ptckpt-attest1")
    h.update(np.int64(steps_run).tobytes())
    h.update(np.int64(cycle_base).tobytes())
    for k in sorted(arrays):
        h.update(k.encode())
        h.update(np.ascontiguousarray(arrays[k]).tobytes())
    return h.hexdigest()


def _attest_members(payload: dict | None) -> dict:
    """Optional attestation-chain members (DESIGN.md §24). Only emitted
    when the engine carries a chain, so --attest off checkpoints stay
    byte-identical to pre-attestation files."""
    if payload is None:
        return {}
    return {
        "attest_head": np.frombuffer(
            str(payload["head"]).encode(), dtype=np.uint8),
        "attest_chunks": np.int64(payload["chunks"]),
        "attest_start": np.int64(payload["start"]),
        "attest_chunk_steps": np.int64(payload["chunk_steps"]),
    }


def _attest_from(z) -> dict | None:
    if "attest_chunks" not in z:
        return None
    return {
        "head": _str_field(z, "attest_head"),
        "chunks": int(z["attest_chunks"]),
        "start": int(z["attest_start"]),
        "chunk_steps": int(z["attest_chunk_steps"]),
    }


def save_checkpoint(path: str, engine) -> None:
    """Snapshot an Engine mid-run (drains device counters first)."""
    engine._drain()
    arrays = _state_arrays(engine.state)
    arrays["host_counters"] = np.stack(
        [engine.host_counters[k] for k in COUNTER_NAMES]
    )
    atomic_save_npz(
        path,
        format=np.int64(_FORMAT),
        cycle_base=np.int64(engine.cycle_base),
        steps_run=np.int64(engine.steps_run),
        prefix_steps=np.int64(getattr(engine, "prefix_steps", 0) or 0),
        prefix_cache_key=np.frombuffer(
            str(getattr(engine, "prefix_cache_key", "") or "").encode(),
            dtype=np.uint8,
        ),
        config_json=np.frombuffer(
            engine.cfg.to_json().encode(), dtype=np.uint8
        ),
        trace_sha=np.frombuffer(
            trace_fingerprint(engine.trace).encode(), dtype=np.uint8
        ),
        **_attest_members(
            engine.attest.payload()
            if getattr(engine, "attest", None) is not None else None
        ),
        **arrays,
    )


def save_stream_checkpoint(path: str, eng) -> None:
    """Snapshot a StreamEngine at a window boundary (its consistent cut):
    the machine-state pytree plus the per-core stream cursors and 64-bit
    host accumulators. Valid whenever no device window is in flight —
    i.e. between `_advance_window` dispatches (`run_events` pauses
    there)."""
    arrays = _state_arrays(eng.state)
    arrays["host_counters"] = np.stack(
        [eng.host_counters[k] for k in COUNTER_NAMES]
    )
    atomic_save_npz(
        path,
        format=np.int64(_FORMAT),
        stream=np.int64(1),
        cycle_base=np.int64(eng.cycle_base),
        steps_run=np.int64(eng.steps_run),
        cursor=eng.cursor,
        window_events=np.int64(eng.W),
        config_json=np.frombuffer(eng.cfg.to_json().encode(), dtype=np.uint8),
        trace_sha=np.frombuffer(
            trace_fingerprint(eng.trace).encode(), dtype=np.uint8
        ),
        **_attest_members(
            eng.attest.payload()
            if getattr(eng, "attest", None) is not None else None
        ),
        **arrays,
    )


def load_stream_checkpoint(path: str, eng) -> None:
    """Restore a streaming snapshot into a freshly-built StreamEngine on
    the same config + trace (fingerprint-validated). Resuming then
    re-fills the window from the restored cursors — bit-exact with an
    uninterrupted run (tests/test_checkpoint.py)."""
    z = load_verified_npz(path)
    _require_format(z, path)
    if "stream" not in z:
        raise ValueError(f"{path}: not a compatible streaming checkpoint")
    if MachineConfig.from_json(bytes(z["config_json"]).decode()) != eng.cfg:
        raise ValueError(f"{path}: checkpoint config does not match engine")
    if bytes(z["trace_sha"]).decode() != trace_fingerprint(eng.trace):
        raise ValueError(f"{path}: checkpoint trace does not match engine")
    if int(z["window_events"]) != eng.W:
        raise ValueError(
            f"{path}: checkpoint window_events {int(z['window_events'])} "
            f"!= engine {eng.W} (windows must match for bit-exact resume)"
        )
    st = _state_from(z)
    if getattr(eng, "mesh", None) is not None:
        # restore the multi-chip layout StreamEngine.__init__ applies
        from ..parallel.sharding import shard_state

        st = shard_state(eng.mesh, st)
    eng.state = st
    eng.cursor = z["cursor"].astype(np.int64)
    eng.cycle_base = np.int64(z["cycle_base"])
    eng.steps_run = int(z["steps_run"])
    hc = z["host_counters"]
    eng.host_counters = {
        k: hc[i].astype(np.int64) for i, k in enumerate(COUNTER_NAMES)
    }
    if getattr(eng, "attest", None) is not None:
        eng.attest.seed(_attest_from(z), int(z["steps_run"]))


def load_checkpoint(path: str, engine) -> None:
    """Restore a snapshot into a freshly-constructed Engine.

    The engine must have been built with the same MachineConfig and Trace
    the checkpoint was taken under (validated by fingerprint).
    """
    z = load_verified_npz(path)
    _require_format(z, path)
    if "stream" in z:
        raise ValueError(
            f"{path}: streaming checkpoint — resume it with a StreamEngine"
        )
    if "fleet" in z:
        raise ValueError(
            f"{path}: fleet checkpoint — resume it with a FleetEngine"
        )
    if "element" in z:
        raise ValueError(
            f"{path}: per-job element checkpoint — splice it into a "
            "serving fleet (FleetEngine.restore_element)"
        )
    cfg_json = bytes(z["config_json"]).decode()
    if MachineConfig.from_json(cfg_json) != engine.cfg:
        raise ValueError(f"{path}: checkpoint config does not match engine config")
    sha = bytes(z["trace_sha"]).decode()
    if sha != trace_fingerprint(engine.trace):
        raise ValueError(f"{path}: checkpoint trace does not match engine trace")
    if z["state_counters"].shape[0] != len(COUNTER_NAMES):
        raise ValueError(
            f"{path}: checkpoint has {z['state_counters'].shape[0]} counter "
            f"rows but this build defines {len(COUNTER_NAMES)} — saved by an "
            "incompatible version"
        )
    st = _state_from(z)
    if engine.mesh is not None:
        # restore the multi-chip layout Engine.__init__ applies — without
        # this the full state materializes unsharded on one device
        from ..parallel.sharding import shard_state

        st = shard_state(engine.mesh, st)
    engine.state = st
    engine.cycle_base = np.int64(z["cycle_base"])
    engine.steps_run = int(z["steps_run"])
    engine.prefix_steps = int(z["prefix_steps"]) if "prefix_steps" in z else 0
    engine.prefix_cache_key = _str_field(z, "prefix_cache_key") or None
    hc = z["host_counters"]
    engine.host_counters = {
        k: hc[i].astype(np.int64) for i, k in enumerate(COUNTER_NAMES)
    }
    if getattr(engine, "attest", None) is not None:
        engine.attest.seed(_attest_from(z), int(z["steps_run"]))


def save_element_checkpoint(path: str, fleet, i: int, job_id: str = "",
                            trace=None) -> None:
    """Snapshot ONE fleet element solo-shaped — the serving daemon's
    per-JOB checkpoint record (DESIGN.md §14). A fleet chunk boundary is
    a consistent per-element cut (elements are mutually independent), so
    the saved state can later be spliced into ANY slot of ANY serving
    fleet on the same geometry (`FleetEngine.restore_element`) and resume
    bit-exactly — the slot number is not part of the job's identity.

    `trace` overrides the fingerprinted workload: the v2 paged allocator
    runs a job's leading WINDOW in a small bucket while the job's
    identity stays the FULL trace — its checkpoints must verify against
    the trace the job will resume with, not the window splice."""
    fleet._drain()
    arrays = _state_arrays(fleet.element_state(i))
    arrays["host_counters"] = np.stack(
        [fleet.host_counters[k][i] for k in COUNTER_NAMES]
    )  # [n_counters, C]
    at = (fleet.attest.payload(i)
          if getattr(fleet, "attest", None) is not None else None)
    extra = _attest_members(at)
    if at is not None:
        # the self-digest is taken from the in-memory values FIRST;
        # anything that mangles the payload after this point (the
        # silent_corruption site below, a DMA/disk fault in real life)
        # fails verification at load even though the CRC manifest —
        # computed over the already-corrupt bytes — passes
        extra["attest_payload_sha"] = np.frombuffer(
            _payload_digest(arrays, fleet.cycle_base[i],
                            fleet.steps_run[i]).encode(),
            dtype=np.uint8,
        )
    chaos.corrupt("checkpoint.payload",
                  {"host_counters": arrays["host_counters"]})
    pre = getattr(fleet, "prefix_steps", None)
    keys = getattr(fleet, "prefix_cache_keys", None)
    atomic_save_npz(
        path,
        format=np.int64(_FORMAT),
        element=np.int64(1),
        cycle_base=np.int64(fleet.cycle_base[i]),
        steps_run=np.int64(fleet.steps_run[i]),
        prefix_steps=np.int64(int(pre[i]) if pre is not None else 0),
        prefix_cache_key=np.frombuffer(
            str((keys[i] if keys is not None else "") or "").encode(),
            dtype=np.uint8,
        ),
        job_id=np.frombuffer(str(job_id).encode(), dtype=np.uint8),
        config_json=np.frombuffer(
            fleet.elem_cfgs[i].to_json().encode(), dtype=np.uint8
        ),
        trace_sha=np.frombuffer(
            trace_fingerprint(
                trace if trace is not None else fleet.traces[i]
            ).encode(),
            dtype=np.uint8,
        ),
        **extra,
        **arrays,
    )


def load_element_checkpoint(path: str, cfg, trace) -> dict:
    """Load a per-job element checkpoint, validated against the job's
    effective config + trace (fingerprints, same discipline as the solo
    loader). Returns the dict `FleetEngine.restore_element` consumes:
    solo-shaped state, 64-bit cycle base / step count, host counters."""
    z = load_verified_npz(path)
    _require_format(z, path)
    if "element" not in z:
        raise ValueError(f"{path}: not a compatible element checkpoint")
    if MachineConfig.from_json(bytes(z["config_json"]).decode()) != cfg:
        raise ValueError(f"{path}: checkpoint config does not match job")
    if bytes(z["trace_sha"]).decode() != trace_fingerprint(trace):
        raise ValueError(f"{path}: checkpoint trace does not match job")
    if z["state_counters"].shape[0] != len(COUNTER_NAMES):
        raise ValueError(
            f"{path}: checkpoint has {z['state_counters'].shape[0]} counter "
            f"rows but this build defines {len(COUNTER_NAMES)} — saved by an "
            "incompatible version"
        )
    if "attest_payload_sha" in z:
        from ..attest.errors import AttestationError

        arrays = {k: v for k, v in z.items() if k.startswith("state_")}
        arrays["host_counters"] = z["host_counters"]
        got = _payload_digest(arrays, z["cycle_base"], z["steps_run"])
        if got != _str_field(z, "attest_payload_sha"):
            raise AttestationError(
                f"{path}: checkpoint payload does not match its attest "
                "self-digest — the file verifies its CRC manifest but "
                "holds values the engine never committed (silent "
                "corruption between hash and write)",
                site="checkpoint.payload",
                unit=_str_field(z, "job_id"),
            )
    hc = z["host_counters"]
    return {
        "state": _state_from(z),
        "cycle_base": np.int64(z["cycle_base"]),
        "steps_run": np.int64(z["steps_run"]),
        "job_id": bytes(z["job_id"]).decode(),
        "prefix_steps": int(z["prefix_steps"]) if "prefix_steps" in z else 0,
        "prefix_cache_key": _str_field(z, "prefix_cache_key") or None,
        "host_counters": {
            k: hc[i].astype(np.int64) for i, k in enumerate(COUNTER_NAMES)
        },
        "attest": _attest_from(z),
    }


def save_fleet_checkpoint(path: str, fleet) -> None:
    """Snapshot a FleetEngine mid-run: the BATCHED state pytree (leading
    axis = fleet element), per-element 64-bit cycle bases and counter
    accumulators, and per-element config/trace fingerprints. Any chunk
    boundary is a consistent cut, exactly as for the solo engine."""
    fleet._drain()
    arrays = _state_arrays(fleet.state)
    arrays["host_counters"] = np.stack(
        [fleet.host_counters[k] for k in COUNTER_NAMES]
    )  # [n_counters, B, C]
    B = len(fleet.elem_cfgs)
    pre = getattr(fleet, "prefix_steps", None)
    if pre is None:
        pre = np.zeros(B, np.int64)
    keys = getattr(fleet, "prefix_cache_keys", None) or [None] * B
    atomic_save_npz(
        path,
        format=np.int64(_FORMAT),
        fleet=np.int64(1),
        cycle_base=fleet.cycle_base,  # [B] int64
        steps_run=fleet.steps_run,  # [B] int64
        prefix_steps=np.asarray(pre, np.int64),  # [B]
        prefix_keys_json=np.frombuffer(
            json.dumps([k or None for k in keys]).encode(), dtype=np.uint8
        ),
        configs_json=np.frombuffer(
            json.dumps(
                [json.loads(c.to_json()) for c in fleet.elem_cfgs]
            ).encode(),
            dtype=np.uint8,
        ),
        trace_shas=np.frombuffer(
            ",".join(trace_fingerprint(t) for t in fleet.traces).encode(),
            dtype=np.uint8,
        ),
        **(
            {"attest_json": np.frombuffer(
                json.dumps([
                    fleet.attest.payload(i) for i in range(B)
                ], sort_keys=True).encode(), dtype=np.uint8)}
            if getattr(fleet, "attest", None) is not None else {}
        ),
        **arrays,
    )


def load_fleet_checkpoint(path: str, fleet) -> None:
    """Restore a fleet snapshot into a freshly-built FleetEngine over the
    same per-element (config, trace) list — order included (the batch
    axis is positional). Resuming is bit-exact per element
    (tests/test_checkpoint.py)."""
    z = load_verified_npz(path)
    _require_format(z, path)
    if "fleet" not in z:
        raise ValueError(f"{path}: not a compatible fleet checkpoint")
    cfgs = [
        MachineConfig.from_dict(d)
        for d in json.loads(bytes(z["configs_json"]).decode())
    ]
    if cfgs != list(fleet.elem_cfgs):
        raise ValueError(
            f"{path}: checkpoint element configs do not match fleet"
        )
    shas = bytes(z["trace_shas"]).decode().split(",")
    if shas != [trace_fingerprint(t) for t in fleet.traces]:
        raise ValueError(
            f"{path}: checkpoint element traces do not match fleet"
        )
    if z["state_counters"].shape[1] != len(COUNTER_NAMES):
        raise ValueError(
            f"{path}: checkpoint has {z['state_counters'].shape[1]} counter "
            f"rows but this build defines {len(COUNTER_NAMES)} — saved by an "
            "incompatible version"
        )
    st = _state_from(z)
    if getattr(fleet, "mesh", None) is not None:
        # restore the shard x vmap layout FleetEngine.__init__ applies
        from ..parallel.sharding import shard_fleet_state

        st = shard_fleet_state(fleet.mesh, st)
    fleet.state = st
    fleet.cycle_base = z["cycle_base"].astype(np.int64)
    fleet.steps_run = z["steps_run"].astype(np.int64)
    if "prefix_steps" in z:
        fleet.prefix_steps = z["prefix_steps"].astype(np.int64)
    if "prefix_keys_json" in z:
        fleet.prefix_cache_keys = json.loads(
            bytes(z["prefix_keys_json"]).decode()
        )
    hc = z["host_counters"]
    fleet.host_counters = {
        k: hc[i].astype(np.int64) for i, k in enumerate(COUNTER_NAMES)
    }
    if getattr(fleet, "attest", None) is not None and "attest_json" in z:
        from ..attest import AttestChain

        for i, p in enumerate(json.loads(bytes(z["attest_json"]).decode())):
            if p and fleet.attest.chain(i) is not None:
                fleet.attest.chains[i] = AttestChain.from_payload(p)


# ---------------------------------------------------------------------------
# Warm-state cache (prefix forking, DESIGN.md §16)
#
# Content-addressed on-disk snapshots of a solo engine after P steps of a
# workload. An entry is valid for ANY run whose first P steps are provably
# identical to the producer's, which the key enforces by hashing exactly
# the inputs that can influence those steps:
#
#   - checkpoint format (state layout identity)
#   - trace fingerprint (events + lengths + addressing)
#   - normalized-geometry hash (cfg.timing_normalized().to_json() — core
#     count, cache shapes, mesh, model selectors, fault capacity/policies)
#   - timing-knob values (knobs_from_config leaves; traced, so not part
#     of the geometry hash)
#   - the fault-schedule PREFIX: scheduled events with step < P (an event
#     at step S fires while executing step index S, so a P-step run fires
#     exactly the events with step < P)
#   - the ECC block (seed + flip/due thresholds) ONLY when a flip rate is
#     nonzero — with both flip thresholds 0 the per-step site hashes are
#     never < threshold, so the seed is architecturally unreachable and
#     seed-varying sweep elements must share one entry
#   - P itself
#
# chunk_steps is deliberately NOT part of the key: every absolute
# observable after P steps is chunking-invariant (the cycle_base/cycles
# split differs by quantum-multiple rebases, but dynamics depend only on
# relative clocks).
# ---------------------------------------------------------------------------

_WARM_DEFAULT_MAX_BYTES = 2 << 30  # 2 GiB before LRU eviction kicks in


def warm_cache_root() -> str:
    """The warm-cache directory: $PRIMETPU_CACHE_DIR, or a per-user
    default under ~/.cache. Created on first use."""
    root = os.environ.get("PRIMETPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "primetpu", "warm"
    )
    os.makedirs(root, exist_ok=True)
    return root


def _geometry_hash(cfg) -> str:
    return hashlib.sha256(cfg.timing_normalized().to_json().encode()).hexdigest()


def _warm_payload(cfg, trace_fp: str) -> dict:
    """The step-count-independent part of the cache key (see module-level
    derivation note above)."""
    from .state import knobs_from_config

    kn = knobs_from_config(cfg)
    payload = {
        "format": _FORMAT,
        "trace": str(trace_fp),
        "geom": _geometry_hash(cfg),
        "knobs": {
            k: np.asarray(v).tolist() for k, v in kn._asdict().items()
        },
    }
    if (
        float(cfg.fault_flip_l1) > 0.0
        or float(cfg.fault_flip_llc) > 0.0
        or float(cfg.fault_due_rate) > 0.0
    ):
        payload["ecc"] = {
            "seed": int(cfg.fault_seed),
            "flip_l1": float(cfg.fault_flip_l1),
            "flip_llc": float(cfg.fault_flip_llc),
            "due_rate": float(cfg.fault_due_rate),
        }
    return payload


def warm_cfg_key(cfg, trace_fp: str) -> str:
    """Hash of the step-independent key inputs — the sidecar index key
    `find_warm_states` scans by."""
    blob = json.dumps(_warm_payload(cfg, trace_fp), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def warm_key(cfg, trace_fp: str, steps: int) -> str:
    """The full content-address of a warm entry: step-independent payload
    + the fault-schedule prefix (events with step < steps) + steps."""
    payload = _warm_payload(cfg, trace_fp)
    payload["events"] = sorted(
        tuple(int(x) for x in e)
        for e in getattr(cfg, "fault_events", ()) or ()
        if int(e[0]) < int(steps)
    )
    payload["steps"] = int(steps)
    blob = json.dumps(payload, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def _warm_paths(root: str, key: str) -> tuple[str, str]:
    return os.path.join(root, f"{key}.npz"), os.path.join(root, f"{key}.json")


def save_warm_state(root: str, cfg, trace_fp: str, steps: int, snap: dict) -> str:
    """Write a warm entry (atomic npz + JSON sidecar) and LRU-prune.

    `snap` is the restore_element-shaped dict a prefix run produces:
    {state, cycle_base, steps_run, host_counters}. Returns the key."""
    key = warm_key(cfg, trace_fp, steps)
    os.makedirs(root, exist_ok=True)
    npz_path, meta_path = _warm_paths(root, key)
    arrays = _state_arrays(snap["state"])
    arrays["host_counters"] = np.stack(
        [snap["host_counters"][k] for k in COUNTER_NAMES]
    )
    atomic_save_npz(
        npz_path,
        format=np.int64(_FORMAT),
        warm=np.int64(1),
        steps=np.int64(steps),
        cycle_base=np.int64(snap["cycle_base"]),
        steps_run=np.int64(snap["steps_run"]),
        trace_sha=np.frombuffer(str(trace_fp).encode(), dtype=np.uint8),
        **arrays,
    )
    meta = {
        "cfg_key": warm_cfg_key(cfg, trace_fp),
        "key": key,
        "trace_sha": str(trace_fp),
        "steps": int(steps),
    }
    # writer-unique temp name, same discipline as atomic_save_npz:
    # concurrent sweeps warming the same entry must not rename each
    # other's sidecar away mid-write
    fd, tmp = tempfile.mkstemp(
        dir=root, prefix=os.path.basename(meta_path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, meta_path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    prune_warm_cache(root)
    return key


def load_warm_state(root: str, key: str, cfg, trace_fp: str, steps: int) -> dict:
    """Load + verify a warm entry and return the restore/fork dict.

    Raises FileNotFoundError when absent (a plain miss), CheckpointCorrupt
    when the file is torn or tampered (the caller recomputes), and
    ValueError when the entry doesn't match the requested identity (a
    hash collision or a renamed file — also recompute)."""
    npz_path, _ = _warm_paths(root, key)
    z = load_verified_npz(npz_path)
    _require_format(z, npz_path)
    if "warm" not in z:
        raise ValueError(f"{npz_path}: not a warm-state cache entry")
    if int(z["steps"]) != int(steps):
        raise ValueError(
            f"{npz_path}: entry holds {int(z['steps'])} steps, wanted {steps}"
        )
    if bytes(z["trace_sha"]).decode() != str(trace_fp):
        raise ValueError(f"{npz_path}: entry trace does not match workload")
    if warm_key(cfg, trace_fp, steps) != key:
        raise ValueError(f"{npz_path}: entry key does not match workload")
    if z["state_counters"].shape[0] != len(COUNTER_NAMES):
        raise ValueError(
            f"{npz_path}: incompatible counter-row count "
            f"{z['state_counters'].shape[0]}"
        )
    try:
        now = None  # LRU touch: refresh mtime so eviction is usage-ordered
        os.utime(npz_path, now)
    except OSError:
        pass
    hc = z["host_counters"]
    return {
        "state": _state_from(z),
        "cycle_base": np.int64(z["cycle_base"]),
        "steps_run": np.int64(z["steps_run"]),
        "host_counters": {
            k: hc[i].astype(np.int64) for i, k in enumerate(COUNTER_NAMES)
        },
    }


def find_warm_states(root: str, cfg, trace_fp: str) -> list[tuple[int, str]]:
    """Scan the cache for entries reusable by (cfg, trace): sidecars whose
    cfg_key matches AND whose full key recomputes identically under this
    cfg (which checks the fault-schedule prefix below the entry's step
    count). Returns [(steps, key)] sorted deepest-first; unreadable
    sidecars are skipped (the npz CRC check still guards the load)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    want_cfg = warm_cfg_key(cfg, trace_fp)
    out = []
    for name in names:
        if not name.endswith(".json") or name.endswith(".json.tmp"):
            continue
        try:
            with open(os.path.join(root, name)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue
        if meta.get("cfg_key") != want_cfg:
            continue
        steps = int(meta.get("steps", 0))
        key = str(meta.get("key", ""))
        if steps > 0 and key and warm_key(cfg, trace_fp, steps) == key:
            out.append((steps, key))
    out.sort(key=lambda sk: (-sk[0], sk[1]))
    return out


def prune_warm_cache(root: str, max_bytes: int | None = None) -> int:
    """Evict least-recently-used entries until the cache fits under
    `max_bytes` (default $PRIMETPU_CACHE_MAX_BYTES or 2 GiB). Returns the
    number of entries removed. Hits refresh mtime, so mtime order IS use
    order.

    The budget is SHARED with the executable cache (§23): warm `.npz`
    entries in `root` and AOT `.bin` entries in `root/exec` form one
    LRU pool, so a burst of geometry sweeps can evict stale executables
    and vice versa — one knob bounds the whole cache tree.

    Budget resolution order: explicit `max_bytes` arg > the process-wide
    `--cache-budget` value (util.diskpressure.budget()) >
    $PRIMETPU_CACHE_MAX_BYTES > the 2 GiB default."""
    if max_bytes is None:
        max_bytes = diskpressure.budget()
    if max_bytes is None:
        max_bytes = int(
            os.environ.get("PRIMETPU_CACHE_MAX_BYTES", _WARM_DEFAULT_MAX_BYTES)
        )
    entries = []
    pools = [(root, ".npz")]
    exec_root = os.path.join(root, "exec")
    if os.path.isdir(exec_root):
        pools.append((exec_root, ".bin"))
    for pool_root, suffix in pools:
        try:
            names = os.listdir(pool_root)
        except OSError:
            continue
        for name in names:
            if not name.endswith(suffix):
                continue
            path = os.path.join(pool_root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path, suffix))
    total = sum(e[1] for e in entries)
    entries.sort()  # oldest first across BOTH pools
    removed = 0
    for mtime, size, path, suffix in entries:
        if total <= max_bytes:
            break
        for victim in (path, path[: -len(suffix)] + ".json"):
            try:
                os.unlink(victim)
            except OSError:
                pass
        total -= size
        removed += 1
    return removed
