"""FleetEngine — batch B independent simulations through ONE program.

Round-5 profiling (BENCH_r05.json) pinned the ~2.8 ms/step floor on the
step's SERIAL kernel-chain depth, not bytes: isolated gathers/scatters of
any tested shape cost ~0.02 ms, so each kernel launch is mostly idle
capacity. PriME's headline use case is throughput across many concurrent
runs (the ISPASS'14 multi-host aggregate bench.py baselines against), and
a parameter sweep is the common shape of that traffic. So: `jax.vmap` the
existing `run_chunk`/`run_loop` over a leading batch axis of B independent
simulations sharing one GEOMETRY (core count, cache shapes, mesh), and one
scan step retires one event per core *per simulation* at nearly the B=1
kernel-chain cost.

Two design points make a whole sweep ONE compilation:

- The per-simulation TIMING knobs (quantum, cpi, cache/NoC/DRAM latencies
  — `sim.state.TimingKnobs`) are TRACED, carried in `MachineState.knobs`
  and stacked over the batch axis. The static jit key is
  `cfg.timing_normalized()`: every timing variant of one geometry hits the
  same cache entry.
- Termination: `jax.vmap` of `lax.while_loop` runs the body while ANY
  element's cond holds and SELECT-masks the carry, so finished elements
  FREEZE at their own chunk boundary — exactly where a solo `run_loop`
  with the same `chunk_steps` stops. Fleet element i is therefore
  bit-exact with a solo `Engine` run of the same (config, trace),
  including the step counter (tests/test_fleet.py).

Scope: preloaded traces only. Streamed (windowed) ingest stays solo — the
host-side window refill rate is per-element state, and batching it buys
nothing while any element's refill stalls the fleet (see DESIGN.md §6).
`pallas_reduce` configs are rejected: the Pallas kernel bakes link/router
latencies in as static kernel params.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import sites as chaos
from ..config.machine import MachineConfig
from ..stats.counters import COUNTER_NAMES
from ..trace.format import EV_BARRIER, EV_END, EV_LOCK, EV_UNLOCK, Trace
from . import exec_cache
from .engine import _ACC_BITS, _np, run_chunk, run_loop
from .state import MachineState, init_state


def idle_trace(n_cores: int) -> Trace:
    """The empty workload: every core's trace is a single END event, so
    the element is done before its first step. Free slots in a serving
    fleet (serve/scheduler.py) hold this trace — the vmapped step is a
    no-op for them while live slots advance."""
    events = np.zeros((n_cores, 1, 4), np.int32)
    events[:, :, 0] = EV_END
    return Trace(events, np.ones(n_cores, np.int32))


def _trace_per_step_bound(cfg: MachineConfig, trace: Trace) -> int:
    """Worst-case per-step instruction-counter increment for one trace
    (the Engine/FleetEngine accumulator-overflow bound)."""
    per_ev = max(
        1,
        int(trace.events[:, :, 1].max(initial=0)),
        int(trace.events[:, :, 3].max(initial=0)) + 1,
    )
    return (cfg.local_run_len + 1) * per_ev

#: Override keys `apply_overrides` accepts — the TimingKnobs fields, named
#: as a user would write them in a sweep spec, plus `fault_seed` (not a
#: TimingKnob — it seeds the traced FaultState — but traced all the same,
#: so `sweep --vary fault_seed` shares one compilation per geometry).
KNOB_KEYS = (
    "quantum",
    "cpi",
    "l1_lat",
    "llc_lat",
    "link_lat",
    "router_lat",
    "dram_lat",
    "dram_service",
    "contention_lat",
    "prefetch_degree",
    "prefetch_lat",
    "fault_seed",
)


def apply_overrides(cfg: MachineConfig, ov: dict | None) -> MachineConfig:
    """A copy of `cfg` with the timing overrides `ov` applied — the
    element's EFFECTIVE config (a solo Engine on it reproduces the fleet
    element exactly). Keys are KNOB_KEYS; `cpi` takes an int (homogeneous)
    or a length-n_cores sequence. Validation runs via the dataclass
    constructors, plus the conflict-key packing bound on quantum."""
    ov = dict(ov or {})
    unknown = sorted(set(ov) - set(KNOB_KEYS))
    if unknown:
        raise ValueError(
            f"unknown timing override(s) {unknown}; valid keys: {KNOB_KEYS}"
        )
    out = cfg
    if "quantum" in ov:
        out = dataclasses.replace(out, quantum=int(ov["quantum"]))
    if "cpi" in ov:
        v = ov["cpi"]
        if isinstance(v, (int, np.integer)):
            core = dataclasses.replace(
                out.core, cpi=int(v), cpi_per_core=None, cpi_pattern=None
            )
        else:
            core = dataclasses.replace(
                out.core,
                cpi_per_core=tuple(int(x) for x in v),
                cpi_pattern=None,
            )
        out = dataclasses.replace(out, core=core)
    if "l1_lat" in ov:
        out = dataclasses.replace(
            out, l1=dataclasses.replace(out.l1, latency=int(ov["l1_lat"]))
        )
    if "llc_lat" in ov:
        out = dataclasses.replace(
            out, llc=dataclasses.replace(out.llc, latency=int(ov["llc_lat"]))
        )
    noc_kw = {
        k: int(ov[k])
        for k in ("link_lat", "router_lat", "contention_lat")
        if k in ov
    }
    if noc_kw:
        out = dataclasses.replace(
            out, noc=dataclasses.replace(out.noc, **noc_kw)
        )
    if "dram_lat" in ov:
        out = dataclasses.replace(out, dram_lat=int(ov["dram_lat"]))
    if "dram_service" in ov:
        out = dataclasses.replace(out, dram_service=int(ov["dram_service"]))
    if "prefetch_degree" in ov:
        out = dataclasses.replace(
            out, prefetch_degree=int(ov["prefetch_degree"])
        )
    if "prefetch_lat" in ov:
        out = dataclasses.replace(out, prefetch_lat=int(ov["prefetch_lat"]))
    if "fault_seed" in ov:
        out = dataclasses.replace(out, fault_seed=int(ov["fault_seed"]))
    if out.quantum * out.n_cores >= 2**31:
        raise ValueError(
            "quantum * n_cores must be < 2^31 (conflict-key packing); "
            f"got {out.quantum} * {out.n_cores}"
        )
    return out


@functools.partial(
    jax.jit, static_argnums=(0, 1), static_argnames=("has_sync",)
)
def fleet_run_chunk(
    cfg: MachineConfig, n_steps: int, events, st: MachineState,
    has_sync: bool = True,
):
    """`run_chunk` vmapped over the leading batch axis. `cfg` must be the
    TIMING-NORMALIZED geometry config — timing comes from st.knobs."""
    return jax.vmap(
        lambda ev, s: run_chunk(cfg, n_steps, ev, s, has_sync=has_sync)
    )(events, st)


@functools.partial(
    jax.jit, static_argnums=(0, 1), static_argnames=("has_sync",)
)
def fleet_run_loop(
    cfg: MachineConfig, chunk_steps: int, events, st: MachineState,
    max_chunks, has_sync: bool = True,
):
    """`run_loop` vmapped over the leading batch axis: one dispatched
    device program for a whole FLEET run. Per-element drain/rebase and
    termination come out of the vmap for free — the while_loop cond
    batches to any(live) and the carry select-masks, so each element's
    (state, counter accumulators, cycle base, chunk count) freezes the
    moment it finishes."""
    return jax.vmap(
        lambda ev, s: run_loop(
            cfg, chunk_steps, ev, s, max_chunks, has_sync=has_sync
        )
    )(events, st)


class FleetEngine:
    """Host runner for a batch of independent simulations on one geometry.

    Elements may differ in TRACE and in the traced TIMING knobs
    (per-element `overrides` dicts, see KNOB_KEYS); everything else —
    geometry and model selectors — comes from the shared `cfg`. The
    public surface mirrors `Engine`, batched: `cycles` is [B, C],
    `counters` maps name -> [B, C], and `element_*` accessors slice out
    solo-shaped views.
    """

    def __init__(
        self,
        cfg: MachineConfig,
        traces: list[Trace],
        overrides: list[dict] | None = None,
        chunk_steps: int = 256,
        min_events_capacity: int = 0,
        force_sync: bool = False,
        mesh=None,
    ):
        if cfg.pallas_reduce:
            raise ValueError(
                "FleetEngine does not support pallas_reduce configs: the "
                "Pallas reduction kernel takes link/router latencies as "
                "static kernel parameters, which defeats the fleet's "
                "traced-knob compilation sharing"
            )
        traces = list(traces)
        if not traces:
            raise ValueError("FleetEngine needs at least one trace")
        if overrides is None:
            overrides = [{}] * len(traces)
        overrides = list(overrides)
        if len(overrides) != len(traces):
            raise ValueError(
                f"got {len(traces)} traces but {len(overrides)} override "
                "dicts (must match 1:1)"
            )
        B = len(traces)
        C = cfg.n_cores
        self.cfg = cfg
        # effective per-element configs (a solo Engine on elem_cfgs[i] +
        # traces[i] reproduces element i bit-exactly); building them also
        # validates every override combination
        self.elem_cfgs = [apply_overrides(cfg, ov) for ov in overrides]
        # the static jit key: one compilation per GEOMETRY
        self.geom_cfg = cfg.timing_normalized()
        self.traces = traces
        from ..trace.format import validate_sync

        has_sync = False
        for t in traces:
            if t.n_cores != C:
                raise ValueError(
                    f"trace has {t.n_cores} cores, config {C}"
                )
            validate_sync(t, cfg.barrier_slots)
            ty = t.events[:, :, 0]
            has_sync = has_sync or bool(
                ((ty == EV_LOCK) | (ty == EV_UNLOCK) | (ty == EV_BARRIER)).any()
            )
        # static specialization is shared: ANY element with sync events
        # turns phase 2.7 on for the whole fleet (a no-op for the others).
        # `force_sync` pins it True so a serving fleet's compiled program
        # never depends on which jobs happen to occupy its slots.
        self.has_sync = has_sync or force_sync
        # events: per-element line-event arrays END-padded to a common T
        # and stacked [B, C, T, 4] (END padding is the format's own
        # convention — engines clamp ptr to T-1). `min_events_capacity`
        # reserves slack so traces up to that length can be SPLICED in
        # later (replace_element) without changing the compiled shape.
        T = max(max(t.max_len for t in traces), int(min_events_capacity))
        evs = []
        for t in traces:
            e = np.asarray(t.line_events(cfg.line_bits))
            if e.shape[1] < T:
                pad = np.zeros((C, T - e.shape[1], 4), e.dtype)
                pad[:, :, 0] = EV_END
                e = np.concatenate([e, pad], axis=1)
            evs.append(e)
        self._events_np = np.stack(evs)
        self.events = jnp.asarray(self._events_np)
        # state: stack the elements' solo init states — init_state(elem
        # cfg) already seeds knobs and quantum_end from the element's
        # effective timing
        states = [init_state(c) for c in self.elem_cfgs]
        self.state = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        self.chunk_steps = chunk_steps
        # same per-chunk counter-accumulator bound as Engine, over the
        # worst event of ANY element
        per_step = max(_trace_per_step_bound(cfg, t) for t in traces)
        if chunk_steps * per_step >= 1 << _ACC_BITS:
            raise ValueError(
                f"chunk_steps={chunk_steps} x max per-step instruction "
                f"increment {per_step} overflows the 2^{_ACC_BITS} "
                "per-chunk counter accumulator; lower chunk_steps or split "
                "large INS batches"
            )
        self.cycle_base = np.zeros(B, np.int64)
        self.host_counters = {
            k: np.zeros((B, C), np.int64) for k in COUNTER_NAMES
        }
        self.steps_run = np.zeros(B, np.int64)
        # original (caller-side) index of each batch position; the fault
        # isolation builder (sim.supervisor.build_fleet_isolated) rewrites
        # this after quarantining elements so reports keep caller indices
        self.element_ids = list(range(B))
        self.element_overrides = [dict(ov) for ov in overrides]
        # telemetry sink (obs.Recorder) — None skips every telemetry
        # branch in the chunked loops; fleet_run_loop never consults it
        self.obs = None
        self.obs_label = "fleet"
        # attestation chains (attest.FleetAttest) — None means chunks are
        # never fingerprinted (DESIGN.md §24); per-element chains advance
        # only for elements live at chunk start, matching the solo loop
        self.attest = None
        # prefix-fork provenance (checkpoint format v6): steps of shared
        # prefix each element was forked from, and the warm-cache key the
        # prefix was saved/loaded under (None = element ran from step 0)
        self.prefix_steps = np.zeros(B, np.int64)
        self.prefix_cache_keys: list = [None] * B
        # shard x vmap (DESIGN.md §22): each element's cores/banks lay out
        # over the mesh's "tiles" axis UNDER the batch vmap (batch dim
        # replicated, per-element layout = the solo state_pspecs). Like the
        # solo Engine, only the INPUTS are placed — the compiled loops'
        # output shardings follow by propagation, which the multichip
        # parity/HLO suites prove is both bit-exact and all-gather-free.
        self.mesh = mesh
        if mesh is not None:
            self._reshard()
        # overlapped chunk dispatch (§23), mirroring Engine: speculate
        # chunk k+1 from the committed state before the caller's host-side
        # durability work; identity of the source state object validates
        # the speculation (element surgery / restore / reshard all
        # reassign self.state, invalidating it automatically)
        self.overlap = False
        self._pending = None

    def _reshard(self) -> None:
        """Re-place events and state on the fleet mesh layout. Called at
        init and after any host-side state surgery (splice/restore/fork)
        whose `.at[i].set` output sharding is not guaranteed to match."""
        from ..parallel.sharding import shard_fleet_events, shard_fleet_state

        self.events = shard_fleet_events(self.mesh, self.events)
        self.state = shard_fleet_state(self.mesh, self.state)

    # ---- batched bookkeeping (Engine's host helpers, vectorized) ---------

    @property
    def n_elements(self) -> int:
        return len(self.traces)

    def _drain(self) -> None:
        cnt = _np(self.state.counters)  # [B, n_counters, C]
        for i, k in enumerate(COUNTER_NAMES):
            self.host_counters[k] += cnt[:, i].astype(np.int64)
        self.state = self.state._replace(
            counters=jnp.zeros_like(self.state.counters)
        )

    def _event_types_at_ptr(self) -> np.ndarray:
        """[B, C] event type codes under each element's trace pointer
        (reads the padded host copy — END padding included)."""
        p = np.minimum(_np(self.state.ptr), self._events_np.shape[2] - 1)
        B, C = p.shape
        return self._events_np[
            np.arange(B)[:, None], np.arange(C)[None, :], p, 0
        ]

    def _dead_mask(self) -> np.ndarray:
        """[B, C] bool — fail-stopped cores (all-False with faults off);
        same contract as Engine._dead_mask, batched."""
        if self.cfg.faults_enabled:
            return _np(self.state.faults.core_dead) != 0
        return np.zeros((self.n_elements, self.cfg.n_cores), bool)

    def done_mask(self) -> np.ndarray:
        return self.core_done_mask().all(axis=1)

    def done(self) -> bool:
        return bool(self.done_mask().all())

    def core_done_mask(self) -> np.ndarray:
        """[B, C] bool — per-element per-core END-or-dead mask (guard
        input; a fail-stopped core never reaches END)."""
        return (self._event_types_at_ptr() == EV_END) | self._dead_mask()

    def live_mask(self) -> np.ndarray:
        """[B, C] bool — cores bounding each element's quantum window:
        not at END, not frozen at a barrier, not fail-stopped (same
        contract as Engine.live_mask, batched)."""
        et = self._event_types_at_ptr()
        frozen = (et == EV_BARRIER) & (_np(self.state.sync_flag) != 0)
        return (et != EV_END) & ~frozen & ~self._dead_mask()

    def _rebase(self) -> None:
        """Per-element host rebase (run_steps path; `run` rebases on
        device): shift each live element's epoch-relative clocks down by
        a multiple of ITS quantum."""
        cyc = _np(self.state.cycles)  # [B, C]
        nd = (self._event_types_at_ptr() != EV_END) & ~self._dead_mask()
        quanta = np.asarray([c.quantum for c in self.elem_cfgs], np.int64)
        m = np.where(nd, cyc, np.iinfo(np.int32).max).min(axis=1)
        delta = np.where(nd.any(axis=1), (m // quanta) * quanta, 0)
        delta = np.maximum(delta, 0)
        if not (delta > 0).any():
            return
        self.cycle_base += delta
        d = jnp.asarray(delta.astype(np.int32))  # [B]
        st = self.state
        self.state = st._replace(
            cycles=st.cycles - d[:, None],
            quantum_end=st.quantum_end - d,
            barrier_time=jnp.where(
                st.barrier_count > 0,
                st.barrier_time - d[:, None],
                st.barrier_time,
            ),
            link_free=(
                jnp.maximum(st.link_free - d[:, None], -(1 << 30))
                if self.cfg.noc.contention
                and self.cfg.noc.contention_model == "router"
                else st.link_free
            ),
            dram_free=(
                jnp.maximum(st.dram_free - d[:, None], -(1 << 30))
                if self.cfg.dram_queue
                else st.dram_free
            ),
        )

    # ---- run -------------------------------------------------------------

    def run(self, max_steps: int = 10_000_000) -> None:
        """Run every element to completion in ONE device dispatch."""
        max_chunks = -(-max_steps // self.chunk_steps)
        st, acc_lo, acc_hi, base_lo, base_hi, k = exec_cache.call(
            fleet_run_loop, "fleet.run_loop",
            (self.geom_cfg, self.chunk_steps),
            (self.events, self.state, jnp.asarray(max_chunks, jnp.int32)),
            {"has_sync": self.has_sync},
        )
        acc_lo = _np(acc_lo).astype(np.int64)  # [B, n_counters, C]
        acc_hi = _np(acc_hi).astype(np.int64)
        total = (acc_hi << _ACC_BITS) + acc_lo
        for i, name in enumerate(COUNTER_NAMES):
            self.host_counters[name] += total[:, i]
        self.cycle_base += (
            _np(base_hi).astype(np.int64) << _ACC_BITS
        ) + _np(base_lo).astype(np.int64)
        self.state = st
        self.steps_run += _np(k).astype(np.int64) * self.chunk_steps
        if not self.done():
            bad = np.flatnonzero(~self.done_mask()).tolist()
            raise RuntimeError(
                f"fleet: max_steps exceeded on element(s) {bad} (deadlock?)"
            )

    def run_steps(self, n_steps: int) -> None:
        """Advance every LIVE element by `n_steps` (whole chunks) without
        the completion check — the checkpointed-run building block.

        Unlike `run` (whose batched while_loop select-masks finished
        elements), the plain vmapped scan steps EVERY element; a finished
        element's steps are no-ops except the `step` counter (phase 0
        proves quantum_end cannot bump once every core sits at END), so
        its machine state stays bit-exact while `state.step` may run
        ahead of a solo engine's."""
        target = int(self.steps_run.max()) + n_steps
        while int(self.steps_run.max()) < target and not self.done():
            self._chunk_once()

    def _chunk_once(self) -> None:
        """One committed chunk: dispatch, drain counters, rebase clocks
        (shared by run_steps and the serving tick's step_chunk)."""
        live = ~self.done_mask()
        if self.obs is None:
            self._dispatch_chunk()
            self.steps_run += np.where(live, self.chunk_steps, 0)
            self._drain()
            self._corrupt_hook()
            self._rebase()
            if self.attest is not None:
                self.attest.observe(self, live)
            if self.overlap and not self.done():
                self._prefetch_chunk()
            return
        # phase cuts mirror Engine.run_steps: dispatch = async enqueue,
        # drain = synchronizing transfer (includes device execution),
        # rebase = host clock bookkeeping
        t0 = time.perf_counter()
        self._dispatch_chunk()
        t1 = time.perf_counter()
        self.steps_run += np.where(live, self.chunk_steps, 0)
        self._drain()
        self._corrupt_hook()
        t2 = time.perf_counter()
        self._rebase()
        t3 = time.perf_counter()
        phases = {"dispatch": t1 - t0, "drain": t2 - t1, "rebase": t3 - t2}
        if self.attest is not None:
            self.attest.observe(self, live)
        if self.overlap and not self.done():
            self._prefetch_chunk()
            phases["prefetch"] = time.perf_counter() - t3
        self.obs.chunk_committed(
            self.obs_label, self.chunk_steps, t3 - t0, self.host_counters,
            phases=phases,
        )

    def _corrupt_hook(self) -> None:
        """silent_corruption site `fleet.counters` (DESIGN.md §24): a
        flip lands AFTER drain and BEFORE the chunk is fingerprinted,
        so the chain honestly covers the corrupted data — exactly what
        a flaky DIMM does. Detection is attestation's cross-execution
        compare, never this process."""
        chaos.corrupt("fleet.counters", self.host_counters)

    def _dispatch_chunk(self) -> None:
        """Advance self.state by one chunk, consuming the prefetched
        result when it was speculated from exactly this state object at
        this chunk size (Engine._dispatch_chunk, batched)."""
        pend, self._pending = self._pending, None
        if (
            pend is not None
            and pend[0] is self.state
            and pend[2] == self.chunk_steps
        ):
            self.state = pend[1]
            return
        self.state = exec_cache.call(
            fleet_run_chunk, "fleet.run_chunk",
            (self.geom_cfg, self.chunk_steps), (self.events, self.state),
            {"has_sync": self.has_sync},
        )

    def _prefetch_chunk(self) -> None:
        src = self.state
        nxt = exec_cache.call(
            fleet_run_chunk, "fleet.run_chunk",
            (self.geom_cfg, self.chunk_steps), (self.events, src),
            {"has_sync": self.has_sync},
        )
        self._pending = (src, nxt, self.chunk_steps)

    def discard_prefetch(self) -> None:
        self._pending = None

    def warm_exec(self) -> bool:
        """Load-or-compile this fleet's chunk executable through the
        active exec cache WITHOUT running it — the pool worker calls this
        at lease grant so a cache hit pays deserialization (not XLA
        compile) before the first chunk, and compile never eats lease
        TTL. No-op (False) when no cache is active."""
        cache = exec_cache.active()
        if cache is None:
            return False
        return cache.ensure(
            fleet_run_chunk, "fleet.run_chunk",
            (self.geom_cfg, self.chunk_steps), (self.events, self.state),
            {"has_sync": self.has_sync},
        )

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.events)
        jax.block_until_ready(self.state)

    # ---- results ---------------------------------------------------------

    @property
    def cycles(self) -> np.ndarray:
        """[B, C] absolute core clocks."""
        return (
            _np(self.state.cycles).astype(np.int64)
            + self.cycle_base[:, None]
        )

    @property
    def counters(self) -> dict[str, np.ndarray]:
        """name -> [B, C] int64."""
        self._drain()
        return self.host_counters

    def element_state(self, i: int) -> MachineState:
        """Element i's machine state, solo-shaped (batch axis sliced)."""
        return jax.tree.map(lambda x: x[i], self.state)

    def element_counters(self, i: int) -> dict[str, np.ndarray]:
        self._drain()
        return {k: v[i] for k, v in self.host_counters.items()}

    # ---- checkpoint / resume --------------------------------------------

    def save_checkpoint(self, path: str) -> None:
        from .checkpoint import save_fleet_checkpoint

        save_fleet_checkpoint(path, self)

    def load_checkpoint(self, path: str) -> None:
        from .checkpoint import load_fleet_checkpoint

        load_fleet_checkpoint(path, self)

    # ---- slot splice / retire (continuous batching; serve/) --------------

    @classmethod
    def make_slots(
        cls,
        cfg: MachineConfig,
        n_slots: int,
        capacity_events: int,
        chunk_steps: int = 256,
        mesh=None,
    ) -> "FleetEngine":
        """An all-idle serving fleet: `n_slots` elements holding the empty
        workload (`idle_trace`), with event storage reserved for traces up
        to `capacity_events` per core. Jobs are spliced into free slots
        with `replace_element` and retired with `clear_element`; the
        compiled program (geometry, [B, C, T] shapes, has_sync=True) never
        changes across the fleet's whole service lifetime."""
        return cls(
            cfg,
            [idle_trace(cfg.n_cores)] * n_slots,
            chunk_steps=chunk_steps,
            min_events_capacity=capacity_events,
            force_sync=True,
            mesh=mesh,
        )

    @property
    def events_capacity(self) -> int:
        """Per-core event-slot capacity (the padded T of the compiled
        shape) — the longest trace `replace_element` accepts."""
        return int(self._events_np.shape[2])

    def replace_element(
        self,
        i: int,
        trace: Trace,
        override: dict | None = None,
        base_cfg: MachineConfig | None = None,
        upload: bool = True,
    ) -> None:
        """Splice a new (trace, override) workload into batch position `i`
        without touching any other element: rewrite the element's event
        row (END-padded to the fleet capacity), reset its machine state to
        `init_state` of its effective config, and zero its host
        accumulators. The compiled program is untouched — geometry, shapes
        and `has_sync` are all static — so admission never recompiles.

        `base_cfg` (default: the fleet's own config) lets a server admit
        under a RELOADED traced-knob config (e.g. a SIGHUP-refreshed fault
        schedule); it must normalize to the fleet's geometry key.

        `upload=False` defers the host->device events copy so a batch of
        splices in one scheduling tick pays for ONE `upload_events()`."""
        from ..trace.format import validate_sync

        ov = dict(override or {})
        ecfg = apply_overrides(base_cfg or self.cfg, ov)
        if ecfg.timing_normalized() != self.geom_cfg:
            raise ValueError(
                "replace_element: effective config does not share this "
                "fleet's compiled geometry"
            )
        if trace.n_cores != self.cfg.n_cores:
            raise ValueError(
                f"trace has {trace.n_cores} cores, config {self.cfg.n_cores}"
            )
        validate_sync(trace, self.cfg.barrier_slots)
        e = np.asarray(trace.line_events(self.cfg.line_bits))
        T = self.events_capacity
        if e.shape[1] > T:
            raise ValueError(
                f"trace needs {e.shape[1]} event slots/core but this "
                f"fleet's capacity is {T}"
            )
        per_step = _trace_per_step_bound(self.cfg, trace)
        if self.chunk_steps * per_step >= 1 << _ACC_BITS:
            raise ValueError(
                f"chunk_steps={self.chunk_steps} x max per-step "
                f"instruction increment {per_step} overflows the "
                f"2^{_ACC_BITS} per-chunk counter accumulator"
            )
        row = np.zeros((self.cfg.n_cores, T, 4), np.int32)
        row[:, :, 0] = EV_END
        row[:, : e.shape[1]] = e
        self._events_np[i] = row
        self.traces[i] = trace
        self.elem_cfgs[i] = ecfg
        self.element_overrides[i] = ov
        # flush the previous occupant's device counters before its state
        # row is overwritten (harvest reads host_counters afterwards)
        self._drain()
        solo = init_state(ecfg)
        self.state = jax.tree.map(
            lambda b, s: b.at[i].set(s), self.state, solo
        )
        self.cycle_base[i] = 0
        self.steps_run[i] = 0
        self.prefix_steps[i] = 0
        self.prefix_cache_keys[i] = None
        for k in self.host_counters:
            self.host_counters[k][i] = 0
        # a new occupant never inherits the previous job's chain; the
        # owner re-tracks the slot if the new workload is attested
        if self.attest is not None:
            self.attest.drop(i)
        if self.mesh is not None:
            self._reshard()
        if upload:
            self.upload_events()

    def clear_element(self, i: int, upload: bool = True) -> None:
        """Retire batch position `i` back to the idle workload (done at
        step 0): the slot stops contributing work to the vmapped step and
        is ready for the next `replace_element`."""
        self.replace_element(i, idle_trace(self.cfg.n_cores), upload=upload)

    def restore_element(self, i: int, snap: dict) -> None:
        """Load an element checkpoint (checkpoint.load_element_checkpoint)
        into batch position `i`. Call `replace_element(i, trace, override)`
        with the SAME workload first — this only overlays the mid-run
        machine state and 64-bit host accumulators, making the resumed
        element bit-exact with one that was never interrupted."""
        self.state = jax.tree.map(
            lambda b, s: b.at[i].set(jnp.asarray(s)),
            self.state,
            snap["state"],
        )
        self.cycle_base[i] = snap["cycle_base"]
        self.steps_run[i] = snap["steps_run"]
        for k in COUNTER_NAMES:
            self.host_counters[k][i] = snap["host_counters"][k]
        if self.mesh is not None:
            self._reshard()

    def fork_element(self, i: int, snap: dict, cache_key: str | None = None) -> None:
        """Fork batch position `i` from a shared-prefix snapshot: overlay
        the snapshot's mid-run machine state (restore_element), then RESEED
        the per-element traced inputs from the element's OWN effective
        config — timing knobs and the FaultState schedule/seed/ECC
        thresholds — while keeping the snapshot's TRAJECTORY state
        (dead-core / dead-link / degrade masks, which record events that
        already fired during the prefix).

        The caller (sim.prefix) guarantees the snapshot's step count is at
        or below the element's divergence point, so the inputs being
        swapped in could not have influenced any state the snapshot
        carries: the forked element is bit-exact with an unforked run.
        Events with step < steps_run never re-fire (firing matches the
        absolute step index), so resetting the schedule arrays wholesale
        is safe. Call `replace_element(i, trace, override)` with the
        element's workload first, exactly as for `restore_element`."""
        from ..faults.schedule import fault_state_from_config
        from .state import knobs_from_config

        self.restore_element(i, snap)
        ecfg = self.elem_cfgs[i]
        fresh = fault_state_from_config(ecfg)
        faults = jax.tree.map(lambda x: x[i], self.state.faults)._replace(
            seed=fresh.seed,
            ev_step=fresh.ev_step,
            ev_kind=fresh.ev_kind,
            ev_a=fresh.ev_a,
            ev_b=fresh.ev_b,
            flip_l1=fresh.flip_l1,
            flip_llc=fresh.flip_llc,
            due_rate=fresh.due_rate,
        )
        self.state = self.state._replace(
            knobs=jax.tree.map(
                lambda b, s: b.at[i].set(jnp.asarray(s)),
                self.state.knobs,
                knobs_from_config(ecfg),
            ),
            faults=jax.tree.map(
                lambda b, s: b.at[i].set(jnp.asarray(s)),
                self.state.faults,
                faults,
            ),
        )
        self.prefix_steps[i] = int(snap["steps_run"])
        self.prefix_cache_keys[i] = cache_key
        if self.mesh is not None:
            self._reshard()

    def upload_events(self) -> None:
        """Push the host event array (mutated by splices) to the device.
        One call covers any number of `upload=False` splices."""
        self.events = jnp.asarray(self._events_np)
        if self.mesh is not None:
            from ..parallel.sharding import shard_fleet_events

            self.events = shard_fleet_events(self.mesh, self.events)

    def step_chunk(self) -> None:
        """Advance the whole batch by exactly ONE committed chunk (the
        serving tick): dispatch, drain counters, rebase clocks. Finished
        and idle elements freeze (their steps_run stays put)."""
        self._chunk_once()
