"""Observable-state helpers for the pull-based engine (NumPy, host-side).

The vectorized engine keeps only locally-written L1 state and derives each
way's effective MESI state from the directory on access (engine.py phase 1).
`effective_l1_state` re-derives that mapping on host arrays so tests and
debug invariants can compare the engine's *observable* cache contents
against the eager golden model bit-for-bit: at every (core, set, way) the
golden's eagerly-maintained state must equal the engine's derived state,
and tags must agree wherever the golden holds a valid line.
"""

from __future__ import annotations

import numpy as np

from ..config.machine import MachineConfig
from .state import E, I, M, S  # noqa: F401  (shared MESI encoding)


def engine_l1_to_golden(cfg: MachineConfig, arr: np.ndarray) -> np.ndarray:
    """Reshape an engine L1 array [C, W1*S1] to golden layout [C, S1, W1]."""
    C = arr.shape[0]
    W1, S1 = cfg.l1.ways, cfg.l1.sets
    return np.transpose(arr.reshape(C, W1, S1), (0, 2, 1))


def effective_l1_state(
    cfg: MachineConfig,
    l1_tag: np.ndarray,  # [C, W1*S1] (engine layout, way-major columns)
    l1_state: np.ndarray,  # [C, W1*S1] locally-written MESI
    llc_tag: np.ndarray,  # [B, S2, W2]
    llc_owner: np.ndarray,  # [B, S2, W2]
    sharers: np.ndarray,  # [B*S2, W2*NW] packed rows (engine layout)
) -> np.ndarray:
    """Directory-validated MESI state per L1 way (engine phase-1 rule).

    Accepts the engine's flattened way-major L1 layout and returns the
    validated states in the golden model's [C, S1, W1] layout.
    """
    l1_tag = engine_l1_to_golden(cfg, l1_tag)
    l1_state = engine_l1_to_golden(cfg, l1_state)
    C, S1, W1 = l1_tag.shape
    B, S2, W2 = llc_tag.shape
    NW = cfg.n_sharer_words
    logB = B.bit_length() - 1

    ltag2 = llc_tag.reshape(B * S2, W2)
    lown2 = llc_owner.reshape(B * S2, W2)
    sh3 = sharers.reshape(B * S2, W2, NW)

    slot = (l1_tag & (B - 1)) * S2 + ((l1_tag >> logB) & (S2 - 1))  # [C,S1,W1]
    tags = ltag2[slot]  # [C,S1,W1,W2]
    match = tags == l1_tag[..., None]
    has = match.any(-1)
    hway = match.argmax(-1)
    owner = np.take_along_axis(lown2[slot], hway[..., None], -1)[..., 0]
    cores = np.arange(C, dtype=np.int64)[:, None, None]
    word = np.take_along_axis(
        sh3[slot],  # [C,S1,W1,W2,NW]
        np.broadcast_to((cores >> 5), slot.shape)[..., None, None],
        -1,
    )[..., 0]  # [C,S1,W1,W2]
    shword = np.take_along_axis(word, hway[..., None], -1)[..., 0]
    shbit = ((shword >> (cores & 31).astype(np.uint32)) & 1) != 0

    return np.where(
        (l1_state == I) | ~has,
        I,
        np.where(owner == cores, l1_state, np.where(shbit, S, I)),
    ).astype(l1_state.dtype)
