"""Observable-state helpers for the pull-based engine (NumPy, host-side).

The vectorized engine keeps only locally-written L1 state and derives each
way's effective MESI state from the directory on access (engine.py phase 1).
`effective_l1_state` re-derives that mapping on host arrays so tests and
debug invariants can compare the engine's *observable* cache contents
against the eager golden model bit-for-bit: at every (core, set, way) the
golden's eagerly-maintained state must equal the engine's derived state,
and tags must agree wherever the golden holds a valid line.
"""

from __future__ import annotations

import numpy as np

from ..config.machine import MachineConfig
from .state import (  # noqa: F401  (shared MESI encoding)
    E,
    I,
    M,
    S,
    llc_meta_width,
)


def engine_l1_to_golden(cfg: MachineConfig, arr: np.ndarray) -> np.ndarray:
    """Reshape an engine L1 plane [C, W1*S1] to golden layout [C, S1, W1]."""
    C = arr.shape[0]
    W1, S1 = cfg.l1.ways, cfg.l1.sets
    return np.transpose(arr.reshape(C, W1, S1), (0, 2, 1))


def l1_views(cfg: MachineConfig, state):
    """Split the engine's fused L1 array into its four planes.

    Returns (tag, state, lru, ptr), each [C, W1*S1] (engine way-major
    column layout; feed through `engine_l1_to_golden` for the golden's
    [C, S1, W1] layout).
    """
    arr = np.asarray(state.l1)
    FS = cfg.l1.ways * cfg.l1.sets
    return (
        arr[:, :FS],
        arr[:, FS : 2 * FS],
        arr[:, 2 * FS : 3 * FS],
        arr[:, 3 * FS : 4 * FS],
    )


def epoch_views(cfg: MachineConfig, state):
    """The invalidation-epoch planes (coarse-vector validation inputs):
    (l1_eph [C, W1*S1], llc_eph [B, S2, W2])."""
    FS = cfg.l1.ways * cfg.l1.sets
    W2, S2, B = cfg.llc.ways, cfg.llc.sets, cfg.n_banks
    l1_eph = np.asarray(state.l1)[:, 4 * FS : 5 * FS]
    llc_eph = np.asarray(state.dirm)[:, 3 * W2 : 4 * W2].reshape(
        B, S2, W2
    )
    return l1_eph, llc_eph


def sharers_view(cfg: MachineConfig, state):
    """The packed sharer words [B*S2, W2*NW] from the fused `dirm` rows,
    reinterpreted as uint32 (engine stores them as int32 bit patterns;
    the golden model uses uint32)."""
    MW = llc_meta_width(cfg)
    return np.asarray(state.dirm)[:, MW:].view(np.uint32)


def llc_views(cfg: MachineConfig, state):
    """Unpack the engine's fused LLC metadata into golden-layout views.

    The engine stores the whole per-(bank,set) LLC metadata in one
    `dirm` row (row slot = bank*S2 + set; columns [2w]=tag,
    [2w+1]=owner, [2*W2+w]=lru); returns (llc_tag, llc_owner, llc_lru)
    as [B, S2, W2] NumPy arrays, the golden model's layout.
    """
    B = cfg.n_banks
    S2, W2 = cfg.llc.sets, cfg.llc.ways
    meta = np.asarray(state.dirm)
    pairs = meta[:, : 2 * W2].reshape(B, S2, W2, 2)
    lru = meta[:, 2 * W2 : 3 * W2].reshape(B, S2, W2)
    return pairs[..., 0], pairs[..., 1], lru


def effective_l1_state(
    cfg: MachineConfig,
    l1_tag: np.ndarray,  # [C, W1*S1] (engine layout, way-major columns)
    l1_state: np.ndarray,  # [C, W1*S1] locally-written MESI
    llc_tag: np.ndarray,  # [B, S2, W2]
    llc_owner: np.ndarray,  # [B, S2, W2]
    sharers: np.ndarray,  # [B*S2, W2*NW] packed rows (engine layout)
    l1_eph: np.ndarray | None = None,  # [C, W1*S1] fill epochs (coarse)
    llc_eph: np.ndarray | None = None,  # [B, S2, W2] entry epochs (coarse)
) -> np.ndarray:
    """Directory-validated MESI state per L1 way (engine phase-1 rule).

    Accepts the engine's flattened way-major L1 layout and returns the
    validated states in the golden model's [C, S1, W1] layout.
    """
    l1_tag = engine_l1_to_golden(cfg, l1_tag)
    l1_state = engine_l1_to_golden(cfg, l1_state)
    C, S1, W1 = l1_tag.shape
    B, S2, W2 = llc_tag.shape
    NW = cfg.n_sharer_words
    logB = B.bit_length() - 1

    ltag2 = llc_tag.reshape(B * S2, W2)
    lown2 = llc_owner.reshape(B * S2, W2)
    sh3 = sharers.reshape(B * S2, W2, NW)
    logG = cfg.sharer_group.bit_length() - 1

    slot = (l1_tag & (B - 1)) * S2 + ((l1_tag >> logB) & (S2 - 1))  # [C,S1,W1]
    tags = ltag2[slot]  # [C,S1,W1,W2]
    match = tags == l1_tag[..., None]
    has = match.any(-1)
    hway = match.argmax(-1)
    owner = np.take_along_axis(lown2[slot], hway[..., None], -1)[..., 0]
    cores = np.arange(C, dtype=np.int64)[:, None, None]
    gbit = cores >> logG  # sharer-GROUP bit index (identity at G=1)
    word = np.take_along_axis(
        sh3[slot],  # [C,S1,W1,W2,NW]
        np.broadcast_to((gbit >> 5), slot.shape)[..., None, None],
        -1,
    )[..., 0]  # [C,S1,W1,W2]
    shword = np.take_along_axis(word, hway[..., None], -1)[..., 0]
    shbit = ((shword >> (gbit & 31).astype(np.uint32)) & 1) != 0
    if cfg.sharer_group > 1:
        # coarse vector: the group bit only validates an entry filled at
        # the directory entry's CURRENT invalidation epoch (engine.py
        # `_validate_ways` — a neighbor's re-share must not resurrect an
        # invalidated copy)
        if l1_eph is None or llc_eph is None:
            raise ValueError(
                "sharer_group > 1 requires l1_eph/llc_eph for validation"
            )
        l1_eph = engine_l1_to_golden(cfg, l1_eph)
        eph2 = llc_eph.reshape(B * S2, W2)
        veph = np.take_along_axis(eph2[slot], hway[..., None], -1)[..., 0]
        shbit = shbit & (veph == l1_eph)

    return np.where(
        (l1_state == I) | ~has,
        I,
        np.where(owner == cores, l1_state, np.where(shbit, S, I)),
    ).astype(l1_state.dtype)


def check_invariants(cfg: MachineConfig, state, done_mask=None) -> None:
    """DESIGN.md §5 debug invariants, checked host-side on a MachineState.

    Raises AssertionError naming the violated invariant. Cheap enough to
    run between chunks (`Engine.run_chunked(debug_invariants=True)`,
    `primetpu run --debug-invariants`); the randomized MESI property tests
    (tests/test_invariants.py) drive it over adversarial request streams.

    `done_mask` ([C] bool) marks finished cores: their epoch-relative
    clocks legitimately go negative once rebases (which track only LIVE
    cores) outrun them — the true clock is `cycles + cycle_base`. Without
    the mask the clock invariant is skipped.

    Fault-aware by construction (DESIGN.md §12): Engine.done_mask() and
    FleetEngine.core_done_mask() fold fail-stopped cores in, so a chaos
    run under `--guard=fail` never false-positives on a dead core. The
    MESI checks need no masking at all — the fail-stop scrub
    (faults.inject.scrub_dead) removes a dead core from every directory
    entry, so its stale locally-written L1 state derives to I here,
    exactly like an invalidated copy.
    """
    def _require(cond, msg):
        if not cond:
            raise AssertionError(msg)

    C = cfg.n_cores
    l1_tag, l1_state, _, _ = l1_views(cfg, state)
    llc_tag, llc_owner, _ = llc_views(cfg, state)
    sharers = sharers_view(cfg, state)
    B, S2, W2 = llc_tag.shape
    NW = cfg.n_sharer_words

    # 1. directory exclusivity (MESI): an owned entry records no
    # sharers. Under MOESI dirty sharing is the point of the Owned
    # state, so the invariant weakens to: an owned entry with sharers
    # must record the OWNER'S own bit (the derived-O contract — engine
    # probe retention and the golden GETS-owner branch both set it).
    sh3 = sharers.reshape(B * S2, W2, NW)
    owned = (llc_owner >= 0).reshape(B * S2, W2)
    if cfg.coherence == "moesi":
        own2 = np.clip(llc_owner.reshape(B * S2, W2), 0, C - 1)
        oword = np.take_along_axis(sh3, (own2 >> 5)[..., None], -1)[..., 0]
        obit = (oword >> (own2 & 31).astype(np.uint32)) & 1
        _require(
            not (owned & (sh3 != 0).any(-1) & (obit == 0)).any(),
            "invariant: moesi owned entry has sharers but no owner bit",
        )
    else:
        _require(
            not (owned & (sh3 != 0).any(-1)).any(),
            "invariant: owned LLC entry has non-empty sharer set",
        )

    # 2. owner / sharer-bit ranges
    _require(
        ((llc_owner >= -1) & (llc_owner < C)).all(),
        "invariant: llc_owner out of range",
    )
    n_grp = cfg.n_sharer_groups
    if n_grp % 32:
        bits = (
            (sh3[..., None] >> np.arange(32, dtype=np.uint32)) & 1
        ).reshape(B * S2, W2, NW * 32)
        _require(
            not (bits[:, :, n_grp:] != 0).any(),
            "invariant: sharer bits set beyond the group count",
        )

    # 3. valid LLC tags unique per (bank, set)
    t2 = llc_tag.reshape(B * S2, W2)
    for w in range(W2):
        for w2 in range(w + 1, W2):
            clash = (t2[:, w] != -1) & (t2[:, w] == t2[:, w2])
            _require(not clash.any(), "invariant: duplicate valid LLC tag in set")

    # 4. valid L1 tags unique per (core, set) — the fill path clears stale
    # duplicates so a line never occupies two ways
    gt = engine_l1_to_golden(cfg, l1_tag)  # [C, S1, W1]
    W1 = gt.shape[2]
    for w in range(W1):
        for w2 in range(w + 1, W1):
            clash = (gt[:, :, w] != -1) & (gt[:, :, w] == gt[:, :, w2])
            _require(not clash.any(), "invariant: duplicate valid L1 tag in set")

    # 5. effective E/M exclusivity: at most one core holds a line in E/M
    l1_eph, llc_eph = (
        epoch_views(cfg, state) if cfg.sharer_group > 1 else (None, None)
    )
    eff = effective_l1_state(
        cfg, l1_tag, l1_state, llc_tag, llc_owner, sharers,
        l1_eph=l1_eph, llc_eph=llc_eph,
    )
    em = eff >= E
    em_lines = gt[em]
    _require(
        len(np.unique(em_lines)) == len(em_lines),
        "invariant: two cores hold the same line in E/M",
    )

    # 6. synchronization tables
    lock_holder = np.asarray(state.lock_holder)
    barrier_count = np.asarray(state.barrier_count)
    barrier_time = np.asarray(state.barrier_time)
    sync_flag = np.asarray(state.sync_flag)
    _require(
        ((lock_holder >= -1) & (lock_holder < C)).all(),
        "invariant: lock_holder out of range",
    )
    _require((barrier_count >= 0).all(), "invariant: negative barrier count")
    _require(
        (barrier_time[barrier_count == 0] == 0).all(),
        "invariant: stale barrier_time on empty slot",
    )
    _require(np.isin(sync_flag, (0, 1)).all(), "invariant: sync_flag not 0/1")

    # 7. core bookkeeping
    ptr = np.asarray(state.ptr)
    _require((ptr >= 0).all(), "invariant: negative trace pointer")
    if done_mask is not None:
        live = ~np.asarray(done_mask)
        _require(
            (np.asarray(state.cycles)[live] >= 0).all(),
            "invariant: negative (under-rebased) live core clock",
        )


def check_chunk_invariants(
    cfg: MachineConfig,
    state,
    done_mask=None,
    live_mask=None,
    prev_totals: dict | None = None,
    totals: dict | None = None,
) -> None:
    """Post-chunk guard (`RunSupervisor`, `--guard=warn|fail`): the full
    MESI/directory consistency suite plus two cross-chunk checks that
    only make sense at a committed cut.

    - clock-window: the slowest LIVE core (not at END, not frozen at a
      barrier, not fail-stopped — `live_mask`, see Engine.live_mask)
      stays within one quantum of `quantum_end`. The golden model asserts this every
      step; here it is the cheap host-side witness that the engine's
      quantum arbitration hasn't drifted.
    - monotone counters: 64-bit host accumulator totals never decrease
      between chunks (`prev_totals`/`totals`, name -> int) — a decrease
      means a drain carry was lost or applied twice.

    Raises AssertionError naming the violated invariant, like
    check_invariants; the supervisor maps that to warn/fail. `state=None`
    skips the state checks (used for the fleet's aggregate counter-total
    check, where per-element states were already checked individually).
    """
    if state is not None:
        check_invariants(cfg, state, done_mask=done_mask)
    if state is not None and live_mask is not None:
        live = np.asarray(live_mask)
        if live.any():
            qe = int(np.asarray(state.quantum_end))
            lo = int(np.asarray(state.cycles)[live].min())
            if qe - lo > cfg.quantum:
                raise AssertionError(
                    f"invariant: cycle skew {qe - lo} exceeds quantum "
                    f"{cfg.quantum} (quantum_end={qe}, slowest live core "
                    f"at {lo})"
                )
    if prev_totals is not None and totals is not None:
        for k, v in totals.items():
            pv = prev_totals.get(k, 0)
            if v < pv:
                raise AssertionError(
                    f"invariant: counter {k!r} decreased ({pv} -> {v})"
                )
