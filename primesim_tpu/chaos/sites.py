"""The fault-site registry and the hooks threaded through real I/O.

A SITE is a named point in the serve/pool stack where infrastructure
can fail: a durable write, a socket operation, a process crashpoint, a
lease/heartbeat clock. The static catalog (`SITES`) maps each name to
its fault class; `plan.generate` draws events from it and the lint rule
PT-CHAOS-SITE keeps the real I/O paths threaded through these hooks so
coverage can't silently rot.

Activation model: a module-level `ChaosRuntime` (`install(plan)`), or
None. Every hook starts with `if _RT is None: return` — with no plan
active the entire subsystem is one predictable branch per site, adds no
measurable overhead, and the stack stays bit-exact. One runtime spans a
whole TRIAL, surviving in-process "restarts" of the component under
test: occurrence counters keep climbing and fired events never re-fire,
which both makes trials deterministic and bounds them (a plan with K
crash events causes at most K restarts).

Crash semantics: injected process death raises `ChaosCrash`, which
inherits **BaseException** on purpose — the serve/pool protocol
boundaries catch `Exception` to convert handler errors into structured
replies, and a fault that those boundaries could swallow would be a
simulated crash that the process survives. In `mode="kill"` (subprocess
trials, env activation) the hook delivers a real SIGKILL instead.
"""

from __future__ import annotations

import os
import signal
import time

from .plan import FaultPlan

# site name -> fault class. Extend HERE when instrumenting a new path
# (and thread the matching hook through the code; PT-CHAOS-SITE insists).
SITES = {
    # durable-write sites
    "journal.append": "durable",       # serve/journal.py append fsync
    "checkpoint.write": "durable",     # sim/checkpoint.py atomic replace
    "exec_cache.write": "durable",     # sim/exec_cache.py atomic replace
    # socket sites (client side of the JSON-lines protocol — serve
    # front door and the pool lease path both ride protocol.request)
    "protocol.send": "socket",
    "protocol.recv": "socket",
    # named process crashpoints (generalizing PRIMETPU_POOL_CRASH)
    "server.post-journal-pre-ack": "crashpoint",
    "scheduler.pre-dispatch": "crashpoint",
    "scheduler.post-dispatch": "crashpoint",
    "scheduler.post-checkpoint": "crashpoint",
    "coordinator.post-lease": "crashpoint",
    "coordinator.post-ack": "crashpoint",
    "worker.pre-ack": "crashpoint",
    "worker.post-checkpoint": "crashpoint",
    # clock-skew sites on the lease/heartbeat timers
    "coordinator.clock": "clock",
    "worker.heartbeat.interval": "clock",
    # replication stream (primary -> replica orders; serve/replicate.py)
    "replicate.send": "replication",
    "replica.pre-fsync-ack": "crashpoint",
    # silent-data-corruption sites (DESIGN.md §24): perturb committed
    # values in place with NO crash — the worker hashes and ACKs the
    # wrong data, and only attestation cross-checks can tell
    "fleet.counters": "silent_corruption",      # sim/fleet.py post-drain
    "checkpoint.payload": "silent_corruption",  # element checkpoint arrays
    # capacity-loss sites (DESIGN.md §26): a mesh shrinking under a live
    # run, and a filesystem that stops taking bytes for a while
    "devices.revoke": "capacity_loss",  # sim/supervisor.py chunk boundary
    "disk.preflight": "capacity_loss",  # util/diskpressure.py space gate
}

ENV_PLAN = "PRIMETPU_CHAOS_PLAN"  # path to a FaultPlan JSON file
ENV_MODE = "PRIMETPU_CHAOS_MODE"  # "kill" (default) or "raise"


class ChaosCrash(BaseException):
    """Injected process death. BaseException so the `except Exception`
    protocol boundaries in server/coordinator/worker cannot absorb it —
    an injected kill must behave like kill -9, not like a bad request."""


class ChaosRuntime:
    def __init__(self, plan: FaultPlan, mode: str = "raise", obs=None,
                 crash_exc=None):
        if mode not in ("raise", "kill"):
            raise ValueError(f"chaos mode must be raise|kill, got {mode!r}")
        self.plan = plan
        self.mode = mode
        self.obs = obs
        # optional exception factory overriding ChaosCrash — the worker's
        # simulate_crash=True compatibility path raises SimulatedCrash
        self.crash_exc = crash_exc
        self.counts: dict[str, int] = {}   # site -> arrivals this trial
        self.fired: set[int] = set()       # plan event indices consumed
        self.injected: list[dict] = []     # flight log for reports/tests
        self.clock_offsets: dict[str, float] = {}
        # site -> remaining arrivals inside an open sustained window
        # (enospc_window: the fault persists across several probes
        # instead of firing once, like a disk that stays full)
        self.windows: dict[str, int] = {}

    def hit(self, site: str):
        """Count one arrival at `site`; return the matching un-fired
        plan event (marking it fired and logging it), or None."""
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        for i, ev in enumerate(self.plan.events):
            if i in self.fired:
                continue
            if ev.site == site and ev.occurrence == n:
                self.fired.add(i)
                self.injected.append(
                    {"site": site, "occurrence": n, "action": ev.action}
                )
                if self.obs is not None:
                    self.obs.chaos_event(site, ev.action, occurrence=n)
                return ev
        return None

    def crash(self, site: str, action: str):
        if self.mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        if self.crash_exc is not None:
            raise self.crash_exc(site)
        raise ChaosCrash(f"{site}: injected {action}")


_RT: ChaosRuntime | None = None


def install(plan: FaultPlan, mode: str = "raise", obs=None,
            crash_exc=None) -> ChaosRuntime:
    global _RT
    _RT = ChaosRuntime(plan, mode=mode, obs=obs, crash_exc=crash_exc)
    return _RT


def deactivate() -> None:
    global _RT
    _RT = None


def runtime() -> ChaosRuntime | None:
    return _RT


class active:
    """Context manager for trial code: install on enter, ALWAYS
    deactivate on exit (including ChaosCrash unwinds)."""

    def __init__(self, plan: FaultPlan, mode: str = "raise", obs=None):
        self.plan = plan
        self.mode = mode
        self.obs = obs
        self.rt: ChaosRuntime | None = None

    def __enter__(self) -> ChaosRuntime:
        self.rt = install(self.plan, mode=self.mode, obs=self.obs)
        return self.rt

    def __exit__(self, *exc):
        deactivate()
        return False


def install_from_env() -> ChaosRuntime | None:
    """Subprocess activation: when PRIMETPU_CHAOS_PLAN names a plan
    file, install it (default mode `kill` — a subprocess under chaos
    dies for real). Called once from the CLI entry point, so spawned
    workers/coordinators inherit the campaign's plan through the
    environment. No-op when the var is unset or a runtime exists."""
    path = os.environ.get(ENV_PLAN)
    if not path or _RT is not None:
        return _RT
    return install(FaultPlan.load(path),
                   mode=os.environ.get(ENV_MODE, "kill"))


# ---- the hooks (each begins with the no-plan fast path) ------------------


def crashpoint(site: str) -> None:
    """Named process crashpoint: die here when the plan says so."""
    if _RT is None:
        return
    ev = _RT.hit(site)
    if ev is not None:
        _RT.crash(site, ev.action)


def durable(site: str, f=None, data=None, path=None) -> None:
    """Durable-write site, called BEFORE the real write/replace.

    `f`+`data` describe an imminent append (journal): `torn` writes a
    plan-chosen prefix of `data` — flushed but never fsynced — and then
    crashes, leaving exactly the torn tail a power cut leaves.
    `path` describes a finished temp file awaiting its atomic rename
    (checkpoint): `torn` truncates the temp file and crashes BEFORE the
    rename, so the destination must still hold the previous complete
    snapshot. `fsync_fail`/`enospc` crash with nothing written at all —
    on a live OS, bytes that never reached a successful fsync must be
    assumed lost, and modeling that as "the append never happened" is
    the conservative corner. `delay` just stalls the caller."""
    if _RT is None:
        return
    ev = _RT.hit(site)
    if ev is None:
        return
    if ev.action == "delay":
        time.sleep(float(ev.arg("s", 0.005)))
        return
    if ev.action == "torn":
        frac = float(ev.arg("frac", 0.5))
        if f is not None and data is not None and len(data):
            cut = max(1, min(len(data) - 1, int(len(data) * frac)))
            f.write(data[:cut])
            f.flush()
        elif path is not None:
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(1, int(size * frac)))
    _RT.crash(site, ev.action)


def socket_send(site: str, sock, payload: bytes) -> bool:
    """Socket-send site. Returns True when the fault consumed the send
    (the caller must NOT sendall); False to proceed normally.

    `short_send` delivers a partial frame then drops the connection —
    the peer sees a torn frame, the caller sees a post-send
    ConnectionError and cannot know whether the request landed (the
    lost-ACK scenario idempotency tokens exist for). `disconnect` drops
    the connection before any byte. `duplicate` delivers the frame
    twice — the peer must dedup. `delay` stalls then sends normally."""
    if _RT is None:
        return False
    ev = _RT.hit(site)
    if ev is None:
        return False
    if ev.action == "delay":
        time.sleep(float(ev.arg("s", 0.005)))
        return False
    if ev.action == "duplicate":
        sock.sendall(payload)
        sock.sendall(payload)
        return True
    if ev.action == "short_send":
        frac = float(ev.arg("frac", 0.5))
        cut = max(1, min(len(payload) - 1, int(len(payload) * frac)))
        try:
            sock.sendall(payload[:cut])
        finally:
            sock.close()
        raise ConnectionError(f"{site}: injected short send + disconnect")
    # disconnect
    sock.close()
    raise ConnectionError(f"{site}: injected disconnect")


def socket_recv(site: str, sock) -> None:
    """Socket-recv site, called after send / before the reply read.
    `disconnect` drops the connection so the reply — and any ACK it
    carried — is lost after the request may already have been handled."""
    if _RT is None:
        return
    ev = _RT.hit(site)
    if ev is None:
        return
    if ev.action == "delay":
        time.sleep(float(ev.arg("s", 0.005)))
        return
    sock.close()
    raise ConnectionError(f"{site}: injected disconnect before reply")


def replication(site: str):
    """Replication-stream site (primary side, before the order goes on
    the wire). `delay` stalls in place and is consumed here; `partition`
    and `duplicate` return the event for the ReplicaLink to enact — a
    partition must close the link AND suppress reconnection for its
    window, which only the link's own state can express."""
    if _RT is None:
        return None
    ev = _RT.hit(site)
    if ev is None:
        return None
    if ev.action == "delay":
        time.sleep(float(ev.arg("s", 0.005)))
        return None
    return ev


def clock_skew(site: str, value: float) -> float:
    """Clock/interval site: pass `value` through, skewed once the plan's
    event has fired (the offset persists for the rest of the trial —
    clocks jump, they don't flicker)."""
    if _RT is None:
        return value
    ev = _RT.hit(site)
    if ev is not None and ev.action == "skew":
        _RT.clock_offsets[site] = (
            _RT.clock_offsets.get(site, 0.0) + float(ev.arg("offset_s", 1.0))
        )
    return value + _RT.clock_offsets.get(site, 0.0)


def corrupt(site: str, arrays: dict) -> bool:
    """Silent-corruption site (DESIGN.md §24): perturb one committed
    int64 value in one of `arrays` (a dict of writable host numpy
    arrays), in place, with NO crash and NO error — the caller proceeds
    to fingerprint, checkpoint and ACK the wrong data exactly like a
    machine with a flaky DIMM would. Detection is attestation's job
    (invariant F), not this hook's. Returns True when a flip fired."""
    if _RT is None:
        return False
    ev = _RT.hit(site)
    if ev is None or ev.action != "flip" or not arrays:
        return False
    keys = sorted(arrays)
    arr = arrays[keys[int(ev.arg("key", 0)) % len(keys)]]
    flat = arr.reshape(-1)
    delta = int(ev.arg("delta", 1)) or 1
    flat[int(ev.arg("pos", 0)) % flat.size] += delta
    return True


def device_revoke(site: str):
    """Capacity-loss site at a supervised chunk boundary: returns the
    plan's `revoke` event (whose `n` arg says how many mesh devices
    vanish) or None. The caller — the supervisor — enacts it via
    `parallel.sharding.revoke_devices` and raises a synthetic
    DEVICE_LOST, because only it knows which devices its mesh holds."""
    if _RT is None:
        return None
    ev = _RT.hit(site)
    if ev is not None and ev.action == "revoke":
        return ev
    return None


def disk_full(site: str) -> bool:
    """Sustained-ENOSPC site: True while a plan-opened window is live.

    Unlike `durable`'s one-shot `enospc` (which models a crash), an
    `enospc_window` event opens a window of `calls` consecutive arrivals
    during which the probe reports a full disk and then heals — the shape
    real disk pressure takes, and the one the diskpressure retry ladder
    is built to ride out without losing ACKed state."""
    if _RT is None:
        return False
    ev = _RT.hit(site)
    if ev is not None and ev.action == "enospc_window":
        _RT.windows[site] = (
            _RT.windows.get(site, 0) + max(1, int(ev.arg("calls", 3)))
        )
    left = _RT.windows.get(site, 0)
    if left > 0:
        _RT.windows[site] = left - 1
        return True
    return False


def wrap_clock(site: str, clock):
    """Wrap a clock callable with the skew site. Returns `clock`
    UNCHANGED when no runtime is active at wrap time — the no-plan path
    keeps the exact original callable (zero per-call overhead), which is
    why chaos must be installed before the component is constructed."""
    if _RT is None:
        return clock

    def skewed():
        return clock_skew(site, clock())

    return skewed
