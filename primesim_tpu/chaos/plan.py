"""Fault plans — the seeded, serializable schedule of what breaks when.

A `FaultPlan` is derived from a single integer seed: `generate(seed)`
expands it into a list of `FaultEvent`s, each keyed by a registered
SITE NAME (chaos.sites.SITES) and a 1-based OCCURRENCE index — "the 3rd
time `journal.append` is reached this trial, tear the write at 40% of
the record". Because the expansion is `random.Random(seed)` and the
serve/pool trial harnesses are single-threaded and deterministic, a
failing trial reproduces from its seed alone; the JSON form exists so a
SHRUNK plan (a subset of the generated events) is just as replayable.

Events fire at most once per trial. An event whose site is never
reached (or reached fewer than `occurrence` times) simply never fires —
plans may therefore be generated against the full site catalog without
knowing which code paths a given workload exercises.
"""

from __future__ import annotations

import dataclasses
import json
import random

# action menus per site class (sites.SITES maps site -> class)
ACTIONS = {
    "durable": ("torn", "fsync_fail", "enospc", "delay"),
    "socket": ("short_send", "disconnect", "delay", "duplicate"),
    "crashpoint": ("kill",),
    "clock": ("skew",),
    "replication": ("partition", "delay", "duplicate"),
    "silent_corruption": ("flip",),
    "capacity_loss": ("revoke", "enospc_window"),
}

# recv-side sockets can only lose or delay the reply — tearing or
# duplicating bytes we are RECEIVING is the peer's doing, not ours
_RECV_ACTIONS = ("disconnect", "delay")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    site: str        # registered site name (sites.SITES key)
    occurrence: int  # fire on the Nth arrival at the site (1-based)
    action: str      # one of ACTIONS[class-of-site]
    args: tuple = () # sorted (key, value) pairs — hashable + JSON-stable

    def arg(self, key: str, default=None):
        for k, v in self.args:
            if k == key:
                return v
        return default

    def as_dict(self) -> dict:
        return {
            "site": self.site,
            "occurrence": self.occurrence,
            "action": self.action,
            "args": {k: v for k, v in self.args},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(
            site=str(d["site"]),
            occurrence=int(d["occurrence"]),
            action=str(d["action"]),
            args=tuple(sorted((d.get("args") or {}).items())),
        )


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    seed: int
    events: tuple = ()

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "events": [e.as_dict() for e in self.events],
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True, indent=2)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(
            seed=int(d.get("seed", 0)),
            events=tuple(FaultEvent.from_dict(e)
                         for e in d.get("events", [])),
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json() + "\n")

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(f.read())

    def without(self, index: int) -> "FaultPlan":
        """A copy with event `index` removed (the shrinker's move)."""
        ev = self.events[:index] + self.events[index + 1:]
        return FaultPlan(seed=self.seed, events=ev)


def _event_args(rng: random.Random, action: str) -> tuple:
    if action == "torn":
        # the plan-chosen tear point, as a fraction of the record
        return (("frac", round(rng.uniform(0.05, 0.95), 3)),)
    if action == "short_send":
        return (("frac", round(rng.uniform(0.1, 0.9), 3)),)
    if action == "delay":
        return (("s", round(rng.uniform(0.001, 0.02), 4)),)
    if action == "partition":
        # how long the replication link stays blacked out before the
        # partition "heals" and the link may reconnect + resync
        return (("s", round(rng.uniform(0.05, 0.4), 3)),)
    if action == "skew":
        return (("offset_s", round(rng.uniform(0.5, 30.0), 3)),)
    if action == "flip":
        # which array (modulo the dict size), which element (modulo its
        # flat size), and a guaranteed-nonzero perturbation
        return (("key", rng.randint(0, 7)),
                ("pos", rng.randint(0, 1 << 16)),
                ("delta", rng.choice((-3, -1, 1, 2, 5, 17)),))
    if action == "revoke":
        # how many devices drop out of the mesh at once
        return (("n", rng.choice((1, 1, 2))),)
    if action == "enospc_window":
        # how many subsequent preflight probes see a full disk before
        # the window "heals"
        return (("calls", rng.randint(2, 6)),)
    return ()


def generate(
    seed: int,
    classes: tuple = ("durable", "crashpoint"),
    sites: list | None = None,
    max_events: int = 3,
    max_occurrence: int = 4,
) -> FaultPlan:
    """Expand a seed into a plan. `classes` filters the site catalog by
    fault class; `sites` (names) narrows it further — the trial
    harnesses pass the sites their stack actually reaches so generated
    events have a fighting chance of firing."""
    from .sites import SITES

    rng = random.Random(seed)
    pool = [
        (name, cls) for name, cls in sorted(SITES.items())
        if cls in classes and (sites is None or name in sites)
    ]
    if not pool:
        raise ValueError(
            f"no chaos sites match classes={classes!r} sites={sites!r}"
        )
    events = []
    for _ in range(rng.randint(1, max_events)):
        name, cls = rng.choice(pool)
        menu = _RECV_ACTIONS if name.endswith(".recv") else ACTIONS[cls]
        action = rng.choice(menu)
        events.append(FaultEvent(
            site=name,
            occurrence=rng.randint(1, max_occurrence),
            action=action,
            args=_event_args(rng, action),
        ))
    # duplicate (site, occurrence) pairs would shadow each other — keep
    # the first so every event in the plan is reachable in principle
    seen, kept = set(), []
    for e in events:
        if (e.site, e.occurrence) in seen:
            continue
        seen.add((e.site, e.occurrence))
        kept.append(e)
    return FaultPlan(seed=seed, events=tuple(kept))


def shrink(plan: FaultPlan, still_fails) -> FaultPlan:
    """Greedy ddmin: drop events one at a time while `still_fails(plan)`
    keeps reproducing the violation. Terminates because every accepted
    move strictly shrinks the event list; the result is 1-minimal (no
    single event can be removed without losing the failure)."""
    cur = plan
    changed = True
    while changed and len(cur.events) > 1:
        changed = False
        for i in range(len(cur.events)):
            cand = cur.without(i)
            if still_fails(cand):
                cur = cand
                changed = True
                break
    return cur
