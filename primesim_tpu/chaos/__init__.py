"""Deterministic chaos for the simulator's OWN infrastructure
(DESIGN.md §20).

`primesim_tpu/faults/` injects faults into the simulated machine; this
package injects faults into the machinery that RUNS the simulation —
journals, checkpoints, sockets, process lifetimes, clocks — and then
machine-checks that the durability invariants survived:

- `plan`     — `FaultPlan`: a seeded, JSON-serializable schedule of
               fault events keyed by site name + occurrence index, so
               any failing trial is a one-line repro.
- `sites`    — the fault-site registry threaded through the real I/O
               paths (journal append, checkpoint replace, protocol
               send/recv, named crashpoints, lease clocks). With no
               plan installed every hook is a no-op and the serve/pool
               stack stays bit-exact.
- `campaign` — seeded trial runner + invariant checks + plan shrinker
               behind the `primetpu chaos` CLI verb.
"""

from .plan import FaultEvent, FaultPlan
from .sites import (
    SITES,
    ChaosCrash,
    active,
    crashpoint,
    deactivate,
    install,
    install_from_env,
)

__all__ = [
    "FaultEvent",
    "FaultPlan",
    "SITES",
    "ChaosCrash",
    "active",
    "crashpoint",
    "deactivate",
    "install",
    "install_from_env",
]
