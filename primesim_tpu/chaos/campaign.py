"""Invariant-checked crash campaigns over the serve/pool stack.

A TRIAL runs a real serving workload under one seeded `FaultPlan` and
then machine-checks the durability story the stack promises:

  A. NO ACKED JOB LOST — every submit whose ACK was observed is present
     (same job_id, exactly once) after every crash/restart, and reaches
     a terminal state.
  B. BIT-EXACT RESULTS — surviving state replays to the same results a
     fault-free GOLDEN run of the identical workload produces
     (deterministic fields only; wall-clock throughput is stripped).
  C. FSCK CLEAN — `primetpu fsck` over the surviving state directory
     finds nothing corrupt (a torn tail in the newest journal segment is
     legal by the WAL contract and repaired on open, so it never shows).
  D. NO DOUBLE-ENQUEUE — a retried submit after a lost ACK (idempotency
     token) must not create a twin job.

The serve trial is IN-PROCESS: it rebuilds the scheduler over the same
state dir after every injected crash, exactly replicating the server's
`_recover()` (journal replay -> fold -> adopt/requeue). Injected process
death arrives as `ChaosCrash` (BaseException) and the harness plays the
role of init: catch, count the restart, boot again. One ChaosRuntime
spans the whole trial, so fired events never re-fire and a plan with K
crash events bounds the trial at K restarts.

The socket trial runs a REAL PrimeServer in a thread and drives it with
a `ServeClient` whose reconnect/idempotency machinery is the system
under test; its plans draw only from the client-side socket sites.

On violation, `run_campaign` shrinks the plan (greedy ddmin re-running
the trial) to a 1-minimal event set and writes a repro artifact: the
seed, the shrunk plan JSON, and the violation text — `primetpu chaos
--plan <artifact>` replays it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile

from . import plan as P
from . import sites

#: Sites the in-process serve trial actually reaches, by fault class.
SERVE_SITES = {
    "durable": ("journal.append", "checkpoint.write"),
    "crashpoint": (
        "server.post-journal-pre-ack",
        "scheduler.pre-dispatch",
        "scheduler.post-dispatch",
        "scheduler.post-checkpoint",
    ),
    "socket": ("protocol.send", "protocol.recv"),
}

#: Sites only the replication trial reaches. Opt-in via `--classes
#: replication` — they are NOT folded into the default campaign, so
#: plain durable/crashpoint runs keep their historical trial shape.
#: `replica.pre-fsync-ack` is crashpoint-CLASS (its only action is
#: kill) but replication-trial-ONLY, so listing "replication" pulls it
#: in: a replication campaign without replica deaths would never
#: exercise catch-up or promotion-under-loss.
REPLICATION_SITES = ("replicate.send", "replica.pre-fsync-ack")

#: Silent-data-corruption sites: the attestation trial (DESIGN.md §24).
#: Opt-in via `--classes silent_corruption` and routed to their OWN
#: trial — a flip in a serve-trial fleet would be undetectable by
#: construction (that is the whole point of attestation) and would read
#: as a bogus invariant-B violation there.
ATTEST_SITES = ("fleet.counters", "checkpoint.payload")

#: Degraded-mode capacity sites (DESIGN.md §26). Opt-in via `--classes
#: capacity_loss` and routed to their OWN trial: seeded device
#: revocation needs a supervised SHARDED engine (the serve trial's
#: fleets have no mesh to lose), and sustained-ENOSPC windows need a
#: harness that plays a backpressured client — retrying on
#: `DiskPressureError` — rather than reading the typed rejection as a
#: crash. The trial machine-checks INVARIANT G: no ACKed job lost and
#: no bit-exactness violation under capacity loss.
CAPACITY_SITES = ("devices.revoke", "disk.preflight")

#: Small deterministic workloads (serve's synth grammar). Distinct seeds
#: give distinct results, so a cross-wired job table fails invariant B.
DEFAULT_SPECS = (
    "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed=101",
    "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed=102",
    "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed=103",
)

_MAX_TICKS = 20_000  # convergence guard for one boot's tick loop

# result fields that depend on wall time, not on the simulation
_NONDET_KEYS = ("wall_s", "value", "latency_s", "accepted_t")


@dataclasses.dataclass
class TrialResult:
    plan: P.FaultPlan
    violations: list
    injected: list        # events that actually fired, in order
    restarts: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "seed": self.plan.seed,
            "plan": self.plan.as_dict(),
            "violations": list(self.violations),
            "injected": list(self.injected),
            "restarts": self.restarts,
        }


def _canon(result) -> str:
    """Canonical form of a job result for bit-exact comparison: drop
    wall-clock-dependent fields, keep every simulation-determined one."""

    def strip(obj):
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in sorted(obj.items())
                    if k not in _NONDET_KEYS}
        if isinstance(obj, list):
            return [strip(v) for v in obj]
        return obj

    return json.dumps(strip(result), sort_keys=True)


def _default_cfg():
    from ..config.machine import small_test_config

    return small_test_config(4)


# ---- the in-process serve trial ------------------------------------------


def _boot(state_dir: str, cfg, buckets, chunk_steps: int):
    """One server lifetime's worth of scheduler, recovered from whatever
    the previous lifetime left on disk — the exact `server._recover()`
    sequence, minus the listener."""
    from ..serve.journal import JobJournal, fold_records, serve_compactor
    from ..serve.scheduler import Scheduler

    journal = JobJournal(state_dir, compactor=serve_compactor)
    sched = Scheduler(
        cfg, journal, state_dir, buckets=buckets, chunk_steps=chunk_steps,
        checkpoint_every_s=0.0,  # checkpoint every tick: deterministic,
        #                          and it exercises checkpoint.write hard
    )
    records, _dropped = journal.replay()
    jobs, _clean = fold_records(records)
    for job in jobs.values():
        if job.terminal:
            sched.adopt_terminal(job)
        else:
            sched.requeue_recovered(job)
    if jobs:
        sched._seq = max(
            (int(j.job_id[1:]) for j in jobs.values()
             if j.job_id.startswith("j") and j.job_id[1:].isdigit()),
            default=0,
        )
    return sched


def _submit_missing(sched, specs, idems, acked, violations) -> None:
    """Replicate the client's retried-submit path: anything not yet
    ACKed is (re)submitted under its idempotency token; a token already
    in the job table means the previous attempt's accept record survived
    a lost ACK and the job is adopted instead of double-enqueued."""
    from ..serve import jobs as J

    for i in range(len(specs)):
        jid = acked.get(i)
        if jid is not None:
            if jid not in sched.jobs:
                violations.append(
                    f"invariant A: ACKed job {jid} (spec {i}) lost after "
                    "restart"
                )
            continue
        dup = next(
            (j for j in sched.jobs.values() if j.idem == idems[i]), None
        )
        if dup is not None:
            acked[i] = dup.job_id  # lost-ACK retry answered by dedup
            continue
        job = J.Job(job_id=sched.next_job_id(), idem=idems[i],
                    client="chaos", synth=specs[i])
        sched.submit(job)  # may ChaosCrash post-journal-pre-ack: no ACK
        acked[i] = job.job_id  # returned = ACK observed


def _check_no_twins(sched, idems, violations) -> None:
    per_tok = {}
    for j in sched.jobs.values():
        if j.idem:
            per_tok[j.idem] = per_tok.get(j.idem, 0) + 1
    for tok, n in sorted(per_tok.items()):
        if tok in set(idems.values()) and n > 1:
            violations.append(
                f"invariant D: idempotency token {tok} enqueued {n} jobs"
            )


def _run_to_completion(state_dir, cfg, specs, idems, acked, violations,
                       buckets, chunk_steps) -> dict:
    """One boot: recover, check invariant A, (re)submit what is missing,
    tick until every ACKed job is terminal. Raises ChaosCrash whenever
    the plan kills this 'process'; the caller restarts us."""
    sched = _boot(state_dir, cfg, buckets, chunk_steps)
    _submit_missing(sched, specs, idems, acked, violations)
    _check_no_twins(sched, idems, violations)
    for _ in range(_MAX_TICKS):
        if all(sched.jobs[j].terminal for j in acked.values()
               if j in sched.jobs):
            break
        sched.tick()
    else:
        violations.append(
            f"trial did not converge within {_MAX_TICKS} ticks"
        )
    out = {}
    for i, jid in acked.items():
        job = sched.jobs.get(jid)
        if job is None:
            continue  # invariant A already recorded the loss
        out[i] = {"state": job.state, "result": job.result}
    sched.journal.close()
    return out


def run_serve_trial(
    plan: P.FaultPlan,
    cfg=None,
    specs=DEFAULT_SPECS,
    golden: dict | None = None,
    workdir: str | None = None,
    keep_dir: bool = False,
    buckets=((2, 1),),
    chunk_steps: int = 16,
) -> TrialResult:
    """One seeded trial of the in-process serve stack (see module doc).
    `golden` is the fault-free reference from `golden_run` (computed
    here when omitted — pass it when running many trials)."""
    from ..analysis.fsck import run_fsck

    cfg = cfg or _default_cfg()
    if golden is None:
        golden = golden_run(cfg, specs, buckets=buckets,
                            chunk_steps=chunk_steps, workdir=workdir)
    tmp = tempfile.mkdtemp(prefix="chaos-trial-", dir=workdir)
    violations: list = []
    acked: dict = {}
    idems = {i: f"chaos-{plan.seed}-{i}" for i in range(len(specs))}
    restarts = 0
    results: dict = {}
    rt = sites.install(plan, mode="raise")
    try:
        while True:
            try:
                results = _run_to_completion(
                    tmp, cfg, specs, idems, acked, violations,
                    buckets, chunk_steps,
                )
                break
            except sites.ChaosCrash:
                restarts += 1
                if restarts > len(plan.events) + 2:
                    # cannot happen while events fire at most once; a
                    # busted runtime must not hang the campaign
                    violations.append(
                        f"restart loop: {restarts} restarts for "
                        f"{len(plan.events)} planned events"
                    )
                    break
        injected = list(rt.injected)
    finally:
        sites.deactivate()

    rep = run_fsck(tmp)
    for f in rep.corrupt:
        violations.append(
            f"invariant C: fsck {f.kind} at {f.path}: {f.detail}"
        )
    for i in sorted(golden):
        got = results.get(i)
        if got is None:
            if f"invariant A" not in " ".join(violations):
                violations.append(
                    f"invariant A: spec {i} never reached a terminal "
                    "state"
                )
            continue
        if _canon(got) != _canon(golden[i]):
            violations.append(
                f"invariant B: spec {i} result diverged from golden "
                f"(got {_canon(got)[:200]}... want "
                f"{_canon(golden[i])[:200]}...)"
            )
    if not keep_dir:
        shutil.rmtree(tmp, ignore_errors=True)
    return TrialResult(plan=plan, violations=violations,
                       injected=injected, restarts=restarts)


def golden_run(cfg=None, specs=DEFAULT_SPECS, buckets=((2, 1),),
               chunk_steps: int = 16, workdir: str | None = None) -> dict:
    """The fault-free reference: run the identical workload with no plan
    installed and keep each job's terminal state + result."""
    cfg = cfg or _default_cfg()
    tmp = tempfile.mkdtemp(prefix="chaos-golden-", dir=workdir)
    violations: list = []
    acked: dict = {}
    idems = {i: f"golden-{i}" for i in range(len(specs))}
    assert sites.runtime() is None, "golden run must be fault-free"
    try:
        out = _run_to_completion(tmp, cfg, specs, idems, acked,
                                 violations, buckets, chunk_steps)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if violations or set(out) != set(range(len(specs))):
        raise RuntimeError(f"golden run unhealthy: {violations or out}")
    for i, rec in out.items():
        if rec["state"] != "DONE":
            raise RuntimeError(
                f"golden run: spec {i} ended {rec['state']}, want DONE"
            )
    return out


# ---- the socket trial (real server + resilient client) -------------------


def run_socket_trial(
    plan: P.FaultPlan,
    cfg=None,
    specs=DEFAULT_SPECS,
    golden: dict | None = None,
    workdir: str | None = None,
    buckets=((2, 1),),
    chunk_steps: int = 16,
) -> TrialResult:
    """One seeded trial of the wire path: a real PrimeServer thread, a
    ServeClient whose reconnect + idempotency machinery is under test,
    and a plan drawn from the client-side socket sites only (short send,
    mid-frame disconnect, lost reply, duplicate delivery, delay)."""
    import threading
    import time as _time

    from ..analysis.fsck import run_fsck
    from ..serve.client import ServeClient
    from ..serve.server import PrimeServer

    for ev in plan.events:
        if sites.SITES.get(ev.site) != "socket":
            raise ValueError(
                f"socket trial plans must be socket-class only, got "
                f"{ev.site}"
            )
    cfg = cfg or _default_cfg()
    if golden is None:
        golden = golden_run(cfg, specs, buckets=buckets,
                            chunk_steps=chunk_steps, workdir=workdir)
    tmp = tempfile.mkdtemp(prefix="chaos-sock-", dir=workdir)
    violations: list = []
    server = PrimeServer(cfg, state_dir=tmp, buckets=buckets,
                         chunk_steps=chunk_steps, checkpoint_every_s=60.0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    deadline = _time.time() + 60
    while not os.path.exists(server.socket_path):
        if _time.time() > deadline:
            raise RuntimeError("server socket never appeared")
        _time.sleep(0.01)

    rt = sites.install(plan, mode="raise")
    try:
        cli = ServeClient(server.socket_path, timeout_s=60.0,
                          max_reconnects=2 * len(plan.events) + 2)
        results: dict = {}
        for i, spec in enumerate(specs):
            job = cli.submit(synth=spec, client="chaos",
                             idem=f"chaos-{plan.seed}-{i}")
            done = cli.wait(job["job_id"], timeout_s=120.0)
            results[i] = {"state": done["state"],
                          "result": done.get("result")}
        listed = cli.status()
        injected = list(rt.injected)
    finally:
        sites.deactivate()
    try:
        ServeClient(server.socket_path, timeout_s=30.0).drain()
        t.join(timeout=60)
    except Exception:
        pass

    if len(listed) != len(specs):
        violations.append(
            f"invariant D: {len(listed)} jobs in table for "
            f"{len(specs)} submits (duplicate enqueue or loss)"
        )
    for i in sorted(golden):
        got = results.get(i)
        if got is None or _canon(got) != _canon(golden[i]):
            violations.append(
                f"invariant B: spec {i} diverged over the wire"
            )
    rep = run_fsck(tmp)
    for f in rep.corrupt:
        violations.append(
            f"invariant C: fsck {f.kind} at {f.path}: {f.detail}"
        )
    shutil.rmtree(tmp, ignore_errors=True)
    return TrialResult(plan=plan, violations=violations,
                       injected=injected)


# ---- the replication trial (primary + replicas + fenced failover) --------

_REIGN1_TICKS = 40  # primary A's tick budget before the injected host loss


def _boot_replicated(state_dir, cfg, buckets, chunk_steps, targets, node):
    """`_boot` plus the replication sink: journal -> sink -> NEW FENCING
    EPOCH -> recover — the exact order the real server uses, so the
    epoch frame is the first record of every reign."""
    from ..serve.journal import JobJournal, fold_records, serve_compactor
    from ..serve.replicate import ReplicationSink
    from ..serve.scheduler import Scheduler

    journal = JobJournal(state_dir, compactor=serve_compactor)
    sink = ReplicationSink(journal, list(targets), policy="block",
                           node=node)
    journal.sink = sink
    sink.begin_epoch()
    sched = Scheduler(
        cfg, journal, state_dir, buckets=buckets, chunk_steps=chunk_steps,
        checkpoint_every_s=0.0,
    )
    records, _dropped = journal.replay()
    jobs, _clean = fold_records(records)
    for job in jobs.values():
        if job.terminal:
            sched.adopt_terminal(job)
        else:
            sched.requeue_recovered(job)
    if jobs:
        sched._seq = max(
            (int(j.job_id[1:]) for j in jobs.values()
             if j.job_id.startswith("j") and j.job_id[1:].isdigit()),
            default=0,
        )
    return sched, sink


def _submit_quorum(sched, sink, specs, idems, acked, violations) -> None:
    """`_submit_missing`, quorum-aware: a submit only counts as ACKed
    when its frames reached the replica quorum — exactly what the real
    server promises the client. A below-quorum submit stays un-ACKed
    and is retried (same idempotency token) once quorum returns; the
    fold-side dedup turning that retry into an adoption is invariant D's
    business."""
    from ..serve import jobs as J

    for i in range(len(specs)):
        jid = acked.get(i)
        if jid is not None:
            if jid not in sched.jobs:
                violations.append(
                    f"invariant A: ACKed job {jid} (spec {i}) lost after "
                    "failover"
                )
            continue
        dup = next(
            (j for j in sched.jobs.values() if j.idem == idems[i]), None
        )
        if dup is not None:
            acked[i] = dup.job_id  # lost-ACK retry answered by dedup
            continue
        if not sink.quorum_ok():
            continue  # admission blocked: correctly NOT ACKed
        job = J.Job(job_id=sched.next_job_id(), idem=idems[i],
                    client="chaos", synth=specs[i])
        sched.submit(job)
        if sink.quorum_ok():
            acked[i] = job.job_id  # quorum ACK observed by the client


def _reborn(replicas, targets) -> None:
    """Restart every chaos-killed replica over its SURVIVING directory
    (the disk outlives the process) on a fresh port — the operator
    action that restores quorum. In-place list mutation so the caller's
    next sink sees the new targets."""
    from ..serve.replicate import ReplicaServer

    for i, rep in enumerate(replicas):
        if not rep.dead:
            continue
        try:
            rep._srv.server_close()
        except (OSError, AttributeError):
            pass
        fresh = ReplicaServer(rep.store.dir, "127.0.0.1:0")
        replicas[i] = fresh
        targets[i] = fresh.start()


def run_replication_trial(
    plan: P.FaultPlan,
    cfg=None,
    specs=DEFAULT_SPECS,
    golden: dict | None = None,
    workdir: str | None = None,
    keep_dir: bool = False,
    buckets=((2, 1),),
    chunk_steps: int = 16,
) -> TrialResult:
    """One seeded trial of the replicated-journal story (DESIGN.md §21):

    1. primary A (quorum-blocking sink over two in-process replicas)
       submits the workload and ticks under the plan's partitions,
       delivery duplicates, link delays and replica kills;
    2. A's HOST is lost mid-flight (we stop driving it but keep its
       sink alive for the dual-primary probe); dead replicas are
       rebooted over their surviving disks;
    3. standby B promotes: pulls the highest-epoch replica chain, opens a
       higher fencing epoch, re-admits anything never ACKed, finishes
       every job;
    4. the deposed A then attempts a quorum round — if it can still
       ACK, that is INVARIANT E (dual primary) and the trial fails;
    5. checks: A (no quorum-ACKed job lost across the failover),
       B (bit-exact vs golden), C (fsck clean over B's dir),
       D (no idempotency twins), E (above), plus `fsck --compare` of
       B's chain against each surviving replica chain.
    """
    from ..analysis.fsck import run_compare, run_fsck
    from ..serve.replicate import ReplicaServer

    cfg = cfg or _default_cfg()
    if golden is None:
        golden = golden_run(cfg, specs, buckets=buckets,
                            chunk_steps=chunk_steps, workdir=workdir)
    root = tempfile.mkdtemp(prefix="chaos-repl-", dir=workdir)
    a_dir = os.path.join(root, "primary-a")
    b_dir = os.path.join(root, "standby-b")
    r_dirs = [os.path.join(root, f"replica{i}") for i in range(2)]
    os.makedirs(a_dir)
    replicas = [ReplicaServer(d, "127.0.0.1:0") for d in r_dirs]
    targets = [r.start() for r in replicas]

    violations: list = []
    acked: dict = {}
    idems = {i: f"chaos-{plan.seed}-{i}" for i in range(len(specs))}
    restarts = 0
    results: dict = {}
    a_journal = None
    a_sink = None
    rt = sites.install(plan, mode="raise")
    try:
        # -- reign 1: primary A under faults, killed mid-flight ----------
        while True:
            try:
                sched, a_sink = _boot_replicated(
                    a_dir, cfg, buckets, chunk_steps, targets, "A"
                )
                a_journal = sched.journal
                _submit_quorum(sched, a_sink, specs, idems, acked,
                               violations)
                _check_no_twins(sched, idems, violations)
                for _ in range(_REIGN1_TICKS):
                    if acked and all(
                        sched.jobs[j].terminal for j in acked.values()
                        if j in sched.jobs
                    ) and len(acked) == len(specs):
                        break
                    sched.tick()
                    if len(acked) < len(specs) and a_sink.quorum_ok():
                        _submit_quorum(sched, a_sink, specs, idems,
                                       acked, violations)
                break
            except sites.ChaosCrash:
                restarts += 1
                if restarts > len(plan.events) + 2:
                    violations.append(
                        f"restart loop: {restarts} restarts for "
                        f"{len(plan.events)} planned events"
                    )
                    break

        # -- the host loss + operator recovery ---------------------------
        # A is no longer driven (its journal/sink stay live only so the
        # deposed-primary probe below can attempt a doomed quorum
        # round). Dead replicas reboot over their surviving disks FIRST:
        # promotion must see every chain any quorum ever wrote to.
        _reborn(replicas, targets)

        # -- reign 2: standby B promotes and finishes ---------------------
        for _attempt in range(len(plan.events) + 3):
            _reborn(replicas, targets)
            try:
                from ..serve.replicate import pull_chain

                pulled = pull_chain(targets, b_dir)
                if pulled["reachable"] < len(targets):
                    continue  # a replica is still down; "reboot" again
                b_sched, b_sink = _boot_replicated(
                    b_dir, cfg, buckets, chunk_steps, targets, "B"
                )
                _submit_quorum(b_sched, b_sink, specs, idems, acked,
                               violations)
                _check_no_twins(b_sched, idems, violations)
                for _ in range(_MAX_TICKS):
                    if len(acked) == len(specs) and all(
                        b_sched.jobs[j].terminal
                        for j in acked.values() if j in b_sched.jobs
                    ):
                        break
                    b_sched.tick()
                    if len(acked) < len(specs) and b_sink.quorum_ok():
                        _submit_quorum(b_sched, b_sink, specs, idems,
                                       acked, violations)
            except sites.ChaosCrash:
                restarts += 1
                continue
            if len(acked) == len(specs) and all(
                j in b_sched.jobs and b_sched.jobs[j].terminal
                for j in acked.values()
            ):
                results = {
                    i: {"state": b_sched.jobs[jid].state,
                        "result": b_sched.jobs[jid].result}
                    for i, jid in acked.items() if jid in b_sched.jobs
                }
                b_sched.journal.close()
                b_sink.close()
                break
        else:
            violations.append(
                f"replication trial did not converge: {len(acked)} of "
                f"{len(specs)} specs ACKed after every recovery attempt"
            )

        # -- invariant E: the deposed primary must not still ACK ----------
        if a_sink is not None and a_journal is not None:
            try:
                a_sink.heartbeat()
                a_journal.append({
                    "t": "note",
                    "msg": "doomed write from the deposed primary",
                })
            except Exception:  # noqa: BLE001 — any failure IS the fence
                pass
            if a_sink.quorum_ok():
                violations.append(
                    "invariant E: deposed primary (epoch "
                    f"{a_sink.epoch}) still reaches its ack quorum "
                    "after the standby promoted — dual-primary window"
                )
            a_sink.close()
            a_journal.close()

        injected = list(rt.injected)
    finally:
        sites.deactivate()
        for rep in replicas:
            try:
                rep.die()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- post-mortem checks over B's surviving state ----------------------
    rep = run_fsck(b_dir) if os.path.isdir(b_dir) else None
    if rep is not None:
        for f in rep.corrupt:
            violations.append(
                f"invariant C: fsck {f.kind} at {f.path}: {f.detail}"
            )
    for rd in r_dirs:
        if not (os.path.isdir(b_dir) and os.path.isdir(rd)):
            continue
        cmp_rep = run_compare(b_dir, rd)
        for f in cmp_rep.corrupt:
            violations.append(
                f"invariant C: fsck --compare {f.kind}: {f.detail}"
            )
    for i in sorted(golden):
        got = results.get(i)
        if got is None:
            if "invariant A" not in " ".join(violations) \
                    and "did not converge" not in " ".join(violations):
                violations.append(
                    f"invariant A: spec {i} never reached a terminal "
                    "state on the promoted primary"
                )
            continue
        if _canon(got) != _canon(golden[i]):
            violations.append(
                f"invariant B: spec {i} result diverged from golden "
                f"across the failover (got {_canon(got)[:200]}... want "
                f"{_canon(golden[i])[:200]}...)"
            )
    if not keep_dir:
        shutil.rmtree(root, ignore_errors=True)
    return TrialResult(plan=plan, violations=violations,
                       injected=injected, restarts=restarts)


# ---- the attestation trial (silent corruption vs the fingerprint chain) --

_ATTEST_DEADLINE_S = 300.0
_ATTEST_WORKERS = 4  # headroom: every resolved mismatch quarantines one

#: fault-free pooled reference, memoized across a campaign's trials
_attest_golden_memo: dict = {}


def _canon_pool(rec) -> str:
    """`_canon` for pool unit records: additionally drop the attest
    payload (golden runs attest-off, so chains exist only on one side)
    and the suspects list (bookkeeping, not simulation output)."""

    def strip(obj):
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in sorted(obj.items())
                    if k not in _NONDET_KEYS + ("attest", "suspects")}
        if isinstance(obj, list):
            return [strip(v) for v in obj]
        return obj

    return json.dumps(strip(rec), sort_keys=True)


def _pool_drain(root, cfg, specs, attest, audit_rate,
                n_workers=_ATTEST_WORKERS):
    """One pooled campaign, in-process: coordinator over a real socket,
    worker THREADS sharing this process's chaos runtime (so a plan's
    flip events land inside worker executions). Returns (results,
    counters, suspect_workers)."""
    import threading
    import time as _time

    from ..pool import PoolCoordinator, PoolWorker
    from ..pool.units import build_units

    units = build_units(
        cfg, [], list(specs), [{} for _ in specs],
        fold=True, chunk_steps=16, max_steps=100_000,
    )
    coord = PoolCoordinator(
        units, root, lease_ttl_s=30.0, hedge=False,
        attest=attest, audit_rate=audit_rate,
    )
    coord.start()
    try:
        threads = [
            threading.Thread(
                target=PoolWorker(coord.socket_path, f"w{k}",
                                  reconnect_timeout_s=10.0).run,
                daemon=True,
            )
            for k in range(n_workers)
        ]
        for t in threads:
            t.start()
        deadline = _time.monotonic() + _ATTEST_DEADLINE_S
        for t in threads:
            t.join(timeout=max(0.1, deadline - _time.monotonic()))
        results = coord.results()
        counters = dict(coord.counters)
        suspects = set(coord.suspect_workers)
    finally:
        coord.close(drained=coord.done)
    return results, counters, suspects


def attest_golden_run(cfg=None, specs=DEFAULT_SPECS,
                      workdir: str | None = None) -> dict:
    """Fault-free pooled reference for invariant F: index -> canonical
    unit result, attest OFF (the trial's attest-on results must strip
    down to exactly these bytes)."""
    cfg = cfg or _default_cfg()
    key = (cfg.to_json(), tuple(specs))
    hit = _attest_golden_memo.get(key)
    if hit is not None:
        return hit
    assert sites.runtime() is None, "golden run must be fault-free"
    tmp = tempfile.mkdtemp(prefix="chaos-attest-golden-", dir=workdir)
    try:
        results, _counters, _suspects = _pool_drain(
            tmp, cfg, specs, attest="off", audit_rate=0.0, n_workers=1)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    out = {}
    for r in results:
        if r["state"] != "DONE":
            raise RuntimeError(
                f"attest golden run: unit {r['unit_id']} ended "
                f"{r['state']}, want DONE"
            )
        out[r["index"]] = _canon_pool(r["result"])
    _attest_golden_memo[key] = out
    return out


def run_attest_trial(
    plan: P.FaultPlan,
    cfg=None,
    specs=DEFAULT_SPECS,
    golden: dict | None = None,
    workdir: str | None = None,
    keep_dir: bool = False,
) -> TrialResult:
    """One seeded trial of the result-integrity story (DESIGN.md §24):
    a pooled campaign with `--attest chain --audit-rate 1.0` under a
    plan of silent-corruption flips, then machine-check

      F. NO CORRUPTED RESULT DONE-UNFLAGGED — every unit that ends DONE
         carries the fault-free golden result; a corrupted execution
         must have been voided (tiebreak re-run) or ended SUSPECT.

    plus the false-positive dual: a trial where NO flip fired must show
    zero mismatches, zero SUSPECT units and zero quarantined workers."""
    cfg = cfg or _default_cfg()
    # `golden` is the serve-shaped reference run_campaign threads
    # through every trial; the pooled reference is its own shape and is
    # memoized per (config, specs) in attest_golden_run
    del golden
    ref = attest_golden_run(cfg, specs, workdir=workdir)
    tmp = tempfile.mkdtemp(prefix="chaos-attest-", dir=workdir)
    violations: list = []
    rt = sites.install(plan, mode="raise")
    try:
        results, counters, suspects = _pool_drain(
            tmp, cfg, specs, attest="chain", audit_rate=1.0)
        injected = list(rt.injected)
    finally:
        sites.deactivate()

    fired_flips = [e for e in injected if e["site"] in ATTEST_SITES]
    flagged = 0
    for r in results:
        want = ref.get(r["index"])
        if r["state"] == "DONE":
            if want is not None and _canon_pool(r["result"]) != want:
                violations.append(
                    f"invariant F: unit {r['unit_id']} is DONE with a "
                    f"result diverging from golden and no flag (got "
                    f"{_canon_pool(r['result'])[:200]}... want "
                    f"{want[:200]}...)"
                )
        elif r["state"] == "SUSPECT":
            flagged += 1
            if not fired_flips:
                violations.append(
                    f"false positive: unit {r['unit_id']} ended SUSPECT "
                    "with no corruption injected"
                )
        else:
            violations.append(
                f"attest trial did not converge: unit {r['unit_id']} "
                f"ended {r['state']}"
            )
    if not fired_flips:
        if counters.get("attest_mismatches", 0):
            violations.append(
                "false positive: "
                f"{counters['attest_mismatches']} chain mismatch(es) "
                "with no corruption injected"
            )
        if suspects:
            violations.append(
                f"false positive: workers {sorted(suspects)} quarantined "
                "with no corruption injected"
            )
    if not keep_dir:
        shutil.rmtree(tmp, ignore_errors=True)
    return TrialResult(plan=plan, violations=violations,
                       injected=injected)


# ---- the capacity-loss trial (invariant G, DESIGN.md §26) ----------------

# memoized fault-free unsharded reference for the supervisor half —
# one per process, the sharded runs under revocation must match it
_CAP_REF: dict = {}


def _capacity_workload():
    from ..config.machine import small_test_config
    from ..trace import synth

    cfg = small_test_config(8, n_banks=8)
    trace = synth.fft_like(8, n_phases=1, points_per_core=12, seed=7)
    return cfg, trace


def _capacity_reference() -> dict:
    """Unsharded, fault-free supervised run of the capacity workload:
    the bit-exact target every degraded run is held to."""
    import numpy as np

    if _CAP_REF:
        return _CAP_REF
    from ..sim.engine import Engine
    from ..sim.supervisor import RunSupervisor

    assert sites.runtime() is None, "capacity reference must be fault-free"
    cfg, trace = _capacity_workload()
    eng = Engine(cfg, trace, chunk_steps=32)
    RunSupervisor(eng, handle_signals=False).run()
    _CAP_REF["cycles"] = np.asarray(eng.cycles).copy()
    _CAP_REF["counters"] = {
        k: np.asarray(v).copy() for k, v in eng.counters.items()
    }
    return _CAP_REF


def _capacity_supervisor_half(tmp: str, violations: list) -> dict:
    """Run the capacity workload SHARDED under the installed plan's
    `devices.revoke` events and hold the recovered run to the fault-free
    reference (invariant G, bit-exact half). On a single-device backend
    the revocation clamps to a no-op and the run must simply complete."""
    import jax
    import numpy as np

    from ..parallel import sharding
    from ..sim.engine import Engine
    from ..sim.supervisor import RunSupervisor

    ref = _CAP_REF  # populated by run_capacity_trial before install
    cfg, trace = _capacity_workload()
    sharding.restore_devices()
    mesh = None
    n_dev = len(jax.devices())
    if n_dev > 1:
        n = sharding.largest_valid_submesh(cfg, n_dev)
        if n > 1:
            mesh = sharding.tile_mesh(devices=jax.devices()[:n])
    eng = Engine(cfg, trace, chunk_steps=32, mesh=mesh)
    sup = RunSupervisor(
        eng, snapshot_dir=os.path.join(tmp, "snaps"),
        checkpoint_every_chunks=1, handle_signals=False,
    )
    try:
        sup.run()
    except BaseException as e:  # noqa: BLE001 — any escape is a violation
        violations.append(
            f"invariant G: supervised run died under device loss: {e!r}"
        )
        return {"degrade_rungs": list(sup.degrade_rungs)}
    finally:
        sharding.restore_devices()
    if not np.array_equal(np.asarray(eng.cycles), ref["cycles"]):
        violations.append(
            "invariant G: cycles diverged after device-loss recovery "
            f"(rungs: {sup.degrade_rungs})"
        )
    for k, v in eng.counters.items():
        if not np.array_equal(np.asarray(v), ref["counters"][k]):
            violations.append(
                f"invariant G: counter {k} diverged after device-loss "
                f"recovery (rungs: {sup.degrade_rungs})"
            )
            break
    return {"degrade_rungs": list(sup.degrade_rungs)}


def run_capacity_trial(
    plan: P.FaultPlan,
    cfg=None,
    specs=DEFAULT_SPECS,
    golden: dict | None = None,
    workdir: str | None = None,
    keep_dir: bool = False,
    buckets=((2, 1),),
    chunk_steps: int = 16,
) -> TrialResult:
    """One seeded capacity-loss trial. Two halves under ONE runtime:

    - `devices.revoke` events fire at supervised chunk boundaries of a
      sharded run; the reshard -> unshard ladder must keep the result
      bit-exact with the fault-free unsharded reference;
    - `disk.preflight` events open sustained ENOSPC windows under the
      in-process serve stack; the harness retries on `DiskPressureError`
      the way a backpressured client would, and every ACKed job must
      still reach its golden terminal state over a clean journal (fsck).
    """
    from ..analysis.fsck import run_fsck
    from ..util.diskpressure import DiskPressureError

    cfg = cfg or _default_cfg()
    revoke_events = [e for e in plan.events if e.site == "devices.revoke"]
    disk_events = [e for e in plan.events if e.site == "disk.preflight"]
    if revoke_events:
        _capacity_reference()
    if disk_events and golden is None:
        golden = golden_run(cfg, specs, buckets=buckets,
                            chunk_steps=chunk_steps, workdir=workdir)
    tmp = tempfile.mkdtemp(prefix="chaos-capacity-", dir=workdir)
    violations: list = []
    acked: dict = {}
    idems = {i: f"chaos-{plan.seed}-{i}" for i in range(len(specs))}
    restarts = 0
    backpressured = 0
    results: dict = {}
    # a sustained window consumes one probe per free-space recheck, so
    # bound the retry loop by the total window budget, not event count
    window_budget = sum(
        max(1, int(e.arg("calls", 3))) for e in disk_events
    )
    rt = sites.install(plan, mode="raise")
    try:
        if revoke_events:
            _capacity_supervisor_half(tmp, violations)
        if disk_events:
            while True:
                try:
                    results = _run_to_completion(
                        tmp, cfg, specs, idems, acked, violations,
                        buckets, chunk_steps,
                    )
                    break
                except DiskPressureError:
                    # the typed backpressure a live client would absorb:
                    # back off (no real sleep — windows drain per probe)
                    backpressured += 1
                    if backpressured > window_budget + len(plan.events) + 4:
                        violations.append(
                            "invariant G: disk pressure never cleared "
                            f"after {backpressured} backoff rounds"
                        )
                        break
                except sites.ChaosCrash:
                    restarts += 1
                    if restarts > len(plan.events) + 2:
                        violations.append(
                            f"restart loop: {restarts} restarts for "
                            f"{len(plan.events)} planned events"
                        )
                        break
        injected = list(rt.injected)
    finally:
        sites.deactivate()

    rep = run_fsck(tmp)
    for f in rep.corrupt:
        violations.append(
            f"invariant G/C: fsck {f.kind} at {f.path}: {f.detail}"
        )
    if disk_events and golden is not None:
        for i in sorted(golden):
            got = results.get(i)
            if got is None:
                violations.append(
                    f"invariant G/A: spec {i} never reached a terminal "
                    "state under disk pressure"
                )
                continue
            if _canon(got) != _canon(golden[i]):
                violations.append(
                    f"invariant G/B: spec {i} diverged under disk "
                    f"pressure (got {_canon(got)[:200]}...)"
                )
    if not keep_dir:
        shutil.rmtree(tmp, ignore_errors=True)
    return TrialResult(plan=plan, violations=violations,
                       injected=injected, restarts=restarts)


# ---- the campaign --------------------------------------------------------


def _trial_sites(classes) -> tuple[list, set]:
    """(site names plans may use, classes routed to the socket trial)."""
    names: list = []
    socket_only = set()
    for cls in classes:
        for s in SERVE_SITES.get(cls, ()):
            names.append(s)
        if cls == "socket":
            socket_only.add(cls)
    if "replication" in classes:
        names.extend(REPLICATION_SITES)
    if "silent_corruption" in classes:
        names.extend(ATTEST_SITES)
    if "capacity_loss" in classes:
        names.extend(CAPACITY_SITES)
    return names, socket_only


def _gen_classes(classes) -> tuple:
    """Classes handed to the plan generator. `replication` implies the
    replica-kill crashpoint (see REPLICATION_SITES) — the site list
    already narrows the pool, so widening the class filter here cannot
    leak serve-side crashpoints into a replication-only campaign."""
    out = tuple(classes)
    if "replication" in out and "crashpoint" not in out:
        out = out + ("crashpoint",)
    return out


def run_trial(plan, cfg=None, specs=DEFAULT_SPECS, golden=None,
              workdir=None, **kw) -> TrialResult:
    """Dispatch one plan to the harness that can reach its sites: plans
    touching any replication site need the primary+replicas+standby
    topology; plans touching only socket sites go over the wire;
    everything else runs the in-process serve trial (mixed plans run
    in-process, where the socket sites are simply never reached and
    those events stay inert)."""
    if plan.events and any(
        e.site in REPLICATION_SITES for e in plan.events
    ):
        return run_replication_trial(plan, cfg=cfg, specs=specs,
                                     golden=golden, workdir=workdir, **kw)
    if plan.events and any(
        e.site in ATTEST_SITES for e in plan.events
    ):
        # a flip in a serve-trial fleet would be an undetectable bogus
        # invariant-B failure; corruption plans get the attested pool
        return run_attest_trial(plan, cfg=cfg, specs=specs,
                                golden=golden, workdir=workdir, **kw)
    if plan.events and any(
        e.site in CAPACITY_SITES for e in plan.events
    ):
        # device revocation needs a sharded supervised engine and
        # ENOSPC windows need a backpressure-aware client (invariant G)
        return run_capacity_trial(plan, cfg=cfg, specs=specs,
                                  golden=golden, workdir=workdir, **kw)
    if plan.events and all(
        sites.SITES.get(e.site) == "socket" for e in plan.events
    ):
        return run_socket_trial(plan, cfg=cfg, specs=specs,
                                golden=golden, workdir=workdir, **kw)
    return run_serve_trial(plan, cfg=cfg, specs=specs, golden=golden,
                           workdir=workdir, **kw)


def run_campaign(
    n_trials: int = 20,
    seed0: int = 0,
    classes: tuple = ("durable", "crashpoint"),
    cfg=None,
    specs=DEFAULT_SPECS,
    workdir: str | None = None,
    artifact_dir: str | None = None,
    max_events: int = 3,
    progress=None,
) -> dict:
    """N seeded trials; on violation, bisect-shrink the plan to a
    1-minimal event set and write a replayable repro artifact. Returns
    the campaign report (the `primetpu chaos` JSON surface)."""
    cfg = cfg or _default_cfg()
    # a pure silent_corruption campaign never runs a serve trial, so
    # its serve-shaped golden would be wasted work
    golden = (golden_run(cfg, specs, workdir=workdir)
              if any(c != "silent_corruption" for c in classes) else None)
    site_pool, _ = _trial_sites(classes)
    report = {
        "trials": 0, "violations": [], "fired_events": 0,
        "classes": list(classes), "seed0": seed0,
    }
    gen_classes = _gen_classes(classes)
    for k in range(n_trials):
        seed = seed0 + k
        plan = P.generate(seed, classes=gen_classes, sites=site_pool,
                          max_events=max_events)
        res = run_trial(plan, cfg=cfg, specs=specs, golden=golden,
                        workdir=workdir)
        report["trials"] += 1
        report["fired_events"] += len(res.injected)
        if progress is not None:
            progress(seed, res)
        if res.ok:
            continue

        def still_fails(cand) -> bool:
            return not run_trial(cand, cfg=cfg, specs=specs,
                                 golden=golden, workdir=workdir).ok

        shrunk = P.shrink(plan, still_fails)
        final = run_trial(shrunk, cfg=cfg, specs=specs, golden=golden,
                          workdir=workdir)
        artifact = {
            "seed": seed,
            "plan": shrunk.as_dict(),
            "original_events": len(plan.events),
            "shrunk_events": len(shrunk.events),
            "violations": list(final.violations or res.violations),
            "injected": list(final.injected),
            "repro": "primetpu chaos --plan <this file>",
        }
        path = None
        if artifact_dir:
            os.makedirs(artifact_dir, exist_ok=True)
            path = os.path.join(artifact_dir, f"chaos-repro-{seed}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
        artifact["artifact_path"] = path
        report["violations"].append(artifact)
    report["ok"] = not report["violations"]
    return report


def replay_artifact(path: str, cfg=None, specs=DEFAULT_SPECS,
                    workdir=None) -> TrialResult:
    """Re-run the exact plan a repro artifact (or bare plan JSON)
    carries — the one-line repro loop."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    plan = P.FaultPlan.from_dict(doc.get("plan", doc))
    return run_trial(plan, cfg=cfg, specs=specs, workdir=workdir)
