"""Dispatch scheduler — the elastic front-end's remote execution path
(DESIGN.md §18).

`PrimeServer --dispatch` swaps the in-process `Scheduler` for this
class: same journal, same job table, same verb surface, but instead of
splicing jobs into local fleet slots it converts each accepted job into
a pool WORK UNIT (units.py) and enqueues it on a dynamic-mode
coordinator, where an autoscaling fleet of `primetpu worker` processes
executes it under the lease/heartbeat/ack protocol. Each worker owns a
warm compiled fleet per geometry bucket, so the slot-bucket design
scales from one process's batch axis to a process fleet.

Process model (everything crash-only):

- the COORDINATOR is spawned as a subprocess over `--pool-dir` unless
  something already listens on the pool socket — in which case this
  front-end ADOPTS it (the standby-takeover path: kill -9 the primary
  front-end, start another on the same state dir + pool dir, and the
  coordinator, its workers, and every lease keep running);
- WORKERS autoscale: the front-end keeps min(max_workers, nonterminal
  jobs) alive, spawning with `--idle-exit` so drained capacity retires
  itself; worker death needs no bookkeeping here because lease expiry
  already re-dispatches (the pool's failure detector is the only one);
- the front-end's own kill -9 is covered by the serve journal: replay
  rebuilds the job table and `requeue_recovered` re-enqueues — the
  coordinator's idempotent `enqueue` verb replies with the unit's
  CURRENT state, including results computed while the front-end was
  dead, so nothing re-simulates.

Bit-exactness: workers run serve units in capacity buckets from the
same page ladder with the same chunking, and their extended ack detail
is mapped 1:1 onto the shape `Scheduler._element_result` produces — a
job's result is identical whether it ran locally, remotely, or via a
post-crash re-dispatch.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

from ..obs.metrics import Histogram
from ..pool.units import unit_key
from . import jobs as J
from .protocol import error_obj, request, socket_alive
from .scheduler import (
    DEFAULT_BUCKETS,
    PAGE_EVENTS,
    QueueFull,
    materialize_workload,
)


class DispatchScheduler:
    """Scheduler-API-compatible front half over a worker pool. The
    server's tick loop, verb handlers, and recovery path drive it
    exactly like the local Scheduler."""

    def __init__(
        self,
        cfg,
        journal,
        state_dir: str,
        pool_dir: str,
        buckets=DEFAULT_BUCKETS,
        chunk_steps: int = 128,
        max_queue: int = 64,
        max_workers: int = 2,
        lease_ttl_s: float = 10.0,
        obs=None,
        spawn: bool = True,
        poll_every_s: float = 0.2,
        devices: int = 0,
        attest: str = "off",
        audit_rate: float = 0.0,
    ):
        self.cfg = cfg
        self.journal = journal
        self.obs = obs
        self.attest = str(attest or "off")
        self.audit_rate = float(audit_rate or 0.0)
        self.state_dir = str(state_dir)
        self.pool_dir = str(pool_dir)
        os.makedirs(self.pool_dir, exist_ok=True)
        self.pool_socket = os.path.join(self.pool_dir, "pool.sock")
        self.page_ladder = sorted({int(p) for _, p in buckets})
        self.chunk_steps = int(chunk_steps)
        self.max_queue = int(max_queue)
        self.max_workers = int(max_workers)
        self.lease_ttl_s = float(lease_ttl_s)
        self.spawn = bool(spawn)  # False: tests run coord/workers themselves
        self.poll_every_s = float(poll_every_s)
        self.devices = int(devices)
        if self.devices:
            # fail service bring-up on a bad mesh shape, not every
            # leased unit on every worker
            from ..parallel.sharding import validate_devices

            validate_devices(cfg, self.devices)

        self.jobs: dict[str, J.Job] = {}
        self.queue: list[str] = []  # accepted, not yet enqueued remotely
        self.dispatched: set[str] = set()  # unit ids enqueued, not terminal
        self.unit_aliases: dict[str, str] = {}  # rebucketed unit id -> job id
        self._rebucket_gen = 0
        self.buckets = []  # API parity: no local fleets in dispatch mode
        self._seq = 0
        self._last_poll_t = 0.0
        self._coord_proc = None
        self._coord_spawn_t = 0.0
        self._workers: list = []
        self._worker_seq = 0
        self._last_worker_spawn_t = 0.0
        self.coordinator_adopted = False  # standby takeover happened
        self.started_t = time.time()
        self.total_instructions = 0
        self.completed = 0
        self._latencies: list[float] = []
        self.latency_hist = Histogram()
        self.last_dispatch_t: float | None = None

    def _serve_event(self, kind: str, **args) -> None:
        if self.obs is not None:
            self.obs.serve_event(kind, args)

    # ---- identity ---------------------------------------------------------

    def next_job_id(self) -> str:
        self._seq += 1
        return f"j{self._seq:06d}"

    # ---- admission --------------------------------------------------------

    def submit(self, job: J.Job) -> J.Job:
        """Admit one job: backpressure check, durable accept record
        (fsynced BEFORE this returns — the ACK invariant), workload
        validation + bucket assignment, enqueue for dispatch."""
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                len(self.queue), retry_after_s=1.0 + 0.1 * len(self.queue)
            )
        self.jobs[job.job_id] = job
        self.journal.accept(job)
        self._serve_event("admit", job_id=job.job_id, client=job.client,
                          priority=job.priority)
        if self._validate_and_bucket(job):
            self.queue.append(job.job_id)
        return job

    def _validate_and_bucket(self, job: J.Job) -> bool:
        """Materialize the workload (deterministic, same as the local
        path), pick the smallest ladder page size whose capacity fits
        the trace, and stash it as `job._pages`. The trace itself is
        dropped — workers re-materialize from the spec; the front-end
        never holds event arrays."""
        try:
            tr = materialize_workload(job, self.cfg)
        except Exception as e:  # bad workload must not kill the daemon
            self._terminal(job, J.QUARANTINED, detail=error_obj(e)["error"])
            return False
        pages = next(
            (p for p in self.page_ladder
             if p * PAGE_EVENTS >= tr.max_len), None
        )
        if pages is None:
            cap = max(self.page_ladder) * PAGE_EVENTS
            self._terminal(
                job, J.QUARANTINED,
                detail={
                    "type": "CapacityError",
                    "location": {},
                    "detail": (
                        f"trace needs {tr.max_len} event slots/core; "
                        f"largest bucket holds {cap}"
                    ),
                },
            )
            return False
        job._pages = pages
        job._trace = None  # workers re-materialize; don't hold events
        job._ctx = None
        return True

    def _unit_spec(self, job: J.Job) -> dict:
        jid = job.job_id
        spec = {
            # a rebucketed job re-enqueues under a FRESH unit id: the
            # coordinator's enqueue is idempotent per (id, key) and the
            # key covers `devices`, so the shrunken bucket is a new unit
            "unit_id": getattr(job, "_unit_alias", None) or jid,
            "index": int(jid[1:]) if jid[1:].isdigit() else 0,
            "config": self.cfg.to_json(),
            "trace_path": job.trace_path,
            "synth": job.synth,
            "fold": bool(job.fold),
            "overrides": dict(job.overrides),
            "chunk_steps": self.chunk_steps,
            "max_steps": int(job.max_steps),
            "warm_cache": False,
            "capacity_pages": int(getattr(job, "_pages", None)
                                  or max(self.page_ladder)),
            "serve_job": True,
            "priority": int(job.priority),
            "client": str(job.client),
        }
        if self.devices:
            # geometry bucket with a mesh shape: the leasing worker owns
            # a sharded fleet over this many devices (shard x vmap)
            spec["devices"] = self.devices
        spec["key"] = unit_key(spec)
        return spec

    # ---- recovery (journal replay, same hooks as Scheduler) ---------------

    def adopt_terminal(self, job: J.Job) -> None:
        self.jobs[job.job_id] = job

    def requeue_recovered(self, job: J.Job) -> None:
        """Journal-replayed non-terminal job after a front-end restart:
        re-validate and line it back up. The coordinator's idempotent
        enqueue resolves what actually happened while we were dead — a
        unit that finished meanwhile comes straight back DONE."""
        self.jobs[job.job_id] = job
        if self._validate_and_bucket(job):
            self.queue.append(job.job_id)

    def cancel(self, job_id: str) -> J.Job:
        job = self.jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        if job.terminal:
            raise ValueError(f"{job_id} already terminal ({job.state})")
        if job_id in self.queue:
            self.queue.remove(job_id)
        # an already-dispatched unit may still finish on a worker; its
        # late collect result is discarded because terminal is sticky
        self.dispatched.discard(job_id)
        alias = getattr(job, "_unit_alias", None)
        if alias:
            self.dispatched.discard(alias)
        self._terminal(job, J.CANCELLED, detail={"detail": "client cancel"})
        return job

    # ---- the dispatch tick ------------------------------------------------

    def tick(self) -> bool:
        """One front-end round: babysit the coordinator, flush pending
        enqueues, autoscale workers, poll for lease/finish transitions.
        Returns True when any job state moved (the server idles its loop
        when False)."""
        now = time.time()
        self._expire_deadlines(now)
        moved = False
        if not self._ensure_coordinator(now):
            return False  # coordinator (re)starting; try next tick
        moved |= self._flush_enqueues()
        self._autoscale(now)
        if now - self._last_poll_t >= self.poll_every_s:
            self._last_poll_t = now
            moved |= self._poll_outcomes()
        return moved

    def _coord_request(self, req: dict) -> dict | None:
        try:
            reply = request(self.pool_socket, req, timeout_s=5.0,
                            connect_timeout_s=2.0)
        except (ConnectionError, OSError):
            return None
        return reply if reply.get("ok") else None

    def _ensure_coordinator(self, now: float) -> bool:
        """True when a coordinator accepts connections on the pool
        socket. An already-live one is ADOPTED (standby takeover, or a
        coordinator that outlived a front-end kill -9 — its leases and
        workers keep running); otherwise spawn one, rate-limited so a
        crash-looping coordinator cannot fork-bomb the host."""
        if socket_alive(self.pool_socket):
            if self._coord_proc is None and not self.coordinator_adopted:
                self.coordinator_adopted = True
                self._serve_event("adopt_coordinator", pool=self.pool_dir)
                self.journal.note(
                    f"dispatch: adopted live coordinator on "
                    f"{self.pool_socket}"
                )
            return True
        if not self.spawn:
            return False
        proc = self._coord_proc
        if proc is not None and proc.poll() is None:
            if now - self._coord_spawn_t < 10.0:
                return False  # own coordinator still binding
            proc.kill()  # alive but never bound: replace, don't stack
            proc.wait(timeout=5)
        if now - self._coord_spawn_t < 1.0:
            return False  # spawn in flight or backing off
        self._coord_spawn_t = now
        self.coordinator_adopted = False
        argv = [
            sys.executable, "-m", "primesim_tpu.cli", "coordinator",
            "--pool-dir", self.pool_dir,
            "--socket", self.pool_socket,
            "--lease-ttl", str(self.lease_ttl_s),
        ]
        if self.attest != "off":
            argv += ["--attest", self.attest,
                     "--audit-rate", str(self.audit_rate)]
        self._coord_proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL)
        self._serve_event("spawn_coordinator", pool=self.pool_dir,
                          pid=self._coord_proc.pid)
        return False  # let it bind; enqueue on a later tick

    def _flush_enqueues(self) -> bool:
        moved = False
        for job_id in list(self.queue):
            job = self.jobs[job_id]
            spec = self._unit_spec(job)
            reply = self._coord_request({"verb": "enqueue", "unit": spec})
            if reply is None:
                break  # coordinator unreachable; retry next tick
            self.queue.remove(job_id)
            self.dispatched.add(spec["unit_id"])
            moved = True
            if reply.get("state") in ("DONE", "POISON", "SUSPECT"):
                # finished while we were down (front-end restart path)
                self._finish_remote(job, reply)
        return moved

    def _autoscale(self, now: float) -> None:
        """Keep min(max_workers, live demand) workers alive. Scale-up is
        spawn; scale-down is the workers' own --idle-exit. Lease expiry
        covers crashed workers' WORK; this covers their CAPACITY."""
        if not self.spawn:
            return
        self._workers = [w for w in self._workers if w.poll() is None]
        want = min(self.max_workers, len(self.queue) + len(self.dispatched))
        if len(self._workers) >= want:
            return
        if now - self._last_worker_spawn_t < 0.5:
            return  # rate-limit a crash-looping fleet
        self._last_worker_spawn_t = now
        while len(self._workers) < want:
            self._worker_seq += 1
            wid = f"dw{self._worker_seq}"
            argv = [
                sys.executable, "-m", "primesim_tpu.cli", "worker",
                "--connect", self.pool_socket,
                "--worker-id", wid,
                "--reconnect-timeout", str(self.lease_ttl_s * 6.0),
                "--idle-exit", "10",
            ]
            # propagate `serve --exec-cache on` so autoscaled workers
            # deserialize the fleet executable at lease grant (§23)
            from ..sim import exec_cache

            if exec_cache.active() is not None:
                argv += ["--exec-cache", "on"]
            proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL)
            self._workers.append(proc)
            self._serve_event("spawn_worker", worker=wid, pid=proc.pid)

    def _poll_outcomes(self) -> bool:
        if not self.dispatched:
            return False
        reply = self._coord_request(
            {"verb": "collect", "unit_ids": sorted(self.dispatched)}
        )
        if reply is None:
            return False
        moved = False
        for unit_id in reply.get("leased", ()):
            job = self._job_for_unit(unit_id)
            if job is not None and job.state == J.PENDING:
                job.attempts += 1
                job.transition(J.RUNNING)
                self.last_dispatch_t = time.time()
                self.journal.state(
                    job.job_id, J.RUNNING,
                    detail={"attempt": job.attempts, "remote": True},
                )
                self._serve_event("dispatch", job_id=job.job_id,
                                  remote=True, attempt=job.attempts)
                moved = True
        for fin in reply.get("finished", ()):
            job = self._job_for_unit(str(fin.get("unit_id")))
            if job is None or job.terminal:
                continue  # cancelled meanwhile, or unknown: drop
            self._finish_remote(job, fin)
            moved = True
        return moved

    def _job_for_unit(self, unit_id: str) -> J.Job | None:
        """Pool unit id -> serve job: identity for first-dispatch units,
        via the alias map for rebucketed re-enqueues."""
        job = self.jobs.get(unit_id)
        if job is not None:
            return job
        return self.jobs.get(self.unit_aliases.get(unit_id, ""))

    def _finish_remote(self, job: J.Job, fin: dict) -> None:
        """Map a worker's unit outcome onto the serve job, producing the
        same result shape as `Scheduler._element_result`."""
        self.dispatched.discard(job.job_id)
        alias = getattr(job, "_unit_alias", None)
        if alias:
            self.dispatched.discard(alias)
        if job.state == J.PENDING:
            # terminal transitions are only legal from RUNNING; the
            # lease happened while we weren't looking
            job.attempts += 1
            job.transition(J.RUNNING)
            self.last_dispatch_t = time.time()
        rec = fin.get("result") or {}
        detail = rec.get("detail") or {}
        if fin.get("state") == "SUSPECT":
            # attested results diverged and the tiebreak could not
            # adjudicate — terminal like poison, but the held evidence
            # stays in the pool ledger for `primetpu audit` / fsck
            suspects = fin.get("suspects") or []
            self._serve_event("suspect", job_id=job.job_id,
                              workers=suspects)
            self._terminal(
                job, J.QUARANTINED,
                detail={
                    "type": "AttestationError",
                    "location": {"unit": job.job_id},
                    "detail": (
                        "attested results diverged across "
                        f"{len(suspects)} worker(s) and a tiebreak did "
                        "not adjudicate; held payloads are in the pool "
                        "ledger"
                    ),
                    "workers": suspects,
                },
            )
            return
        if fin.get("state") == "POISON":
            self._terminal(
                job, J.QUARANTINED,
                detail={
                    "type": "PoisonError",
                    "location": {},
                    "detail": (
                        "unit killed "
                        f"{len(fin.get('kills') or [])} worker(s); "
                        "quarantined as poison"
                    ),
                },
            )
            return
        if rec.get("metric") == "quarantined":
            err = detail.get("error") or {}
            if (err.get("type") == "DeviceMeshError"
                    and self._rebucket_devices(job, err)):
                return  # re-enqueued on a smaller geometry bucket
            self._terminal(
                job, J.QUARANTINED,
                detail=detail.get("error")
                or {"detail": "quarantined on worker"},
            )
            return
        result = {
            "cycles": int(detail.get("max_core_cycles", 0)),
            "core_cycles": detail.get("core_cycles"),
            "steps": detail.get("steps"),
            "instructions": int(detail.get("instructions", 0)),
            "counters": detail.get("counters"),
        }
        if detail.get("attest"):
            # chain head rides the journaled result, same as the local
            # Scheduler's _element_result (fsck / offline audit hook)
            result["attest"] = detail["attest"]
        self.total_instructions += result["instructions"]
        self.completed += 1
        self._terminal(job, J.DONE, result=result, detail={
            "worker_mips": rec.get("value"),
            "resumed_steps": fin.get("resumed_steps", 0),
        })
        self._serve_event("retire", job_id=job.job_id, state=J.DONE,
                          remote=True)

    def _rebucket_devices(self, job: J.Job, err: dict) -> bool:
        """Degraded-mode elasticity (DESIGN.md §26): a worker could not
        host this job's mesh (devices revoked or too few visible), so the
        unit came back quarantined with a DeviceMeshError. Instead of
        quarantining the JOB, shrink the service's geometry bucket to the
        largest mesh the reported capacity can host and re-enqueue under
        a fresh unit id. False means the error is not recoverable this
        way (no smaller valid mesh) and the caller quarantines as before."""
        if not self.devices or self.devices <= 1:
            return False
        from ..parallel.sharding import DeviceMeshError, largest_valid_submesh

        loc = err.get("location") or {}
        try:
            visible = int(loc.get("visible"))
        except (TypeError, ValueError):
            visible = self.devices - 1
        try:
            n = largest_valid_submesh(
                self.cfg, min(visible, self.devices - 1)
            )
        except DeviceMeshError:
            return False  # zero capacity reported: nothing to shrink to
        if n < 1 or n >= self.devices:
            return False
        prev, self.devices = self.devices, n
        self._rebucket_gen += 1
        alias = f"{job.job_id}r{self._rebucket_gen}"
        job._unit_alias = alias
        self.unit_aliases[alias] = job.job_id
        job.transition(J.PENDING)
        self.queue.append(job.job_id)
        self.journal.state(
            job.job_id, J.PENDING,
            detail={"rebucket": {"devices_from": prev, "devices_to": n}},
        )
        self._serve_event("rebucket", job_id=job.job_id,
                          devices_from=prev, devices_to=n)
        return True

    def _expire_deadlines(self, now: float) -> None:
        for job_id in list(self.queue):
            job = self.jobs[job_id]
            if job.deadline_expired(now):
                self.queue.remove(job_id)
                self._terminal(
                    job, J.TIMEOUT,
                    detail={"detail": f"deadline {job.deadline_s}s expired "
                                      "in queue"},
                )

    # ---- server-loop hooks ------------------------------------------------

    def pending_work(self) -> bool:
        return bool(self.queue) or bool(self.dispatched)

    def drain(self) -> int:
        """Graceful shutdown: journal the drain marker. In-flight units
        keep their coordinator-side checkpoints; the next front-end
        re-adopts them through idempotent enqueue. Returns the number of
        unfinished jobs."""
        unfinished = len(self.queue) + len(self.dispatched)
        self.journal.drain()
        return unfinished

    def checkpoint_running(self) -> None:
        """No-op in dispatch mode: workers own the element checkpoints
        (deterministic per-unit paths under the pool dir)."""

    def shutdown_children(self, graceful: bool = True) -> None:
        """Retire the subprocesses this front-end spawned. Adopted
        coordinators are left alone — the standby that adopted them (or
        the next front-end) still needs them."""
        for w in self._workers:
            if w.poll() is None:
                (w.terminate if graceful else w.kill)()
        if self._coord_proc is not None and self._coord_proc.poll() is None:
            (self._coord_proc.terminate
             if graceful else self._coord_proc.kill)()
        deadline = time.time() + 5.0
        for p in [*self._workers, self._coord_proc]:
            if p is None:
                continue
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        self._workers = []
        self._coord_proc = None

    # ---- terminal bookkeeping / stats (Scheduler parity) ------------------

    def _terminal(self, job: J.Job, state: str, detail: dict | None = None,
                  result: dict | None = None) -> None:
        job.transition(state, detail=detail)
        if result is not None:
            job.result = result
        self.journal.state(job.job_id, state, detail=detail, result=result)
        if job.latency_s is not None:
            self._latencies.append(job.latency_s)
            self.latency_hist.observe(job.latency_s)
            if len(self._latencies) > 512:
                del self._latencies[:-512]

    def stats(self) -> dict:
        now = time.time()
        by_state = {s: 0 for s in J.STATES}
        for job in self.jobs.values():
            by_state[job.state] += 1
        lat = sorted(self._latencies)

        def pct(p):
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3)

        wall = max(now - self.started_t, 1e-9)
        live_workers = sum(1 for w in self._workers if w.poll() is None)
        return {
            "queue_depth": len(self.queue),
            "dispatched": len(self.dispatched),
            "slots": {
                # dispatch mode: "slots" are worker processes
                "total": self.max_workers,
                "occupied": live_workers,
                "buckets": [],
            },
            "workers": {
                "live": live_workers,
                "max": self.max_workers,
                "spawned": self._worker_seq,
                "coordinator_adopted": self.coordinator_adopted,
            },
            "jobs": by_state,
            "completed": self.completed,
            "aggregate_mips": round(
                self.total_instructions / wall / 1e6, 3
            ),
            "latency_s": {"p50": pct(0.50), "p90": pct(0.90),
                          "p99": pct(0.99)},
            "uptime_s": round(wall, 1),
            "last_dispatch_t": self.last_dispatch_t,
            "last_dispatch_age_s": (
                round(now - self.last_dispatch_t, 1)
                if self.last_dispatch_t else None
            ),
        }

    def service_report(self) -> dict:
        s = self.stats()
        return {
            "jobs_completed": s["completed"],
            "jobs_by_state": {k: v for k, v in s["jobs"].items() if v},
            "aggregate_mips": s["aggregate_mips"],
            "latency_s": s["latency_s"],
            "uptime_s": s["uptime_s"],
            "workers": s["workers"],
        }
