"""Journal replication + fenced hot-standby failover (DESIGN.md §21).

The crash-safety story so far (§14/§18) bottoms out in ONE fsynced
journal chain on ONE filesystem: kill -9 of any process is survivable,
losing the front-end HOST (or its disk) is not. This module closes that
hole with classic primary-backup quorum commit:

- the primary's `JobJournal` streams every appended frame — and every
  segment roll / compaction BASE — to N follower replicas over the same
  JSON-lines protocol the front door speaks (`repl.*` verbs);
- frames travel as RAW framed lines, so a follower's segment chain is
  byte-identical to the primary's (same CRCs, same headers, same roll
  points) and `primetpu fsck --compare` can hold the two directories to
  frame-for-frame agreement;
- `append()` reports quorum only after K replicas ACKed an fsync of the
  frame (default K = a strict majority of the N replicas, `N//2 + 1`;
  any explicit `--quorum` must satisfy `2K > N`, the intersection
  property the fencing argument stands on). The SERVER only ACKs a
  submit whose accept record reached quorum — ACKed now means "on K+1
  disks", not "on one disk";
- a follower that was down catches up on reconnect: the primary reads
  its tip (active seq + record count + last chained CRC), verifies the
  tip CRC against its own chain at the identical position (seq ranges
  alone cannot prove a byte-prefix once a diverged tail has crossed a
  roll boundary), and re-ships the segment range past it; a follower
  behind a compaction BASE — or one whose tip CRC diverges — is reset
  and resynced from the BASE (its stale chain, including any
  un-quorumed tail inherited from a deposed primary, is discarded
  wholesale);
- FENCING: each primary reign opens by appending a monotonically
  increasing `{"t": "epoch"}` frame and announcing the epoch on every
  link. Replicas remember the highest epoch they ever ACKed and refuse
  (reply `fenced`) anything older. A deposed primary sees `fenced` on
  its next quorum round, stops ACKing, and exits 75 — a healed
  partition can never yield two concurrently-ACKing primaries, because
  the new primary's epoch frame must itself reach quorum before the new
  primary ACKs, and any quorum overlaps any other quorum in at least
  one replica that will fence the loser.

Degradation is explicit policy, not accident: below quorum the server
either blocks admission with `ReplicaQuorumLost` + retry_after_s
(default) or — opt-in `--quorum-policy degrade` — keeps ACKing on local
fsync while loudly flagging health and metrics.

The follower side (`ReplicaServer` over a `ReplicaStore`) is a plain
directory of journal segments maintained by byte-blind application of
primary orders, so the coordinator's pool ledger — same `JobJournal`
class — replicates through the identical machinery for free.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from ..chaos import sites as chaos
from ..util.backoff import DecorrelatedJitter
from .journal import JobJournal, _line_crc, _scan_lines, _unframe
from .protocol import (
    encode,
    error_obj,
    format_target,
    make_listener,
    parse_target,
    read_line,
)

#: replica-side verbs (one JSON line each way, over a PERSISTENT
#: connection — unlike the front door's one-shot `request()`):
#:   repl.hello  {epoch}                      -> {epoch, tip}
#:   repl.append {epoch, seq, prev, line}     -> ack after fsync
#:   repl.roll   {epoch, seq, header_line}    -> rolled + fresh active
#:   repl.seg    {epoch, seq, lines, active}  -> wholesale segment write
#:   repl.reset  {epoch}                      -> wipe chain (pre-resync)
#:   repl.fetch  {from_seq}                   -> {segments} (standby pull)
#:   repl.status {}                           -> {epoch, chain_epoch, tip}
REPL_VERBS = (
    "repl.hello", "repl.append", "repl.roll", "repl.seg",
    "repl.reset", "repl.fetch", "repl.status",
)

_ACTIVE = "journal.jsonl"


class ReplicaQuorumLost(RuntimeError):
    """Fewer than the configured quorum of replicas ACKed — under the
    default `block` policy the server refuses admission with this (plus
    a retry_after_s hint) instead of ACKing a frame that is durable on
    one disk only."""

    def __init__(self, msg: str, retry_after_s: float = 2.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class PrimaryFenced(RuntimeError):
    """A replica reported a higher fencing epoch: another primary has
    been promoted. This node must stop ACKing and exit 75 — its
    un-quorumed tail will be discarded when it rejoins as a follower."""

    def __init__(self, msg: str, epoch: int = 0):
        super().__init__(msg)
        self.epoch = int(epoch)


def max_epoch(records: list[dict]) -> int:
    """Highest fencing epoch in a replayed record stream (0 = none)."""
    e = 0
    for rec in records:
        if rec.get("t") == "epoch":
            e = max(e, int(rec.get("epoch", 0)))
    return e


# ---- follower side -------------------------------------------------------


class ReplicaStore:
    """A follower's journal directory: byte-blind segment chain kept
    identical to the primary's by applying its orders verbatim. Never
    parses record semantics beyond the frame CRC it inherits on disk —
    replication is a transport, the fold stays the primary's business."""

    def __init__(self, directory: str):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, _ACTIVE)
        self._lock = threading.Lock()
        self.applied = 0
        self.resyncs = 0

    # -- chain introspection ----------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        from .journal import _SEG_RE

        out = []
        for name in os.listdir(self.dir):
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, name)))
        out.sort()
        if os.path.exists(self.path):
            seq = out[-1][0] + 1 if out else 0
            lines = _scan_lines(self.path)
            if lines:
                first = _unframe(lines[0])
                if first is not None and first.get("t") == "seg":
                    seq = int(first.get("seq", seq))
            out.append((seq, self.path))
        return out

    def tip(self) -> dict:
        """{seq, records, crc} of the active segment as it sits on disk
        — the position the primary diffs against for catch-up."""
        segs = self._segments()
        if not segs:
            return {"seq": -1, "records": 0, "crc": 0}
        seq, path = segs[-1]
        lines = _scan_lines(path)
        n = 0
        crc = 0
        for i, line in enumerate(lines):
            rec = _unframe(line)
            if rec is None:
                break  # torn tail: position is the last whole frame
            if not (i == 0 and rec.get("t") == "seg"):
                n += 1
            crc = _line_crc(line)
        return {"seq": seq, "records": n, "crc": crc}

    def _fsync_dir(self) -> None:
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _write_durable(self, path: str, text: str, mode: str) -> None:
        with open(path, mode, encoding="utf-8") as f:
            f.write(text)
            f.flush()
            chaos.crashpoint("replica.pre-fsync-ack")
            os.fsync(f.fileno())

    # -- orders from the primary ------------------------------------------

    def apply_append(self, seq: int, prev: int, line: str) -> dict:
        """Append one raw frame iff it chains onto our tip; a position
        mismatch (we missed frames, or carry a diverged tail) asks the
        primary for a resync instead of corrupting the chain."""
        with self._lock:
            t = self.tip()
            if t["seq"] != int(seq) or t["crc"] != int(prev):
                return {"ok": False, "resync": True, "tip": t}
            self._write_durable(self.path, line + "\n", "a")
            self.applied += 1
            return {"ok": True, "crc": _line_crc(line)}

    def apply_roll(self, seq: int, header_line: str) -> dict:
        """Mirror the primary's roll: rename our active segment into the
        rolled sequence and open a fresh active holding `header_line`."""
        with self._lock:
            t = self.tip()
            if t["seq"] != int(seq) - 1:
                return {"ok": False, "resync": True, "tip": t}
            if os.path.exists(self.path):
                rolled = os.path.join(
                    self.dir, f"journal-{t['seq']:06d}.jsonl"
                )
                os.replace(self.path, rolled)
            self._write_durable(self.path, header_line + "\n", "w")
            self._fsync_dir()
            return {"ok": True, "crc": _line_crc(header_line)}

    def apply_seg(self, seq: int, lines: list[str], active: bool) -> dict:
        """Wholesale segment write (catch-up / resync): our copy of the
        segment becomes exactly these raw lines."""
        with self._lock:
            path = self.path if active else os.path.join(
                self.dir, f"journal-{int(seq):06d}.jsonl"
            )
            self._write_durable(path, "".join(l + "\n" for l in lines),
                                "w")
            self._fsync_dir()
            return {"ok": True}

    def apply_reset(self) -> dict:
        """Wipe the local chain ahead of a full resync — how a diverged
        or behind-a-BASE follower discards history (including any
        un-quorumed tail a deposed primary left us)."""
        with self._lock:
            for _, path in self._segments():
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._fsync_dir()
            self.resyncs += 1
            return {"ok": True}

    def fetch(self, from_seq: int = 0) -> dict:
        """Raw segments with seq >= from_seq — the standby's pull-sync
        and promotion read path."""
        with self._lock:
            segs = self._segments()
            out = []
            for seq, path in segs:
                if seq < int(from_seq):
                    continue
                out.append({
                    "seq": seq,
                    "active": path == self.path,
                    "lines": _scan_lines(path),
                })
            return {"ok": True, "segments": out}


class ReplicaServer:
    """`primetpu replica` — a follower daemon: a `ReplicaStore` behind a
    threaded JSON-lines listener speaking the `repl.*` verbs, tracking
    the highest fencing epoch it ever accepted and refusing anything
    older (the fence half of the no-dual-primary argument)."""

    def __init__(self, directory: str, target: str):
        self.store = ReplicaStore(directory)
        self.target = str(target)
        # the fence: highest epoch ever accepted, recovered from the
        # chain itself (epoch frames are ordinary journal records)
        self.epoch = self._scan_epoch()
        self._srv = None
        self.dead = False  # set by an injected replica crash

    def _scan_epoch(self) -> int:
        e = 0
        for _, path in self.store._segments():
            for line in _scan_lines(path):
                rec = _unframe(line)
                if rec is not None and rec.get("t") == "epoch":
                    e = max(e, int(rec.get("epoch", 0)))
        return e

    def _check_epoch(self, req: dict) -> dict | None:
        e = int(req.get("epoch", 0))
        if e < self.epoch:
            return {"ok": False, "fenced": True, "epoch": self.epoch}
        self.epoch = max(self.epoch, e)
        return None

    def handle(self, req: dict) -> dict:
        verb = req.get("verb")
        try:
            if verb == "repl.status":
                # chain_epoch is the highest epoch frame ON DISK —
                # distinct from the fence (self.epoch), which a hello
                # can raise without shipping any chain bytes. Promotion
                # orders candidate chains by chain_epoch: a reign's
                # quorum-ACKed history always starts with its epoch
                # frame, so a deposed primary's stale (possibly longer)
                # tail can never outrank the newest reign's chain.
                return {"ok": True, "epoch": self.epoch,
                        "chain_epoch": self._scan_epoch(),
                        "tip": self.store.tip(), "dir": self.store.dir}
            if verb == "repl.fetch":
                out = self.store.fetch(int(req.get("from_seq", 0)))
                out["epoch"] = self.epoch
                return out
            fenced = self._check_epoch(req)
            if fenced is not None:
                return fenced
            if verb == "repl.hello":
                return {"ok": True, "epoch": self.epoch,
                        "tip": self.store.tip()}
            if verb == "repl.append":
                return self.store.apply_append(
                    int(req["seq"]), int(req["prev"]), str(req["line"])
                )
            if verb == "repl.roll":
                return self.store.apply_roll(
                    int(req["seq"]), str(req["header_line"])
                )
            if verb == "repl.seg":
                return self.store.apply_seg(
                    int(req["seq"]), list(req["lines"]),
                    bool(req.get("active")),
                )
            if verb == "repl.reset":
                return self.store.apply_reset()
            raise KeyError(f"unknown replication verb {verb!r}")
        except chaos.ChaosCrash:
            # an injected replica death: in-process trials cannot
            # SIGKILL the host process, so the replica plays dead —
            # stops listening, drops the link, never ACKs this frame
            self.die()
            raise
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return {"ok": False, **error_obj(e)}

    def bind(self) -> str:
        if self._srv is None:
            server = self

            import socketserver

            class Handler(socketserver.StreamRequestHandler):
                def handle(self):
                    while not server.dead:
                        try:
                            req = read_line(self.rfile)
                        except ValueError:
                            return
                        if req is None:
                            return
                        try:
                            reply = server.handle(req)
                        except chaos.ChaosCrash:
                            return  # connection drops, no ack
                        try:
                            self.wfile.write(encode(reply))
                            self.wfile.flush()
                        except (BrokenPipeError, ValueError, OSError):
                            return

            self._srv, fam = make_listener(self.target, Handler)
            if fam == "tcp":
                host, port = self._srv.server_address[:2]
                self.target = f"{host}:{port}"
        return self.target

    def serve_forever(self) -> None:
        self.bind()
        self._srv.serve_forever()

    def start(self) -> str:
        """Bind + serve on a daemon thread (tests / in-process trials);
        returns the resolved target."""
        target = self.bind()
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return target

    def die(self) -> None:
        """Simulated replica host death (chaos): stop accepting, drop
        every connection. The store stays on disk for a later rebirth."""
        self.dead = True
        if self._srv is not None:
            threading.Thread(target=self._srv.shutdown,
                             daemon=True).start()

    def shutdown(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            if parse_target(self.target)[0] == "unix":
                try:
                    os.unlink(self.target)
                except OSError:
                    pass


# ---- primary side --------------------------------------------------------


class ReplicaLink:
    """One persistent connection from the primary to one replica, with
    reconnect backoff and a partition blackout window (chaos). All calls
    happen on the journal-owning thread — no locking needed."""

    def __init__(self, target: str, timeout_s: float = 5.0, rng=None):
        self.target = str(target)
        self.timeout_s = float(timeout_s)
        self._sock = None
        self._rfile = None
        self.backoff = DecorrelatedJitter(base=0.05, cap=2.0, rng=rng)
        self.retry_at = 0.0     # no reconnect attempt before this
        self.blackout_until = 0.0  # injected partition: no sends before
        self.needs_sync = True  # fresh/reconnected links resync first
        self.acks = 0
        self.failures = 0

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None
        self.needs_sync = True
        self.retry_at = time.monotonic() + self.backoff.next_delay()

    def connect(self) -> bool:
        """(Re)connect when allowed; True when a socket is up."""
        if self._sock is not None:
            return True
        now = time.monotonic()
        if now < self.retry_at or now < self.blackout_until:
            return False
        fam, addr = parse_target(self.target)
        s = socket.socket(
            socket.AF_INET6 if fam == "tcp" and ":" in addr[0]
            else socket.AF_INET if fam == "tcp"
            else socket.AF_UNIX,
            socket.SOCK_STREAM,
        )
        s.settimeout(self.timeout_s)
        try:
            s.connect(addr if fam == "tcp" else str(addr))
        except OSError:
            s.close()
            self.failures += 1
            self.retry_at = time.monotonic() + self.backoff.next_delay()
            return False
        self._sock = s
        self._rfile = s.makefile("rb")
        self.backoff.reset()
        self.needs_sync = True
        return True

    def call(self, req: dict) -> dict | None:
        """One order/ack round trip; None when the link is down (the
        frame simply did not replicate — quorum accounting's problem).
        Chaos `replicate.send` rides here: partition closes the link and
        blacks it out, duplicate delivers the frame twice (the replica's
        position check rejects the echo)."""
        if time.monotonic() < self.blackout_until:
            self._drop()
            return None
        if not self.connect():
            return None
        payload = encode(req)
        dup = False
        ev = chaos.replication("replicate.send")
        if ev is not None:
            if ev.action == "partition":
                self.blackout_until = (
                    time.monotonic() + float(ev.arg("s", 0.2))
                )
                self._drop()
                return None
            if ev.action == "duplicate":
                dup = True
        try:
            self._sock.sendall(payload)
            reply = read_line(self._rfile)
            if dup:
                # the duplicated frame draws its own reply; the replica
                # rejected it on position, which must not poison the
                # stream — drain it and keep the FIRST reply
                self._sock.sendall(payload)
                echo = read_line(self._rfile)
                if echo is not None and echo.get("resync"):
                    self.needs_sync = True
        except (OSError, ValueError):
            self.failures += 1
            self._drop()
            return None
        if reply is None:
            self.failures += 1
            self._drop()
            return None
        return reply

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None


class ReplicationSink:
    """The primary half: fans every journal mutation out to the replica
    links and accounts the quorum. Plugs into `JobJournal.sink` — the
    journal calls `on_append`/`on_roll`/`on_base` from its own write
    path, AFTER the local fsync (local durability first, then the wire).

    `quorum` counts REPLICA acks; the default `N//2 + 1` is a strict
    majority of the replicas, and any explicit quorum must satisfy
    `2K > N` — the intersection property the fencing safety argument
    stands on: two K-sized ack sets out of N replicas are guaranteed to
    share a replica ONLY when 2K > N (K=(N+1)//2 fails this for even N,
    e.g. two disjoint single-replica "quorums" at N=2), and that shared
    replica is the one that fences the deposed primary."""

    def __init__(self, journal: JobJournal, replicas: list[str],
                 quorum: int | None = None, policy: str = "block",
                 retry_after_s: float = 2.0, obs=None, rng=None,
                 node: str = "primary"):
        if policy not in ("block", "degrade"):
            raise ReplicaQuorumLost(
                f"--quorum-policy must be block|degrade, got {policy!r}"
            )
        self.journal = journal
        self.links = [ReplicaLink(t, rng=rng) for t in replicas]
        n = len(self.links)
        self.quorum = int(quorum) if quorum else n // 2 + 1
        if not 1 <= self.quorum <= n:
            raise ReplicaQuorumLost(
                f"--quorum {self.quorum} out of range 1..{n} "
                f"for {n} replica(s)"
            )
        if 2 * self.quorum <= n:
            raise ReplicaQuorumLost(
                f"--quorum {self.quorum} of {n} replica(s) does not "
                f"guarantee quorum intersection (needs 2K > N, i.e. "
                f">= {n // 2 + 1}): two disjoint ack sets could each "
                "reach quorum and a promoted standby would never fence "
                "the old primary"
            )
        self.policy = policy
        self.retry_after_s = float(retry_after_s)
        self.obs = obs
        self.node = str(node)
        self.epoch = 0
        self.fenced = False
        self.last_quorum_ok = True
        self.degraded_acks = 0
        self.quorum_losses = 0
        self.resyncs = 0

    # -- chain reading (primary's own segments, raw) -----------------------

    def _chain(self) -> list[tuple[int, str, bool]]:
        """(seq, path, active) for the primary's on-disk chain."""
        segs = [(seq, path, False)
                for seq, path in self.journal._rolled_segments()]
        if os.path.exists(self.journal.path):
            segs.append((self.journal._active_seq, self.journal.path,
                         True))
        return segs

    def _base_seq(self) -> int:
        """Seq of the newest BASE segment (0 when never compacted)."""
        base = 0
        for seq, path, _ in self._chain():
            lines = _scan_lines(path)
            if lines:
                first = _unframe(lines[0])
                if first is not None and first.get("t") == "seg" \
                        and first.get("base"):
                    base = max(base, seq)
        return base

    # -- per-link sync -----------------------------------------------------

    def _crc_at(self, seq: int, records: int) -> int | None:
        """Chained line CRC of OUR segment `seq` after `records` records
        — the value a follower whose chain is a byte-prefix of ours
        must report as its tip crc. None when we hold no such position
        (no segment with that seq, or fewer records than asked)."""
        for s, path, _ in self._chain():
            if s != int(seq):
                continue
            lines = _scan_lines(path)
            n = 0
            crc = 0
            for i, line in enumerate(lines):
                rec = _unframe(line)
                if rec is None:
                    break  # torn tail: nothing past the last whole frame
                if not (i == 0 and rec.get("t") == "seg"):
                    if n == int(records):
                        break
                    n += 1
                crc = _line_crc(line)
            return crc if n == int(records) else None
        return None

    def _sync_link(self, link: ReplicaLink) -> bool:
        """Bring one replica to our exact chain: hello for its tip, then
        re-ship whole segments from where it diverges (or reset + ship
        everything from the newest BASE when the tip is behind one or
        its bytes diverge from ours). Raw bytes only — the replica ends
        byte-identical or not at all."""
        hello = link.call({"verb": "repl.hello", "epoch": self.epoch})
        if hello is None:
            return False
        if hello.get("fenced"):
            self._fence(int(hello.get("epoch", 0)))
            return False
        tip = hello.get("tip") or {}
        chain = self._chain()
        if not chain:
            link.needs_sync = False
            return True
        base = self._base_seq()
        from_seq = int(tip.get("seq", -1))
        diverged = False
        if base <= from_seq <= chain[-1][0]:
            # the seq range alone cannot prove the follower's chain is a
            # prefix of ours: a deposed primary whose un-quorumed tail
            # crossed a roll boundary has rolled segments at the SAME
            # seqs with different bytes. Hold its tip crc to our chain
            # at the identical (segment, record) position — the tip
            # line's crc chains over the whole prefix (each roll header
            # back-links the previous segment's last line), so a match
            # certifies the prefix and a mismatch forces a full resync.
            want = self._crc_at(from_seq, int(tip.get("records", 0)))
            diverged = want is None or want != int(tip.get("crc", 0))
        if diverged or from_seq < base or from_seq > chain[-1][0]:
            # behind a compaction BASE, ahead of us entirely, or
            # byte-diverged: the follower's history is not a prefix of
            # ours — discard and resync from the BASE. This is also
            # where a deposed primary's un-quorumed tail dies on rejoin.
            if link.call({"verb": "repl.reset",
                          "epoch": self.epoch}) is None:
                return False
            from_seq = base if base else chain[0][0]
        ok = True
        for seq, path, active in chain:
            if seq < from_seq:
                continue
            r = link.call({
                "verb": "repl.seg", "epoch": self.epoch, "seq": seq,
                "lines": _scan_lines(path), "active": active,
            })
            if r is None or not r.get("ok"):
                if r is not None and r.get("fenced"):
                    self._fence(int(r.get("epoch", 0)))
                ok = False
                break
        if ok:
            link.needs_sync = False
            self.resyncs += 1
            if self.obs is not None:
                self.obs.repl_event("resync", target=link.target,
                                    from_seq=from_seq)
        return ok

    def _fence(self, epoch: int) -> None:
        if not self.fenced and self.obs is not None:
            self.obs.repl_event("fenced", epoch=epoch)
        self.fenced = True
        self.fenced_by = int(epoch)

    # -- journal seams -----------------------------------------------------

    def _ship(self, req: dict) -> int:
        """Send one order to every link (syncing stragglers first);
        returns the ack count and keeps the quorum book."""
        acks = 0
        for link in self.links:
            if self.fenced:
                break
            if link.needs_sync:
                # the sync ships our on-disk chain, which ALREADY holds
                # this order's effect (the journal seams run after the
                # local write) — the per-frame order would only bounce
                # off the replica's position check and buy a second
                # wholesale resync. A successful sync IS the ack.
                if self._sync_link(link):
                    acks += 1
                    link.acks += 1
                continue
            r = link.call(req)
            if r is None:
                continue
            if r.get("fenced"):
                self._fence(int(r.get("epoch", 0)))
                continue
            if r.get("resync"):
                # position mismatch: catch the replica up, then replay
                # this one order on the freshly-synced chain — EXCEPT
                # appends, which the sync already shipped as part of
                # the active segment's raw lines
                link.needs_sync = True
                if self._sync_link(link):
                    acks += 1
                    link.acks += 1
                continue
            if r.get("ok"):
                acks += 1
                link.acks += 1
        self.last_quorum_ok = acks >= self.quorum and not self.fenced
        if not self.last_quorum_ok:
            self.quorum_losses += 1
            if self.policy == "degrade" and not self.fenced:
                self.degraded_acks += 1
        return acks

    def on_append(self, line: str, seq: int, prev: int) -> None:
        self._ship({"verb": "repl.append", "epoch": self.epoch,
                    "seq": int(seq), "prev": int(prev), "line": line})

    def on_roll(self, seq: int, header_line: str) -> None:
        self._ship({"verb": "repl.roll", "epoch": self.epoch,
                    "seq": int(seq), "header_line": header_line})

    def on_base(self) -> None:
        """Compaction rewrote history: every follower must resync from
        the new BASE (their pre-compaction chain is no longer a prefix
        of ours)."""
        acks = 0
        for link in self.links:
            link.needs_sync = True
            if not self.fenced and self._sync_link(link):
                acks += 1
        self.last_quorum_ok = acks >= self.quorum and not self.fenced

    # -- lifecycle ---------------------------------------------------------

    def begin_epoch(self) -> int:
        """Open this primary's reign: epoch = 1 + max(own chain, every
        reachable replica), announced by appending the epoch frame as
        the first record of the reign. The frame replicates like any
        other — once it reaches quorum, every older primary's next
        quorum round meets the fence."""
        records, _ = self.journal.replay()
        e = max_epoch(records)
        for link in self.links:
            hello = link.call({"verb": "repl.status"})
            if hello is not None:
                e = max(e, int(hello.get("epoch", 0)))
        self.epoch = e + 1
        self.journal.append({
            "t": "epoch", "epoch": self.epoch, "node": self.node,
        })
        if self.obs is not None:
            self.obs.repl_event("epoch", epoch=self.epoch,
                                node=self.node)
        return self.epoch

    def heartbeat(self) -> None:
        """Idle-path quorum round (the serve loop calls this between
        ticks): reconnects and resyncs stragglers, and — crucially —
        gives a deposed primary a bounded-time path to SEEING the fence
        even when no client is writing."""
        acks = 0
        for link in self.links:
            if self.fenced:
                break
            if link.needs_sync:
                if self._sync_link(link):
                    acks += 1
                continue
            r = link.call({"verb": "repl.hello", "epoch": self.epoch})
            if r is None:
                continue
            if r.get("fenced"):
                self._fence(int(r.get("epoch", 0)))
            elif r.get("ok"):
                acks += 1
        self.last_quorum_ok = acks >= self.quorum and not self.fenced

    def quorum_ok(self) -> bool:
        return self.last_quorum_ok and not self.fenced

    def check_admission(self) -> None:
        """The server's gate, BEFORE a job id exists: under `block`,
        refuse admission while below quorum (the client gets typed
        backpressure, not a single-disk ACK)."""
        if self.fenced:
            raise PrimaryFenced(
                "this primary has been fenced by epoch "
                f"{getattr(self, 'fenced_by', 0)} (a standby promoted); "
                "resubmit to the new primary", getattr(self, "fenced_by", 0),
            )
        if self.policy == "block" and not self.last_quorum_ok:
            raise ReplicaQuorumLost(
                f"replication quorum lost ({self.quorum} ack(s) "
                f"required from {len(self.links)} replica(s))",
                self.retry_after_s,
            )

    def status(self) -> dict:
        return {
            "replicas": [
                {"target": l.target, "connected": l.connected,
                 "acks": l.acks, "failures": l.failures,
                 "needs_sync": l.needs_sync}
                for l in self.links
            ],
            "quorum": self.quorum,
            "policy": self.policy,
            "epoch": self.epoch,
            "fenced": self.fenced,
            "quorum_ok": self.quorum_ok(),
            "degraded_acks": self.degraded_acks,
            "quorum_losses": self.quorum_losses,
            "resyncs": self.resyncs,
        }

    def close(self) -> None:
        for link in self.links:
            link.close()


# ---- standby / promotion -------------------------------------------------


def _repl_call(target: str, req: dict, timeout_s: float = 5.0) -> dict:
    """One-shot repl.* round trip (standby pull path; no persistence)."""
    link = ReplicaLink(target, timeout_s=timeout_s)
    try:
        r = link.call(req)
    finally:
        link.close()
    if r is None:
        raise ConnectionError(
            f"replica at {format_target(target)} unreachable"
        )
    return r


def pull_chain(replicas: list[str], dest_dir: str) -> dict:
    """Copy the best reachable replica chain into `dest_dir` verbatim
    (wiping whatever chain sat there — a stale standby tail is exactly
    the history a promotion must discard). Candidates are ordered by
    (chain epoch, seq, records): EPOCH FIRST, because a deposed
    primary's replica-local un-quorumed tail can be LONGER than the new
    reign's quorum-ACKed chain — adopting it by length alone would
    silently discard quorum-ACKed jobs (invariant A). Every reign's
    chain opens with its epoch frame, so the highest chain epoch marks
    the replica that holds the newest reign's history; length only
    breaks ties within one reign, where chains are linear prefixes of
    each other. Returns {source, epoch, tip, reachable}; raises
    ReplicaQuorumLost when no replica answers."""
    best = None
    reachable = 0
    for t in replicas:
        try:
            st = _repl_call(t, {"verb": "repl.status"})
        except (ConnectionError, OSError):
            continue
        reachable += 1
        tip = st.get("tip") or {}
        key = (int(st.get("chain_epoch", 0)),
               int(tip.get("seq", -1)), int(tip.get("records", 0)))
        if best is None or key > best[0]:
            best = (key, t, st)
    if best is None:
        raise ReplicaQuorumLost(
            f"no replica reachable out of {len(replicas)}", 5.0
        )
    _, src, st = best
    fetched = _repl_call(src, {"verb": "repl.fetch", "from_seq": 0})
    store = ReplicaStore(dest_dir)
    store.apply_reset()
    for seg in fetched.get("segments", []):
        store.apply_seg(int(seg["seq"]), list(seg["lines"]),
                        bool(seg.get("active")))
    return {"source": src, "epoch": int(fetched.get("epoch", 0)),
            "tip": store.tip(), "reachable": reachable}


class Standby:
    """`primetpu serve --standby-of PRIMARY`: tail a follower while the
    primary lives, promote when it stays dead past the grace window.

    Promotion = pull the best (highest-epoch) reachable replica chain into our own
    state dir, then start serving with a fresh fencing epoch — the
    epoch frame's quorum commit is what actually deposes the old
    primary; until it lands, the standby is not a primary."""

    def __init__(self, primary: str, replicas: list[str], state_dir: str,
                 grace_s: float = 3.0, poll_s: float = 0.5, rng=None,
                 min_reachable: int | None = None):
        self.primary = str(primary)
        self.replicas = list(replicas)
        self.state_dir = str(state_dir)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.rng = rng
        n = len(self.replicas)
        # same 2K > N majority as the sink's quorum: a minority-
        # partition standby must not elect itself
        self.min_reachable = (
            int(min_reachable) if min_reachable else n // 2 + 1
        )
        self.last_sync: dict | None = None

    def wait_for_takeover(self, max_wait_s: float | None = None) -> dict:
        """Block until the primary has been dead for the grace window,
        keeping our state dir warm with periodic pull-syncs; returns the
        final pull report. Raises TimeoutError when `max_wait_s` passes
        with the primary still alive."""
        from .protocol import socket_alive

        jit = DecorrelatedJitter(base=self.poll_s,
                                 cap=max(4 * self.poll_s, 2.0),
                                 rng=self.rng)
        dead_since = None
        t0 = time.monotonic()
        while True:
            if socket_alive(self.primary):
                dead_since = None
                jit.reset()
                try:
                    self.last_sync = pull_chain(self.replicas,
                                                self.state_dir)
                except (ReplicaQuorumLost, ConnectionError, OSError):
                    pass  # replicas flapping; primary is alive anyway
            else:
                now = time.monotonic()
                dead_since = dead_since or now
                if now - dead_since >= self.grace_s:
                    return self.promote_pull()
            if max_wait_s is not None \
                    and time.monotonic() - t0 > max_wait_s:
                raise TimeoutError(
                    f"primary {self.primary} still alive after "
                    f"{max_wait_s}s of standby watch"
                )
            time.sleep(jit.next_delay())

    def promote_pull(self) -> dict:
        """The final pre-promotion pull: require a quorum's worth of
        reachable replicas (a minority view must not elect itself), then
        adopt the highest-epoch chain."""
        report = pull_chain(self.replicas, self.state_dir)
        if report["reachable"] < self.min_reachable:
            raise ReplicaQuorumLost(
                f"only {report['reachable']} replica(s) reachable; "
                f"promotion needs {self.min_reachable}", 5.0,
            )
        return report
