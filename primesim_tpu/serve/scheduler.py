"""Continuous-batching scheduler: jobs in, fleet slots spliced, results out.

The scheduler owns one compiled fleet program per CAPACITY BUCKET and
never recompiles during service. A bucket is `n_slots` batch elements
whose event storage is `n_pages * page_events` slots per core
(`FleetEngine.make_slots`); admission routes each job to the
smallest-capacity bucket its trace fits, so short traces don't pay the
worst-case [B, C, T] shape — the paged/pooled allocator the fleet's
fixed-shape splice contract makes possible.

One `tick()` is the serving round:

    expire deadlines -> splice pending jobs into free slots ->
    one committed chunk per busy bucket -> harvest retired elements ->
    periodic per-job element checkpoints

Every state transition is journaled BEFORE the slot is recycled, and
in-flight jobs are checkpointed to deterministic per-job paths
(`<dir>/jobs/<job_id>.npz`), so the restart path (server.py) can rebuild
exactly this table from the journal + checkpoint files alone.

Failure containment: a batch dispatch failure cannot be attributed to
one element from the exception, so the whole bucket rolls back — its
fleet is rebuilt all-idle (host arrays are authoritative) and each
occupant consults its `JobContext` retry budget: transient/oom failures
re-enqueue with exponential backoff (resuming from the newest element
checkpoint), permanent ones go FAILED. A job whose workload won't even
validate never reaches a fleet: it is QUARANTINED at admission, exactly
like `sweep --isolate` does for bad elements.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..chaos import sites as chaos
from ..obs.metrics import Histogram
from ..sim.fleet import FleetEngine, apply_overrides
from ..sim.supervisor import JobContext, validate_fleet_element
from . import jobs as J
from .protocol import error_obj

#: One event-storage page, in per-core event slots. Bucket capacities are
#: whole pages: (slots, pages) -> capacity = pages * PAGE_EVENTS.
PAGE_EVENTS = 64

#: Default bucket ladder: small/large. Most synthetic traces fit one page.
DEFAULT_BUCKETS = ((6, 1), (2, 8))


class QueueFull(RuntimeError):
    """Admission refused: the bounded queue is at capacity. Carries the
    backpressure hint the protocol surfaces as `retry_after_s`."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"queue full ({depth} pending); retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s


class WorkloadSpecError(ValueError):
    """A job's workload SPEC (synth grammar / trace-vs-synth choice) is
    invalid. Subclasses ValueError so every existing quarantine path
    (`except ValueError` at the scheduler/server boundary) still
    catches it, but carries a `.location()` so the CLI and protocol can
    emit the structured {type, location, detail} error shape."""

    def __init__(self, msg: str, *, spec: str | None = None,
                 field: str | None = None):
        super().__init__(msg)
        self.spec = spec
        self.field = field

    def location(self) -> dict:
        loc: dict = {}
        if self.spec is not None:
            loc["spec"] = self.spec
        if self.field is not None:
            loc["field"] = self.field
        return loc


def parse_synth_spec(spec: str, n_cores: int, fold: bool):
    """`name:k=v,...` -> Trace (the CLI's --synth grammar, but raising
    WorkloadSpecError (a ValueError) instead of SystemExit so a bad
    spec quarantines the job with a structured error rather than
    killing the daemon)."""
    from ..trace import synth
    from ..trace.format import fold_ins

    name, _, args = spec.partition(":")
    if name not in synth.GENERATORS:
        raise WorkloadSpecError(
            f"unknown generator {name!r}; have: "
            f"{', '.join(sorted(synth.GENERATORS))}", spec=spec,
        )
    kw = {}
    if args:
        for pair in args.split(","):
            k, eq, v = pair.partition("=")
            if not eq or not k:
                raise WorkloadSpecError(
                    f"bad synth arg {pair!r} (want key=value)",
                    spec=spec, field=k or pair,
                )
            try:
                kw[k] = int(v)
            except ValueError:
                raise WorkloadSpecError(
                    f"bad synth arg {pair!r}: value must be an integer",
                    spec=spec, field=k,
                ) from None
    try:
        tr = synth.GENERATORS[name](n_cores, **kw)
    except TypeError as e:
        raise WorkloadSpecError(
            f"synth {name!r}: {e}", spec=spec
        ) from None
    return fold_ins(tr) if fold else tr


def materialize_workload(job: J.Job, cfg):
    """Load/generate the job's trace from its journaled SPEC and compute
    its effective config. Deterministic — re-running it after a crash
    yields the identical workload, which is what makes replay bit-exact.
    Raises (TraceError/ValueError/OSError) when the workload is bad; the
    caller quarantines."""
    from ..trace.format import Trace, fold_ins

    if (job.trace_path is None) == (job.synth is None):
        raise WorkloadSpecError(
            "job needs exactly one of trace_path | synth",
            field="trace_path|synth",
        )
    if job.trace_path is not None:
        tr = Trace.load(job.trace_path)
        if job.fold:
            tr = fold_ins(tr)
    else:
        tr = parse_synth_spec(job.synth, cfg.n_cores, job.fold)
    ecfg = apply_overrides(cfg, job.overrides)
    validate_fleet_element(cfg, tr, job.overrides)
    job._trace = tr
    job._elem_cfg = ecfg
    job._ctx = JobContext()
    return tr


class SlotBucket:
    """One compiled fleet + its slot table. `slots[i]` is the occupying
    Job or None; the fleet element under a None slot holds `idle_trace`
    and contributes nothing to the vmapped step."""

    def __init__(self, cfg, n_slots: int, n_pages: int,
                 chunk_steps: int = 128, obs=None, attest: bool = False):
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.n_pages = int(n_pages)
        self.capacity = int(n_pages) * PAGE_EVENTS
        self.chunk_steps = int(chunk_steps)
        self.obs = obs
        self.attest_on = bool(attest)
        self.fleet = self._make_fleet()
        self.slots: list[J.Job | None] = [None] * self.n_slots

    def _make_fleet(self):
        fleet = FleetEngine.make_slots(
            self.cfg, self.n_slots, self.capacity,
            chunk_steps=self.chunk_steps,
        )
        if self.attest_on:
            # per-slot fingerprint chains (DESIGN.md §24): slots are
            # tracked at splice and dropped at retire, so a job's chain
            # covers exactly its own chunks
            from ..attest import FleetAttest

            fleet.attest = FleetAttest()
        # AOT warm (§23): with `serve --exec-cache on` the bucket's
        # chunk executable deserializes from disk instead of compiling
        # on the first dispatch tick. No-op when the cache is inactive.
        fleet.warm_exec()
        if self.obs is not None:
            # per-bucket timeline row: the recorder keys counter deltas
            # by label, so each bucket diffs against its own history
            self.obs.attach(fleet, label=f"bucket{self.n_pages}p")
        return fleet

    def free_slot(self) -> int | None:
        for i, occ in enumerate(self.slots):
            if occ is None:
                return i
        return None

    @property
    def occupied(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def busy(self) -> bool:
        """Any occupied slot still running (not yet harvested)?"""
        if self.occupied == 0:
            return False
        dm = self.fleet.done_mask()
        return any(
            s is not None and not dm[i] for i, s in enumerate(self.slots)
        )

    def rebuild(self) -> None:
        """Host rollback after a failed dispatch: throw the (possibly
        poisoned) device state away and start an all-idle fleet on the
        same compiled geometry. Occupants must be re-enqueued by the
        caller BEFORE this runs."""
        self.fleet = self._make_fleet()
        self.slots = [None] * self.n_slots


class Scheduler:
    """The serving core. Owns the job table, the bounded pending queue,
    the bucket fleets, and the journal write side. Single-threaded by
    design — the server's listener threads only ENQUEUE closures onto
    `self.inbox`; every mutation happens on the tick loop."""

    def __init__(
        self,
        cfg,
        journal,
        state_dir: str,
        buckets=DEFAULT_BUCKETS,
        chunk_steps: int = 128,
        max_queue: int = 64,
        checkpoint_every_s: float = 2.0,
        max_retries: int = 2,
        obs=None,
        warm_cache: bool = False,
        attest: str = "off",
    ):
        self.cfg = cfg
        self.journal = journal
        self.obs = obs
        self.attest = str(attest or "off")
        # warm-state cache consult at admission (DESIGN.md §16): a
        # resubmitted (trace, config) job starts from the deepest cached
        # snapshot whose content key matches, instead of step 0
        if warm_cache:
            from ..sim.checkpoint import warm_cache_root

            self.warm_root = warm_cache_root()
        else:
            self.warm_root = None
        self.state_dir = str(state_dir)
        self.jobs_dir = os.path.join(self.state_dir, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self.buckets = [
            SlotBucket(cfg, n, p, chunk_steps=chunk_steps, obs=obs,
                       attest=self.attest == "chain")
            for n, p in sorted(buckets, key=lambda b: b[1])
        ]
        self.max_queue = int(max_queue)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self.max_retries = int(max_retries)
        self.jobs: dict[str, J.Job] = {}
        self.queue: list[str] = []  # pending job_ids, accept order
        self._seq = 0
        self._last_pick: dict[str, int] = {}  # client -> rr stamp
        self._pick_n = 0
        self._last_ckpt_t = time.time()
        self._backoff_until = 0.0
        self.started_t = time.time()
        self.total_instructions = 0
        self.completed = 0
        self._latencies: list[float] = []  # terminal latencies, capped
        # always-on accept-to-terminal latency histogram (the Prometheus
        # surface) + last-dispatch stamp (health/metrics liveness signal)
        self.latency_hist = Histogram()
        self.last_dispatch_t: float | None = None
        # v2 paged allocator: slot migrations between capacity buckets
        self.promotions = 0
        self.demotions = 0

    def _serve_event(self, kind: str, **args) -> None:
        if self.obs is not None:
            self.obs.serve_event(kind, args)

    # ---- identity / paths ------------------------------------------------

    def next_job_id(self) -> str:
        self._seq += 1
        return f"j{self._seq:06d}"

    def job_ckpt_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.npz")

    @property
    def total_slots(self) -> int:
        return sum(b.n_slots for b in self.buckets)

    @property
    def max_capacity(self) -> int:
        return max(b.capacity for b in self.buckets)

    # ---- admission -------------------------------------------------------

    def submit(self, job: J.Job) -> J.Job:
        """Admit one job: backpressure check, durable accept record
        (fsynced BEFORE this returns — the ACK invariant), workload
        validation (bad -> QUARANTINED), enqueue."""
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                len(self.queue), retry_after_s=1.0 + 0.1 * len(self.queue)
            )
        self.jobs[job.job_id] = job
        self.journal.accept(job)
        # the accept record is durable but the caller has NOT been told:
        # dying here is the lost-ACK window idempotency tokens cover
        chaos.crashpoint("server.post-journal-pre-ack")
        self._serve_event("admit", job_id=job.job_id, client=job.client,
                          priority=job.priority)
        self._validate_or_quarantine(job)
        if not job.terminal:
            self.queue.append(job.job_id)
        return job

    def _validate_or_quarantine(self, job: J.Job) -> bool:
        try:
            tr = materialize_workload(job, self.cfg)
        except Exception as e:  # bad workload must not kill the daemon
            self._terminal(job, J.QUARANTINED, detail=error_obj(e)["error"])
            return False
        if tr.max_len > self.max_capacity:
            self._terminal(
                job,
                J.QUARANTINED,
                detail={
                    "type": "CapacityError",
                    "location": {},
                    "detail": (
                        f"trace needs {tr.max_len} event slots/core; "
                        f"largest bucket holds {self.max_capacity}"
                    ),
                },
            )
            return False
        return True

    def requeue_recovered(self, job: J.Job) -> None:
        """Journal-replayed non-terminal job: re-materialize its workload
        from the accept facts, point it at its newest element checkpoint
        when one survived, and put it back in line."""
        self.jobs[job.job_id] = job
        if not self._validate_or_quarantine(job):
            return
        if os.path.exists(self.job_ckpt_path(job.job_id)):
            job._resume_from = self.job_ckpt_path(job.job_id)
        self.queue.append(job.job_id)

    def adopt_terminal(self, job: J.Job) -> None:
        """Journal-replayed job already in a terminal state: keep it for
        STATUS/RESULT queries; nothing to run."""
        self.jobs[job.job_id] = job

    def cancel(self, job_id: str) -> J.Job:
        job = self.jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        if job.terminal:
            raise ValueError(f"{job_id} already terminal ({job.state})")
        if job.state == J.PENDING and job_id in self.queue:
            self.queue.remove(job_id)
        elif job.state == J.RUNNING:
            self._evict(job)
        self._terminal(job, J.CANCELLED, detail={"detail": "client cancel"})
        return job

    # ---- the serving tick ------------------------------------------------

    def tick(self) -> bool:
        """One serving round. Returns True when any device work ran (the
        server idles its loop when False)."""
        now = time.time()
        self._expire_deadlines(now)
        if now >= self._backoff_until:
            self._fill_slots()
        worked = False
        for b in self.buckets:
            if not b.busy():
                continue
            chaos.crashpoint("scheduler.pre-dispatch")
            try:
                b.fleet.step_chunk()
                worked = True
            except Exception as e:  # noqa: BLE001 — classified below
                self._dispatch_failed(b, e)
                return True
            chaos.crashpoint("scheduler.post-dispatch")
        self._harvest(now)
        # promotion check runs BETWEEN chunks: a windowed job must leave
        # its small bucket before the next chunk could reach the window
        # edge (see _promote_windows for the pointer-bound argument)
        self._promote_windows()
        if now - self._last_ckpt_t >= self.checkpoint_every_s:
            self.checkpoint_running()
            self._last_ckpt_t = now
            chaos.crashpoint("scheduler.post-checkpoint")
        return worked

    def pending_work(self) -> bool:
        """Anything admitted but not yet terminal — the server's busy
        signal for idle-exit and drain decisions."""
        return bool(self.queue) or any(b.occupied for b in self.buckets)

    def _expire_deadlines(self, now: float) -> None:
        for job_id in list(self.queue):
            job = self.jobs[job_id]
            if job.deadline_expired(now):
                self.queue.remove(job_id)
                self._terminal(
                    job, J.TIMEOUT,
                    detail={"detail": f"deadline {job.deadline_s}s expired "
                                      "in queue"},
                )
        for b in self.buckets:
            for i, job in enumerate(b.slots):
                if job is not None and job.deadline_expired(now):
                    self._evict(job)
                    self._terminal(
                        job, J.TIMEOUT,
                        detail={
                            "detail": f"deadline {job.deadline_s}s expired "
                                      f"after {int(self._slot_steps(job))} "
                                      "steps",
                        },
                    )

    def _pick_next(self, capacity: int) -> J.Job | None:
        """Highest priority first; per-client round-robin within a
        priority tier (a chatty client cannot starve others); accept
        order last. Only jobs whose trace fits `capacity`."""
        best = None
        best_key = None
        for job_id in self.queue:
            job = self.jobs[job_id]
            if job._trace is None or job._trace.max_len > capacity:
                continue
            key = (
                -job.priority,
                self._last_pick.get(job.client, -1),
                job.accepted_t,
            )
            if best_key is None or key < best_key:
                best, best_key = job, key
        return best

    def _fill_slots(self) -> None:
        """Splice pending jobs into free slots, smallest-fitting bucket
        first; one deferred `upload_events` per bucket covers the whole
        batch of splices. Two passes per bucket (v2 paged allocator):
        full-fit jobs first, then WINDOW admissions — an oversized job's
        leading `capacity-1` events run in the small bucket now and the
        job migrates up by checkpoint before the window edge matters."""
        self._demote_for_queued()
        for b in self.buckets:
            spliced = False
            while True:
                i = b.free_slot()
                if i is None:
                    break
                job = self._pick_next(b.capacity)
                if job is None:
                    break
                self.queue.remove(job.job_id)
                self._pick_n += 1
                self._last_pick[job.client] = self._pick_n
                self._place(b, i, job, upload=False)
                spliced = True
            if spliced:
                b.fleet.upload_events()
        # window pass, all buckets — runs only after every full-fit
        # splice, so a job starts windowed only when no bucket that fully
        # fits it has a free slot
        for b in self.buckets:
            spliced = False
            while True:
                i = b.free_slot()
                if i is None:
                    break
                job = self._pick_window(b)
                if job is None:
                    break
                self.queue.remove(job.job_id)
                self._pick_n += 1
                self._last_pick[job.client] = self._pick_n
                job._window = self._window_trace(job._trace, b.capacity)
                self._place(b, i, job, upload=False)
                spliced = True
            if spliced:
                b.fleet.upload_events()

    # ---- v2 paged allocator: windows + bucket migration ------------------

    def _window_trace(self, tr, capacity: int):
        """The leading `capacity-1` events of each core's row, with a
        FORCED END at index capacity-1 for every core that was truncated.
        The promotion bound keeps every trace pointer strictly below that
        index, so the forced END is never consumed and the windowed
        element's state stays bit-identical to a full-trace run."""
        from ..trace.format import EV_END, Trace

        keep = capacity - 1
        n_cores = tr.events.shape[0]
        ev = np.zeros((n_cores, capacity, 4), np.int32)
        ev[:, :, 0] = EV_END
        ev[:, :keep] = tr.events[:, :keep]
        lengths = np.where(
            tr.lengths > keep, keep + 1, tr.lengths
        ).astype(np.int32)
        return Trace(ev, lengths, line_addressed=tr.line_addressed,
                     line_bits=tr.line_bits)

    def _window_ok(self, job: J.Job, b: SlotBucket) -> bool:
        """May `job` run its leading window in bucket `b`? Requires: the
        full trace does NOT fit b (else pass 1 handles it) but DOES fit
        some bucket (else quarantined at admission); no checkpoint resume
        pending (a snapshot taken past the window edge cannot replay
        inside it); a window deep enough to outlast one chunk; and no
        sync events — a barrier truncated out of one core's window would
        deadlock the cores that kept it."""
        tr = job._trace
        if tr is None or tr.max_len <= b.capacity:
            return False
        if tr.max_len > self.max_capacity:
            return False
        if job._resume_from is not None:
            return False
        if b.capacity - 1 <= b.chunk_steps:
            return False
        if any(sb.capacity >= tr.max_len and sb.free_slot() is not None
               for sb in self.buckets):
            return False  # a full-fit slot is free; windowing would waste it
        if job._has_sync is None:
            from ..trace.format import SYNC_TYPES

            job._has_sync = bool(
                np.isin(tr.events[:, :, 0], SYNC_TYPES).any()
            )
        return not job._has_sync

    def _pick_window(self, b: SlotBucket) -> J.Job | None:
        """Window-admission pick: same fairness key as _pick_next, over
        jobs whose full trace does not fit this bucket."""
        best = None
        best_key = None
        for job_id in self.queue:
            job = self.jobs[job_id]
            if not self._window_ok(job, b):
                continue
            key = (
                -job.priority,
                self._last_pick.get(job.client, -1),
                job.accepted_t,
            )
            if best_key is None or key < best_key:
                best, best_key = job, key
        return best

    def _migrate_out(self, b: SlotBucket, i: int, job: J.Job,
                     why: str) -> None:
        """Checkpoint-evict a RUNNING occupant back to the queue head so
        the next fill re-splices it elsewhere and it resumes mid-run.
        The snapshot is fingerprinted against the FULL trace — machine
        state is geometry-shaped, not capacity-shaped, so it restores
        into any bucket."""
        from ..sim.checkpoint import save_element_checkpoint

        path = self.job_ckpt_path(job.job_id)
        save_element_checkpoint(path, b.fleet, i, job_id=job.job_id,
                                trace=job._trace)
        b.fleet.clear_element(i)
        b.slots[i] = None
        job._window = None
        job._resume_from = path
        job.transition(J.PENDING)
        self.queue.insert(0, job.job_id)
        self.journal.state(
            job.job_id, J.PENDING,
            detail={"detail": why, "migrated": True,
                    "from_pages": b.n_pages},
        )

    def _promote_windows(self) -> None:
        """Migrate windowed jobs UP before the window edge can matter.
        Bound: a chunk advances any trace pointer by at most chunk_steps
        (one event per core per step), so promoting whenever
        max(ptr) >= keep - chunk_steps after a chunk guarantees
        ptr <= keep-1 always — the forced END at `keep` is never read,
        and the promoted job resumes from state a full-trace run would
        have produced identically."""
        for b in self.buckets:
            for i, job in enumerate(b.slots):
                if job is None or job._window is None:
                    continue
                keep = b.capacity - 1
                ptr = int(np.asarray(b.fleet.state.ptr)[i].max())
                if ptr < keep - b.chunk_steps:
                    continue
                steps = int(b.fleet.steps_run[i])
                self._migrate_out(
                    b, i, job,
                    f"promoted out of {b.n_pages}p window at event {ptr}",
                )
                self.promotions += 1
                self._serve_event("promote", job_id=job.job_id,
                                  from_pages=b.n_pages, ptr=ptr,
                                  steps=steps)

    def _demote_for_queued(self) -> None:
        """Starvation valve (at most one migration per tick): a queued
        job that only fits the larger buckets is blocked while they are
        full; if one of their occupants would fully fit a FREE smaller
        slot, checkpoint-migrate the occupant down and free the big
        slot."""
        blocked = None
        for job_id in self.queue:
            q = self.jobs[job_id]
            if q._trace is None:
                continue
            fitting = [b for b in self.buckets
                       if b.capacity >= q._trace.max_len]
            if fitting and all(b.free_slot() is None for b in fitting):
                blocked = q
                break
        if blocked is None:
            return
        for b in reversed(self.buckets):  # largest candidates first
            if b.capacity < blocked._trace.max_len:
                continue
            for i, occ in enumerate(b.slots):
                if occ is None or occ._window is not None:
                    continue
                target = next(
                    (sb for sb in self.buckets
                     if sb.capacity < b.capacity
                     and sb.capacity >= occ._trace.max_len
                     and sb.free_slot() is not None),
                    None,
                )
                if target is None:
                    continue
                self._migrate_out(
                    b, i, occ,
                    f"demoted from {b.n_pages}p to {target.n_pages}p "
                    f"to unblock {blocked.job_id}",
                )
                self.demotions += 1
                self._serve_event("demote", job_id=occ.job_id,
                                  from_pages=b.n_pages,
                                  to_pages=target.n_pages,
                                  unblocks=blocked.job_id)
                return

    def _place(self, b: SlotBucket, i: int, job: J.Job,
               upload: bool = True) -> None:
        from ..sim.checkpoint import load_element_checkpoint

        b.fleet.replace_element(
            i,
            job._window if job._window is not None else job._trace,
            base_cfg=job._elem_cfg,
            upload=upload,
        )
        resumed = False
        warm_steps = 0
        ckpt_attest = None
        if job._resume_from:
            try:
                snap = load_element_checkpoint(
                    job._resume_from, job._elem_cfg, job._trace
                )
                b.fleet.restore_element(i, snap)
                ckpt_attest = snap.get("attest")
                resumed = True
            except Exception as e:  # corrupt/mismatched ckpt: fresh start
                self.journal.note(
                    f"{job.job_id}: element checkpoint unusable "
                    f"({type(e).__name__}: {e}); restarting from step 0"
                )
        if not resumed and self.warm_root is not None \
                and job._window is None:
            # (windowed splices skip the warm cache: a warm state's trace
            # pointer may already sit past the window edge)
            # no mid-run checkpoint of its own: check the warm cache. The
            # content key proves the first `steps` steps of this exact
            # (trace, config) workload; fork_element reseeds the traced
            # fault inputs so a schedule/seed difference past the prefix
            # stays the job's own
            from ..sim.checkpoint import (
                CheckpointCorrupt,
                find_warm_states,
                load_warm_state,
                trace_fingerprint,
            )

            fp = trace_fingerprint(job._trace)
            for steps, key in find_warm_states(
                self.warm_root, job._elem_cfg, fp
            ):
                if steps >= job.max_steps:
                    continue  # would overshoot the job's step budget
                try:
                    snap = load_warm_state(
                        self.warm_root, key, job._elem_cfg, fp, steps
                    )
                except (FileNotFoundError, CheckpointCorrupt, ValueError) as e:
                    self.journal.note(
                        f"{job.job_id}: warm entry {key[:12]} unusable "
                        f"({type(e).__name__}); trying next"
                    )
                    continue
                b.fleet.fork_element(i, snap, cache_key=key)
                warm_steps = steps
                self.journal.note(
                    f"{job.job_id}: admitted from warm cache at step "
                    f"{steps} (key {key[:12]})"
                )
                if self.obs is not None:
                    self.obs.prefix_event(
                        "warm-hit", job_id=job.job_id, key=key, steps=steps
                    )
                break
        if b.fleet.attest is not None:
            # continue a checkpointed chain when the cadence still
            # matches; otherwise the chain restarts at the boundary the
            # slot resumes from (migration, warm fork, fresh start) and
            # `comparable()` keeps it from false-matching a full run
            cs = b.chunk_steps
            if ckpt_attest and ckpt_attest.get("head") \
                    and int(ckpt_attest.get("chunk_steps", 0)) == cs:
                b.fleet.attest.track(
                    i, cs, start=int(ckpt_attest.get("start", 0)),
                    head=ckpt_attest["head"],
                    chunks=int(ckpt_attest.get("chunks", 0)),
                )
            else:
                b.fleet.attest.track(
                    i, cs, start=int(b.fleet.steps_run[i])
                )
        b.slots[i] = job
        job.attempts += 1
        job.transition(J.RUNNING)
        self.last_dispatch_t = time.time()
        self.journal.state(
            job.job_id, J.RUNNING,
            detail={"attempt": job.attempts, "resumed": resumed,
                    "warm_steps": warm_steps,
                    "bucket_pages": b.n_pages, "slot": i,
                    "window": job._window is not None},
        )
        self._serve_event("dispatch", job_id=job.job_id, slot=i,
                          bucket_pages=b.n_pages, attempt=job.attempts,
                          resumed=resumed, warm_steps=warm_steps,
                          window=job._window is not None)

    def _slot_of(self, job: J.Job) -> tuple[SlotBucket, int] | None:
        for b in self.buckets:
            for i, occ in enumerate(b.slots):
                if occ is job:
                    return b, i
        return None

    def _slot_steps(self, job: J.Job) -> int:
        loc = self._slot_of(job)
        if loc is None:
            return 0
        b, i = loc
        return int(b.fleet.steps_run[i])

    def _evict(self, job: J.Job) -> None:
        """Free a RUNNING job's slot without journaling (caller decides
        the terminal record)."""
        loc = self._slot_of(job)
        if loc is not None:
            b, i = loc
            b.fleet.clear_element(i)
            b.slots[i] = None

    def _harvest(self, now: float) -> None:
        for b in self.buckets:
            if b.occupied == 0:
                continue
            dm = b.fleet.done_mask()
            cleared = False
            for i, job in enumerate(b.slots):
                if job is None:
                    continue
                if dm[i]:
                    result = self._element_result(b, i)
                    b.fleet.clear_element(i, upload=False)
                    b.slots[i] = None
                    cleared = True
                    self.total_instructions += result["instructions"]
                    self.completed += 1
                    self._terminal(job, J.DONE, result=result)
                    self._serve_event("retire", job_id=job.job_id,
                                      state=J.DONE,
                                      steps=result["steps"],
                                      instructions=result["instructions"])
                    self._drop_ckpt(job.job_id)
                elif int(b.fleet.steps_run[i]) >= job.max_steps:
                    steps = int(b.fleet.steps_run[i])
                    b.fleet.clear_element(i, upload=False)
                    b.slots[i] = None
                    cleared = True
                    self._terminal(
                        job, J.QUARANTINED,
                        detail={
                            "type": "StepBudget",
                            "location": {},
                            "detail": f"step budget {job.max_steps} "
                                      f"exhausted at {steps} steps "
                                      "(deadlock?)",
                        },
                    )
                    self._serve_event("retire", job_id=job.job_id,
                                      state=J.QUARANTINED, steps=steps)
                    self._drop_ckpt(job.job_id)
            if cleared:
                b.fleet.upload_events()

    def _element_result(self, b: SlotBucket, i: int) -> dict:
        """The job's result record: per-core cycles and counters, exactly
        what a solo Engine run of (elem_cfg, trace) reports — the
        bit-exactness contract the tests pin."""
        cyc = b.fleet.cycles[i]
        counters = b.fleet.element_counters(i)
        res = {
            "cycles": int(cyc.max()),
            "core_cycles": [int(c) for c in cyc],
            "steps": int(b.fleet.steps_run[i]),
            "instructions": int(counters["instructions"].sum()),
            "counters": {
                k: [int(x) for x in v] for k, v in counters.items()
            },
        }
        if b.fleet.attest is not None:
            # the chain head rides the journaled result record, so fsck
            # can cross-check it against the job's last element
            # checkpoint and `primetpu audit` can re-derive it offline
            at = b.fleet.attest.payload(i)
            if at is not None:
                res["attest"] = at
        return res

    # ---- failure / retry -------------------------------------------------

    def _dispatch_failed(self, b: SlotBucket, exc: BaseException) -> None:
        """A chunk dispatch failed. The exception cannot name the guilty
        element, so the bucket rolls back wholesale: every occupant
        spends one retry (with backoff + checkpoint resume) or goes
        FAILED, then the fleet is rebuilt all-idle."""
        occupants = [j for j in b.slots if j is not None]
        self._serve_event("rollback", bucket_pages=b.n_pages,
                          error=type(exc).__name__,
                          occupants=len(occupants))
        self.journal.note(
            f"bucket[{b.n_pages}p] dispatch failed with "
            f"{type(exc).__name__}: {exc}; rolling back "
            f"{len(occupants)} occupant(s)"
        )
        max_delay = 0.0
        for job in occupants:
            delay = job._ctx.next_retry(exc) if job._ctx else None
            if delay is None:
                job.transition(J.FAILED, detail=error_obj(exc)["error"])
                job.detail["retry_log"] = list(job._ctx.log) if job._ctx \
                    else []
                self.journal.state(
                    job.job_id, J.FAILED, detail=job.detail
                )
                self._finish_stats(job)
                self._drop_ckpt(job.job_id)
            else:
                max_delay = max(max_delay, delay)
                job.transition(J.PENDING)
                if os.path.exists(self.job_ckpt_path(job.job_id)):
                    job._resume_from = self.job_ckpt_path(job.job_id)
                self.queue.append(job.job_id)
                self.journal.state(
                    job.job_id, J.PENDING,
                    detail={"detail": "re-enqueued after dispatch failure"},
                )
        b.rebuild()
        self._backoff_until = time.time() + max_delay

    # ---- durability ------------------------------------------------------

    def checkpoint_running(self) -> None:
        """Element-checkpoint every RUNNING job to its deterministic
        per-job path (atomic tmp+rename, so a crash mid-save leaves the
        previous checkpoint intact)."""
        from ..sim.checkpoint import save_element_checkpoint
        from ..util.diskpressure import DiskPressureError

        for b in self.buckets:
            for i, job in enumerate(b.slots):
                if job is not None:
                    try:
                        # fingerprint the FULL trace even for windowed
                        # elements: recovery re-materializes the full
                        # trace and must accept this snapshot
                        save_element_checkpoint(
                            self.job_ckpt_path(job.job_id), b.fleet, i,
                            job_id=job.job_id, trace=job._trace,
                        )
                    except DiskPressureError as e:
                        # a skipped cadence checkpoint only widens this
                        # job's recovery replay window; the job itself —
                        # and every ACKed record — is untouched
                        self._serve_event(
                            "disk-pressure", job_id=job.job_id,
                            detail=str(e),
                        )
                        continue
                    self._serve_event(
                        "checkpoint", job_id=job.job_id,
                        steps=int(b.fleet.steps_run[i]),
                    )

    def _drop_ckpt(self, job_id: str) -> None:
        try:
            os.unlink(self.job_ckpt_path(job_id))
        except OSError:
            pass

    def drain(self) -> int:
        """Graceful shutdown: checkpoint every in-flight job so the next
        server resumes it mid-run, then journal the clean-drain marker.
        Returns the number of jobs left unfinished (pending+running)."""
        self.checkpoint_running()
        unfinished = len(self.queue)
        for b in self.buckets:
            for job in b.slots:
                if job is not None:
                    unfinished += 1
        self.journal.drain()
        return unfinished

    # ---- terminal bookkeeping / stats ------------------------------------

    def _terminal(self, job: J.Job, state: str, detail: dict | None = None,
                  result: dict | None = None) -> None:
        job.transition(state, detail=detail)
        if result is not None:
            job.result = result
        self.journal.state(job.job_id, state, detail=detail, result=result)
        self._finish_stats(job)

    def _finish_stats(self, job: J.Job) -> None:
        if job.latency_s is not None:
            self._latencies.append(job.latency_s)
            self.latency_hist.observe(job.latency_s)
            if len(self._latencies) > 512:
                del self._latencies[:-512]

    def stats(self) -> dict:
        now = time.time()
        by_state = {s: 0 for s in J.STATES}
        for job in self.jobs.values():
            by_state[job.state] += 1
        lat = sorted(self._latencies)

        def pct(p):
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3)

        wall = max(now - self.started_t, 1e-9)
        return {
            "queue_depth": len(self.queue),
            "slots": {
                "total": self.total_slots,
                "occupied": sum(b.occupied for b in self.buckets),
                "buckets": [
                    {
                        "pages": b.n_pages,
                        "capacity_events": b.capacity,
                        "slots": b.n_slots,
                        "occupied": b.occupied,
                    }
                    for b in self.buckets
                ],
            },
            "jobs": by_state,
            "completed": self.completed,
            "migrations": {"promotions": self.promotions,
                           "demotions": self.demotions},
            "aggregate_mips": round(
                self.total_instructions / wall / 1e6, 3
            ),
            "latency_s": {"p50": pct(0.50), "p90": pct(0.90),
                          "p99": pct(0.99)},
            "uptime_s": round(wall, 1),
            "last_dispatch_t": self.last_dispatch_t,
            "last_dispatch_age_s": (
                round(now - self.last_dispatch_t, 1)
                if self.last_dispatch_t else None
            ),
        }

    def service_report(self) -> dict:
        """The SERVICE section for stats.report.render_report."""
        s = self.stats()
        return {
            "jobs_completed": s["completed"],
            "jobs_by_state": {k: v for k, v in s["jobs"].items() if v},
            "aggregate_mips": s["aggregate_mips"],
            "latency_s": s["latency_s"],
            "uptime_s": s["uptime_s"],
        }
