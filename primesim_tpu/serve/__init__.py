"""primesim_tpu.serve — crash-safe continuous-batching simulation service.

`primetpu serve` owns one compiled fleet program per capacity bucket and
splices client jobs into free slots as elements retire; every accepted
job is journaled (WAL) and checkpointed so a `kill -9` loses nothing.
See DESIGN.md §14 and README "Serving simulations".

Light modules (jobs, journal, protocol, client) import eagerly; the
scheduler/server (which pull in the JAX-backed fleet) resolve lazily so
`import primesim_tpu.serve` stays cheap for clients and error paths.
"""

from .client import ServeClient, ServeError
from .jobs import (
    CANCELLED,
    DONE,
    FAILED,
    PENDING,
    QUARANTINED,
    RUNNING,
    TERMINAL_STATES,
    TIMEOUT,
    Job,
)
from .journal import JobJournal, JournalCorrupt, fold_records
from .protocol import error_obj

_LAZY = {
    "Scheduler": "scheduler",
    "SlotBucket": "scheduler",
    "QueueFull": "scheduler",
    "DEFAULT_BUCKETS": "scheduler",
    "PAGE_EVENTS": "scheduler",
    "materialize_workload": "scheduler",
    "PrimeServer": "server",
    "EX_TEMPFAIL": "server",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


__all__ = [
    "CANCELLED",
    "DEFAULT_BUCKETS",
    "DONE",
    "EX_TEMPFAIL",
    "FAILED",
    "Job",
    "JobJournal",
    "JournalCorrupt",
    "PAGE_EVENTS",
    "PENDING",
    "PrimeServer",
    "QUARANTINED",
    "QueueFull",
    "RUNNING",
    "Scheduler",
    "ServeClient",
    "ServeError",
    "SlotBucket",
    "TERMINAL_STATES",
    "TIMEOUT",
    "error_obj",
    "fold_records",
    "materialize_workload",
]
