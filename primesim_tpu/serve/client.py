"""Client for a running `primetpu serve` daemon — thin verb wrappers over
the JSON-lines protocol, used by `primetpu submit` / `primetpu
serve-status` and directly by tests.

Targets are either a unix-socket path or `host:port` (the TCP
front-end). Resilience contract:

- CONNECT-phase failures (`ServeUnavailable` — nothing was sent) retry
  under decorrelated-jitter backoff for any verb: the retry cannot
  double-submit because the server never saw the request.
- POST-SEND failures (plain ConnectionError/OSError — the connection
  died after bytes left, so the request MAY have been handled and its
  ACK lost) retry only for verbs marked idempotent. `max_reconnects`
  defaults to 1 so an interactive CLI reports a dead daemon quickly;
  long-lived callers (chaos trials, batch drivers) raise it. Reads (status,
  result, wait, health, metrics) are naturally idempotent; `submit` is
  MADE idempotent by a client-generated idempotency token — the server
  answers a retried token with the already-accepted job instead of
  enqueueing a twin. `cancel` stays single-shot.
"""

from __future__ import annotations

import time
import uuid

from ..util.backoff import DecorrelatedJitter, jittered
from .protocol import ServeUnavailable, request


class ServeError(RuntimeError):
    """Server replied `ok: false`. Carries the structured error object
    and the backpressure hint when one was offered."""

    def __init__(self, reply: dict):
        err = reply.get("error") or {}
        super().__init__(err.get("detail") or "server error")
        self.reply = reply
        self.error = err
        self.retry_after_s = reply.get("retry_after_s")


class ServeClient:
    def __init__(self, target: str, timeout_s: float = 30.0,
                 connect_timeout_s: float = 5.0,
                 max_reconnects: int = 1, rng=None):
        # `target` may be a comma-separated failover list ("primary,
        # standby"): a connect-phase failure rotates to the next entry,
        # so a watched/submitting client rides out a promotion instead
        # of dying with the old primary
        self.targets = [t.strip() for t in str(target).split(",")
                        if t.strip()] or [str(target)]
        self._ti = 0
        self.target = self.targets[0]
        self.socket_path = self.target  # legacy alias (pre-TCP callers)
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_reconnects = int(max_reconnects)
        self.rng = rng
        self.reconnects = 0  # observable retry count (tests/diagnostics)

    def _call(self, req: dict, timeout_s: float | None = None,
              idempotent: bool = False) -> dict:
        """One verb round-trip under the resilience contract above."""
        jitter = DecorrelatedJitter(base=0.2, cap=3.0, rng=self.rng)
        attempt = 0
        while True:
            try:
                reply = self._request(req, timeout_s)
                break
            except ServeUnavailable:
                # connect never completed: always safe to retry — on
                # the NEXT target of the failover list when one exists
                self._rotate()
                if attempt >= self.max_reconnects:
                    raise
            except (ConnectionError, OSError):
                # post-send: the server may have handled the request and
                # the reply died on the wire — only a token-carrying or
                # read-only request may be replayed
                if not idempotent or attempt >= self.max_reconnects:
                    raise
                self._rotate()
            attempt += 1
            self.reconnects += 1
            time.sleep(jitter.next_delay())
        if not reply.get("ok", False):
            raise ServeError(reply)
        return reply

    def _rotate(self) -> None:
        if len(self.targets) > 1:
            self._ti = (self._ti + 1) % len(self.targets)
            self.target = self.targets[self._ti]

    def _request(self, req: dict, timeout_s: float | None) -> dict:
        return request(
            self.target, req,
            timeout_s=self.timeout_s if timeout_s is None else timeout_s,
            connect_timeout_s=self.connect_timeout_s,
        )

    def submit(
        self,
        trace_path: str | None = None,
        synth: str | None = None,
        overrides: dict | None = None,
        fold: bool = True,
        deadline_s: float | None = None,
        max_steps: int = 10_000_000,
        priority: int = 0,
        client: str = "anon",
        retries: int = 0,
        idem: str | None = None,
    ) -> dict:
        """Submit one job; the reply's job is ACKed = durably journaled.
        A fresh idempotency token is generated unless `idem` is given,
        so transparent reconnect-retries cannot double-enqueue. With
        `retries`, honors RETRY_AFTER backpressure by sleeping and
        resubmitting up to that many times."""
        req = {
            "verb": "submit",
            "trace_path": trace_path,
            "synth": synth,
            "overrides": dict(overrides or {}),
            "fold": fold,
            "deadline_s": deadline_s,
            "max_steps": max_steps,
            "priority": priority,
            "client": client,
            "idem": idem or uuid.uuid4().hex,
        }
        attempt = 0
        while True:
            try:
                return self._call(req, idempotent=True)["job"]
            except ServeError as e:
                if e.retry_after_s is None or attempt >= retries:
                    raise
                attempt += 1
                # jitter the server's hint (util.backoff): N clients told
                # "retry in 5s" must not resubmit in the same instant
                time.sleep(jittered(float(e.retry_after_s), rng=self.rng))

    def status(self, job_id: str | None = None) -> dict | list:
        reply = self._call({"verb": "status", "job_id": job_id},
                           idempotent=True)
        return reply["job"] if job_id else reply["jobs"]

    def result(self, job_id: str) -> dict:
        return self._call({"verb": "result", "job_id": job_id},
                          idempotent=True)

    def wait(self, job_id: str, timeout_s: float = 300.0) -> dict:
        """Block until the job is terminal; returns its public view."""
        reply = self._call(
            {"verb": "wait", "job_id": job_id, "timeout_s": timeout_s},
            timeout_s=timeout_s + 10.0,
            idempotent=True,
        )
        return reply["job"]

    def cancel(self, job_id: str) -> dict:
        return self._call({"verb": "cancel", "job_id": job_id})["job"]

    def health(self) -> dict:
        return self._call({"verb": "health"}, idempotent=True)

    def metrics(self) -> str:
        """Prometheus text exposition from the daemon's `metrics` verb."""
        return self._call({"verb": "metrics"}, idempotent=True)["text"]

    def drain(self) -> dict:
        return self._call({"verb": "drain"}, idempotent=True)
