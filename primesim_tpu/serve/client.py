"""Client for a running `primetpu serve` daemon — thin verb wrappers over
the JSON-lines protocol, used by `primetpu submit` / `primetpu
serve-status` and directly by tests.

Targets are either a unix-socket path or `host:port` (the TCP
front-end). Connects are bounded by `connect_timeout_s` and retried
ONCE on a connect-phase failure (`ServeUnavailable` — nothing was sent,
so the retry cannot double-submit) before the service is reported down;
post-send failures propagate immediately."""

from __future__ import annotations

import time

from ..util.backoff import jittered
from .protocol import ServeUnavailable, request


class ServeError(RuntimeError):
    """Server replied `ok: false`. Carries the structured error object
    and the backpressure hint when one was offered."""

    def __init__(self, reply: dict):
        err = reply.get("error") or {}
        super().__init__(err.get("detail") or "server error")
        self.reply = reply
        self.error = err
        self.retry_after_s = reply.get("retry_after_s")


class ServeClient:
    def __init__(self, target: str, timeout_s: float = 30.0,
                 connect_timeout_s: float = 5.0):
        self.target = str(target)
        self.socket_path = self.target  # legacy alias (pre-TCP callers)
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)

    def _call(self, req: dict, timeout_s: float | None = None) -> dict:
        try:
            reply = self._request(req, timeout_s)
        except ServeUnavailable:
            # connect never completed: one jittered retry before the
            # service is declared down (front-end failover window)
            time.sleep(jittered(0.2))
            reply = self._request(req, timeout_s)
        if not reply.get("ok", False):
            raise ServeError(reply)
        return reply

    def _request(self, req: dict, timeout_s: float | None) -> dict:
        return request(
            self.target, req,
            timeout_s=self.timeout_s if timeout_s is None else timeout_s,
            connect_timeout_s=self.connect_timeout_s,
        )

    def submit(
        self,
        trace_path: str | None = None,
        synth: str | None = None,
        overrides: dict | None = None,
        fold: bool = True,
        deadline_s: float | None = None,
        max_steps: int = 10_000_000,
        priority: int = 0,
        client: str = "anon",
        retries: int = 0,
    ) -> dict:
        """Submit one job; the reply's job is ACKed = durably journaled.
        With `retries`, honors RETRY_AFTER backpressure by sleeping and
        resubmitting up to that many times."""
        req = {
            "verb": "submit",
            "trace_path": trace_path,
            "synth": synth,
            "overrides": dict(overrides or {}),
            "fold": fold,
            "deadline_s": deadline_s,
            "max_steps": max_steps,
            "priority": priority,
            "client": client,
        }
        attempt = 0
        while True:
            try:
                return self._call(req)["job"]
            except ServeError as e:
                if e.retry_after_s is None or attempt >= retries:
                    raise
                attempt += 1
                # jitter the server's hint (util.backoff): N clients told
                # "retry in 5s" must not resubmit in the same instant
                time.sleep(jittered(float(e.retry_after_s)))

    def status(self, job_id: str | None = None) -> dict | list:
        reply = self._call({"verb": "status", "job_id": job_id})
        return reply["job"] if job_id else reply["jobs"]

    def result(self, job_id: str) -> dict:
        return self._call({"verb": "result", "job_id": job_id})

    def wait(self, job_id: str, timeout_s: float = 300.0) -> dict:
        """Block until the job is terminal; returns its public view."""
        reply = self._call(
            {"verb": "wait", "job_id": job_id, "timeout_s": timeout_s},
            timeout_s=timeout_s + 10.0,
        )
        return reply["job"]

    def cancel(self, job_id: str) -> dict:
        return self._call({"verb": "cancel", "job_id": job_id})["job"]

    def health(self) -> dict:
        return self._call({"verb": "health"})

    def metrics(self) -> str:
        """Prometheus text exposition from the daemon's `metrics` verb."""
        return self._call({"verb": "metrics"})["text"]

    def drain(self) -> dict:
        return self._call({"verb": "drain"})
