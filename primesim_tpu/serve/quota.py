"""Per-tenant admission quotas (DESIGN.md §18).

The serve scheduler's priority/fairness tiers order work AFTER
admission; quotas bound what each tenant may admit in the first place.
One token bucket per client id: `rate` tokens/second refill up to
`burst` capacity, one token per accepted submit. A drained bucket
rejects with `QuotaExceeded` carrying `retry_after_s` — the exact time
until one token exists — so well-behaved clients back off precisely
instead of hammering (the same structured-backpressure shape QueueFull
uses, and `ServeClient.submit(retries=...)` already honors it).

The bucket is deliberately NOT durable: a front-end restart refills
everyone. Quotas protect the service's admission rate, not a billing
ledger — forgiving a crash window is the right failure mode.
"""

from __future__ import annotations

import threading
import time


class QuotaExceeded(RuntimeError):
    """Per-tenant admission rate exceeded. `retry_after_s` is the exact
    delay until the tenant's bucket holds one token again."""

    def __init__(self, client: str, retry_after_s: float):
        super().__init__(
            f"client {client!r} exceeded its admission quota; retry in "
            f"{retry_after_s:.2f}s"
        )
        self.client = client
        self.retry_after_s = retry_after_s


class TenantQuota:
    """Token buckets for every tenant under one (rate, burst) policy.
    `clock` is injectable so tests don't sleep."""

    def __init__(self, rate: float, burst: float | None = None,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"quota rate must be positive, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(
            1.0, self.rate
        )
        if self.burst < 1.0:
            raise ValueError(
                f"burst {self.burst} < 1 token: nothing could ever submit"
            )
        self.clock = clock
        self.rejections = 0
        self._lock = threading.Lock()
        self._buckets: dict[str, tuple[float, float]] = {}  # client ->
        #   (tokens, last refill time)

    def admit(self, client: str) -> None:
        """Spend one token for `client` or raise QuotaExceeded."""
        client = str(client or "anon")
        now = self.clock()
        with self._lock:
            tokens, last = self._buckets.get(client, (self.burst, now))
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            if tokens >= 1.0:
                self._buckets[client] = (tokens - 1.0, now)
                return
            self._buckets[client] = (tokens, now)
            self.rejections += 1
        raise QuotaExceeded(client, (1.0 - tokens) / self.rate)

    @staticmethod
    def parse(spec: str) -> "TenantQuota":
        """CLI form `RATE` or `RATE:BURST` (e.g. `2`, `0.5:10`)."""
        rate, _, burst = str(spec).partition(":")
        return TenantQuota(float(rate), float(burst) if burst else None)
