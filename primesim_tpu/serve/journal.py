"""Crash-safe job journal — a segmented WAL for the serving daemon
(DESIGN.md §14, §18).

The journal is a sequence of append-only JSON-lines SEGMENTS in the
state directory. The ACTIVE segment is always `journal.jsonl`; when it
reaches `segment_records` records it is rolled: closed, renamed to
`journal-<seq:06d>.jsonl`, and a fresh active segment is opened whose
first record is a framed header

    {"t": "seg", "seq": <n>, "prev": <crc32 of the rolled segment's
                                      last raw line>}

so the segment chain is both SEQUENCE-NUMBERED and CRC-CHAINED: a
deleted middle segment is a sequence gap, a substituted one breaks the
chain — both raise `JournalCorrupt`, never a silent skip. A journal
that never rolled is byte-identical to the legacy single-file format
(headerless seq-0 active segment), so old state directories replay
unchanged.

Durability discipline mirrors `checkpoint.atomic_save_npz` adapted to an
append-only log:

- every record is framed `{"c": crc32(payload_json), "r": payload}` so a
  torn or bit-rotted line is detected before it is trusted;
- `append()` writes the line, flushes, and `fsync`s BEFORE returning —
  the server only ACKs a submission after its accept record is durable,
  which is the whole crash-safety invariant: ACKed => journaled =>
  replayed => reaches a terminal state;
- the journal directory is fsynced at creation and after every segment
  rename, so the files' own existence survives power loss.

Replay walks the segments in sequence order and tolerates a torn TAIL
(the one partial line a crash mid-append can leave) ONLY in the newest
segment — rolled segments were closed at a clean record boundary, so
any bad line inside one is media rot and raises `JournalCorrupt`, as
does a bad record followed by valid ones inside the active segment.
Before its first append a reopened journal REPAIRS a torn tail by
truncating it (the torn line was never ACKed): appending after a torn
line would otherwise concatenate into it and turn a tolerated tail into
mid-file corruption on the next replay.

COMPACTION (snapshot + truncate): with a `compactor` — a function
`records -> records` that must preserve the journal's fold (serve:
`serve_compactor` via `fold_records`; pool: `units.pool_compactor` via
`fold_unit_records`) — the journal periodically folds its whole history
into a minimal equivalent record list and rewrites it as a single
snapshot-BASE segment (`"base": true` in its header), then deletes the
older segments. Replay starts at the newest base segment; older
leftovers (a crash between the atomic snapshot rename and the deletes)
are ignored, so compaction is crash-safe at every instant.

Record types (`t` field): `accept` (the Job accept_record), `state`
(job_id + new state + detail/result), `drain` (clean shutdown marker),
`note` (operator annotations), `seg` (segment header, filtered out of
`replay()` results), `epoch` (fencing epoch, replicate.py — preserved
across compaction by `compact()` itself, since no domain compactor
knows about it), plus the pool ledger types (units.py).
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib

from ..chaos import sites as chaos
from ..obs.metrics import Histogram
from ..util import diskpressure

#: default active-segment record cap before a roll; None = never roll
#: (the legacy single-file behavior)
DEFAULT_SEGMENT_RECORDS = 512

#: rolled-segment count that triggers compaction (when a compactor is set)
DEFAULT_COMPACT_SEGMENTS = 4

_SEG_RE = re.compile(r"^journal-(\d{6})\.jsonl$")


class JournalCorrupt(ValueError):
    """Journal corruption that cannot be a torn append: a record failing
    its CRC ahead of valid ones, a bad line in a rolled (closed) segment,
    a missing segment in the sequence, or a broken segment CRC chain."""


def _frame(rec: dict) -> str:
    payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    return json.dumps(
        {"c": zlib.crc32(payload.encode()), "r": rec},
        sort_keys=True,
        separators=(",", ":"),
    )


def _unframe(line: str) -> dict | None:
    """Decode + CRC-verify one journal line; None when unusable."""
    try:
        obj = json.loads(line)
        rec = obj["r"]
        payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        if zlib.crc32(payload.encode()) != int(obj["c"]):
            return None
        return rec
    except (ValueError, KeyError, TypeError):
        return None


def _line_crc(line: str) -> int:
    return zlib.crc32(line.encode())


def _scan_lines(path: str) -> list[str]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return [ln for ln in f.read().splitlines() if ln.strip()]


class JobJournal:
    """Append-only fsynced record log: active segment
    `directory/journal.jsonl` plus rolled `journal-NNNNNN.jsonl`."""

    def __init__(
        self,
        directory: str,
        segment_records: int | None = DEFAULT_SEGMENT_RECORDS,
        compactor=None,
        compact_segments: int = DEFAULT_COMPACT_SEGMENTS,
    ):
        self.dir = str(directory)
        self.path = os.path.join(self.dir, "journal.jsonl")
        self.segment_records = segment_records
        self.compactor = compactor
        self.compact_segments = int(compact_segments)
        fresh = not os.path.isdir(self.dir)
        os.makedirs(self.dir, exist_ok=True)
        if fresh:
            dfd = os.open(
                os.path.dirname(os.path.abspath(self.dir)) or ".",
                os.O_RDONLY,
            )
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        # crash mid-roll: the rename committed but the new active segment
        # was never created — recreate it so the chain stays closed
        rolled = self._rolled_segments()
        if rolled and not os.path.exists(self.path):
            last_lines = _scan_lines(rolled[-1][1])
            self._open_active(
                seq=rolled[-1][0] + 1,
                prev_crc=_line_crc(last_lines[-1]) if last_lines else 0,
            )
        else:
            self._f = open(self.path, "a", encoding="utf-8")
            self._active_seq, self._active_records, self._last_crc = \
                self._scan_active()
        # torn-tail repair is LAZY (first append): replay() must still
        # report the torn line of a journal that is only being read
        self._tail_checked = False
        self.appended = 0
        self.segments_rolled = 0
        self.compactions = 0
        # always-on fsync latency histogram (Prometheus `metrics` verb);
        # obs is an optional Recorder that additionally puts each fsync
        # on the flight-recorder timeline
        self.fsync_hist = Histogram()
        self.obs = None
        # optional replication sink (serve/replicate.py): called AFTER
        # the local fsync with the exact raw bytes on disk, so follower
        # chains stay byte-identical to this one
        self.sink = None
        # disk-pressure ladder: compaction folds rolled segments into
        # one base segment, the only space the journal may legally give
        # back — ACKed state itself is never an eviction candidate
        if self.compactor is not None:
            diskpressure.register_compactor(
                f"journal:{self.dir}", self.compact
            )

    # ---- segment bookkeeping ---------------------------------------------

    def _rolled_segments(self) -> list[tuple[int, str]]:
        """(seq, path) of every rolled segment, ascending by seq."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, name)))
        return sorted(out)

    def _scan_active(self) -> tuple[int, int, int]:
        """(seq, record count, last-valid-line crc) of the active segment
        as it sits on disk. Tolerant: corruption is replay()'s problem."""
        lines = _scan_lines(self.path)
        seq, n, last_crc = 0, 0, 0
        for i, line in enumerate(lines):
            rec = _unframe(line)
            if rec is None:
                continue
            if i == 0 and rec.get("t") == "seg":
                seq = int(rec.get("seq", 0))
            else:
                n += 1
            last_crc = _line_crc(line)
        return seq, n, last_crc

    def _open_active(self, seq: int, prev_crc: int, base: bool = False,
                     initial: list[dict] | None = None) -> None:
        """Create a fresh active segment (header first) atomically: built
        under a temp name, fsynced, then renamed over `journal.jsonl`."""
        header = {"t": "seg", "seq": int(seq), "prev": int(prev_crc)}
        if base:
            header["base"] = True
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            line = _frame(header)
            f.write(line + "\n")
            last = line
            for rec in initial or []:
                line = _frame(rec)
                f.write(line + "\n")
                last = line
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fsync_dir()
        self._f = open(self.path, "a", encoding="utf-8")
        self._active_seq = int(seq)
        self._active_records = len(initial or [])
        self._last_crc = _line_crc(last)

    def _fsync_dir(self) -> None:
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _repair_tail(self) -> None:
        """Truncate trailing torn lines before the first append of this
        process — a torn line was never ACKed, and appending after it
        would concatenate into mid-file corruption."""
        lines = []
        trailing_newline = True
        if os.path.exists(self.path):
            with open(self.path, encoding="utf-8") as f:
                raw = f.read()
            trailing_newline = (raw == "") or raw.endswith("\n")
            lines = raw.splitlines()
        bad_at = None
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            if _unframe(line) is None:
                if bad_at is None:
                    bad_at = i
            elif bad_at is not None:
                return  # mid-file rot: leave it for replay() to raise
        if bad_at is None and trailing_newline:
            return
        keep = lines[:bad_at] if bad_at is not None else lines
        self._f.close()
        with open(self.path, "w", encoding="utf-8") as f:
            for line in keep:
                f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f = open(self.path, "a", encoding="utf-8")
        self._active_seq, self._active_records, self._last_crc = \
            self._scan_active()

    def _roll(self) -> None:
        """Close the active segment at a record boundary, rename it into
        the rolled sequence, and chain a fresh active segment to it."""
        self._f.close()
        rolled_path = os.path.join(
            self.dir, f"journal-{self._active_seq:06d}.jsonl"
        )
        os.replace(self.path, rolled_path)
        self._fsync_dir()
        self._open_active(seq=self._active_seq + 1, prev_crc=self._last_crc)
        self.segments_rolled += 1
        if self.sink is not None:
            header_lines = _scan_lines(self.path)
            if header_lines:
                self.sink.on_roll(self._active_seq, header_lines[0])
        if (
            self.compactor is not None
            and len(self._rolled_segments()) >= self.compact_segments
        ):
            self.compact()

    # ---- write side ------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Durably append one record: write + flush + fsync. The caller
        may ACK the fact the record carries only AFTER this returns."""
        if not self._tail_checked:
            self._tail_checked = True
            self._repair_tail()
        if (
            self.segment_records is not None
            and self._active_records >= self.segment_records
        ):
            self._roll()
        t0 = time.perf_counter()
        line = _frame(rec)
        prev_crc = self._last_crc
        # disk-pressure gate: on a full disk this evicts caches/rotated
        # snapshots, compacts the journal, and raises DiskPressureError
        # (admission backpressure) BEFORE the append half-lands — the
        # record was not ACKed, so refusing it loses nothing
        diskpressure.preflight(self.path, len(line) + 1, kind="journal")
        chaos.durable("journal.append", f=self._f, data=line + "\n")
        self._f.write(line + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        dt = time.perf_counter() - t0
        self._last_crc = _line_crc(line)
        self._active_records += 1
        self.appended += 1
        self.fsync_hist.observe(dt)
        if self.obs is not None:
            self.obs.fsync_event(dt)
        if self.sink is not None:
            # locally durable first, then the wire: the sink ships the
            # raw line and books the quorum; the SERVER decides whether
            # an under-quorum frame may still be ACKed (quorum policy)
            self.sink.on_append(line, self._active_seq, prev_crc)

    def accept(self, job) -> None:
        self.append({"t": "accept", "job": job.accept_record()})

    def state(self, job_id: str, state: str, detail: dict | None = None,
              result: dict | None = None) -> None:
        rec = {"t": "state", "job_id": job_id, "state": state}
        if detail:
            rec["detail"] = detail
        if result is not None:
            rec["result"] = result
        self.append(rec)

    def note(self, msg: str) -> None:
        self.append({"t": "note", "msg": str(msg)})

    def drain(self) -> None:
        self.append({"t": "drain"})

    def close(self) -> None:
        diskpressure.unregister(f"journal:{self.dir}")
        try:
            self._f.close()
        except OSError:
            pass

    # ---- compaction ------------------------------------------------------

    def compact(self) -> int:
        """Snapshot + truncate: fold the whole history through the
        compactor into a minimal equivalent record list, write it as a
        fresh BASE segment (atomic rename over the active segment), then
        delete the older segments. Returns the compacted record count.
        Crash-safe: until the rename commits, the old chain is intact;
        after it, replay starts at the new base and ignores leftovers."""
        if self.compactor is None:
            raise RuntimeError("journal has no compactor configured")
        records, _ = self.replay()
        kept = list(self.compactor(records))
        # the fencing epoch (replicate.py) must survive compaction even
        # though domain compactors only know their own record types: a
        # BASE that propagated to every replica is the ONLY copy of the
        # chain left, and losing the epoch frame would let epochs
        # regress after a restart — a stale primary could rejoin
        # un-fenced, or a new reign could reuse a fenced epoch number.
        # Re-emit the highest epoch frame first, where a reign puts it.
        fence = None
        for rec in records:
            if rec.get("t") == "epoch" and (
                fence is None
                or int(rec.get("epoch", 0)) > int(fence.get("epoch", 0))
            ):
                fence = rec
        if fence is not None and not any(
            r.get("t") == "epoch" for r in kept
        ):
            kept.insert(0, fence)
        stale = self._rolled_segments()
        self._f.close()
        self._open_active(
            seq=self._active_seq + 1, prev_crc=0, base=True, initial=kept
        )
        for _, path in stale:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._fsync_dir()
        self.compactions += 1
        if self.sink is not None:
            # history was rewritten under the followers: resync them
            # from the new BASE before the next per-frame order
            self.sink.on_base()
        self.append({
            "t": "note",
            "msg": f"compacted: {len(records)} records -> {len(kept)}",
        })
        return len(kept)

    # ---- read side -------------------------------------------------------

    def _parse_segment(
        self, path: str, newest: bool
    ) -> tuple[dict | None, list[dict], int, int]:
        """One segment -> (header, records, last-valid-line crc, torn
        lines dropped). Only the NEWEST segment may have a torn tail;
        anywhere else a bad line raises JournalCorrupt."""
        lines = _scan_lines(path)
        header: dict | None = None
        records: list[dict] = []
        last_crc = 0
        bad_at: int | None = None
        for n, line in enumerate(lines):
            rec = _unframe(line)
            if rec is None:
                if not newest:
                    raise JournalCorrupt(
                        f"{path}: record at line {n + 1} fails CRC in a "
                        "closed segment — media rot, not a torn append"
                    )
                if bad_at is None:
                    bad_at = n
                continue
            if bad_at is not None:
                raise JournalCorrupt(
                    f"{path}: record at line {bad_at + 1} fails CRC "
                    f"but line {n + 1} is valid — mid-file corruption"
                )
            if n == 0 and rec.get("t") == "seg":
                header = rec
            else:
                records.append(rec)
            last_crc = _line_crc(line)
        dropped = (len(lines) - bad_at) if bad_at is not None else 0
        return header, records, last_crc, dropped

    def replay(self) -> tuple[list[dict], int]:
        """All valid records across the segment chain in append order,
        plus the count of dropped torn-TAIL lines (0 on a clean log).
        Raises JournalCorrupt on mid-file rot, a bad line in a closed
        segment, a sequence gap, or a broken segment CRC chain."""
        segments = self._rolled_segments()
        if os.path.exists(self.path):
            active_seq = self._scan_active()[0] if segments else \
                getattr(self, "_active_seq", 0)
            # trust the on-disk header over cached state: replay() must
            # see what a fresh process would see
            lines = _scan_lines(self.path)
            if lines:
                first = _unframe(lines[0])
                if first is not None and first.get("t") == "seg":
                    active_seq = int(first.get("seq", active_seq))
            segments = segments + [(active_seq, self.path)]
        if not segments:
            return [], 0
        parsed = []  # (seq, path, header, records, last_crc, dropped)
        for seq, path in segments:
            header, records, last_crc, dropped = self._parse_segment(
                path, newest=(path == segments[-1][1])
            )
            if header is not None and int(header.get("seq", seq)) != seq:
                raise JournalCorrupt(
                    f"{path}: segment header seq {header.get('seq')} does "
                    f"not match its position {seq} in the chain"
                )
            parsed.append((seq, path, header, records, last_crc, dropped))
        # replay starts at the newest BASE segment (compaction snapshot);
        # anything older is a crash-window leftover and is ignored
        start = 0
        for i, (_, _, header, _, _, _) in enumerate(parsed):
            if header is not None and header.get("base"):
                start = i
        parsed = parsed[start:]
        # sequence contiguity + CRC chain from the base onward
        for k in range(1, len(parsed)):
            prev_seq, _, _, _, prev_crc, _ = parsed[k - 1]
            seq, path, header, _, _, _ = parsed[k]
            if seq != prev_seq + 1:
                raise JournalCorrupt(
                    f"{self.dir}: journal segment {prev_seq + 1} is "
                    f"missing (found {seq} after {prev_seq})"
                )
            if header is None:
                raise JournalCorrupt(
                    f"{path}: segment {seq} has no header but is not the "
                    "base of the chain"
                )
            if int(header.get("prev", -1)) != prev_crc:
                raise JournalCorrupt(
                    f"{path}: segment {seq} chain CRC mismatch — the "
                    f"preceding segment is not the one it was rolled from"
                )
        records: list[dict] = []
        dropped = 0
        for _, _, _, recs, _, d in parsed:
            records.extend(recs)
            dropped += d
        return records, dropped


def fold_records(records: list[dict]):
    """Fold a replayed record stream into the job table the scheduler
    restarts from: `(jobs, clean_drain)` where `jobs` maps job_id ->
    rebuilt Job (terminal jobs carry their journaled result; non-terminal
    ones are back in PENDING, ready to re-enqueue) and `clean_drain` is
    True when the log ends with a drain marker (graceful shutdown).

    The fold is FIRST-TERMINAL-WINS and duplicate-tolerant — the
    property the pool coordinator's lease-epoch/first-ACK-wins protocol
    (DESIGN.md §17) leans on when it reuses this journal:

    - a duplicate `accept` for a known job_id is ignored (re-accepting
      must not resurrect a job that already reached a terminal state);
    - once a job is terminal, later non-terminal records (a RUNNING
      record from a hedged or re-leased attempt, delivered out of order)
      do not demote it, and later terminal records do not overwrite the
      first result."""
    from .jobs import RUNNING, TERMINAL_STATES, Job

    jobs: dict[str, Job] = {}
    clean_drain = False
    for rec in records:
        t = rec.get("t")
        if t == "accept":
            job = Job.from_accept_record(rec["job"])
            if job.job_id not in jobs:  # duplicate accept: first wins
                jobs[job.job_id] = job
            clean_drain = False
        elif t == "state":
            job = jobs.get(rec["job_id"])
            if job is None:
                continue  # state for a job we never saw accepted
            state = rec["state"]
            if job.state in TERMINAL_STATES:
                # terminal is forever: a late RUNNING (out-of-order
                # redispatch) or a duplicate terminal (second ACK of a
                # hedged pair) never rewrites the first outcome
                clean_drain = False
                continue
            if state in TERMINAL_STATES:
                job.state = state
                job.detail = rec.get("detail") or {}
                job.result = rec.get("result")
                job.finished_t = job.accepted_t  # latency lost across crash
            elif state == RUNNING:
                # mid-flight at crash: back to PENDING for re-admission
                job.state = "PENDING"
            clean_drain = False
        elif t == "drain":
            clean_drain = True
    return jobs, clean_drain


def serve_compactor(records: list[dict]) -> list[dict]:
    """Compaction fold for the SERVE journal: re-emit the minimal record
    list whose `fold_records` equals the original history's — one accept
    per job, one terminal state record per finished job, the drain marker
    when the log ended clean. Idempotent: compacting a compacted journal
    is a no-op fold-wise."""
    from .jobs import TERMINAL_STATES

    jobs, clean = fold_records(records)
    out: list[dict] = []
    for job in jobs.values():
        out.append({"t": "accept", "job": job.accept_record()})
        if job.state in TERMINAL_STATES:
            rec = {"t": "state", "job_id": job.job_id, "state": job.state}
            if job.detail:
                rec["detail"] = job.detail
            if job.result is not None:
                rec["result"] = job.result
            out.append(rec)
    if clean:
        out.append({"t": "drain"})
    return out
