"""Crash-safe job journal — a WAL for the serving daemon (DESIGN.md §14).

One append-only JSON-lines file, `journal.jsonl`, holding every fact the
server must not lose across a `kill -9`: job acceptances and state
transitions. Durability discipline mirrors `checkpoint.atomic_save_npz`
adapted to an append-only log:

- every record is framed `{"c": crc32(payload_json), "r": payload}` so a
  torn or bit-rotted line is detected before it is trusted;
- `append()` writes the line, flushes, and `fsync`s BEFORE returning —
  the server only ACKs a submission after its accept record is durable,
  which is the whole crash-safety invariant: ACKed => journaled =>
  replayed => reaches a terminal state;
- the journal directory is fsynced once at creation so the file's own
  existence survives power loss (same dir-fsync the atomic saver does).

Replay walks the file in order and tolerates a torn TAIL (the one
partial line a crash mid-append can leave): parsing stops at the first
bad record and reports how many trailing lines were dropped. A bad
record can only be the unACKed last append, so nothing acknowledged is
ever lost. Mid-file corruption (bad CRC with valid records after it)
means the medium rotted, not a torn append — that raises
`JournalCorrupt` rather than silently resurrecting half a history.

Record types (`t` field): `accept` (the Job accept_record), `state`
(job_id + new state + detail/result), `drain` (clean shutdown marker),
`note` (operator-visible annotations: schedule reloads, recovery stats).
"""

from __future__ import annotations

import json
import os
import time
import zlib

from ..obs.metrics import Histogram


class JournalCorrupt(ValueError):
    """Mid-file journal corruption: a record failed its CRC while later
    records are intact — media rot, not a torn append. Distinct from the
    tolerated torn tail (see module docstring)."""


def _frame(rec: dict) -> str:
    payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    return json.dumps(
        {"c": zlib.crc32(payload.encode()), "r": rec},
        sort_keys=True,
        separators=(",", ":"),
    )


def _unframe(line: str) -> dict | None:
    """Decode + CRC-verify one journal line; None when unusable."""
    try:
        obj = json.loads(line)
        rec = obj["r"]
        payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        if zlib.crc32(payload.encode()) != int(obj["c"]):
            return None
        return rec
    except (ValueError, KeyError, TypeError):
        return None


class JobJournal:
    """Append-only fsynced record log in `directory/journal.jsonl`."""

    def __init__(self, directory: str):
        self.dir = str(directory)
        self.path = os.path.join(self.dir, "journal.jsonl")
        fresh = not os.path.isdir(self.dir)
        os.makedirs(self.dir, exist_ok=True)
        if fresh:
            dfd = os.open(
                os.path.dirname(os.path.abspath(self.dir)) or ".",
                os.O_RDONLY,
            )
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._f = open(self.path, "a", encoding="utf-8")
        self.appended = 0
        # always-on fsync latency histogram (Prometheus `metrics` verb);
        # obs is an optional Recorder that additionally puts each fsync
        # on the flight-recorder timeline
        self.fsync_hist = Histogram()
        self.obs = None

    # ---- write side ------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Durably append one record: write + flush + fsync. The caller
        may ACK the fact the record carries only AFTER this returns."""
        t0 = time.perf_counter()
        self._f.write(_frame(rec) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        dt = time.perf_counter() - t0
        self.appended += 1
        self.fsync_hist.observe(dt)
        if self.obs is not None:
            self.obs.fsync_event(dt)

    def accept(self, job) -> None:
        self.append({"t": "accept", "job": job.accept_record()})

    def state(self, job_id: str, state: str, detail: dict | None = None,
              result: dict | None = None) -> None:
        rec = {"t": "state", "job_id": job_id, "state": state}
        if detail:
            rec["detail"] = detail
        if result is not None:
            rec["result"] = result
        self.append(rec)

    def note(self, msg: str) -> None:
        self.append({"t": "note", "msg": str(msg)})

    def drain(self) -> None:
        self.append({"t": "drain"})

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    # ---- read side -------------------------------------------------------

    def replay(self) -> tuple[list[dict], int]:
        """All valid records in append order, plus the count of dropped
        torn-TAIL lines (0 on a clean log). Raises JournalCorrupt when a
        bad record is followed by valid ones (mid-file rot)."""
        if not os.path.exists(self.path):
            return [], 0
        with open(self.path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        records: list[dict] = []
        bad_at: int | None = None
        for n, line in enumerate(lines):
            if not line.strip():
                continue
            rec = _unframe(line)
            if rec is None:
                if bad_at is None:
                    bad_at = n
                continue
            if bad_at is not None:
                raise JournalCorrupt(
                    f"{self.path}: record at line {bad_at + 1} fails CRC "
                    f"but line {n + 1} is valid — mid-file corruption"
                )
            records.append(rec)
        dropped = (len(lines) - bad_at) if bad_at is not None else 0
        return records, dropped


def fold_records(records: list[dict]):
    """Fold a replayed record stream into the job table the scheduler
    restarts from: `(jobs, clean_drain)` where `jobs` maps job_id ->
    rebuilt Job (terminal jobs carry their journaled result; non-terminal
    ones are back in PENDING, ready to re-enqueue) and `clean_drain` is
    True when the log ends with a drain marker (graceful shutdown).

    The fold is FIRST-TERMINAL-WINS and duplicate-tolerant — the
    property the pool coordinator's lease-epoch/first-ACK-wins protocol
    (DESIGN.md §17) leans on when it reuses this journal:

    - a duplicate `accept` for a known job_id is ignored (re-accepting
      must not resurrect a job that already reached a terminal state);
    - once a job is terminal, later non-terminal records (a RUNNING
      record from a hedged or re-leased attempt, delivered out of order)
      do not demote it, and later terminal records do not overwrite the
      first result."""
    from .jobs import RUNNING, TERMINAL_STATES, Job

    jobs: dict[str, Job] = {}
    clean_drain = False
    for rec in records:
        t = rec.get("t")
        if t == "accept":
            job = Job.from_accept_record(rec["job"])
            if job.job_id not in jobs:  # duplicate accept: first wins
                jobs[job.job_id] = job
            clean_drain = False
        elif t == "state":
            job = jobs.get(rec["job_id"])
            if job is None:
                continue  # state for a job we never saw accepted
            state = rec["state"]
            if job.state in TERMINAL_STATES:
                # terminal is forever: a late RUNNING (out-of-order
                # redispatch) or a duplicate terminal (second ACK of a
                # hedged pair) never rewrites the first outcome
                clean_drain = False
                continue
            if state in TERMINAL_STATES:
                job.state = state
                job.detail = rec.get("detail") or {}
                job.result = rec.get("result")
                job.finished_t = job.accepted_t  # latency lost across crash
            elif state == RUNNING:
                # mid-flight at crash: back to PENDING for re-admission
                job.state = "PENDING"
            clean_drain = False
        elif t == "drain":
            clean_drain = True
    return jobs, clean_drain
