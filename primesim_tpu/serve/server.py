"""`primetpu serve` — the daemon around the scheduler (DESIGN.md §14).

Threading model: listener threads (socketserver.ThreadingMixIn over a
unix stream socket) PARSE requests and enqueue closures onto the
scheduler inbox; the main thread runs the serve loop (tick + inbox
drain) and owns every mutable structure, so the scheduler stays
single-threaded and signal handling stays on the main thread. Replies
that need scheduler state are fulfilled via per-request Events.

Signals:
    SIGTERM/SIGINT  graceful drain — stop admissions, checkpoint every
                    in-flight job, journal the drain marker, exit 75
                    (EX_TEMPFAIL, same "rerun to continue" contract as
                    the supervisor's Preempted path) when work remains,
                    0 when the queue finished.
    SIGHUP          reload the config file (fault schedules etc.); the
                    reloaded config must normalize to the SAME geometry
                    key — traced knobs may change, compiled shapes may
                    not. Applies to subsequently admitted jobs.

Restart: `PrimeServer(...)` replays the journal before listening. Every
ACKed job is either terminal (kept for STATUS/RESULT) or re-enqueued,
resuming from its newest per-job element checkpoint when one exists —
`kill -9` at ANY instant loses no accepted job.
"""

from __future__ import annotations

import os
import queue
import signal
import socketserver
import threading
import time

from . import jobs as J
from .journal import JobJournal, fold_records, serve_compactor
from .protocol import (
    encode,
    error_obj,
    make_listener,
    parse_target,
    read_line,
)
from ..util.diskpressure import DiskPressureError
from .quota import QuotaExceeded, TenantQuota
from .replicate import PrimaryFenced, ReplicaQuorumLost
from .scheduler import DEFAULT_BUCKETS, QueueFull, Scheduler

EX_TEMPFAIL = 75  # drained with work remaining; restart to continue


class _Request:
    """One parsed client request awaiting the main loop: `fn` runs ON the
    scheduler thread and returns the reply dict."""

    def __init__(self, fn):
        self.fn = fn
        self.reply: dict | None = None
        self.done = threading.Event()


class PrimeServer:
    def __init__(
        self,
        cfg,
        state_dir: str,
        socket_path: str | None = None,
        buckets=DEFAULT_BUCKETS,
        chunk_steps: int = 128,
        max_queue: int = 64,
        checkpoint_every_s: float = 2.0,
        config_path: str | None = None,
        idle_exit_s: float | None = None,
        obs=None,
        warm_cache: bool = False,
        pool_dir: str | None = None,
        max_workers: int = 2,
        lease_ttl_s: float = 10.0,
        quota: TenantQuota | None = None,
        spawn_pool: bool = True,
        replicas: list[str] | tuple[str, ...] | None = None,
        quorum: int | None = None,
        quorum_policy: str = "block",
        node: str | None = None,
        devices: int = 0,
        attest: str = "off",
        audit_rate: float = 0.0,
    ):
        self.state_dir = str(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.socket_path = socket_path or os.path.join(
            self.state_dir, "serve.sock"
        )
        self.config_path = config_path
        self.idle_exit_s = idle_exit_s
        self.obs = obs
        self.quota = quota
        self.journal = JobJournal(self.state_dir, compactor=serve_compactor)
        self.journal.obs = obs
        self.repl = None
        if replicas:
            # replicated journal + fencing (DESIGN.md §21): attach the
            # sink BEFORE recovery so the epoch frame that opens this
            # reign is both the first record of the reign and the first
            # frame the followers see from us
            from .replicate import ReplicationSink

            self.repl = ReplicationSink(
                self.journal, list(replicas), quorum=quorum,
                policy=quorum_policy, obs=obs,
                node=node or f"serve-{os.getpid()}",
            )
            self.journal.sink = self.repl
            self.repl.begin_epoch()
        if pool_dir:
            # dispatch mode: jobs run on an autoscaling worker fleet via
            # a (spawned or adopted) pool coordinator — DESIGN.md §18
            from .dispatch import DispatchScheduler

            self.sched = DispatchScheduler(
                cfg,
                self.journal,
                self.state_dir,
                pool_dir,
                buckets=buckets,
                chunk_steps=chunk_steps,
                max_queue=max_queue,
                max_workers=max_workers,
                lease_ttl_s=lease_ttl_s,
                obs=obs,
                spawn=spawn_pool,
                devices=devices,
                attest=attest,
                audit_rate=audit_rate,
            )
        else:
            if devices:
                # caller contract, not a user-reachable path: cmd_serve
                # rejects --devices without --pool-dir before constructing
                # ptlint: allow=PT-TYPED-ERR
                raise ValueError(
                    "serve --devices needs dispatch mode (--pool-dir): "
                    "sharded fleets live on pool workers, not in the "
                    "front-end process"
                )
            self.sched = Scheduler(
                cfg,
                self.journal,
                self.state_dir,
                buckets=buckets,
                chunk_steps=chunk_steps,
                max_queue=max_queue,
                checkpoint_every_s=checkpoint_every_s,
                obs=obs,
                warm_cache=warm_cache,
                attest=attest,
            )
        self.inbox: "queue.Queue[_Request]" = queue.Queue()
        self._draining = False
        self._stop = False
        self.recovered = self._recover()
        self._srv = None

    # ---- crash recovery --------------------------------------------------

    def _recover(self) -> dict:
        """Replay the journal into the scheduler's job table. Terminal
        jobs are adopted for queries; non-terminal ones re-enqueue (with
        checkpoint resume). Returns recovery stats for healthz/logs."""
        records, dropped = self.journal.replay()
        jobs, clean = fold_records(records)
        requeued = 0
        for job in jobs.values():
            if job.terminal:
                self.sched.adopt_terminal(job)
            else:
                self.sched.requeue_recovered(job)
                requeued += 1
        if jobs:
            self.sched._seq = max(
                (int(j.job_id[1:]) for j in jobs.values()
                 if j.job_id.startswith("j") and j.job_id[1:].isdigit()),
                default=0,
            )
        stats = {
            "journal_records": len(records),
            "torn_tail_dropped": dropped,
            "jobs_replayed": len(jobs),
            "jobs_requeued": requeued,
            "clean_drain": clean,
        }
        if records:
            self.journal.note(f"recovered: {stats}")
        return stats

    # ---- request handlers (run on the scheduler thread) ------------------

    def _handle(self, req: dict) -> dict:
        verb = req.get("verb")
        try:
            if verb == "submit":
                return self._h_submit(req)
            if verb == "status":
                return self._h_status(req)
            if verb == "result":
                return self._h_result(req)
            if verb == "cancel":
                job = self.sched.cancel(str(req["job_id"]))
                return {"ok": True, "job": job.public()}
            if verb == "health":
                return self._h_health()
            if verb == "metrics":
                return self._h_metrics()
            if verb == "drain":
                self._draining = True
                return {"ok": True, "draining": True}
            raise ValueError(f"unknown verb {verb!r}")
        except (QueueFull, QuotaExceeded, ReplicaQuorumLost,
                DiskPressureError) as e:
            out = {"ok": False, "retry_after_s": round(e.retry_after_s, 1)}
            out.update(error_obj(e))
            return out
        except PrimaryFenced as e:
            # a standby promoted past us: refuse, and let the serve
            # loop turn the fence into exit 75 on its next pass
            out = {"ok": False, "fenced": True}
            out.update(error_obj(e))
            return out
        except Exception as e:  # noqa: BLE001 — protocol boundary
            out = {"ok": False}
            out.update(error_obj(e))
            return out

    def _h_submit(self, req: dict) -> dict:
        if self._draining:
            out = {"ok": False, "retry_after_s": 5.0}
            out.update(error_obj(RuntimeError("server is draining")))
            return out
        if self.repl is not None:
            # quorum gate BEFORE a job id exists: under `block`, a
            # below-quorum primary refuses admission (typed
            # backpressure); a fenced one refuses, period
            self.repl.check_admission()
        idem = req.get("idem")
        if idem:
            # idempotent resubmit: a client retrying after a lost ACK
            # (or a duplicated frame) presents the same token; answer
            # with the already-accepted job. Tokens ride the accept
            # record, so the dedup also holds across a server restart.
            for j in self.sched.jobs.values():
                if j.idem == str(idem) \
                        and j.client == str(req.get("client", "anon")):
                    return {"ok": True, "job": j.public(),
                            "duplicate": True}
        if self.quota is not None:
            # admission quota spends a token BEFORE a job id exists, so
            # rejected submits leave no trace in the journal or job table
            self.quota.admit(str(req.get("client", "anon")))
        job = J.Job(
            job_id=self.sched.next_job_id(),
            idem=str(idem) if idem else None,
            client=str(req.get("client", "anon")),
            trace_path=req.get("trace_path"),
            synth=req.get("synth"),
            overrides=dict(req.get("overrides") or {}),
            fold=bool(req.get("fold", True)),
            deadline_s=(
                float(req["deadline_s"])
                if req.get("deadline_s") is not None else None
            ),
            max_steps=int(req.get("max_steps", 10_000_000)),
            priority=int(req.get("priority", 0)),
        )
        self.sched.submit(job)  # fsyncs the accept record before returning
        if self.repl is not None and not self.repl.quorum_ok() \
                and self.repl.policy == "block":
            # the accept record is on OUR disk but missed quorum: do
            # not ACK a frame a host-loss failover would forget. The
            # job stays admitted locally; the client's idempotent retry
            # dedups to it once quorum is back (and if we die first,
            # "never ACKed" and "not on the replicas" agree).
            raise ReplicaQuorumLost(
                f"accept record for {job.job_id} missed the replication "
                f"quorum of {self.repl.quorum}; retry with the same "
                "idempotency token", self.repl.retry_after_s,
            )
        return {"ok": True, "job": job.public()}

    def _h_status(self, req: dict) -> dict:
        job_id = req.get("job_id")
        if job_id:
            job = self.sched.jobs.get(str(job_id))
            if job is None:
                raise KeyError(f"unknown job {job_id!r}")
            return {"ok": True, "job": job.public()}
        return {
            "ok": True,
            "jobs": [
                j.public() for j in self.sched.jobs.values()
            ],
        }

    def _h_result(self, req: dict) -> dict:
        job = self.sched.jobs.get(str(req["job_id"]))
        if job is None:
            raise KeyError(f"unknown job {req['job_id']!r}")
        if not job.terminal:
            return {"ok": True, "pending": True, "job": job.public()}
        return {"ok": True, "job": job.public()}

    def _h_health(self) -> dict:
        out = {"ok": True, "draining": self._draining}
        out.update(self.sched.stats())
        out["recovered"] = self.recovered
        if self.quota is not None:
            out["quota"] = {"rate": self.quota.rate,
                            "burst": self.quota.burst,
                            "rejections": self.quota.rejections}
        out["journal"] = {
            "appends": self.journal.appended,
            "fsync_count": self.journal.fsync_hist.count,
            "fsync_total_s": round(self.journal.fsync_hist.sum, 6),
        }
        if self.repl is not None:
            out["replication"] = self.repl.status()
        return out

    def _h_metrics(self) -> dict:
        """Prometheus text exposition of the live scheduler/journal
        state — scrape with `primetpu serve-status --metrics` or any
        client speaking the line protocol."""
        from ..obs.prom import render_prometheus

        text = render_prometheus(
            self.sched, journal=self.journal,
            draining=self._draining, recovered=self.recovered,
            quota=self.quota, repl=self.repl,
        )
        return {"ok": True, "content_type":
                "text/plain; version=0.0.4", "text": text}

    # ---- signals ---------------------------------------------------------

    def _install_signals(self) -> None:
        def _drain(signum, frame):
            self._draining = True
            self._stop = True

        def _reload(signum, frame):
            # flag only — the reload itself runs on the scheduler thread
            self._reload_requested = True

        self._reload_requested = False
        try:
            signal.signal(signal.SIGTERM, _drain)
            signal.signal(signal.SIGINT, _drain)
            if hasattr(signal, "SIGHUP"):
                signal.signal(signal.SIGHUP, _reload)
        except ValueError:
            # not the main thread (in-process tests drive the loop from a
            # worker thread); signal-driven drain simply isn't armed
            pass

    def reload_config(self) -> None:
        """SIGHUP: re-read the config file; traced knobs (fault schedules,
        seeds, rates) may change freely, the geometry key may not —
        admission would need a recompile, which serving forbids."""
        if not self.config_path:
            self.journal.note("SIGHUP ignored: no --config file to reload")
            return
        from ..cli import _load_config

        try:
            new_cfg = _load_config(self.config_path)
        except Exception as e:  # noqa: BLE001 — keep serving on bad reload
            self.journal.note(
                f"SIGHUP reload failed ({type(e).__name__}: {e}); "
                "keeping previous config"
            )
            return
        old_key = self.sched.cfg.timing_normalized()
        if new_cfg.timing_normalized() != old_key:
            self.journal.note(
                "SIGHUP reload REJECTED: new config changes the compiled "
                "geometry; restart the server instead"
            )
            return
        self.sched.cfg = new_cfg
        for b in self.sched.buckets:
            b.cfg = new_cfg
        self.journal.note(f"SIGHUP: reloaded config from {self.config_path}")

    # ---- listener --------------------------------------------------------

    def _make_listener(self):
        server = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = read_line(self.rfile)
                    except ValueError as e:
                        self.wfile.write(
                            encode({"ok": False, **error_obj(e)})
                        )
                        return
                    if req is None:
                        return
                    if req.get("verb") == "wait":
                        reply = server._wait_reply(req)
                    else:
                        r = _Request(lambda req=req: server._handle(req))
                        server.inbox.put(r)
                        r.done.wait(timeout=600.0)
                        reply = r.reply or {
                            "ok": False,
                            **error_obj(TimeoutError("server busy")),
                        }
                    try:
                        self.wfile.write(encode(reply))
                        self.wfile.flush()
                    except (BrokenPipeError, ValueError):
                        return

        listener, fam = make_listener(self.socket_path, Handler)
        if fam == "tcp":
            # --tcp HOST:0 binds an ephemeral port; expose the real one
            host, port = listener.server_address[:2]
            self.socket_path = f"{host}:{port}"
        return listener

    def bind(self) -> str:
        """Bind the listener now (idempotent) and return the resolved
        target — the CLI prints its readiness line from this, so a
        `--tcp HOST:0` caller learns the kernel-assigned port."""
        if self._srv is None:
            self._srv = self._make_listener()
        return self.socket_path

    def _wait_reply(self, req: dict) -> dict:
        """`wait` blocks the LISTENER thread (never the scheduler) by
        polling job state through cheap status requests."""
        deadline = time.time() + float(req.get("timeout_s", 300.0))
        job_id = str(req.get("job_id", ""))
        while True:
            r = _Request(
                lambda: self._handle({"verb": "status", "job_id": job_id})
            )
            self.inbox.put(r)
            r.done.wait(timeout=600.0)
            reply = r.reply or {}
            job = (reply or {}).get("job")
            if not reply.get("ok", False):
                return reply
            if job and job["state"] in J.TERMINAL_STATES:
                return reply
            if time.time() >= deadline:
                return {
                    "ok": False,
                    **error_obj(TimeoutError(
                        f"{job_id} not terminal within wait timeout"
                    )),
                }
            time.sleep(0.05)

    # ---- main loop -------------------------------------------------------

    def _drain_inbox(self) -> None:
        while True:
            try:
                r = self.inbox.get_nowait()
            except queue.Empty:
                return
            try:
                r.reply = r.fn()
            except Exception as e:  # noqa: BLE001 — never kill the loop
                r.reply = {"ok": False, **error_obj(e)}
            finally:
                r.done.set()

    def serve_forever(self) -> int:
        """Run until drained (SIGTERM/SIGINT/drain verb) or, with
        idle_exit_s, until the queue has been empty that long. Returns
        the process exit code (0 all work finished, EX_TEMPFAIL=75 when
        unfinished jobs were checkpointed for the next server)."""
        self._install_signals()
        self.bind()
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        idle_since = time.time()
        fenced = False
        last_hb = 0.0
        try:
            while not self._stop:
                if self._reload_requested:
                    self._reload_requested = False
                    self.reload_config()
                if self.repl is not None:
                    now = time.time()
                    if now - last_hb >= 0.25:
                        last_hb = now
                        self.repl.heartbeat()
                    if self.repl.fenced:
                        # a higher epoch ACKed: self-fence. Stop ACKing
                        # NOW and leave with the supervisor contract's
                        # "rerun to continue" code — except rerunning
                        # this node rejoins as a follower, not a primary
                        fenced = True
                        self.journal.note(
                            "fenced by epoch "
                            f"{getattr(self.repl, 'fenced_by', 0)}; "
                            "self-deposing"
                        )
                        break
                self._drain_inbox()
                worked = self.sched.tick()
                busy = worked or self.sched.pending_work()
                if busy:
                    idle_since = time.time()
                elif self._draining:
                    break  # drain verb: queue ran dry, clean exit
                elif (
                    self.idle_exit_s is not None
                    and time.time() - idle_since >= self.idle_exit_s
                ):
                    break
                if not worked:
                    time.sleep(0.01)
        finally:
            self._srv.shutdown()
            self._srv.server_close()
            if parse_target(self.socket_path)[0] == "unix":
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass
        unfinished = self.sched.drain()
        if hasattr(self.sched, "shutdown_children"):
            self.sched.shutdown_children()
        self._drain_inbox()  # flush replies so clients aren't left hanging
        if self.repl is not None:
            self.repl.close()
        self.journal.close()
        # a fenced primary always exits 75: its remaining work belongs
        # to the new primary's reign, never to a local rerun as primary
        return EX_TEMPFAIL if (unfinished or fenced) else 0
