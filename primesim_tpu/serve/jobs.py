"""Job records and the slot-lifecycle state machine (DESIGN.md §14).

A job is one (trace, config-override, deadline, priority) simulation
request. Its lifecycle:

    PENDING ──admit──> RUNNING ──finish──> DONE
       │                  │
       │                  ├─ wall deadline ──> TIMEOUT
       │                  ├─ step budget / poisoned ──> QUARANTINED
       │                  ├─ retryable failure ──(re-enqueue)──> PENDING
       │                  └─ exhausted retries ──> FAILED
       ├─ wall deadline ──> TIMEOUT
       ├─ unloadable/invalid workload ──> QUARANTINED
       └─ client cancel ──> CANCELLED   (also from RUNNING)

Terminal states are sticky; every transition is journaled
(serve/journal.py) so a `kill -9` at any instant loses no accepted job.
The workload is stored as a SPEC (trace path or synth spec), not as
event bytes: specs are deterministic to re-materialize, which is what
makes journal replay bit-exact.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field

# non-terminal
PENDING = "PENDING"
RUNNING = "RUNNING"
# terminal
DONE = "DONE"
FAILED = "FAILED"
TIMEOUT = "TIMEOUT"
QUARANTINED = "QUARANTINED"
CANCELLED = "CANCELLED"

TERMINAL_STATES = (DONE, FAILED, TIMEOUT, QUARANTINED, CANCELLED)
STATES = (PENDING, RUNNING) + TERMINAL_STATES

_LEGAL = {
    PENDING: {RUNNING, TIMEOUT, QUARANTINED, CANCELLED},
    RUNNING: {PENDING, DONE, FAILED, TIMEOUT, QUARANTINED, CANCELLED},
}


@dataclass
class Job:
    """One accepted simulation request. `trace_path`/`synth` is the
    workload spec (exactly one set); `overrides` are fleet timing-knob
    overrides (sim.fleet.KNOB_KEYS); `deadline_s` is a WALL-clock budget
    measured from acceptance (None = none); `max_steps` the step budget."""

    job_id: str
    client: str = "anon"
    trace_path: str | None = None
    synth: str | None = None
    overrides: dict = field(default_factory=dict)
    fold: bool = True
    deadline_s: float | None = None
    max_steps: int = 10_000_000
    priority: int = 0
    accepted_t: float = field(default_factory=time.time)
    # client-supplied idempotency token: a retried submit after a lost
    # ACK presents the same token and is answered with THIS job instead
    # of double-enqueueing (journaled, so dedup survives restart)
    idem: str | None = None
    # mutable progress (not part of the accept record)
    state: str = PENDING
    detail: dict = field(default_factory=dict)
    result: dict | None = None
    attempts: int = 0
    finished_t: float | None = None
    # host-only (never journaled): materialized workload + supervision
    _trace: object = None
    _elem_cfg: object = None
    _ctx: object = None
    _resume_from: str | None = None
    # v2 paged allocator: the truncated WINDOW trace currently spliced
    # into a small bucket (None = the full trace is resident), plus a
    # cached has-sync flag (sync events pin a trace to full residency)
    _window: object = None
    _has_sync: bool | None = None

    # ---- state machine ---------------------------------------------------

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def transition(self, new: str, detail: dict | None = None) -> None:
        if new not in STATES:
            raise ValueError(f"unknown job state {new!r}")
        if self.terminal or new not in _LEGAL[self.state]:
            raise ValueError(
                f"illegal job transition {self.state} -> {new} ({self.job_id})"
            )
        self.state = new
        if detail:
            self.detail = dict(detail)
        if new in TERMINAL_STATES:
            self.finished_t = time.time()

    def deadline_expired(self, now: float | None = None) -> bool:
        if self.deadline_s is None:
            return False
        return (now if now is not None else time.time()) \
            >= self.accepted_t + self.deadline_s

    @property
    def latency_s(self) -> float | None:
        """Accept-to-terminal wall latency (None while in flight)."""
        if self.finished_t is None:
            return None
        return self.finished_t - self.accepted_t

    # ---- journal (de)serialization --------------------------------------

    def accept_record(self) -> dict:
        """The immutable acceptance facts — everything needed to re-run
        the job from scratch after a crash."""
        return {
            "job_id": self.job_id,
            "client": self.client,
            "trace_path": self.trace_path,
            "synth": self.synth,
            "overrides": dict(self.overrides),
            "fold": self.fold,
            "deadline_s": self.deadline_s,
            "max_steps": self.max_steps,
            "priority": self.priority,
            "accepted_t": self.accepted_t,
            "idem": self.idem,
        }

    @classmethod
    def from_accept_record(cls, rec: dict) -> "Job":
        keys = {f.name for f in dataclasses.fields(cls)
                if not f.name.startswith("_")}
        return cls(**{k: v for k, v in rec.items() if k in keys})

    def public(self) -> dict:
        """The client-visible job view (STATUS replies, health detail)."""
        out = {
            "job_id": self.job_id,
            "client": self.client,
            "state": self.state,
            "priority": self.priority,
            "attempts": self.attempts,
            "accepted_t": self.accepted_t,
        }
        if self.detail:
            out["detail"] = self.detail
        if self.latency_s is not None:
            out["latency_s"] = round(self.latency_s, 3)
        if self.result is not None:
            out["result"] = self.result
        return out
