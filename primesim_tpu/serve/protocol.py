"""Wire protocol for `primetpu serve` — JSON lines over a unix socket.

Each request and each reply is one JSON object on one line (UTF-8,
newline-terminated). Requests carry a `verb`; replies carry `ok: bool`
plus verb-specific fields, or `ok: false` with a structured `error`
object (same shape the CLI emits for run/sweep failures):

    {"error": {"type": "TraceError", "location": {...}, "detail": "..."}}

Verbs:
    submit  {trace_path|synth, overrides?, fold?, deadline_s?,
             max_steps?, priority?, client?}       -> {job_id} | RETRY_AFTER
    status  {job_id?}                              -> {job}|{jobs}
    result  {job_id}                               -> {job} (terminal only)
    wait    {job_id, timeout_s?}                   -> {job} once terminal
    cancel  {job_id}                               -> {job}
    health  {}                                     -> service stats + journal
                                                      recovery/fsync info
    metrics {}                                     -> {text} Prometheus
                                                      text exposition
    drain   {}                                     -> ack; server checkpoints
                                                      in-flight work and exits

Backpressure: a submit against a full queue gets
`{"ok": false, "retry_after_s": <float>, "error": {...}}` — the client
is expected to back off, not spin.
"""

from __future__ import annotations

import json
import socket

MAX_LINE = 1 << 20  # 1 MiB per message — traces travel by path, not value


def error_obj(exc: BaseException) -> dict:
    """Structured error payload for an exception: stable `type`, the
    exception's own `location()` dict when it has one (TraceError,
    FaultConfigError carry source coordinates), and the message."""
    loc = {}
    locate = getattr(exc, "location", None)
    if callable(locate):
        try:
            loc = dict(locate())
        except Exception:
            loc = {}
    return {
        "error": {
            "type": type(exc).__name__,
            "location": loc,
            "detail": str(exc),
        }
    }


def encode(obj: dict) -> bytes:
    line = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    data = line.encode() + b"\n"
    if len(data) > MAX_LINE:
        raise ValueError(f"message of {len(data)} bytes exceeds {MAX_LINE}")
    return data


def decode(line: bytes | str) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError("protocol message must be a JSON object")
    return obj


def read_line(f) -> dict | None:
    """Read one framed message from a file-like socket reader; None on
    EOF (peer closed)."""
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol message")
    return decode(line)


def socket_alive(sock_path: str, timeout_s: float = 0.5) -> bool:
    """True when something ACCEPTS connections on `sock_path`. False for
    a missing path or a STALE socket file — the inode a SIGKILLed daemon
    leaves behind, which refuses connections because no process listens.
    A connect that times out counts as alive (a bound-but-busy peer)."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.settimeout(timeout_s)
        s.connect(sock_path)
        return True
    except socket.timeout:
        return True  # bound and backlogged — definitely not stale
    except OSError:
        return False  # ENOENT / ECONNREFUSED: absent or dead
    finally:
        s.close()


def claim_socket_path(sock_path: str) -> None:
    """Make `sock_path` bindable: probe an existing socket file and
    unlink it ONLY when dead (previous owner was SIGKILLed and never got
    to clean up). A live listener raises — silently stealing a running
    daemon's socket would orphan it mid-service."""
    import os

    if not os.path.exists(sock_path):
        return
    if socket_alive(sock_path):
        raise RuntimeError(
            f"{sock_path}: a live server already accepts connections "
            "here; refusing to steal its socket (stop it first, or pick "
            "another --socket path)"
        )
    os.unlink(sock_path)  # stale: previous owner died without cleanup


def request(sock_path: str, req: dict, timeout_s: float = 30.0) -> dict:
    """One request/reply round trip against the server socket."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout_s)
        s.connect(sock_path)
        s.sendall(encode(req))
        f = s.makefile("rb")
        reply = read_line(f)
    if reply is None:
        raise ConnectionError(f"server at {sock_path} closed without reply")
    return reply
