"""Wire protocol for `primetpu serve` — JSON lines over a unix socket
or a TCP listener (DESIGN.md §18: the elastic front-end admits many
concurrent clients over `--tcp HOST:PORT`; the unix socket stays for
single-host compat).

Each request and each reply is one JSON object on one line (UTF-8,
newline-terminated). Requests carry a `verb`; replies carry `ok: bool`
plus verb-specific fields, or `ok: false` with a structured `error`
object (same shape the CLI emits for run/sweep failures):

    {"error": {"type": "TraceError", "location": {...}, "detail": "..."}}

Verbs:
    submit  {trace_path|synth, overrides?, fold?, deadline_s?,
             max_steps?, priority?, client?}       -> {job_id} | RETRY_AFTER
    status  {job_id?}                              -> {job}|{jobs}
    result  {job_id}                               -> {job} (terminal only)
    wait    {job_id, timeout_s?}                   -> {job} once terminal
    cancel  {job_id}                               -> {job}
    health  {}                                     -> service stats + journal
                                                      recovery/fsync info
    metrics {}                                     -> {text} Prometheus
                                                      text exposition
    drain   {}                                     -> ack; server checkpoints
                                                      in-flight work and exits

Backpressure: a submit against a full queue gets
`{"ok": false, "retry_after_s": <float>, "error": {...}}` — the client
is expected to back off, not spin. A below-quorum replicated primary
(DESIGN.md §21) answers the same shape with a `ReplicaQuorumLost`
error; a fenced one adds `"fenced": true`.

The journal-replication verbs (`repl.hello/append/roll/seg/reset/
fetch/status` — serve/replicate.py) ride this same framing over a
PERSISTENT connection: the primary's sink holds one socket per replica
and exchanges one order/ack line pair per journal mutation, instead of
`request()`'s connect-per-call.
"""

from __future__ import annotations

import json
import socket

from ..chaos import sites as chaos

MAX_LINE = 1 << 20  # 1 MiB per message — traces travel by path, not value


class ServeUnavailable(ConnectionError):
    """Connect-phase failure: nothing was sent, so the caller may retry
    the SAME request without double-submitting. Post-send failures stay
    plain ConnectionError — retrying those could duplicate a submit."""


def parse_target(target) -> tuple[str, object]:
    """Classify a service target string: `("tcp", (host, port))` for
    `host:port` / `[v6::addr]:port`, else `("unix", path)`. A path can
    contain a colon only alongside a slash, so `./sock:dir/s` stays a
    path while `localhost:7077` is TCP."""
    t = str(target)
    if ":" in t and "/" not in t:
        host, _, port = t.rpartition(":")
        if host and port.isdigit():
            return "tcp", (host.strip("[]"), int(port))
    return "unix", t


def format_target(target) -> str:
    """Canonical display string for either target family."""
    fam, addr = parse_target(target)
    return f"{addr[0]}:{addr[1]}" if fam == "tcp" else str(addr)


def _connect(target, timeout_s: float):
    """Open a connected socket to a unix-path or host:port target.
    Raises ServeUnavailable on ANY connect-phase failure."""
    fam, addr = parse_target(target)
    if fam == "tcp":
        s = socket.socket(socket.AF_INET6 if ":" in addr[0]
                          else socket.AF_INET, socket.SOCK_STREAM)
    else:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout_s)
    try:
        s.connect(addr if fam == "tcp" else str(addr))
    except OSError as e:
        s.close()
        raise ServeUnavailable(
            f"cannot connect to {format_target(target)}: {e}"
        ) from e
    return s


def error_obj(exc: BaseException) -> dict:
    """Structured error payload for an exception: stable `type`, the
    exception's own `location()` dict when it has one (TraceError,
    FaultConfigError carry source coordinates), and the message."""
    loc = {}
    locate = getattr(exc, "location", None)
    if callable(locate):
        try:
            loc = dict(locate())
        except Exception:
            loc = {}
    return {
        "error": {
            "type": type(exc).__name__,
            "location": loc,
            "detail": str(exc),
        }
    }


def encode(obj: dict) -> bytes:
    line = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    data = line.encode() + b"\n"
    if len(data) > MAX_LINE:
        raise ValueError(f"message of {len(data)} bytes exceeds {MAX_LINE}")
    return data


def decode(line: bytes | str) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError("protocol message must be a JSON object")
    return obj


def read_line(f) -> dict | None:
    """Read one framed message from a file-like socket reader; None on
    EOF (peer closed). A partial line at EOF — the peer died mid-frame —
    is a TORN FRAME, rejected as such rather than handed to the JSON
    decoder: a truncated frame that happened to parse would silently
    become a different message."""
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol message")
    nl = b"\n" if isinstance(line, bytes) else "\n"
    if not line.endswith(nl):
        raise ValueError("torn protocol frame (peer closed mid-message)")
    return decode(line)


def socket_alive(target, timeout_s: float = 0.5) -> bool:
    """True when something ACCEPTS connections on `target` (unix path or
    host:port). False for a missing path or a STALE socket file — the
    inode a SIGKILLed daemon leaves behind, which refuses connections
    because no process listens. A connect that times out counts as alive
    (a bound-but-busy peer)."""
    try:
        _connect(target, timeout_s).close()
        return True
    except ServeUnavailable as e:
        if isinstance(e.__cause__, socket.timeout):
            return True  # bound and backlogged — definitely not stale
        return False  # ENOENT / ECONNREFUSED: absent or dead


def claim_socket_path(sock_path: str) -> None:
    """Make `sock_path` bindable: probe an existing socket file and
    unlink it ONLY when dead (previous owner was SIGKILLed and never got
    to clean up). A live listener raises — silently stealing a running
    daemon's socket would orphan it mid-service."""
    import os

    if not os.path.exists(sock_path):
        return
    if socket_alive(sock_path):
        raise RuntimeError(
            f"{sock_path}: a live server already accepts connections "
            "here; refusing to steal its socket (stop it first, or pick "
            "another --socket path)"
        )
    os.unlink(sock_path)  # stale: previous owner died without cleanup


def request(target, req: dict, timeout_s: float = 30.0,
            connect_timeout_s: float | None = None) -> dict:
    """One request/reply round trip against the server (unix path or
    host:port). `connect_timeout_s` bounds the connect phase separately
    (defaults to `timeout_s`); a connect failure raises ServeUnavailable
    (retry-safe), a post-send failure plain ConnectionError (not)."""
    s = _connect(target, connect_timeout_s
                 if connect_timeout_s is not None else timeout_s)
    try:
        s.settimeout(timeout_s)
        payload = encode(req)
        if not chaos.socket_send("protocol.send", s, payload):
            s.sendall(payload)
        f = s.makefile("rb")
        chaos.socket_recv("protocol.recv", s)
        reply = read_line(f)
    finally:
        s.close()
    if reply is None:
        raise ConnectionError(
            f"server at {format_target(target)} closed without reply"
        )
    return reply


def make_listener(target, handler_cls):
    """A threaded line-protocol listener on either family: a
    `ThreadingTCPServer` (SO_REUSEADDR; port 0 = kernel-assigned, read
    the real one from `.server_address`) or a `ThreadingUnixStreamServer`
    after `claim_socket_path`. The caller owns serve_forever/shutdown."""
    import socketserver

    fam, addr = parse_target(target)
    if fam == "tcp":
        class Listener(socketserver.ThreadingMixIn, socketserver.TCPServer):
            daemon_threads = True
            allow_reuse_address = True

        return Listener(addr, handler_cls), "tcp"

    class Listener(socketserver.ThreadingMixIn,
                   socketserver.UnixStreamServer):
        daemon_threads = True

    claim_socket_path(str(addr))
    return Listener(str(addr), handler_cls), "unix"
