"""Microbenchmark-driven knob calibration (DESIGN.md §25).

PriME's breadth came from fitting its abstract timing model to many real
machines; the zoo selectors (topology/coherence/prefetcher) give this
reproduction the model space, and this package closes the loop: load a
published latency/bandwidth table (e.g. the Graphcore IPU
microbenchmarks, arXiv:1912.03413), sweep candidate `TimingKnobs` as ONE
fleet per coordinate step — timing is traced, so the whole fit compiles
once per geometry — and report the best-fit knobs plus per-entry
relative residuals.
"""

from .fit import (
    FIT_KEYS_DEFAULT,
    METRICS,
    FitResult,
    fit,
    knob_start,
    simulate_matrix,
    synthesize_observed,
)
from .table import CalibEntry, CalibError, CalibTable, load_table

__all__ = [
    "CalibEntry",
    "CalibError",
    "CalibTable",
    "FIT_KEYS_DEFAULT",
    "FitResult",
    "METRICS",
    "fit",
    "knob_start",
    "load_table",
    "simulate_matrix",
    "synthesize_observed",
]
