"""Coordinate-descent knob fitting over ONE compiled fleet program.

The fit is a pattern search (Hooke-Jeeves style) over the integer traced
timing knobs: each coordinate step evaluates a FIXED-SIZE candidate set
for one knob — {v - step, v - 1, v, v + 1, v + step}, clipped and padded
with v so the count never varies — as a single FleetEngine batch of
B = n_candidates x n_entries elements. Knobs are TRACED (the jit key is
the timing-normalized geometry), the entry traces are built once (fixed
padded T), and B is constant, so EVERY fleet dispatch after the first is
a jit-cache hit: the whole calibration compiles once per geometry.

Cost is the sum of squared RELATIVE residuals, residual_e =
(sim_e - obs_e) / obs_e — dimensionless, so cycle-count and
cycles-per-op entries mix in one objective. When a knob's winning
candidate is the center (or a +-1 refinement), its step halves; the
search stops when a full round moves nothing and every step is 1.
"""

from __future__ import annotations

import dataclasses as _dc
from dataclasses import dataclass

from .table import METRIC_NAMES, CalibError, CalibTable

#: metric namespace re-export (fit computes them; table validates them)
METRICS = METRIC_NAMES

#: knobs fitted by default — the latency ladder a latency/bandwidth
#: microbenchmark table actually constrains. quantum/contention/
#: prefetch knobs opt in via --fit.
FIT_KEYS_DEFAULT = (
    "cpi", "l1_lat", "llc_lat", "link_lat", "router_lat", "dram_lat",
)

#: every fittable knob -> (reader from MachineConfig, lower bound)
_KNOB_READERS = {
    "quantum": (lambda cfg: cfg.quantum, 1),
    "cpi": (lambda cfg: cfg.core.cpi, 1),
    "l1_lat": (lambda cfg: cfg.l1.latency, 0),
    "llc_lat": (lambda cfg: cfg.llc.latency, 0),
    "link_lat": (lambda cfg: cfg.noc.link_lat, 0),
    "router_lat": (lambda cfg: cfg.noc.router_lat, 0),
    "dram_lat": (lambda cfg: cfg.dram_lat, 0),
    "dram_service": (lambda cfg: cfg.dram_service, 0),
    "contention_lat": (lambda cfg: cfg.noc.contention_lat, 0),
    "prefetch_degree": (lambda cfg: cfg.prefetch_degree, 1),
    "prefetch_lat": (lambda cfg: cfg.prefetch_lat, 0),
}

#: retired memory ops, per the counter taxonomy: every op lands in
#: exactly one of these five buckets
_MEM_OP_COUNTERS = (
    "l1_read_hits", "l1_read_misses", "l1_write_hits", "l1_write_misses",
    "upgrades",
)

N_CANDIDATES = 5


@dataclass(frozen=True)
class FitResult:
    knobs: dict  # best-fit {knob: int}
    start: dict  # where the search started
    cost: float  # sum of squared relative residuals at `knobs`
    residuals: tuple  # per-entry (name, simulated, observed, residual)
    rounds: int  # coordinate-descent rounds executed
    fleet_runs: int  # fleet dispatches (all jit-cache hits after #1)
    batch: int  # constant fleet batch size per dispatch

    def report(self) -> dict:
        return {
            "knobs": dict(self.knobs),
            "start": dict(self.start),
            "cost": self.cost,
            "rounds": self.rounds,
            "fleet_runs": self.fleet_runs,
            "batch": self.batch,
            "residuals": [
                {
                    "entry": n, "simulated": s, "observed": o,
                    "residual": r,
                }
                for n, s, o, r in self.residuals
            ],
        }


def check_fit_keys(keys) -> tuple:
    keys = tuple(keys)
    if not keys:
        raise CalibError("no fit keys given", field="fit")
    for k in keys:
        if k not in _KNOB_READERS:
            raise CalibError(
                f"unknown fit knob {k!r} (have: "
                f"{', '.join(sorted(_KNOB_READERS))})",
                field="fit",
            )
    return keys


def knob_start(cfg, keys) -> dict:
    """The search's starting point: the config's own knob values."""
    if "cpi" in keys and (
        cfg.core.cpi_per_core is not None or cfg.core.cpi_pattern is not None
    ):
        raise CalibError(
            "cannot fit 'cpi' on a heterogeneous-cpi config "
            "(cpi_per_core/cpi_pattern set)",
            field="fit",
        )
    return {k: int(_KNOB_READERS[k][0](cfg)) for k in keys}


def build_traces(cfg, table: CalibTable) -> list:
    """One synthetic trace per table entry (built once; every fleet
    dispatch reuses them, keeping the padded event geometry constant)."""
    from ..trace import synth

    traces = []
    for e in table.entries:
        try:
            traces.append(synth.GENERATORS[e.generator](cfg.n_cores,
                                                        **e.params))
        except TypeError as exc:
            raise CalibError(
                f"generator {e.generator!r} rejected params: {exc}",
                entry=e.name, field="params",
            ) from None
    return traces


class _FleetEvaluator:
    """Runs knob-candidate sets against the entry traces as one fleet.

    The batch layout is candidate-major: element k * E + e simulates
    entry e under candidate knob set k. The candidate COUNT is fixed by
    the caller, so B = K * E never changes and neither does the padded
    trace geometry — one compile, then cache hits.
    """

    def __init__(self, cfg, table: CalibTable, traces, chunk_steps=256):
        self.cfg = cfg
        self.table = table
        self.traces = traces
        self.chunk_steps = chunk_steps
        self.runs = 0

    def __call__(self, knob_sets):
        """[K knob dicts] -> list of K per-entry metric-value lists."""
        import numpy as np

        from ..sim.fleet import FleetEngine

        E = len(self.table.entries)
        K = len(knob_sets)
        fleet = FleetEngine(
            self.cfg,
            list(self.traces) * K,
            [dict(ks) for ks in knob_sets for _ in range(E)],
            chunk_steps=self.chunk_steps,
        )
        fleet.run()
        self.runs += 1
        cycles = np.asarray(fleet.cycles)  # [B, C]
        counters = fleet.counters
        mem_ops = sum(counters[n] for n in _MEM_OP_COUNTERS).sum(axis=1)
        total = cycles.max(axis=1)  # [B]
        out = []
        for k in range(K):
            row = []
            for e, ent in enumerate(self.table.entries):
                b = k * E + e
                if ent.metric == "total_cycles":
                    row.append(float(total[b]))
                else:  # cycles_per_mem_op: makespan / MEAN per-core ops
                    ops = int(mem_ops[b])
                    if ops == 0:
                        raise CalibError(
                            "trace retired no memory ops — "
                            "cycles_per_mem_op is undefined",
                            entry=ent.name, field="metric",
                        )
                    row.append(
                        float(total[b]) * self.cfg.n_cores / ops
                    )
            out.append(row)
        return out


def _cost(sims, table: CalibTable) -> float:
    return sum(
        ((s - e.observed) / e.observed) ** 2
        for s, e in zip(sims, table.entries)
    )


def _candidates(v: int, step: int, lo: int) -> list[int]:
    """Exactly N_CANDIDATES values: coarse +-step probes and +-1
    refinements around v, clipped to lo and PADDED with v (duplicates
    simulate redundantly but keep the batch size constant)."""
    cand = [max(lo, v - step), max(lo, v - 1), v, v + 1, v + step]
    assert len(cand) == N_CANDIDATES
    return cand


def fit(
    cfg,
    table: CalibTable,
    fit_keys=FIT_KEYS_DEFAULT,
    max_rounds: int = 24,
    chunk_steps: int = 256,
    log=None,
) -> FitResult:
    """Fit `fit_keys` to the table's observed values by per-knob pattern
    search; every dispatch is a constant-shape fleet (compile once)."""
    keys = check_fit_keys(fit_keys)
    base = knob_start(cfg, keys)
    lo = {k: _KNOB_READERS[k][1] for k in keys}
    step = {k: max(1, base[k] // 2) for k in keys}
    ev = _FleetEvaluator(cfg, table, build_traces(cfg, table), chunk_steps)
    rounds = 0
    for rounds in range(1, max_rounds + 1):
        moved = False
        for k in keys:
            v = base[k]
            cand = _candidates(v, step[k], lo[k])
            sims = ev([dict(base, **{k: c}) for c in cand])
            costs = [_cost(row, table) for row in sims]
            best = min(range(N_CANDIDATES), key=lambda i: costs[i])
            if cand[best] != v:
                base[k] = cand[best]
                moved = True
            # coarse probe won -> keep striding; center/refinement won
            # -> tighten the bracket
            if cand[best] not in (max(lo[k], v - step[k]), v + step[k]):
                step[k] = max(1, step[k] // 2)
            if log is not None:
                log(
                    f"round {rounds} {k}: {v} -> {base[k]} "
                    f"(cost {costs[best]:.6g}, step {step[k]})"
                )
        if not moved and all(s == 1 for s in step.values()):
            break
    final = ev([base])[0]
    residuals = tuple(
        (e.name, s, e.observed, (s - e.observed) / e.observed)
        for s, e in zip(final, table.entries)
    )
    return FitResult(
        knobs=dict(base),
        start=knob_start(cfg, keys),
        cost=_cost(final, table),
        residuals=residuals,
        rounds=rounds,
        fleet_runs=ev.runs,
        batch=N_CANDIDATES * len(table.entries),
    )


def simulate_matrix(cfg, table: CalibTable, knob_sets, chunk_steps=256):
    """Metric values for explicit knob sets: [K dicts] -> K x E lists
    (the building block `fit` loops; exposed for tests/bench)."""
    ev = _FleetEvaluator(cfg, table, build_traces(cfg, table), chunk_steps)
    return ev([dict(ks) for ks in knob_sets])


def synthesize_observed(cfg, table: CalibTable, truth: dict,
                        chunk_steps=256) -> CalibTable:
    """The table with observed values REPLACED by simulating at the
    ground-truth knobs `truth` — the calibrate self-test target: a fit
    started elsewhere must recover `truth` with ~zero residual."""
    check_fit_keys(truth.keys())
    sims = simulate_matrix(cfg, table, [truth], chunk_steps)[0]
    return table.with_observed(sims)


def apply_fit(cfg, knobs: dict):
    """`cfg` with the fitted knob values written back into the static
    config fields (for `--out` round-tripping into a machine config)."""
    out = cfg
    if "quantum" in knobs:
        out = _dc.replace(out, quantum=int(knobs["quantum"]))
    if "cpi" in knobs:
        out = _dc.replace(
            out, core=_dc.replace(out.core, cpi=int(knobs["cpi"]))
        )
    if "l1_lat" in knobs:
        out = _dc.replace(
            out, l1=_dc.replace(out.l1, latency=int(knobs["l1_lat"]))
        )
    if "llc_lat" in knobs:
        out = _dc.replace(
            out, llc=_dc.replace(out.llc, latency=int(knobs["llc_lat"]))
        )
    noc_kw = {
        k: int(knobs[k])
        for k in ("link_lat", "router_lat", "contention_lat")
        if k in knobs
    }
    if noc_kw:
        out = _dc.replace(out, noc=_dc.replace(out.noc, **noc_kw))
    for k in ("dram_lat", "dram_service", "prefetch_degree",
              "prefetch_lat"):
        if k in knobs:
            out = _dc.replace(out, **{k: int(knobs[k])})
    return out


__all__ = [
    "FIT_KEYS_DEFAULT",
    "METRICS",
    "N_CANDIDATES",
    "FitResult",
    "apply_fit",
    "build_traces",
    "check_fit_keys",
    "fit",
    "knob_start",
    "simulate_matrix",
    "synthesize_observed",
]
