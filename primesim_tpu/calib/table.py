"""Calibration tables: published microbenchmark numbers as fit targets.

A table is a JSON file:

    {
      "name": "ipu_mk1",
      "source": "arXiv:1912.03413",
      "entries": [
        {"name": "tile_stream",
         "generator": "stream", "params": {"n_mem_ops": 128},
         "metric": "cycles_per_mem_op", "observed": 9.5},
        ...
      ]
    }

Each entry names a synthetic workload (`generator` + integer `params`
over trace.synth.GENERATORS — the same namespace as `--synth` specs),
the METRIC the paper measured, and the observed value. The fit minimizes
the sum of squared RELATIVE residuals (sim - obs) / obs over entries.

Kept import-light (no jax): the CLI's typed-error catch imports
`CalibError` on every invocation; the fleet machinery lives in fit.py.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

#: Metrics an entry may target (computed in fit.py from fleet outputs).
METRIC_NAMES = ("total_cycles", "cycles_per_mem_op")


class CalibError(ValueError):
    """A calibration table is malformed or names unknown generators /
    metrics / fit keys. Typed like ConfigError: the CLI exits 2 with one
    structured `{"error": ...}` JSON line; `entry`/`field` locate the
    offending table row."""

    def __init__(
        self,
        message: str,
        *,
        entry: str | int | None = None,
        field: str | None = None,
    ):
        self.entry = entry
        self.field = field
        where = []
        if entry is not None:
            where.append(f"entry {entry!r}")
        if field is not None:
            where.append(f"field {field!r}")
        prefix = (
            f"calibration table: {', '.join(where)}: " if where
            else "calibration table: "
        )
        super().__init__(prefix + message)

    def location(self) -> dict:
        out = {}
        if self.entry is not None:
            out["entry"] = str(self.entry)
        if self.field is not None:
            out["field"] = self.field
        return out


@dataclass(frozen=True)
class CalibEntry:
    name: str
    generator: str
    params: dict
    metric: str
    observed: float


@dataclass(frozen=True)
class CalibTable:
    name: str
    entries: tuple[CalibEntry, ...]
    source: str = ""
    note: str = ""

    def with_observed(self, values) -> "CalibTable":
        """A copy with each entry's observed value replaced (synthetic
        ground-truth tables for the calibrate self-test)."""
        if len(values) != len(self.entries):
            raise CalibError(
                f"{len(values)} observed values for "
                f"{len(self.entries)} entries"
            )
        ents = tuple(
            CalibEntry(e.name, e.generator, dict(e.params), e.metric, float(v))
            for e, v in zip(self.entries, values)
        )
        return CalibTable(self.name, ents, self.source, self.note)


def _check_entry(i: int, raw) -> CalibEntry:
    from ..trace import synth

    if not isinstance(raw, dict):
        raise CalibError("entry must be an object", entry=i)
    name = raw.get("name")
    if not isinstance(name, str) or not name:
        raise CalibError("missing/empty name", entry=i, field="name")
    gen = raw.get("generator")
    if gen not in synth.GENERATORS:
        raise CalibError(
            f"unknown generator {gen!r} (have: "
            f"{', '.join(sorted(synth.GENERATORS))})",
            entry=name, field="generator",
        )
    params = raw.get("params", {})
    if not isinstance(params, dict):
        raise CalibError("params must be an object", entry=name,
                         field="params")
    for k, v in params.items():
        if not isinstance(v, int) or isinstance(v, bool):
            raise CalibError(
                f"param {k!r} must be an integer (got {v!r})",
                entry=name, field="params",
            )
    metric = raw.get("metric")
    if metric not in METRIC_NAMES:
        raise CalibError(
            f"unknown metric {metric!r} (have: {', '.join(METRIC_NAMES)})",
            entry=name, field="metric",
        )
    obs = raw.get("observed")
    if not isinstance(obs, (int, float)) or isinstance(obs, bool) or obs <= 0:
        raise CalibError(
            f"observed must be a positive number (got {obs!r}) — the fit "
            "minimizes RELATIVE residuals",
            entry=name, field="observed",
        )
    return CalibEntry(name, gen, dict(params), metric, float(obs))


def parse_table(text: str) -> CalibTable:
    try:
        raw = json.loads(text)
    except json.JSONDecodeError as e:
        raise CalibError(f"not valid JSON: {e}") from None
    if not isinstance(raw, dict):
        raise CalibError("top level must be an object")
    name = raw.get("name")
    if not isinstance(name, str) or not name:
        raise CalibError("missing/empty table name", field="name")
    raw_entries = raw.get("entries")
    if not isinstance(raw_entries, list) or not raw_entries:
        raise CalibError("entries must be a non-empty array",
                         field="entries")
    entries = tuple(_check_entry(i, e) for i, e in enumerate(raw_entries))
    seen: set[str] = set()
    for e in entries:
        if e.name in seen:
            raise CalibError("duplicate entry name", entry=e.name)
        seen.add(e.name)
    return CalibTable(
        name, entries,
        source=str(raw.get("source", "")), note=str(raw.get("note", "")),
    )


def load_table(path: str) -> CalibTable:
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise CalibError(f"cannot read {path!r}: {e}") from None
    return parse_table(text)


__all__ = [
    "METRIC_NAMES",
    "CalibEntry",
    "CalibError",
    "CalibTable",
    "load_table",
    "parse_table",
]
