"""2-D torus NoC topology plugin (DESIGN.md §25).

The mesh with wrap-around edges: XY dimension-ordered routing, but each
phase takes the SHORTER way around its ring (ties break toward the
positive direction). Link ids reuse the mesh numbering — every tile still
sources four directed links, id = tile*4 + dir with dir 0=E (+x), 1=W
(-x), 2=N (+y), 3=S (-y) — so `n_links` and the contention models'
scatter shapes are unchanged; only which links a route crosses differs.

Same layered contract as `mesh`: `hops` works on NumPy and traced jnp
arrays alike (the `xp` module parameter picks), `route_links` is the
memoized scalar reference walk, `path_links` the vectorized builder the
engine consumes, and the two must match link-for-link.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig


def ring_dist(xp, a, b, m: int):
    """Shortest distance between positions a and b on a ring of m tiles."""
    d = xp.abs(a - b)
    return xp.minimum(d, m - d)


def hops(tile_a, tile_b, mesh_x: int, mesh_y: int, xp=jnp):
    ax, ay = tile_a % mesh_x, tile_a // mesh_x
    bx, by = tile_b % mesh_x, tile_b // mesh_x
    return ring_dist(xp, ax, bx, mesh_x) + ring_dist(xp, ay, by, mesh_y)


def path_width(mesh_x: int, mesh_y: int) -> int:
    """Max route length (torus diameter): half of each ring."""
    return max(1, mesh_x // 2 + mesh_y // 2)


def _ring_step(a: int, b: int, m: int) -> tuple[int, int]:
    """Scalar (direction, count) of the shortest way a -> b around a ring
    of m positions; ties break positive (matches `path_links`)."""
    dpos = (b - a) % m
    dneg = (a - b) % m
    return (1, dpos) if dpos <= dneg else (-1, dneg)


@functools.lru_cache(maxsize=None)
def route_links(a: int, b: int, mesh_x: int, mesh_y: int) -> tuple[int, ...]:
    """Directed link ids on the torus route tile a -> tile b (scalar,
    memoized reference walk; the vectorized `path_links` must match
    link-for-link)."""
    ax, ay = a % mesh_x, a // mesh_x
    bx, by = b % mesh_x, b // mesh_x
    links = []
    s, n = _ring_step(ax, bx, mesh_x)
    x = ax
    for _ in range(n):
        links.append((ay * mesh_x + x) * 4 + (0 if s > 0 else 1))
        x = (x + s) % mesh_x
    s, n = _ring_step(ay, by, mesh_y)
    y = ay
    for _ in range(n):
        links.append((y * mesh_x + bx) * 4 + (2 if s > 0 else 3))
        y = (y + s) % mesh_y
    return tuple(links)


def path_links(cfg: MachineConfig, a, b):
    """Vectorized torus route a->b as directed link ids, -1-padded to the
    torus diameter — link-for-link identical to `route_links` (shorter-way
    x phase at the source row, then shorter-way y phase at the destination
    column)."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    H = path_width(mx, my)
    ax, ay = a % mx, a // mx
    bx, by = b % mx, b // mx
    i = jnp.arange(H, dtype=jnp.int32)[None, :]
    dxp = (bx - ax) % mx
    dxn = (ax - bx) % mx
    posx = dxp <= dxn
    sx = jnp.where(posx, 1, -1)
    nx = jnp.minimum(dxp, dxn)
    px = (ax[:, None] + sx[:, None] * i) % mx
    xlink = (ay[:, None] * mx + px) * 4 + jnp.where(posx[:, None], 0, 1)
    dyp = (by - ay) % my
    dyn = (ay - by) % my
    posy = dyp <= dyn
    sy = jnp.where(posy, 1, -1)
    ny = jnp.minimum(dyp, dyn)
    j = i - nx[:, None]
    py = (ay[:, None] + sy[:, None] * j) % my
    ylink = (py * mx + bx[:, None]) * 4 + jnp.where(posy[:, None], 2, 3)
    return jnp.where(
        i < nx[:, None], xlink, jnp.where(j < ny[:, None], ylink, -1)
    )


def detour_hops_table(cfg: MachineConfig) -> np.ndarray:
    """Extra hops a route pays to detour around each FAILED directed link
    (faults/inject.py). A torus edge has the same minimal fallback as a
    mesh edge — one orthogonal sidestep and return, +2 hops — so the
    table is uniform (link faults require >= 2x2, as on the mesh)."""
    return np.full(cfg.n_tiles * 4, 2, np.int32)
