"""Multi-ring NoC topology plugin (DESIGN.md §25).

One horizontal ring per row plus ONE vertical ring at column 0 — the
hierarchical-ring shape (row rings bridged by a global spine). A message
between rows takes three legs: shortest way around the source row's ring
to column 0, shortest way around the spine to the destination row, then
shortest way around the destination row's ring to the target column.
Same-row traffic stays on its row ring.

Link ids reuse the mesh numbering (tile*4 + dir, 0=E 1=W 2=N 3=S) so
`n_links` and every contention/fault scatter shape is unchanged; the
non-spine vertical links (columns > 0) simply never carry traffic. Same
layered contract as `mesh`/`torus`: xp-generic `hops`, memoized scalar
`route_links` reference walk, vectorized `path_links` matching it
link-for-link.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from .torus import _ring_step, ring_dist


def hops(tile_a, tile_b, mesh_x: int, mesh_y: int, xp=jnp):
    ax, ay = tile_a % mesh_x, tile_a // mesh_x
    bx, by = tile_b % mesh_x, tile_b // mesh_x
    direct = ring_dist(xp, ax, bx, mesh_x)
    via = (
        ring_dist(xp, ax, 0 * ax, mesh_x)
        + ring_dist(xp, ay, by, mesh_y)
        + ring_dist(xp, 0 * bx, bx, mesh_x)
    )
    return xp.where(ay == by, direct, via)


def path_width(mesh_x: int, mesh_y: int) -> int:
    """Max route length: two half row-rings plus half the spine."""
    return max(1, 2 * (mesh_x // 2) + mesh_y // 2)


@functools.lru_cache(maxsize=None)
def route_links(a: int, b: int, mesh_x: int, mesh_y: int) -> tuple[int, ...]:
    """Directed link ids on the ring route tile a -> tile b (scalar,
    memoized reference walk; the vectorized `path_links` must match
    link-for-link)."""
    ax, ay = a % mesh_x, a // mesh_x
    bx, by = b % mesh_x, b // mesh_x
    links = []

    def row_leg(y: int, x0: int, x1: int) -> None:
        s, n = _ring_step(x0, x1, mesh_x)
        x = x0
        for _ in range(n):
            links.append((y * mesh_x + x) * 4 + (0 if s > 0 else 1))
            x = (x + s) % mesh_x

    if ay == by:
        row_leg(ay, ax, bx)
        return tuple(links)
    row_leg(ay, ax, 0)
    s, n = _ring_step(ay, by, mesh_y)
    y = ay
    for _ in range(n):
        links.append((y * mesh_x + 0) * 4 + (2 if s > 0 else 3))
        y = (y + s) % mesh_y
    row_leg(by, 0, bx)
    return tuple(links)


def path_links(cfg: MachineConfig, a, b):
    """Vectorized ring route a->b as directed link ids, -1-padded to the
    ring diameter — three concatenated shorter-way legs (source row ring
    to the spine, spine to the destination row, destination row ring),
    collapsing to the direct row leg when the rows match."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    H = path_width(mx, my)
    ax, ay = a % mx, a // mx
    bx, by = b % mx, b // mx
    same = ay == by
    i = jnp.arange(H, dtype=jnp.int32)[None, :]
    # leg 1: row ay's ring, ax -> (bx when same row, else the spine at 0)
    t1 = jnp.where(same, bx, 0)
    d1p = (t1 - ax) % mx
    d1n = (ax - t1) % mx
    pos1 = d1p <= d1n
    s1 = jnp.where(pos1, 1, -1)
    n1 = jnp.minimum(d1p, d1n)
    p1 = (ax[:, None] + s1[:, None] * i) % mx
    l1 = (ay[:, None] * mx + p1) * 4 + jnp.where(pos1[:, None], 0, 1)
    # leg 2: the column-0 spine ring, ay -> by (skipped when same row)
    d2p = (by - ay) % my
    d2n = (ay - by) % my
    pos2 = d2p <= d2n
    s2 = jnp.where(pos2, 1, -1)
    n2 = jnp.where(same, 0, jnp.minimum(d2p, d2n))
    j = i - n1[:, None]
    p2 = (ay[:, None] + s2[:, None] * j) % my
    l2 = (p2 * mx) * 4 + jnp.where(pos2[:, None], 2, 3)
    # leg 3: row by's ring, 0 -> bx (skipped when same row)
    d3p = bx % mx
    d3n = (-bx) % mx
    pos3 = d3p <= d3n
    s3 = jnp.where(pos3, 1, -1)
    n3 = jnp.where(same, 0, jnp.minimum(d3p, d3n))
    k = j - n2[:, None]
    p3 = (s3[:, None] * k) % mx
    l3 = (by[:, None] * mx + p3) * 4 + jnp.where(pos3[:, None], 0, 1)
    return jnp.where(
        i < n1[:, None],
        l1,
        jnp.where(j < n2[:, None], l2, jnp.where(k < n3[:, None], l3, -1)),
    )


def detour_hops_table(cfg: MachineConfig) -> np.ndarray:
    """Extra hops to detour around each FAILED directed link: a ring has
    no orthogonal sidestep, so the fallback is the LONG way around the
    same ring — (m - 1) hops replacing 1, i.e. m - 2 extra. Row-ring
    links (dirs 0/1) detour around their row (mx - 2); spine links (dirs
    2/3) around the spine (my - 2). Config validation requires
    mesh_x >= 3 and mesh_y >= 3 for ring link faults, keeping every
    entry positive."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    tbl = np.empty((cfg.n_tiles, 4), np.int32)
    tbl[:, 0:2] = mx - 2
    tbl[:, 2:4] = my - 2
    return tbl.reshape(-1)
