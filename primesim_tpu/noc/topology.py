"""NoC topology dispatch (DESIGN.md §25).

The machine zoo's pluggable-topology seam: `cfg.noc.topology` is a STATIC
selector (part of `timing_normalized()`, so it joins the jit / exec-cache
key like `contention_model`), and every engine/golden/fault consumer
routes through this module instead of importing `mesh` directly. Each
plugin provides the same layered contract:

- ``coord_hops`` / ``hops``: hop count, generic over the array module
  (``xp=np`` for host-side tables and the golden model, ``xp=jnp`` for
  traced code, plain ints for scalars);
- ``route_links``: the memoized scalar reference walk;
- ``path_links``: the vectorized [C, H] route builder (-1-padded to the
  topology's ``path_width``) that must match ``route_links``
  link-for-link;
- ``detour_hops_table``: per-directed-link extra hops a route pays to
  detour around that link when FAILED (faults/inject.py);
- ``detour_stats``: the scalar fault-penalty reference for one leg.

All topologies share the mesh's link numbering (tile*4 + dir), so
``n_links`` and every contention/fault scatter shape is
topology-invariant.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config.machine import NOC_TOPOLOGIES as TOPOLOGIES
from ..config.machine import MachineConfig
from . import mesh as _mesh
from . import ring as _ring
from . import torus as _torus

__all__ = [
    "TOPOLOGIES", "coord_hops", "hops", "one_way_lat", "path_width",
    "route_links", "path_links", "detour_hops_table", "detour_stats",
]


def coord_hops(topology: str, ax, ay, bx, by, mesh_x: int, mesh_y: int, xp=jnp):
    """Hop count between tile COORDINATES under `topology`; `xp` picks the
    array module (np/jnp — also the form the Pallas reduction kernel
    inlines, all elementwise min/abs/where arithmetic)."""
    if topology == "torus":
        return _torus.ring_dist(xp, ax, bx, mesh_x) + _torus.ring_dist(
            xp, ay, by, mesh_y
        )
    if topology == "ring":
        direct = _torus.ring_dist(xp, ax, bx, mesh_x)
        via = (
            _torus.ring_dist(xp, ax, 0 * ax, mesh_x)
            + _torus.ring_dist(xp, ay, by, mesh_y)
            + _torus.ring_dist(xp, 0 * bx, bx, mesh_x)
        )
        return xp.where(ay == by, direct, via)
    return xp.abs(ax - bx) + xp.abs(ay - by)


def hops(cfg: MachineConfig, tile_a, tile_b, xp=jnp):
    """Hop count between TILE ids under cfg's topology."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    return coord_hops(
        cfg.noc.topology, tile_a % mx, tile_a // mx, tile_b % mx,
        tile_b // mx, mx, my, xp,
    )


def one_way_lat(cfg: MachineConfig, tile_a, tile_b):
    """One-way message latency: hops*link + (hops+1)*router (the golden
    model's scalar form; `mesh.one_way_lat` stays as the mesh-only
    legacy entry point)."""
    h = hops(cfg, tile_a, tile_b, xp=np)
    return h * cfg.noc.link_lat + (h + 1) * cfg.noc.router_lat


def path_width(cfg: MachineConfig) -> int:
    """The -1-padded route length H of `path_links` for this topology."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    if cfg.noc.topology == "torus":
        return _torus.path_width(mx, my)
    if cfg.noc.topology == "ring":
        return _ring.path_width(mx, my)
    return max(1, (mx - 1) + (my - 1))


def route_links(cfg: MachineConfig, a: int, b: int) -> tuple[int, ...]:
    """Directed link ids on the scalar reference route a -> b."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    if cfg.noc.topology == "torus":
        return _torus.route_links(int(a), int(b), mx, my)
    if cfg.noc.topology == "ring":
        return _ring.route_links(int(a), int(b), mx, my)
    return _mesh.xy_links(int(a), int(b), mx)


def path_links(cfg: MachineConfig, a, b):
    """Vectorized route a->b as directed link ids [C, H], -1-padded."""
    if cfg.noc.topology == "torus":
        return _torus.path_links(cfg, a, b)
    if cfg.noc.topology == "ring":
        return _ring.path_links(cfg, a, b)
    return _mesh.path_links(cfg, a, b)


def detour_hops_table(cfg: MachineConfig) -> np.ndarray:
    """[n_links] extra hops a route pays to detour around each directed
    link when FAILED. Mesh and torus pay the orthogonal sidestep (+2
    everywhere); the ring pays the long way around the affected ring."""
    if cfg.noc.topology == "ring":
        return _ring.detour_hops_table(cfg)
    if cfg.noc.topology == "torus":
        return _torus.detour_hops_table(cfg)
    return np.full(cfg.n_tiles * 4, 2, np.int32)


def detour_stats(
    cfg: MachineConfig, a: int, b: int, link_dead, link_extra,
    link_lat: int, router_lat: int,
) -> tuple[int, int, int]:
    """Scalar fault penalty of the one-way leg a -> b under cfg's
    topology: (extra cycles, extra hops, rerouted flag) — the reference
    the vectorized `faults.inject.leg_fault_penalty` must match per leg
    (generalizes `mesh.detour_stats`, which remains the mesh-only form)."""
    tbl = detour_hops_table(cfg)
    dead_hops = 0
    extra = 0
    for l in route_links(cfg, a, b):
        if link_dead[l]:
            dead_hops += int(tbl[l])
        else:
            extra += int(link_extra[l])
    return (
        dead_hops * (link_lat + router_lat) + extra,
        dead_hops,
        int(dead_hops > 0),
    )
