"""2-D mesh NoC geometry and analytic latency (DESIGN.md §1).

TPU-native replacement for the reference's hop-by-hop `Network` mesh router
(SURVEY.md §2 #6). v1 is the analytic uncontended model shared verbatim by
the golden simulator and the JAX engine (these helpers are written so they
work on NumPy arrays AND traced jnp arrays alike). The congestion-aware
Pallas router (per-link occupancy, ICI neighbor exchange under shard_map) is
the planned v2 behind `NocConfig` gating.
"""

from __future__ import annotations

from ..config.machine import MachineConfig


def tile_xy(tile, mesh_x: int):
    return tile % mesh_x, tile // mesh_x


def hops(tile_a, tile_b, mesh_x: int):
    ax, ay = tile_xy(tile_a, mesh_x)
    bx, by = tile_xy(tile_b, mesh_x)
    return abs(ax - bx) + abs(ay - by)


def one_way_lat(tile_a, tile_b, cfg: MachineConfig):
    """One-way message latency: hops*link + (hops+1)*router."""
    h = hops(tile_a, tile_b, cfg.noc.mesh_x)
    return h * cfg.noc.link_lat + (h + 1) * cfg.noc.router_lat


def core_tile(core, cfg: MachineConfig):
    return core % cfg.n_tiles


def bank_tile(bank, cfg: MachineConfig):
    return bank % cfg.n_tiles
