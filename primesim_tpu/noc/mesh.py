"""2-D mesh NoC geometry and analytic latency (DESIGN.md §1).

TPU-native replacement for the reference's hop-by-hop `Network` mesh router
(SURVEY.md §2 #6). v1 is the analytic uncontended model shared verbatim by
the golden simulator and the JAX engine (these helpers are written so they
work on NumPy arrays AND traced jnp arrays alike). The congestion-aware
Pallas router (per-link occupancy, ICI neighbor exchange under shard_map) is
the planned v2 behind `NocConfig` gating.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from ..config.machine import MachineConfig


def tile_xy(tile, mesh_x: int):
    return tile % mesh_x, tile // mesh_x


def hops(tile_a, tile_b, mesh_x: int):
    ax, ay = tile_xy(tile_a, mesh_x)
    bx, by = tile_xy(tile_b, mesh_x)
    return abs(ax - bx) + abs(ay - by)


def one_way_lat(tile_a, tile_b, cfg: MachineConfig):
    """One-way message latency: hops*link + (hops+1)*router."""
    h = hops(tile_a, tile_b, cfg.noc.mesh_x)
    return h * cfg.noc.link_lat + (h + 1) * cfg.noc.router_lat


def core_tile(core, cfg: MachineConfig):
    return core % cfg.n_tiles


def bank_tile(bank, cfg: MachineConfig):
    return bank % cfg.n_tiles


# Directed links for the per-link contention model: each tile sources four
# links, id = tile*4 + dir with dir 0=E (+x), 1=W (-x), 2=N (+y), 3=S (-y).
# XY routing uses x-phase links at the source row, then y-phase links at
# the destination column — `xy_links` is the scalar reference walk the
# vectorized engine path builder must match link-for-link.


def n_links(cfg: MachineConfig) -> int:
    return cfg.n_tiles * 4


@functools.lru_cache(maxsize=None)
def xy_links(a: int, b: int, mesh_x: int) -> tuple[int, ...]:
    """Directed link ids on the XY route tile a -> tile b (scalar,
    memoized — tile pairs repeat heavily across golden steps; immutable
    so the cached value cannot be corrupted)."""
    ax, ay = a % mesh_x, a // mesh_x
    bx, by = b % mesh_x, b // mesh_x
    links = []
    x, y = ax, ay
    while x != bx:
        d = 0 if bx > x else 1
        links.append((y * mesh_x + x) * 4 + d)
        x += 1 if bx > x else -1
    while y != by:
        d = 2 if by > y else 3
        links.append((y * mesh_x + x) * 4 + d)
        y += 1 if by > y else -1
    return tuple(links)


def path_links(cfg: MachineConfig, a, b):
    """Vectorized XY route a->b as directed link ids, -1-padded to the
    mesh diameter — link-for-link identical to `xy_links` (x phase at the
    source row, then y phase at the destination column). Shared by the
    engine's per-link contention models and the fault-injection detour
    model (faults/inject.py)."""
    mx, my = cfg.noc.mesh_x, cfg.noc.mesh_y
    H = max(1, (mx - 1) + (my - 1))
    ax, ay = a % mx, a // mx
    bx, by = b % mx, b // mx
    i = jnp.arange(H, dtype=jnp.int32)[None, :]
    sx = jnp.sign(bx - ax)
    nx = jnp.abs(bx - ax)
    px = ax[:, None] + sx[:, None] * i
    xlink = (ay[:, None] * mx + px) * 4 + jnp.where(sx[:, None] > 0, 0, 1)
    sy = jnp.sign(by - ay)
    ny = jnp.abs(by - ay)
    j = i - nx[:, None]
    py = ay[:, None] + sy[:, None] * j
    ylink = (py * mx + bx[:, None]) * 4 + jnp.where(sy[:, None] > 0, 2, 3)
    return jnp.where(
        i < nx[:, None], xlink, jnp.where(j < ny[:, None], ylink, -1)
    )


def concat_legs(legs):
    """Concatenate per-leg XY paths and their lane masks into the
    contention models' [C, legs·H] layout: ``legs`` is a sequence of
    (path_links result [C, H], lane mask [C]) pairs.  Both the "link"
    occupancy count and the hop-by-hop router block run every per-link
    operation ONCE over this concatenation (one scatter, one rank, one
    gather pair) — per-kernel overhead is the budget, so per-path loops
    become per-path kernels (sim/engine.py)."""
    pths = [p for p, _ in legs]
    masks = [jnp.broadcast_to(m[:, None], p.shape) for p, m in legs]
    return jnp.concatenate(pths, axis=1), jnp.concatenate(masks, axis=1)


# ---- fault-model detour (DESIGN.md §12) -----------------------------------
# A FAILED directed link on a message's XY path forces an adaptive
# fallback around it: one orthogonal sidestep and return, i.e. +2 hops and
# +2 * (link_lat + router_lat) cycles per failed hop (the minimal X-Y
# detour around a single dead edge of a >= 2x2 mesh; config validation
# rejects link faults on thinner meshes). A DEGRADED (alive) link adds its
# `extra` cycles each traversal; a dead link's extra is moot (the detour
# replaces the traversal). `detour_stats` is the scalar reference the
# vectorized `faults.inject.leg_fault_penalty` must match per leg.


def detour_stats(
    a: int, b: int, mesh_x: int, link_dead, link_extra,
    link_lat: int, router_lat: int,
) -> tuple[int, int, int]:
    """Scalar fault penalty of the one-way leg a -> b: (extra cycles,
    extra hops, rerouted flag)."""
    dead = 0
    extra = 0
    for l in xy_links(a, b, mesh_x):
        if link_dead[l]:
            dead += 1
        else:
            extra += int(link_extra[l])
    return (
        dead * 2 * (link_lat + router_lat) + extra,
        2 * dead,
        int(dead > 0),
    )
