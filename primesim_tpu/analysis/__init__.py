"""Static analysis subsystem (DESIGN.md §19): `primetpu lint` checks
the SOURCE against the repo's invariant catalog, `primetpu fsck`
checks DURABLE ARTIFACTS (journals, ledgers, checkpoints, warm cache)
with zero simulation, and `recompile_sentinel` guards the one-compile-
per-geometry contract at runtime."""

from .errors import AnalysisError, FsckCorrupt, RecompileError
from .fsck import run_fsck
from .lint import run_lint
from .recompile import recompile_sentinel

__all__ = [
    "AnalysisError",
    "FsckCorrupt",
    "RecompileError",
    "run_fsck",
    "run_lint",
    "recompile_sentinel",
]
