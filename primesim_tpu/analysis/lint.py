"""AST lint framework for the repo's load-bearing invariants.

The rules themselves live in `rules.py`; this module is the machinery:
a registry, per-line suppression comments, a committed baseline for
grandfathered findings, and human/JSON rendering. The contract (also
DESIGN.md §19):

  - a rule is a function `check(tree, ctx)` yielding `(lineno, col,
    message)` tuples, registered with @rule(id, summary, scope=...);
    `scope` is a tuple of path substrings matched against
    "/" + repo-relative-posix-path (empty scope = every file)
  - `# ptlint: allow=PT-XXX` (comma list, or `*`) on the flagged line
    or the line directly above suppresses a finding at that site
  - LINT_BASELINE.json grandfathers pre-existing findings: entries
    match by (rule, path, stripped line text) and each absorbs up to
    `count` findings; every entry carries a one-line `why`.  Entries
    that match nothing are reported as stale (the debt was paid —
    delete the entry)
  - exit codes: 0 clean, 1 findings, 2 AnalysisError (via the CLI's
    structured-error contract)
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Iterator

from .errors import AnalysisError

BASELINE_NAME = "LINT_BASELINE.json"

_ALLOW_RE = re.compile(r"#\s*ptlint:\s*allow=([A-Za-z0-9_\-*,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # repo-relative posix path
    line: int        # 1-based
    col: int
    message: str
    line_text: str   # stripped source line (the baseline matching key)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    summary: str
    scope: tuple
    check: Callable


RULES: dict[str, Rule] = {}


def rule(rule_id: str, summary: str, scope: tuple = ()):
    """Register a lint rule. `check(tree, ctx)` yields (lineno, col,
    message); the framework attaches path/line-text and handles
    suppression + baseline."""

    def deco(fn):
        RULES[rule_id] = Rule(rule_id, summary, tuple(scope), fn)
        return fn

    return deco


class FileContext:
    """What a rule sees about the file under scrutiny."""

    def __init__(self, relpath: str, src: str):
        self.relpath = relpath
        self.src = src
        self.lines = src.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _scope_matches(scope: tuple, relpath: str) -> bool:
    if not scope:
        return True
    probe = "/" + relpath.replace(os.sep, "/")
    return any(s in probe for s in scope)


def _allowed_rules(ctx: FileContext, lineno: int) -> set:
    """Rule ids suppressed at `lineno` (same line or the line above)."""
    out: set = set()
    for ln in (lineno, lineno - 1):
        text = ctx.line_text(ln)
        m = _ALLOW_RE.search(text)
        if m:
            out |= {t.strip() for t in m.group(1).split(",") if t.strip()}
    return out


def iter_py_files(roots: Iterable[str]) -> Iterator[str]:
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".fsck-quarantine")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def repo_root() -> str:
    """The directory holding the primesim_tpu package (= repo root)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


@dataclasses.dataclass
class LintResult:
    findings: list          # surviving Findings (fail the run)
    suppressed: int         # killed by # ptlint: allow=
    baselined: int          # absorbed by the baseline file
    stale: list             # baseline entries that matched nothing
    files: int              # files scanned

    @property
    def clean(self) -> bool:
        return not self.findings


class _Baseline:
    """Matches findings against committed entries.

    Each entry {rule, path, line_text, count, why} absorbs up to
    `count` findings whose (rule, path, stripped line text) agree —
    line NUMBERS deliberately don't participate, so unrelated edits
    above a grandfathered site don't invalidate the baseline.
    """

    def __init__(self, entries: list):
        self._budget: dict = {}
        self._entries = entries
        for i, e in enumerate(entries):
            for field in ("rule", "path", "line_text", "why"):
                if not isinstance(e.get(field), str) or not e[field]:
                    raise AnalysisError(
                        f"baseline entry {i}: missing/empty '{field}'"
                    )
            key = (e["rule"], e["path"], e["line_text"].strip())
            self._budget[key] = self._budget.get(key, 0) + int(
                e.get("count", 1)
            )
        self._spent: dict = {k: 0 for k in self._budget}

    def absorb(self, f: Finding) -> bool:
        key = (f.rule, f.path, f.line_text)
        if self._spent.get(key, 0) < self._budget.get(key, 0):
            self._spent[key] += 1
            return True
        return False

    def stale_entries(self) -> list:
        return [
            {"rule": k[0], "path": k[1], "line_text": k[2],
             "unused": self._budget[k] - self._spent[k]}
            for k in self._budget
            if self._spent[k] < self._budget[k]
        ]


def load_baseline(path: str) -> _Baseline:
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return _Baseline([])
    except json.JSONDecodeError as e:
        raise AnalysisError(
            f"baseline is not valid JSON: {e}", path=path, line=e.lineno
        )
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), list):
        raise AnalysisError(
            "baseline must be {\"entries\": [...]}", path=path
        )
    try:
        return _Baseline(doc["entries"])
    except AnalysisError as e:
        raise AnalysisError(str(e), path=path)


def run_lint(
    paths: Iterable[str] | None = None,
    root: str | None = None,
    baseline_path: str | None = None,
    select: Iterable[str] | None = None,
) -> LintResult:
    """Lint `paths` (default: the primesim_tpu package under `root`).

    `root` anchors repo-relative paths (default: the repo root derived
    from this package's location). Raises AnalysisError on unparseable
    source or a malformed baseline.
    """
    # the shipped rules register on import
    from . import rules as _rules  # noqa: F401

    root = os.path.abspath(root or repo_root())
    if paths is None:
        paths = [os.path.join(root, "primesim_tpu")]
    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_NAME)
    baseline = load_baseline(baseline_path)

    active = list(RULES.values())
    if select:
        select = set(select)
        unknown = select - set(RULES)
        if unknown:
            raise AnalysisError(
                f"unknown rule id(s): {', '.join(sorted(unknown))}"
            )
        active = [r for r in active if r.rule_id in select]

    findings: list = []
    suppressed = 0
    baselined = 0
    n_files = 0
    for fpath in iter_py_files(paths):
        relpath = os.path.relpath(fpath, root).replace(os.sep, "/")
        try:
            with open(fpath, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            raise AnalysisError(f"cannot read source: {e}", path=relpath)
        scoped = [r for r in active if _scope_matches(r.scope, relpath)]
        if not scoped:
            continue
        n_files += 1
        try:
            tree = ast.parse(src, filename=relpath)
        except SyntaxError as e:
            raise AnalysisError(
                f"syntax error: {e.msg}", path=relpath, line=e.lineno
            )
        ctx = FileContext(relpath, src)
        for r in scoped:
            for lineno, col, message in r.check(tree, ctx):
                f_obj = Finding(
                    rule=r.rule_id, path=relpath, line=lineno, col=col,
                    message=message, line_text=ctx.line_text(lineno),
                )
                if r.rule_id in _allowed_rules(ctx, lineno) or (
                    "*" in _allowed_rules(ctx, lineno)
                ):
                    suppressed += 1
                elif baseline.absorb(f_obj):
                    baselined += 1
                else:
                    findings.append(f_obj)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(
        findings=findings, suppressed=suppressed, baselined=baselined,
        stale=baseline.stale_entries(), files=n_files,
    )


def render_human(res: LintResult) -> str:
    out = []
    for f in res.findings:
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        out.append(f"    {f.line_text}")
    for s in res.stale:
        out.append(
            f"stale baseline entry ({s['unused']} unused): "
            f"{s['rule']} {s['path']}: {s['line_text']}"
        )
    out.append(
        f"{len(res.findings)} finding(s) in {res.files} file(s) "
        f"({res.baselined} baselined, {res.suppressed} suppressed, "
        f"{len(res.stale)} stale baseline entries)"
    )
    return "\n".join(out)


def render_json(res: LintResult) -> str:
    return json.dumps(
        {
            "findings": [f.as_dict() for f in res.findings],
            "stale_baseline": res.stale,
            "summary": {
                "findings": len(res.findings),
                "files": res.files,
                "baselined": res.baselined,
                "suppressed": res.suppressed,
            },
        },
        indent=2,
        sort_keys=True,
    )
