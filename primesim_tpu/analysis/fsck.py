"""`primetpu fsck` — static verification of durable state.

Walks a directory tree and validates every durable artifact the repo
writes, with ZERO simulation and without mutating anything it checks:

  - journal/ledger segment chains (serve/journal.py): per-line frame
    CRCs, torn-tail-only-in-the-newest-segment, header seq agreement,
    sequence contiguity, the rolled-segment prev-CRC back-links, and
    base-segment restarts — a read-only reimplementation of
    `JobJournal.replay()` that reports findings instead of raising
    (and, crucially, never instantiates JobJournal: its constructor
    repairs crash debris, which would destroy the evidence)
  - serve job records: state-machine legality of the journaled
    transition stream under the fold's documented tolerances
    (duplicate accepts, post-terminal duplicates, RUNNING->PENDING
    crash re-admission)
  - pool ledger records: unit-key consistency — every lease/ack/spec
    key for one unit must agree, and a `unit` spec must hash to its
    own stamped key
  - checkpoints (*.npz): CRC manifest via `load_verified_npz`,
    `_FORMAT` version, per-kind required members, counter-row counts
  - warm-cache entries: sidecar↔filename↔npz agreement (key stem,
    steps, trace_sha); orphan sidecars and mkstemp leftovers are
    reported as notes, not corruption (they are expected kill -9
    debris)
  - AOT executable entries (exec/*.bin, DESIGN.md §23): magic + CRC of
    the serialized executable, sidecar key↔content agreement (the
    payload must re-hash to its own filename), required toolchain
    version fields; an entry lowered under a different jax/jaxlib is a
    note (the cache treats it as a plain miss), a tampered one is
    corrupt

`--repair quarantine` moves (never deletes) corrupt or orphaned FILES
into `<root>/.fsck-quarantine/<relpath>`; logical findings that span a
chain (an illegal transition inside an intact segment) are reported
but not repairable. Exit codes ride the CLI contract: 0 clean (notes
allowed — crash debris is normal), 2 with structured JSON when any
corrupt finding exists.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil

from .errors import FsckCorrupt

_JOURNAL_ACTIVE = "journal.jsonl"
_SERVE_TYPES = {"accept", "state"}
_POOL_TYPES = {"unit", "lease", "expire", "ack", "poison",
               "ack_dup", "suspect", "verdict", "audit"}
# pool record types that may carry a fingerprint-chain payload
# (DESIGN.md §24), directly or inside a `held` evidence list
_ATTEST_TYPES = {"ack", "ack_dup", "suspect", "verdict"}


@dataclasses.dataclass
class Finding:
    kind: str        # "journal-chain" | "journal-record" | "job-transition"
    #                  | "ledger-key" | "checkpoint" | "warm-cache" | "orphan"
    path: str        # root-relative
    detail: str
    corrupt: bool    # True -> fsck exits 2
    repairable: bool = False  # a file quarantine can move aside

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FsckResult:
    root: str
    findings: list
    checked: dict      # category -> count
    quarantined: list  # root-relative paths moved aside

    @property
    def corrupt(self) -> list:
        return [f for f in self.findings if f.corrupt]

    @property
    def clean(self) -> bool:
        return not self.corrupt


# ---- journal chain ------------------------------------------------------


def _scan_lines_ro(path: str) -> list:
    """Like journal._scan_lines but byte-tolerant: undecodable bytes
    (media rot inside a segment) must surface as CRC findings, not
    crash the checker. Replacement characters guarantee the framed
    line's CRC fails, which is exactly the right diagnosis."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8", errors="replace") as f:
        return [ln for ln in f.read().splitlines() if ln.strip()]


def _parse_segment_ro(path: str, rel: str, newest: bool):
    """Read-only mirror of JobJournal._parse_segment: one segment ->
    (header, records, last_line_crc, findings, torn_dropped)."""
    from ..serve.journal import _line_crc, _unframe

    lines = _scan_lines_ro(path)
    header = None
    records: list = []
    last_crc = 0
    bad_at = None
    findings: list = []
    for n, line in enumerate(lines):
        rec = _unframe(line)
        if rec is None:
            if not newest:
                findings.append(Finding(
                    "journal-record", rel,
                    f"line {n + 1} fails its frame CRC in a CLOSED "
                    "segment — media rot, not a torn append",
                    corrupt=True, repairable=True,
                ))
                continue
            if bad_at is None:
                bad_at = n
            continue
        if bad_at is not None:
            findings.append(Finding(
                "journal-record", rel,
                f"line {bad_at + 1} fails its frame CRC but line "
                f"{n + 1} is valid — mid-file corruption, not a torn "
                "tail", corrupt=True, repairable=True,
            ))
            bad_at = None
        if n == 0 and isinstance(rec, dict) and rec.get("t") == "seg":
            header = rec
        elif isinstance(rec, dict):
            records.append(rec)
        last_crc = _line_crc(line)
    dropped = 0
    if bad_at is not None:
        dropped = len(lines) - bad_at
        findings.append(Finding(
            "journal-record", rel,
            f"torn tail: {dropped} unfinished line(s) at the end of "
            "the newest segment (normal kill -9 debris; replay drops "
            "them)", corrupt=False,
        ))
    return header, records, last_crc, findings, dropped


def _check_journal_dir(dirpath: str, root: str) -> tuple:
    """Verify one journal directory's segment chain; returns
    (records, findings). Mirrors JobJournal.replay() ordering/base
    semantics without opening anything for write."""
    from ..serve.journal import _SEG_RE

    rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
    findings: list = []
    rolled = []
    for name in os.listdir(dirpath):
        m = _SEG_RE.match(name)
        if m:
            rolled.append((int(m.group(1)), os.path.join(dirpath, name)))
    rolled.sort()
    segments = list(rolled)
    active = os.path.join(dirpath, _JOURNAL_ACTIVE)
    if os.path.exists(active):
        from ..serve.journal import _unframe

        active_seq = rolled[-1][0] + 1 if rolled else 0
        lines = _scan_lines_ro(active)
        if lines:
            first = _unframe(lines[0])
            if first is not None and first.get("t") == "seg":
                active_seq = int(first.get("seq", active_seq))
        segments.append((active_seq, active))
    if not segments:
        return [], findings

    parsed = []
    for seq, path in segments:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        newest = path == segments[-1][1]
        header, records, last_crc, segfinds, dropped = _parse_segment_ro(
            path, rel, newest
        )
        findings.extend(segfinds)
        if header is not None and int(header.get("seq", seq)) != seq:
            findings.append(Finding(
                "journal-chain", rel,
                f"segment header claims seq {header.get('seq')} but "
                f"sits at chain position {seq} (renamed or transplanted "
                "segment)", corrupt=True, repairable=True,
            ))
        parsed.append((seq, path, rel, header, records, last_crc))

    # replay starts at the newest BASE segment (compaction snapshot)
    start = 0
    for i, (_, _, _, header, _, _) in enumerate(parsed):
        if header is not None and header.get("base"):
            start = i
    parsed = parsed[start:]

    for k in range(1, len(parsed)):
        prev_seq, _, _, _, _, prev_crc = parsed[k - 1]
        seq, _, rel, header, _, _ = parsed[k]
        if seq != prev_seq + 1:
            findings.append(Finding(
                "journal-chain", rel_dir,
                f"segment {prev_seq + 1} is missing from the chain "
                f"(found {seq} after {prev_seq})", corrupt=True,
            ))
        if header is None:
            findings.append(Finding(
                "journal-chain", rel,
                f"segment {seq} has no header but is not the base of "
                "the chain", corrupt=True, repairable=True,
            ))
        elif int(header.get("prev", -1)) != prev_crc:
            findings.append(Finding(
                "journal-chain", rel,
                f"segment {seq} back-link CRC mismatch — the preceding "
                "segment is not the one this was rolled from (tampered "
                "or transplanted chain)", corrupt=True, repairable=True,
            ))

    records: list = []
    for _, _, _, _, recs, _ in parsed:
        records.extend(recs)
    return records, findings


# ---- chain comparison (fsck --compare) ----------------------------------


def _flatten_chain(dirpath: str):
    """One journal directory -> (base_seq, [(seq, raw_line), ...],
    findings): every valid framed line from the newest BASE onward, in
    append order, torn tail in the newest segment excluded (it is by
    definition not durable). Raw LINES, not records — replication ships
    bytes, so agreement is judged on bytes."""
    from ..serve.journal import _SEG_RE, _unframe

    segments = []
    for name in os.listdir(dirpath):
        m = _SEG_RE.match(name)
        if m:
            segments.append((int(m.group(1)),
                             os.path.join(dirpath, name)))
    segments.sort()
    active = os.path.join(dirpath, _JOURNAL_ACTIVE)
    if os.path.exists(active):
        seq = segments[-1][0] + 1 if segments else 0
        lines = _scan_lines_ro(active)
        if lines:
            first = _unframe(lines[0])
            if first is not None and first.get("t") == "seg":
                seq = int(first.get("seq", seq))
        segments.append((seq, active))

    parsed = []
    findings: list = []
    base_seq = segments[0][0] if segments else 0
    for seq, path in segments:
        rel = os.path.basename(path)
        newest = path == segments[-1][1]
        lines = _scan_lines_ro(path)
        kept = []
        for line in lines:
            rec = _unframe(line)
            if rec is None:
                if not newest:
                    findings.append(Finding(
                        "journal-record", rel,
                        "bad line in a closed segment (compare runs on "
                        "top of a chain fsck — fix that first)",
                        corrupt=True,
                    ))
                break  # torn tail: everything after is not durable
            if rec.get("t") == "seg" and kept == [] \
                    and rec.get("base"):
                base_seq = max(base_seq, seq)
            kept.append((seq, line))
        parsed.extend(kept)
    return base_seq, [p for p in parsed if p[0] >= base_seq], findings


def run_compare(dir_a: str, dir_b: str) -> FsckResult:
    """`primetpu fsck --compare A B`: frame-for-frame agreement of two
    journal chains up to the SHORTER one's durable point — the offline
    proof that a primary and a replica really are bit-identical
    (DESIGN.md §21). Chains are aligned at the newer of the two
    compaction BASEs; a divergent frame is corrupt (exit 2), one chain
    being a strict prefix of the other is clean (a follower mid
    catch-up is behind, not wrong)."""
    from ..serve.journal import _line_crc

    for d in (dir_a, dir_b):
        if not os.path.isdir(d):
            raise FsckCorrupt(f"not a directory: {d}", path=d)
    findings: list = []
    base_a, chain_a, fa = _flatten_chain(dir_a)
    base_b, chain_b, fb = _flatten_chain(dir_b)
    findings.extend(fa)
    findings.extend(fb)

    # align at the newer BASE: the chain with the older base still
    # carries pre-compaction history the other one folded away
    base = max(base_a, base_b)
    chain_a = [p for p in chain_a if p[0] >= base]
    chain_b = [p for p in chain_b if p[0] >= base]
    label = f"{dir_a} <> {dir_b}"
    checked = {"frames_a": len(chain_a), "frames_b": len(chain_b),
               "frames_compared": 0, "base_seq": base}

    if not chain_a or not chain_b:
        findings.append(Finding(
            "journal-compare", label,
            f"no overlapping segments at or past base {base} "
            f"(A starts at base {base_a}, B at {base_b}) — one side is "
            "behind a compaction it never resynced from; nothing is "
            "comparable", corrupt=False,
        ))
    else:
        n = min(len(chain_a), len(chain_b))
        checked["frames_compared"] = n
        for i in range(n):
            seq_a, line_a = chain_a[i]
            seq_b, line_b = chain_b[i]
            if seq_a != seq_b or line_a != line_b:
                findings.append(Finding(
                    "journal-compare", label,
                    f"frame {i} diverges: A seg {seq_a} crc "
                    f"{_line_crc(line_a)} vs B seg {seq_b} crc "
                    f"{_line_crc(line_b)} — the chains are not copies "
                    "of one history", corrupt=True,
                ))
                break

    findings.sort(key=lambda f: (f.path, f.kind, f.detail))
    return FsckResult(root=label, findings=findings, checked=checked,
                      quarantined=[])


# ---- record-stream legality --------------------------------------------


def _check_serve_records(records: list, rel_dir: str) -> list:
    """Job state-machine legality under the fold's tolerances."""
    from ..serve.jobs import _LEGAL, STATES, TERMINAL_STATES, Job

    findings: list = []
    state: dict = {}
    for rec in records:
        t = rec.get("t")
        if t == "accept":
            job = rec.get("job") or {}
            try:
                Job.from_accept_record(dict(job))
            except (TypeError, ValueError) as e:
                findings.append(Finding(
                    "job-transition", rel_dir,
                    f"unparseable accept record "
                    f"({job.get('job_id', '?')}): {e}", corrupt=True,
                ))
                continue
            state.setdefault(str(job.get("job_id")), "PENDING")
        elif t == "state":
            jid = str(rec.get("job_id"))
            new = rec.get("state")
            if new not in STATES:
                findings.append(Finding(
                    "job-transition", rel_dir,
                    f"job {jid}: unknown state {new!r}", corrupt=True,
                ))
                continue
            cur = state.get(jid)
            if cur is None:
                findings.append(Finding(
                    "job-transition", rel_dir,
                    f"job {jid}: state record with no accept record in "
                    "the chain (lost acceptance)", corrupt=True,
                ))
                state[jid] = new
                continue
            # fold tolerances: terminal-is-forever swallows everything
            # after the first terminal; exact-duplicate states are
            # redispatch/hedge echoes
            if cur in TERMINAL_STATES or new == cur:
                continue
            if new not in _LEGAL.get(cur, ()):
                findings.append(Finding(
                    "job-transition", rel_dir,
                    f"job {jid}: illegal transition {cur} -> {new}",
                    corrupt=True,
                ))
            state[jid] = new
    return findings


def _check_pool_records(records: list, rel_dir: str) -> list:
    """Pool-ledger unit-key consistency (DESIGN.md §17)."""
    from ..pool.units import unit_key

    findings: list = []
    keys: dict = {}  # unit_id -> {key: first-source}

    def note_key(uid: str, key, source: str):
        if not key:
            return
        seen = keys.setdefault(uid, {})
        if key not in seen:
            seen[key] = source
            if len(seen) > 1:
                srcs = ", ".join(
                    f"{k[:8]}… from {v}" for k, v in seen.items()
                )
                findings.append(Finding(
                    "ledger-key", rel_dir,
                    f"unit {uid}: conflicting unit keys in one ledger "
                    f"({srcs}) — the campaign definition changed under "
                    "a live ledger", corrupt=True,
                ))

    for rec in records:
        t = rec.get("t")
        if t == "unit":
            spec = rec.get("unit") or {}
            uid = str(spec.get("unit_id", "?"))
            stamped = spec.get("key")
            recomputed = unit_key(spec)
            if stamped and stamped != recomputed:
                findings.append(Finding(
                    "ledger-key", rel_dir,
                    f"unit {uid}: spec record does not hash to its own "
                    f"stamped key (stamped {str(stamped)[:8]}…, content "
                    f"hashes to {recomputed[:8]}…) — edited spec",
                    corrupt=True,
                ))
            note_key(uid, stamped, "unit spec")
        elif t in ("lease", "ack", "poison", "ack_dup", "suspect",
                   "verdict"):
            note_key(str(rec.get("unit_id", "?")), rec.get("key"), t)
    return findings


# ---- attestation records (DESIGN.md §24) -------------------------------


def _attest_shape(at) -> str:
    """'' when `at` is a well-formed chain payload, else what's wrong."""
    if not isinstance(at, dict):
        return f"payload is {type(at).__name__}, not a dict"
    head = at.get("head")
    if not (isinstance(head, str) and len(head) == 64
            and all(c in "0123456789abcdef" for c in head)):
        return "head is not a 64-hex sha256 digest"
    for field, lo in (("chunks", 1), ("start", 0), ("chunk_steps", 1)):
        v = at.get(field)
        if not isinstance(v, int) or isinstance(v, bool) or v < lo:
            return f"{field} is not an int >= {lo}"
    return ""


def _check_attest_records(records: list, rel_dir: str,
                          dirpath: str, root: str) -> list:
    """Attestation-record legality: payload shapes, ack->suspect chain
    continuity, suspect->verdict ordering, and static ack-vs-checkpoint
    agreement against the unit's surviving units/<uid>.npz. Purely
    structural — `primetpu audit` is the dynamic (re-execution) half."""
    findings: list = []
    last_ack: dict = {}       # unit_id -> attest of the winning ack
    open_suspect: set = set()  # units with a held divergence pending

    def bad(uid: str, t: str, why: str):
        findings.append(Finding(
            "attest-record", rel_dir,
            f"unit {uid}: {t} record carries a malformed chain payload "
            f"({why})", corrupt=True,
        ))

    for rec in records:
        t = rec.get("t")
        if t not in _ATTEST_TYPES and t != "audit":
            continue
        uid = str(rec.get("unit_id", "?"))
        at = rec.get("attest")
        if at is not None:
            why = _attest_shape(at)
            if why:
                bad(uid, t, why)
                at = None
        for h in (rec.get("held") or []):
            ha = h.get("attest") if isinstance(h, dict) else None
            if ha is not None:
                why = _attest_shape(ha)
                if why:
                    bad(uid, f"{t}.held", why)
        if t == "ack":
            last_ack[uid] = at
        elif t == "suspect":
            held = rec.get("held") or []
            prior = last_ack.get(uid)
            if prior is not None and held:
                first = held[0].get("attest") \
                    if isinstance(held[0], dict) else None
                if first != prior:
                    findings.append(Finding(
                        "attest-record", rel_dir,
                        f"unit {uid}: suspect record's first held "
                        "payload is not the chain the preceding ack "
                        "journaled — retained evidence was rewritten",
                        corrupt=True,
                    ))
            open_suspect.add(uid)
            last_ack.pop(uid, None)
        elif t == "verdict":
            if uid not in open_suspect:
                findings.append(Finding(
                    "attest-record", rel_dir,
                    f"unit {uid}: verdict record with no preceding "
                    "suspect record in the chain — a tiebreak for a "
                    "divergence nobody journaled", corrupt=True,
                ))
            open_suspect.discard(uid)
            if rec.get("outcome") == "resolved":
                last_ack[uid] = at
        elif t == "audit" and uid not in last_ack \
                and uid not in open_suspect:
            findings.append(Finding(
                "attest-record", rel_dir,
                f"unit {uid}: audit record for a unit with no acked "
                "result in the chain", corrupt=True,
            ))

    # static ack-vs-checkpoint agreement: a surviving unit checkpoint
    # must be a plausible PREFIX of the acked chain — same cadence and
    # origin, no more chunks than the ack, identical head when equal
    for uid, at in sorted(last_ack.items()):
        if at is None:
            continue
        path = os.path.join(dirpath, "units", f"{uid}.npz")
        if not os.path.isfile(path):
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            from ..sim.checkpoint import _attest_from, load_verified_npz

            ca = _attest_from(load_verified_npz(path))
        except Exception:  # noqa: BLE001 — _check_npz owns that finding
            continue
        if not (ca and ca.get("head")) or _attest_shape(ca):
            continue
        if (int(ca["start"]) != int(at["start"])
                or int(ca["chunk_steps"]) != int(at["chunk_steps"])):
            continue  # resumed/halved cadence — incomparable, not wrong
        if int(ca["chunks"]) > int(at["chunks"]):
            findings.append(Finding(
                "attest-checkpoint", rel,
                f"unit {uid}: checkpoint chain claims "
                f"{int(ca['chunks'])} chunk(s) but the acked result "
                f"committed only {int(at['chunks'])} — the checkpoint "
                "holds progress past the journaled truth",
                corrupt=True, repairable=True,
            ))
        elif int(ca["chunks"]) == int(at["chunks"]) \
                and ca["head"] != at["head"]:
            findings.append(Finding(
                "attest-checkpoint", rel,
                f"unit {uid}: checkpoint chain head disagrees with the "
                "acked result at the same chunk count — one of them "
                "was not produced by the committed execution",
                corrupt=True, repairable=True,
            ))
    return findings


# ---- checkpoints + warm cache ------------------------------------------

_CKPT_REQUIRED = {
    # kind -> members beyond the common {format, cycle_base, steps_run}
    "warm": ("steps", "trace_sha", "state_counters", "host_counters"),
    "fleet": ("configs_json", "trace_shas", "state_counters"),
    "element": ("config_json", "trace_sha", "state_counters"),
    "stream": ("config_json", "trace_sha", "state_counters"),
    "solo": ("config_json", "trace_sha", "state_counters"),
}


def _npz_kind(z: dict) -> str:
    for kind in ("warm", "fleet", "element", "stream"):
        if kind in z:
            return kind
    return "solo"


def _check_npz(path: str, rel: str) -> list:
    from ..sim.checkpoint import (
        _FORMAT,
        CheckpointCorrupt,
        load_verified_npz,
    )
    from ..stats.counters import COUNTER_NAMES

    try:
        z = load_verified_npz(path)
    except CheckpointCorrupt as e:
        return [Finding("checkpoint", rel, str(e), corrupt=True,
                        repairable=True)]
    findings: list = []
    got = int(z["format"]) if "format" in z else None
    if got != _FORMAT:
        findings.append(Finding(
            "checkpoint", rel,
            f"unsupported format {got} (this build reads {_FORMAT})",
            corrupt=True, repairable=True,
        ))
        return findings
    kind = _npz_kind(z)
    missing = [
        m for m in ("cycle_base", "steps_run") + _CKPT_REQUIRED[kind]
        if m not in z
    ]
    if missing:
        findings.append(Finding(
            "checkpoint", rel,
            f"{kind} checkpoint is missing member(s): "
            f"{', '.join(missing)}", corrupt=True, repairable=True,
        ))
        return findings
    axis = 1 if kind == "fleet" else 0
    rows = z["state_counters"].shape[axis]
    if rows != len(COUNTER_NAMES):
        findings.append(Finding(
            "checkpoint", rel,
            f"{kind} checkpoint carries {rows} counter rows but this "
            f"build defines {len(COUNTER_NAMES)}", corrupt=True,
            repairable=True,
        ))
    if kind == "warm":
        findings.extend(_check_warm(path, rel, z))
    return findings


# ---- AOT executable cache (DESIGN.md §23) ------------------------------

_EXEC_VERSION_FIELDS = ("exec_format", "ckpt_format", "jax", "jaxlib",
                        "backend", "devices")


def _check_exec_bin(path: str, rel: str) -> list:
    """One exec/*.bin entry: framing, then sidecar↔content agreement.
    The runtime degrades any of these to miss-and-recompile, so every
    finding here is about a cache that silently stopped paying, not a
    wrong simulation."""
    import struct
    import zlib

    from ..sim.exec_cache import _MAGIC, exec_key

    findings: list = []
    stem = os.path.basename(path)[:-len(".bin")]
    try:
        with open(path, "rb") as f:
            record = f.read()
    except OSError as e:
        return [Finding("exec-cache", rel, f"unreadable entry: {e}",
                        corrupt=True, repairable=True)]
    head = len(_MAGIC) + 4
    if len(record) < head or record[:len(_MAGIC)] != _MAGIC:
        return [Finding(
            "exec-cache", rel,
            "bad magic / truncated — not a serialized executable (the "
            "cache misses-and-recompiles; safe to quarantine)",
            corrupt=True, repairable=True,
        )]
    (crc,) = struct.unpack("<I", record[len(_MAGIC):head])
    if zlib.crc32(record[head:]) & 0xFFFFFFFF != crc:
        return [Finding(
            "exec-cache", rel,
            "body fails its CRC — torn write or media rot (the cache "
            "misses-and-recompiles; safe to quarantine)",
            corrupt=True, repairable=True,
        )]

    meta_path = path[:-len(".bin")] + ".json"
    if not os.path.exists(meta_path):
        findings.append(Finding(
            "exec-cache", rel,
            "exec entry has no JSON sidecar — key↔content agreement "
            "unverifiable (interrupted save; the entry itself is "
            "loadable)", corrupt=False, repairable=True,
        ))
        return findings
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        findings.append(Finding(
            "exec-cache", rel, f"unreadable sidecar: {e}",
            corrupt=True, repairable=True,
        ))
        return findings
    payload = meta.get("payload")
    if meta.get("key") != stem:
        findings.append(Finding(
            "exec-cache", rel,
            f"sidecar key {str(meta.get('key'))[:12]}… does not match "
            f"filename stem {stem[:12]}… (renamed entry)",
            corrupt=True, repairable=True,
        ))
    elif not isinstance(payload, dict):
        findings.append(Finding(
            "exec-cache", rel, "sidecar carries no key payload",
            corrupt=True, repairable=True,
        ))
    elif exec_key(payload) != stem:
        findings.append(Finding(
            "exec-cache", rel,
            "sidecar payload does not hash to the entry's address — "
            "edited payload or mismatched sidecar",
            corrupt=True, repairable=True,
        ))
    else:
        missing = [k for k in _EXEC_VERSION_FIELDS if k not in payload]
        if missing:
            findings.append(Finding(
                "exec-cache", rel,
                f"payload is missing version field(s): "
                f"{', '.join(missing)}", corrupt=True, repairable=True,
            ))
        else:
            import jax

            if (payload["jax"] != jax.__version__
                    or payload["jaxlib"] != jax.lib.__version__):
                findings.append(Finding(
                    "exec-cache", rel,
                    f"entry was lowered under jax {payload['jax']}/"
                    f"jaxlib {payload['jaxlib']}; this toolchain is "
                    f"{jax.__version__}/{jax.lib.__version__} — a dead "
                    "address the cache will never read again (prunable, "
                    "not corrupt)", corrupt=False, repairable=True,
                ))
    return findings


def _check_warm(path: str, rel: str, z: dict) -> list:
    """Sidecar ↔ filename ↔ npz agreement for one warm entry."""
    findings: list = []
    stem = os.path.basename(path)[:-len(".npz")]
    meta_path = path[:-len(".npz")] + ".json"
    if not os.path.exists(meta_path):
        findings.append(Finding(
            "warm-cache", rel,
            "warm entry has no JSON sidecar — unreachable by "
            "find_warm_states (interrupted save; safe to quarantine)",
            corrupt=False, repairable=True,
        ))
        return findings
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        findings.append(Finding(
            "warm-cache", rel, f"unreadable sidecar: {e}", corrupt=True,
            repairable=True,
        ))
        return findings
    if meta.get("key") != stem:
        findings.append(Finding(
            "warm-cache", rel,
            f"sidecar key {str(meta.get('key'))[:12]}… does not match "
            f"filename stem {stem[:12]}… (renamed entry)", corrupt=True,
            repairable=True,
        ))
    if int(meta.get("steps", -1)) != int(z["steps"]):
        findings.append(Finding(
            "warm-cache", rel,
            f"sidecar claims {meta.get('steps')} steps but the entry "
            f"holds {int(z['steps'])}", corrupt=True, repairable=True,
        ))
    if str(meta.get("trace_sha")) != bytes(z["trace_sha"]).decode():
        findings.append(Finding(
            "warm-cache", rel,
            "sidecar trace fingerprint disagrees with the entry",
            corrupt=True, repairable=True,
        ))
    return findings


# ---- the walk -----------------------------------------------------------


def run_fsck(root: str, repair: str = "none") -> FsckResult:
    """Verify every durable artifact under `root`. `repair` is "none"
    (default, purely read-only) or "quarantine" (move — never delete —
    repairable corrupt/orphan FILES into `<root>/.fsck-quarantine/`)."""
    if repair not in ("none", "quarantine"):
        raise FsckCorrupt(f"unknown --repair mode {repair!r}")
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        raise FsckCorrupt(f"not a directory: {root}", path=root)

    from ..serve.journal import _SEG_RE

    findings: list = []
    checked = {"journals": 0, "records": 0, "checkpoints": 0,
               "warm_entries": 0, "exec_entries": 0, "orphans": 0}

    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != ".fsck-quarantine"]
        names = set(filenames)
        is_journal_dir = _JOURNAL_ACTIVE in names or any(
            _SEG_RE.match(n) for n in names
        )
        journal_files = {
            n for n in names
            if n == _JOURNAL_ACTIVE or _SEG_RE.match(n)
        }
        if is_journal_dir:
            checked["journals"] += 1
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            records, jfinds = _check_journal_dir(dirpath, root)
            findings.extend(jfinds)
            checked["records"] += len(records)
            types = {r.get("t") for r in records}
            if types & _SERVE_TYPES:
                findings.extend(_check_serve_records(records, rel_dir))
            if types & _POOL_TYPES:
                findings.extend(_check_pool_records(records, rel_dir))
            if types & (_ATTEST_TYPES | {"audit"}):
                findings.extend(_check_attest_records(
                    records, rel_dir, dirpath, root))
        for name in sorted(names - journal_files):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if name.endswith(".tmp"):
                checked["orphans"] += 1
                findings.append(Finding(
                    "orphan", rel,
                    "leftover atomic-write temp file (normal kill -9 "
                    "debris; safe to quarantine)", corrupt=False,
                    repairable=True,
                ))
            elif (name.endswith((".npz", ".bin", ".json"))
                    and os.path.getsize(path) == 0):
                checked["orphans"] += 1
                findings.append(Finding(
                    "orphan", rel,
                    "zero-length artifact (ENOSPC-starved or "
                    "interrupted write; safe to quarantine)",
                    corrupt=False, repairable=True,
                ))
            elif name.endswith(".npz"):
                checked["checkpoints"] += 1
                nf = _check_npz(path, rel)
                if any(f.kind == "warm-cache" or "warm" in f.detail
                       for f in nf) or _is_warm_file(path):
                    checked["warm_entries"] += 1
                findings.extend(nf)
            elif name.endswith(".bin") and _is_exec_file(path):
                checked["exec_entries"] += 1
                findings.extend(_check_exec_bin(path, rel))
            elif name.endswith(".json") and _looks_like_sidecar(name):
                stem_path = path[:-len(".json")]
                if not (os.path.exists(stem_path + ".npz")
                        or os.path.exists(stem_path + ".bin")):
                    checked["orphans"] += 1
                    findings.append(Finding(
                        "orphan", rel,
                        "cache sidecar with no npz/bin entry (the "
                        "entry was pruned or its save was interrupted)",
                        corrupt=False, repairable=True,
                    ))

    quarantined: list = []
    if repair == "quarantine":
        qroot = os.path.join(root, ".fsck-quarantine")
        for f in findings:
            if not f.repairable or not (f.corrupt or f.kind == "orphan"):
                continue
            src = os.path.join(root, f.path)
            if not os.path.isfile(src):
                continue
            dst = os.path.join(qroot, f.path)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.move(src, dst)
            quarantined.append(f.path)

    findings.sort(key=lambda f: (f.path, f.kind, f.detail))
    return FsckResult(root=root, findings=findings, checked=checked,
                      quarantined=quarantined)


def _is_warm_file(path: str) -> bool:
    stem = os.path.basename(path)[:-len(".npz")]
    return len(stem) == 64 and all(c in "0123456789abcdef" for c in stem)


def _is_exec_file(path: str) -> bool:
    stem = os.path.basename(path)[:-len(".bin")]
    return len(stem) == 64 and all(c in "0123456789abcdef" for c in stem)


def _looks_like_sidecar(name: str) -> bool:
    stem = name[:-len(".json")]
    return len(stem) == 64 and all(c in "0123456789abcdef" for c in stem)


# ---- rendering ----------------------------------------------------------


def render_human(res: FsckResult) -> str:
    out = []
    for f in res.findings:
        tag = "CORRUPT" if f.corrupt else "note"
        out.append(f"{tag}: {f.path}: [{f.kind}] {f.detail}")
    for p in res.quarantined:
        out.append(f"quarantined: {p} -> .fsck-quarantine/{p}")
    c = res.checked
    if "frames_compared" in c:  # --compare mode
        out.append(
            f"compared {c['frames_compared']} frame(s) from base seg "
            f"{c['base_seq']} (A holds {c['frames_a']}, B holds "
            f"{c['frames_b']}): {len(res.corrupt)} corrupt, "
            f"{len(res.findings) - len(res.corrupt)} note(s)"
        )
    else:
        out.append(
            f"checked {c['journals']} journal(s) / {c['records']} "
            f"record(s), {c['checkpoints']} checkpoint(s), "
            f"{c['warm_entries']} warm entr(ies), "
            f"{c.get('exec_entries', 0)} exec entr(ies), {c['orphans']} "
            f"orphan(s): {len(res.corrupt)} corrupt, "
            f"{len(res.findings) - len(res.corrupt)} note(s)"
        )
    return "\n".join(out)


def render_json(res: FsckResult) -> str:
    return json.dumps(
        {
            "root": res.root,
            "findings": [f.as_dict() for f in res.findings],
            "quarantined": res.quarantined,
            "checked": res.checked,
            "summary": {
                "corrupt": len(res.corrupt),
                "notes": len(res.findings) - len(res.corrupt),
            },
        },
        indent=2,
        sort_keys=True,
    )
