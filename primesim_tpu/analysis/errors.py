"""Typed errors for the analysis subsystem (DESIGN.md §19).

Both verbs ride the existing CLI error contract: `primetpu` catches
these in `main()` and prints `{"error": {type, location, detail}}` on
stderr with exit code 2, exactly like TraceError / FaultConfigError /
CheckpointCorrupt. `location()` follows the same shape those errors
use: a small dict of wherever the problem is anchored.
"""

from __future__ import annotations


class AnalysisError(ValueError):
    """The analysis itself failed (unparseable source, malformed
    baseline, bad rule selection) — distinct from "findings exist",
    which is a normal exit-1 outcome for `primetpu lint`."""

    def __init__(self, msg: str, *, path: str | None = None,
                 line: int | None = None):
        super().__init__(msg)
        self.path = path
        self.line = line

    def location(self) -> dict:
        loc: dict = {}
        if self.path is not None:
            loc["path"] = self.path
        if self.line is not None:
            loc["line"] = self.line
        return loc


class FsckCorrupt(ValueError):
    """`primetpu fsck` found corruption in durable state: a broken CRC
    chain, an illegal state-machine transition, a checkpoint that fails
    its manifest, a warm-cache entry whose key disagrees with its
    content. Carries the first corrupt path plus the total count."""

    def __init__(self, msg: str, *, path: str | None = None,
                 n_corrupt: int = 0):
        super().__init__(msg)
        self.path = path
        self.n_corrupt = n_corrupt

    def location(self) -> dict:
        loc: dict = {"n_corrupt": self.n_corrupt}
        if self.path is not None:
            loc["path"] = self.path
        return loc


class RecompileError(AnalysisError):
    """The runtime recompile sentinel saw a jitted entry point compile
    more than its budget inside the guarded region — the jit-key
    invariant (one compilation per geometry, knobs traced) regressed."""

    def __init__(self, msg: str, *, growth: dict | None = None):
        super().__init__(msg)
        self.growth = dict(growth or {})

    def location(self) -> dict:
        return {"growth": self.growth}
