"""The shipped lint rules (DESIGN.md §19 invariant catalog).

Each rule checks one load-bearing, mechanically-checkable contract the
repo has converged on over PRs 1-11:

  PT-TRACED-BRANCH  traced TimingKnobs/FaultState values never reach
                    Python control flow or host casts inside the
                    simulator (they are jax-traced; branching on them
                    either crashes under jit or silently bakes one
                    knob value into the compiled program)
  PT-JIT-KEY        every jax.jit site is review-gated (the jit key
                    must stay the timing-normalized geometry), and no
                    knob-derived name appears in static_argnames
  PT-MOSAIC         kernels/ stays Mosaic-safe: core identity comes
                    from data, never pl.program_id; no dynamic-shape
                    ops outside the layouts.py idioms
  PT-DURABLE        no raw write-mode open() and no shared
                    deterministic "<path>.tmp" names on durability
                    paths — atomic_save_npz / journal append or bust
                    (the PR 10 hedged-twin bug class)
  PT-TYPED-ERR      no bare ValueError/RuntimeError on CLI-reachable
                    paths: errors users can hit must be typed with a
                    .location() so `main()` can structure them
  PT-OBS-HOOK       any function calling a self.obs.* hook keeps a
                    `self.obs is None` comparison in (an enclosing)
                    function — the obs-off path must stay fused and
                    bit-exact

Rules yield (lineno, col, message); framework mechanics (suppression,
baseline, scoping) live in lint.py.
"""

from __future__ import annotations

import ast

from .lint import rule

# Traced-pytree field names. Mirrored literally (rather than imported
# from sim.state / faults.schedule) so linting never needs jax in the
# process; test_analysis.py asserts the mirror stays in sync.
KNOB_FIELDS = frozenset({
    "quantum", "cpi", "l1_lat", "llc_lat", "link_lat", "router_lat",
    "dram_lat", "dram_service", "contention_lat", "prefetch_degree",
    "prefetch_lat",
})
FAULT_FIELDS = frozenset({
    "seed", "core_dead", "link_dead", "link_extra", "ev_step",
    "ev_kind", "ev_a", "ev_b", "flip_l1", "flip_llc", "due_rate",
})
TRACED_FIELDS = KNOB_FIELDS | FAULT_FIELDS

# Static zoo selectors (DESIGN.md §25): string-valued config fields that
# pick a compiled variant and ride the jit/exec-cache key via
# timing_normalized. The inverse contract of TRACED_FIELDS — these must
# branch in PYTHON (`if cfg.coherence == ...`), never inside traced
# select ops, or both variants compile into one program and the static
# key stops meaning anything.
SELECTOR_FIELDS = frozenset({
    "topology", "coherence", "prefetcher", "contention_model",
    "step_impl",
})
_TRACED_SELECTS = {"where", "select", "select_n", "cond", "switch"}

_HOST_CASTS = {"bool", "float", "int"}
_DYNSHAPE_OPS = {"nonzero", "flatnonzero", "unique", "argwhere"}


def _traced_attrs(node: ast.AST):
    """Attribute accesses that look like traced knob/fault fields:
    the attr is a TimingKnobs/FaultState field AND the base expression
    mentions knobs or faults (so `cfg.seed`-ish lookalikes on foreign
    objects don't fire)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in TRACED_FIELDS:
            base = ast.unparse(n.value).lower()
            if "knob" in base or "fault" in base:
                yield n


@rule(
    "PT-TRACED-BRANCH",
    "no Python control flow / host casts on traced knob or fault fields",
    scope=("/sim/", "/kernels/", "/faults/"),
)
def check_traced_branch(tree, ctx):
    hits: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While)):
            for a in _traced_attrs(node.test):
                hits[(a.lineno, a.col_offset)] = (
                    f"Python `{type(node).__name__.lower()}` on traced "
                    f"field `.{a.attr}` — traced TimingKnobs/FaultState "
                    "values must stay in jax ops (lax.cond/jnp.where), "
                    "never host control flow"
                )
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in _HOST_CASTS:
                for arg in node.args:
                    for a in _traced_attrs(arg):
                        hits[(a.lineno, a.col_offset)] = (
                            f"host cast `{node.func.id}()` on traced "
                            f"field `.{a.attr}` — forces a device sync "
                            "and bakes the knob into host state"
                        )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _TRACED_SELECTS
            and ast.unparse(node.func.value)
            in ("jnp", "np", "jax.numpy", "lax", "jax.lax")
        ):
            for arg in node.args:
                for a in ast.walk(arg):
                    if (
                        isinstance(a, ast.Attribute)
                        and a.attr in SELECTOR_FIELDS
                    ):
                        hits[(a.lineno, a.col_offset)] = (
                            f"static selector `.{a.attr}` inside traced "
                            f"`{node.func.attr}` — zoo selectors are jit-"
                            "key statics; branch in Python so only the "
                            "selected variant compiles"
                        )
    for (lineno, col), msg in sorted(hits.items()):
        yield lineno, col, msg


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


@rule(
    "PT-JIT-KEY",
    "jit sites are review-gated; no knob-derived static_argnames",
)
def check_jit_key(tree, ctx):
    for node in ast.walk(tree):
        if _is_jax_jit(node):
            yield (
                node.lineno, node.col_offset,
                "jax.jit site — the jit key must stay the timing-"
                "normalized geometry (knobs ride traced state, never "
                "static args); baseline this site once reviewed",
            )
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    yield (
                        node.lineno, node.col_offset,
                        "`from jax import jit` hides jit sites from "
                        "review — use `jax.jit` so sites stay greppable",
                    )
        elif isinstance(node, ast.Call) and any(
            _is_jax_jit(n) for n in ast.walk(node.func)
        ) or (
            isinstance(node, ast.Call)
            and any(_is_jax_jit(n) for a in node.args for n in ast.walk(a))
        ):
            for kw in node.keywords:
                if kw.arg != "static_argnames":
                    continue
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        s = c.value.lower()
                        if s in TRACED_FIELDS or "knob" in s or (
                            "fault" in s
                        ):
                            yield (
                                c.lineno, c.col_offset,
                                f"knob-derived name '{c.value}' in "
                                "static_argnames — a traced timing/"
                                "fault value in the jit key recompiles "
                                "per knob variant",
                            )


@rule(
    "PT-MOSAIC",
    "Mosaic safety: no pl.program_id core identity, no dynamic shapes",
    scope=("/kernels/",),
)
def check_mosaic(tree, ctx):
    in_layouts = ctx.relpath.endswith("layouts.py")
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            if node.attr == "program_id":
                base = ast.unparse(node.value).lower()
                if base == "pl" or "pallas" in base:
                    yield (
                        node.lineno, node.col_offset,
                        "pl.program_id as core identity — Mosaic may "
                        "re-tile the grid; core ids must arrive as "
                        "data (iota/refs), never the grid index",
                    )
            elif node.attr in _DYNSHAPE_OPS and not in_layouts:
                base = ast.unparse(node.value)
                if base in ("jnp", "np", "jax.numpy", "numpy"):
                    yield (
                        node.lineno, node.col_offset,
                        f"dynamic-shape op `{base}.{node.attr}` in a "
                        "kernel file — data-dependent shapes cannot "
                        "lower to Mosaic; keep these to layouts.py "
                        "host-side planning",
                    )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "where"
            and len(node.args) == 1
            and not in_layouts
        ):
            base = ast.unparse(node.func.value)
            if base in ("jnp", "np", "jax.numpy", "numpy"):
                yield (
                    node.lineno, node.col_offset,
                    "single-argument where() is a dynamic-shape op — "
                    "use the three-argument select form in kernels",
                )


def _open_write_mode(call: ast.Call) -> str | None:
    """The mode string if this is a write-mode builtin open(), else
    None."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and any(ch in mode for ch in "wax"):
        return mode
    return None


@rule(
    "PT-DURABLE",
    "durable writes are atomic with writer-unique temp names",
    scope=("/serve/", "/pool/", "checkpoint.py", "exec_cache.py"),
)
def check_durable(tree, ctx):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            mode = _open_write_mode(node)
            if mode is not None:
                yield (
                    node.lineno, node.col_offset,
                    f"raw write-mode open(..., '{mode}') on a "
                    "durability-scoped path — route durable bytes "
                    "through atomic_save_npz / JobJournal.append "
                    "(mkstemp + fsync + os.replace)",
                )
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Add)
            and isinstance(node.right, ast.Constant)
            and isinstance(node.right.value, str)
            and node.right.value.endswith(".tmp")
        ):
            yield (
                node.lineno, node.col_offset,
                "deterministic '<path>.tmp' temp name — two writers "
                "racing the same name can rename each other's work "
                "away (the PR 10 bug); use tempfile.mkstemp",
            )
        elif isinstance(node, ast.JoinedStr):
            parts = node.values
            if parts and isinstance(parts[-1], ast.Constant) and (
                isinstance(parts[-1].value, str)
                and parts[-1].value.endswith(".tmp")
            ):
                yield (
                    node.lineno, node.col_offset,
                    "deterministic f'...tmp' temp name — two writers "
                    "racing the same name can rename each other's "
                    "work away (the PR 10 bug); use tempfile.mkstemp",
                )


@rule(
    "PT-CHAOS-SITE",
    "durable writes and socket sends stay behind chaos fault sites",
    scope=("/serve/", "/pool/", "checkpoint.py", "exec_cache.py"),
)
def check_chaos_site(tree, ctx):
    """A function that fsyncs or sendalls on the serve/pool paths must
    also call a registered chaos hook (`chaos.durable`, `chaos.
    socket_send`, `chaos.crashpoint`, ...) so the fault-injection
    coverage of DESIGN.md §20 cannot silently rot as I/O paths are
    added. Maintenance-only paths (tail repair, dir fsync) baseline
    with a `why`."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        risky = []   # (lineno, col, what)
        covered = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if (
                f.attr == "fsync"
                and isinstance(f.value, ast.Name)
                and f.value.id == "os"
            ):
                risky.append((node.lineno, node.col_offset, "os.fsync"))
            elif f.attr == "sendall":
                risky.append((node.lineno, node.col_offset, "sendall"))
            elif (
                isinstance(f.value, ast.Name) and f.value.id == "chaos"
            ):
                covered = True
        if covered:
            continue
        for lineno, col, what in risky:
            yield (
                lineno, col,
                f"{what} in {fn.name}() without a chaos fault site — "
                "thread chaos.durable/chaos.socket_send/chaos."
                "crashpoint through this path (chaos/sites.py) or "
                "baseline it with a why",
            )


@rule(
    "PT-TYPED-ERR",
    "no bare ValueError/RuntimeError on CLI-reachable paths",
    scope=("/cli/", "/serve/", "/pool/"),
)
def check_typed_err(tree, ctx):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        name = None
        if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc, ast.Name):
            name = exc.id
        if name in ("ValueError", "RuntimeError"):
            yield (
                node.lineno, node.col_offset,
                f"bare {name} on a CLI-reachable path — raise a typed "
                "error carrying .location() (TraceError grammar) so "
                "main() can emit the structured exit-2 JSON, or "
                "baseline with the boundary that converts it",
            )


@rule(
    "PT-OBS-HOOK",
    "obs hook callers keep the dead `self.obs is None` branch",
    scope=("/sim/", "/ingest/"),
)
def check_obs_hook(tree, ctx):
    funcs = []  # (lineno, end_lineno, has_guard)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            guard = any(
                isinstance(n, ast.Compare)
                and ast.unparse(n.left) == "self.obs"
                and any(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops)
                for n in ast.walk(node)
            )
            funcs.append((node.lineno, node.end_lineno, guard))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "obs"
            and isinstance(node.func.value.value, ast.Name)
            and node.func.value.value.id == "self"
        ):
            covered = any(
                lo <= node.lineno <= hi and guard
                for lo, hi, guard in funcs
            )
            if not covered:
                yield (
                    node.lineno, node.col_offset,
                    f"self.obs.{node.func.attr}() without a `self.obs "
                    "is None` branch in an enclosing function — the "
                    "obs-off path must stay fused/bit-exact (DESIGN.md "
                    "§14 dead-branch contract)",
                )
