"""Runtime recompile sentinel: one compilation per geometry, enforced.

The fleet contract (DESIGN.md §7) is that a knob sweep is ONE
compilation — the jit key is `cfg.timing_normalized()` and every
timing/fault knob rides traced state. A regression (a knob leaking
into the static key) doesn't fail any functional test; it just
silently recompiles per element and the sweep gets slow. This
contextmanager makes that failure loud:

    with recompile_sentinel(allowed=1, watch=("fleet",)):
        FleetEngine(cfg, traces, overrides).run()

It snapshots the jit compile-cache entry count (`fn._cache_size()`,
present on jax's jitted callables) of the watched entry points on
entry and asserts on exit that no watched function grew by more than
`allowed` entries. `allowed=1` permits the first compile of a fresh
geometry; `allowed=0` guards an already-warm measurement loop
(bench.py's timed sections). If the running jax build doesn't expose
`_cache_size` the sentinel degrades to a no-op rather than failing.
"""

from __future__ import annotations

import importlib
from contextlib import contextmanager

from .errors import RecompileError

# preset name -> (module, jitted entry point attribute names)
_PRESETS = {
    "engine": ("primesim_tpu.sim.engine", ("run_loop", "run_chunk")),
    "fleet": ("primesim_tpu.sim.fleet",
              ("fleet_run_loop", "fleet_run_chunk")),
}


def _resolve(watch) -> dict:
    """Map display name -> jitted callable exposing `_cache_size`."""
    fns: dict = {}
    for w in watch if watch is not None else tuple(_PRESETS):
        if isinstance(w, str):
            if w not in _PRESETS:
                raise RecompileError(
                    f"unknown watch preset '{w}' "
                    f"(have: {', '.join(sorted(_PRESETS))})"
                )
            modname, names = _PRESETS[w]
            mod = importlib.import_module(modname)
            for name in names:
                fns[f"{w}:{name}"] = getattr(mod, name)
        else:
            fns[getattr(w, "__name__", repr(w))] = w
    return {k: f for k, f in fns.items() if hasattr(f, "_cache_size")}


class Sentinel:
    """Live view inside the guarded region (mostly for tests)."""

    def __init__(self, fns: dict):
        self._fns = fns
        self._before = {k: f._cache_size() for k, f in fns.items()}

    @property
    def active(self) -> bool:
        return bool(self._fns)

    def growth(self) -> dict:
        return {
            k: f._cache_size() - self._before[k]
            for k, f in self._fns.items()
        }


@contextmanager
def recompile_sentinel(allowed: int = 1, watch=None, label: str = ""):
    """Assert no watched jit entry point compiles more than `allowed`
    times inside the block. `watch` takes preset names ("engine",
    "fleet") and/or jitted callables; default watches both presets.
    Raises RecompileError (exit 2 via the CLI contract) on breach."""
    sentinel = Sentinel(_resolve(watch))
    yield sentinel
    growth = sentinel.growth()
    over = {k: g for k, g in growth.items() if g > allowed}
    if over:
        what = ", ".join(f"{k} compiled {g}x" for k, g in over.items())
        raise RecompileError(
            f"recompile sentinel{f' [{label}]' if label else ''}: "
            f"{what} (allowed {allowed} per geometry) — a knob likely "
            "leaked into the static jit key",
            growth=growth,
        )
