"""Execution-capture bridge: build + drive the LD_PRELOAD frontend.

Host-side half of the execution-driven mode (SURVEY.md §2 #1/#8): compiles
the native capture shim (`primesim_tpu/frontend/ptpu_capture.cpp`) on
demand, runs a real multithreaded binary under it, and loads the PTPU v3
trace it emits — the trace then drives the simulation engines exactly like
a synthetic one.

    from primesim_tpu.ingest.capture import capture_run
    trace = capture_run(["./my_pthread_app", "args"], line=64)
"""

from __future__ import annotations

import os
import subprocess
import tempfile

from ..trace.format import Trace

_FRONTEND_DIR = os.path.join(os.path.dirname(__file__), "..", "frontend")


def build_shim(out_dir: str | None = None, cxx: str = "g++") -> str:
    """Compile the capture shim (cached on mtime); returns the .so path."""
    src = os.path.abspath(os.path.join(_FRONTEND_DIR, "ptpu_capture.cpp"))
    out_dir = out_dir or os.path.abspath(_FRONTEND_DIR)
    so = os.path.join(out_dir, "libptpu_capture.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cmd = [
        cxx, "-O2", "-shared", "-fPIC", "-o", so, src, "-ldl", "-lpthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return so


def capture_run(
    cmd: list[str],
    *,
    trace_out: str | None = None,
    capture_memops: bool = True,
    line: int = 64,
    max_cores: int = 256,
    max_events: int = 1 << 20,
    memop_max_lines: int = 64,
    timeout: float | None = 120.0,
    env: dict[str, str] | None = None,
) -> Trace:
    """Run `cmd` under the capture shim and return the captured Trace."""
    so = build_shim()
    tmp = None
    if trace_out is None:
        fd, tmp = tempfile.mkstemp(suffix=".ptpu")
        os.close(fd)
        trace_out = tmp
    run_env = dict(os.environ if env is None else env)
    preload = run_env.get("LD_PRELOAD", "")
    run_env.update(
        LD_PRELOAD=(so + (" " + preload if preload else "")),
        PTPU_TRACE_OUT=trace_out,
        PTPU_CAPTURE_MEMOPS="1" if capture_memops else "0",
        PTPU_LINE=str(line),
        PTPU_MAX_CORES=str(max_cores),
        PTPU_MAX_EVENTS=str(max_events),
        PTPU_MEMOP_MAX_LINES=str(memop_max_lines),
    )
    try:
        proc = subprocess.run(
            cmd, env=run_env, timeout=timeout, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"capture_run: {cmd!r} exited {proc.returncode}\n"
                f"stderr:\n{proc.stderr}"
            )
        return Trace.load(trace_out)
    finally:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)


def capture_online(
    cmd: list[str],
    *,
    n_cores: int,
    ring_path: str | None = None,
    capture_memops: bool = True,
    line: int = 64,
    max_cores: int = 256,
    ring_records: int = 1 << 16,
    memop_max_lines: int = 64,
    retain_history: bool = True,
    env: dict[str, str] | None = None,
):
    """ONLINE execution-driven mode (SURVEY.md §2 #9): launch `cmd` under
    the capture shim in shared-memory-ring mode and return
    (process, RingSource) — feed the source to `ingest.ring.OnlineEngine`
    to simulate WHILE the target runs. The caller owns both: wait() the
    process and close() the source when the simulation returns.
    """
    from .ring import RingSource

    so = build_shim()
    if ring_path is None:
        fd, ring_path = tempfile.mkstemp(suffix=".ptpuring")
        os.close(fd)
    run_env = dict(os.environ if env is None else env)
    preload = run_env.get("LD_PRELOAD", "")
    run_env.update(
        LD_PRELOAD=(so + (" " + preload if preload else "")),
        PTPU_RING_OUT=ring_path,
        PTPU_RING_RECORDS=str(ring_records),
        PTPU_CAPTURE_MEMOPS="1" if capture_memops else "0",
        PTPU_LINE=str(line),
        PTPU_MAX_CORES=str(max_cores),
        PTPU_MEMOP_MAX_LINES=str(memop_max_lines),
    )
    proc = subprocess.Popen(
        cmd, env=run_env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # the mkstemp ring file is ours: RingSource.close() unlinks it.
        # retain_history keeps the full stream for to_trace() replay
        # comparisons; pass False for billion-event production captures
        # (memory then stays bounded by the unconsumed backlog).
        src = RingSource(
            ring_path,
            n_cores,
            unlink_on_close=True,
            retain_history=retain_history,
        )
    except Exception:
        proc.kill()
        raise
    return proc, src
