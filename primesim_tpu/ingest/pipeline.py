"""MPMD-pipelined streaming ingest — the rung-5 end-to-end path
(DESIGN.md §22, PAPERS.md: MPMD pipeline parallelism).

At 16384 cores the streaming engine's wall-clock splits into two serial
stages: the HOST window fill (gather + line-normalize O(C*W) events per
window) and the DEVICE window simulation. This module pipelines them
MPMD-style over the existing pool lease protocol:

- stage 1 (ingest): the trace is cut into fixed-size SEGMENTS — segment k
  holds every core's events [k*L, (k+1)*L) — and each segment is one pool
  work unit (`pool.units.build_ingest_units`). Worker processes
  materialize segments concurrently (line-normalized, END-padded) into
  atomic npz files under `<pool_dir>/segments/`, ahead of the simulation.
- stage 2 (sim): `PipelineStreamEngine` — a `StreamEngine` whose window
  fill assembles the (simulation-dependent, per-core-cursor) dynamic
  window from resident segments instead of re-reading and re-normalizing
  the raw source. It blocks only when the ingest stage has not yet
  produced a segment the cursors need.
- stage 3 (stats): unchanged — the engine's host accumulators fold
  downstream exactly as for any streaming run, so checkpoints/resume and
  the supervisor contract are untouched.

Segment boundaries are trace-indexed (not simulation-dependent), which is
what makes stage 1 embarrassingly parallel and restartable: segments are
mutually independent units, so lease expiry, hedging, and poison verdicts
apply unchanged, and a resumed run re-uses every segment already on disk.

Bit-exactness: segments carry the SAME line-normalized event values the
plain `StreamEngine._fill_window` would produce, so the assembled window
is byte-identical and the simulated results are bit-exact vs both the
plain stream engine and a preloaded `Engine.run()`.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..trace.format import EV_END, Trace
from .stream import StreamEngine


def normalize_segment(cfg, trace: Trace, seg_index: int,
                      seg_events: int) -> tuple[np.ndarray, int]:
    """Materialize segment `seg_index` of `trace`: every core's events
    [k*L, (k+1)*L), line-normalized for `cfg`, END-padded past each
    core's real (pre-END) length. Returns (events [C, L, 4] int32,
    n_valid). Pure and deterministic — any worker produces identical
    bytes for the same unit."""
    from ..trace.format import EV_LD, EV_LOCK, EV_ST, EV_UNLOCK

    C = cfg.n_cores
    if trace.n_cores != C:
        raise ValueError(f"trace has {trace.n_cores} cores, config {C}")
    if trace.line_addressed:
        trace.line_events(cfg.line_bits)  # line-size validation only
    L = int(seg_events)
    start = int(seg_index) * L
    src = trace.events
    real_len = np.asarray(trace.lengths, dtype=np.int64) - 1
    arr = np.zeros((C, L, 4), dtype=np.int32)
    arr[:, :, 0] = EV_END
    stop = min(start + L, src.shape[1])
    if stop > start:
        n = stop - start
        # memmap sources fault in only this segment's pages
        vals = np.asarray(src[:, start:stop], dtype=np.int32)
        idx = start + np.arange(n, dtype=np.int64)
        valid = idx[None, :] < real_len[:, None]
        arr[:, :n] = np.where(valid[:, :, None], vals, arr[:, :n])
    if not trace.line_addressed:
        t = arr[:, :, 0]
        addr_ev = (
            (t == EV_LD) | (t == EV_ST) | (t == EV_LOCK) | (t == EV_UNLOCK)
        )
        arr[:, :, 2] = np.where(
            addr_ev, arr[:, :, 2] >> cfg.line_bits, arr[:, :, 2]
        )
    n_valid = int(
        np.minimum(np.maximum(real_len - start, 0), L).sum()
    )
    return arr, n_valid


def segment_path(pool_dir: str, seg_index: int) -> str:
    return os.path.join(
        str(pool_dir), "segments", f"seg-{int(seg_index):05d}.npz"
    )


def write_segment(path: str, seg_index: int, seg_events: int,
                  events: np.ndarray) -> None:
    """Atomic (tmp+rename, CRC-manifested) segment write — a reader never
    sees a torn segment, and hedged ingest twins writing the same path
    are both complete snapshots of identical bytes."""
    from ..sim.checkpoint import atomic_save_npz

    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_save_npz(
        path,
        seg_index=np.int64(seg_index),
        seg_events=np.int64(seg_events),
        events=np.asarray(events, np.int32),
    )


def read_segment(path: str, seg_index: int, seg_events: int) -> np.ndarray:
    """CRC-verified segment read, validated against the expected slot
    (a mis-addressed or stale file must not silently feed the sim)."""
    from ..sim.checkpoint import load_verified_npz

    z = load_verified_npz(path)
    if int(z["seg_index"]) != int(seg_index) or int(
        z["seg_events"]
    ) != int(seg_events):
        raise ValueError(
            f"{path}: segment identity mismatch (got seg "
            f"{int(z['seg_index'])}/L={int(z['seg_events'])}, expected "
            f"{int(seg_index)}/L={int(seg_events)})"
        )
    return z["events"]


class SegmentSpool:
    """Host-side cache of resident ingest segments for one run.

    `acquire(lo, hi)` returns {seg_index: events} for every segment in
    [lo, hi], blocking (with `wait_cb` ticks — the driver pumps the
    coordinator's lease expiry there) until the ingest stage has
    produced the missing ones. `evict_below(k)` drops segments the
    cursors have fully passed, bounding residency to the cursor spread
    plus one window."""

    def __init__(self, pool_dir: str, seg_events: int, n_segments: int,
                 wait_cb=None, poll_s: float = 0.05,
                 timeout_s: float = 600.0):
        self.pool_dir = str(pool_dir)
        self.seg_events = int(seg_events)
        self.n_segments = int(n_segments)
        self.wait_cb = wait_cb
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self._resident: dict[int, np.ndarray] = {}
        self.waits = 0  # pipeline stalls (sim outran ingest)

    def _try_load(self, k: int) -> bool:
        from ..sim.checkpoint import CheckpointCorrupt

        try:
            self._resident[k] = read_segment(
                segment_path(self.pool_dir, k), k, self.seg_events
            )
            return True
        except (FileNotFoundError, CheckpointCorrupt):
            return False  # not produced yet (or mid-rewrite); keep polling

    def acquire(self, lo: int, hi: int) -> dict[int, np.ndarray]:
        lo = max(0, int(lo))
        hi = min(int(hi), self.n_segments - 1)
        missing = [
            k for k in range(lo, hi + 1) if k not in self._resident
        ]
        deadline = time.monotonic() + self.timeout_s
        stalled = False
        while missing:
            missing = [k for k in missing if not self._try_load(k)]
            if not missing:
                break
            if not stalled:
                stalled = True
                self.waits += 1
            if self.wait_cb is not None:
                self.wait_cb()
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"ingest pipeline stalled: segment(s) {missing} not "
                    f"produced within {self.timeout_s:.0f}s (ingest "
                    "workers dead and leases unrecoverable?)"
                )
            time.sleep(self.poll_s)
        return {k: self._resident[k] for k in range(lo, hi + 1)}

    def evict_below(self, k: int) -> None:
        for j in [j for j in self._resident if j < k]:
            del self._resident[j]


class PipelineStreamEngine(StreamEngine):
    """StreamEngine fed by the ingest stage: the window fill gathers from
    resident (pre-normalized) segments instead of the raw source. The
    device loop, drain protocol, checkpoint format, and supervisor
    contract are all inherited unchanged — only where the window's bytes
    come from differs, and those bytes are identical."""

    def __init__(self, cfg, trace: Trace, spool: SegmentSpool,
                 window_events: int = 1024, mesh=None):
        if window_events > spool.seg_events:
            raise ValueError(
                f"window_events={window_events} exceeds the ingest "
                f"segment size {spool.seg_events}; a window must span at "
                "most two segments"
            )
        super().__init__(cfg, trace, window_events=window_events,
                         mesh=mesh)
        self.spool = spool

    def _fill_window(self):
        C = self.cfg.n_cores
        L = self.spool.seg_events
        buf = np.zeros((C, self.W + 1, 4), dtype=np.int32)
        buf[:, :, 0] = EV_END
        take = np.minimum(self.W, self.real_len - self.cursor)
        take = np.maximum(take, 0)
        filled = take.astype(np.int32)
        exhausted = self.cursor + take >= self.real_len
        live = take > 0
        if live.any():
            lo = int(self.cursor[live].min()) // L
            hi = int((self.cursor + take - 1)[live].max()) // L
            segs = self.spool.acquire(lo, hi)
            arr = np.concatenate(
                [segs[j] for j in range(lo, hi + 1)], axis=1
            )
            idx = (
                self.cursor[:, None]
                + np.arange(self.W, dtype=np.int64)[None, :]
                - lo * L
            )
            valid = np.arange(self.W)[None, :] < take[:, None]
            idx = np.clip(idx, 0, arr.shape[1] - 1)
            vals = np.take_along_axis(arr, idx[:, :, None], axis=1)
            buf[:, : self.W] = np.where(
                valid[:, :, None], vals, buf[:, : self.W]
            )
            self.spool.evict_below(int(self.cursor.min()) // L)
        return buf, exhausted, filled


def _spawn_ingest_worker(socket_path: str, worker_id: str):
    import subprocess
    import sys

    cmd = [
        sys.executable, "-m", "primesim_tpu.cli", "worker",
        "--connect", socket_path,
        "--worker-id", worker_id,
    ]
    # stdout is the run's JSON surface — workers must not write to it
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL)


def run_pipelined(
    cfg,
    trace: Trace,
    *,
    trace_path: str | None = None,
    synth_spec: str | None = None,
    window_events: int = 1024,
    seg_events: int | None = None,
    ingest_workers: int = 2,
    pool_dir: str | None = None,
    mesh=None,
    lease_ttl_s: float = 10.0,
    supervisor_kwargs: dict | None = None,
    max_steps: int | None = None,
    resume: bool = False,
    obs=None,
    log=None,
):
    """Drive one pipelined streaming run end-to-end: in-process pool
    coordinator over the ingest units, `ingest_workers` worker
    subprocesses, and a supervised `PipelineStreamEngine` in THIS process
    (checkpoints/resume work exactly as for any supervised stream run —
    plus segments persist under `pool_dir`, so a resumed run re-uses
    every segment already ingested). Returns (engine, supervisor,
    ingest_stats)."""
    import shutil
    import tempfile

    from ..pool.coordinator import PoolCoordinator
    from ..pool.units import DONE, build_ingest_units
    from ..sim.supervisor import RunSupervisor

    if (trace_path is None) == (synth_spec is None):
        raise ValueError(
            "run_pipelined needs exactly one of trace_path/synth_spec "
            "(the portable source spec ingest workers materialize)"
        )
    L = int(seg_events) if seg_events else max(int(window_events), 4096)
    real_max = int(
        (np.asarray(trace.lengths, dtype=np.int64) - 1).max(initial=0)
    )
    n_segments = max(1, -(-real_max // L))
    units = build_ingest_units(
        cfg, trace_path, synth_spec, L, n_segments
    )
    ephemeral = pool_dir is None
    pool_dir = pool_dir or tempfile.mkdtemp(prefix="primetpu-ingest-")
    coord = PoolCoordinator(
        units, pool_dir, lease_ttl_s=lease_ttl_s, obs=obs
    )
    pre_done = sum(
        1 for u in coord.units.values() if u["state"] == DONE
    )
    coord.start()
    if log:
        log(
            f"ingest pipeline: {n_segments} segment(s) of {L} events/core"
            f" ({pre_done} already ingested), {ingest_workers} worker(s) "
            f"on {coord.socket_path}"
        )
    workers = [
        _spawn_ingest_worker(coord.socket_path, f"ing{k}")
        for k in range(int(ingest_workers))
    ]

    def _pump():
        coord.tick()
        if not coord.done and all(w.poll() is not None for w in workers):
            # liveness: the sim must not wait forever on a dead stage
            workers.append(
                _spawn_ingest_worker(
                    coord.socket_path, f"ing{len(workers)}"
                )
            )

    spool = SegmentSpool(
        pool_dir, L, n_segments, wait_cb=_pump,
        timeout_s=max(600.0, 60.0 * lease_ttl_s),
    )
    try:
        eng = PipelineStreamEngine(
            cfg, trace, spool, window_events=int(window_events),
            mesh=mesh,
        )
        if obs is not None and hasattr(obs, "attach"):
            obs.attach(eng)
        sup = RunSupervisor(eng, **(supervisor_kwargs or {}))
        if resume:
            sup.resume()
        try:
            sup.run(
                max_steps=(
                    max_steps if max_steps else eng._default_budget()
                )
            )
        except Exception as e:
            # callers (the CLI's preemption path) need the supervisor's
            # summary even when the run stops early
            e.supervisor = sup
            raise
        ingest_stats = {
            "segments": n_segments,
            "seg_events": L,
            "segments_preingested": pre_done,
            "pipeline_stalls": spool.waits,
            "pool": coord.pool_report(),
        }
        return eng, sup, ingest_stats
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        coord.close(drained=coord.done)
        if ephemeral:
            shutil.rmtree(pool_dir, ignore_errors=True)
