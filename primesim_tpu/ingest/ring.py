"""Online execution-driven ingest — the shared-memory queue fast path
(SURVEY.md §2 #9 [DRIVER], §3.1/3.3): the C++ capture frontend
(frontend/ptpu_capture.cpp, PTPU_RING_OUT mode) streams events into
per-thread SPSC rings inside one mmap'd file, and `OnlineEngine`
simulates them WHILE the target program runs — the reference's defining
operating mode, replacing round-4's capture-to-file-then-replay.

Decoupling rule (the reference's UncoreManager bounded-queue pattern):
the host drains rings EAGERLY into unbounded per-core host buffers, so a
producer thread never blocks on the simulator's progress — only on the
host's drain cadence. A bounded ring plus an unbounded host queue cannot
deadlock against target-side pthread dependencies (a full ring held by a
thread another thread's barrier waits on would otherwise wedge both the
target and the simulation).

Simulated results are BIT-EXACT with capturing to a file and replaying:
the simulation consumes the same per-core event streams through the same
windowed `stream_loop`, and window timing never affects timing-model
results (tests/test_frontend.py proves end-to-end equality on a real
pthread binary).
"""

from __future__ import annotations

import mmap
import os
import time

import numpy as np

from ..config.machine import MachineConfig
from ..sim.engine import _ACC_BITS, stream_loop
from ..sim.state import init_state
from ..stats.counters import zero_counters
from ..trace.format import EV_BARRIER, EV_END
from .stream import absorb_stream_outputs

RING_MAGIC = 0x50525247  # 'PRRG'
RSTATE_UNUSED, RSTATE_ACTIVE, RSTATE_DONE = 0, 1, 2

_HDR_WORDS = 16  # 64-byte header, u32 words
_CTL_WORDS = 16  # 64-byte control block per ring, u32 words


class RingSource:
    """Reader side of the capture shim's mmap'd ring file.

    `drain()` moves every newly published record into per-core host
    buffers and releases the ring slots (advancing `ridx` AFTER the copy
    — the producer's release-store on `widx` orders its data writes, and
    x86 load ordering makes the acquire side implicit).
    """

    def __init__(
        self,
        path: str,
        n_cores: int,
        timeout_s: float = 30.0,
        unlink_on_close: bool = False,
        retain_history: bool = False,
    ):
        self._unlink = unlink_on_close
        self._path = path
        self.retain_history = retain_history
        t0 = time.monotonic()
        # the shim creates+sizes the file at target launch; wait for the
        # release-published magic
        while True:
            try:
                if os.path.getsize(path) >= 64:
                    self._f = open(path, "r+b")
                    self._mm = mmap.mmap(self._f.fileno(), 0)
                    # plain byte read for the probe — a numpy view would
                    # pin the mmap (BufferError on close) if we must retry
                    if int.from_bytes(self._mm[:4], "little") == RING_MAGIC:
                        break
                    self._mm.close()
                    self._f.close()
            except OSError:
                pass
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(f"ring file {path} never initialized")
            time.sleep(0.005)
        hdr = np.frombuffer(self._mm, np.uint32, _HDR_WORDS, 0)
        self.version = int(hdr[1])
        self.max_cores = int(hdr[2])
        self.records = int(hdr[3])
        self.line = int(hdr[4])
        self.flags = int(hdr[5])
        self.line_bits = (self.flags >> 8) & 0xFF
        if n_cores > self.max_cores:
            raise ValueError(
                f"ring has {self.max_cores} slots but {n_cores} cores asked"
            )
        self.n_cores = n_cores
        ctl_off = _HDR_WORDS * 4
        self._ctl64 = np.frombuffer(
            self._mm, np.uint64, self.max_cores * 8, ctl_off
        ).reshape(self.max_cores, 8)  # [widx, ridx, state|pad, dropped, ...]
        self._ctl32 = np.frombuffer(
            self._mm, np.uint32, self.max_cores * _CTL_WORDS,
            ctl_off,
        ).reshape(self.max_cores, _CTL_WORDS)
        data_off = ctl_off + self.max_cores * _CTL_WORDS * 4
        self._data = np.frombuffer(
            self._mm, np.int32, self.max_cores * self.records * 4, data_off
        ).reshape(self.max_cores, self.records, 4)
        # unbounded per-core host buffers — the decoupling queue. Chunks
        # append per drain; `read` consolidates into one array anchored at
        # `_base[c]` (the absolute index of its first event), and
        # `discard` trims consumed prefixes so the consolidation copy
        # stays bounded by the UNCONSUMED backlog, not the whole history
        # (retain_history=True keeps everything for to_trace()).
        self._chunks: list[list[np.ndarray]] = [[] for _ in range(n_cores)]
        self._solid: list[np.ndarray] = [
            np.zeros((0, 4), np.int32) for _ in range(n_cores)
        ]
        self._base = np.zeros(n_cores, np.int64)
        self.total = np.zeros(n_cores, np.int64)

    @property
    def producer_done(self) -> bool:
        hdr = np.frombuffer(self._mm, np.uint32, _HDR_WORDS, 0)
        return bool(hdr[6])

    def core_done(self, c: int) -> bool:
        state = int(self._ctl32[c, 4])
        if state == RSTATE_DONE:
            return True
        return state == RSTATE_UNUSED and self.producer_done

    def drain(self) -> int:
        """Pull all newly published records into host buffers; returns
        how many records moved."""
        moved = 0
        for c in range(self.n_cores):
            w = int(self._ctl64[c, 0])  # widx (producer release-stores)
            r = int(self._ctl64[c, 1])  # ridx (ours)
            if w == r:
                continue
            n = w - r
            lo = r % self.records
            hi = lo + n
            if hi <= self.records:
                chunk = self._data[c, lo:hi].copy()
            else:
                chunk = np.concatenate(
                    [self._data[c, lo:], self._data[c, : hi - self.records]]
                )
            self._chunks[c].append(chunk)
            self.total[c] += n
            moved += n
            self._ctl64[c, 1] = np.uint64(w)  # release the slots
        return moved

    def read(self, c: int, start: int, count: int) -> np.ndarray:
        """Events [start, start+count) of core c from the host buffers
        (must already be drained; start+count <= total[c], and start must
        not have been `discard`ed)."""
        if self._chunks[c]:
            self._solid[c] = np.concatenate([self._solid[c]] + self._chunks[c])
            self._chunks[c] = []
        lo = start - int(self._base[c])
        if lo < 0:
            raise ValueError(
                f"ring core {c}: events before {int(self._base[c])} were "
                "discarded"
            )
        return self._solid[c][lo : lo + count]

    def discard(self, c: int, upto: int) -> None:
        """Drop core c's events below absolute index `upto` (consumed by
        the simulation) — keeps online memory bounded by the backlog."""
        if self.retain_history:
            return
        drop = int(upto - self._base[c])
        if drop > 0 and self._solid[c].shape[0] >= drop:
            self._solid[c] = self._solid[c][drop:]
            self._base[c] += drop

    def dropped(self) -> int:
        return int(self._ctl64[: self.n_cores, 3].sum())

    def to_trace(self):
        """Materialize everything drained so far as a padded Trace — the
        capture-then-replay equivalent of the SAME execution (perf-based
        instruction batches are not reproducible across runs, so the
        online-vs-replay bit-exactness proof replays this stream).
        Requires `retain_history=True` (the production path discards
        consumed events)."""
        from ..trace.format import N_FIELDS, EV_END, Trace

        if self._base.any():
            raise ValueError("to_trace: history was discarded")
        C = self.n_cores
        lengths = (self.total + 1).astype(np.int32)
        max_len = int(lengths.max()) if C else 1
        events = np.zeros((C, max_len, N_FIELDS), np.int32)
        events[:, :, 0] = EV_END
        for c in range(C):
            n = int(self.total[c])
            if n:
                events[c, :n] = self.read(c, 0, n)
        return Trace(
            events, lengths, line_addressed=True, line_bits=self.line_bits
        )

    def close(self):
        # numpy views pin the mmap's exported buffer; drop them first
        self._ctl64 = self._ctl32 = self._data = None
        self._mm.close()
        self._f.close()
        if self._unlink:  # capture_online's mkstemp ring file
            try:
                os.unlink(self._path)
            except OSError:
                pass


class OnlineEngine:
    """Execution-driven simulation: drains a RingSource produced by the
    running target and simulates through the same windowed `stream_loop`
    as StreamEngine — one `window_events`-deep device window per core,
    refilled as the host buffers grow. Exits when the producer is done
    and every stream is fully consumed."""

    def __init__(
        self,
        cfg: MachineConfig,
        source: RingSource,
        window_events: int = 1024,
        poll_s: float = 0.002,
        idle_timeout_s: float = 120.0,
    ):
        if source.n_cores != cfg.n_cores:
            raise ValueError("ring n_cores != cfg.n_cores")
        if source.line_bits != cfg.line_bits:
            raise ValueError(
                f"ring captured {1 << source.line_bits}-byte lines but the "
                f"machine uses {cfg.l1.line}-byte lines"
            )
        if window_events < max(1, cfg.local_run_len + 1):
            raise ValueError(
                "window_events must cover at least one local run + 1 event"
            )
        # the shim caps per-event batches at 2^20; the streaming loop
        # drains counters every 64 steps
        if 64 * (cfg.local_run_len + 1) * (1 << 20) >= 1 << (_ACC_BITS + 1):
            raise ValueError("local_run_len too large for online ingest")
        self.cfg = cfg
        self.src = source
        self.W = int(window_events)
        self.poll_s = poll_s
        self.idle_timeout_s = idle_timeout_s
        self.cursor = np.zeros(cfg.n_cores, np.int64)
        self.state = init_state(cfg)
        self.cycle_base = np.int64(0)
        self.host_counters = zero_counters(cfg.n_cores)
        self.steps_run = 0

    def _fill_window(self, done_before_drain):
        import jax.numpy as jnp  # noqa: F401  (device arrays built here)

        C = self.cfg.n_cores
        buf = np.zeros((C, self.W + 1, 4), np.int32)
        buf[:, :, 0] = EV_END
        filled = np.zeros(C, np.int32)
        exhausted = np.zeros(C, bool)
        for c in range(C):
            avail = int(self.src.total[c] - self.cursor[c])
            take = min(self.W, avail)
            if take:
                ev = self.src.read(c, int(self.cursor[c]), take)
                if (
                    (ev[:, 0] == EV_BARRIER)
                    & (ev[:, 2] >= self.cfg.barrier_slots)
                ).any():
                    raise ValueError(
                        "captured barrier id >= cfg.barrier_slots"
                    )
                buf[c, :take] = ev
            filled[c] = take
            # exhaustion uses the DONE status observed BEFORE the last
            # drain: a thread whose exit flush landed between the drain
            # and this check has trailing events the drain missed, and
            # treating it exhausted now would silently truncate its
            # stream — the next drain picks them up instead
            exhausted[c] = done_before_drain[c] and take == avail
        return buf, exhausted, filled

    def warmup(self) -> None:
        """Compile `stream_loop` at this run's window shapes with a
        zero-step budget and block until ready (mirrors
        StreamEngine.warmup): callers that time `run()` must not bill
        one-off compilation to simulation speed."""
        import jax.numpy as jnp

        C = self.cfg.n_cores
        buf = np.zeros((C, self.W + 1, 4), np.int32)
        buf[:, :, 0] = EV_END
        out = stream_loop(
            self.cfg,
            jnp.asarray(buf),
            self.state._replace(ptr=jnp.zeros(C, jnp.int32)),
            jnp.zeros(C, bool),
            jnp.zeros(C, jnp.int32),
            jnp.asarray(0, jnp.int32),
            has_sync=True,
        )
        np.asarray(out[0].cycles)  # block until compiled

    def run(self, max_steps: int | None = None) -> None:
        import jax.numpy as jnp

        cfg = self.cfg
        C = cfg.n_cores
        budget = max_steps if max_steps is not None else 1 << 62
        last_progress = time.monotonic()
        while True:
            done_before = [self.src.core_done(c) for c in range(C)]
            self.src.drain()
            buf, exhausted, filled = self._fill_window(done_before)
            # progress requires every live core to hold a full step's
            # events (stream_loop's exit margin); otherwise poll
            need = cfg.local_run_len + 1
            live_low = (~exhausted) & (filled < np.minimum(need, self.W))
            runnable = not live_low.any()
            if runnable:
                st = self.state._replace(ptr=jnp.zeros(C, jnp.int32))
                out = stream_loop(
                    cfg,
                    jnp.asarray(buf),
                    st,
                    jnp.asarray(exhausted),
                    jnp.asarray(filled),
                    jnp.asarray(min(budget, 2**31 - 1), jnp.int32),
                    has_sync=True,  # unknown until the target finishes
                )
                k_int, consumed, at_end = absorb_stream_outputs(
                    self, out, buf
                )
                budget -= k_int
                for c in range(C):  # free consumed backlog (no-op if
                    self.src.discard(c, int(self.cursor[c]))  # retained)
                if (at_end & exhausted).all():
                    return
                if budget <= 0:
                    raise RuntimeError("online engine: step budget exhausted")
                if k_int or consumed.any():
                    last_progress = time.monotonic()
                    continue
            # waiting on the target to produce more events
            if time.monotonic() - last_progress > self.idle_timeout_s:
                raise RuntimeError(
                    "online engine: no progress for "
                    f"{self.idle_timeout_s}s (target stalled or dead; "
                    f"consumed {int(self.cursor.sum())} events)"
                )
            time.sleep(self.poll_s)

    # ---- results (Engine-compatible surface) -----------------------------

    @property
    def cycles(self) -> np.ndarray:
        return np.asarray(self.state.cycles).astype(np.int64) + self.cycle_base

    @property
    def counters(self):
        return self.host_counters
