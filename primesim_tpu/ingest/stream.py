"""Windowed (streaming) trace ingest — SURVEY.md §2 #8 / §7.

The reference's UncoreManager drains a bounded queue of frontend events;
the TPU-native equivalent streams a trace through BOUNDED device memory:
the host holds per-core cursors into the (possibly memory-mapped) event
source, uploads one `window_events`-deep window at a time, and the device
`stream_loop` simulates until some core's window runs dry — its per-STEP
exit condition fires before that core could have joined an arbitration it
would have entered with the full trace, so windowed results are BIT-EXACT
with a preloaded `Engine.run()`, LRU stamps included.

This is what makes BASELINE rung-4/5 traces (billions of events, far
beyond the [C, T, 4] device array a preloaded run needs) simulatable:
device memory is O(C * window_events), host memory is O(1) beyond the
mmapped file.

    from primesim_tpu.ingest.stream import StreamEngine
    eng = StreamEngine(cfg, Trace.load("huge.ptpu", mmap=True),
                       window_events=4096)
    eng.run()
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from ..config.machine import MachineConfig
from ..stats.counters import COUNTER_NAMES, zero_counters
from ..sim import exec_cache
from ..sim.engine import _ACC_BITS, stream_loop
from ..sim.state import init_state
from ..trace.format import (
    EV_BARRIER,
    EV_END,
    Trace,
    TraceError,
    scan_trace_meta,
)


def absorb_stream_outputs(eng, out, buf):
    """Fold one `stream_loop` dispatch's outputs into a streaming
    engine's host accumulators (64-bit counter fold with the _ACC_BITS
    carry, cycle-base advance, cursor advance) — the ONE implementation
    of the drain protocol, shared by StreamEngine and the online
    ring-fed engine so the two can never diverge. Returns
    (steps_executed, consumed, at_end_mask)."""
    import jax.numpy as jnp

    st, acc_lo, acc_hi, base_lo, base_hi, k = out
    acc = (
        (np.asarray(acc_hi).astype(np.int64) << _ACC_BITS)
        + np.asarray(acc_lo).astype(np.int64)
        + np.asarray(st.counters).astype(np.int64)
    )
    for i, name in enumerate(COUNTER_NAMES):
        eng.host_counters[name] += acc[i]
    eng.cycle_base += (
        np.int64(np.asarray(base_hi)) << _ACC_BITS
    ) + np.int64(np.asarray(base_lo))
    st = st._replace(counters=jnp.zeros_like(st.counters))
    consumed = np.asarray(st.ptr).astype(np.int64)
    k_int = int(np.asarray(k))
    eng.steps_run += k_int
    eng.state = st
    at_end = (
        buf[np.arange(eng.cfg.n_cores), np.minimum(consumed, eng.W), 0]
        == EV_END
    )
    eng.cursor += consumed
    return k_int, consumed, at_end


class StreamEngine:
    """Bounded-memory streaming runner; results bit-exact vs Engine.run."""

    def __init__(
        self,
        cfg: MachineConfig,
        trace: Trace,
        window_events: int = 1024,
        mesh=None,
    ):
        assert trace.n_cores == cfg.n_cores
        if window_events < max(1, cfg.local_run_len + 1):
            raise ValueError(
                "window_events must cover at least one local run + 1 event"
            )
        self.cfg = cfg
        self.trace = trace
        # raw (possibly mmapped) source; byte-addressed traces are
        # line-normalized PER WINDOW below so no full-array copy ever
        # materializes (v4 line-addressed traces need no conversion, but
        # their recorded line size must match — reuse the shared check)
        if trace.line_addressed:
            trace.line_events(cfg.line_bits)  # line-size validation only
        self.src = trace.events
        # one bounded-memory pass (chunked by core rows, mmap-friendly)
        # for sync presence, the max instruction batch, and barrier ids
        self.has_sync, per_ev, bad_bid = scan_trace_meta(
            trace, cfg.barrier_slots
        )
        if bad_bid:
            raise TraceError(
                f"trace uses barrier ids >= barrier_slots={cfg.barrier_slots}",
                core=bad_bid[0],
                offset=bad_bid[1],
            )
        # real (pre-END) event count per core
        self.real_len = np.asarray(trace.lengths, dtype=np.int64) - 1
        self.cursor = np.zeros(cfg.n_cores, dtype=np.int64)
        self.W = int(window_events)
        # 64-step on-device drain cadence bounds per-drain counter growth
        if 64 * (cfg.local_run_len + 1) * per_ev >= 1 << _ACC_BITS:
            raise ValueError(
                "trace's max per-event instruction batch overflows the "
                "streaming 64-step counter drain; split INS batches"
            )
        self.state = init_state(cfg)
        # multi-chip layout (DESIGN.md §22): shard the machine over the
        # mesh's "tiles" axis at init; stream_loop outputs keep it by
        # propagation, so only the per-window fresh uploads (window
        # buffer, exhausted/filled masks, the reset ptr) need explicit
        # placement — see _place_core_axis/_zero_ptr.
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.sharding import shard_state

            self.state = shard_state(mesh, self.state)
        self.cycle_base = np.int64(0)
        self.host_counters = zero_counters(cfg.n_cores)
        self.steps_run = 0
        # telemetry sink (obs.Recorder) — None skips every telemetry
        # branch in _advance_window
        self.obs = None
        self.obs_label = "stream"
        # attestation chain (attest.SoloAttest) — window-scoped: the
        # stream engine's natural chunk is the WINDOW, so its chain is
        # comparable only to another streamed run of the same trace
        # (DESIGN.md §24); None = never fingerprint
        self.attest = None

    def _fill_window(self):
        from ..trace.format import EV_LD, EV_LOCK, EV_ST, EV_UNLOCK

        C = self.cfg.n_cores
        buf = np.zeros((C, self.W + 1, 4), dtype=np.int32)
        buf[:, :, 0] = EV_END
        # vectorized fill: one gather over per-core cursors instead of an
        # O(C) Python loop (the loop was the wall at 4096-16384 cores —
        # thousands of host iterations per window refill). Peak temporaries
        # stay O(C * W), the same bound as the window itself.
        take = np.minimum(self.W, self.real_len - self.cursor)
        take = np.maximum(take, 0)
        idx = self.cursor[:, None] + np.arange(self.W, dtype=np.int64)[None, :]
        valid = idx < (self.cursor + take)[:, None]
        idx = np.minimum(idx, self.src.shape[1] - 1)
        vals = np.take_along_axis(
            self.src, idx[:, :, None], axis=1
        )  # [C, W, 4]; memmap sources fault in only the touched pages
        buf[:, : self.W] = np.where(valid[:, :, None], vals, buf[:, : self.W])
        filled = take.astype(np.int32)
        exhausted = self.cursor + take >= self.real_len
        if not self.trace.line_addressed:
            t = buf[:, :, 0]
            addr_ev = (
                (t == EV_LD) | (t == EV_ST) | (t == EV_LOCK) | (t == EV_UNLOCK)
            )
            buf[:, :, 2] = np.where(
                addr_ev, buf[:, :, 2] >> self.cfg.line_bits, buf[:, :, 2]
            )
        return buf, exhausted, filled

    def _place_core_axis(self, x):
        """Upload a host array whose leading axis is the core axis,
        sharded over the mesh when one is set (fresh uploads carry no
        sharding of their own to propagate from)."""
        a = jnp.asarray(x)
        if self.mesh is None:
            return a
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.sharding import AXIS

        return jax.device_put(a, NamedSharding(self.mesh, P(AXIS)))

    def _zero_ptr(self):
        """The per-window ptr reset, placed like state.ptr so the reset
        cannot silently drop the mesh layout mid-run."""
        return self._place_core_axis(
            np.zeros(self.cfg.n_cores, np.int32)
        )

    def warmup(self) -> None:
        """Compile `stream_loop` at this run's window shapes with a
        ZERO-step budget (the budget is a traced arg, so the real run
        reuses the compilation) and block until ready. Call before a
        wall-clock measurement, mirroring Engine.block_until_ready —
        keeping this next to run() so the warm-up and the real dispatch
        cannot desynchronize."""
        cfg = self.cfg
        buf, exhausted, filled = self._fill_window()
        out = exec_cache.call(
            stream_loop, "stream.loop",
            (cfg,),
            (
                self._place_core_axis(buf),
                self.state._replace(ptr=self._zero_ptr()),
                self._place_core_axis(exhausted),
                self._place_core_axis(filled),
                jnp.asarray(0, jnp.int32),
            ),
            {"has_sync": self.has_sync},
        )
        np.asarray(out[0].cycles)  # block until compiled

    def _advance_window(self, budget: int) -> tuple[int, bool]:
        """Dispatch ONE windowed device loop: fill, simulate until some
        core's window runs low, drain counters, advance cursors. Returns
        (steps executed, finished). After it returns, the engine is at a
        CONSISTENT CUT — cursors and state fully describe the run — which
        is what makes streaming checkpoints possible."""
        cfg = self.cfg
        t0 = time.perf_counter() if self.obs is not None else 0.0
        buf, exhausted, filled = self._fill_window()
        t1 = time.perf_counter() if self.obs is not None else 0.0
        st = self.state._replace(ptr=self._zero_ptr())
        # NOTE: no overlapped dispatch here — the next window's input is
        # produced by the host-side fill/absorb cycle itself (the very
        # work overlap would hide), so there is nothing device-side to
        # speculate. The exec cache still applies.
        out = exec_cache.call(
            stream_loop, "stream.loop",
            (cfg,),
            (
                self._place_core_axis(buf),
                st,
                self._place_core_axis(exhausted),
                self._place_core_axis(filled),
                jnp.asarray(min(budget, 2**31 - 1), jnp.int32),
            ),
            {"has_sync": self.has_sync},
        )
        t2 = time.perf_counter() if self.obs is not None else 0.0
        k_int, consumed, at_end = absorb_stream_outputs(self, out, buf)
        if self.obs is not None:
            # one sample per WINDOW (the stream engine's natural chunk);
            # absorb's host transfer synchronizes, so it includes the
            # device executing the window
            t3 = time.perf_counter()
            self.obs.chunk_committed(
                self.obs_label, k_int, t3 - t0, self.host_counters,
                phases={"fill": t1 - t0, "dispatch": t2 - t1,
                        "absorb": t3 - t2},
            )
        if self.attest is not None:
            self.attest.observe(self)
        finished = bool((at_end & exhausted).all())
        if not finished and k_int == 0 and not consumed.any():
            raise RuntimeError(
                "stream engine: no progress in a window (window_events "
                "too small for this trace shape?)"
            )
        return k_int, finished

    def _default_budget(self) -> int:
        return max(10_000_000, 64 * int(self.real_len.sum()))

    def done(self) -> bool:
        """All cores consumed their real (pre-END) events."""
        return bool((self.cursor >= self.real_len).all())

    def done_mask(self) -> np.ndarray:
        """Per-core finished mask (host-side, from the stream cursors)."""
        return self.cursor >= self.real_len

    def live_mask(self) -> np.ndarray:
        """Cores that bound the quantum window at this cut: not finished
        and not frozen at a barrier (frozen clocks legally lag
        quantum_end until release). Supervisor guard input — same
        contract as Engine.live_mask, but read from host cursors into
        the (possibly mmapped) source instead of a device ptr gather."""
        C = self.cfg.n_cores
        at = np.minimum(self.cursor, np.maximum(self.real_len - 1, 0))
        et = np.asarray(self.src[np.arange(C), at, 0])
        frozen = (et == EV_BARRIER) & (
            np.asarray(self.state.sync_flag) != 0
        )
        return (self.cursor < self.real_len) & ~frozen

    def run(self, max_steps: int | None = None) -> None:
        """Stream to completion. `max_steps` defaults to a budget derived
        from the trace's total event count (retries/spins included via a
        generous per-event multiplier) — a 10M constant would abort the
        billion-event runs this engine exists for."""
        budget = max_steps if max_steps is not None else self._default_budget()
        while True:
            k, finished = self._advance_window(budget)
            budget -= k
            if finished:
                return
            if budget <= 0:
                raise RuntimeError(
                    f"stream engine: step budget ({max_steps}) exhausted at "
                    f"{int(self.cursor.sum())}/{int(self.real_len.sum())} "
                    "events consumed — deadlocked barrier/lock, or pass a "
                    "larger max_steps"
                )

    def run_events(self, target_events: int) -> bool:
        """Advance window-by-window until at least `target_events` trace
        events are consumed in total (or the stream finishes); the natural
        pause point for a streaming checkpoint. Returns finished."""
        budget = self._default_budget()
        while int(self.cursor.sum()) < target_events:
            k, finished = self._advance_window(budget)
            budget -= k
            if finished:
                return True
            if budget <= 0:
                raise RuntimeError("stream engine: step budget exhausted")
        return False

    # ---- checkpoint / resume (SURVEY.md §5.4, streaming) -----------------

    def save_checkpoint(self, path: str) -> None:
        from ..sim.checkpoint import save_stream_checkpoint

        save_stream_checkpoint(path, self)

    def load_checkpoint(self, path: str) -> None:
        from ..sim.checkpoint import load_stream_checkpoint

        load_stream_checkpoint(path, self)

    # ---- results (Engine-compatible surface) -----------------------------

    @property
    def cycles(self) -> np.ndarray:
        return np.asarray(self.state.cycles).astype(np.int64) + self.cycle_base

    @property
    def counters(self):
        return self.host_counters
