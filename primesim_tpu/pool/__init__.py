"""primesim_tpu.pool — elastic worker pool for multi-process sweeps.

`primetpu sweep --workers N` decomposes a sweep into per-element work
units and leases them to N independent worker processes over the serve
wire protocol. Leases expire when heartbeats stop (crash/OOM-kill), the
unit re-dispatches and resumes from its last element checkpoint; a unit
that kills `poison_threshold` distinct workers is quarantined as poison;
near campaign end the coordinator hedges stragglers (first-ACK-wins).
The lease ledger is a serve `JobJournal`, so `kill -9`ing the
coordinator and restarting with the same --pool-dir replays the campaign
without re-simulating any committed chunk. See DESIGN.md §17 and README
"Elastic sweeps".

Unit/ledger helpers import eagerly; the coordinator, worker, and
campaign runner (which pull in the JAX-backed fleet) resolve lazily so
`import primesim_tpu.pool` stays cheap for protocol-only callers.
"""

from .units import (
    DEFAULT_POISON_THRESHOLD,
    DONE,
    LEASED,
    PENDING,
    POISON,
    SUSPECT,
    build_units,
    fold_unit_records,
    unit_key,
)

_LAZY = {
    "PoolCoordinator": "coordinator",
    "PoolWorker": "worker",
    "LeaseLost": "worker",
    "SimulatedCrash": "worker",
    "run_worker": "worker",
    "run_pooled_sweep": "campaign",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


__all__ = [
    "DEFAULT_POISON_THRESHOLD",
    "DONE",
    "LEASED",
    "LeaseLost",
    "PENDING",
    "POISON",
    "SUSPECT",
    "PoolCoordinator",
    "PoolWorker",
    "SimulatedCrash",
    "build_units",
    "fold_unit_records",
    "run_pooled_sweep",
    "run_worker",
    "unit_key",
]
