"""Work units — the pool's unit of dispatch (DESIGN.md §17).

A sweep campaign decomposes into one work unit per fleet element: a
self-contained, SERIALIZABLE description (effective config JSON, trace
path or synth spec, timing overrides, step budgets) that any worker
process can materialize deterministically — the same property
`serve.scheduler.materialize_workload` gives the daemon, which is what
makes re-dispatch after a worker crash bit-exact: re-running a unit from
its spec (or from its last element checkpoint) yields the identical
simulation.

The coordinator's durable state is a `serve.journal.JobJournal` in the
pool directory, holding pool record types:

    lease   {unit_id, worker, epoch, key, hedge}
    expire  {unit_id, worker, epoch}          (missed heartbeat)
    ack     {unit_id, worker, epoch, key, result, resumed_steps, attest}
    ack_dup {unit_id, worker, epoch, key, result, resumed_steps, attest}
    suspect {unit_id, key, workers, held}      (attested twins diverged)
    verdict {unit_id, key, outcome, ...}       (tiebreak resolution)
    audit   {unit_id, worker, ok, attest}      (sampled re-execution)
    poison  {unit_id, key, kills}
    note    {msg}                              (operator annotations)
    drain   {}                                 (campaign completed)

`fold_unit_records` rebuilds the restart state with the same invariants
as serve's `fold_records`: duplicate-tolerant and first-ACK-wins — the
first `ack` for a unit is authoritative; later acks (the losing half of
a hedged pair, or a redelivery) are RETAINED as `ack_dup` records with
their full payload (attestation needs both sides of a hedged pair) but
never change the result. Expire records survive the fold so poison
counting spans coordinator restarts. The attestation records
(DESIGN.md §24) are order-sensitive: a `suspect` voids the unit's
result back to PENDING with both held payloads on record, and a
`verdict` either restores an authoritative result (quarantining the
divergent worker) or parks the unit in the terminal SUSPECT state.
"""

from __future__ import annotations

import hashlib
import json

#: a unit whose lease expired under K DISTINCT workers is poison — the
#: fleet-level analogue of build_fleet_isolated's element quarantine
DEFAULT_POISON_THRESHOLD = 2

# unit lifecycle states (coordinator-side). SUSPECT is distinct from
# POISON: poison marks a unit that repeatedly KILLS workers (the unit is
# the problem), suspect marks a unit whose attested results DIVERGED and
# could not be tiebroken (some worker is the problem, and we can no
# longer tell which result to trust) — see DESIGN.md §24.
PENDING = "PENDING"
LEASED = "LEASED"
DONE = "DONE"
POISON = "POISON"
SUSPECT = "SUSPECT"


def unit_key(unit: dict) -> str:
    """Content address of a unit's WORKLOAD identity (not its id): the
    ledger stamps every lease/ack with it so a restarted coordinator
    rejects replayed results whose campaign definition changed."""
    payload = {
        k: unit.get(k)
        for k in ("index", "config", "trace_path", "synth", "fold",
                  "overrides", "chunk_steps", "max_steps")
    }
    # later workload dimensions join the identity only when SET, so every
    # pre-existing ledger key (no mesh, sim-kind units) stays unchanged
    for k in ("devices", "kind", "seg_events", "seg_index"):
        if unit.get(k):
            payload[k] = unit.get(k)
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_units(
    cfg,
    trace_paths: list[str],
    synth_specs: list[str],
    overrides: list[dict],
    fold: bool,
    chunk_steps: int,
    max_steps: int,
    warm_cache: bool = False,
    devices: int = 0,
) -> list[dict]:
    """Decompose a sweep (the CLI's fan rule output: sources and
    overrides already paired 1:1) into per-element work units. Trace
    sources travel by PATH and synth sources by SPEC — workers
    materialize them locally (traces never cross the wire)."""
    sources: list[tuple[str, str]] = [("trace_path", p) for p in trace_paths]
    sources += [("synth", s) for s in synth_specs]
    if len(sources) != len(overrides):
        raise ValueError(
            f"{len(sources)} sources vs {len(overrides)} override dicts "
            "(the caller applies the fan rule first)"
        )
    cfg_json = cfg.to_json()
    units = []
    for i, ((kind, src), ov) in enumerate(zip(sources, overrides)):
        unit = {
            "unit_id": f"u{i:05d}",
            "index": i,
            "config": cfg_json,
            "trace_path": src if kind == "trace_path" else None,
            "synth": src if kind == "synth" else None,
            "fold": bool(fold),
            "overrides": dict(ov),
            "chunk_steps": int(chunk_steps),
            "max_steps": int(max_steps),
            "warm_cache": bool(warm_cache),
        }
        if devices:
            # mesh shape is part of the leased workload's identity: an
            # acked result must have been produced on the geometry bucket
            # the campaign asked for (shard x vmap, DESIGN.md §22)
            unit["devices"] = int(devices)
        unit["key"] = unit_key(unit)
        units.append(unit)
    return units


def build_ingest_units(
    cfg,
    trace_path: str | None,
    synth_spec: str | None,
    seg_events: int,
    n_segments: int,
    chunk_steps: int = 0,
) -> list[dict]:
    """Decompose a rung-scale streaming run's INGEST stage into one work
    unit per fixed-size trace segment (MPMD pipeline stage 1, DESIGN.md
    §22): unit k materializes per-core events [k*L, (k+1)*L) of the
    source — line-normalized, END-padded — into an atomic npz under the
    pool dir. Segments are mutually independent, so the existing lease
    protocol (hedging, poison, resume) applies unchanged."""
    if (trace_path is None) == (synth_spec is None):
        # caller contract, not a user-reachable path: the CLI rejects a
        # bad --trace/--synth combination before building units
        # ptlint: allow=PT-TYPED-ERR
        raise ValueError("ingest units need exactly one of trace/synth source")
    cfg_json = cfg.to_json()
    units = []
    for k in range(n_segments):
        unit = {
            "unit_id": f"g{k:05d}",
            "index": k,
            "kind": "ingest",
            "config": cfg_json,
            "trace_path": trace_path,
            "synth": synth_spec,
            "fold": False,
            "overrides": {},
            "chunk_steps": int(chunk_steps),
            "max_steps": 0,
            "seg_events": int(seg_events),
            "seg_index": k,
        }
        unit["key"] = unit_key(unit)
        units.append(unit)
    return units


def fold_unit_records(records: list[dict]):
    """Fold a replayed pool ledger into restart state:
    `(units, clean_drain)` where `units` maps unit_id -> {result,
    result_epoch, kills, max_epoch, poison, resumed_steps}.

    Invariants (tested under duplicates and out-of-order delivery):
    - first ACK wins: the first `ack` per unit is kept verbatim; every
      later ack for that unit is a discarded duplicate, whatever its
      epoch says;
    - an `ack` is authoritative even when its `lease` record was never
      seen (out-of-order append across a torn tail);
    - `expire` records accumulate DISTINCT workers per unit (poison
      evidence survives coordinator restarts); expires arriving after
      the ack don't un-finish the unit;
    - `poison` marks stick unless the unit also has a result (a hedged
      twin finished before the poison verdict landed — the result wins,
      the campaign keeps the data)."""
    units: dict[str, dict] = {}
    clean_drain = False

    def _u(unit_id: str) -> dict:
        return units.setdefault(
            unit_id,
            {"result": None, "result_epoch": None, "kills": set(),
             "max_epoch": 0, "poison": False, "resumed_steps": 0,
             "key": None, "attest": None, "ack_worker": None,
             "dup_acks": [], "suspects": set(), "held": [],
             "suspect": None, "audits": []},
        )

    for rec in records:
        t = rec.get("t")
        if t == "unit":
            # dynamic-mode spec record (coordinator enqueue); the spec
            # itself is consumed by the coordinator's recovery pass —
            # here it only breaks a trailing drain
            clean_drain = False
        elif t == "lease":
            u = _u(str(rec["unit_id"]))
            u["max_epoch"] = max(u["max_epoch"], int(rec.get("epoch", 0)))
            u["key"] = u["key"] or rec.get("key")
            clean_drain = False
        elif t == "expire":
            u = _u(str(rec["unit_id"]))
            u["kills"].add(str(rec.get("worker", "?")))
            u["max_epoch"] = max(u["max_epoch"], int(rec.get("epoch", 0)))
            clean_drain = False
        elif t == "ack":
            u = _u(str(rec["unit_id"]))
            if u["result"] is None:  # first ACK wins; duplicates discarded
                u["result"] = rec.get("result")
                u["result_epoch"] = int(rec.get("epoch", 0))
                u["resumed_steps"] = int(rec.get("resumed_steps", 0))
                u["key"] = rec.get("key") or u["key"]
                u["attest"] = rec.get("attest")
                u["ack_worker"] = rec.get("worker")
            u["max_epoch"] = max(u["max_epoch"], int(rec.get("epoch", 0)))
            clean_drain = False
        elif t == "ack_dup":
            # the losing half of a hedged pair (or an audit re-run),
            # retained with its FULL payload so cross-checks and
            # post-hoc audits can see both sides — never authoritative
            u = _u(str(rec["unit_id"]))
            u["dup_acks"].append({
                "worker": str(rec.get("worker", "?")),
                "epoch": int(rec.get("epoch", 0)),
                "result": rec.get("result"),
                "resumed_steps": int(rec.get("resumed_steps", 0)),
                "attest": rec.get("attest"),
                "audit": bool(rec.get("audit")),
            })
            u["max_epoch"] = max(u["max_epoch"], int(rec.get("epoch", 0)))
            clean_drain = False
        elif t == "suspect":
            # attested twins diverged: the unit's result is VOIDED back
            # to pending, both held payloads stay on record, and the
            # divergent workers are barred from re-running this unit
            u = _u(str(rec["unit_id"]))
            u["result"] = None
            u["result_epoch"] = None
            u["resumed_steps"] = 0
            u["attest"] = None
            u["ack_worker"] = None
            u["suspect"] = "pending"
            u["suspects"] |= {str(w) for w in rec.get("workers", [])}
            u["held"] = list(rec.get("held") or [])
            clean_drain = False
        elif t == "verdict":
            u = _u(str(rec["unit_id"]))
            if rec.get("outcome") == "resolved":
                u["result"] = rec.get("result")
                u["result_epoch"] = int(rec.get("epoch", 0))
                u["resumed_steps"] = int(rec.get("resumed_steps", 0))
                u["attest"] = rec.get("attest")
                u["ack_worker"] = rec.get("worker")
                u["suspect"] = None
                u["suspects"] |= {
                    str(w) for w in rec.get("quarantined", [])}
                u["held"] = []
            else:  # unresolved: three mutually-divergent results
                u["suspect"] = "terminal"
                u["held"] = list(rec.get("held") or u["held"])
            clean_drain = False
        elif t == "audit":
            u = _u(str(rec["unit_id"]))
            u["audits"].append({
                "worker": str(rec.get("worker", "?")),
                "ok": rec.get("ok"),
            })
            clean_drain = False
        elif t == "poison":
            u = _u(str(rec["unit_id"]))
            if u["result"] is None:
                u["poison"] = True
                u["kills"] |= {str(w) for w in rec.get("kills", [])}
            clean_drain = False
        elif t == "drain":
            clean_drain = True
    return units, clean_drain


def pool_compactor(records: list[dict]) -> list[dict]:
    """Compaction fold for the POOL ledger (`JobJournal(compactor=...)`):
    re-emit the minimal record list whose `fold_unit_records` equals the
    original history's. Per unit, in first-seen order:

    - the first `unit` spec record (dynamic-mode enqueues — the
      coordinator's recovery pass rebuilds specs from these);
    - one synthetic `lease` carrying the fold's `max_epoch` and `key`
      (worker "compact" — the fold only reads epoch/key from leases);
    - one `expire` per distinct killer (poison evidence must survive);
    - the authoritative `ack` (result, result_epoch, resumed_steps) or
      the `poison` verdict, whichever the fold kept;
    - the trailing `drain` when the history ended clean.

    `max_epoch >= result_epoch` always holds in a real fold (the ack
    itself raises max_epoch), so re-folding the compacted list restores
    both epochs exactly.

    Attestation history (ack_dup / suspect / verdict / audit records,
    DESIGN.md §24) is EVIDENCE, not just state — compaction re-emits a
    unit's full ack/attestation flow verbatim, in original order,
    whenever any such record exists, because the fold of that flow is
    order-sensitive and post-hoc audits need both sides of every
    divergence."""
    specs: dict[str, dict] = {}
    flows: dict[str, list] = {}
    _FLOW = ("ack", "ack_dup", "suspect", "verdict", "audit")
    for rec in records:
        t = rec.get("t")
        if t == "unit":
            spec = rec.get("unit") or {}
            uid = str(spec.get("unit_id", ""))
            if uid and uid not in specs:
                specs[uid] = rec
        elif t in _FLOW:
            flows.setdefault(str(rec.get("unit_id", "")), []).append(rec)
    units, clean = fold_unit_records(records)
    out: list[dict] = []
    for unit_id, u in units.items():
        if unit_id in specs:
            out.append(specs[unit_id])
        if u["max_epoch"] or u["key"]:
            out.append({"t": "lease", "unit_id": unit_id,
                        "worker": "compact", "epoch": u["max_epoch"],
                        "key": u["key"]})
        for worker in sorted(u["kills"]):
            out.append({"t": "expire", "unit_id": unit_id,
                        "worker": worker, "epoch": 0})
        flow = flows.get(unit_id, [])
        if any(r.get("t") != "ack" for r in flow):
            out.extend(flow)
            if u["poison"] and u["result"] is None:
                out.append({"t": "poison", "unit_id": unit_id,
                            "key": u["key"], "kills": sorted(u["kills"])})
        elif u["result"] is not None:
            out.append({"t": "ack", "unit_id": unit_id,
                        "worker": u["ack_worker"] or "compact",
                        "epoch": u["result_epoch"], "key": u["key"],
                        "result": u["result"],
                        "resumed_steps": u["resumed_steps"],
                        **({"attest": u["attest"]} if u["attest"]
                           else {})})
        elif u["poison"]:
            out.append({"t": "poison", "unit_id": unit_id,
                        "key": u["key"], "kills": sorted(u["kills"])})
    # spec records for units never leased/acked yet (queued work must
    # survive compaction too)
    for uid, rec in specs.items():
        if uid not in units:
            out.append(rec)
    if clean:
        out.append({"t": "drain"})
    return out
