"""Pooled sweep campaign — `primetpu sweep --workers N` (DESIGN.md §17).

Runs the coordinator in-process and N `primetpu worker` subprocesses
against its socket. The campaign loop only bookkeeps: tick the
coordinator (lease expiry), babysit the worker processes, and emit the
per-element JSON lines — in fleet-index order, byte-compatible with the
in-process sweep path — once every unit is DONE or POISON.

Worker deaths are NOT monitored through the process table: the lease
protocol is the failure detector, so a `kill -9`'d worker is detected by
its heartbeat going silent exactly like a worker on another machine
would be. The campaign watches pids for one thing only — LIVENESS: if
every worker is dead while units remain, it spawns a replacement (a
campaign must not hang because the OOM killer got lucky N times).

Chaos hook: PRIMETPU_POOL_CRASH="w0:3" makes worker w0 SIGKILL itself at
its 3rd committed chunk — the deterministic stand-in the crash-recovery
tests use when pgrep racing would flake. The env var is now a documented
ALIAS over the chaos crashpoint registry (DESIGN.md §20): it maps to
`--crash-after-chunks`, which the worker turns into a one-event
FaultPlan firing `kill` at the Nth `worker.post-checkpoint` arrival.
Richer fault schedules use PRIMETPU_CHAOS_PLAN (a plan JSON path) via
`primetpu chaos`.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

from .coordinator import PoolCoordinator
from .units import DONE, POISON, SUSPECT, build_units


def _fan_sources(ns):
    """The sweep fan rule (cli.cmd_sweep) applied to RAW specs: returns
    (trace_paths, synth_specs, overrides) already paired 1:1, traces
    ordered before synths — the same element order the in-process path
    produces, so per-element output lines up index for index."""
    from ..cli import _parse_vary

    traces = list(ns.trace or [])
    synths = list(ns.synth or [])
    if not traces and not synths:
        raise SystemExit("sweep: need --trace FILE and/or --synth SPEC")
    ovs = [_parse_vary(s) for s in (ns.vary or [])]
    A, V = len(traces) + len(synths), len(ovs)
    if V == 0:
        ovs = [{}] * A
    elif A == 1 and V > 1:
        traces, synths = traces * V, synths * V
    elif V == 1 and A > 1:
        ovs = ovs * A
    elif A != V:
        raise SystemExit(
            f"sweep: {A} traces vs {V} --vary sets — lengths must match, "
            "or one side must be a single entry to replicate"
        )
    return traces, synths, ovs


def _check_pool_flags(ns) -> None:
    """The pool path has its own durability story (per-unit element
    checkpoints + the lease ledger); flags that configure the in-fleet
    one would silently do nothing, so they are refused loudly."""
    from ..cli import _supervised

    if _supervised(ns):
        raise SystemExit(
            "sweep: --checkpoint-*/--resume/--guard configure the "
            "in-process supervised path; with --workers every unit is "
            "checkpointed under --pool-dir automatically"
        )
    for flag, active in (
        ("--report-dir", getattr(ns, "report_dir", None)),
        ("--strict", getattr(ns, "strict", False)),
    ):
        if active:
            raise SystemExit(
                f"sweep: {flag} is not supported with --workers (the "
                "pooled report is --report; bad units quarantine into "
                "their own JSON lines)"
            )
    if ns.fork_prefix != "off":
        raise SystemExit(
            "sweep: --fork-prefix needs the shared in-process fleet; with "
            "--workers use --warm-cache on (workers fork from the "
            "warm-state cache instead)"
        )


def _crash_flag(worker_id: str) -> list[str]:
    spec = os.environ.get("PRIMETPU_POOL_CRASH", "")
    for part in spec.split(","):
        wid, _, chunks = part.partition(":")
        if wid == worker_id and chunks.isdigit():
            return ["--crash-after-chunks", chunks]
    return []


def _spawn_worker(ns, socket_path: str, worker_id: str):
    cmd = [
        sys.executable, "-m", "primesim_tpu.cli", "worker",
        "--connect", socket_path,
        "--worker-id", worker_id,
        "--warm-cache", ns.warm_cache,
        "--exec-cache", getattr(ns, "exec_cache", "off"),
        "--overlap", getattr(ns, "overlap", "off"),
        "--reconnect-timeout", str(ns.lease_ttl * 6.0),
        *_crash_flag(worker_id),
    ]
    # stdout is the campaign's JSON surface — workers must not write to
    # it; their stderr (JAX warnings, tracebacks) passes through
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL)


def run_pooled_sweep(ns, cfg) -> int:
    """The `--workers N` sweep path: coordinator + worker subprocesses.
    Emits the same per-element JSON lines as the in-process sweep, plus
    pool stats in the aggregate line. Exit 0 on a clean campaign, 3 when
    any unit was poisoned or quarantined (partial, like sweep's)."""
    from ..cli import _build_recorder, _finalize_obs

    _check_pool_flags(ns)
    traces, synths, ovs = _fan_sources(ns)
    devices = int(getattr(ns, "devices", 0) or 0)
    if devices:
        # fail the campaign up front (exit 2, typed) rather than letting
        # every worker quarantine its first unit on the same bad mesh
        from ..parallel.sharding import validate_devices

        validate_devices(cfg, devices)
    units = build_units(
        cfg, traces, synths, ovs,
        fold=ns.fold,
        chunk_steps=ns.chunk_steps,
        max_steps=ns.max_steps or 10_000_000,
        warm_cache=ns.warm_cache == "on",
        devices=devices,
    )
    ephemeral = ns.pool_dir is None
    pool_dir = ns.pool_dir or tempfile.mkdtemp(prefix="primetpu-pool-")
    rec = _build_recorder(ns)
    coord = PoolCoordinator(
        units,
        pool_dir,
        lease_ttl_s=ns.lease_ttl,
        poison_threshold=ns.poison_threshold,
        hedge=ns.hedge == "on",
        obs=rec,
        attest=getattr(ns, "attest", "off") or "off",
        audit_rate=float(getattr(ns, "audit_rate", 0.0) or 0.0),
    )
    if coord.recovered["results_adopted"]:
        print(
            f"sweep: pool ledger replayed — "
            f"{coord.recovered['results_adopted']} unit(s) already done, "
            f"{len(units) - coord.recovered['results_adopted']} to go",
            file=sys.stderr,
        )
    coord.start()
    print(
        f"sweep: pool of {ns.workers} worker(s) on {coord.socket_path} "
        f"({len(units)} units, lease ttl {ns.lease_ttl:.1f}s)",
        file=sys.stderr,
    )
    workers = [
        _spawn_worker(ns, coord.socket_path, f"w{k}")
        for k in range(ns.workers)
    ]
    respawns = 0
    t0 = time.perf_counter()
    try:
        while not coord.done:
            coord.tick()
            live = [w for w in workers if w.poll() is None]
            if not live:
                # the failure detector found them all dead and will have
                # re-dispatched their units; keep ONE replacement coming
                # so the campaign cannot hang (liveness)
                if respawns >= max(4, 2 * ns.workers):
                    print(
                        "sweep: workers keep dying and the respawn budget "
                        "is spent; abandoning the campaign",
                        file=sys.stderr,
                    )
                    break
                respawns += 1
                wid = f"w{ns.workers + respawns - 1}"
                print(f"sweep: all workers dead; spawning {wid}",
                      file=sys.stderr)
                workers.append(_spawn_worker(ns, coord.socket_path, wid))
            time.sleep(0.05)
        wall = time.perf_counter() - t0
        # campaign done: workers see {done: true} on their next lease
        # request and exit 0 on their own
        deadline = time.time() + 10.0
        for w in workers:
            try:
                w.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                w.kill()
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        coord.close(drained=coord.done)

    return _emit_campaign(ns, cfg, coord, wall, rec, _finalize_obs,
                          pool_dir, ephemeral)


def _emit_campaign(ns, cfg, coord, wall, rec, finalize_obs,
                   pool_dir: str, ephemeral: bool) -> int:
    total_ins = 0
    casualties = 0
    results = coord.results()
    for r in results:
        if r["state"] == DONE and r["result"] is not None:
            line = r["result"]
            if line.get("metric") == "simulated_MIPS":
                total_ins += int(line["detail"].get("instructions", 0))
            else:
                casualties += 1  # worker-side quarantine
            print(json.dumps(line))
        elif r["state"] == SUSPECT:
            # distinct from poison: the results diverged under
            # attestation and the tiebreak could not adjudicate — the
            # held evidence stays in the pool ledger for `primetpu
            # audit` / fsck
            casualties += 1
            print(json.dumps({
                "metric": "suspect",
                "value": None,
                "unit": None,
                "detail": {
                    "engine": "fleet",
                    "fleet_index": r["index"],
                    "unit_id": r["unit_id"],
                    "status": "suspect",
                    "workers": r["suspects"],
                    "detail": (
                        "attested results diverged and a tiebreak did "
                        "not adjudicate; all held payloads are in the "
                        "pool ledger"
                    ),
                },
            }))
        elif r["state"] == POISON:
            casualties += 1
            print(json.dumps({
                "metric": "poisoned",
                "value": None,
                "unit": None,
                "detail": {
                    "engine": "fleet",
                    "fleet_index": r["index"],
                    "unit_id": r["unit_id"],
                    "status": "poisoned",
                    "kills": r["kills"],
                    "detail": (
                        f"unit killed {len(r['kills'])} distinct "
                        "worker(s); quarantined from the campaign"
                    ),
                },
            }))
        else:  # campaign abandoned with units in flight
            casualties += 1
            print(json.dumps({
                "metric": "unfinished",
                "value": None,
                "unit": None,
                "detail": {
                    "engine": "fleet",
                    "fleet_index": r["index"],
                    "unit_id": r["unit_id"],
                    "status": r["state"].lower(),
                },
            }))
    pool = coord.pool_report()
    print(json.dumps({
        "metric": "fleet_aggregate_MIPS",
        "value": round(total_ins / max(wall, 1e-9) / 1e6, 3),
        "unit": "MIPS",
        "detail": {
            "engine": "fleet",
            "n_elements": len(results),
            "n_cores": cfg.n_cores,
            "instructions": total_ins,
            "wall_s": round(wall, 3),
            "pool": pool,
        },
    }))
    if ns.report:
        import numpy as np

        from ..stats.counters import COUNTER_NAMES
        from ..stats.report import write_report

        # per-core axes span heterogeneous units — they render zero and
        # the POOL section carries the campaign story (cmd_serve's
        # SERVICE-report convention)
        write_report(
            ns.report, cfg,
            {k: np.zeros(cfg.n_cores, np.int64) for k in COUNTER_NAMES},
            np.zeros(cfg.n_cores, np.int64),
            title="primetpu sweep --workers",
            pool=pool,
            timeline=rec.timeline_summary() if rec is not None else None,
        )
        print(f"report written to {ns.report}", file=sys.stderr)
    finalize_obs(rec)
    if casualties:
        print(
            f"sweep: partial — {casualties} of {len(results)} units "
            "poisoned/quarantined/unfinished",
            file=sys.stderr,
        )
        return 3
    if ephemeral:
        shutil.rmtree(pool_dir, ignore_errors=True)
    return 0
