"""Pool coordinator — lease-based work distribution (DESIGN.md §17).

The coordinator owns the campaign: a table of work units, a durable
ledger (the serve `JobJournal` reused verbatim), and a unix socket
speaking the same JSON-lines protocol as `primetpu serve`. Workers are
peers that PULL:

    lease      {worker}                      -> {unit, epoch, checkpoint?}
                                              | {idle, retry_after_s}
                                              | {done: true}
    heartbeat  {worker, unit_id, epoch, steps} -> {ok} | {lost: true}
    ack        {worker, unit_id, epoch, key, result, resumed_steps}
                                             -> {accepted} | {duplicate}
    status     {}                            -> campaign stats
    metrics    {}                            -> Prometheus text

Lease discipline: a grant carries an `epoch` (monotonic per unit) and a
deadline `lease_ttl_s` ahead; heartbeats renew it. A worker that stops
heartbeating — crashed, OOM-killed, wedged — has its lease EXPIRE, which
journals the kill evidence and returns the unit to PENDING for
re-dispatch, where the next worker resumes from the unit's last element
checkpoint. Expiry is the only failure detector: the coordinator never
watches pids, so workers may live anywhere the socket reaches.

Safety: a unit whose leases expired under `poison_threshold` DISTINCT
workers is quarantined as poison (it is killing whoever touches it) and
the campaign proceeds without it. Liveness: first-ACK-wins — an ack is
accepted even from an expired epoch, because units are deterministic, so
a "lost" worker that was merely slow still contributes its result.

Hedging: when PENDING runs dry but leases remain in flight, a lease
request is answered with a SPECULATIVE twin of the oldest single-leased
unit (epoch bumped). First ack wins; the loser's ack is RETAINED in the
ledger (`ack_dup`, full payload) rather than discarded.

Attestation (`attest="chain"`, DESIGN.md §24): ack records carry the
worker's per-chunk fingerprint chain head, and the coordinator CHECKS
rather than discards every duplicate — a hedged twin whose chain
disagrees with the winner's voids the result, holds both payloads, and
re-runs the unit fresh on a third worker as tiebreaker; whichever held
worker the tiebreak refutes is quarantined (refused all future leases)
under the SUSPECT state, distinct from poison. Lease grants also verify
the worker's toolchain fields (jax/jaxlib/backend — the exec-cache key
triple) so a wrong-toolchain worker is refused before computing
anything, and `audit_rate=p` re-dispatches a deterministic fraction of
DONE units to a different worker for sampled re-execution audit.
"""

from __future__ import annotations

import os
import socketserver
import threading
import time

from ..chaos import sites as chaos
from ..serve.journal import JobJournal
from ..serve.protocol import (
    encode,
    error_obj,
    make_listener,
    parse_target,
    read_line,
)
from . import units as U


class PoolCoordinator:
    def __init__(
        self,
        units: list[dict],
        pool_dir: str,
        socket_path: str | None = None,
        lease_ttl_s: float = 10.0,
        poison_threshold: int = U.DEFAULT_POISON_THRESHOLD,
        hedge: bool = True,
        obs=None,
        clock=time.monotonic,
        dynamic: bool = False,
        attest: str = "off",
        audit_rate: float = 0.0,
    ):
        self.pool_dir = str(pool_dir)
        os.makedirs(os.path.join(self.pool_dir, "units"), exist_ok=True)
        self.socket_path = socket_path or os.path.join(
            self.pool_dir, "pool.sock"
        )
        self.lease_ttl_s = float(lease_ttl_s)
        self.poison_threshold = int(poison_threshold)
        self.hedge_enabled = bool(hedge)
        # dynamic mode (the elastic front-end, DESIGN.md §18): units
        # arrive via the `enqueue` verb instead of a fixed campaign, the
        # ledger stores their specs (`unit` records), and `done` never
        # trips — idle workers wait (or --idle-exit) instead of exiting
        self.dynamic = bool(dynamic)
        self.obs = obs
        # chaos clock-skew site wraps the lease/expiry clock; with no
        # plan active this returns `clock` itself (zero overhead)
        self.clock = chaos.wrap_clock("coordinator.clock", clock)
        # segmentation + compaction keep the pool ledger bounded across
        # long services; pool_compactor preserves fold_unit_records
        self.journal = JobJournal(self.pool_dir,
                                  compactor=U.pool_compactor)
        self.journal.obs = obs

        self._lock = threading.Lock()
        # unit_id -> mutable coordinator state wrapped around the spec
        self.units: dict[str, dict] = {}
        for spec in units:
            self.units[spec["unit_id"]] = self._entry(spec)
        self.workers_seen: set[str] = set()
        self.counters = {
            "leases": 0, "expired": 0, "redispatches": 0, "hedges": 0,
            "acks": 0, "duplicates": 0, "poisoned": 0, "heartbeats": 0,
            "readoptions": 0, "enqueued": 0,
            # attestation (DESIGN.md §24)
            "attest_confirms": 0, "attest_mismatches": 0,
            "attest_incomparable": 0, "suspects": 0, "verdicts": 0,
            "audits": 0, "audits_ok": 0, "toolchain_refused": 0,
            # degraded-mode elasticity (DESIGN.md §26): acks whose lease
            # ran on a smaller mesh than requested after device loss
            "capacity_degraded": 0,
        }
        if attest not in ("off", "chain"):
            from ..attest import AttestationError
            raise AttestationError(
                f"attest must be off|chain, got {attest!r}",
                site="coordinator.init",
            )
        self.attest_mode = str(attest)
        self.audit_rate = float(audit_rate)
        # workers a tiebreak refuted: refused every future lease
        self.suspect_workers: set[str] = set()
        # unit_id -> sampled re-execution audit bookkeeping
        self.audits: dict[str, dict] = {}
        self._toolchain = None  # lazy reference triple (attest on only)
        # per-client round-robin bookkeeping for the QoS lease pick
        self._last_pick: dict[str, int] = {}
        self._pick_n = 0
        self.recovered = self._recover()
        self._srv = None
        if self.attest_mode != "off" and not self.dynamic:
            # offline audit (`primetpu audit`) replays units from the
            # ledger alone — journal each classic-campaign spec once so
            # a kill -9'd pool dir is self-describing (dynamic mode
            # already journals specs at enqueue)
            for uid, u in self.units.items():
                if uid not in self._spec_journaled:
                    self.journal.append({"t": "unit", "unit": u["spec"]})
                    self._spec_journaled.add(uid)

    @staticmethod
    def _entry(spec: dict) -> dict:
        return {
            "spec": spec,
            "state": U.PENDING,
            "epoch": 0,
            # worker -> {epoch, deadline, granted, steps, hedge}
            "leases": {},
            "kills": set(),
            "result": None,
            "resumed_steps": 0,
            # attestation (§24): the authoritative ack's chain payload
            # and worker, payloads held across a divergence, and workers
            # barred from re-running THIS unit (the divergent pair)
            "attest": None,
            "ack_worker": None,
            "held": [],
            "suspects": set(),
        }

    # ---- restart recovery ------------------------------------------------

    def _recover(self) -> dict:
        """Replay the pool ledger: adopt journaled results (matching unit
        key only — a changed campaign definition must not inherit stale
        results), poison marks, and kill evidence. Unfinished units go
        back to PENDING; their in-flight workers re-adopt their leases on
        the next heartbeat (see `_h_heartbeat`)."""
        records, dropped = self.journal.replay()
        # first pass: re-create dynamically enqueued units from their
        # journaled specs (a kill -9'd coordinator has no campaign list
        # to hand back in — the ledger IS the unit table), remember which
        # specs are already on record, and re-adopt worker quarantines
        respawned = 0
        self._spec_journaled: set[str] = set()
        for rec in records:
            t = rec.get("t")
            if t == "verdict":
                self.suspect_workers |= {
                    str(w) for w in rec.get("quarantined", [])}
                continue
            if t != "unit":
                continue
            spec = rec.get("unit") or {}
            uid = str(spec.get("unit_id", ""))
            if uid:
                self._spec_journaled.add(uid)
            if uid and uid not in self.units:
                self.units[uid] = self._entry(spec)
                respawned += 1
        folded, clean = U.fold_unit_records(records)
        adopted = stale = 0
        for unit_id, f in folded.items():
            u = self.units.get(unit_id)
            if u is None:
                stale += 1
                continue
            if f["key"] is not None and f["key"] != u["spec"]["key"]:
                stale += 1  # ledger describes a different campaign
                continue
            u["epoch"] = max(u["epoch"], f["max_epoch"])
            u["kills"] |= f["kills"]
            u["suspects"] |= f["suspects"]
            u["held"] = list(f["held"])
            if f["result"] is not None:
                u["state"] = U.DONE
                u["result"] = f["result"]
                u["resumed_steps"] = f["resumed_steps"]
                u["attest"] = f["attest"]
                u["ack_worker"] = f["ack_worker"]
                adopted += 1
                if self._audit_due(u) and not f["audits"]:
                    # the sample decision is a pure function of the unit
                    # key, so a restart re-derives exactly the audits
                    # that had not yet completed
                    self.audits[unit_id] = {
                        "state": "pending", "worker": None, "epoch": 0,
                        "orig": str(f["ack_worker"] or ""),
                        "deadline": 0.0, "tried": set(),
                    }
            elif f["suspect"] == "terminal":
                u["state"] = U.SUSPECT
            elif f["poison"]:
                u["state"] = U.POISON
            # f["suspect"] == "pending" stays PENDING: the tiebreak
            # re-dispatch survives a coordinator restart via u["held"]
        stats = {
            "ledger_records": len(records),
            "torn_tail_dropped": dropped,
            "results_adopted": adopted,
            "stale_entries": stale,
            "units_respawned": respawned,
            "clean_drain": clean,
        }
        if records:
            self.journal.note(f"pool recovered: {stats}")
        return stats

    # ---- lease bookkeeping (call with self._lock held) -------------------

    def _expire_stale(self) -> None:
        now = self.clock()
        for unit_id, u in self.units.items():
            if u["state"] != U.LEASED:
                continue
            for worker in [w for w, l in u["leases"].items()
                           if l["deadline"] < now]:
                lease = u["leases"].pop(worker)
                u["kills"].add(worker)
                self.counters["expired"] += 1
                self.journal.append({
                    "t": "expire", "unit_id": unit_id, "worker": worker,
                    "epoch": lease["epoch"],
                })
                self._pool_event("expire", unit=unit_id, worker=worker,
                                 epoch=lease["epoch"])
            if not u["leases"]:
                if len(u["kills"]) >= self.poison_threshold:
                    u["state"] = U.POISON
                    self.counters["poisoned"] += 1
                    self.journal.append({
                        "t": "poison", "unit_id": unit_id,
                        "key": u["spec"]["key"],
                        "kills": sorted(u["kills"]),
                    })
                    self._pool_event("poison", unit=unit_id,
                                     kills=len(u["kills"]))
                else:
                    u["state"] = U.PENDING  # re-dispatch on next lease
        for unit_id, a in self.audits.items():
            if a["state"] == "leased" and a["deadline"] < now:
                # audit worker went quiet: back to pending, and let the
                # same worker retry later (liveness over strictness)
                a["tried"].discard(a["worker"])
                a["state"] = "pending"
                a["worker"] = None

    def _checkpoint_rel(self, unit_id: str) -> str | None:
        rel = os.path.join("units", f"{unit_id}.npz")
        if os.path.exists(os.path.join(self.pool_dir, rel)):
            return rel
        return None

    def _grant(self, u: dict, worker: str, hedge: bool) -> dict:
        unit_id = u["spec"]["unit_id"]
        u["epoch"] += 1
        u["state"] = U.LEASED
        redispatch = bool(u["kills"]) and not hedge
        u["leases"][worker] = {
            "epoch": u["epoch"],
            "deadline": self.clock() + self.lease_ttl_s,
            "granted": self.clock(),
            "steps": 0,
            "hedge": hedge,
        }
        self.counters["leases"] += 1
        if hedge:
            self.counters["hedges"] += 1
        if redispatch:
            self.counters["redispatches"] += 1
        self.journal.append({
            "t": "lease", "unit_id": unit_id, "worker": worker,
            "epoch": u["epoch"], "key": u["spec"]["key"],
            "hedge": hedge,
        })
        # lease journaled, grant not yet delivered: the restart must
        # re-adopt or expire this lease, never lose the unit
        chaos.crashpoint("coordinator.post-lease")
        self._pool_event(
            "hedge" if hedge else ("redispatch" if redispatch else "lease"),
            unit=unit_id, worker=worker, epoch=u["epoch"],
        )
        grant = {
            "ok": True,
            "unit": u["spec"],
            "epoch": u["epoch"],
            "lease_ttl_s": self.lease_ttl_s,
            "checkpoint": self._checkpoint_rel(unit_id),
            "pool_dir": self.pool_dir,
            "hedge": hedge,
        }
        if self.attest_mode != "off":
            grant["attest"] = self.attest_mode
        if u["held"]:
            # tiebreak re-run after a divergence: no checkpoint resume,
            # no warm fork — the third chain must be comparable to both
            # held chains, and a held worker's checkpoint could carry
            # the very corruption under adjudication
            grant["fresh"] = True
            grant["checkpoint"] = None
        return grant

    def _hedge_candidate(self, worker: str) -> dict | None:
        """Oldest single-leased in-flight unit not already held by this
        worker — the straggler most worth a speculative twin."""
        best = None
        for u in self.units.values():
            if u["state"] != U.LEASED or worker in u["leases"]:
                continue
            if len(u["leases"]) != 1:
                continue  # one hedge twin at a time
            granted = min(l["granted"] for l in u["leases"].values())
            if best is None or granted < best[0]:
                best = (granted, u)
        return best[1] if best else None

    # ---- verb handlers ---------------------------------------------------

    def handle(self, req: dict) -> dict:
        verb = req.get("verb")
        try:
            if verb == "metrics":
                # rendered OUTSIDE the lock: render_pool_prometheus
                # calls stats(), which takes it (non-reentrant)
                from ..obs.prom import render_pool_prometheus

                return {
                    "ok": True,
                    "content_type": "text/plain; version=0.0.4",
                    "text": render_pool_prometheus(self),
                }
            with self._lock:
                if verb == "lease":
                    return self._h_lease(req)
                if verb == "heartbeat":
                    return self._h_heartbeat(req)
                if verb == "ack":
                    return self._h_ack(req)
                if verb == "enqueue":
                    return self._h_enqueue(req)
                if verb == "collect":
                    return self._h_collect(req)
                if verb == "status":
                    return {"ok": True, **self._stats()}
                raise ValueError(f"unknown verb {verb!r}")
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return {"ok": False, **error_obj(e)}

    def _h_lease(self, req: dict) -> dict:
        worker = str(req.get("worker", "anon"))
        self.workers_seen.add(worker)
        self._expire_stale()
        if self.attest_mode != "off":
            refused = self._verify_worker(worker, req)
            if refused is not None:
                return refused
        pending = [u for u in self.units.values()
                   if u["state"] == U.PENDING
                   and worker not in u["suspects"]]
        if pending:
            u = min(pending, key=self._pick_key)
            self._pick_n += 1
            self._last_pick[
                str(u["spec"].get("client", "anon"))
            ] = self._pick_n
            return self._grant(u, worker, hedge=False)
        audit = self._audit_candidate(worker)
        if audit is not None:
            return self._grant_audit(audit, worker)
        if self.done:
            return {"ok": True, "done": True}
        if self.hedge_enabled:
            u = self._hedge_candidate(worker)
            if u is not None:
                return self._grant(u, worker, hedge=True)
        return {"ok": True, "idle": True,
                "retry_after_s": max(0.2, self.lease_ttl_s / 5.0)}

    def _verify_worker(self, worker: str, req: dict) -> dict | None:
        """Attested lease admission: quarantined workers and workers on
        a different toolchain are refused BEFORE they compute anything.
        Returns the refusal reply, or None to proceed."""
        from ..attest import AttestationError, toolchain_matches

        if worker in self.suspect_workers:
            e = AttestationError(
                f"worker {worker!r} is quarantined as SUSPECT (a "
                "tiebreak refuted its attested result)",
                site="coordinator.lease", unit="")
            return {"ok": False, "refused": "suspect", **error_obj(e)}
        tc = req.get("toolchain")
        if tc is not None:
            if self._toolchain is None:
                from ..attest import toolchain_fingerprint

                self._toolchain = toolchain_fingerprint()
            field = toolchain_matches(self._toolchain, tc)
            if field:
                self.counters["toolchain_refused"] += 1
                self._pool_event("toolchain_refused", worker=worker,
                                 field=field)
                e = AttestationError(
                    f"worker {worker!r} toolchain mismatch on "
                    f"{field!r}: coordinator "
                    f"{self._toolchain.get(field)!r} vs worker "
                    f"{tc.get(field)!r} — results would not be "
                    "comparable (exec-cache key fields)",
                    site="coordinator.lease", unit="")
                return {"ok": False, "refused": "toolchain",
                        **error_obj(e)}
        return None

    # ---- sampled re-execution audit (attest on, DESIGN.md §24) ----------

    def _audit_due(self, u: dict) -> bool:
        if (self.audit_rate <= 0 or self.attest_mode == "off"
                or u["spec"].get("kind") == "ingest"):
            return False
        if self.audit_rate >= 1.0:
            return True
        import hashlib

        blob = f"{u['spec']['key']}:{u['spec']['unit_id']}:audit"
        frac = int(hashlib.sha256(blob.encode()).hexdigest()[:8], 16)
        return frac / 0xFFFFFFFF < self.audit_rate

    def _audit_candidate(self, worker: str) -> str | None:
        """A pending audit this worker may serve: a DIFFERENT worker
        than the original acker, preferably. When the campaign is
        otherwise complete and nobody else will ever ask, a self-audit
        beats hanging the campaign (it still catches nondeterministic
        corruption, not a systematically-wrong worker)."""
        if not self.audits:
            return None
        live = any(u["state"] in (U.PENDING, U.LEASED)
                   for u in self.units.values())
        fallback = None
        for unit_id, a in self.audits.items():
            u = self.units.get(unit_id)
            if (a["state"] != "pending" or u is None
                    or u["state"] != U.DONE or worker in a["tried"]):
                continue
            if worker != a["orig"]:
                return unit_id
            if not live:
                fallback = fallback or unit_id
        return fallback

    def _grant_audit(self, unit_id: str, worker: str) -> dict:
        u = self.units[unit_id]
        a = self.audits[unit_id]
        u["epoch"] += 1
        a.update(state="leased", worker=worker, epoch=u["epoch"],
                 deadline=self.clock() + self.lease_ttl_s)
        a["tried"].add(worker)
        self.counters["audits"] += 1
        self.journal.append({
            "t": "lease", "unit_id": unit_id, "worker": worker,
            "epoch": u["epoch"], "key": u["spec"]["key"],
            "hedge": False, "audit": True,
        })
        self._pool_event("audit", unit=unit_id, worker=worker,
                         epoch=u["epoch"])
        return {
            "ok": True,
            "unit": u["spec"],
            "epoch": u["epoch"],
            "lease_ttl_s": self.lease_ttl_s,
            "checkpoint": None,
            "pool_dir": self.pool_dir,
            "hedge": False,
            "audit": True,
            "fresh": True,
            "attest": self.attest_mode,
        }

    def _pick_key(self, u: dict):
        """Lease pick order = the serve scheduler's QoS tiers carried
        through dispatch: priority first, then least-recently-served
        client (fairness under one chatty tenant), then campaign index
        (classic sweeps have neither and keep their index order)."""
        spec = u["spec"]
        return (
            -int(spec.get("priority", 0)),
            self._last_pick.get(str(spec.get("client", "anon")), 0),
            int(spec.get("index", 0)),
        )

    def _h_heartbeat(self, req: dict) -> dict:
        worker = str(req.get("worker", "anon"))
        unit_id = str(req.get("unit_id", ""))
        epoch = int(req.get("epoch", 0))
        self.counters["heartbeats"] += 1
        u = self.units.get(unit_id)
        a = self.audits.get(unit_id)
        if (a is not None and a["state"] == "leased"
                and a["worker"] == worker and a["epoch"] == epoch):
            a["deadline"] = self.clock() + self.lease_ttl_s
            return {"ok": True, "lease_ttl_s": self.lease_ttl_s}
        if u is None or u["state"] in (U.DONE, U.POISON, U.SUSPECT):
            return {"ok": True, "lost": True}
        lease = u["leases"].get(worker)
        if lease is None and u["state"] == U.PENDING and epoch == u["epoch"]:
            # graceful coordinator restart: the worker outlived us and is
            # still simulating the current epoch — re-adopt its lease
            # rather than wastefully re-dispatching the unit
            u["state"] = U.LEASED
            lease = u["leases"][worker] = {
                "epoch": epoch, "granted": self.clock(),
                "deadline": 0.0, "steps": 0, "hedge": False,
            }
            self.workers_seen.add(worker)
            self.counters["readoptions"] += 1
            self._pool_event("readopt", unit=unit_id, worker=worker,
                             epoch=epoch)
        if lease is None or lease["epoch"] != epoch:
            return {"ok": True, "lost": True}  # expired or superseded
        lease["deadline"] = self.clock() + self.lease_ttl_s
        lease["steps"] = int(req.get("steps", lease["steps"]))
        self._pool_event("heartbeat", unit=unit_id, worker=worker,
                         epoch=epoch, steps=lease["steps"])
        return {"ok": True, "lease_ttl_s": self.lease_ttl_s}

    def _h_ack(self, req: dict) -> dict:
        worker = str(req.get("worker", "anon"))
        unit_id = str(req.get("unit_id", ""))
        epoch = int(req.get("epoch", 0))
        u = self.units.get(unit_id)
        if u is None:
            raise KeyError(f"unknown unit {unit_id!r}")
        if str(req.get("key", "")) != u["spec"]["key"]:
            raise ValueError(
                f"{unit_id}: ack key mismatch (campaign changed under "
                "the worker?)"
            )
        if u["state"] in (U.DONE, U.SUSPECT):
            # the losing half of a hedged pair, an audit re-execution, or
            # a redelivery after a lost ack reply. First ACK already won
            # the result — but the loser's chain is evidence, not waste:
            # journal it and compare heads (DESIGN.md §24)
            return self._h_ack_dup(u, req, worker, epoch)
        if u["held"]:
            # third execution after an attested divergence: adjudicate
            return self._h_tiebreak(u, req, worker, epoch)
        # first-ACK-wins: accept even from an expired epoch — the unit is
        # deterministic, a slow-but-alive "lost" worker's result is the
        # same result
        result = req.get("result")
        resumed = int(req.get("resumed_steps", 0))
        attest = req.get("attest") if self.attest_mode != "off" else None
        rec = {
            "t": "ack", "unit_id": unit_id, "worker": worker,
            "epoch": epoch, "key": u["spec"]["key"], "result": result,
            "resumed_steps": resumed,
        }
        if attest:
            rec["attest"] = attest
        self.journal.append(rec)
        # result durable, worker not yet told: a crash here must replay
        # to DONE and fold the worker's re-ack away as a duplicate
        chaos.crashpoint("coordinator.post-ack")
        u["state"] = U.DONE
        u["result"] = result
        u["resumed_steps"] = resumed
        u["attest"] = attest
        u["ack_worker"] = worker
        u["leases"].clear()
        self.counters["acks"] += 1
        self._pool_event("ack", unit=unit_id, worker=worker, epoch=epoch,
                         resumed_steps=resumed)
        granted = (result or {}).get("detail", {}).get("devices_granted")
        if granted:
            # the worker re-leased onto a shrunken mesh (device loss):
            # book the capacity change durably so a replayed coordinator
            # and the campaign report both carry it
            self.counters["capacity_degraded"] += 1
            self.journal.append({
                "t": "note", "kind": "capacity", "unit_id": unit_id,
                "worker": worker,
                "devices_requested": int(
                    (result or {}).get("detail", {}).get("devices", 0)
                ),
                "devices_granted": int(granted),
            })
            self._pool_event("capacity_degraded", unit=unit_id,
                             worker=worker, devices_granted=int(granted))
        if (not req.get("audit") and unit_id not in self.audits
                and self._audit_due(u)):
            self.audits[unit_id] = {
                "state": "pending", "orig": worker, "worker": None,
                "epoch": 0, "deadline": 0.0, "tried": set(),
            }
        # unit checkpoint is dead weight once the result is durable
        rel = self._checkpoint_rel(unit_id)
        if rel:
            try:
                os.unlink(os.path.join(self.pool_dir, rel))
            except OSError:
                pass
        return {"ok": True, "accepted": True}

    def _h_ack_dup(self, u: dict, req: dict, worker: str,
                   epoch: int) -> dict:
        """A second execution's ack for an already-terminal unit. The
        legacy path dropped these on the floor; with attestation the
        loser's chain head is the cheapest integrity check we will ever
        get — a full independent re-execution that already happened."""
        unit_id = u["spec"]["unit_id"]
        attest = req.get("attest") if self.attest_mode != "off" else None
        is_audit = bool(req.get("audit"))
        rec = {
            "t": "ack_dup", "unit_id": unit_id, "worker": worker,
            "epoch": epoch, "key": u["spec"]["key"],
            "result": req.get("result"),
            "resumed_steps": int(req.get("resumed_steps", 0)),
        }
        if attest:
            rec["attest"] = attest
        if is_audit:
            rec["audit"] = True
        self.journal.append(rec)
        self.counters["duplicates"] += 1
        a = self.audits.get(unit_id)
        audit_closing = (is_audit and a is not None
                         and a.get("worker") == worker)
        if u["state"] == U.SUSPECT or u["attest"] is None or not attest:
            # terminal-suspect unit, attest off, or a chainless twin:
            # nothing to compare, the record alone is the retention win
            if audit_closing:
                a["state"] = "done"
            self._pool_event("duplicate", unit=unit_id, worker=worker,
                             epoch=epoch)
            return {"ok": True, "accepted": False, "duplicate": True}
        from ..attest import chain as _chain

        if not _chain.comparable(u["attest"], attest):
            # warm-forked / OOM-halved cadence: equally valid, not
            # comparable — count it, never suspect it
            self.counters["attest_incomparable"] += 1
            if audit_closing:
                a["state"] = "done"
                self.journal.append({"t": "audit", "unit_id": unit_id,
                                     "worker": worker, "ok": None})
            self._pool_event("duplicate", unit=unit_id, worker=worker,
                             epoch=epoch)
            return {"ok": True, "accepted": False, "duplicate": True}
        if _chain.heads_equal(u["attest"], attest):
            self.counters["attest_confirms"] += 1
            if audit_closing:
                a["state"] = "done"
                self.counters["audits_ok"] += 1
                self.journal.append({"t": "audit", "unit_id": unit_id,
                                     "worker": worker, "ok": True})
                self._pool_event("audit_ok", unit=unit_id, worker=worker)
            self._pool_event("attest_confirm", unit=unit_id,
                             worker=worker, epoch=epoch)
            return {"ok": True, "accepted": False, "duplicate": True}
        return self._attest_mismatch(u, req, worker, epoch, attest)

    def _attest_mismatch(self, u: dict, req: dict, worker: str,
                         epoch: int, attest: dict) -> dict:
        """Two comparable chains disagree: neither result can be
        trusted (first-ack-wins picked a winner by latency, not by
        correctness). Hold BOTH payloads, void the unit back to PENDING
        for a third execution on a different worker, and bar both
        claimants from picking it back up."""
        unit_id = u["spec"]["unit_id"]
        self.counters["attest_mismatches"] += 1
        held = [
            {"worker": u["ack_worker"], "result": u["result"],
             "resumed_steps": u["resumed_steps"], "attest": u["attest"]},
            {"worker": worker, "result": req.get("result"),
             "resumed_steps": int(req.get("resumed_steps", 0)),
             "attest": attest},
        ]
        workers = sorted({str(h["worker"]) for h in held})
        self.journal.append({
            "t": "suspect", "unit_id": unit_id, "key": u["spec"]["key"],
            "workers": workers, "held": held,
        })
        chaos.crashpoint("coordinator.post-ack")
        u["state"] = U.PENDING
        u["result"] = None
        u["resumed_steps"] = 0
        u["attest"] = None
        u["ack_worker"] = None
        u["held"] = held
        u["suspects"] |= set(workers)
        u["leases"].clear()
        self.audits.pop(unit_id, None)
        # either claimant may have rewritten the unit checkpoint after
        # the first ack — it is evidence-tainted, force fresh runs
        rel = self._checkpoint_rel(unit_id)
        if rel:
            try:
                os.unlink(os.path.join(self.pool_dir, rel))
            except OSError:
                pass
        self._pool_event("suspect", unit=unit_id, workers=workers)
        return {"ok": True, "accepted": False, "duplicate": True,
                "mismatch": True}

    def _h_tiebreak(self, u: dict, req: dict, worker: str,
                    epoch: int) -> dict:
        """Third execution's verdict on a held divergence: whichever
        held chain it reproduces was right, the other worker is
        quarantined as SUSPECT. No match -> the unit itself is SUSPECT
        (terminal, unresolved) and all three chains are preserved."""
        from ..attest import chain as _chain

        unit_id = u["spec"]["unit_id"]
        attest = req.get("attest") if self.attest_mode != "off" else None
        third = {"worker": worker, "result": req.get("result"),
                 "resumed_steps": int(req.get("resumed_steps", 0)),
                 "attest": attest}
        match = None
        if attest:
            for h in u["held"]:
                if (_chain.comparable(h["attest"], attest)
                        and _chain.heads_equal(h["attest"], attest)):
                    match = h
                    break
        self.counters["verdicts"] += 1
        if match is not None:
            quarantined = sorted(
                str(h["worker"]) for h in u["held"] if h is not match)
            self.journal.append({
                "t": "verdict", "unit_id": unit_id,
                "key": u["spec"]["key"], "outcome": "resolved",
                "worker": worker, "epoch": epoch,
                "result": req.get("result"),
                "resumed_steps": third["resumed_steps"],
                "attest": attest, "quarantined": quarantined,
                "confirmed": str(match["worker"]),
            })
            chaos.crashpoint("coordinator.post-ack")
            u["state"] = U.DONE
            u["result"] = req.get("result")
            u["resumed_steps"] = third["resumed_steps"]
            u["attest"] = attest
            u["ack_worker"] = worker
            u["held"] = []
            u["leases"].clear()
            self.counters["acks"] += 1
            for w in quarantined:
                if w not in self.suspect_workers:
                    self.suspect_workers.add(w)
                    self.counters["suspects"] += 1
                    self._pool_event("suspect_quarantine", worker=w,
                                     unit=unit_id)
            rel = self._checkpoint_rel(unit_id)
            if rel:
                try:
                    os.unlink(os.path.join(self.pool_dir, rel))
                except OSError:
                    pass
            self._pool_event("verdict", unit=unit_id, worker=worker,
                             outcome="resolved")
            return {"ok": True, "accepted": True}
        # three executions, three stories (or the tiebreak came back
        # chainless): nobody can be trusted, keep all the evidence
        held = u["held"] + [third]
        self.journal.append({
            "t": "verdict", "unit_id": unit_id, "key": u["spec"]["key"],
            "outcome": "unresolved", "held": held,
        })
        chaos.crashpoint("coordinator.post-ack")
        u["state"] = U.SUSPECT
        u["held"] = held
        u["leases"].clear()
        self._pool_event("verdict", unit=unit_id, worker=worker,
                         outcome="unresolved")
        return {"ok": True, "accepted": False, "suspect": True}

    def _h_enqueue(self, req: dict) -> dict:
        """Dynamic-mode admission (the elastic front-end's dispatch
        path). Idempotent by (unit_id, key): re-enqueueing after a
        front-end restart replies the unit's CURRENT state — including
        its result when a worker finished it while the front-end was
        down — instead of double-scheduling the work."""
        spec = dict(req.get("unit") or {})
        unit_id = str(spec.get("unit_id", ""))
        if not unit_id:
            raise ValueError("enqueue: unit spec has no unit_id")
        if spec.get("synth") is None and spec.get("trace_path") is None:
            raise ValueError(f"enqueue {unit_id}: no synth or trace_path")
        if not spec.get("config"):
            raise ValueError(f"enqueue {unit_id}: no config")
        spec.setdefault("key", U.unit_key(spec))
        u = self.units.get(unit_id)
        if u is not None:
            if u["spec"]["key"] != spec["key"]:
                raise ValueError(
                    f"enqueue {unit_id}: key mismatch with the already-"
                    "enqueued spec (same id, different workload)"
                )
            return {"ok": True, "unit_id": unit_id, "state": u["state"],
                    "result": u["result"],
                    "resumed_steps": u["resumed_steps"],
                    "duplicate": True}
        self.journal.append({"t": "unit", "unit": spec})
        self.units[unit_id] = self._entry(spec)
        self.counters["enqueued"] += 1
        self._pool_event("enqueue", unit=unit_id,
                         client=spec.get("client", "anon"))
        return {"ok": True, "unit_id": unit_id, "state": U.PENDING,
                "result": None, "resumed_steps": 0, "duplicate": False}

    def _h_collect(self, req: dict) -> dict:
        """Outcomes for the requested unit ids (the front-end polls this
        to map worker results back onto serve jobs): terminal units in
        `finished`, currently-leased ids in `leased` (the front-end's
        PENDING -> RUNNING signal)."""
        want = req.get("unit_ids")
        finished, leased = [], []
        for unit_id in (want if want is not None else self.units):
            u = self.units.get(str(unit_id))
            if u is None:
                continue
            if u["state"] == U.LEASED:
                leased.append(u["spec"]["unit_id"])
            elif u["state"] in (U.DONE, U.POISON, U.SUSPECT):
                finished.append({
                    "unit_id": u["spec"]["unit_id"],
                    "state": u["state"],
                    "result": u["result"],
                    "resumed_steps": u["resumed_steps"],
                    "kills": sorted(u["kills"]),
                    "suspects": sorted(u["suspects"]),
                })
        return {"ok": True, "finished": finished, "leased": leased}

    # ---- campaign state --------------------------------------------------

    @property
    def done(self) -> bool:
        if self.dynamic:
            return False  # a service is never "done"; workers idle-wait
        if not all(u["state"] in (U.DONE, U.POISON, U.SUSPECT)
                   for u in self.units.values()):
            return False
        # open audits hold the campaign: a sampled re-execution that
        # never runs is a sampled re-execution that never detects
        return all(a["state"] == "done" for a in self.audits.values())

    def results(self) -> list[dict]:
        """Per-unit outcomes in index order (poisoned units carry
        result=None plus their kill evidence)."""
        out = []
        for u in sorted(self.units.values(),
                        key=lambda u: u["spec"]["index"]):
            out.append({
                "unit_id": u["spec"]["unit_id"],
                "index": u["spec"]["index"],
                "state": u["state"],
                "result": u["result"],
                "resumed_steps": u["resumed_steps"],
                "kills": sorted(u["kills"]),
                "suspects": sorted(u["suspects"]),
            })
        return out

    def _stats(self) -> dict:
        states = {s: 0 for s in (U.PENDING, U.LEASED, U.DONE, U.POISON,
                                 U.SUSPECT)}
        leases_active = 0
        for u in self.units.values():
            states[u["state"]] += 1
            leases_active += len(u["leases"])
        return {
            "units": states,
            "leases_active": leases_active,
            "workers_seen": sorted(self.workers_seen),
            "counters": dict(self.counters),
            "recovered": self.recovered,
            "done": self.done,
        }

    def stats(self) -> dict:
        with self._lock:
            return self._stats()

    def pool_report(self) -> dict:
        """POOL section payload for stats.report.render_report."""
        s = self.stats()
        return {
            "units_total": len(self.units),
            "units_done": s["units"][U.DONE],
            "units_poisoned": s["units"][U.POISON],
            "units_suspect": s["units"][U.SUSPECT],
            "workers_seen": len(s["workers_seen"]),
            "redispatches": s["counters"]["redispatches"],
            "expired_leases": s["counters"]["expired"],
            "hedges": s["counters"]["hedges"],
            "duplicate_acks": s["counters"]["duplicates"],
            "heartbeats": s["counters"]["heartbeats"],
            "attest_confirms": s["counters"]["attest_confirms"],
            "attest_mismatches": s["counters"]["attest_mismatches"],
            "audits": s["counters"]["audits"],
            "suspect_workers": s["counters"]["suspects"],
        }

    def _pool_event(self, kind: str, **args) -> None:
        if self.obs is not None:
            self.obs.pool_event(kind, **args)

    # ---- socket front door -----------------------------------------------

    def start(self):
        """Bind the pool socket and serve verbs from daemon threads.
        Handlers take self._lock per request, so no inbox/main-loop dance
        is needed — the coordinator never simulates, it only bookkeeps."""
        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = read_line(self.rfile)
                    except ValueError as e:
                        self.wfile.write(encode({"ok": False,
                                                 **error_obj(e)}))
                        return
                    if req is None:
                        return
                    try:
                        self.wfile.write(encode(coord.handle(req)))
                        self.wfile.flush()
                    except (BrokenPipeError, ValueError):
                        return

        self._srv, fam = make_listener(self.socket_path, Handler)
        if fam == "tcp" and parse_target(self.socket_path)[1][1] == 0:
            # port 0 = kernel-assigned: rewrite the target so status
            # lines and spawned workers see the real port
            host, port = self._srv.server_address[:2]
            self.socket_path = f"{host}:{port}"
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        return self._srv

    def tick(self) -> None:
        """Periodic housekeeping from the campaign loop: expire leases
        whose heartbeats stopped."""
        with self._lock:
            self._expire_stale()

    def close(self, drained: bool = False) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if parse_target(self.socket_path)[0] == "unix":
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        if drained:
            self.journal.drain()
        self.journal.close()
