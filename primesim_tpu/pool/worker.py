"""Pool worker — one process, one fleet element at a time (DESIGN.md §17).

A worker is a pull loop against the coordinator socket: lease a unit,
materialize its workload locally (deterministic, same contract as
`serve.scheduler.materialize_workload`), simulate it under a
`RunSupervisor` whose `on_chunk` callback does the two pool duties —

- element-checkpoint the unit to its deterministic path under the pool
  directory (atomic tmp+rename), so whoever re-leases this unit after we
  die resumes from the last committed chunk instead of step 0;
- heartbeat the lease every ttl/3; a `lost` reply means the coordinator
  expired or superseded us (we were presumed dead, or a hedge twin won)
  and we abandon the unit without acking.

The worker NEVER trusts its connection: every coordinator call rides a
decorrelated-jitter reconnect loop (util.backoff), and a heartbeat that
cannot reach the coordinator is tolerated — we keep simulating, because
first-ACK-wins means a result computed during a network hole still
counts when the link returns. Only when the coordinator stays dark past
`reconnect_timeout_s` does the worker give up (exit 75, EX_TEMPFAIL).

Crash injection rides the chaos crashpoint registry (DESIGN.md §20):
the worker's committed-chunk boundary is the `worker.post-checkpoint`
site and the moment before its ack is `worker.pre-ack`. The legacy
`crash_after_chunks=N` knob (and the `PRIMETPU_POOL_CRASH` env alias
the campaign translates into it) is kept as a documented shorthand: it
installs a one-event FaultPlan killing this process at the Nth
`worker.post-checkpoint` arrival. In-process tests use
`simulate_crash=True`, which swaps the kill for a raised
`SimulatedCrash` at the same site (the test then plays the role of the
dead process by simply not acking).
"""

from __future__ import annotations

import os
import threading
import time

from ..chaos import plan as cplan
from ..chaos import sites as chaos
from ..serve.protocol import request
from ..util.backoff import DecorrelatedJitter, jittered

EX_TEMPFAIL = 75


class LeaseLost(Exception):
    """Coordinator told us the lease is gone (expired and re-dispatched,
    or the unit already finished) — abandon the unit, take the next."""


class SimulatedCrash(Exception):
    """In-process stand-in for SIGKILL: the test's worker vanishes
    mid-unit without acking or cleaning up."""


class _Heartbeat:
    """Background lease keep-alive for one unit. Runs on its own daemon
    thread so the lease survives phases where the simulation can't reach
    a chunk boundary — trace materialization and especially the first
    chunk's JIT compilation, which alone can outlast a short TTL. The
    thread only SETS flags; the simulating thread raises LeaseLost at
    the next chunk boundary (a clean commit point)."""

    def __init__(self, worker: "PoolWorker", unit_id: str, epoch: int,
                 interval_s: float):
        self.worker = worker
        self.unit_id = unit_id
        self.epoch = epoch
        self.interval_s = interval_s
        self.lost = False
        self.steps = 0  # updated by the simulating thread
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def start(self) -> "_Heartbeat":
        self._t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=2.0)

    def _run(self) -> None:
        down_since = None
        # any failure — refused connect, reset mid-reply, protocol
        # garbage from a half-restarted coordinator — must leave this
        # thread ALIVE and retrying under decorrelated jitter: a dead
        # keep-alive thread under a healthy simulation looks exactly
        # like a worker death and gets the lease expired out from under
        # a run that is still making progress
        jitter = DecorrelatedJitter(
            base=min(0.2, self.interval_s),
            cap=max(self.interval_s, 0.2),
            rng=self.worker.rng,
        )
        wait_s = self.interval_s
        while not self._stop.wait(wait_s):
            try:
                reply = self.worker._call({
                    "verb": "heartbeat",
                    "unit_id": self.unit_id,
                    "epoch": self.epoch,
                    "steps": int(self.steps),
                }, patient=False)
            except Exception:  # noqa: BLE001 — reconnect, never die
                # keep simulating through the hole: first-ACK-wins makes
                # the result still worth computing, unless the
                # coordinator stays dark past the reconnect window
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                elif now - down_since >= self.worker.reconnect_timeout_s:
                    self.lost = True
                    return
                wait_s = jitter.next_delay()
                continue
            down_since = None
            jitter.reset()
            wait_s = self.interval_s
            if reply.get("lost"):
                self.lost = True
                return


class PoolWorker:
    def __init__(
        self,
        socket_path: str,
        worker_id: str,
        warm_cache: bool = False,
        reconnect_timeout_s: float = 60.0,
        crash_after_chunks: int | None = None,
        simulate_crash: bool = False,
        rng=None,
        idle_exit_s: float | None = None,
        overlap: bool = False,
    ):
        self.socket_path = str(socket_path)
        self.worker_id = str(worker_id)
        self.warm_cache = bool(warm_cache)
        self.overlap = bool(overlap)
        self.reconnect_timeout_s = float(reconnect_timeout_s)
        self.crash_after_chunks = crash_after_chunks
        self.simulate_crash = bool(simulate_crash)
        if crash_after_chunks is not None:
            # legacy knob -> one-event crashpoint plan. Installing per
            # construction resets the occurrence counter, matching the
            # old per-instance `_chunks_seen` semantics exactly.
            chaos.install(
                cplan.FaultPlan(seed=0, events=(cplan.FaultEvent(
                    site="worker.post-checkpoint",
                    occurrence=int(crash_after_chunks),
                    action="kill",
                ),)),
                mode="raise" if self.simulate_crash else "kill",
                crash_exc=SimulatedCrash if self.simulate_crash else None,
            )
        self.rng = rng
        self.idle_exit_s = idle_exit_s
        self.units_done = 0
        self.units_lost = 0
        self.units_degraded = 0  # leases re-granted on a smaller mesh
        self._chunks_seen = 0
        self._toolchain_cache = None
        # warm compiled fleets, one per geometry bucket: keyed by
        # (config JSON, events capacity, chunk_steps), so serve jobs in
        # the same bucket reuse the compiled program across units — the
        # per-worker half of the front-end's slot-bucket design
        self._bucket_fleets: dict[tuple, object] = {}

    def _toolchain(self) -> dict:
        """The jax/jaxlib/backend triple the coordinator verifies on
        attested lease grants (chain heads from different toolchains
        would diverge for boring reasons). Sent on every lease; ignored
        by attest-off coordinators."""
        if self._toolchain_cache is None:
            from ..attest import toolchain_fingerprint

            self._toolchain_cache = toolchain_fingerprint()
        return self._toolchain_cache

    # ---- coordinator RPC with reconnect ----------------------------------

    def _call(self, req: dict, patient: bool = True) -> dict:
        """One verb round-trip. With `patient`, connection failures retry
        under decorrelated jitter until `reconnect_timeout_s` of
        continuous darkness, then re-raise (the campaign is gone)."""
        req = {**req, "worker": self.worker_id}
        jitter = DecorrelatedJitter(base=0.2, cap=5.0, rng=self.rng)
        deadline = time.monotonic() + self.reconnect_timeout_s
        while True:
            try:
                return request(self.socket_path, req)
            except (ConnectionError, OSError):
                if not patient or time.monotonic() >= deadline:
                    raise
                time.sleep(jitter.next_delay())

    # ---- the pull loop ---------------------------------------------------

    def run(self) -> int:
        """Lease/execute until the coordinator says the campaign is done
        (exit 0) or stays unreachable (exit 75). With `idle_exit_s`, a
        worker left idle that long also exits 0 — the autoscaling
        front-end's scale-DOWN path (it respawns workers on demand)."""
        idle_since = None
        while True:
            try:
                reply = self._call({"verb": "lease",
                                    "toolchain": self._toolchain()})
            except (ConnectionError, OSError):
                return EX_TEMPFAIL
            if reply.get("refused"):
                # attested admission said no — quarantined as SUSPECT or
                # wrong toolchain. Terminal for this worker: retrying
                # with the same identity/toolchain can never succeed.
                import json
                import sys

                print(json.dumps({"worker": self.worker_id,
                                  "refused": reply["refused"],
                                  "error": reply.get("error")}),
                      file=sys.stderr, flush=True)
                return EX_TEMPFAIL
            if not reply.get("ok", False):
                time.sleep(jittered(1.0, rng=self.rng))
                continue
            if reply.get("done"):
                return 0
            if reply.get("idle"):
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                elif (self.idle_exit_s is not None
                      and now - idle_since >= self.idle_exit_s):
                    return 0
                time.sleep(
                    jittered(float(reply.get("retry_after_s", 1.0)),
                             rng=self.rng)
                )
                continue
            idle_since = None
            self.run_unit(reply)

    # ---- unit execution --------------------------------------------------

    def run_unit(self, grant: dict) -> None:
        """Simulate one leased unit and ack its result. Lease loss
        abandons silently; workload errors ack a quarantined result so
        the campaign records the casualty and moves on."""
        unit = grant["unit"]
        epoch = int(grant["epoch"])
        try:
            result, resumed_steps = self._simulate(grant)
        except LeaseLost:
            self.units_lost += 1
            return
        except SimulatedCrash:
            raise
        except Exception as e:  # noqa: BLE001 — a bad unit must not kill us
            result = _quarantine_result(unit, e)
            resumed_steps = 0
        # the unit is fully simulated and checkpointed but NOT acked —
        # dying here is the classic lost-result window the coordinator's
        # lease expiry + re-dispatch must absorb
        chaos.crashpoint("worker.pre-ack")
        ack = {
            "verb": "ack",
            "unit_id": unit["unit_id"],
            "epoch": epoch,
            "key": unit["key"],
            "result": result,
            "resumed_steps": resumed_steps,
        }
        attest = (result or {}).get("detail", {}).get("attest")
        if attest:
            ack["attest"] = attest
        if grant.get("audit"):
            ack["audit"] = True
        try:
            self._call(ack)
            self.units_done += 1
        except (ConnectionError, OSError):
            # result lost with the coordinator; the unit's checkpoint
            # survives, so the re-lease (to us or a peer) is cheap
            self.units_lost += 1

    def _simulate(self, grant: dict) -> tuple[dict, int]:
        unit = grant["unit"]
        unit_id = unit["unit_id"]
        epoch = int(grant["epoch"])
        ttl = float(grant.get("lease_ttl_s", 10.0))
        ckpt_path = os.path.join(
            grant["pool_dir"], "units", f"{unit_id}.npz"
        )
        # keep-alive from the moment of the grant: materialization + JIT
        # compilation happen before the first chunk boundary and must not
        # look like a death to the coordinator
        hb = _Heartbeat(
            self, unit_id, epoch,
            # clock-skew site: a skewed interval makes the worker
            # heartbeat too slowly and drift into lease expiry
            interval_s=chaos.clock_skew(
                "worker.heartbeat.interval", max(0.1, ttl / 3.0)
            ),
        ).start()
        try:
            return self._simulate_leased(grant, unit, unit_id, ckpt_path,
                                         hb)
        finally:
            hb.stop()

    def _unit_mesh(self, unit, cfg):
        """The device mesh a unit's `devices` field asks for (None for
        the default solo layout). Validation is typed so a bad mesh
        request quarantines with a structured error instead of a
        mid-compile shape failure.

        Degraded-mode elasticity (DESIGN.md §26): when fewer HEALTHY
        devices remain than the lease asked for, the unit re-leases onto
        the largest valid smaller mesh instead of quarantining — the
        granted size is recorded on the unit (re-keying its geometry
        bucket) and surfaced in the ack so the coordinator books the
        capacity change. Sharded parity is mesh-invariant, so the result
        is bit-exact either way."""
        devices = int(unit.get("devices") or 0)
        if not devices:
            return None
        from ..parallel.sharding import (
            healthy_devices,
            largest_valid_submesh,
            tile_mesh,
            validate_devices,
        )

        healthy = healthy_devices()
        if len(healthy) >= devices:
            validate_devices(cfg, devices)  # geometry errors quarantine
            return tile_mesh(devices=healthy[:devices])
        n = largest_valid_submesh(cfg, len(healthy))  # raises at 0 healthy
        unit["_granted_devices"] = n
        self.units_degraded += 1
        return tile_mesh(devices=healthy[:n])

    def _bucket_fleet(self, unit, cfg):
        """The warm compiled slot fleet for a unit's geometry bucket
        (`capacity_pages` units = serve jobs dispatched by the elastic
        front-end). Compiled once per (config, capacity, chunk_steps,
        devices) and reused across every unit in the bucket —
        `replace_element` splices workloads without recompiling."""
        from ..serve.scheduler import PAGE_EVENTS
        from ..sim.fleet import FleetEngine

        cap = int(unit["capacity_pages"]) * PAGE_EVENTS
        # the mesh resolves first: under capacity loss the GRANTED size
        # keys the bucket, so degraded and full-size units never share a
        # warm fleet compiled for the wrong layout
        mesh = self._unit_mesh(unit, cfg)
        devices = int(
            unit.get("_granted_devices") or unit.get("devices") or 0
        )
        key = (unit["config"], cap, int(unit["chunk_steps"]), devices)
        fleet = self._bucket_fleets.get(key)
        if fleet is None:
            fleet = FleetEngine.make_slots(
                cfg, 1, cap, chunk_steps=int(unit["chunk_steps"]),
                mesh=mesh,
            )
            self._bucket_fleets[key] = fleet
        return fleet

    def _simulate_leased(self, grant, unit, unit_id, ckpt_path,
                         hb) -> tuple[dict, int]:
        from ..config.machine import MachineConfig
        from ..serve.scheduler import parse_synth_spec
        from ..sim.checkpoint import load_element_checkpoint
        from ..sim.fleet import FleetEngine
        from ..sim.supervisor import RunSupervisor
        from ..trace.format import Trace, fold_ins

        cfg = MachineConfig.from_json(unit["config"])
        if unit.get("kind") == "ingest":
            # MPMD pipeline stage 1 (DESIGN.md §22): materialize one trace
            # segment to the pool dir instead of simulating anything
            return self._ingest_segment(grant, unit, cfg, hb)
        if unit["synth"] is not None:
            trace = parse_synth_spec(unit["synth"], cfg.n_cores,
                                     unit["fold"])
        else:
            trace = Trace.load(unit["trace_path"])
            if unit["fold"]:
                trace = fold_ins(trace)
        bucketed = unit.get("capacity_pages") is not None
        if bucketed:
            fleet = self._bucket_fleet(unit, cfg)
            fleet.replace_element(0, trace, override=dict(unit["overrides"]))
        else:
            fleet = FleetEngine(
                cfg, [trace], [dict(unit["overrides"])],
                chunk_steps=int(unit["chunk_steps"]),
                mesh=self._unit_mesh(unit, cfg),
            )
        fleet.overlap = self.overlap
        # AOT warm at lease grant (§23): with an exec cache active, pay
        # deserialization (or compile-once) NOW, before the first chunk —
        # the heartbeat from `grant` already covers this window, so a
        # cache hit means compile never eats lease TTL
        fleet.warm_exec()

        attest_on = grant.get("attest") == "chain"
        # tiebreak / audit re-runs are granted `fresh`: no checkpoint
        # resume, no warm fork, no checkpoint WRITES — their chains must
        # cover the whole run, and the unit checkpoint on disk belongs
        # to the execution under adjudication
        fresh = bool(grant.get("fresh"))
        fleet.attest = None  # bucketed fleets are reused across units
        resumed_steps = 0
        ckpt_attest = None
        if grant.get("checkpoint") and not fresh:
            try:
                snap = load_element_checkpoint(
                    ckpt_path, fleet.elem_cfgs[0], trace
                )
                fleet.restore_element(0, snap)
                resumed_steps = int(fleet.steps_run[0])
                ckpt_attest = snap.get("attest")
            except Exception:
                # corrupt / mismatched / AttestationError (payload sha
                # refuted the checkpoint, §24): fresh start — slower but
                # honest, and the fresh chain covers every chunk we ack
                resumed_steps = 0
                ckpt_attest = None
        if (resumed_steps == 0 and not fresh
                and unit.get("warm_cache") and self.warm_cache):
            resumed_steps = self._warm_fork(fleet, trace)
        if attest_on:
            from ..attest import FleetAttest

            fa = FleetAttest()
            cs = int(unit["chunk_steps"])
            if (ckpt_attest and ckpt_attest.get("head")
                    and int(ckpt_attest.get("chunk_steps", 0)) == cs):
                fa.track(0, cs, start=int(ckpt_attest.get("start", 0)),
                         head=ckpt_attest["head"],
                         chunks=int(ckpt_attest.get("chunks", 0)))
            else:
                # fresh run, warm fork, or pre-attestation checkpoint:
                # the chain's coverage starts where this execution does
                fa.track(0, cs, start=resumed_steps)
            fleet.attest = fa

        def on_chunk(sup):
            self._chunks_seen += 1
            # checkpoint BEFORE the crashpoint: a worker killed at chunk
            # N leaves chunk N durable, so the re-lease resumes exactly
            # where the victim died
            if not fresh:
                self._checkpoint(ckpt_path, fleet, unit_id)
            chaos.crashpoint("worker.post-checkpoint")
            hb.steps = int(fleet.steps_run[0])
            if hb.lost:
                # expired-and-superseded, or the coordinator stayed dark
                # past the reconnect window: abandon at this clean commit
                # point (the checkpoint above stays for whoever re-leases)
                raise LeaseLost(unit_id)

        sup = RunSupervisor(fleet, handle_signals=False, on_chunk=on_chunk)
        t0 = time.perf_counter()
        try:
            sup.run(max_steps=int(unit["max_steps"]))
        except BaseException:
            fleet.attest = None
            if bucketed:
                # evict the failed workload so the warm fleet is clean
                # for the next unit in this bucket
                try:
                    fleet.clear_element(0)
                except Exception:
                    self._bucket_fleets.pop(
                        (unit["config"],
                         fleet.events_capacity,
                         int(unit["chunk_steps"]),
                         int(unit.get("_granted_devices")
                             or unit.get("devices") or 0)), None)
            raise
        wall = time.perf_counter() - t0

        # the per-element record, byte-for-byte the shape `primetpu
        # sweep` emits in-process — the chaos CI diff depends on it
        ec = fleet.element_counters(0)
        ins = int(ec["instructions"].sum())
        result = {
            "metric": "simulated_MIPS",
            "value": round(ins / max(wall, 1e-9) / 1e6, 3),
            "unit": "MIPS",
            "detail": {
                "engine": "fleet",
                "fleet_index": unit["index"],
                "n_cores": cfg.n_cores,
                "instructions": ins,
                "max_core_cycles": int(fleet.cycles[0].max()),
                "overrides": dict(unit["overrides"]),
                "wall_s": round(wall, 3),
                "noc_msgs": int(ec["noc_msgs"].sum()),
            },
        }
        if unit.get("devices"):
            # present ONLY for sharded campaigns, so unsharded sweep
            # records stay byte-identical for the pool-chaos CI diff
            result["detail"]["devices"] = int(unit["devices"])
            if unit.get("_granted_devices"):
                # capacity loss: the lease ran on a SMALLER mesh than it
                # asked for — the coordinator books the change
                result["detail"]["devices_granted"] = int(
                    unit["_granted_devices"]
                )
        if unit.get("serve_job"):
            # the front-end maps this into the serve job's result and
            # bit-exactness tests diff it against a solo Engine run —
            # extend ONLY for serve units so sweep records stay
            # byte-identical for the pool-chaos CI diff
            result["detail"]["core_cycles"] = [
                int(c) for c in fleet.cycles[0]
            ]
            result["detail"]["steps"] = int(fleet.steps_run[0])
            result["detail"]["counters"] = {
                k: [int(x) for x in v] for k, v in ec.items()
            }
        if attest_on and fleet.attest is not None:
            # present ONLY under --attest chain, so attest-off records
            # stay byte-identical (same rule as `devices` above)
            result["detail"]["attest"] = fleet.attest.payload(0)
            fleet.attest = None
        if bucketed:
            fleet.clear_element(0)
        return result, resumed_steps

    def _ingest_segment(self, grant, unit, cfg, hb) -> tuple[dict, int]:
        """Execute one MPMD ingest unit: materialize trace segment
        `seg_index` (line-normalized, END-padded) and write it atomically
        under the pool dir for the sim stage to consume. Deterministic,
        so hedged twins and re-leases produce identical bytes."""
        from ..ingest.pipeline import (
            normalize_segment,
            segment_path,
            write_segment,
        )
        from ..serve.scheduler import parse_synth_spec
        from ..trace.format import Trace

        if unit["synth"] is not None:
            trace = parse_synth_spec(unit["synth"], cfg.n_cores,
                                     unit["fold"])
        else:
            trace = Trace.load(unit["trace_path"], mmap=True)
        k = int(unit["seg_index"])
        L = int(unit["seg_events"])
        t0 = time.perf_counter()
        arr, n_valid = normalize_segment(cfg, trace, k, L)
        path = segment_path(grant["pool_dir"], k)
        write_segment(path, k, L, arr)
        if hb.lost:
            raise LeaseLost(unit["unit_id"])
        return {
            "metric": "ingested_events",
            "value": n_valid,
            "unit": "events",
            "detail": {
                "engine": "ingest",
                "fleet_index": unit["index"],
                "seg_index": k,
                "seg_events": L,
                "n_cores": cfg.n_cores,
                "path": path,
                "wall_s": round(time.perf_counter() - t0, 3),
            },
        }, 0

    def _checkpoint(self, path: str, fleet, unit_id: str) -> None:
        from ..sim.checkpoint import save_element_checkpoint

        save_element_checkpoint(path, fleet, 0, job_id=unit_id)

    def _warm_fork(self, fleet, trace) -> int:
        """Warm-state cache consult (DESIGN.md §16) for a fresh unit:
        fork from the deepest proven prefix of this exact workload."""
        from ..sim.checkpoint import (
            CheckpointCorrupt,
            find_warm_states,
            load_warm_state,
            trace_fingerprint,
            warm_cache_root,
        )

        root = warm_cache_root()
        ecfg = fleet.elem_cfgs[0]
        fp = trace_fingerprint(trace)
        for steps, key in find_warm_states(root, ecfg, fp):
            try:
                snap = load_warm_state(root, key, ecfg, fp, steps)
            except (FileNotFoundError, CheckpointCorrupt, ValueError):
                continue
            fleet.fork_element(0, snap, cache_key=key)
            return steps
        return 0


def _quarantine_result(unit: dict, exc: BaseException) -> dict:
    from ..serve.protocol import error_obj

    return {
        "metric": "quarantined",
        "value": None,
        "unit": None,
        "detail": {
            "engine": "fleet",
            "fleet_index": unit["index"],
            "status": "quarantined",
            "overrides": dict(unit["overrides"]),
            **error_obj(exc),
        },
    }


def run_worker(
    socket_path: str,
    worker_id: str,
    warm_cache: bool = False,
    reconnect_timeout_s: float = 60.0,
    crash_after_chunks: int | None = None,
    idle_exit_s: float | None = None,
    overlap: bool = False,
) -> int:
    return PoolWorker(
        socket_path,
        worker_id,
        warm_cache=warm_cache,
        reconnect_timeout_s=reconnect_timeout_s,
        crash_after_chunks=crash_after_chunks,
        idle_exit_s=idle_exit_s,
        overlap=overlap,
    ).run()
