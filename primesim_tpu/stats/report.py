"""End-of-run text report (SURVEY.md §2 #12, §5.5).

The reference writes a text report at fini with per-core and aggregate
stats (per-core ins/cycles/IPC, cache hit/miss per level, network traffic,
simulated time, host wall time, MIPS). This module renders the same
content from the canonical counter dict + per-core cycle array; the CLI
(`primesim_tpu.cli run --report`) uses it.
"""

from __future__ import annotations

import numpy as np

from ..config.machine import MachineConfig


def _rate(hits, total) -> str:
    t = int(total)
    return f"{int(hits) / t:7.2%}" if t else "    n/a"


def render_report(
    cfg: MachineConfig,
    counters: dict[str, np.ndarray],
    cycles: np.ndarray,
    wall_s: float | None = None,
    per_core_limit: int = 64,
    title: str = "primesim_tpu simulation report",
    resilience: list[str] | None = None,
    service: dict | None = None,
    timeline: dict | None = None,
    pool: dict | None = None,
) -> str:
    """Render the reference-style text report.

    `counters` is the canonical per-core counter dict (stats.counters),
    `cycles` the per-core final clocks; `wall_s` (host wall time) enables
    the MIPS line. Per-core rows are capped at `per_core_limit` (0 = all).
    `resilience` (RunSupervisor.log_lines()) appends a RESILIENCE section
    recording every checkpoint/retry/degradation decision of a supervised
    run — the audit trail the failure-model contract (DESIGN.md §10)
    promises. `service` (serve Scheduler.service_report()) appends a
    SERVICE section: jobs by terminal state, aggregate MIPS over the
    serving window, and accept-to-terminal latency percentiles.
    `timeline` (obs.MetricStore.summary(), present only when `--obs` is
    on) appends a TIMELINE section: per-chunk throughput extremes and
    the slowest chunk's index in the run.
    `pool` (PoolCoordinator.pool_report()) appends a POOL section: unit
    outcomes and the lease protocol's decisions — redispatches, expired
    leases, hedges, duplicate acks — for an elastic `sweep --workers`
    campaign.
    """
    C = cfg.n_cores
    ins = counters["instructions"].astype(np.int64)
    cyc = np.asarray(cycles, dtype=np.int64)
    tot_ins = int(ins.sum())
    max_cyc = int(cyc.max()) if C else 0

    l1_reads = counters["l1_read_hits"] + counters["l1_read_misses"]
    l1_writes = counters["l1_write_hits"] + counters["l1_write_misses"] + counters["upgrades"]
    llc_acc = counters["llc_hits"] + counters["llc_misses"]

    lines: list[str] = []
    add = lines.append
    add("=" * 72)
    add(title)
    add("=" * 72)
    add(
        f"machine: {C} cores, {cfg.n_banks} LLC banks, "
        f"{cfg.noc.mesh_x}x{cfg.noc.mesh_y} mesh, quantum {cfg.quantum}"
    )
    add(
        f"l1: {cfg.l1.size}B {cfg.l1.ways}w lat {cfg.l1.latency} | "
        f"llc/bank: {cfg.llc.size}B {cfg.llc.ways}w lat {cfg.llc.latency} | "
        f"dram {cfg.dram_lat} | line {cfg.l1.line}B"
    )
    add("")
    add("AGGREGATE")
    add(f"  instructions        {tot_ins:>16,}")
    add(f"  max core cycles     {max_cyc:>16,}")
    ipc = tot_ins / (max_cyc * C) if max_cyc and C else 0.0
    add(f"  IPC (agg/core/cyc)  {ipc:>16.4f}")
    if wall_s is not None and wall_s > 0:
        add(f"  host wall seconds   {wall_s:>16.2f}")
        add(f"  simulated MIPS      {tot_ins / wall_s / 1e6:>16.3f}")
        add(f"  sim cycles/sec      {max_cyc / wall_s:>16,.0f}")
    add(f"  L1 read hit rate    {_rate(counters['l1_read_hits'].sum(), l1_reads.sum()):>16}")
    add(f"  L1 write hit rate   {_rate(counters['l1_write_hits'].sum(), l1_writes.sum()):>16}")
    add(f"  LLC hit rate        {_rate(counters['llc_hits'].sum(), llc_acc.sum()):>16}")
    add(f"  DRAM accesses       {int(counters['dram_accesses'].sum()):>16,}")
    add(f"  L1 writebacks       {int(counters['l1_writebacks'].sum()):>16,}")
    add(f"  LLC writebacks      {int(counters['llc_writebacks'].sum()):>16,}")
    add(f"  probes              {int(counters['probes'].sum()):>16,}")
    add(f"  invalidations       {int(counters['invalidations'].sum()):>16,}")
    add(f"  NoC messages        {int(counters['noc_msgs'].sum()):>16,}")
    add(f"  NoC hops            {int(counters['noc_hops'].sum()):>16,}")
    add(f"  arbitration retries {int(counters['retries'].sum()):>16,}")
    add(f"  NoC contention cyc  {int(counters['noc_contention_cycles'].sum()):>16,}")
    add(f"  DRAM queue cycles   {int(counters['dram_queue_cycles'].sum()):>16,}")
    locks = int(counters["lock_acquires"].sum())
    if locks or int(counters["barrier_waits"].sum()):
        add(f"  lock acquires       {locks:>16,}")
        add(f"  lock spins          {int(counters['lock_spins'].sum()):>16,}")
        add(f"  barrier waits       {int(counters['barrier_waits'].sum()):>16,}")
    add("")
    n_show = C if per_core_limit == 0 else min(C, per_core_limit)
    add(f"PER-CORE (first {n_show} of {C})")
    add(
        "  core      instructions          cycles     IPC   l1r_hit  l1w_hit"
        "   llc_hit"
    )
    for c in range(n_show):
        cipc = ins[c] / cyc[c] if cyc[c] else 0.0
        add(
            f"  {c:>4}  {int(ins[c]):>16,}  {int(cyc[c]):>14,}  {cipc:6.3f}"
            f"  {_rate(counters['l1_read_hits'][c], l1_reads[c])}"
            f"  {_rate(counters['l1_write_hits'][c], l1_writes[c])}"
            f"  {_rate(counters['llc_hits'][c], llc_acc[c])}"
        )
    fault_keys = ("core_failstops", "noc_reroutes", "ecc_corrected", "ecc_due")
    fault_total = sum(int(counters[k].sum()) for k in fault_keys if k in counters)
    if getattr(cfg, "faults_enabled", False) or fault_total:
        # only rendered when fault injection is configured (or somehow
        # counted): the faults-off report stays byte-identical to goldens
        add("")
        add("FAULTS")
        add(f"  core fail-stops     {int(counters['core_failstops'].sum()):>16,}")
        add(f"  NoC reroutes        {int(counters['noc_reroutes'].sum()):>16,}")
        add(f"  ECC corrected       {int(counters['ecc_corrected'].sum()):>16,}")
        add(f"  ECC DUE             {int(counters['ecc_due'].sum()):>16,}")
        dead = np.flatnonzero(counters["core_failstops"])
        if dead.size:
            add(f"  dead cores          {', '.join(map(str, dead.tolist()))}")
    if timeline:
        add("")
        add("TIMELINE")
        add(f"  chunks committed    {int(timeline.get('chunks', 0)):>16,}")
        if timeline.get("dropped"):
            add(f"  samples dropped     {int(timeline['dropped']):>16,}")
        add(f"  peak chunk MIPS     {float(timeline.get('peak_chunk_mips', 0.0)):>16.3f}")
        add(f"  mean chunk MIPS     {float(timeline.get('mean_chunk_mips', 0.0)):>16.3f}")
        if timeline.get("slowest_chunk_seq", -1) >= 0:
            add(
                f"  slowest chunk       {int(timeline['slowest_chunk_seq']):>16,}"
                f"  ({float(timeline.get('slowest_chunk_wall_s', 0.0)) * 1e3:.1f} ms)"
            )
        labels = timeline.get("labels") or {}
        if "prefix" in labels:
            # a prefix-forked campaign: show where the wall went so the
            # shared-prefix win (or a cache hit's absent prefix) is visible
            pre = labels["prefix"]
            tail_wall = sum(
                v.get("wall_s", 0.0) for k, v in labels.items() if k != "prefix"
            )
            add(f"  prefix wall seconds {float(pre.get('wall_s', 0.0)):>16.2f}")
            add(f"  tail wall seconds   {tail_wall:>16.2f}")
    if resilience:
        add("")
        add("RESILIENCE")
        for line in resilience:
            add(f"  {line}")
    if service:
        add("")
        add("SERVICE")
        add(f"  jobs completed      {int(service.get('jobs_completed', 0)):>16,}")
        for state, n in sorted(service.get("jobs_by_state", {}).items()):
            add(f"  {state.lower():<19} {int(n):>16,}")
        add(f"  aggregate MIPS      {float(service.get('aggregate_mips', 0.0)):>16.3f}")
        lat = service.get("latency_s") or {}
        for p in ("p50", "p90", "p99"):
            if lat.get(p) is not None:
                add(f"  latency {p}         {lat[p]:>16.3f}s")
        if service.get("uptime_s") is not None:
            add(f"  uptime seconds      {float(service['uptime_s']):>16.1f}")
    if pool:
        add("")
        add("POOL")
        add(f"  units total         {int(pool.get('units_total', 0)):>16,}")
        add(f"  units done          {int(pool.get('units_done', 0)):>16,}")
        add(f"  units poisoned      {int(pool.get('units_poisoned', 0)):>16,}")
        add(f"  workers seen        {int(pool.get('workers_seen', 0)):>16,}")
        add(f"  expired leases      {int(pool.get('expired_leases', 0)):>16,}")
        add(f"  redispatches        {int(pool.get('redispatches', 0)):>16,}")
        add(f"  hedges              {int(pool.get('hedges', 0)):>16,}")
        add(f"  duplicate acks      {int(pool.get('duplicate_acks', 0)):>16,}")
        add(f"  heartbeats          {int(pool.get('heartbeats', 0)):>16,}")
    add("=" * 72)
    return "\n".join(lines) + "\n"


def write_report(path: str, *args, **kw) -> None:
    with open(path, "w") as f:
        f.write(render_report(*args, **kw))
