"""Canonical stat counters (DESIGN.md §3).

Replaces the reference's scattered per-model counters + report fields
(SURVEY.md §2 #12). Every counter is tracked PER CORE (attributed to the
requesting core for uncore events) so the report can show both per-core and
aggregate numbers like the reference's text report.

Both engines carry these as arrays `[n_cores]`; the JAX engine uses int32 on
device and drains into an int64 host-side accumulator at chunk boundaries.
"""

from __future__ import annotations

import numpy as np

COUNTER_NAMES = (
    "instructions",    # INS batch counts + 1 per retired memory op
    "l1_read_hits",
    "l1_read_misses",  # GETS issued
    "l1_write_hits",   # write hit in E/M (incl. silent E->M)
    "l1_write_misses", # GETM issued
    "upgrades",        # ST hit in S -> UPG issued
    "llc_hits",
    "llc_misses",
    "dram_accesses",
    "l1_writebacks",   # M victim evicted from L1
    "llc_writebacks",  # owned victim evicted from LLC
    "probes",          # owner probes sent
    "invalidations",   # invalidation messages sent (sharer + back-inv)
    "noc_msgs",
    "noc_hops",
    "retries",         # conflict-serialization retries (lost (bank,set) race)
    "lock_acquires",   # LOCK events retired
    "lock_spins",      # failed LOCK attempts (charged spin round trips)
    "barrier_waits",   # BARRIER arrivals
    "noc_contention_cycles",  # router-occupancy queueing cycles charged
    "dram_queue_cycles",  # memory-controller queueing waits (dram_queue)
    # ---- fault injection (DESIGN.md §12; zero with faults disabled) ----
    "noc_reroutes",    # one-way messages detoured around a dead link
    "ecc_corrected",   # single-bit flips corrected in-line by SECDED
    "ecc_due",         # detected-uncorrectable (double-bit) errors
    "core_failstops",  # cores fail-stopped (scheduled or DUE-escalated)
    # ---- machine zoo (DESIGN.md §25; zero with prefetcher "none") ------
    "prefetch_hits",   # LLC misses served by the stride prefetcher
)


def zero_counters(n_cores: int, dtype=np.int64) -> dict[str, np.ndarray]:
    return {k: np.zeros(n_cores, dtype=dtype) for k in COUNTER_NAMES}
