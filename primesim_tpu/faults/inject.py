"""Step-time fault injection: scheduled events, ECC draws, dead-core
scrubbing, and link-detour penalties (DESIGN.md §12).

Everything here is called from inside `sim.engine.step` under the STATIC
`cfg.faults_enabled` gate, on TRACED values only — no host randomness, no
data-dependent shapes — so a fault-enabled program still compiles once
per geometry and vmaps over the fleet's batch axis unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config.machine import (
    FAULT_CORE_FAILSTOP,
    FAULT_LINK_DEGRADE,
    FAULT_LINK_FAIL,
    MachineConfig,
)
from ..noc.topology import detour_hops_table, path_links
from ..sim.state import llc_meta_width
from .prng import DUE_SALT, site_hash


def fire_events(cfg: MachineConfig, fs, step_no):
    """Apply this step's scheduled events: (kill_sched [C] int32 0/1,
    link_dead [NL], link_extra [NL]). Duplicate events are idempotent
    (set/max scatters); padding rows (ev_step == -1) never match."""
    C = cfg.n_cores
    NL = cfg.n_tiles * 4
    fire = fs.ev_step == step_no  # [K]; K == 0 is fine (drop scatters)
    kill_t = fire & (fs.ev_kind == FAULT_CORE_FAILSTOP)
    kill_sched = (
        jnp.zeros(C, jnp.int32)
        .at[jnp.where(kill_t, fs.ev_a, C)]
        .max(1, mode="drop")
    )
    lf = fire & (fs.ev_kind == FAULT_LINK_FAIL)
    link_dead = fs.link_dead.at[jnp.where(lf, fs.ev_a, NL)].max(
        1, mode="drop"
    )
    ld = fire & (fs.ev_kind == FAULT_LINK_DEGRADE)
    link_extra = fs.link_extra.at[jnp.where(ld, fs.ev_a, NL)].max(
        fs.ev_b, mode="drop"
    )
    return kill_sched, link_dead, link_extra


def ecc_step(cfg: MachineConfig, fs, step_no, arange_c):
    """This step's transient-flip draws under the SECDED model.

    One flip draw per L1 (site = core id) and per LLC bank (site =
    C + bank), plus a salted second draw classifying each flip as
    single-bit (corrected in-line by SECDED — counted, no architectural
    effect) or double-bit (detected-uncorrectable). Returns
    (corrected [C], due [C], l1_due [C] bool): LLC-bank draws are
    attributed to core (bank % C) for counting; only an L1 DUE can
    escalate to a fail-stop of its core (an LLC DUE has no single owning
    core — the line's data is lost but which core pays is workload
    policy, out of model scope)."""
    C = cfg.n_cores
    B = cfg.n_banks
    h1 = site_hash(fs.seed, step_no, arange_c)
    l1_flip = h1 < fs.flip_l1
    l1_due = l1_flip & (
        site_hash(fs.seed, step_no, arange_c, DUE_SALT) < fs.due_rate
    )
    arange_b = jnp.arange(B, dtype=jnp.int32)
    site_b = C + arange_b
    hb = site_hash(fs.seed, step_no, site_b)
    llc_flip = hb < fs.flip_llc
    llc_due = llc_flip & (
        site_hash(fs.seed, step_no, site_b, DUE_SALT) < fs.due_rate
    )
    corr = (l1_flip & ~l1_due).astype(jnp.int32)
    due = l1_due.astype(jnp.int32)
    corr = corr.at[arange_b % C].add(
        (llc_flip & ~llc_due).astype(jnp.int32), mode="drop"
    )
    due = due.at[arange_b % C].add(llc_due.astype(jnp.int32), mode="drop")
    return corr, due, l1_due


def scrub_dead(cfg: MachineConfig, dirm, lock_holder, kill_b):
    """Remove this step's freshly killed cores from the coherence fabric.

    - Sharer bits: every sharer word drops the killed cores' bits (fail-
      stop requires sharer_group == 1 — config-validated — so bit == core
      id; with G == 1 the epoch guard is unused and no epoch bump is
      needed: clearing a core's own bit only affects that core's future
      validation, and a dead core never accesses again).
    - Owners: entries owned by a killed core lose their owner. Under
      "writeback" policy the line's data survives in the LLC (the home
      cannot see silent E->M, so every owned line conservatively counts
      one writeback, attributed to the dead owner — golden does the same
      for back-invalidated owners); under "drop" the tag is invalidated
      and the way's sharer words cleared — the dirty data is lost and the
      next access refetches from DRAM.
    - Locks: slots held by a killed core release (a fail-stop detection +
      recovery idealization; without it every waiter spins forever, which
      is a workload property, not a machine one).

    The dead core's own L1 needs no scrub: pull-based coherence means no
    other core ever reads it. Returns (dirm, lock_holder, wb [C])."""
    C = cfg.n_cores
    W2 = cfg.llc.ways
    NW = cfg.n_sharer_words
    MW = llc_meta_width(cfg)
    R = dirm.shape[0]
    arange_c = jnp.arange(C, dtype=jnp.int32)
    kill_i = kill_b.astype(jnp.int32)
    # killed-core bits packed as words (distinct bits: add == OR)
    killw = jnp.zeros(NW, jnp.int32).at[arange_c >> 5].add(
        jnp.where(kill_b, jnp.int32(1) << (arange_c & 31), 0)
    )
    rowmask = jnp.concatenate(
        [jnp.zeros(MW, jnp.int32), jnp.tile(killw, W2)]
    )
    dirm = dirm & ~rowmask[None, :]
    meta = dirm[:, : 2 * W2].reshape(R, W2, 2)
    own = meta[..., 1]
    tag = meta[..., 0]
    downer = (own >= 0) & (jnp.take(kill_i, jnp.clip(own, 0, C - 1)) != 0)
    new_own = jnp.where(downer, -1, own)
    if cfg.fault_dead_policy == "drop":
        new_tag = jnp.where(downer, -1, tag)
        way_dead = jnp.repeat(downer, NW, axis=1)  # [R, W2*NW]
        sh = jnp.where(way_dead, 0, dirm[:, MW:])
        wb = jnp.zeros(C, jnp.int32)
    else:
        new_tag = tag
        sh = dirm[:, MW:]
        wb = jnp.zeros(C, jnp.int32).at[
            jnp.where(downer, jnp.clip(own, 0, C - 1), C)
        ].add(1, mode="drop")
    dirm = jnp.concatenate(
        [
            jnp.stack([new_tag, new_own], axis=-1).reshape(R, 2 * W2),
            dirm[:, 2 * W2 : MW],
            sh,
        ],
        axis=1,
    )
    held_dead = (lock_holder >= 0) & (
        jnp.take(kill_i, jnp.clip(lock_holder, 0, C - 1)) != 0
    )
    lock_holder = jnp.where(held_dead, -1, lock_holder)
    return dirm, lock_holder, wb


def scrub_dead_cond(cfg: MachineConfig, dirm, lock_holder, kill_now):
    """`scrub_dead` behind a lax.cond on `any(kill_now)`: fail-stops fire
    on a handful of steps per run, so the full-directory scrub pass must
    not execute on the steps where nothing died (the faults-on steady-
    state overhead is the two ECC hashes and the leg gathers)."""
    C = cfg.n_cores
    return jax.lax.cond(
        jnp.any(kill_now != 0),
        lambda args: scrub_dead(cfg, args[0], args[1], args[2] != 0),
        lambda args: (args[0], args[1], jnp.zeros(C, jnp.int32)),
        (dirm, lock_holder, kill_now),
    )


def leg_fault_penalty(cfg: MachineConfig, fs, kn, atile, btile):
    """Vectorized fault penalty of the one-way legs atile -> btile:
    (extra cycles, extra hops, rerouted 0/1) per lane — the traced twin
    of `noc.topology.detour_stats`. Each dead link on the route detours
    at the TOPOLOGY's per-link extra-hop cost (mesh/torus: the orthogonal
    sidestep, +2 everywhere; ring: the long way around the affected
    ring), paying (link+router) per extra hop; each live degraded link
    adds its extra cycles. The table is a host-side constant baked per
    geometry, so fault sweeps still compile once."""
    p = path_links(cfg, atile, btile)  # [C, H]
    ok = p >= 0
    pc = jnp.where(ok, p, 0)
    tbl = jnp.asarray(detour_hops_table(cfg), jnp.int32)
    dead = jnp.where(ok, fs.link_dead[pc], 0)
    dh = jnp.where(ok, tbl[pc] * dead, 0)  # extra hops per dead link
    extra = jnp.where(ok & (dead == 0), fs.link_extra[pc], 0)
    d = jnp.sum(dh, axis=1)
    lat = d * (kn.link_lat + kn.router_lat) + jnp.sum(extra, axis=1)
    return lat, d, (jnp.sum(dead, axis=1) > 0).astype(jnp.int32)


__all__ = [
    "fire_events",
    "ecc_step",
    "scrub_dead",
    "scrub_dead_cond",
    "leg_fault_penalty",
]
