"""Counter-based fault PRNG: hash (seed, step, site) -> uniform uint32.

No host randomness and no traced RNG state: every draw is a pure function
of the simulation seed, the step number, and a site id, so a schedule
replays bit-exactly solo vs fleet-vmapped vs resumed-from-checkpoint, and
the fleet's batch axis vmaps through it like any other arithmetic.

The mixer is the murmur3 fmix32 finalizer — full avalanche on 32 bits —
over a Weyl-style combination of the inputs. A draw fires an event of
probability p when `hash < threshold(p)` with threshold = round(p * 2^32)
saturated to uint32 (p=0 never fires; p=1 misses only the single all-ones
hash value, error 2^-32).

`site_hash_np` is the NumPy twin used by tests to predict device draws.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# distinct odd constants decorrelate the step and site counters
_STEP_MUL = 0x9E3779B9
_SITE_MUL = 0x85EBCA77
#: salt for the second (DUE-classification) draw per site
DUE_SALT = 0x2545F491


def fmix32(x):
    """murmur3 32-bit finalizer (jnp uint32 in/out)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def site_hash(seed, step, site, salt: int = 0):
    """Uniform uint32 draw for (seed, step, site). `seed` a traced uint32
    scalar, `step` a traced int32 scalar, `site` an int32 array."""
    x = (
        seed.astype(jnp.uint32)
        ^ jnp.uint32(salt)
        ^ (step.astype(jnp.uint32) * jnp.uint32(_STEP_MUL))
        ^ (site.astype(jnp.uint32) * jnp.uint32(_SITE_MUL))
    )
    return fmix32(x)


def site_hash_np(seed: int, step, site, salt: int = 0) -> np.ndarray:
    """Host-side reference of `site_hash` (bit-identical)."""
    with np.errstate(over="ignore"):
        x = (
            np.uint32(seed)
            ^ np.uint32(salt)
            ^ (np.asarray(step, np.uint32) * np.uint32(_STEP_MUL))
            ^ (np.asarray(site, np.uint32) * np.uint32(_SITE_MUL))
        )
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EBCA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2AE35)
        x = x ^ (x >> np.uint32(16))
    return x


def prob_threshold(p: float) -> np.uint32:
    """Probability -> uint32 compare threshold (fires when hash < t)."""
    return np.uint32(min(0xFFFFFFFF, int(round(float(p) * 4294967296.0))))
