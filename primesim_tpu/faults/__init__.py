"""Deterministic, seeded fault injection for the SIMULATED machine
(DESIGN.md §12).

Three architectural fault classes, all fully TRACED so fleet sweeps still
compile once per geometry and `sweep --vary fault_seed` never recompiles:

- core fail-stop at a scheduled step (the dead core leaves the quantum
  barrier, its directory footprint is scrubbed, its owned lines are
  written back or dropped per policy);
- mesh link failure/degradation (failed hops take an X-Y fallback detour
  with extra latency, counted as rerouted messages);
- transient L1/LLC bit flips under a SECDED ECC model (corrected vs
  detected-uncorrectable counters; DUE optionally escalates to a
  fail-stop).

Randomness is a counter-based PRNG keyed on (seed, step, site) — no host
RNG, no traced RNG state — so the same schedule replays bit-exactly solo,
fleet-vmapped, and across checkpoint/resume (the supervisor's chaos mode
rides the PR 3 guard/checkpoint machinery unchanged).
"""

from .prng import fmix32, site_hash, site_hash_np  # noqa: F401
from .schedule import (  # noqa: F401
    FaultSchedule,
    FaultState,
    fault_state_from_config,
    load_schedule,
)
