"""FaultSchedule (user-facing) and FaultState (the traced pytree).

A `FaultSchedule` is what the CLI loads from `--fault-schedule file.json`:
a list of scheduled events plus ECC rates and policies. It is applied to a
MachineConfig (static capacity + policies, traced seed/events/rates — see
config.machine), and `init_state` carries the traced values into the
`FaultState` field of MachineState via `fault_state_from_config`.

Schedule JSON shape (all fields optional):

    {
      "events": [
        {"step": 100, "kind": "core_failstop", "core": 3},
        {"step": 50,  "kind": "link_fail",    "link": 17},
        {"step": 50,  "kind": "link_degrade", "link": 6, "extra": 8}
      ],
      "flip_l1": 1e-6, "flip_llc": 1e-7, "due_rate": 0.01,
      "dead_policy": "writeback", "due_failstop": false
    }

Malformed schedules raise the typed `FaultConfigError` (site, step,
field) from config.machine instead of a bare traceback.
"""

from __future__ import annotations

import dataclasses
import json
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..config.machine import (
    FAULT_CORE_FAILSTOP,
    FAULT_LINK_DEGRADE,
    FAULT_LINK_FAIL,
    FaultConfigError,
    MachineConfig,
)
from .prng import prob_threshold

_KIND_NAMES = {
    "core_failstop": FAULT_CORE_FAILSTOP,
    "link_fail": FAULT_LINK_FAIL,
    "link_degrade": FAULT_LINK_DEGRADE,
}


class FaultState(NamedTuple):
    """Traced fault-injection state carried in MachineState.faults.

    Always present (pytree structure is shape-stable across configs);
    with cfg.faults_enabled == False the step function never reads it.
    The schedule arrays are [K = cfg.max_fault_events] (K is static
    geometry; values are traced), masks evolve as events fire.
    """

    seed: jnp.ndarray  # [] uint32 — the fault PRNG seed
    core_dead: jnp.ndarray  # [C] int32 0/1 — failed-stop cores
    link_dead: jnp.ndarray  # [n_links] int32 0/1 — failed directed links
    link_extra: jnp.ndarray  # [n_links] int32 — degrade cycles per traversal
    ev_step: jnp.ndarray  # [K] int32 — firing step (-1 = padding)
    ev_kind: jnp.ndarray  # [K] int32 — FAULT_* kind (0 = padding)
    ev_a: jnp.ndarray  # [K] int32 — core id / link id
    ev_b: jnp.ndarray  # [K] int32 — degrade extra cycles
    flip_l1: jnp.ndarray  # [] uint32 — L1 per-core per-step flip threshold
    flip_llc: jnp.ndarray  # [] uint32 — LLC per-bank per-step flip threshold
    due_rate: jnp.ndarray  # [] uint32 — DUE-classification threshold


def fault_state_from_config(cfg: MachineConfig) -> FaultState:
    """The config's fault knobs as the traced FaultState pytree (solo
    engine seeding; fleet elements stack per-element values)."""
    K = cfg.max_fault_events
    nl = cfg.n_tiles * 4
    ev = np.zeros((K, 4), np.int32)
    ev[:, 0] = -1
    for i, e in enumerate(cfg.fault_events):
        ev[i] = [int(x) for x in e]
    return FaultState(
        seed=jnp.asarray(np.uint32(cfg.fault_seed & 0xFFFFFFFF)),
        core_dead=jnp.zeros(cfg.n_cores, jnp.int32),
        link_dead=jnp.zeros(nl, jnp.int32),
        link_extra=jnp.zeros(nl, jnp.int32),
        ev_step=jnp.asarray(ev[:, 0]),
        ev_kind=jnp.asarray(ev[:, 1]),
        ev_a=jnp.asarray(ev[:, 2]),
        ev_b=jnp.asarray(ev[:, 3]),
        flip_l1=jnp.asarray(prob_threshold(cfg.fault_flip_l1)),
        flip_llc=jnp.asarray(prob_threshold(cfg.fault_flip_llc)),
        due_rate=jnp.asarray(prob_threshold(cfg.fault_due_rate)),
    )


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """User-facing fault schedule (CLI/config layer)."""

    events: tuple = ()  # ((step, kind, a, b), ...) — FAULT_* kinds
    flip_l1: float = 0.0
    flip_llc: float = 0.0
    due_rate: float = 0.0
    dead_policy: str = "writeback"
    due_failstop: bool = False

    def apply(self, cfg: MachineConfig, seed: int = 0) -> MachineConfig:
        """`cfg` with this schedule installed and faults enabled.

        `max_fault_events` is rounded up to the next power of two (min 1)
        so schedules of similar size share the static jit key.
        """
        k = max(1, len(self.events))
        k = 1 << (k - 1).bit_length()
        return dataclasses.replace(
            cfg,
            faults_enabled=True,
            max_fault_events=max(cfg.max_fault_events, k),
            fault_dead_policy=self.dead_policy,
            fault_due_failstop=self.due_failstop,
            fault_seed=int(seed),
            fault_events=tuple(tuple(int(x) for x in e) for e in self.events),
            fault_flip_l1=float(self.flip_l1),
            fault_flip_llc=float(self.flip_llc),
            fault_due_rate=float(self.due_rate),
        )


def _event_from_dict(d: dict) -> tuple:
    if not isinstance(d, dict):
        raise FaultConfigError(
            f"event {d!r} must be an object", field="events"
        )
    kind_s = d.get("kind")
    if kind_s not in _KIND_NAMES:
        raise FaultConfigError(
            f"unknown kind {kind_s!r} (valid: {sorted(_KIND_NAMES)})",
            step=d.get("step"), field="kind",
        )
    kind = _KIND_NAMES[kind_s]
    if "step" not in d:
        raise FaultConfigError("event missing 'step'", field="step")
    estep = int(d["step"])
    if kind == FAULT_CORE_FAILSTOP:
        if "core" not in d:
            raise FaultConfigError(
                "core_failstop event missing 'core'", step=estep,
                field="core",
            )
        return (estep, kind, int(d["core"]), 0)
    if "link" not in d:
        raise FaultConfigError(
            f"{kind_s} event missing 'link'", step=estep, field="link"
        )
    extra = int(d.get("extra", 0)) if kind == FAULT_LINK_DEGRADE else 0
    return (estep, kind, int(d["link"]), extra)


def schedule_from_dict(d: dict) -> FaultSchedule:
    known = {
        "events", "flip_l1", "flip_llc", "due_rate", "dead_policy",
        "due_failstop",
    }
    unknown = sorted(set(d) - known)
    if unknown:
        raise FaultConfigError(
            f"unknown schedule field(s) {unknown}", field=unknown[0]
        )
    return FaultSchedule(
        events=tuple(_event_from_dict(e) for e in d.get("events", ())),
        flip_l1=float(d.get("flip_l1", 0.0)),
        flip_llc=float(d.get("flip_llc", 0.0)),
        due_rate=float(d.get("due_rate", 0.0)),
        dead_policy=str(d.get("dead_policy", "writeback")),
        due_failstop=bool(d.get("due_failstop", False)),
    )


def load_schedule(path: str) -> FaultSchedule:
    """Load a fault-schedule JSON file (typed errors on malformed input)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except json.JSONDecodeError as e:
        raise FaultConfigError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(d, dict):
        raise FaultConfigError(f"{path}: schedule must be a JSON object")
    return schedule_from_dict(d)
